"""Cross-host device-array transfer (round-3 VERDICT item 3).

A ``jax.Array`` crossing processes no longer takes a host PICKLE round trip
(device_get → in-band pickle → head relay → unpickle → numpy): the device
envelope reduces it to metadata + an out-of-band raw buffer on the peer
data plane, and the consumer rebuilds a REAL device array via
``jax.device_put``.  On real multi-host TPU the same pull negotiates a
``jax.experimental.transfer`` device-to-device ticket instead (probed; CPU
and the single-chip tunnel fall back to the envelope transparently).

Reference anchor: the role NCCL channels play for GPU tensors —
``python/ray/experimental/channel/nccl_group.py:18``; SURVEY §5.8.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import ray_tpu as rt
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ObjectStore
from ray_tpu.runtime import data_plane, device_plane
from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy

from test_multihost import _spawn_agent, _wait_for_nodes, two_process_cluster  # noqa: F401


# ==========================================================================
# unit: the device envelope
# ==========================================================================
def test_device_array_serializes_out_of_band():
    """The array's bytes never enter the pickle stream: meta stays tiny and
    the payload rides as a raw out-of-band buffer."""
    x = jnp.arange(250_000, dtype=jnp.float32)  # 1 MB
    meta, buffers = data_plane.to_frames(x)
    assert len(meta) < 4096, f"meta unexpectedly large: {len(meta)} (in-band pickle?)"
    assert sum(memoryview(b).cast('B').nbytes for b in buffers) >= x.nbytes


def test_device_array_roundtrips_as_device_array():
    before = device_plane.stats.snapshot()["arrays_restored"]
    x = jnp.arange(100_000, dtype=jnp.float32) * 3.0
    meta, buffers = data_plane.to_frames(x)
    y = data_plane.from_frames(meta, [bytearray(memoryview(b).cast('B')) for b in buffers])
    assert isinstance(y, jax.Array), type(y)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    assert device_plane.stats.snapshot()["arrays_restored"] > before


def test_device_arrays_nested_in_containers():
    value = {"params": {"w": jnp.ones((64, 64), jnp.bfloat16)}, "step": 3,
             "host": np.arange(10)}
    meta, buffers = data_plane.to_frames(value)
    got = data_plane.from_frames(meta, [bytes(memoryview(b).cast('B')) for b in buffers])
    assert isinstance(got["params"]["w"], jax.Array)
    assert got["params"]["w"].dtype == jnp.bfloat16
    assert got["step"] == 3 and isinstance(got["host"], np.ndarray)


def test_tracers_are_not_enveloped():
    """Inside a jit trace the reducer must not try to export buffers."""

    @jax.jit
    def f(x):
        # pickling never happens here; just assert the predicate is safe
        assert not device_plane.is_device_array(x)
        return x * 2

    np.testing.assert_array_equal(np.asarray(f(jnp.ones(4))), 2 * np.ones(4))


def test_transfer_server_probe_degrades_gracefully():
    """On backends without transfer-server support (CPU / tunnel), the probe
    yields None and pulls silently use the envelope."""
    addr = device_plane.transfer_address()
    assert addr is None or isinstance(addr, str)


def test_pull_of_device_array_via_data_server():
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store)
    try:
        oid = ObjectID.from_random()
        store.put(oid, jnp.full((512, 512), 7.0, jnp.float32))
        client = data_plane.DataClient()
        got, is_error = client.pull(server.address, oid.binary())
        assert not is_error
        assert isinstance(got, jax.Array)
        assert float(got[0, 0]) == 7.0
        client.close()
    finally:
        server.close()


# ==========================================================================
# the ICI/DCN negotiation protocol, executed through the fake transfer
# server (round-3 VERDICT missing #1: offer_device_pull/device_pull had
# zero executed lines — CPU can't build the real server, the tunnel can't
# host two processes).  The fake keeps the exact surface and moves the
# staged array's host bytes over TCP, so offer → ticket → pull → release →
# fallback all run for real.
# ==========================================================================
@pytest.fixture
def fake_transfer():
    from ray_tpu.runtime.fake_transfer import FakeTransferServer

    server = FakeTransferServer()
    device_plane.install_transfer_server(server)
    try:
        yield server
    finally:
        device_plane.install_transfer_server(None)
        server.close()


def test_device_pull_negotiation_end_to_end(fake_transfer):
    """A pull of a device-resident object negotiates a transfer ticket:
    the data server answers with device_xfer instead of the host envelope,
    and the consumer receives a REAL device array through the transfer
    connection."""
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store)
    try:
        oid = ObjectID.from_random()
        store.put(oid, jnp.arange(4096, dtype=jnp.float32) * 2.0)
        ici_before = device_plane.stats.snapshot()["ici_pulls"]
        client = data_plane.DataClient()
        got, is_error = client.pull(server.address, oid.binary())
        assert not is_error
        assert isinstance(got, jax.Array)
        assert float(got[3]) == 6.0
        assert device_plane.stats.snapshot()["ici_pulls"] == ici_before + 1
        assert fake_transfer.pulls_served == 1
        client.close()
    finally:
        server.close()


def test_ticket_released_on_consume(fake_transfer):
    """One staging per pull: the staged entry is consumed by its pull and
    the admission slot (staging cap) is released via the ticket's done
    callback."""
    import time

    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store)
    try:
        oid = ObjectID.from_random()
        store.put(oid, jnp.ones((256, 256), jnp.float32))
        client = data_plane.DataClient()
        got, _ = client.pull(server.address, oid.binary())
        assert isinstance(got, jax.Array)
        # entry consumed server-side; admission slot released by the ticket
        assert fake_transfer.staged_count() == 0
        deadline = time.monotonic() + 5
        while device_plane._staged_outstanding != 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert device_plane._staged_outstanding == 0
        client.close()
    finally:
        server.close()


def test_concurrent_offers_pull_by_uuid(fake_transfer):
    """Several arrays staged SIMULTANEOUSLY (offer_device_pull called for
    each before any pull): every device_pull resolves its own uuid."""
    arrays = {100 + i: jnp.full((64,), float(i + 1), jnp.float32) for i in range(3)}
    for uuid, arr in arrays.items():
        assert device_plane.offer_device_pull(uuid, arr)
    assert fake_transfer.staged_count() == 3
    addr = device_plane.transfer_address()
    # pull out of order to prove uuid routing, not FIFO luck
    for uuid in [102, 100, 101]:
        template = jax.ShapeDtypeStruct((64,), jnp.float32)
        got = device_plane.device_pull(addr, uuid, template)
        assert isinstance(got, jax.Array)
        assert float(got[0]) == float(uuid - 100 + 1)
    assert fake_transfer.staged_count() == 0


def test_midflight_refusal_falls_back_to_envelope():
    """The producer offers a ticket but the consumer's backend refuses the
    device connection mid-flight: the pull must transparently retry as a
    host-envelope pull (data_plane.pull fallback) and still deliver the
    value."""
    from ray_tpu.runtime.fake_transfer import FakeTransferServer

    refusing = FakeTransferServer(refuse_pulls=True)
    device_plane.install_transfer_server(refusing)
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store)
    try:
        oid = ObjectID.from_random()
        store.put(oid, jnp.arange(1000, dtype=jnp.float32))
        ici_before = device_plane.stats.snapshot()["ici_pulls"]
        client = data_plane.DataClient()
        got, is_error = client.pull(server.address, oid.binary())
        assert not is_error
        assert isinstance(got, jax.Array) and float(got[999]) == 999.0
        # the device path never completed; the envelope carried it
        assert device_plane.stats.snapshot()["ici_pulls"] == ici_before
        client.close()
    finally:
        device_plane.install_transfer_server(None)
        refusing.close()
        server.close()


def test_unstaged_uuid_raises_keyerror(fake_transfer):
    """Protocol edge: pulling a uuid nobody staged fails cleanly."""
    conn = fake_transfer.connect(fake_transfer.address())
    with pytest.raises(KeyError):
        conn.pull(424242, jax.ShapeDtypeStruct((4,), jnp.float32))


# ==========================================================================
# integration: device array produced on the agent, consumed by the driver
# and by peer tasks — no host pickle round trip
# ==========================================================================
def test_device_array_crosses_processes_without_host_pickle(two_process_cluster):
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1})
    def produce():
        return jnp.arange(1_000_000, dtype=jnp.float32) + 1.0  # 4MB: lazy commit

    @rt.remote(resources={"remote": 1})
    def norm(x):
        assert hasattr(x, "devices"), f"consumer got {type(x)}, not a device array"
        return float(jnp.max(x))

    restored_before = device_plane.stats.snapshot()["arrays_restored"]
    ref = produce.remote()

    # driver-side consumption: a REAL device array arrives
    arr = rt.get(ref, timeout=120)
    assert isinstance(arr, jax.Array), type(arr)
    assert float(arr[0]) == 1.0 and float(arr[-1]) == 1_000_000.0

    # the envelope restored it (device_put), no in-band pickle round trip
    assert device_plane.stats.snapshot()["arrays_restored"] > restored_before

    # same-node peer consumption sees a device array too
    assert rt.get(norm.remote(ref), timeout=120) == 1_000_000.0

    # the head's directory knows the object is device-resident at its source
    assert cluster.directory.is_device(ref.id())
