"""Cross-host device-array transfer (round-3 VERDICT item 3).

A ``jax.Array`` crossing processes no longer takes a host PICKLE round trip
(device_get → in-band pickle → head relay → unpickle → numpy): the device
envelope reduces it to metadata + an out-of-band raw buffer on the peer
data plane, and the consumer rebuilds a REAL device array via
``jax.device_put``.  On real multi-host TPU the same pull negotiates a
``jax.experimental.transfer`` device-to-device ticket instead (probed; CPU
and the single-chip tunnel fall back to the envelope transparently).

Reference anchor: the role NCCL channels play for GPU tensors —
``python/ray/experimental/channel/nccl_group.py:18``; SURVEY §5.8.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import ray_tpu as rt
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ObjectStore
from ray_tpu.runtime import data_plane, device_plane
from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy

from test_multihost import _spawn_agent, _wait_for_nodes, two_process_cluster  # noqa: F401


# ==========================================================================
# unit: the device envelope
# ==========================================================================
def test_device_array_serializes_out_of_band():
    """The array's bytes never enter the pickle stream: meta stays tiny and
    the payload rides as a raw out-of-band buffer."""
    x = jnp.arange(250_000, dtype=jnp.float32)  # 1 MB
    meta, buffers = data_plane.to_frames(x)
    assert len(meta) < 4096, f"meta unexpectedly large: {len(meta)} (in-band pickle?)"
    assert sum(memoryview(b).cast('B').nbytes for b in buffers) >= x.nbytes


def test_device_array_roundtrips_as_device_array():
    before = device_plane.stats.snapshot()["arrays_restored"]
    x = jnp.arange(100_000, dtype=jnp.float32) * 3.0
    meta, buffers = data_plane.to_frames(x)
    y = data_plane.from_frames(meta, [bytearray(memoryview(b).cast('B')) for b in buffers])
    assert isinstance(y, jax.Array), type(y)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    assert device_plane.stats.snapshot()["arrays_restored"] > before


def test_device_arrays_nested_in_containers():
    value = {"params": {"w": jnp.ones((64, 64), jnp.bfloat16)}, "step": 3,
             "host": np.arange(10)}
    meta, buffers = data_plane.to_frames(value)
    got = data_plane.from_frames(meta, [bytes(memoryview(b).cast('B')) for b in buffers])
    assert isinstance(got["params"]["w"], jax.Array)
    assert got["params"]["w"].dtype == jnp.bfloat16
    assert got["step"] == 3 and isinstance(got["host"], np.ndarray)


def test_tracers_are_not_enveloped():
    """Inside a jit trace the reducer must not try to export buffers."""

    @jax.jit
    def f(x):
        # pickling never happens here; just assert the predicate is safe
        assert not device_plane.is_device_array(x)
        return x * 2

    np.testing.assert_array_equal(np.asarray(f(jnp.ones(4))), 2 * np.ones(4))


def test_transfer_server_probe_degrades_gracefully():
    """On backends without transfer-server support (CPU / tunnel), the probe
    yields None and pulls silently use the envelope."""
    addr = device_plane.transfer_address()
    assert addr is None or isinstance(addr, str)


def test_pull_of_device_array_via_data_server():
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store)
    try:
        oid = ObjectID.from_random()
        store.put(oid, jnp.full((512, 512), 7.0, jnp.float32))
        client = data_plane.DataClient()
        got, is_error = client.pull(server.address, oid.binary())
        assert not is_error
        assert isinstance(got, jax.Array)
        assert float(got[0, 0]) == 7.0
        client.close()
    finally:
        server.close()


# ==========================================================================
# integration: device array produced on the agent, consumed by the driver
# and by peer tasks — no host pickle round trip
# ==========================================================================
def test_device_array_crosses_processes_without_host_pickle(two_process_cluster):
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1})
    def produce():
        return jnp.arange(1_000_000, dtype=jnp.float32) + 1.0  # 4MB: lazy commit

    @rt.remote(resources={"remote": 1})
    def norm(x):
        assert hasattr(x, "devices"), f"consumer got {type(x)}, not a device array"
        return float(jnp.max(x))

    restored_before = device_plane.stats.snapshot()["arrays_restored"]
    ref = produce.remote()

    # driver-side consumption: a REAL device array arrives
    arr = rt.get(ref, timeout=120)
    assert isinstance(arr, jax.Array), type(arr)
    assert float(arr[0]) == 1.0 and float(arr[-1]) == 1_000_000.0

    # the envelope restored it (device_put), no in-band pickle round trip
    assert device_plane.stats.snapshot()["arrays_restored"] > restored_before

    # same-node peer consumption sees a device array too
    assert rt.get(norm.remote(ref), timeout=120) == 1_000_000.0

    # the head's directory knows the object is device-resident at its source
    assert cluster.directory.is_device(ref.id())
