"""MirrorPool consistency under concurrent head placements + agent-local
dispatch (round-2 VERDICT weak #6).

The head schedules against a MirrorPool (its view of the agent's pool,
echoed one-way) while the agent's local scheduler acquires concurrently;
periodic resource reports reconcile drift.  These tests drive both sides
at once and assert the invariant that matters: the agent's REAL capacity
is never oversubscribed (measured by actual task-execution overlap), and
the views converge after quiescence (ray_syncer's versioned-view role,
reference ray_syncer.h:88).
"""

import os
import threading
import time

import ray_tpu as rt

from test_multihost import _spawn_agent, _wait_for_nodes, two_process_cluster  # noqa: F401


def test_no_oversubscription_under_concurrent_placement(two_process_cluster, tmp_path):
    cluster, proc = two_process_cluster  # agent: CPU=2, remote=4
    log_path = str(tmp_path / "overlap.log")
    open(log_path, "w").close()

    @rt.remote(resources={"remote": 1})
    def work(i, log_path):
        import os
        import time as _t

        # O_APPEND single-write records are atomic at this size
        with open(log_path, "a") as f:
            f.write(f"s {_t.time():.6f}\n")
            f.flush()
        _t.sleep(0.05)
        with open(log_path, "a") as f:
            f.write(f"e {_t.time():.6f}\n")
            f.flush()
        return i

    results = []
    errors = []

    def submit_tasks():
        try:
            for wave in range(5):
                refs = [work.remote(wave * 8 + i, log_path) for i in range(8)]
                results.extend(rt.get(refs, timeout=120))
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    def churn_placement_groups():
        from ray_tpu.util.placement import placement_group, remove_placement_group

        try:
            for _ in range(15):
                pg = placement_group([{"remote": 1.0}], strategy="PACK")
                pg.wait(timeout_seconds=30)
                remove_placement_group(pg)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [
        threading.Thread(target=submit_tasks),
        threading.Thread(target=churn_placement_groups),
        threading.Thread(target=churn_placement_groups),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors, errors
    assert sorted(results) == list(range(40))

    # actual concurrency on the agent never exceeded its remote capacity
    events = []
    with open(log_path) as f:
        for line in f:
            kind, ts = line.split()
            events.append((float(ts), 1 if kind == "s" else -1))
    events.sort()
    load = max_load = 0
    for _ts, delta in events:
        load += delta
        max_load = max(max_load, load)
    assert 1 <= max_load <= 4, f"oversubscribed: {max_load} concurrent > capacity 4"


def test_views_reconcile_after_quiescence(two_process_cluster):
    """After the churn stops, the head's mirror converges to the agent's
    authoritative pool (periodic resource_report reconcile)."""
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1})
    def touch():
        return 1

    assert sum(rt.get([touch.remote() for _ in range(12)], timeout=120)) == 12

    handle = next(
        n for nid, n in cluster.nodes.items()
        if nid != cluster.head_node.node_id and not n.dead
    )
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        avail = handle.pool.available.to_dict()
        total = handle.pool.total.to_dict()
        if avail == total:
            return
        time.sleep(0.1)
    raise AssertionError(
        f"mirror never reconciled to full availability: {avail} != {total}"
    )
