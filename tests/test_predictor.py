"""Predictor surface (parity: train/predictor.py + the framework
predictors): format dispatch, preprocessor application, pandas-UDF wrapper,
checkpoint loading, non-serializability, and batch inference through
Dataset.map_batches with a callable class."""

import sys
import types

import numpy as np
import pandas as pd
import pytest

import ray_tpu
from ray_tpu.train import Checkpoint, JaxPredictor, Predictor
from ray_tpu.train.predictor import PredictorNotSerializableException


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


class _DoublePredictor(Predictor):
    @classmethod
    def from_checkpoint(cls, checkpoint, **kw):
        return cls(**kw)

    def _predict_pandas(self, df, **kw):
        return pd.DataFrame({"predictions": df.sum(axis=1) * 2})


def test_pandas_in_pandas_out():
    df = pd.DataFrame({"a": [1.0, 2.0], "b": [3.0, 4.0]})
    out = _DoublePredictor().predict(df)
    assert list(out["predictions"]) == [8.0, 12.0]


def test_numpy_dict_cross_converts_through_pandas_impl():
    out = _DoublePredictor().predict({"a": np.array([1.0, 2.0]), "b": np.array([3.0, 4.0])})
    assert isinstance(out, dict)
    assert list(out["predictions"]) == [8.0, 12.0]


def test_preprocessor_applies_before_predict():
    class AddOne:
        def transform_batch(self, df):
            return df + 1

    df = pd.DataFrame({"a": [1.0], "b": [1.0]})
    out = _DoublePredictor(preprocessor=AddOne()).predict(df)
    assert list(out["predictions"]) == [8.0]


def test_from_pandas_udf():
    p = Predictor.from_pandas_udf(lambda df: pd.DataFrame({"predictions": df["x"] * 10}))
    out = p.predict(pd.DataFrame({"x": [1.0, 2.0]}))
    assert list(out["predictions"]) == [10.0, 20.0]


def test_predictor_not_serializable():
    import pickle

    with pytest.raises(PredictorNotSerializableException, match="from_checkpoint"):
        pickle.dumps(_DoublePredictor())


def test_unsupported_batch_type():
    with pytest.raises(TypeError, match="Unsupported batch type"):
        _DoublePredictor().predict([1, 2, 3])


def test_jax_predictor_from_pytree_checkpoint(tmp_path):
    import jax.numpy as jnp

    w = {"scale": np.array(3.0)}
    ckpt = Checkpoint.from_pytree(w, base_dir=str(tmp_path))

    def apply_fn(params, x):
        return params["scale"] * jnp.sum(x, axis=-1)

    p = JaxPredictor.from_checkpoint(ckpt, apply_fn)
    out = p.predict({"a": np.array([1.0, 2.0]), "b": np.array([1.0, 0.0])})
    assert np.allclose(out["predictions"], [6.0, 6.0])
    out2 = p.predict(np.array([[1.0, 1.0], [2.0, 0.0]]))
    assert np.allclose(out2["predictions"], [6.0, 6.0])


def test_batch_inference_via_map_batches():
    from ray_tpu import data as rd

    ds = rd.from_pandas(pd.DataFrame({"a": np.arange(8.0), "b": np.ones(8)}))

    class Scorer:
        def __init__(self):
            self.predictor = _DoublePredictor()

        def __call__(self, batch):
            return self.predictor.predict(batch)

    rows = ds.map_batches(Scorer, batch_format="pandas").take_all()
    got = sorted(r["predictions"] for r in rows)
    want = sorted((a + 1) * 2 for a in np.arange(8.0))
    assert got == pytest.approx(want)


def test_xgboost_predictor_roundtrip(monkeypatch, tmp_path):
    mod = types.ModuleType("xgboost")

    class DMatrix:
        def __init__(self, df, **kw):
            self.df = df

    class Booster:
        def __init__(self):
            self.rounds = 7

        def load_model(self, path):
            with open(path) as f:
                self.rounds = int(f.read())

        def save_model(self, path):
            with open(path, "w") as f:
                f.write(str(self.rounds))

        def predict(self, dmat, **kw):
            return np.asarray(dmat.df.sum(axis=1)) * self.rounds

    mod.DMatrix = DMatrix
    mod.Booster = Booster
    monkeypatch.setitem(sys.modules, "xgboost", mod)

    from ray_tpu.train.xgboost import XGBoostCheckpoint, XGBoostPredictor

    ckpt = XGBoostCheckpoint.from_model(Booster(), base_dir=str(tmp_path))
    p = XGBoostPredictor.from_checkpoint(ckpt)
    out = p.predict(pd.DataFrame({"a": [1.0, 2.0], "b": [0.0, 1.0]}))
    assert list(out["predictions"]) == [7.0, 21.0]


def test_lightgbm_predictor_roundtrip(monkeypatch, tmp_path):
    mod = types.ModuleType("lightgbm")

    class Booster:
        def __init__(self, model_file=None):
            self.iters = 5
            if model_file is not None:
                with open(model_file) as f:
                    self.iters = int(f.read())

        def save_model(self, path):
            with open(path, "w") as f:
                f.write(str(self.iters))

        def predict(self, df, **kw):
            return np.asarray(df.sum(axis=1)) * self.iters

    mod.Booster = Booster
    monkeypatch.setitem(sys.modules, "lightgbm", mod)

    from ray_tpu.train.lightgbm import LightGBMCheckpoint, LightGBMPredictor

    ckpt = LightGBMCheckpoint.from_model(Booster(), base_dir=str(tmp_path))
    p = LightGBMPredictor.from_checkpoint(ckpt)
    out = p.predict(pd.DataFrame({"a": [2.0], "b": [1.0]}))
    assert list(out["predictions"]) == [15.0]


def test_torch_predictor_roundtrip(tmp_path):
    import torch

    from ray_tpu.train.torch import TorchCheckpoint, TorchPredictor

    model = torch.nn.Linear(2, 1, bias=False)
    with torch.no_grad():
        model.weight.copy_(torch.tensor([[2.0, 3.0]]))
    ckpt = TorchCheckpoint.from_model(model, base_dir=str(tmp_path))
    p = TorchPredictor.from_checkpoint(ckpt, torch.nn.Linear(2, 1, bias=False))
    out = p.predict({"a": np.array([1.0, 0.0]), "b": np.array([0.0, 1.0])})
    assert np.allclose(out["predictions"].ravel(), [2.0, 3.0])


def test_tensorflow_predictor_roundtrip(tmp_path):
    tf = pytest.importorskip("tensorflow")

    from ray_tpu.train.tensorflow import TensorflowCheckpoint, TensorflowPredictor

    model = tf.keras.Sequential(
        [tf.keras.layers.Input(shape=(2,)), tf.keras.layers.Dense(1, use_bias=False)]
    )
    model.layers[0].set_weights([np.array([[2.0], [3.0]], dtype=np.float32)])
    ckpt = TensorflowCheckpoint.from_model(model, base_dir=str(tmp_path))
    p = TensorflowPredictor.from_checkpoint(ckpt)
    out = p.predict(np.array([[1.0, 0.0], [0.0, 1.0]], dtype=np.float32))
    assert np.allclose(out["predictions"].ravel(), [2.0, 3.0])


def test_torch_predictor_dataframe_path_with_2d_output(tmp_path):
    # DataFrame in -> DataFrame out must survive (n, 1)-shaped model output
    import torch

    from ray_tpu.train.torch import TorchCheckpoint, TorchPredictor

    model = torch.nn.Linear(2, 1, bias=False)
    with torch.no_grad():
        model.weight.copy_(torch.tensor([[1.0, 1.0]]))
    p = TorchPredictor.from_checkpoint(
        TorchCheckpoint.from_model(model, base_dir=str(tmp_path)),
        torch.nn.Linear(2, 1, bias=False),
    )
    out = p.predict(pd.DataFrame({"a": [1.0, 2.0], "b": [3.0, 4.0]}))
    assert [float(np.asarray(v).ravel()[0]) for v in out["predictions"]] == [4.0, 6.0]


def test_base_predictor_requires_an_impl():
    class Empty(Predictor):
        pass

    with pytest.raises(NotImplementedError, match="implements neither"):
        Empty().predict(pd.DataFrame({"a": [1.0]}))


def test_transformers_predictor_roundtrip(tmp_path):
    transformers = pytest.importorskip("transformers")

    from ray_tpu.train.huggingface import TransformersCheckpoint, TransformersPredictor

    model = transformers.GPT2LMHeadModel(
        transformers.GPT2Config(vocab_size=32, n_positions=8, n_embd=8, n_layer=1, n_head=2)
    )
    ckpt = TransformersCheckpoint.from_model(model, base_dir=str(tmp_path))
    p = TransformersPredictor.from_checkpoint(ckpt, model_cls=transformers.GPT2LMHeadModel)
    ids = np.array([[1, 2, 3], [4, 5, 6]], dtype=np.int64)
    out = p.predict(ids)
    assert out["predictions"].shape == (2, 3, 32)
    # reloaded weights match the saved model exactly (eval: dropout off)
    import torch

    model.eval()
    with torch.no_grad():
        want = model(input_ids=torch.from_numpy(ids)).logits.numpy()
    assert np.allclose(out["predictions"], want, atol=1e-5)


def test_transformers_predictor_requires_model_or_pipeline():
    pytest.importorskip("transformers")
    from ray_tpu.train.huggingface import TransformersPredictor

    with pytest.raises(ValueError, match="model or a pipeline"):
        TransformersPredictor()


def test_transformers_predictor_default_class_keeps_logits_contract(tmp_path):
    transformers = pytest.importorskip("transformers")

    from ray_tpu.train.huggingface import TransformersCheckpoint, TransformersPredictor

    model = transformers.GPT2LMHeadModel(
        transformers.GPT2Config(vocab_size=32, n_positions=8, n_embd=8, n_layer=1, n_head=2)
    )
    ckpt = TransformersCheckpoint.from_model(model, base_dir=str(tmp_path))
    p = TransformersPredictor.from_checkpoint(ckpt)  # no model_cls
    out = p.predict(np.array([[1, 2, 3]], dtype=np.int64))
    assert out["predictions"].shape == (1, 3, 32)  # vocab logits, not hidden states


def test_transformers_predictor_sole_column_and_error(tmp_path):
    transformers = pytest.importorskip("transformers")

    from ray_tpu.train.huggingface import TransformersCheckpoint, TransformersPredictor

    model = transformers.GPT2LMHeadModel(
        transformers.GPT2Config(vocab_size=32, n_positions=8, n_embd=8, n_layer=1, n_head=2)
    )
    p = TransformersPredictor.from_checkpoint(
        TransformersCheckpoint.from_model(model, base_dir=str(tmp_path))
    )
    ids = np.array([[1, 2, 3]], dtype=np.int64)
    # a single dict column under any name is accepted as the token ids
    out = p.predict({"tokens": ids})
    assert out["predictions"].shape == (1, 3, 32)
    with pytest.raises(KeyError, match="input_ids"):
        p.predict({"a": ids, "b": ids})
