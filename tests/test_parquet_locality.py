"""Parquet column/predicate pushdown + locality-aware split (VERDICT item 8).

Reference anchors: python/ray/data/datasource/parquet_datasource.py
(columns/filter pushdown through pyarrow) and
python/ray/data/_internal/execution/operators/output_splitter.py:1
(locality hints).
"""

import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

import ray_tpu as rt  # noqa: E402
from ray_tpu.data.datasource import ParquetDatasource  # noqa: E402


@pytest.fixture
def runtime():
    rt.init(num_cpus=4)
    try:
        yield rt
    finally:
        rt.shutdown()


@pytest.fixture
def parquet_dir(tmp_path):
    """One file, 10 row groups of 100 rows each; `k` ascending so row-group
    min/max statistics give clean pruning boundaries."""
    path = str(tmp_path / "data.parquet")
    table = pa.table(
        {
            "k": np.arange(1000, dtype=np.int64),
            "v": np.random.default_rng(0).random(1000),
            "label": np.array([f"row{i}" for i in range(1000)]),
        }
    )
    pq.write_table(table, path, row_group_size=100)
    return str(tmp_path)


def test_column_pruning(runtime, parquet_dir):
    import ray_tpu.data as data

    ds = data.read_parquet(parquet_dir, columns=["k"])
    rows = ds.take(5)
    assert set(rows[0].keys()) == {"k"}


def test_predicate_pushdown_skips_row_groups(runtime, parquet_dir):
    import ray_tpu.data as data

    # e2e: exact rows survive the filter
    ds = data.read_parquet(parquet_dir, filters=[("k", ">=", 850)])
    rows = ds.take_all()
    assert sorted(r["k"] for r in rows) == list(range(850, 1000))

    # pushdown proof (driver-side read: tasks run in worker processes, so
    # stats are asserted on a direct datasource read): min/max statistics
    # prune row groups BEFORE any IO on them
    src = ParquetDatasource(parquet_dir, filters=[("k", ">=", 850)])
    ParquetDatasource.reset_read_stats()
    path = os.path.join(parquet_dir, "data.parquet")
    block = src._read_file(path)
    stats = ParquetDatasource.read_stats
    assert stats["row_groups_total"] == 10
    assert stats["row_groups_read"] <= 2
    assert sorted(block["k"].tolist()) == list(range(850, 1000))


def test_pushdown_combined_with_columns(runtime, parquet_dir):
    import ray_tpu.data as data

    ds = data.read_parquet(parquet_dir, columns=["v"], filters=[("k", "<", 100)])
    rows = ds.take_all()
    assert len(rows) == 100
    assert set(rows[0].keys()) == {"v"}

    src = ParquetDatasource(parquet_dir, columns=["v"], filters=[("k", "<", 100)])
    ParquetDatasource.reset_read_stats()
    block = src._read_file(os.path.join(parquet_dir, "data.parquet"))
    assert ParquetDatasource.read_stats["row_groups_read"] <= 1
    assert set(block.keys()) == {"v"}


def test_filter_no_match_returns_empty(runtime, parquet_dir):
    import ray_tpu.data as data

    ds = data.read_parquet(parquet_dir, filters=[("k", ">", 10_000)])
    assert ds.take_all() == []


# ----------------------------------------------------------- locality split
def test_split_respects_locality_hints(runtime):
    import ray_tpu.data as data
    from ray_tpu.core.ids import NodeID

    cluster = rt.get_cluster()
    node_b = cluster.add_node({"CPU": 2})
    head_id = cluster.head_node.node_id

    ds = data.from_items([{"x": i} for i in range(100)], parallelism=4)
    mat = ds.materialize()
    # move two blocks' objects to node_b (exclusive location) so hints have
    # something to match
    for ref in mat._refs[:2]:
        value = rt.get(ref)
        node_b.store.put(ref.id(), value)
        cluster.directory.add_location(ref.id(), node_b.node_id)
        cluster.directory.remove_location(ref.id(), head_id)

    splits = mat.split(2, locality_hints=[head_id, node_b.node_id])
    # node_b's split got the blocks that live there
    b_refs = set(r.id() for r in splits[1]._refs)
    for ref in mat._refs[:2]:
        assert ref.id() in b_refs
    # balanced overall
    assert sum(len(s._refs) for s in splits) == len(mat._refs)


def test_split_hint_length_mismatch_raises(runtime):
    import ray_tpu.data as data

    ds = data.from_items([{"x": i} for i in range(10)])
    with pytest.raises(ValueError):
        ds.split(2, locality_hints=[None])


def test_streaming_split_locality(runtime):
    import ray_tpu.data as data

    cluster = rt.get_cluster()
    node_b = cluster.add_node({"CPU": 2})
    ds = data.from_items([{"x": i} for i in range(40)], parallelism=4)
    its = ds.streaming_split(2, equal=False, locality_hints=[cluster.head_node.node_id, node_b.node_id])
    rows = []
    for it in its:
        for batch in it.iter_batches(batch_size=10):
            rows.extend(np.asarray(batch["x"]).tolist())
    assert sorted(rows) == list(range(40))
