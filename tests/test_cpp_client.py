"""C++ frontend end-to-end: compile the native client against a live
thin-client server and drive it (reference parity: the cpp/ user API and
cross_language call path, exercised the way cpp/src tests drive a real
cluster)."""

import os
import shutil
import subprocess
import sys

import pytest

import ray_tpu
from ray_tpu.util.client.server import ClientServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cpp_binary(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("g++ not available")
    out = tmp_path_factory.mktemp("cppbin") / "cpp_client_test"
    subprocess.run(
        [
            gxx, "-O1", "-std=c++17",
            os.path.join(REPO, "tests", "cpp_client_main.cpp"),
            os.path.join(REPO, "ray_tpu", "native", "src", "client.cpp"),
            "-o", str(out),
        ],
        check=True,
        capture_output=True,
    )
    return str(out)


@pytest.fixture(scope="module")
def client_server():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    server = ClientServer(port=0).start()
    yield server
    server.stop()
    ray_tpu.shutdown()


def test_cpp_client_end_to_end(cpp_binary, client_server):
    env = {**os.environ, "PYTHONPATH": os.pathsep.join([REPO] + sys.path)}
    proc = subprocess.run(
        [cpp_binary, client_server.host, str(client_server.port)],
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout!r} stderr={proc.stderr!r}"
    assert "CPP CLIENT OK" in proc.stdout


def test_binary_protocol_python_roundtrip(client_server):
    """Drive the binary protocol from Python (no compiler needed) so the
    wire format stays covered even where g++ is missing."""
    import socket
    import struct

    from ray_tpu.util.client import binary as B

    s = socket.create_connection((client_server.host, client_server.port))
    s.sendall(B.BINARY_MAGIC)

    def req(op, payload):
        s.sendall(struct.pack("<IBQ", len(payload), op, 7) + payload)
        head = B.recv_exact(s, 13)
        ln, status, rid = struct.unpack("<IBQ", head)
        body = B.recv_exact(s, ln) if ln else b""
        return status, body

    status, pong = req(B.OP_PING, b"")
    assert status == 0 and pong == b"pong"

    status, ref = req(B.OP_PUT, b"\x00\x01binary")
    assert status == 0 and len(ref) == 16

    status, val = req(B.OP_GET, ref + struct.pack("<d", 10.0))
    assert status == 0 and val == b"\x00\x01binary"

    # unknown op errors without killing the connection
    status, err = req(99, b"")
    assert status == 1 and b"unknown" in err
    status, pong = req(B.OP_PING, b"")
    assert status == 0 and pong == b"pong"
    s.close()
