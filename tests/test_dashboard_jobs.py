"""Dashboard REST API, job submission, runtime envs, CLI.

Parity coverage: ``dashboard/modules/job`` REST + SDK tests and the state
CLI (``python/ray/tests/test_state_api.py`` style, scaled down).
"""

import json
import os
import subprocess
import sys
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu.job.sdk import JobSubmissionClient


@pytest.fixture
def dash_cluster():
    rt.init(num_cpus=2, include_dashboard=True)
    cluster = rt.get_cluster()
    yield cluster
    rt.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


# ----------------------------------------------------------------------
def test_dashboard_state_routes(dash_cluster):
    url = dash_cluster.dashboard.url

    @rt.remote
    def f():
        return 1

    rt.get([f.remote() for _ in range(3)])

    assert _get(url + "/api/healthz")["status"] == "ok"
    assert _get(url + "/api/version")["version"]
    assert len(_get(url + "/api/nodes")["nodes"]) == 1
    status = _get(url + "/api/cluster_status")
    assert status["num_nodes"] == 1 and status["resources_total"]["CPU"] == 2
    tasks = _get(url + "/api/tasks")["tasks"]
    assert sum(1 for t in tasks if t["name"] == "f") == 3
    summary = _get(url + "/api/summary/tasks")
    assert summary["summary"]["f"]["state_counts"]["FINISHED"] == 3
    timeline = _get(url + "/api/timeline")
    assert all(ev["ph"] == "X" for ev in timeline)


def test_dashboard_metrics_endpoint(dash_cluster):
    url = dash_cluster.dashboard.url

    @rt.remote
    def g():
        return 1

    rt.get(g.remote())
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "ray_tpu_tasks_terminal_total" in text


def test_dashboard_404(dash_cluster):
    url = dash_cluster.dashboard.url
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        _get(url + "/api/nope")
    assert exc_info.value.code == 404


# ----------------------------------------------------------------------
# job submission
# ----------------------------------------------------------------------
def test_job_submit_success_and_logs(dash_cluster):
    client = JobSubmissionClient(dash_cluster.dashboard.url)
    sub_id = client.submit_job(entrypoint=f"{sys.executable} -c \"print('job ran ok')\"")
    info = client.wait_until_finished(sub_id, timeout=60)
    assert info["status"] == "SUCCEEDED"
    assert "job ran ok" in client.get_job_logs(sub_id)
    assert any(j["submission_id"] == sub_id for j in client.list_jobs())


def test_job_failure_reports_failed(dash_cluster):
    client = JobSubmissionClient(dash_cluster.dashboard.url)
    sub_id = client.submit_job(entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    info = client.wait_until_finished(sub_id, timeout=60)
    assert info["status"] == "FAILED"
    assert "exit code 3" in info["message"]


def test_job_stop(dash_cluster):
    client = JobSubmissionClient(dash_cluster.dashboard.url)
    sub_id = client.submit_job(entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
    assert client.stop_job(sub_id)
    info = client.wait_until_finished(sub_id, timeout=30)
    assert info["status"] == "STOPPED"


def test_job_runtime_env_env_vars_and_driver_uses_framework(dash_cluster, tmp_path):
    client = JobSubmissionClient(dash_cluster.dashboard.url)
    script = tmp_path / "driver.py"
    script.write_text(
        "import os\n"
        "import ray_tpu as rt\n"
        "rt.init(num_cpus=1)\n"
        "@rt.remote\n"
        "def f(): return os.environ.get('MY_FLAG')\n"
        "print('flag=' + str(rt.get(f.remote())))\n"
        "rt.shutdown()\n"
    )
    sub_id = client.submit_job(
        entrypoint=f"{sys.executable} {script}",
        runtime_env={"env_vars": {"MY_FLAG": "hello-env"}},
    )
    info = client.wait_until_finished(sub_id, timeout=120)
    logs = client.get_job_logs(sub_id)
    assert info["status"] == "SUCCEEDED", logs
    assert "flag=hello-env" in logs


def test_job_runtime_env_working_dir(dash_cluster, tmp_path):
    workdir = tmp_path / "app"
    workdir.mkdir()
    (workdir / "data.txt").write_text("payload42")
    client = JobSubmissionClient(dash_cluster.dashboard.url)
    sub_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print(open('data.txt').read())\"",
        runtime_env={"working_dir": str(workdir)},
    )
    info = client.wait_until_finished(sub_id, timeout=60)
    assert info["status"] == "SUCCEEDED"
    assert "payload42" in client.get_job_logs(sub_id)


# ----------------------------------------------------------------------
# runtime env plugins (unit)
# ----------------------------------------------------------------------
def test_runtime_env_validation():
    from ray_tpu.runtime_env import validate_runtime_env

    validate_runtime_env({"env_vars": {"A": "1"}})
    with pytest.raises(TypeError):
        validate_runtime_env({"env_vars": {"A": 1}})
    with pytest.raises(ValueError):
        validate_runtime_env({"bogus_field": 1})


def test_runtime_env_py_modules(tmp_path):
    from ray_tpu.runtime_env.plugin import apply_to_process_env

    pkg = tmp_path / "mypkg_rt_test"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("VALUE = 7\n")
    env, cwd = apply_to_process_env({"py_modules": [str(pkg)]}, {})
    assert any("py_modules" in p for p in env["PYTHONPATH"].split(os.pathsep))
    # import works from the staged path
    code = "import sys; sys.path[:0]=%r.split(%r); import mypkg_rt_test; print(mypkg_rt_test.VALUE)" % (
        env["PYTHONPATH"],
        os.pathsep,
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True)
    assert out.stdout.strip() == "7"


def test_uri_cache_eviction(tmp_path):
    from ray_tpu.runtime_env.uri_cache import URICache

    cache = URICache(max_total_size_bytes=100)

    def make(name, size):
        p = tmp_path / name
        p.write_bytes(b"x" * size)
        return str(p)

    cache.get_or_create("uri://a", lambda: make("a", 80))
    cache.add_reference("uri://a")
    cache.get_or_create("uri://b", lambda: make("b", 80))  # exceeds: b unreferenced but a pinned
    assert cache.get("uri://a") is not None
    cache.remove_reference("uri://a")
    cache.get_or_create("uri://c", lambda: make("c", 80))
    # total > 100 → oldest unreferenced evicted
    assert cache.total_size() <= 160


# ----------------------------------------------------------------------
# CLI (against a live dashboard over HTTP)
# ----------------------------------------------------------------------
def test_cli_status_list_summary(dash_cluster, capsys):
    from ray_tpu.scripts.cli import main

    url = dash_cluster.dashboard.url

    @rt.remote
    def h():
        return 1

    rt.get(h.remote())

    assert main(["status", "--address", url]) == 0
    out = capsys.readouterr().out
    assert "Nodes: 1" in out

    assert main(["list", "nodes", "--address", url]) == 0
    assert "node_id" in capsys.readouterr().out

    assert main(["summary", "tasks", "--address", url]) == 0
    assert "h" in capsys.readouterr().out


def test_cli_timeline_and_job(dash_cluster, tmp_path, capsys):
    from ray_tpu.scripts.cli import main

    url = dash_cluster.dashboard.url

    @rt.remote
    def k():
        return 1

    rt.get(k.remote())
    out_file = tmp_path / "tl.json"
    assert main(["timeline", "--address", url, "-o", str(out_file)]) == 0
    assert json.loads(out_file.read_text())

    rc = main(
        ["job", "submit", "--address", url, "--", sys.executable, "-c", "print('cli job')"]
    )
    assert rc == 0
    assert "cli job" in capsys.readouterr().out


def test_job_bad_runtime_env_fails_not_pending(dash_cluster):
    """A runtime_env failure must yield a FAILED job, not a phantom PENDING."""
    mgr = dash_cluster.dashboard.job_manager
    sid = mgr.submit_job("echo hi", runtime_env={"working_dir": "/nonexistent-xyz"})
    info = mgr.get_job(sid)
    assert info["status"] == "FAILED"
    assert "runtime_env" in info["message"]


def test_stop_pending_job_prevents_launch(dash_cluster):
    """stop_job on a not-yet-launched entry must keep it from running."""
    from ray_tpu.job.manager import JobStatus, _JobEntry

    mgr = dash_cluster.dashboard.job_manager
    sid = "rtjob_pending_stop"
    with mgr._lock:
        mgr._jobs[sid] = _JobEntry(sid, "echo never", None)  # staged, pre-launch
    assert mgr.stop_job(sid) is True
    info = mgr.get_job(sid)
    assert info["status"] == "STOPPED"
    assert info["end_time"] is not None


def test_working_dir_change_restages(dash_cluster, tmp_path):
    """Content fingerprinting: editing the dir yields a fresh staged copy."""
    src = tmp_path / "wd"
    src.mkdir()
    (src / "f.txt").write_text("one")
    from ray_tpu.runtime_env.plugin import apply_to_process_env

    _env, cwd1 = apply_to_process_env({"working_dir": str(src)}, {})
    import os as _os
    import time as _time

    _time.sleep(0.01)
    (src / "f.txt").write_text("two-changed")
    _os.utime(src / "f.txt")
    _env, cwd2 = apply_to_process_env({"working_dir": str(src)}, {})
    assert cwd1 != cwd2
    assert (open(_os.path.join(cwd2, "f.txt")).read()) == "two-changed"


def test_dashboard_serves_ui_index(dash_cluster):
    import urllib.request

    with urllib.request.urlopen(dash_cluster.dashboard.url + "/", timeout=30) as resp:
        body = resp.read().decode()
        ctype = resp.headers.get("Content-Type", "")
    assert "text/html" in ctype
    assert "ray_tpu" in body and "/api/cluster_status" in body


def test_workflow_http_event_trigger(dash_cluster, tmp_path):
    """POST /api/workflows/events/<name> resumes a workflow blocked on
    wait_for_event (HTTPEventProvider parity)."""
    import json
    import threading
    import urllib.error
    import urllib.request

    import ray_tpu
    from ray_tpu import workflow

    workflow.init(str(tmp_path / "wf"))

    @ray_tpu.remote
    def unwrap(evt):
        return evt["decision"]

    dag = unwrap.bind(workflow.wait_for_event(workflow.QueueEventListener, "release", 30.0))
    result = {}

    def run():
        result["value"] = workflow.run(dag, workflow_id="wf_http")

    t = threading.Thread(target=run)
    t.start()
    # the trigger 404s until the workflow is actually blocked on the event
    # (unmatched events are rejected, not queued) — retry until it lands
    import time as _time

    deadline = _time.monotonic() + 30
    delivered = False
    while _time.monotonic() < deadline and not delivered:
        req = urllib.request.Request(
            dash_cluster.dashboard.url + "/api/workflows/events/release",
            data=json.dumps({"decision": "approved"}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert json.loads(resp.read())["delivered"] == "release"
                delivered = True
        except urllib.error.HTTPError as e:
            assert e.code == 404
            _time.sleep(0.05)
    assert delivered
    t.join(timeout=60)
    assert result.get("value") == "approved"
