"""Tests for ray_tpu.tune — search expansion, controller loop, schedulers,
Train-on-Tune layering (mirrors tune/tests strategy)."""

import pytest

import ray_tpu
from ray_tpu import train, tune
from ray_tpu.train import JaxTrainer, ScalingConfig
from ray_tpu.tune import (
    AsyncHyperBandScheduler,
    BasicVariantGenerator,
    MedianStoppingRule,
    PopulationBasedTraining,
    TuneConfig,
    Tuner,
)


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_grid_expansion():
    gen = BasicVariantGenerator({"a": tune.grid_search([1, 2, 3]), "b": tune.grid_search([10, 20])}, num_samples=2)
    assert gen.total_trials == 12


def test_random_sampling_domains():
    gen = BasicVariantGenerator(
        {
            "u": tune.uniform(0, 1),
            "lu": tune.loguniform(1e-4, 1e-1),
            "c": tune.choice(["x", "y"]),
            "ri": tune.randint(0, 10),
            "q": tune.quniform(0, 1, 0.25),
        },
        num_samples=5,
        seed=0,
    )
    for _ in range(5):
        cfg = gen.suggest("t")
        assert 0 <= cfg["u"] <= 1
        assert 1e-4 <= cfg["lu"] <= 1e-1
        assert cfg["c"] in ("x", "y")
        assert 0 <= cfg["ri"] < 10
        assert cfg["q"] in (0.0, 0.25, 0.5, 0.75, 1.0)
    assert gen.suggest("t") is None


def test_basic_tune_run():
    def trainable(config):
        tune.report({"score": config["x"] ** 2})

    results = tune.run(trainable, config={"x": tune.grid_search([1, 2, 3, -4])}, metric="score", mode="max")
    assert len(results) == 4
    best = results.get_best_result()
    assert best.metrics["score"] == 16


def test_returned_dict_counts_as_final_report():
    def trainable(config):
        return {"score": config["x"]}

    results = tune.run(trainable, config={"x": tune.grid_search([5, 7])}, metric="score", mode="min")
    assert results.get_best_result().metrics["score"] == 5


def test_multi_report_iterations():
    def trainable(config):
        for i in range(5):
            tune.report({"training_iteration": i + 1, "acc": config["lr"] * (i + 1)})

    results = tune.run(trainable, config={"lr": tune.grid_search([0.1, 0.2])}, metric="acc", mode="max")
    best = results.get_best_result()
    assert best.metrics["acc"] == pytest.approx(1.0)
    assert len(best.metrics_dataframe) == 5


def test_asha_stops_bad_trials():
    stopped = []

    def trainable(config):
        import time

        for i in range(1, 17):
            # Model a real epoch taking wall time: gives the controller the
            # window to deliver the scheduler's stop decision.
            time.sleep(0.03)
            tune.report({"training_iteration": i, "score": config["quality"] * i})
        stopped.append(config["quality"])

    # Strong trials run first (concurrency 4 of 8); the weak half arrives at
    # rungs already populated by strong results and must be pruned — ASHA's
    # asynchronous-promotion semantics (async_hyperband.py).
    scheduler = AsyncHyperBandScheduler(max_t=16, grace_period=2, reduction_factor=2)
    results = tune.run(
        trainable,
        config={"quality": tune.grid_search([20.0, 10.0, 5.0, 2.0, 0.05, 0.02, 0.01, 0.005])},
        metric="score",
        mode="max",
        scheduler=scheduler,
        max_concurrent_trials=4,
    )
    assert len(results) == 8
    best = results.get_best_result()
    assert best.metrics["score"] == pytest.approx(20.0 * 16)
    # at least one weak trial must have been stopped before completing
    iters = [len(t.history) for t in results._trials]
    assert min(iters) < 16


def test_median_stopping():
    def trainable(config):
        for i in range(1, 9):
            tune.report({"training_iteration": i, "score": config["q"]})

    results = tune.run(
        trainable,
        config={"q": tune.grid_search([1.0, 1.0, 1.0, 0.0])},
        metric="score",
        mode="max",
        scheduler=MedianStoppingRule(grace_period=2),
        max_concurrent_trials=4,
    )
    assert len(results) == 4


def test_checkpoint_through_tune(tmp_path):
    from ray_tpu.train import Checkpoint

    def trainable(config):
        tune.report({"v": 1}, checkpoint=Checkpoint.from_dict({"cfg": config["x"]}, base_dir=str(tmp_path)))

    results = tune.run(trainable, config={"x": tune.grid_search([42])}, metric="v", mode="max")
    assert results.get_best_result().checkpoint.to_dict()["cfg"] == 42


def test_tuner_with_trainer():
    def loop(config):
        train.report({"loss": (config["lr"] - 0.3) ** 2})

    tuner = Tuner(
        JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1)),
        param_space={"train_loop_config": {"lr": tune.grid_search([0.1, 0.3, 0.9])}},
        tune_config=TuneConfig(metric="loss", mode="min"),
    )
    results = tuner.fit()
    assert len(results) == 3
    assert results.get_best_result().metrics["loss"] == pytest.approx(0.0)


def test_errors_surface():
    def trainable(config):
        if config["x"] == 2:
            raise RuntimeError("boom")
        tune.report({"s": config["x"]})

    results = tune.run(trainable, config={"x": tune.grid_search([1, 2])}, metric="s", mode="max")
    assert len(results.errors) == 1
    assert results.get_best_result().metrics["s"] == 1


def test_pbt_runs():
    def trainable(config):
        score = 0.0
        for i in range(1, 9):
            score += config["lr"]
            tune.report({"training_iteration": i, "score": score})

    scheduler = PopulationBasedTraining(
        perturbation_interval=4,
        hyperparam_mutations={"lr": [0.01, 0.1, 1.0]},
        seed=0,
    )
    results = tune.run(
        trainable,
        config={"lr": tune.choice([0.01, 0.1, 1.0])},
        num_samples=4,
        metric="score",
        mode="max",
        scheduler=scheduler,
        max_concurrent_trials=4,
    )
    assert len(results) == 4
    assert results.get_best_result().metrics["score"] > 0


# --------------------------------------------------------------------------
# Model-based search (native TPE) + searcher utilities
# --------------------------------------------------------------------------
def test_tpe_searcher_finds_optimum():
    """TPE must concentrate samples near the optimum of a smooth bowl and
    beat random search's best-found on average."""
    from ray_tpu import tune

    def objective(config):
        x, y = config["x"], config["y"]
        tune.report({"loss": (x - 0.7) ** 2 + (y + 0.3) ** 2})

    space = {"x": tune.uniform(-2, 2), "y": tune.uniform(-2, 2)}
    searcher = tune.TPESearcher(space, metric="loss", mode="min", n_startup_trials=6, seed=0)
    tuner = tune.Tuner(
        objective,
        param_space=space,
        tune_config=tune.TuneConfig(search_alg=searcher, num_samples=30, metric="loss", mode="min"),
    )
    grid = tuner.fit()
    best = grid.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] < 0.25
    assert len(grid) == 30


def test_concurrency_limiter_caps_inflight():
    from ray_tpu import tune
    from ray_tpu.tune.search import BasicVariantGenerator, ConcurrencyLimiter

    inner = BasicVariantGenerator({"x": tune.uniform(0, 1)}, num_samples=6)
    limiter = ConcurrencyLimiter(inner, max_concurrent=2)
    # suggest 2 fine, 3rd deferred until a completion
    assert limiter.suggest("a") is not None
    assert limiter.suggest("b") is not None
    assert limiter.suggest("c") is None
    limiter.on_trial_complete("a", {"x": 1})
    assert limiter.suggest("c") is not None


def test_repeater_averages_metric():
    from ray_tpu import tune
    from ray_tpu.tune.search import Repeater, Searcher

    class Fixed(Searcher):
        def __init__(self):
            super().__init__(metric="score", mode="max")
            self.completed = []

        def suggest(self, trial_id):
            return {"c": 1}

        def on_trial_complete(self, trial_id, result=None, error=False):
            self.completed.append(result)

    inner = Fixed()
    rep = Repeater(inner, repeat=3)
    ids = ["t1", "t2", "t3"]
    for t in ids:
        assert rep.suggest(t) == {"c": 1}
    for t, score in zip(ids, [1.0, 2.0, 3.0]):
        rep.on_trial_complete(t, {"score": score})
    assert len(inner.completed) == 1
    assert inner.completed[0]["score"] == 2.0


def test_external_searchers_gate_with_importerror():
    from ray_tpu import tune

    with pytest.raises(ImportError, match="optuna"):
        tune.OptunaSearch()
    with pytest.raises(ImportError, match="hyperopt"):
        tune.HyperOptSearch()


# --------------------------------------------------------------------------
# PB2 (GP-bandit explore; parity: schedulers/pb2.py)
# --------------------------------------------------------------------------
class _FakeTrial:
    def __init__(self, tid, config):
        self.trial_id = tid
        self.config = config
        self.latest_checkpoint = None


def test_pb2_collects_reward_rate_observations():
    from ray_tpu.tune import PB2

    sched = PB2(metric="score", mode="max", perturbation_interval=2,
                hyperparam_bounds={"lr": [0.0, 1.0]}, seed=0)
    t = _FakeTrial("t1", {"lr": 0.5})
    for i, score in [(2, 4.0), (4, 10.0)]:
        sched.on_trial_result(t, {"training_iteration": i, "score": score})
    assert len(sched._obs) == 1
    t_obs, xs, rate = sched._obs[0]
    assert t_obs == 4 and xs == [0.5] and rate == pytest.approx(3.0)


def test_pb2_cold_start_samples_within_bounds():
    from ray_tpu.tune import PB2

    sched = PB2(metric="score", mode="max",
                hyperparam_bounds={"lr": [0.1, 0.9], "wd": [1e-5, 1e-3]}, seed=1)
    cfg = sched._select_bounded({})
    assert 0.1 <= cfg["lr"] <= 0.9
    assert 1e-5 <= cfg["wd"] <= 1e-3


def test_pb2_gp_moves_toward_better_region():
    from ray_tpu.tune import PB2

    sched = PB2(metric="score", mode="max", hyperparam_bounds={"lr": [0.0, 1.0]},
                seed=0, ucb_kappa=1.0)
    # population evidence: reward rate grows linearly with lr
    for i in range(24):
        lr = (i % 8) / 8.0
        sched._obs.append((float(i + 1), [lr], lr * 10.0))
    picks = [sched._select_bounded({})["lr"] for _ in range(5)]
    assert sum(p > 0.5 for p in picks) >= 4, picks


def test_pb2_requires_bounds():
    from ray_tpu.tune import PB2

    with pytest.raises(ValueError, match="hyperparam_bounds"):
        PB2(metric="score", mode="max")


def test_pb2_runs_end_to_end():
    from ray_tpu.tune import PB2

    def trainable(config):
        score = 0.0
        for i in range(1, 9):
            score += config["lr"]
            tune.report({"training_iteration": i, "score": score})

    scheduler = PB2(
        perturbation_interval=4,
        hyperparam_bounds={"lr": [0.01, 1.0]},
        seed=0,
    )
    results = tune.run(
        trainable,
        config={"lr": tune.uniform(0.01, 1.0)},
        num_samples=4,
        metric="score",
        mode="max",
        scheduler=scheduler,
        max_concurrent_trials=4,
    )
    assert len(results) == 4
    best = results.get_best_result().metrics["score"]
    assert best > 0
    # every exploited config stayed inside the declared bounds
    for r in results:
        assert 0.01 <= r.config["lr"] <= 1.0


def test_pbt_exploit_cooldown_prevents_restart_loop():
    """An exploited trial that restarts from scratch re-crosses the
    t%interval boundary; without the last-perturbation cooldown (reference:
    pbt.py last_perturbation_time) it is exploited forever."""
    sched = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=4,
        hyperparam_mutations={"lr": [0.1, 1.0]}, seed=0,
    )
    weak = _FakeTrial("weak", {"lr": 0.1})
    strong = _FakeTrial("strong", {"lr": 1.0})
    sched.on_trial_result(strong, {"training_iteration": 8, "score": 8.0})
    sched.on_trial_result(weak, {"training_iteration": 4, "score": 0.4})
    assert sched.exploit_target(weak) is not None
    # the trial restarted from zero and reached the same boundary again
    sched.on_trial_result(weak, {"training_iteration": 4, "score": 0.4})
    assert sched.exploit_target(weak) is None  # cooling down
    # after a full fresh interval beyond the exploit point it is eligible
    sched.on_trial_result(weak, {"training_iteration": 8, "score": 0.8})
    assert sched.exploit_target(weak) is not None


def test_pb2_exploit_drops_open_observation_window():
    """The exploited trial jumps to the donor checkpoint; its next score
    delta reflects the swap, not the new config, and must not enter the GP."""
    from ray_tpu.tune import PB2

    sched = PB2(metric="score", mode="max", perturbation_interval=4,
                hyperparam_bounds={"lr": [0.0, 1.0]}, seed=0)
    weak, strong = _FakeTrial("weak", {"lr": 0.1}), _FakeTrial("strong", {"lr": 0.9})
    sched.on_trial_result(strong, {"training_iteration": 8, "score": 8.0})
    sched.on_trial_result(weak, {"training_iteration": 4, "score": 0.4})
    assert "weak" in sched._window_start
    assert sched.exploit_target(weak) is not None
    assert "weak" not in sched._window_start
    # post-restart boundary: opens a fresh window instead of emitting a
    # spurious (donor_score - old_score) observation
    n_obs = len(sched._obs)
    sched.on_trial_result(weak, {"training_iteration": 8, "score": 8.5})
    assert len(sched._obs) == n_obs


# --------------------------------------------------------------------------
# Scheduler tail: ASHA alias, PBT replay, resource-changing (parity:
# schedulers/__init__.py, pbt.py Replay, resource_changing_scheduler.py)
# --------------------------------------------------------------------------
def test_asha_alias_and_bohb_names():
    from ray_tpu.tune import ASHAScheduler, AsyncHyperBandScheduler, HyperBandForBOHB, TuneBOHB

    assert ASHAScheduler is AsyncHyperBandScheduler
    assert issubclass(HyperBandForBOHB, AsyncHyperBandScheduler)
    with pytest.raises(ImportError, match="ConfigSpace"):
        TuneBOHB()


def test_pbt_replay_applies_recorded_schedule():
    from ray_tpu.tune import PopulationBasedTrainingReplay

    def trainable(config):
        for i in range(1, 9):
            tune.report({"training_iteration": i, "lr_seen": config["lr"], "score": i})

    replay = PopulationBasedTrainingReplay([(4, {"lr": 0.5})])
    results = tune.run(
        trainable, config={"lr": 0.1}, num_samples=1,
        metric="score", mode="max", scheduler=replay,
    )
    r = results[0]
    assert r.config["lr"] == 0.5          # switched at the recorded time
    assert r.metrics["lr_seen"] == 0.5    # and the restarted loop saw it
    assert replay._next == 1              # schedule fully consumed


def test_pbt_save_policy_roundtrips_into_replay(tmp_path):
    sched = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=4,
        hyperparam_mutations={"lr": [0.9]}, resample_probability=1.0, seed=0,
    )
    weak, strong = _FakeTrial("weak", {"lr": 0.1}), _FakeTrial("strong", {"lr": 1.0})
    sched.on_trial_result(strong, {"training_iteration": 8, "score": 8.0})
    sched.on_trial_result(weak, {"training_iteration": 4, "score": 0.4})
    assert sched.exploit_target(weak) is not None
    path = str(tmp_path / "policy.jsonl")
    sched.save_policy(path, "weak")
    from ray_tpu.tune import PopulationBasedTrainingReplay

    replay = PopulationBasedTrainingReplay(path)
    assert replay._policy[0][0] == 4
    assert replay._policy[0][1]["lr"] == 0.9


def test_resource_changing_scheduler_sets_trial_resources():
    from ray_tpu.tune import ResourceChangingScheduler

    seen = []

    def alloc(controller, trial, result, scheduler):
        seen.append(result["training_iteration"])
        return {"CPU": 2.0}

    sched = ResourceChangingScheduler(resources_allocation_function=alloc)
    t = _FakeTrial("t1", {"x": 1})
    t.status = "RUNNING"
    assert sched.on_trial_result(t, {"training_iteration": 1, "score": 1.0}) == "CONTINUE"
    assert t.resources == {"CPU": 2.0}
    assert seen == [1]


def test_resource_changing_scheduler_end_to_end():
    from ray_tpu.tune import DistributeResources, ResourceChangingScheduler

    def trainable(config):
        for i in range(1, 4):
            tune.report({"training_iteration": i, "score": i * config["lr"]})

    sched = ResourceChangingScheduler(
        resources_allocation_function=DistributeResources({"CPU": 1}),
    )
    results = tune.run(trainable, config={"lr": tune.choice([0.1, 1.0])},
                       num_samples=2, metric="score", mode="max", scheduler=sched)
    assert len(results) == 2
    assert all(r.metrics["training_iteration"] == 3 for r in results)


def test_resource_changing_wrapper_forwards_pbt_exploits():
    from ray_tpu.tune import ResourceChangingScheduler

    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=4,
        hyperparam_mutations={"lr": [0.9]}, resample_probability=1.0, seed=0,
    )
    wrapper = ResourceChangingScheduler(base_scheduler=pbt)
    weak, strong = _FakeTrial("weak", {"lr": 0.1}), _FakeTrial("strong", {"lr": 1.0})
    wrapper.on_trial_result(strong, {"training_iteration": 8, "score": 8.0})
    wrapper.on_trial_result(weak, {"training_iteration": 4, "score": 0.4})
    assert wrapper.at_perturbation_boundary({"training_iteration": 4, "score": 0.4})
    out = wrapper.exploit_target(weak)
    assert out is not None and out[0]["lr"] == 0.9


def test_pb2_policy_log_records_post_gp_config():
    from ray_tpu.tune import PB2

    sched = PB2(metric="score", mode="max", perturbation_interval=4,
                hyperparam_bounds={"lr": [0.0, 1.0]}, seed=0)
    weak, strong = _FakeTrial("weak", {"lr": 0.1}), _FakeTrial("strong", {"lr": 0.9})
    sched.on_trial_result(strong, {"training_iteration": 8, "score": 8.0})
    sched.on_trial_result(weak, {"training_iteration": 4, "score": 0.4})
    new_cfg, _ = sched.exploit_target(weak)
    assert sched.policy_log[-1]["config"]["lr"] == new_cfg["lr"]


def test_replay_binds_to_one_trial():
    from ray_tpu.tune import PopulationBasedTrainingReplay

    replay = PopulationBasedTrainingReplay([(4, {"lr": 0.5})])
    a, b = _FakeTrial("a", {"lr": 0.1}), _FakeTrial("b", {"lr": 0.2})
    replay.on_trial_result(a, {"training_iteration": 2, "score": 1.0})
    with pytest.warns(RuntimeWarning, match="ONE trial"):
        replay.on_trial_result(b, {"training_iteration": 4, "score": 1.0})
    # the sibling never consumes the policy step...
    assert replay.exploit_target(b) is None and replay._next == 0
    # ...which stays available for the bound trial
    replay.on_trial_result(a, {"training_iteration": 4, "score": 2.0})
    out = replay.exploit_target(a)
    assert out is not None and out[0]["lr"] == 0.5 and replay._next == 1


def test_distribute_resources_floor_is_declared_request():
    from ray_tpu.tune import DistributeResources

    class _Ctl:
        class trainable:
            _tune_resources = {"CPU": 4, "TPU": 2}

        trials = []

    alloc = DistributeResources()
    out = alloc(_Ctl(), _FakeTrial("t", {}), {"training_iteration": 1}, None)
    assert out["CPU"] >= 4.0          # never below the declared request
    assert out["TPU"] == 2            # accelerators pass through


# --------------------------------------------------------------------------
# Tuner.restore / can_restore (parity: reference Tuner resume)
# --------------------------------------------------------------------------
def _resumable_trainable(config):
    """Counts iterations through its checkpoint, so a resumed trial
    continues instead of restarting; every executed step is appended to
    config["log"] so tests can see exactly what re-ran."""
    from ray_tpu.train import Checkpoint
    from ray_tpu.tune.session import get_checkpoint

    ckpt = get_checkpoint()
    start = ckpt.to_dict()["i"] + 1 if ckpt is not None else 0
    for i in range(start, 4):
        with open(config["log"], "a") as f:
            f.write(f"{config['x']},{i}\n")
        tune.report(
            {"training_iteration": i + 1, "i": i, "x": config["x"]},
            checkpoint=Checkpoint.from_dict({"i": i}),
        )


def test_tuner_restore_reruns_only_unfinished_trials(tmp_path):
    import pickle

    from ray_tpu.tune import TuneConfig, Tuner
    from ray_tpu.train import RunConfig

    tune_cfg = dict(metric="i", mode="max", num_samples=1)
    log = str(tmp_path / "steps.log")
    tuner = Tuner(
        _resumable_trainable,
        param_space={"x": tune.grid_search([10, 20, 30]), "log": log},
        tune_config=TuneConfig(**tune_cfg),
        run_config=RunConfig(name="resume_exp", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results) == 3 and all(r.metrics["i"] == 3 for r in results)
    exp_dir = str(tmp_path / "resume_exp")
    assert Tuner.can_restore(exp_dir)

    # simulate an interruption: mark the last trial unfinished at i=1
    state_path = exp_dir + "/experiment_state.pkl"
    with open(state_path, "rb") as f:
        state = pickle.load(f)
    from ray_tpu.train import Checkpoint

    doctored = state["trials"][-1]
    doctored["status"] = "RUNNING"
    doctored["last_result"] = {"training_iteration": 2, "i": 1, "x": doctored["config"]["x"]}
    doctored["checkpoint_path"] = Checkpoint.from_dict(
        {"i": 1}, base_dir=str(tmp_path / "interrupted_ckpt")).path
    with open(state_path, "wb") as f:
        pickle.dump(state, f)

    open(log, "w").close()  # observe only post-restore executions
    restored = Tuner.restore(
        exp_dir, _resumable_trainable,
        param_space={"x": tune.grid_search([10, 20, 30]), "log": log},
        tune_config=TuneConfig(**tune_cfg),
    ).fit()
    assert len(restored) == 3
    by_x = {r.config["x"]: r for r in restored}
    # finished trials kept their recorded results without re-running
    assert by_x[10].metrics["i"] == 3 and by_x[20].metrics["i"] == 3
    # the interrupted one resumed from its checkpoint (i=1 -> 2, 3)
    assert by_x[30].metrics["i"] == 3
    # and the grid was NOT re-suggested from the start: exactly 3 trials
    assert len({r.config["x"] for r in restored}) == 3
    # only the interrupted trial executed, and only its REMAINING steps
    steps = [tuple(map(int, l.split(","))) for l in open(log) if l.strip()]
    assert steps == [(30, 2), (30, 3)], steps


def test_tuner_restore_requires_state(tmp_path):
    from ray_tpu.tune import Tuner

    assert not Tuner.can_restore(str(tmp_path))
    with pytest.raises(ValueError, match="no experiment state"):
        Tuner.restore(str(tmp_path), lambda c: None)


def test_tuner_get_results():
    from ray_tpu.tune import TuneConfig, Tuner

    def trainable(config):
        tune.report({"score": config["x"]})

    t = Tuner(trainable, param_space={"x": tune.grid_search([1, 2])},
              tune_config=TuneConfig(metric="score", mode="max"))
    with pytest.raises(RuntimeError, match="call fit"):
        t.get_results()
    grid = t.fit()
    assert t.get_results() is grid


def test_tuner_restore_requires_param_space(tmp_path):
    import pickle

    from ray_tpu.tune import Tuner

    (tmp_path / "experiment_state.pkl").write_bytes(pickle.dumps({"trials": []}))
    with pytest.raises(ValueError, match="param_space"):
        Tuner.restore(str(tmp_path), lambda c: None)


def test_restore_keeps_errored_trials_errored(tmp_path):
    import pickle

    from ray_tpu.tune import TuneConfig, Tuner

    def trainable(config):
        tune.report({"i": 1})

    exp = tmp_path / "err_exp"
    exp.mkdir()
    state = {"trials": [
        {"trial_id": "trial_00000", "config": {"x": 1}, "status": "TERMINATED",
         "last_result": {"i": 1}, "history": [], "checkpoint_path": None,
         "error": None},
        {"trial_id": "trial_00001", "config": {"x": 2}, "status": "ERROR",
         "last_result": {}, "history": [], "checkpoint_path": None,
         "error": "ValueError('bad config')"},
    ]}
    (exp / "experiment_state.pkl").write_bytes(pickle.dumps(state))
    results = Tuner.restore(
        str(exp), trainable, param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="i", mode="max"),
    ).fit()
    assert len(results) == 2
    errs = results.errors
    assert len(errs) == 1 and "bad config" in str(errs[0])


def test_tpe_on_restore_registers_live_and_completed():
    from ray_tpu.tune.search import TPESearcher

    space = {"x": tune.uniform(0.0, 1.0)}
    s = TPESearcher(space, metric="score", mode="max")
    s.on_restore("done", {"x": 0.7}, {"score": 1.0}, completed=True)
    assert s._observed[-1] == ({"x": 0.7}, 1.0)
    s.on_restore("inflight", {"x": 0.2}, {}, completed=False)
    assert s._live["inflight"] == {"x": 0.2}
    # the resumed trial's eventual completion pairs with its REAL config
    s.on_trial_complete("inflight", {"score": 2.0})
    assert s._observed[-1] == ({"x": 0.2}, 2.0)
