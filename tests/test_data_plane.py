"""Peer-to-peer chunked object data plane.

Round-3 milestone: bulk object bytes move agent-to-agent on dedicated data
sockets (``runtime/data_plane.py``) — the head is only the address book.
Validates the reference object manager's roles (node-to-node Push/Pull with
chunking and admission control — object_manager.h:117, pull_manager.h:52,
push_manager.h:30) and the round-2 verdict's acceptance bar: a large
dependency between two agents never transits the head, and control-plane
RTT stays low while bulk bytes are in flight.
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ObjectStore
from ray_tpu.runtime import data_plane

from test_multihost import REPO_ROOT, _spawn_agent, _wait_for_nodes  # noqa: F401


# ==========================================================================
# unit: DataServer / DataClient over a local ObjectStore
# ==========================================================================
@pytest.fixture
def server_store():
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store, chunk_bytes=1 << 20)
    yield store, server
    server.close()


def test_pull_roundtrip(server_store):
    store, server = server_store
    oid = ObjectID.from_random()
    value = np.arange(1000, dtype=np.int64)
    store.put(oid, value)

    client = data_plane.DataClient(chunk_bytes=1 << 20)
    got, is_error = client.pull(server.address, oid.binary())
    assert not is_error
    np.testing.assert_array_equal(got, value)
    client.close()


def test_pull_chunked_large_object(server_store):
    store, server = server_store
    oid = ObjectID.from_random()
    value = np.random.default_rng(0).integers(0, 255, size=5 * (1 << 20), dtype=np.uint8)
    store.put(oid, value)

    client = data_plane.DataClient(chunk_bytes=1 << 20)
    got, _ = client.pull(server.address, oid.binary())
    np.testing.assert_array_equal(got, value)
    # out-of-band frames: the array bytes moved raw, not as one pickle frame
    assert server.stats.snapshot()["bytes_sent"] >= value.nbytes
    client.close()


def test_push_roundtrip(server_store):
    store, server = server_store
    oid = ObjectID.from_random()
    value = {"weights": np.ones((256, 256), np.float32), "step": 7}
    client = data_plane.DataClient(chunk_bytes=1 << 20)
    client.push(server.address, oid.binary(), value)
    got = store.get(oid, timeout=5)
    assert got["step"] == 7
    np.testing.assert_array_equal(got["weights"], value["weights"])
    client.close()


def test_pull_not_found(server_store):
    _store, server = server_store
    client = data_plane.DataClient()
    with pytest.raises(data_plane.ObjectNotFound):
        client.pull(server.address, ObjectID.from_random().binary(), timeout=0.2)
    client.close()


def test_pull_waits_for_inflight_materialization(server_store):
    """A pull that arrives before the object materializes blocks (on its own
    data thread) and completes when the value lands — in-flight pushes are
    transparent to consumers."""
    store, server = server_store
    oid = ObjectID.from_random()

    def late_put():
        time.sleep(0.3)
        store.put(oid, b"late-bytes")

    threading.Thread(target=late_put, daemon=True).start()
    client = data_plane.DataClient()
    got, _ = client.pull(server.address, oid.binary(), timeout=10)
    assert got == b"late-bytes"
    client.close()


def test_error_objects_carry_flag(server_store):
    store, server = server_store
    oid = ObjectID.from_random()
    store.put(oid, ValueError("boom"), is_error=True)
    client = data_plane.DataClient()
    got, is_error = client.pull(server.address, oid.binary())
    assert is_error
    assert isinstance(got, ValueError)
    client.close()


def test_concurrent_pulls(server_store):
    """Admission control queues, never drops: many concurrent pulls all
    complete even above the concurrency cap."""
    store, server = server_store
    oids = []
    for i in range(12):
        oid = ObjectID.from_random()
        store.put(oid, np.full(200_000, i, np.int32))
        oids.append(oid)
    client = data_plane.DataClient(max_concurrent=3)
    results = [None] * len(oids)

    def pull(i):
        results[i], _ = client.pull(server.address, oids[i].binary())

    threads = [threading.Thread(target=pull, args=(i,)) for i in range(len(oids))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for i, r in enumerate(results):
        assert r is not None and r[0] == i
    client.close()


def test_recv_exact_large_reads_use_single_buffer():
    """Reads >= 1 MiB route through recv_into on one preallocated buffer —
    no per-chunk bytes objects and no b"".join copy (ISSUE 3 satellite)."""
    import socket as socket_mod

    a, b = socket_mod.socketpair()
    try:
        payload = os.urandom(3 * (1 << 20) + 17)

        def send():
            a.sendall(payload)

        t = threading.Thread(target=send, daemon=True)
        t.start()
        got = data_plane._recv_exact(b, len(payload))
        t.join(timeout=10)
        assert isinstance(got, bytearray)  # the recv_into path, not join()
        assert bytes(got) == payload
        # small reads still return bytes
        a.sendall(b"tiny")
        small = data_plane._recv_exact(b, 4)
        assert isinstance(small, bytes) and small == b"tiny"
    finally:
        a.close()
        b.close()


# ==========================================================================
# integration: two agents, peer-to-peer transfer (the round-3 bar)
# ==========================================================================
@pytest.fixture
def two_agent_cluster():
    rt.init(num_cpus=2)
    cluster = rt.get_cluster()
    address = cluster.start_head_service()
    proc_a = _spawn_agent(address, extra_resources='{"ra": 4}')
    proc_b = _spawn_agent(address, extra_resources='{"rb": 4}')
    try:
        _wait_for_nodes(cluster, 3)
        yield cluster
    finally:
        for p in (proc_a, proc_b):
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        rt.shutdown()


def _head_bulk_stats(cluster):
    head = cluster.head_service
    ds = head.data_server.stats.snapshot()
    dc = head.data_client.stats.snapshot()
    return {
        "served_bytes": ds["bytes_sent"] + ds["bytes_received"],
        "client_bytes": dc["bytes_sent"] + dc["bytes_received"],
    }


def test_1gb_dependency_never_transits_head_and_control_stays_live(two_agent_cluster):
    """THE acceptance test: a ~1 GB object produced on agent A and consumed
    on agent B moves directly A→B on the data plane.  The head serves only
    the locate_object metadata — zero bulk bytes transit it — and its
    control connections answer pings in <10 ms while the transfer runs."""
    cluster = two_agent_cluster
    n = 1 << 30  # 1 GiB of uint8

    @rt.remote(resources={"ra": 1})
    def produce():
        return np.ones(n, np.uint8)

    @rt.remote(resources={"rb": 1})
    def consume(x):
        return int(x[:10].sum()) + int(x[-10:].sum()), x.nbytes

    before = _head_bulk_stats(cluster)
    ref = produce.remote()
    done = threading.Event()
    result_box = {}

    def run_consume():
        try:
            result_box["value"] = rt.get(consume.remote(ref), timeout=300)
        finally:
            done.set()

    t = threading.Thread(target=run_consume, daemon=True)
    t.start()

    # ping every agent's CONTROL connection while the bulk bytes fly: a
    # single fast answer per probe proves control never queues behind data
    rtts = []
    while not done.is_set():
        for conn in cluster.head_service.server.connections():
            t0 = time.monotonic()
            try:
                conn.request("ping", {}, timeout=5)
                rtts.append(time.monotonic() - t0)
            except Exception:
                pass
        time.sleep(0.02)
    t.join(timeout=10)

    assert result_box["value"] == (20, n)
    after = _head_bulk_stats(cluster)
    # no bulk byte transited the head in either direction
    assert after["served_bytes"] == before["served_bytes"]
    assert after["client_bytes"] == before["client_bytes"]
    # control stayed responsive during the transfer
    assert rtts, "no pings completed during the transfer"
    assert min(rtts) < 0.010, f"min control RTT {min(rtts)*1e3:.1f} ms"


def test_direct_pull_records_location_at_head(two_agent_cluster):
    cluster = two_agent_cluster

    @rt.remote(resources={"ra": 1})
    def produce():
        return np.arange(2_000_000, dtype=np.int64)  # 16 MB: lazy commit

    @rt.remote(resources={"rb": 1})
    def consume(x):
        return int(x[123])

    ref = produce.remote()
    assert rt.get(consume.remote(ref), timeout=120) == 123
    # after the direct pull, BOTH agents are recorded locations (the
    # object_location notice): recovery and future consumers see the copy
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if len(cluster.directory.locations(ref.id())) >= 2:
            break
        time.sleep(0.05)
    assert len(cluster.directory.locations(ref.id())) >= 2


def test_driver_get_of_lazy_remote_result_uses_data_plane(two_agent_cluster):
    cluster = two_agent_cluster

    @rt.remote(resources={"ra": 1})
    def produce():
        return np.full(1_000_000, 7, np.int32)  # 4 MB: lazy commit

    before = cluster.head_service.data_client.stats.snapshot()["pulls_issued"]
    out = rt.get(produce.remote(), timeout=120)
    assert out.shape == (1_000_000,) and int(out[0]) == 7
    after = cluster.head_service.data_client.stats.snapshot()["pulls_issued"]
    assert after > before  # the bytes came over the data plane, not control


def test_peer_death_mid_transfer_recovers(two_agent_cluster):
    """Chaos: the producing agent dies while a consumer depends on its lazy
    result — the pull fails over to lineage reconstruction and the consumer
    still completes (PullManager + recovery roles together)."""
    cluster = two_agent_cluster

    @rt.remote(resources={"ra": 1}, max_retries=2)
    def produce():
        return np.ones(4_000_000, np.uint8)  # 4MB: lazy commit on agent A

    @rt.remote(resources={"rb": 1})
    def consume(x):
        return int(x[0]) + x.nbytes

    ref = produce.remote()
    rt.wait([ref], num_returns=1, timeout=60)

    # kill agent A (the only holder of the bytes) BEFORE the consumer
    # pulls, via the cluster chaos hook (same path as a real death:
    # socket close + node sweep)
    target = next(
        nid for nid, n in cluster.nodes.items()
        if not n.dead and (n.pool.total.to_dict().get("ra", 0) > 0)
    )
    cluster.kill_node(target)

    # the dependency's only copy died; lineage resubmits produce (retries
    # left) onto... only 'ra' existed on the dead node, so reconstruction
    # is infeasible — the consumer must FAIL CLEANLY, not hang
    try:
        out = rt.get(consume.remote(ref), timeout=90)
        # if a second ra-capable node existed the value would reconstruct;
        # with it gone, reaching here means the pull fell back before death
        assert out == 1 + 4_000_000
    except Exception as exc:  # noqa: BLE001 — clean failure is the contract
        assert "Lost" in type(exc).__name__ or "Task" in type(exc).__name__, exc


def test_small_values_stay_on_control_plane(two_agent_cluster):
    """Latency path: tiny results ride the ordered control connection (no
    extra data-plane round trip)."""
    cluster = two_agent_cluster

    @rt.remote(resources={"ra": 1})
    def tiny():
        return 42

    before = cluster.head_service.data_client.stats.snapshot()["pulls_issued"]
    assert rt.get(tiny.remote(), timeout=60) == 42
    after = cluster.head_service.data_client.stats.snapshot()["pulls_issued"]
    assert after == before


# ==========================================================================
# same-host shm handoff (plasma zero-copy local sharing role: store.h:55)
# ==========================================================================
@pytest.fixture
def shm_server_store():
    from ray_tpu.native.shm_store import ShmObjectStore

    shm = ShmObjectStore(f"/rt_test_dp_{os.getpid():x}_{os.urandom(2).hex()}", 1 << 28)
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store, chunk_bytes=1 << 20, shm_store=shm)
    yield store, server, shm
    server.close()
    shm.close()
    shm.unlink()


def test_same_host_pull_moves_zero_socket_bytes(shm_server_store):
    """A same-host pull hands the payload through the shm arena: the data
    socket carries only the offer header — zero object bytes."""
    store, server, shm = shm_server_store
    oid = ObjectID.from_random()
    value = np.arange(500_000, dtype=np.int64)  # 4 MB, well over inline
    store.put(oid, value)

    client = data_plane.DataClient(chunk_bytes=1 << 20)
    got, is_error = client.pull(server.address, oid.binary())
    assert not is_error
    np.testing.assert_array_equal(got, value)
    stats = server.stats.snapshot()
    assert stats["shm_handoffs"] == 1
    assert stats["bytes_sent"] == 0  # ZERO object bytes on the socket
    assert client.stats.snapshot()["shm_handoffs"] == 1
    # handoff values are read-only views (plasma Get semantics)
    assert isinstance(got, np.ndarray) and not got.flags.writeable
    client.close()


def test_same_host_pull_disabled_by_config(shm_server_store, monkeypatch):
    store, server, shm = shm_server_store
    monkeypatch.setenv("RAY_TPU_SAME_HOST_SHM_TRANSFER", "0")
    from ray_tpu.core import config as config_mod

    config_mod.reload_config() if hasattr(config_mod, "reload_config") else None
    oid = ObjectID.from_random()
    value = np.arange(200_000, dtype=np.int64)
    store.put(oid, value)
    client = data_plane.DataClient(chunk_bytes=1 << 20)
    try:
        from ray_tpu.core.config import get_config

        if get_config().same_host_shm_transfer:
            pytest.skip("config not env-reloadable in-process")
        got, _ = client.pull(server.address, oid.binary())
        np.testing.assert_array_equal(got, value)
        assert server.stats.snapshot()["shm_handoffs"] == 0
    finally:
        client.close()


def test_shm_offer_fallback_when_entry_vanishes(shm_server_store):
    """If the staged/passthrough entry disappears between offer and consume,
    the client falls back to the socket path and still succeeds."""
    store, server, shm = shm_server_store
    oid = ObjectID.from_random()
    value = np.arange(300_000, dtype=np.int64)
    store.put(oid, value)

    client = data_plane.DataClient(chunk_bytes=1 << 20)
    real_consume = client._consume_shm_offer
    calls = {"n": 0}

    def broken_consume(offer, is_error):
        calls["n"] += 1
        raise data_plane.DataPlaneError("simulated vanished entry")

    client._consume_shm_offer = broken_consume
    got, _ = client.pull(server.address, oid.binary())
    np.testing.assert_array_equal(got, value)
    assert calls["n"] == 1  # the shm path was attempted, then fell back
    client._consume_shm_offer = real_consume
    client.close()


def test_worker_put_refs_release_arena(tmp_path):
    """Worker-side borrower ledger: dropping the last worker-held ref for a
    bulk put drains the head's shm arena (regression: pins used to live for
    the job's lifetime, so put churn filled the arena forever)."""
    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        if cluster.shm_store is None:
            pytest.skip("no shm arena on this host")

        @rt.remote
        def churn():
            for _ in range(3):
                r = rt.put(np.zeros(2 * 1024 * 1024, dtype=np.uint8))
                del r
            return None

        rt.get(churn.remote(), timeout=60)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if cluster.shm_store.num_objects == 0:
                break
            time.sleep(0.2)
        assert cluster.shm_store.num_objects == 0, (
            f"arena still holds {cluster.shm_store.num_objects} objects "
            f"({cluster.shm_store.used_bytes >> 20} MB) after refs died"
        )
    finally:
        rt.shutdown()
