"""Tests for ray_tpu.serve — deployment lifecycle, routing, batching,
composition, autoscaling, HTTP ingress (mirrors serve/tests strategy:
drive real HTTP)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=8)
    serve.start(http_port=0)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _clean_apps():
    yield
    # delete all deployments between tests
    st = serve.status()
    for name in st["deployments"]:
        serve.delete(name)


def test_function_deployment():
    @serve.deployment
    def hello(name):
        return f"hello {name}"

    handle = serve.run(hello.bind(), route_prefix=None)
    assert handle.remote("world").result() == "hello world"


def test_class_deployment_with_state():
    @serve.deployment
    class Counter:
        def __init__(self, start):
            self.count = start

        def __call__(self, inc):
            self.count += inc
            return self.count

    handle = serve.run(Counter.bind(10), route_prefix=None)
    assert handle.remote(1).result() == 11
    assert handle.remote(2).result() == 13


def test_method_calls():
    @serve.deployment
    class Calc:
        def add(self, a, b):
            return a + b

        def mul(self, a, b):
            return a * b

    handle = serve.run(Calc.bind(), route_prefix=None)
    assert handle.add.remote(2, 3).result() == 5
    assert handle.mul.remote(2, 3).result() == 6


def test_multiple_replicas_route():
    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __init__(self):
            self.id = id(self)

        def __call__(self, _):
            return self.id

    handle = serve.run(WhoAmI.bind(), route_prefix=None)
    seen = {handle.remote(None).result() for _ in range(30)}
    assert len(seen) >= 2  # pow-2 routing spreads load


def test_composition():
    @serve.deployment
    class Preprocess:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Model:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            doubled = self.pre.remote(x).result()
            return doubled + 1

    handle = serve.run(Model.bind(Preprocess.bind()), route_prefix=None)
    assert handle.remote(10).result() == 21


def test_batching():
    batch_sizes = []

    @serve.deployment
    class BatchedModel:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.1)
        def handle_batch(self, items):
            batch_sizes.append(len(items))
            return [i * 10 for i in items]

        def __call__(self, x):
            return self.handle_batch(x)

    handle = serve.run(BatchedModel.bind(), route_prefix=None)
    responses = [handle.remote(i) for i in range(8)]
    results = sorted(r.result() for r in responses)
    assert results == [i * 10 for i in range(8)]
    assert max(batch_sizes) > 1  # some batching actually happened


def test_http_ingress():
    @serve.deployment
    def echo(payload):
        return {"got": payload}

    serve.run(echo.bind(), route_prefix="/echo")
    url = serve.proxy_url()
    req = urllib.request.Request(
        url + "/echo", data=json.dumps({"a": 1}).encode(), headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        body = json.loads(resp.read())
    assert body == {"got": {"a": 1}}


def test_http_404():
    url = serve.proxy_url()
    try:
        urllib.request.urlopen(url + "/nonexistent-route-xyz", timeout=10)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_user_config_reconfigure():
    @serve.deployment(user_config={"threshold": 5})
    class Configurable:
        def __init__(self):
            self.threshold = 0

        def reconfigure(self, config):
            self.threshold = config["threshold"]

        def __call__(self, _):
            return self.threshold

    handle = serve.run(Configurable.bind(), route_prefix=None)
    assert handle.remote(None).result() == 5


def test_autoscaling_scales_up():
    @serve.deployment(
        autoscaling_config={"min_replicas": 1, "max_replicas": 3, "target_ongoing_requests": 1, "upscale_delay_s": 0.0},
        max_ongoing_requests=16,
    )
    class Slow:
        def __call__(self, _):
            time.sleep(0.3)
            return 1

    handle = serve.run(Slow.bind(), route_prefix=None)
    # flood with concurrent requests from threads
    results = []

    def worker():
        for _ in range(3):
            results.append(handle.remote(None).result())

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 18
    st = serve.status()["deployments"]["Slow"]
    assert st["num_replicas"] >= 2  # scaled beyond min


def test_multiplexing():
    from ray_tpu.serve import get_multiplexed_model_id

    loads = []

    @serve.deployment
    class MultiModel:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            loads.append(model_id)
            return lambda x: f"{model_id}:{x}"

        def __call__(self, payload):
            model = self.get_model(payload["model"])
            return model(payload["x"])

    handle = serve.run(MultiModel.bind(), route_prefix=None)
    assert handle.remote({"model": "a", "x": 1}).result() == "a:1"
    assert handle.remote({"model": "a", "x": 2}).result() == "a:2"
    assert handle.remote({"model": "b", "x": 3}).result() == "b:3"
    assert loads == ["a", "b"]  # model "a" cached after first load


def test_deploy_upgrade_replaces():
    @serve.deployment
    def v(x):
        return "v1"

    serve.run(v.bind(), route_prefix=None)

    @serve.deployment(name="v")
    def v2(x):
        return "v2"

    handle = serve.run(v2.bind(), route_prefix=None)
    time.sleep(0.3)
    assert handle.remote(None).result() == "v2"


# --------------------------------------------------------------------------
# Declarative config (schema.py; parity: serve deploy config path)
# --------------------------------------------------------------------------
# module-level bound app the config import_path can resolve
@serve.deployment
def _config_app_fn(x):
    return {"doubled": x * 2}


config_app = _config_app_fn.bind()


def test_run_config_deploys_and_overrides():
    cfg = {
        "applications": [
            {
                "name": "cfg_app",
                "route_prefix": "/cfg",
                "import_path": "tests.test_serve:config_app",
                "deployments": [
                    {"name": "_config_app_fn", "num_replicas": 2, "max_ongoing_requests": 7}
                ],
            }
        ]
    }
    deployed = serve.run_config(cfg)
    assert deployed["cfg_app"]["ingress"] == "_config_app_fn"
    handle = serve.get_deployment_handle("_config_app_fn")
    assert handle.remote(21).result() == {"doubled": 42}
    st = serve.status()["deployments"]["_config_app_fn"]
    assert st["target_replicas"] == 2


def test_config_validation_rejects_bad_configs():
    from ray_tpu.serve.schema import ServeConfigError, validate_config

    with pytest.raises(ServeConfigError):
        validate_config({})
    with pytest.raises(ServeConfigError):
        validate_config({"applications": [{"name": "x"}]})  # no import_path
    with pytest.raises(ServeConfigError):
        validate_config(
            {
                "applications": [
                    {"name": "a", "import_path": "m:a", "route_prefix": "nope"}
                ]
            }
        )
    with pytest.raises(ServeConfigError):
        validate_config(
            {
                "applications": [
                    {"name": "a", "import_path": "m:a"},
                    {"name": "a", "import_path": "m:b"},
                ]
            }
        )


def test_run_config_from_yaml_file(tmp_path):
    import yaml

    path = tmp_path / "serve.yaml"
    path.write_text(
        yaml.safe_dump(
            {
                "applications": [
                    {
                        "name": "yaml_app",
                        "route_prefix": "/yaml",
                        "import_path": "tests.test_serve:config_app",
                    }
                ]
            }
        )
    )
    deployed = serve.run_config(str(path))
    assert "yaml_app" in deployed
    handle = serve.get_deployment_handle("_config_app_fn")
    assert handle.remote(3).result() == {"doubled": 6}


def test_long_poll_pushes_membership():
    """The router's long-poll watcher must pick up scale-ups without a
    request-driven refresh."""

    @serve.deployment(num_replicas=1)
    class Scaled:
        def __call__(self, x):
            return x

    handle = serve.run(Scaled.bind(), route_prefix=None)
    assert handle.remote(1).result() == 1
    router = handle._router
    v0 = router._version
    # scale up via redeploy and wait for the watcher to observe it
    serve.run(Scaled.options(num_replicas=3).bind(), route_prefix=None)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(router._replicas) < 3:
        time.sleep(0.05)
    assert len(router._replicas) == 3
    assert router._version != v0


def test_dead_replica_replaced_by_health_check():
    """A replica whose actor dies must be pruned by the controller's health
    check and respawned by reconcile; requests keep succeeding."""

    @serve.deployment(num_replicas=2)
    class Svc:
        def __call__(self, x):
            return x + 1

    handle = serve.run(Svc.bind(), route_prefix=None)
    assert handle.remote(1).result() == 2
    # kill one replica actor out from under the controller
    from ray_tpu.serve import api as serve_api

    _v, replicas = ray_tpu.get(
        serve_api._controller.get_replicas.remote("Svc")
    )
    ray_tpu.kill(replicas[0])
    deadline = time.monotonic() + 15
    healed = False
    while time.monotonic() < deadline:
        _v2, reps = ray_tpu.get(serve_api._controller.get_replicas.remote("Svc"))
        if len(reps) == 2 and replicas[0] not in reps:
            healed = True
            break
        time.sleep(0.1)
    assert healed, "controller never replaced the killed replica"
    for i in range(6):
        assert handle.remote(i).result() == i + 1


def test_requests_failover_while_replica_dies():
    """The full-suite flake made real: requests racing a replica's death
    window (killed, not yet replaced) must fail over through the router —
    the dead replica is pruned locally and the retry waits for usable
    membership — never surfacing ActorDiedError to the caller."""

    # idempotent=True: replica-death replay is gated on the deployment
    # declaring re-execution safe (ISSUE 9 satellite) — pure functions are
    @serve.deployment(num_replicas=2, idempotent=True)
    class Svc:
        def __call__(self, x):
            return x * 10

    handle = serve.run(Svc.bind(), route_prefix=None)
    assert handle.remote(1).result() == 10
    from ray_tpu.serve import api as serve_api

    _v, replicas = ray_tpu.get(serve_api._controller.get_replicas.remote("Svc"))
    # kill and IMMEDIATELY hammer — no wait for the health check
    ray_tpu.kill(replicas[0])
    results = [handle.remote(i).result(timeout=60) for i in range(12)]
    assert results == [i * 10 for i in range(12)]


def test_single_replica_failover_waits_for_replacement():
    """num_replicas=1 is the deterministic worst case: every request picks
    the (only) dead replica, so failover must WAIT for the controller's
    replacement, not burn retries against the stale snapshot."""

    @serve.deployment(num_replicas=1, idempotent=True)
    class Solo:
        def __call__(self, x):
            return x + 100

    handle = serve.run(Solo.bind(), route_prefix=None)
    assert handle.remote(1).result() == 101
    from ray_tpu.serve import api as serve_api

    _v, replicas = ray_tpu.get(serve_api._controller.get_replicas.remote("Solo"))
    ray_tpu.kill(replicas[0])
    assert handle.remote(7).result(timeout=60) == 107


def test_application_topology_in_status():
    """serve.status() exposes the deployment DAG (the dashboard's
    application topology view): ingress marked, dependencies-first edges."""

    @serve.deployment
    class Embed:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Rank:
        def __init__(self, embed):
            self.embed = embed

        def __call__(self, x):
            return self.embed.remote(x).result() + 1

    handle = serve.run(Rank.bind(Embed.bind()), name="pipeline", route_prefix=None)
    assert handle.remote(3).result() == 7
    from ray_tpu import serve as serve_mod

    topo = serve_mod.status()["applications"]["pipeline"]
    assert topo["ingress"] == "Rank"
    by_name = {d["name"]: d for d in topo["deployments"]}
    assert by_name["Rank"]["depends_on"] == ["Embed"]
    assert by_name["Embed"]["depends_on"] == []
