"""Request-scope serving observability tests (ISSUE 16).

Covers the three tentpole layers end to end:

  * ``LatencySketch`` — deterministic quantiles (same multiset, any
    insertion order, byte-identical answers), associative/commutative
    merge, boundary-mismatch rejection, wire round-trip, and the
    float-rank guard at p99.
  * ``RequestTrace`` / ``TraceStore`` — the phase algebra (waterfall sums
    exactly to e2e, handler fallback for non-LLM requests, idempotent
    marks, first-terminal-claim-wins), bounded rings under 10k traces,
    the sampling knob, and the off switch.
  * the serving stack — phase monotonicity + completeness for real HTTP
    requests (streaming and non-streaming) through proxy -> router ->
    replica -> engine, engine-side sketches/finished-ring without any
    HTTP ingress, the flight recorder's event-ring snapshots, and the
    chaos contract: same-seed fault logs are byte-identical with
    ``serve_request_trace`` on vs off (tracing consumes zero failpoint
    decisions).
"""

import json
import time
import uuid

import jax
import jax.numpy as jnp
import pytest

import ray_tpu as rt
from ray_tpu.core.config import get_config
from ray_tpu.observability import reqtrace
from ray_tpu.observability.reqtrace import MARKS, RequestTrace, TraceStore
from ray_tpu.observability.sketch import (
    SERVING_LATENCY_BOUNDS,
    LatencySketch,
    merged,
)

CFG = None  # built lazily: the sketch/trace tests must not touch JAX


def _model_cfg():
    global CFG
    if CFG is None:
        from ray_tpu.models import TransformerConfig

        CFG = TransformerConfig(
            vocab_size=89, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=64, attention="dense", dtype=jnp.float32,
        )
    return CFG


@pytest.fixture(scope="module")
def params():
    from ray_tpu.models import init_params

    return init_params(_model_cfg(), jax.random.key(11))


@pytest.fixture(autouse=True)
def _clean_trace_store():
    reqtrace.global_trace_store().reset()
    yield
    reqtrace.global_trace_store().reset()


# --------------------------------------------------------------------------
# LatencySketch: determinism, merge algebra, wire format
# --------------------------------------------------------------------------
_OBS = [0.0003, 0.0009, 0.004, 0.004, 0.03, 0.07, 0.2, 0.8, 3.0, 45.0]


def test_sketch_deterministic_and_order_invariant():
    a, b = LatencySketch(), LatencySketch()
    for v in _OBS:
        a.observe(v)
    for v in reversed(_OBS):  # same multiset, different insertion order
        b.observe(v)
    assert a.to_dict() == b.to_dict()
    assert a.percentiles() == b.percentiles()
    # quantiles answer with bucket upper edges (or the exact max overflow)
    assert a.quantile(0.5) in SERVING_LATENCY_BOUNDS
    assert a.quantile(1.0) == 45.0  # overflow bucket answers the true max


def test_sketch_quantile_edges():
    sk = LatencySketch()
    assert sk.quantile(0.5) == 0.0  # empty
    assert sk.percentiles()["count"] == 0
    sk.observe(0.003)
    # single observation: every quantile is its bucket's upper edge
    assert sk.quantile(0.01) == sk.quantile(0.99) == 0.005
    assert sk.percentiles()["max"] == 0.003


def test_sketch_p99_float_rank_guard():
    """0.99 * 100 is 99.000...01 in IEEE; a bare ceil would bump the rank
    to 100 and misreport p99 as the single outlier."""
    sk = LatencySketch()
    for _ in range(99):
        sk.observe(0.0001)
    sk.observe(99.0)
    assert sk.quantile(0.99) == SERVING_LATENCY_BOUNDS[0]
    assert sk.quantile(1.0) == 99.0


def test_sketch_merge_associative_commutative():
    def fresh(values):
        sk = LatencySketch()
        for v in values:
            sk.observe(v)
        return sk

    a_obs, b_obs, c_obs = _OBS[:3], _OBS[3:7], _OBS[7:]
    left = merged([fresh(a_obs), fresh(b_obs)]).merge(fresh(c_obs))
    right = fresh(a_obs).merge(merged([fresh(b_obs), fresh(c_obs)]))
    shuffled = merged([fresh(c_obs), fresh(a_obs), fresh(b_obs)])
    for other in (right, shuffled, fresh(_OBS)):
        # counts/total/max (and therefore every quantile) are exactly
        # associative; `sum` is float addition, order-dependent in the ulps
        assert left.counts == other.counts
        assert left.total == other.total == len(_OBS)
        assert left.max == other.max
        for q in (0.5, 0.95, 0.99, 1.0):
            assert left.quantile(q) == other.quantile(q)
        assert left.sum == pytest.approx(other.sum, rel=1e-12)


def test_sketch_boundary_contract():
    with pytest.raises(ValueError):
        LatencySketch((0.5, 0.1))  # unsorted
    with pytest.raises(ValueError):
        LatencySketch(())  # empty
    with pytest.raises(ValueError):
        LatencySketch((0.1, 1.0)).merge(LatencySketch())  # mismatched grids


def test_sketch_wire_roundtrip():
    sk = LatencySketch()
    for v in _OBS:
        sk.observe(v)
    clone = LatencySketch.from_dict(json.loads(json.dumps(sk.to_dict())))
    assert clone.to_dict() == sk.to_dict()
    assert clone.percentiles() == sk.percentiles()


# --------------------------------------------------------------------------
# RequestTrace: the phase algebra
# --------------------------------------------------------------------------
def _routed_trace(**kw):
    tr = RequestTrace(route="/llm", deployment="LLMServer", **kw)
    for name in ("router_in", "router_dequeue", "replica_in",
                 "engine_submit", "wfq_pop", "admitted"):
        tr.mark(name)
    return tr


def test_trace_phases_sum_exactly_to_e2e():
    store = TraceStore(ring=16)
    tr = _routed_trace()
    tr.note_token(0.0)  # stamps first_token
    for gap in (0.001, 0.003, 0.002):
        tr.note_token(gap)
    store.finish(tr, "ok")
    phases = tr.phases()
    assert [p for p, _, _ in phases] == [
        "proxy", "router_queue", "dispatch", "replica",
        "engine_queue", "kv_block_wait", "prefill", "decode",
    ]
    # monotone, gap-free, and the waterfall telescopes to e2e exactly
    for (_, a, b), (_, a2, _) in zip(phases, phases[1:]):
        assert b == a2
    assert sum(b - a for _, a, b in phases) == pytest.approx(tr.e2e_s, rel=1e-9)
    assert tr.tokens == 4
    assert tr.to_dict()["inter_token"]["count"] == 3


def test_trace_handler_phase_for_non_llm():
    store = TraceStore(ring=16)
    tr = RequestTrace(route="/echo", deployment="Echo")
    tr.mark("router_in")
    tr.mark("router_dequeue")
    tr.mark("replica_in")
    store.finish(tr, "ok")
    assert tr.phases()[-1][0] == "handler"  # no first_token: not decode


def test_trace_marks_idempotent_ordered_and_bounded():
    tr = _routed_trace()
    tr.mark("router_in")  # held-request re-entry must not re-stamp
    names = [n for n, _ in tr.marks]
    assert names.count("router_in") == 1
    offsets = [t for _, t in tr.marks]
    assert offsets == sorted(offsets)
    for i in range(100):  # hard per-trace bound
        tr.mark(f"extension_{i}")
    assert len(tr.marks) <= 32


def test_outcome_first_claim_wins():
    store = TraceStore(ring=16)
    tr = _routed_trace()
    tr.set_outcome("crash", "engine loop died")  # engine claims first
    store.finish(tr, "error", "proxy saw a 500")
    assert tr.outcome == "crash"
    assert tr.detail == "engine loop died"


# --------------------------------------------------------------------------
# TraceStore: bounded rings, sampling, off switch
# --------------------------------------------------------------------------
def test_ring_bounded_under_10k_traces(monkeypatch):
    monkeypatch.setattr(get_config(), "serve_request_trace_ring", 64)
    monkeypatch.setattr(get_config(), "tracing_enabled", False)
    store = TraceStore(ring=64)
    for i in range(10_000):
        tr = store.start(route="/r", deployment="d")
        assert tr is not None
        store.finish(tr, "ok")
    snap = store.snapshot(limit=100_000)
    assert len(snap["recent"]) <= 64
    assert len(snap["slowest"]) <= 32
    assert snap["in_flight"] == []
    assert snap["deployments"]["d"]["e2e"]["count"] == 10_000


def test_ring_rebinds_when_knob_shrinks(monkeypatch):
    store = TraceStore(ring=512)
    monkeypatch.setattr(get_config(), "serve_request_trace_ring", 8)
    monkeypatch.setattr(get_config(), "tracing_enabled", False)
    for _ in range(50):
        store.finish(store.start(route="/r", deployment="d"), "ok")
    assert len(store.snapshot(limit=1000)["recent"]) <= 8


def test_slowest_heap_keeps_worst():
    store = TraceStore(ring=4)  # tiny ring: slowest must survive churn
    for i in range(40):
        tr = RequestTrace(route="/r", deployment="d")
        tr.t0 -= i * 0.01  # synthetic e2e: trace i took ~10*i ms
        store.finish(tr, "ok")
    slowest = store.snapshot(limit=100)["slowest"]
    assert len(slowest) == 32
    assert slowest[0]["e2e_s"] == max(t["e2e_s"] for t in slowest)
    assert slowest[0]["e2e_s"] > 0.38  # the 390 ms worst case survived


def test_sampling_knob_thins_traces(monkeypatch):
    monkeypatch.setattr(get_config(), "serve_request_trace_sample_n", 4)
    store = TraceStore(ring=get_config().serve_request_trace_ring)
    traced = [store.start(route="/r") for _ in range(20)]
    assert sum(t is not None for t in traced) == 5  # every 4th, from the 1st
    assert traced[0] is not None and traced[1] is None


def test_disabled_knob_returns_none(monkeypatch):
    monkeypatch.setattr(get_config(), "serve_request_trace", False)
    assert reqtrace.start_trace(route="/r") is None
    reqtrace.finish_trace(None)  # no-op, must not raise


# --------------------------------------------------------------------------
# Flight recorder
# --------------------------------------------------------------------------
def test_flight_record_snapshots_into_event_ring():
    from ray_tpu.observability.events import EventSeverity, global_event_manager

    store = reqtrace.global_trace_store()
    for _ in range(3):
        store.finish(store.start(route="/llm", deployment="d"), "ok")
    label = f"test_crash_{uuid.uuid4().hex[:8]}"
    reqtrace.flight_record(
        label, "engine loop crashed in a test", severity="ERROR",
        state={"slots": 2, "queue": 7}, layer="engine",
    )
    events = [e for e in global_event_manager().list_events(source_type="SERVE")
              if e.label == label]
    assert len(events) == 1
    ev = events[0]
    assert ev.severity == EventSeverity.ERROR
    assert ev.custom_fields["layer"] == "engine"
    assert json.loads(ev.custom_fields["state"]) == {"slots": 2, "queue": 7}
    recs = json.loads(ev.custom_fields["requests"])
    assert len(recs) == 3 and all(r["outcome"] == "ok" for r in recs)


def test_snapshot_due_throttles_per_key():
    key = f"shed:test:{uuid.uuid4().hex[:8]}"
    assert reqtrace.snapshot_due(key, min_interval_s=60.0)
    assert not reqtrace.snapshot_due(key, min_interval_s=60.0)
    assert reqtrace.snapshot_due(f"{key}:other", min_interval_s=60.0)


# --------------------------------------------------------------------------
# LLM engine: sketches + finished ring work without any HTTP ingress
# --------------------------------------------------------------------------
@pytest.fixture()
def engine(params):
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_model_cfg(), params, max_batch_size=2, max_seq_len=64)
    yield eng
    eng.shutdown()


def test_engine_sketches_and_finished_ring(engine):
    out = engine.generate([3, 1, 4], max_tokens=6)
    assert len(out) == 6
    lat = engine.admission_snapshot()["latency"]
    assert lat["ttft"]["count"] == 1 and lat["ttft"]["p99"] > 0.0
    assert lat["queue_wait"]["count"] == 1
    assert lat["e2e"]["count"] == 1 and lat["e2e"]["p99"] > 0.0
    assert lat["inter_token"]["count"] == 5  # 6 tokens -> 5 gaps
    rec = list(engine._finished_ring)[-1]
    assert rec["outcome"] == "finish"
    assert rec["generated"] == 6
    assert rec["ttft_ms"] is not None and rec["e2e_ms"] > 0.0


@pytest.mark.full
def test_engine_stream_disconnect_lands_in_ring(engine):
    it = engine.submit_stream([2, 3], max_tokens=40)
    next(it)
    it.close()  # client went away mid-stream
    deadline = time.time() + 10.0
    while time.time() < deadline:
        if any(r["outcome"] == "disconnect" for r in list(engine._finished_ring)):
            break
        time.sleep(0.05)
    outcomes = [r["outcome"] for r in engine._finished_ring]
    assert "disconnect" in outcomes, outcomes


# --------------------------------------------------------------------------
# proxy outcome vocabulary (shed/deadline/crash/error mapping)
# --------------------------------------------------------------------------
def test_trace_outcome_mapping():
    from ray_tpu.exceptions import (
        DeadlineExceededError,
        OverloadedError,
        WorkerCrashedError,
    )
    from ray_tpu.serve.proxy import _trace_outcome

    assert _trace_outcome(OverloadedError("router full"))[0] == "shed"
    assert _trace_outcome(DeadlineExceededError("too slow"))[0] == "deadline"
    assert _trace_outcome(WorkerCrashedError("boom"))[0] == "crash"
    outcome, detail = _trace_outcome(ValueError("bad prompt"))
    assert outcome == "error" and "ValueError" in detail


# --------------------------------------------------------------------------
# the full serving stack over HTTP: phase monotonicity + completeness
# --------------------------------------------------------------------------
@pytest.mark.full
def test_http_traces_streaming_and_blocking(params):
    import urllib.request

    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMServer

    rt.init(num_cpus=4)
    serve.start(http_port=0)
    try:
        app = serve.deployment(LLMServer).bind(
            lambda: (_model_cfg(), params), max_batch_size=2, max_seq_len=64
        )
        serve.run(app, route_prefix="/llm")
        reqtrace.global_trace_store().reset()

        def post(payload):
            req = urllib.request.Request(
                serve.proxy_url() + "/llm",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = urllib.request.urlopen(req, timeout=120)
            return resp.read()

        post({"prompt": [3, 1, 4], "max_tokens": 4})
        body = post({"prompt": [2, 7, 9], "max_tokens": 4, "stream": True})
        assert b"data: " in body  # SSE frames actually streamed

        snap = reqtrace.global_trace_store().snapshot(limit=10)
        traces = snap["recent"]
        assert len(traces) == 2
        mark_order = {name: i for i, name in enumerate(MARKS)}
        for tr in traces:
            assert tr["outcome"] == "ok"
            assert tr["deployment"] == "LLMServer" and tr["route"] == "/llm"
            # marks: known names, strictly ordered in both index and time
            names = [n for n, _ in tr["marks"]]
            offsets = [t for _, t in tr["marks"]]
            assert all(n in mark_order for n in names)
            idx = [mark_order[n] for n in names]
            assert idx == sorted(idx) and len(set(idx)) == len(idx)
            assert offsets == sorted(offsets)
            # completeness: the request reached the engine and produced
            # tokens, so every serving phase must be present
            phases = [p["phase"] for p in tr["phases"]]
            assert phases == [
                "proxy", "router_queue", "dispatch", "replica",
                "engine_queue", "kv_block_wait", "prefill", "decode",
            ], phases
            assert tr["tokens"] == 4
            assert tr["ttft_s"] is not None and 0 < tr["ttft_s"] <= tr["e2e_s"]
            # the waterfall sums to e2e (to_dict rounds at 1 us)
            total = sum(p["dur_s"] for p in tr["phases"])
            assert total == pytest.approx(tr["e2e_s"], abs=1e-4)
        dep = snap["deployments"]["LLMServer"]
        assert dep["e2e"]["count"] == 2 and dep["e2e"]["p99"] > 0.0
        assert dep["queue_wait"]["count"] >= 2
    finally:
        serve.shutdown()
        rt.shutdown()


# --------------------------------------------------------------------------
# chaos contract: tracing on vs off leaves the fault log byte-identical
# --------------------------------------------------------------------------
@pytest.mark.full
def test_chaos_fault_log_identical_tracing_on_vs_off(ray_start_regular):
    """Same (seed, spec, workload), run once with serve_request_trace on
    and once with it off: the deterministic fault logs must be identical,
    proving the tracer consumes zero failpoint decisions (ids come from
    os.urandom, never the seeded stream)."""
    from ray_tpu.chaos import ChaosEvent, ChaosRunner, ChaosSchedule
    from ray_tpu.runtime import failpoints

    failpoints.reset()
    schedule = ChaosSchedule(
        [ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)")],
        seed=77, name="put-fault-traced",
    )

    def workload():
        refs = []
        for i in range(10):
            tr = reqtrace.start_trace(route="/llm", deployment="chaosd")
            if tr is not None:
                tr.mark("router_in")
                tr.mark("replica_in")
            while True:  # app-level retry: each miss consumes one hit
                try:
                    refs.append(rt.put(("blob", i)))
                    break
                except failpoints.FailpointInjected:
                    continue
            reqtrace.finish_trace(tr, "ok")
        assert rt.get(refs, timeout=30) == [("blob", i) for i in range(10)]
        return refs

    cfg = get_config()
    try:
        cfg.serve_request_trace = True
        r_on = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
        traced = reqtrace.global_trace_store().snapshot(limit=100)
        cfg.serve_request_trace = False
        r_off = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
    finally:
        cfg.serve_request_trace = True
        failpoints.reset()
    assert r_on.ok, (r_on.workload_error, r_on.invariants.violations)
    assert r_off.ok, (r_off.workload_error, r_off.invariants.violations)
    assert r_on.faults, "the put failpoint must actually fire"
    assert r_on.same_faults(r_off), (r_on.faults, r_off.faults)
    # and the traced run really did trace: the fault log equality above is
    # meaningful only if tracing was exercised alongside the failpoints
    assert len(traced["recent"]) == 10
