"""Parallelism layer tests on the virtual 8-device CPU mesh.

Covers mesh construction, SPMD collectives, actor collective groups, ring /
Ulysses attention numerics vs dense reference, the GPipe pipeline, and the
Pallas flash-attention kernel (interpret mode on CPU).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
try:
    from jax import shard_map
except ImportError:  # older jax: pre-promotion location
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import (
    MeshManager,
    collective,
    pipeline_sharded,
    ring_attention_sharded,
    shard_array,
    ulysses_attention_sharded,
)
from ray_tpu.ops.attention import flash_attention, mha


@pytest.fixture(scope="module")
def mesh8():
    return MeshManager().create_mesh({"dp": 8})


@pytest.fixture(scope="module")
def mesh_sp():
    return MeshManager().create_mesh({"sp": 8})


def test_mesh_construction_and_inference():
    mm = MeshManager()
    mesh = mm.create_mesh({"dp": 2, "tp": -1}, name="train")
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4
    assert mm.get_mesh("train") is mesh
    with pytest.raises(ValueError):
        mm.create_mesh({"dp": 3})


def test_canonical_axis_order():
    mm = MeshManager()
    mesh = mm.create_mesh({"tp": 2, "dp": 2, "sp": 2})
    assert mesh.axis_names == ("dp", "sp", "tp")


def test_spmd_allreduce_allgather(mesh8):
    x = jnp.arange(8.0)
    xs = shard_array(x, mesh8, "dp")

    def f(shard):
        return collective.allreduce(shard.sum(), "dp")

    total = shard_map(f, mesh=mesh8, in_specs=P("dp"), out_specs=P())(xs)
    assert float(total) == 28.0

    def g(shard):
        return collective.allgather(shard, "dp")

    gathered = shard_map(g, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))(xs)
    assert gathered.shape == (64,)


def test_spmd_reducescatter_broadcast(mesh8):
    x = jnp.ones((8, 4))
    xs = shard_array(x, mesh8, "dp")

    def rs(shard):
        return collective.reducescatter(jnp.broadcast_to(shard, (8, 4)), "dp")

    out = shard_map(rs, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))(xs)
    assert out.shape == (8, 4)
    np.testing.assert_allclose(np.asarray(out), 8.0)

    def bc(shard):
        return collective.broadcast(shard, "dp", root=3)

    x2 = shard_array(jnp.arange(8.0), mesh8, "dp")
    out2 = shard_map(bc, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))(x2)
    np.testing.assert_allclose(np.asarray(out2), 3.0)


def test_send_recv_ring(mesh8):
    x = shard_array(jnp.arange(8.0), mesh8, "dp")

    def shift(shard):
        return collective.send_recv(shard, "dp", shift=1)

    out = shard_map(shift, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_actor_collective_group():
    collective.init_collective_group(world_size=4, rank=0, group_name="g1")
    results = {}

    def participant(rank):
        collective.init_collective_group(4, rank, group_name="g1")
        out = collective.allreduce_tensor(np.full((4,), float(rank + 1)), rank, "g1")
        results[rank] = np.asarray(out)

    threads = [threading.Thread(target=participant, args=(r,)) for r in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for r in range(4):
        np.testing.assert_allclose(results[r], 10.0)  # 1+2+3+4
    collective.destroy_collective_group("g1")


def test_actor_collective_broadcast_and_gather():
    name = "g2"
    results = {}

    def participant(rank):
        collective.init_collective_group(3, rank, group_name=name)
        results[rank] = (
            collective.broadcast_tensor(rank * 10, rank, src_rank=1, group_name=name),
            collective.allgather_tensor(rank, rank, group_name=name),
        )

    threads = [threading.Thread(target=participant, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for r in range(3):
        assert results[r][0] == 10
        assert results[r][1] == [0, 1, 2]
    collective.destroy_collective_group(name)


# ------------------------------------------------------------------ attention
def _qkv(B=2, H=8, T=128, D=32, dtype=jnp.float32):
    keys = jax.random.split(jax.random.PRNGKey(7), 3)
    return tuple(jax.random.normal(k, (B, H, T, D), dtype) for k in keys)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(mesh_sp, causal):
    q, k, v = _qkv()
    ref = mha(q, k, v, causal=causal)
    spec = (None, None, "sp", None)
    qs, ks, vs = (shard_array(x, mesh_sp, *spec) for x in (q, k, v))
    out = ring_attention_sharded(qs, ks, vs, mesh_sp, "sp", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_grads_match_dense(mesh_sp):
    """The lse-combined ring gradient must match dense attention's."""
    q, k, v = _qkv()
    spec = (None, None, "sp", None)
    qs, ks, vs = (shard_array(x, mesh_sp, *spec) for x in (q, k, v))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh_sp, "sp", causal=True).astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True).astype(jnp.float32) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(qs, ks, vs)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_ulysses_matches_dense(mesh_sp):
    q, k, v = _qkv()
    ref = mha(q, k, v, causal=True)
    spec = (None, None, "sp", None)
    qs, ks, vs = (shard_array(x, mesh_sp, *spec) for x in (q, k, v))
    out = ulysses_attention_sharded(qs, ks, vs, mesh_sp, "sp", causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_dense(causal):
    q, k, v = _qkv(T=256, D=64)
    ref = mha(q, k, v, causal=causal)
    out = flash_attention(q, k, v, None, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("T", [100, 192, 200])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_ragged_lengths(T, causal):
    """Sequence lengths that are not block multiples (tail-block regression)."""
    q, k, v = _qkv(T=T, D=32)
    ref = mha(q, k, v, causal=causal)
    out = flash_attention(q, k, v, None, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_grads():
    q, k, v = _qkv(T=128, D=32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, None, True) ** 2).sum()

    def loss_ref(q, k, v):
        return (mha(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


# ------------------------------------------------------------------ pipeline
def test_pipeline_matches_sequential(mesh8):
    mm = MeshManager()
    mesh = mm.create_mesh({"pp": 4}, devices=mesh8.devices.flatten()[:4])
    S, M, Bm, F = 4, 8, 4, 16
    keys = jax.random.split(jax.random.PRNGKey(0), S)
    ws = jnp.stack([jax.random.normal(k, (F, F)) * 0.3 for k in keys])
    xs = jax.random.normal(jax.random.PRNGKey(1), (M, Bm, F))

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    out = pipeline_sharded(stage_fn, ws, xs, mesh, "pp")

    expected = xs
    for s in range(S):
        expected = jnp.tanh(expected @ ws[s])
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


def test_pipeline_gradients_flow():
    """Training through the pipeline: jax autodiff reverses the microbatch
    schedule (backward hops ride the same ICI ring), so grads w.r.t. every
    stage's params must be nonzero and match a single-device reference."""
    from jax.sharding import Mesh

    n = 4
    devices = np.array(jax.devices()[:n])
    mesh = Mesh(devices, ("pp",))
    d = 8
    rng = np.random.default_rng(0)
    stacked = {"w": jnp.asarray(rng.standard_normal((n, d, d)) * 0.3, jnp.float32)}
    mb = jnp.asarray(rng.standard_normal((4, 2, d)), jnp.float32)

    def stage(p, x):
        return jnp.tanh(x @ p["w"])

    def loss_pipe(params):
        out = pipeline_sharded(stage, params, mb, mesh)
        return jnp.sum(out ** 2)

    def loss_ref(params):
        x = mb
        for i in range(n):
            x = jnp.tanh(x @ params["w"][i])
        return jnp.sum(x ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    np.testing.assert_allclose(np.asarray(g_pipe["w"]), np.asarray(g_ref["w"]), rtol=1e-4, atol=1e-5)
    assert float(jnp.abs(g_pipe["w"]).sum()) > 0


# ------------------------------------------------------------- multi-host
def test_multihost_mesh_layout():
    """The DCN axis must own whole host blocks: device order within each
    dcn slice stays contiguous (inner axes ride ICI)."""
    from ray_tpu.parallel.distributed import multihost_mesh

    mesh = multihost_mesh(("dp", "tp"), (2, -1), dcn_axis="dp")
    assert mesh.shape == {"dp": 2, "tp": 4}
    devs = mesh.devices
    ids = np.vectorize(lambda d: d.id)(devs)
    # row 0 = first host's 4 devices, row 1 = second host's
    assert sorted(ids[0].tolist()) == [0, 1, 2, 3]
    assert sorted(ids[1].tolist()) == [4, 5, 6, 7]


def test_rendezvous_via_cluster_kv():
    import ray_tpu

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        from ray_tpu.parallel.distributed import rendezvous_via_cluster

        addr0, ws, r0 = rendezvous_via_cluster(0, 2)
        addr1, _, r1 = rendezvous_via_cluster(1, 2)
        assert addr0 == addr1 and ":" in addr0
        assert (r0, r1) == (0, 1)
    finally:
        ray_tpu.shutdown()


def test_multihost_mesh_three_axes_dcn_not_first():
    """3-axis layout with the DCN axis in the middle: shape must be right
    AND the dcn axis must own contiguous host blocks (moveaxis regression)."""
    from ray_tpu.parallel.distributed import multihost_mesh

    mesh = multihost_mesh(("a", "dp", "b"), (2, 2, 2), dcn_axis="dp")
    assert dict(mesh.shape) == {"a": 2, "dp": 2, "b": 2}
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    # fixing a,b and varying dp must jump by a whole host block (4 devices)
    for a in range(2):
        for b in range(2):
            assert abs(int(ids[a, 1, b]) - int(ids[a, 0, b])) == 4


@pytest.mark.parametrize("T,window,causal", [(256, 64, True), (300, 100, True), (256, 32, False)])
def test_sliding_window_attention_matches_dense(T, window, causal):
    """Local attention: off-window blocks are skipped; result and grads
    must match a densely-masked reference."""
    from ray_tpu.ops.attention import NEG_INF, sliding_window_attention

    q, k, v = _qkv(T=T, D=32)

    def dense_window(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
        qpos, kpos = jnp.arange(T)[:, None], jnp.arange(T)[None, :]
        mask = kpos > qpos - window
        if causal:
            mask &= kpos <= qpos
        s = jnp.where(mask[None, None], s, NEG_INF)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)

    out = sliding_window_attention(q, k, v, window, causal=causal, block_q=128, block_k=128)
    ref = dense_window(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    g1 = jax.grad(
        lambda a, b, c: jnp.sum(
            sliding_window_attention(a, b, c, window, causal=causal, block_q=128, block_k=128) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(lambda a, b, c: jnp.sum(dense_window(a, b, c) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
