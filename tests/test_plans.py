"""Compiled execution plans (ISSUE 5): install-once DAG schedules with
persistent data-plane channels.

Covers the channel layer (SeqChannel backpressure, chan_push streams), the
plan lifecycle (compile -> install -> execute -> teardown), the acceptance
bar (a 4-stage cross-node pipeline runs N=100 iterations with ZERO
per-iteration TaskSpec/ObjectRef creation, asserted via the scheduler/task
counters), pipelined execute_async, the failure story (actor kill and agent
kill -9 -> typed error + BROKEN), and the observability surfaces
(/api/plans + `rt plans`).
"""

import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu.dag import ChannelClosed, InputNode, MultiOutputNode
from ray_tpu.exceptions import ActorDiedError, RayTaskError, WorkerCrashedError
from ray_tpu.observability import metric_defs
from ray_tpu.runtime.channel_manager import ChannelManager, SeqChannel


# --------------------------------------------------------------------------
# channel layer
# --------------------------------------------------------------------------
def test_seq_channel_backpressure_and_order():
    ch = SeqChannel("t")
    ch.write(0, "a")
    # single slot: the second write must block until the slot drains
    blocked = threading.Event()
    done = threading.Event()

    def second():
        blocked.set()
        ch.write(1, "b")
        done.set()

    t = threading.Thread(target=second, daemon=True)
    t.start()
    blocked.wait(2)
    time.sleep(0.05)
    assert not done.is_set()
    assert ch.read() == (0, "a", False)
    t.join(2)
    assert done.is_set()
    assert ch.read() == (1, "b", False)


def test_seq_channel_close_with_typed_error_wakes_reader():
    ch = SeqChannel("t")
    out = {}

    def reader():
        try:
            ch.read()
        except BaseException as exc:  # noqa: BLE001
            out["exc"] = exc

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    time.sleep(0.05)
    ch.close(ActorDiedError("stage actor killed"))
    t.join(2)
    assert isinstance(out["exc"], ActorDiedError)
    # closed channel rejects writes with the same typed error
    with pytest.raises(ActorDiedError):
        ch.write(2, "x")


def test_chan_push_stream_delivers_and_nacks_unknown():
    """A persistent ChannelStream lands seq-numbered frames in the peer's
    channel manager; unknown channels nack (ChannelClosed at the writer)."""
    import numpy as np

    from ray_tpu.core.object_store import ObjectStore
    from ray_tpu.runtime import channel_manager, data_plane

    mgr = channel_manager.global_manager()
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store)
    try:
        chans = mgr.register("testplan", ["c1"])
        stream = data_plane.ChannelStream(server.address, "testplan", "c1")
        payload = np.arange(1000, dtype=np.float32)
        stream.push(0, payload)
        seq, value, is_err = chans["c1"].read()
        assert seq == 0 and not is_err
        np.testing.assert_array_equal(np.asarray(value), payload)
        # error frames carry the exception with is_error=True
        stream.push(1, ValueError("boom"), is_error=True)
        seq, value, is_err = chans["c1"].read()
        assert seq == 1 and is_err and isinstance(value, ValueError)
        # unknown channel: clean nack, not a wedged stream
        bad = data_plane.ChannelStream(server.address, "testplan", "nope")
        with pytest.raises(ChannelClosed):
            bad.push(0, 1)
        bad.close()
        stream.close()
    finally:
        mgr.release_plan("testplan")
        server.close()


def test_channel_manager_release_closes_blocked():
    mgr = ChannelManager()
    chans = mgr.register("p", ["a"])
    errs = []

    def reader():
        try:
            chans["a"].read()
        except ChannelClosed as exc:
            errs.append(exc)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    time.sleep(0.05)
    mgr.release_plan("p")
    t.join(2)
    assert len(errs) == 1
    assert mgr.channel("p", "a") is None


# --------------------------------------------------------------------------
# plan lifecycle on an in-process multi-node cluster
# --------------------------------------------------------------------------
@pytest.fixture
def two_node_pipeline(ray_start_cluster):
    rt_mod, cluster = ray_start_cluster
    cluster.add_node({"CPU": 2, "stage": 4})

    @rt.remote
    class Stage:
        def __init__(self, k):
            self.k = k

        def step(self, x):
            return x + self.k

        def fail(self, x):
            raise ValueError(f"stage error at {x}")

        def flaky(self, x):
            if x < 0:
                raise ValueError(f"stage error at {x}")
            return x + self.k

    head = dict(execution="inproc")
    other = dict(execution="inproc", resources={"stage": 1}, num_cpus=0)
    actors = [
        Stage.options(**head).remote(1),
        Stage.options(**other).remote(10),
        Stage.options(**other).remote(100),
        Stage.options(**head).remote(1000),
    ]
    yield cluster, Stage, actors


def _chain(actors, inp):
    d = inp
    for a in actors:
        d = a.step.bind(d)
    return d


def test_plan_100_iterations_zero_taskspecs(two_node_pipeline):
    """Acceptance bar: a 4-stage pipeline of actors spanning 2 nodes runs
    N=100 iterations through the installed plan with ZERO per-iteration
    TaskSpec / scheduler-dispatch / ObjectRef creation."""
    cluster, Stage, actors = two_node_pipeline
    with InputNode() as inp:
        d = _chain(actors, inp)
    plan = d.compile_plan(name="accept")
    assert plan.state == "READY"
    assert {s["node"] for s in plan.snapshot()["stages"]} == {
        n.hex()[:8] for n in cluster.nodes
    }

    before = (
        metric_defs.TASKS_SUBMITTED.series(),
        metric_defs.ACTOR_CALLS_SUBMITTED.series(),
        metric_defs.SCHEDULER_TASKS_DISPATCHED.series(),
    )
    refs_before = cluster.core_worker.ref_counter.num_tracked()
    for i in range(100):
        assert plan.execute(i) == i + 1111
    after = (
        metric_defs.TASKS_SUBMITTED.series(),
        metric_defs.ACTOR_CALLS_SUBMITTED.series(),
        metric_defs.SCHEDULER_TASKS_DISPATCHED.series(),
    )
    assert before == after, "plan.execute must create zero TaskSpecs"
    assert cluster.core_worker.ref_counter.num_tracked() == refs_before, (
        "plan.execute must create zero ObjectRefs"
    )
    snap = plan.snapshot()
    assert snap["executions"] == 100 and snap["state"] == "READY"
    plan.teardown()


def test_plan_execute_async_pipelines_iterations(two_node_pipeline):
    cluster, Stage, actors = two_node_pipeline
    with InputNode() as inp:
        d = _chain(actors, inp)
    plan = d.compile_plan()
    futs = [plan.execute_async(i) for i in range(50)]
    assert [f.result(timeout=60) for f in futs] == [i + 1111 for i in range(50)]
    plan.teardown()


def test_plan_multi_output_and_kwargs(two_node_pipeline):
    cluster, Stage, actors = two_node_pipeline
    with InputNode() as inp:
        first = actors[0].step.bind(inp)
        d = MultiOutputNode([actors[1].step.bind(first), actors[2].step.bind(first)])
    plan = d.compile_plan()
    assert plan.execute(5) == [5 + 1 + 10, 5 + 1 + 100]
    assert plan.execute(0) == [11, 101]
    plan.teardown()


def test_plan_user_exception_fails_iteration_not_plan(two_node_pipeline):
    """A stage raising a USER error fails that iteration (typed error out of
    the output channel) but the plan stays READY — only actor/node death
    breaks it (reference aDAG semantics)."""
    cluster, Stage, actors = two_node_pipeline
    with InputNode() as inp:
        d = actors[1].fail.bind(actors[0].step.bind(inp))
    plan = d.compile_plan()
    with pytest.raises(RayTaskError, match="stage error"):
        plan.execute(3)
    assert plan.state == "READY"
    # and the pipeline keeps serving afterwards
    with pytest.raises(RayTaskError):
        plan.execute(4)
    plan.teardown()


def test_plan_multi_output_error_does_not_desync_siblings(two_node_pipeline):
    """One leaf erroring must drain the sibling leaf's output slot too —
    otherwise every later iteration reads the previous iteration's stale
    value (outputs permanently desynced from futures)."""
    cluster, Stage, actors = two_node_pipeline
    with InputNode() as inp:
        first = actors[0].step.bind(inp)
        d = MultiOutputNode([actors[1].flaky.bind(first), actors[2].step.bind(first)])
    plan = d.compile_plan()
    with pytest.raises(RayTaskError, match="stage error"):
        plan.execute(-5)  # leaf 0 errors; leaf 1 still produced a value
    assert plan.state == "READY"
    # the SAME plan's next iteration must return ITS values, not the stale
    # sibling slot from the errored iteration
    assert plan.execute(5) == [5 + 1 + 10, 5 + 1 + 100]
    assert plan.execute(0) == [11, 101]
    plan.teardown()


def test_plan_actor_kill_breaks_plan_with_typed_error(two_node_pipeline):
    cluster, Stage, actors = two_node_pipeline
    with InputNode() as inp:
        d = _chain(actors, inp)
    plan = d.compile_plan()
    assert plan.execute(1) == 1112
    rt.kill(actors[2])
    deadline = time.monotonic() + 10
    while plan.state != "BROKEN" and time.monotonic() < deadline:
        time.sleep(0.02)
    assert plan.state == "BROKEN"
    with pytest.raises(ActorDiedError):
        plan.execute(2)
    plan.teardown()


def test_plan_node_death_breaks_plan(two_node_pipeline):
    cluster, Stage, actors = two_node_pipeline
    with InputNode() as inp:
        d = _chain(actors, inp)
    plan = d.compile_plan()
    assert plan.execute(0) == 1111
    victim = next(
        nid for nid, n in cluster.nodes.items() if n is not cluster.head_node
    )
    cluster.kill_node(victim, reason="test")
    assert plan.state == "BROKEN"
    with pytest.raises((ActorDiedError, WorkerCrashedError)):
        plan.execute(1)
    plan.teardown()


def test_plan_teardown_idempotent_and_execute_after(two_node_pipeline):
    cluster, Stage, actors = two_node_pipeline
    with InputNode() as inp:
        d = _chain(actors[:2], inp)
    plan = d.compile_plan()
    assert plan.execute(0) == 11
    assert plan.plan_id in cluster.compiled_plans
    plan.teardown()
    plan.teardown()  # idempotent
    assert plan.plan_id not in cluster.compiled_plans
    with pytest.raises(RuntimeError, match="torn down"):
        plan.execute(0)


def test_plan_rejects_function_nodes_and_const_only_stages(ray_start_regular):
    @rt.remote
    def f(x):
        return x

    @rt.remote
    class A:
        def m(self, x):
            return x

    a = A.options(execution="inproc").remote()
    with InputNode() as inp:
        d = f.bind(inp)
    with pytest.raises(ValueError, match="CompiledDAG"):
        d.compile_plan()
    with InputNode() as inp:
        d = a.m.bind(7)  # no per-iteration input
    with pytest.raises(ValueError, match="per-iteration"):
        d.compile_plan()


def test_plan_const_args_and_input_selectors(ray_start_regular):
    @rt.remote
    class Mixer:
        def mix(self, x, y, scale=1):
            return (x + y) * scale

    m = Mixer.options(execution="inproc").remote()
    with InputNode() as inp:
        d = m.mix.bind(inp.a, inp.b, scale=10)
    plan = d.compile_plan()
    assert plan.execute(a=3, b=4) == 70
    assert plan.execute(a=1, b=1) == 20
    plan.teardown()


# --------------------------------------------------------------------------
# multihost: a real agent process hosts half the pipeline
# --------------------------------------------------------------------------
def test_plan_install_and_execute_across_processes():
    from test_multihost import _spawn_agent, _wait_for_nodes

    rt.init(num_cpus=2)
    proc = None
    try:
        cluster = rt.get_cluster()
        address = cluster.start_head_service()
        proc = _spawn_agent(address)
        _wait_for_nodes(cluster, 2)

        @rt.remote
        class Stage:
            def __init__(self, k):
                self.k = k

            def step(self, x):
                return x + self.k

        head = dict(execution="inproc")
        remote = dict(execution="inproc", resources={"remote": 1}, num_cpus=0)
        actors = [
            Stage.options(**head).remote(1),
            Stage.options(**remote).remote(10),
            Stage.options(**remote).remote(100),
            Stage.options(**head).remote(1000),
        ]
        with InputNode() as inp:
            d = _chain(actors, inp)
        plan = d.compile_plan(name="xproc")
        sent_before = metric_defs.COMPILED_CHANNEL_BYTES.get({"direction": "sent"})
        before = (
            metric_defs.TASKS_SUBMITTED.series(),
            metric_defs.ACTOR_CALLS_SUBMITTED.series(),
        )
        for i in range(100):
            assert plan.execute(i) == i + 1111
        assert (
            metric_defs.TASKS_SUBMITTED.series(),
            metric_defs.ACTOR_CALLS_SUBMITTED.series(),
        ) == before
        # the iterations crossed processes on the persistent channel streams
        assert metric_defs.COMPILED_CHANNEL_BYTES.get({"direction": "sent"}) > sent_before
        # pipelined async across the process boundary
        futs = [plan.execute_async(i) for i in range(30)]
        assert [f.result(timeout=60) for f in futs] == [i + 1111 for i in range(30)]
        plan.teardown()
        # teardown released the agent-side channels: a fresh plan reinstalls
        plan2 = d.compile_plan()
        assert plan2.execute(0) == 1111
        plan2.teardown()
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        rt.shutdown()


def test_plan_agent_kill9_yields_typed_error_and_broken():
    import signal

    from test_multihost import _spawn_agent, _wait_for_nodes

    rt.init(num_cpus=2)
    proc = None
    try:
        cluster = rt.get_cluster()
        address = cluster.start_head_service()
        proc = _spawn_agent(address)
        _wait_for_nodes(cluster, 2)

        @rt.remote
        class Stage:
            def step(self, x):
                return x + 1

        a = Stage.options(execution="inproc").remote()
        b = Stage.options(
            execution="inproc", resources={"remote": 1}, num_cpus=0
        ).remote()
        with InputNode() as inp:
            d = b.step.bind(a.step.bind(inp))
        plan = d.compile_plan()
        assert plan.execute(0) == 2
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        # keep executing until the death sweep breaks the plan; every
        # surfaced failure must be the typed error, never a hang
        deadline = time.monotonic() + 60
        with pytest.raises((ActorDiedError, WorkerCrashedError)):
            while time.monotonic() < deadline:
                plan.execute(1)
        assert plan.state == "BROKEN"
        with pytest.raises((ActorDiedError, WorkerCrashedError)):
            plan.execute(2)
        plan.teardown()
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        rt.shutdown()


# --------------------------------------------------------------------------
# observability: /api/plans + `rt plans` CLI smoke
# --------------------------------------------------------------------------
def test_api_plans_and_cli_smoke(capsys):
    from ray_tpu.scripts.cli import main

    rt.init(num_cpus=2, include_dashboard=True)
    try:
        url = rt.get_cluster().dashboard.url

        @rt.remote
        class Stage:
            def step(self, x):
                return x * 2

        a = Stage.options(execution="inproc").remote()
        b = Stage.options(execution="inproc").remote()
        with InputNode() as inp:
            d = b.step.bind(a.step.bind(inp))
        plan = d.compile_plan(name="cli-smoke")
        assert plan.execute(3) == 12
        assert main(["plans", "--address", url]) == 0
        out = capsys.readouterr().out
        assert "cli-smoke" in out and "READY" in out
        assert "step()" in out
        # json form round-trips
        assert main(["plans", "--address", url, "--format", "json"]) == 0
        import json as _json

        data = _json.loads(capsys.readouterr().out)
        assert data["plans"][0]["executions"] >= 1
        assert data["totals"]["executions_ok"] >= 1
        plan.teardown()
        assert main(["plans", "--address", url]) == 0
        assert "0 installed" in capsys.readouterr().out
    finally:
        rt.shutdown()


def test_plan_metric_families_in_catalog():
    """The plan families ride ALL_METRICS, so the tier-1
    exposition-validity test (test_tracing) covers them automatically."""
    names = {m.name for m in metric_defs.ALL_METRICS}
    assert {
        "compiled_plan_executions_total",
        "compiled_channel_bytes_total",
        "compiled_channel_occupancy",
        "compiled_device_channel_bytes_total",
        "plan_stage_group_executions_total",
    } <= names


# --------------------------------------------------------------------------
# device channels (ISSUE 11): HBM-resident slots + control-only streams
# --------------------------------------------------------------------------
def test_device_seq_channel_slot_semantics_and_stats():
    """A device-kind slot hands a jax array over as a REFERENCE move (the
    DeviceChannel contract), device_puts a host ndarray exactly once (kind
    transition), passes non-array payloads through untouched, and keeps the
    process HBM-resident accounting symmetric across write/read."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.runtime.channel_manager import device_channel_stats

    base = device_channel_stats()
    ch = SeqChannel("d", kind="device")
    arr = jnp.arange(1024, dtype=jnp.float32)
    ch.write(0, arr)
    s = device_channel_stats()
    assert s["occupied_slots"] == base["occupied_slots"] + 1
    assert s["hbm_resident_bytes"] == base["hbm_resident_bytes"] + arr.nbytes
    seq, out, is_err = ch.read()
    assert (seq, is_err) == (0, False)
    assert out is arr  # co-located hand-off: the same buffer, no copy
    assert device_channel_stats() == base
    # host ndarray arriving on a device slot: placed (device_put) once
    ch.write(1, np.ones(16, np.float32))
    _, out2, _ = ch.read()
    assert isinstance(out2, jax.Array)
    # non-array payload (the per-seq pickle fallback) rides the slot as-is
    ch.write(2, {"k": 1})
    assert ch.read()[1] == {"k": 1}
    assert device_channel_stats() == base


def test_device_seq_channel_close_while_blocked_restores_stats():
    """close() on a device slot holding an array wakes the blocked writer
    with ChannelClosed AND returns the HBM accounting to baseline — a torn
    -down plan must not leak phantom HBM-resident bytes."""
    import jax.numpy as jnp

    from ray_tpu.runtime.channel_manager import device_channel_stats

    base = device_channel_stats()
    ch = SeqChannel("d", kind="device")
    ch.write(0, jnp.ones(256, jnp.float32))
    errs = []
    blocked = threading.Event()

    def second_write():
        blocked.set()
        try:
            ch.write(1, jnp.zeros(256, jnp.float32))
        except ChannelClosed as exc:
            errs.append(exc)

    t = threading.Thread(target=second_write, daemon=True)
    t.start()
    blocked.wait(2)
    time.sleep(0.05)
    assert device_channel_stats()["occupied_slots"] == base["occupied_slots"] + 1
    ch.close()
    t.join(2)
    assert len(errs) == 1
    assert device_channel_stats() == base


def test_device_chan_push_host_staged_roundtrip_no_pickle():
    """CPU fallback transport: with no transfer server, a device-kind edge
    streams the raw host view and the receiver reassembles a real device
    array — array payloads NEVER touch the pickler (the zero-pickle
    acceptance bar), while non-array payloads on the SAME edge fall back to
    the pickle frames per seq."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.core.object_store import ObjectStore
    from ray_tpu.runtime import channel_manager, data_plane, device_plane

    mgr = channel_manager.global_manager()
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store)
    try:
        chans = mgr.register("devplan", ["e"], kinds={"e": "device"})
        stream = data_plane.ChannelStream(server.address, "devplan", "e", kind="device")
        arr = jnp.asarray(np.random.default_rng(0).standard_normal((64, 32)), jnp.float32)
        packed_before = device_plane.stats.arrays_packed
        sent_before = metric_defs.COMPILED_DEVICE_CHANNEL_BYTES.get({"direction": "sent"})
        recv_before = metric_defs.COMPILED_DEVICE_CHANNEL_BYTES.get({"direction": "received"})

        stream.push(0, arr)
        seq, value, is_err = chans["e"].read()
        assert (seq, is_err) == (0, False)
        assert isinstance(value, jax.Array)
        np.testing.assert_array_equal(np.asarray(value), np.asarray(arr))

        # mixed payloads on the same edge: dict (pickle), error, array again
        stream.push(1, {"meta": [1, 2, 3]})
        assert chans["e"].read()[1] == {"meta": [1, 2, 3]}
        stream.push(2, ValueError("boom"), is_error=True)
        seq, value, is_err = chans["e"].read()
        assert is_err and isinstance(value, ValueError)
        stream.push(3, arr * 2)
        np.testing.assert_array_equal(np.asarray(chans["e"].read()[1]), np.asarray(arr) * 2)

        # the acceptance bar: zero array payloads went through the pickler,
        # and the device-byte counter saw them on both directions
        assert device_plane.stats.arrays_packed == packed_before
        moved = 2 * arr.nbytes
        assert metric_defs.COMPILED_DEVICE_CHANNEL_BYTES.get({"direction": "sent"}) == sent_before + moved
        assert metric_defs.COMPILED_DEVICE_CHANNEL_BYTES.get({"direction": "received"}) == recv_before + moved
        stream.close()
    finally:
        mgr.release_plan("devplan")
        server.close()


def test_device_chan_push_ticket_path_and_refused_pull_fallback():
    """With a transfer server installed the push is control-only: the
    payload moves through the staged device-to-device pull (ticket in the
    header, zero array bytes on the stream).  A consumer whose pull fails
    nacks with the fallback flag and the producer resends that seq
    host-staged — delivery never depends on the fast path."""
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.core.object_store import ObjectStore
    from ray_tpu.runtime import channel_manager, data_plane, device_plane
    from ray_tpu.runtime.fake_transfer import FakeTransferServer

    mgr = channel_manager.global_manager()
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store)
    fake = FakeTransferServer()
    try:
        device_plane.install_transfer_server(fake)
        chans = mgr.register("tickplan", ["e"], kinds={"e": "device"})
        stream = data_plane.ChannelStream(server.address, "tickplan", "e", kind="device")
        arr = jnp.arange(4096, dtype=jnp.float32)
        stream.push(0, arr)
        np.testing.assert_array_equal(np.asarray(chans["e"].read()[1]), np.asarray(arr))
        assert fake.pulls_served >= 1, "payload must ride the device pull, not the stream"

        # refused pulls: the nack/fallback resend still delivers the seq
        fake.refuse_pulls = True
        stream.push(1, arr + 1)
        np.testing.assert_array_equal(np.asarray(chans["e"].read()[1]), np.asarray(arr) + 1)
        stream.close()
    finally:
        device_plane.install_transfer_server(None)
        fake.close()
        mgr.release_plan("tickplan")
        server.close()


# --------------------------------------------------------------------------
# SPMD stage groups (ISSUE 11)
# --------------------------------------------------------------------------
def test_stage_group_plan_trace_once_and_zero_pickle(ray_start_regular):
    """A gang stage splits device-array inputs across its members, runs the
    SAME jit'd step on each, and reassembles one array — with the jit trace
    primed ONCE at install (warmup): the retrace counter stays flat across
    every iteration, array edges stay pickle-free, and the group executions
    counter advances."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.dag import StageGroup
    from ray_tpu.runtime import device_plane

    step_fn = jax.jit(lambda x: x * 2.0 + 1.0)

    @rt.remote
    class Member:
        def step(self, x):
            return step_fn(x)

    members = [Member.options(execution="inproc").remote() for _ in range(2)]
    gang = StageGroup(members, "step", split_axis=0, warmup=((8, 16), "float32"))
    with InputNode() as inp:
        out = gang.bind(inp)
    plan = out.compile_plan(name="gang")
    try:
        x = jnp.asarray(np.random.default_rng(1).standard_normal((8, 16)), jnp.float32)
        expected = np.asarray(x) * 2.0 + 1.0
        traces_after_install = step_fn._cache_size()
        assert traces_after_install >= 1, "warmup must prime the trace at install"
        packed_before = device_plane.stats.arrays_packed
        execs_before = metric_defs.PLAN_STAGE_GROUP_EXECUTIONS.get()
        for _ in range(20):
            result = plan.execute(x)
            assert isinstance(result, jax.Array)
            np.testing.assert_allclose(np.asarray(result), expected, rtol=1e-6)
        # trace once, execute many: not one retrace over 20 iterations
        assert step_fn._cache_size() == traces_after_install
        # steady-state device-edge execution: zero array pickling
        assert device_plane.stats.arrays_packed == packed_before
        assert metric_defs.PLAN_STAGE_GROUP_EXECUTIONS.get() >= execs_before + 20
        # the observability snapshot carries the gang size + edge kinds
        snap = plan.snapshot()
        assert any(s.get("group") == 2 for s in snap["stages"])
        assert snap["channel_kinds"] and all(
            k == "device" for k in snap["channel_kinds"].values()
        )
    finally:
        plan.teardown()


def test_stage_group_validation_and_interpreted_execute_rejected(ray_start_regular):
    from ray_tpu.core.config import get_config
    from ray_tpu.dag import StageGroup

    with pytest.raises(ValueError):
        StageGroup([], "step")

    @rt.remote
    class Member:
        def step(self, x):
            return x

    members = [Member.options(execution="inproc").remote() for _ in range(2)]
    gang = StageGroup(members, "step")
    with InputNode() as inp:
        node = gang.bind(inp)
    # stage groups only exist compiled: interpreted .execute() is an error
    with pytest.raises(ValueError):
        node.execute(1)
    # compile-time gang-size bound
    cfg = get_config()
    old = cfg.plan_stage_group_max_members
    cfg.plan_stage_group_max_members = 1
    try:
        with pytest.raises(ValueError):
            node.compile_plan()
    finally:
        cfg.plan_stage_group_max_members = old


def test_api_plans_device_fields_and_cli(capsys):
    """/api/plans + `rt plans` surface the device-channel story: per-edge
    kind, HBM-resident bytes, device-channel occupancy, gang sizes."""
    import jax.numpy as jnp

    from ray_tpu.scripts.cli import main

    rt.init(num_cpus=2, include_dashboard=True)
    try:
        url = rt.get_cluster().dashboard.url

        @rt.remote
        class Stage:
            def step(self, x):
                return x * 2

        a = Stage.options(execution="inproc").remote()
        with InputNode() as inp:
            d = a.step.bind(inp)
        plan = d.compile_plan(name="dev-smoke")
        out = plan.execute(jnp.ones(512, jnp.float32))
        assert float(out.sum()) == 1024.0

        import json as _json
        import urllib.request

        with urllib.request.urlopen(f"{url}/api/plans", timeout=10) as resp:
            data = _json.loads(resp.read())
        totals = data["totals"]
        for key in (
            "device_channel_bytes_sent",
            "device_channel_bytes_received",
            "device_channel_occupancy",
            "hbm_resident_bytes",
            "stage_group_executions",
        ):
            assert key in totals, f"missing {key} in /api/plans totals"
        assert data["plans"][0]["channel_kinds"]

        assert main(["plans", "--address", url]) == 0
        text = capsys.readouterr().out
        assert "device channels:" in text
        assert "edge " in text and "device" in text
        plan.teardown()
    finally:
        rt.shutdown()
