"""Unit tests for the native frame codec (`ray_tpu/native/src/hotpath.c`).

Wire format parity with the Python framing in `runtime/protocol.py`:
4-byte LE length + payload.  The two implementations must interoperate in
both directions and across fragmentation patterns — the decoder buffers
partial frames across recv calls and drains multi-frame bursts.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading

import pytest

from ray_tpu.native import hotpath as hp
from ray_tpu.runtime import protocol

_LEN = struct.Struct("<I")


@pytest.fixture
def pair():
    a, b = socket.socketpair()
    yield a, b
    a.close()
    b.close()


def test_roundtrip_c_to_c(pair):
    a, b = pair
    dec = hp.FrameDecoder()
    payloads = [b"x" * n for n in (0, 1, 5, 1000, 70_000)]
    for p in payloads:
        hp.send_frame(a.fileno(), p)
    for p in payloads:
        assert dec.read_frame(b.fileno()) == p


def test_c_sender_python_reader(pair):
    a, b = pair
    hp.send_frame(a.fileno(), pickle.dumps(("hello", {"k": 1})))
    assert protocol.recv_msg(b) == ("hello", {"k": 1})


def test_python_sender_c_reader(pair):
    a, b = pair
    data = pickle.dumps(("msg", {"v": list(range(100))}))
    a.sendall(_LEN.pack(len(data)) + data)
    dec = hp.FrameDecoder()
    assert pickle.loads(dec.read_frame(b.fileno())) == ("msg", {"v": list(range(100))})


def test_fragmented_delivery(pair):
    """Frames arriving one byte at a time still parse."""
    a, b = pair
    payload = os.urandom(300)
    frame = _LEN.pack(len(payload)) + payload
    got = []
    dec = hp.FrameDecoder()

    def reader():
        got.append(dec.read_frame(b.fileno()))

    t = threading.Thread(target=reader)
    t.start()
    for i in range(len(frame)):
        a.sendall(frame[i : i + 1])
    t.join(timeout=10)
    assert got == [payload]


def test_burst_drains_without_extra_recv(pair):
    """Many small frames sent as one write: all parse; the buffered tail is
    visible through pending()."""
    a, b = pair
    frames = [os.urandom(n) for n in (10, 0, 200, 33)]
    blob = b"".join(_LEN.pack(len(p)) + p for p in frames)
    a.sendall(blob)
    dec = hp.FrameDecoder()
    assert dec.read_frame(b.fileno()) == frames[0]
    # everything else is already buffered — no more socket reads needed
    assert dec.pending() == len(blob) - 4 - len(frames[0])
    for p in frames[1:]:
        assert dec.read_frame(b.fileno()) == p
    assert dec.pending() == 0


def test_large_frame_grows_and_shrinks(pair):
    """A frame far beyond the initial buffer allocates, parses, and the
    decoder returns to a small buffer afterwards (no 1 GiB held hostage)."""
    a, b = pair
    payload = os.urandom(8 << 20)

    t = threading.Thread(target=hp.send_frame, args=(a.fileno(), payload))
    t.start()
    dec = hp.FrameDecoder()
    assert dec.read_frame(b.fileno()) == payload
    t.join(timeout=30)
    # follow-up small frame still works (buffer state consistent post-shrink)
    hp.send_frame(a.fileno(), b"tail")
    assert dec.read_frame(b.fileno()) == b"tail"


def test_eof_raises_connection_error(pair):
    a, b = pair
    a.close()
    dec = hp.FrameDecoder()
    with pytest.raises(ConnectionError):
        dec.read_frame(b.fileno())


def test_eof_mid_frame_raises(pair):
    a, b = pair
    a.sendall(_LEN.pack(100) + b"only-some")
    a.close()
    dec = hp.FrameDecoder()
    with pytest.raises(ConnectionError):
        dec.read_frame(b.fileno())


def test_closed_fd_raises_oserror(pair):
    a, b = pair
    dec = hp.FrameDecoder()
    fd = b.fileno()
    b.close()
    with pytest.raises(OSError):
        dec.read_frame(fd)


def test_frame_reader_wrapper_matches_send_msg(pair):
    """protocol.FrameReader over a socket interoperates with send_msg —
    the integration surface the pool/rpc reader loops actually use."""
    a, b = pair
    reader = protocol.FrameReader(b)
    protocol.send_msg(a, "result", {"task_id": b"t" * 20, "value": 42})
    assert reader.recv() == ("result", {"task_id": b"t" * 20, "value": 42})


def test_concurrent_senders_one_lock_no_interleave(pair):
    """send_frame under a lock (as every caller does) never interleaves
    frames: 200 frames from 4 threads all arrive intact."""
    a, b = pair
    lock = threading.Lock()
    sent = []

    def sender(tid):
        for i in range(50):
            p = bytes([tid]) * (i + 1)
            with lock:
                sent.append(p)
                hp.send_frame(a.fileno(), p)

    threads = [threading.Thread(target=sender, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    dec = hp.FrameDecoder()
    got = [dec.read_frame(b.fileno()) for _ in range(200)]
    assert sorted(got) == sorted(sent)
