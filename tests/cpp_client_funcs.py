"""Python functions invoked by name from the C++ client test
(cross-language call targets; see tests/cpp_client_main.cpp)."""


def format_sum(a: int, b: int, label: str) -> str:
    return f"{label}={a + b}"


def reverse_bytes(data: bytes) -> bytes:
    return bytes(reversed(data))
