"""The examples/ scripts stay runnable (parity model: the reference CIs its
doc examples). Each runs as a real subprocess — user-style, own interpreter,
CPU platform — and must exit 0."""

import os
import pathlib
import subprocess
import sys

import pytest

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


def _run(name: str, timeout: int = 240) -> str:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = str(EXAMPLES.parent) + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, str(EXAMPLES / name)],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )
    assert proc.returncode == 0, f"{name} failed:\n{proc.stdout}\n{proc.stderr}"
    return proc.stdout


def test_example_tasks_and_actors():
    assert "tasks/actors tour OK" in _run("01_tasks_and_actors.py")


def test_example_data_pipeline():
    assert "data tour OK" in _run("02_data_pipeline.py")


def test_example_train_transformer():
    assert "train tour OK" in _run("03_train_transformer.py")


def test_example_generation():
    assert "generation tour OK" in _run("07_generation.py")


@pytest.mark.full
def test_example_tune_search():
    assert "tune tour OK" in _run("04_tune_search.py")


@pytest.mark.full
def test_example_serve_deployment():
    assert "serve tour OK" in _run("05_serve_deployment.py")


@pytest.mark.full
def test_example_rllib_ppo():
    assert "rllib tour OK" in _run("06_rllib_ppo.py")


def test_example_workflows():
    assert "workflow tour OK" in _run("08_workflows.py")


@pytest.mark.full
def test_example_llm_serving():
    assert "llm tour OK" in _run("09_llm_serving.py")


def test_example_dask_graphs():
    assert "dask tour OK" in _run("10_dask_graphs.py")


@pytest.mark.full
def test_example_openai_serving():
    assert "openai serving tour OK" in _run("11_openai_serving.py")
