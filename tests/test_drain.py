"""Graceful elasticity: node drain, head failover, crash-atomic snapshots,
and the failpoint-state round trip that keeps chaos deterministic through a
head restart (ISSUE 6).

The chaos-schedule integration lives in ``test_chaos.py`` (schedules 8-10);
this file covers the mechanisms one at a time:

  * ``Cluster.drain_node``: sole-replica evacuation, actor restarts off the
    draining node, scheduler exclusion (including parked demand-queue
    entries), autoscaler termination routing,
  * ``control.save_snapshot``: fsync + rename + ``.prev`` rotation — a torn
    current generation restores the previous one, never garbage,
  * ``failpoints.snapshot_state``/``restore_state``: hit counters and the
    fault log resume across a simulated process death, byte-identically,
  * ``rt chaos validate``: friendly schema errors before a run burns time.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.chaos.schedule import validate_schedule
from ray_tpu.runtime import failpoints
from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy


def _wait_for(predicate, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# --------------------------------------------------------------------------
# drain_node
# --------------------------------------------------------------------------
def test_drain_evacuates_sole_replica_objects(ray_start_cluster):
    rt_mod, cluster = ray_start_cluster
    node_b = cluster.add_node({"CPU": 1})

    @rt.remote(execution="thread")
    def produce(i):
        return np.full(300_000, i, np.uint8)

    refs = [
        produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_b.node_id)
        ).remote(i)
        for i in range(4)
    ]
    # wait for commits WITHOUT get() — a get would replicate onto the head
    # and there would be nothing sole-replica left to evacuate
    assert _wait_for(
        lambda: all(cluster.directory.locations(r.id()) for r in refs)
    )
    assert all(
        cluster.directory.locations(r.id()) == {node_b.node_id} for r in refs
    )

    report = cluster.drain_node(node_b.node_id)
    assert report["outcome"] == "ok", report
    assert report["evacuated"] == 4
    assert report["evacuated_bytes"] >= 4 * 300_000
    assert node_b.dead
    # every value survived the node via its evacuated replica — no lineage
    # reconstruction ran (the tasks would otherwise re-execute)
    values = rt.get(refs, timeout=30)
    assert all(v[0] == i and v.nbytes == 300_000 for i, v in enumerate(values))
    for r in refs:
        assert node_b.node_id not in cluster.directory.locations(r.id())
    assert cluster.drain_reports[-1] is report

    from ray_tpu.runtime.control import NodeState

    assert cluster.control.nodes.get(node_b.node_id).state is NodeState.DEAD


def test_drain_restarts_actor_elsewhere(ray_start_cluster):
    rt_mod, cluster = ray_start_cluster
    node_b = cluster.add_node({"CPU": 1, "R": 1})
    node_c = cluster.add_node({"CPU": 1, "R": 1})

    @rt.remote
    class Holder:
        def __init__(self):
            self.pid_tag = "alive"

        def ping(self):
            return self.pid_tag

    h = (
        Holder.options(
            max_restarts=2,
            resources={"R": 1},
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_b.node_id, soft=True),
        ).remote()
    )
    assert rt.get(h.ping.remote(), timeout=30) == "alive"
    info = cluster.control.actors.get(h._actor_id)
    assert info.node_id == node_b.node_id

    report = cluster.drain_node(node_b.node_id)
    assert report["actors_restarted"] == 1
    # the restart FSM brought it back on the survivor, not the drained node
    assert rt.get(h.ping.remote(), timeout=30) == "alive"
    info = cluster.control.actors.get(h._actor_id)
    assert info.node_id == node_c.node_id
    assert info.num_restarts == 1


def test_draining_node_excluded_from_placement(ray_start_cluster):
    rt_mod, cluster = ray_start_cluster
    node_b = cluster.add_node({"CPU": 2})
    node_c = cluster.add_node({"CPU": 2})
    cluster.cluster_scheduler.set_draining(node_b.node_id)
    try:
        before = node_b.scheduler.num_submitted

        @rt.remote(execution="thread")
        def f(i):
            return i

        refs = [
            f.options(scheduling_strategy="SPREAD").remote(i) for i in range(12)
        ]
        assert rt.get(refs, timeout=30) == list(range(12))
        assert node_b.scheduler.num_submitted == before
        assert node_c.scheduler.num_submitted > 0
    finally:
        cluster.cluster_scheduler.set_draining(node_b.node_id, False)


def test_drain_revokes_worker_leases(ray_start_cluster):
    """ISSUE 7 satellite: flipping a node DRAINING revokes its cached
    worker leases — the drain never waits on an idle-but-leased worker,
    and repeat-shape tasks re-grant on survivors."""
    rt_mod, cluster = ray_start_cluster
    node_b = cluster.add_node({"CPU": 2, "aux": 2})

    @rt.remote(resources={"aux": 1}, num_cpus=0, execution="thread")
    def on_aux():
        return 1

    assert rt.get([on_aux.remote() for _ in range(5)], timeout=30) == [1] * 5
    lm = cluster.lease_manager
    assert lm.leases_on(node_b.node_id) == 1
    revoked_before = lm.revoked
    report = cluster.drain_node(node_b.node_id)
    assert report["outcome"] == "ok", report
    assert lm.leases_on(node_b.node_id) == 0
    assert lm.revoked > revoked_before
    # a survivor with the resource picks the shape back up via a new grant
    node_c = cluster.add_node({"CPU": 2, "aux": 2})
    grants_before = lm.grants
    assert rt.get(on_aux.remote(), timeout=30) == 1
    assert lm.grants > grants_before
    assert lm.leases_on(node_c.node_id) == 1


def test_parked_demand_does_not_dispatch_to_draining_node(ray_start_cluster):
    """A demand-queue entry parked while its only feasible node is draining
    must wait for a NEW node, never dispatch onto the draining one."""
    rt_mod, cluster = ray_start_cluster
    node_b = cluster.add_node({"CPU": 1, "special": 1})
    cluster.cluster_scheduler.set_draining(node_b.node_id)

    @rt.remote(resources={"special": 1}, execution="thread")
    def f():
        return "ran"

    ref = f.remote()  # parks: the only "special" node is draining
    time.sleep(0.3)
    assert node_b.scheduler.num_submitted == 0
    node_c = cluster.add_node({"CPU": 1, "special": 1})
    assert rt.get(ref, timeout=30) == "ran"
    assert node_b.scheduler.num_submitted == 0
    assert node_c.scheduler.num_submitted == 1
    cluster.cluster_scheduler.set_draining(node_b.node_id, False)


def test_autoscaler_terminate_routes_through_drain(ray_start_cluster):
    """Idle scale-down must not strand the only copy of a live object: the
    provider's terminate_node drains (evacuates) instead of hard-killing."""
    from ray_tpu.autoscaler.demand import NodeTypeConfig
    from ray_tpu.autoscaler.node_provider import InProcessNodeProvider

    rt_mod, cluster = ray_start_cluster
    provider = InProcessNodeProvider(cluster)
    (pid,) = provider.create_nodes(
        NodeTypeConfig(name="worker", resources={"CPU": 1}), 1
    )
    node = next(n for nid, n in cluster.nodes.items() if nid.hex() == pid)

    @rt.remote(execution="thread")
    def produce():
        return np.arange(200_000, dtype=np.uint8)

    ref = produce.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node.node_id)
    ).remote()
    assert _wait_for(lambda: bool(cluster.directory.locations(ref.id())))
    assert cluster.directory.locations(ref.id()) == {node.node_id}

    provider.terminate_node(pid)
    assert node.dead
    assert cluster.drain_reports[-1]["evacuated"] == 1
    assert rt.get(ref, timeout=30).nbytes == 200_000


def test_drain_head_node_rejected(ray_start_regular):
    cluster = rt.get_cluster()
    with pytest.raises(ValueError, match="head"):
        cluster.drain_node(cluster.head_node.node_id)


# --------------------------------------------------------------------------
# crash-atomic snapshots
# --------------------------------------------------------------------------
def test_snapshot_truncated_file_falls_back_to_prev(tmp_path):
    from ray_tpu.runtime.control import ControlService

    path = str(tmp_path / "control.snap")
    svc = ControlService()
    svc.kv.put(b"gen", b"one")
    svc.save_snapshot(path)
    svc.kv.put(b"gen", b"two")
    svc.save_snapshot(path)  # rotates gen-one to .prev

    # tear the current generation mid-write (what a kill -9 leaves behind
    # when the filesystem loses the tail)
    with open(path, "rb") as f:
        raw = f.read()
    with open(path, "wb") as f:
        f.write(raw[: len(raw) // 2])

    restored = ControlService()
    assert restored.restore_snapshot(path) is True
    assert restored.kv.get(b"gen") == b"one"  # previous complete generation
    restored.shutdown()
    svc.shutdown()


def test_snapshot_both_generations_torn_starts_empty(tmp_path):
    from ray_tpu.runtime.control import ControlService

    path = str(tmp_path / "control.snap")
    with open(path, "wb") as f:
        f.write(b"RTSNAP1\n" + b"\x00" * 10)  # torn beyond recovery
    with open(path + ".prev", "wb") as f:
        f.write(b"garbage")
    restored = ControlService()
    assert restored.restore_snapshot(path) is False
    assert restored.kv.get(b"gen") is None
    restored.shutdown()


def test_snapshot_round_trip_preserves_state(tmp_path):
    from ray_tpu.runtime.control import ControlService

    path = str(tmp_path / "control.snap")
    svc = ControlService()
    svc.kv.put(b"k", b"v")
    svc.task_events.add({"task_id": "t", "state": "FINISHED", "attempt": 0})
    svc.spans.add({"type": "span", "name": "retry::f"})
    svc.save_snapshot(path)
    restored = ControlService()
    assert restored.restore_snapshot(path) is True
    assert restored.kv.get(b"k") == b"v"
    assert restored.task_events.list_events()[-1]["task_id"] == "t"
    assert restored.spans.list_events()[-1]["name"] == "retry::f"
    restored.shutdown()
    svc.shutdown()


# --------------------------------------------------------------------------
# failpoint state through a (simulated) head death
# --------------------------------------------------------------------------
def test_failpoint_state_round_trip_is_byte_identical():
    """The determinism contract THROUGH a restart: a run whose failpoint
    state is snapshotted, wiped (process death), and restored produces the
    same fault log as an uninterrupted run of the same seed."""
    def drive(n):
        hits = []
        for _ in range(n):
            try:
                failpoints.fp("demo.site")
            except failpoints.FailpointInjected:
                hits.append(1)
        return hits

    try:
        # uninterrupted reference run: 30 hits
        failpoints.reset()
        failpoints.arm("demo.site=raise(0.5)", seed=1234)
        drive(30)
        reference = failpoints.fault_log()
        assert reference, "the failpoint must fire at p=0.5"

        # interrupted run: 12 hits, snapshot, full wipe, restore, 18 more
        failpoints.reset()
        failpoints.arm("demo.site=raise(0.5)", seed=1234)
        drive(12)
        snap = failpoints.snapshot_state()
        failpoints.reset()  # the head process died
        assert failpoints.fault_log() == []
        failpoints.restore_state(snap)
        assert failpoints.ARMED  # armed spec came back with the state
        drive(18)
        assert failpoints.fault_log() == reference
    finally:
        failpoints.reset()


def test_control_snapshot_carries_failpoint_state(tmp_path):
    from ray_tpu.runtime.control import ControlService

    path = str(tmp_path / "control.snap")
    try:
        failpoints.reset()
        failpoints.arm("demo.snap=raise(0.5)", seed=9)
        for _ in range(10):
            try:
                failpoints.fp("demo.snap")
            except failpoints.FailpointInjected:
                pass
        log_before = failpoints.fault_log()
        svc = ControlService()
        svc.save_snapshot(path)
        failpoints.reset()
        restored = ControlService()
        assert restored.restore_snapshot(path) is True
        assert failpoints.fault_log() == log_before
        assert failpoints.configured("demo.snap")["prob"] == 0.5
        restored.shutdown()
        svc.shutdown()
    finally:
        failpoints.reset()


# --------------------------------------------------------------------------
# head kill/restart mechanism (schedule-driven variant in test_chaos.py)
# --------------------------------------------------------------------------
def test_kill_restart_head_preserves_named_actor_and_kv(ray_start_regular):
    cluster = rt.get_cluster()

    @rt.remote
    class Keeper:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    k = Keeper.options(name="drain-keeper").remote()
    assert rt.get(k.bump.remote(), timeout=30) == 1
    cluster.control.kv.put(b"marker", b"pre-kill")

    path = cluster.kill_head()
    # a doomed-incarnation write: discarded at restart, like any write to a
    # dying GCS that never committed
    cluster.control.kv.put(b"doomed", b"lost")
    info = cluster.restart_head()
    assert info["reconciled"] >= 1
    assert cluster.head_restarts == 1
    assert cluster.control.kv.get(b"marker") == b"pre-kill"
    assert cluster.control.kv.get(b"doomed") is None

    # the named record survived AND the live instance reconciled: in-process
    # state (n == 1) carried through the outage
    k2 = rt.get_actor("drain-keeper")
    assert rt.get(k2.bump.remote(), timeout=30) == 2
    import os

    assert path.startswith("/") and os.path.exists(path)


def test_restart_head_without_kill_rejected(ray_start_regular):
    cluster = rt.get_cluster()
    with pytest.raises(RuntimeError, match="kill_head"):
        cluster.restart_head()


def test_double_kill_head_rejected(ray_start_regular):
    """A second kill_head before restart would snapshot the doomed
    incarnation — persisting exactly the writes the first kill promised
    to discard."""
    cluster = rt.get_cluster()
    cluster.kill_head()
    with pytest.raises(RuntimeError, match="already down"):
        cluster.kill_head()
    cluster.restart_head()


def test_restart_head_readopts_live_placement_groups(ray_start_regular):
    """Live placement groups (bundle resources held in surviving node
    pools) must survive a head restart like live actors do — dropping the
    registry would leak the acquired capacity forever."""
    from ray_tpu.util.placement import placement_group, remove_placement_group

    cluster = rt.get_cluster()
    pg = placement_group([{"CPU": 1}])
    assert rt.get(pg.ready(), timeout=30)
    head_pool = cluster.head_node.pool
    held = head_pool.available.to_dict().get("CPU")

    cluster.kill_head()
    cluster.restart_head()

    infos = cluster.control.placement_groups.list_groups()
    assert any(i.pg_id == pg.id for i in infos)
    # removal through the FRESH control releases the bundle's resources
    remove_placement_group(pg)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if head_pool.available.to_dict().get("CPU") == held + 1:
            break
        time.sleep(0.05)
    assert head_pool.available.to_dict().get("CPU") == held + 1


# --------------------------------------------------------------------------
# plan repair (chaos-driven variant in test_chaos.py)
# --------------------------------------------------------------------------
def test_plan_repair_after_restartable_stage_death(ray_start_cluster):
    from ray_tpu.dag import InputNode
    from ray_tpu.exceptions import ActorDiedError, RayActorError

    rt_mod, cluster = ray_start_cluster
    cluster.add_node({"CPU": 1, "stage": 1})
    cluster.add_node({"CPU": 1, "stage": 1})

    @rt.remote
    class Stage:
        def __init__(self, k):
            self.k = k

        def step(self, x):
            return x + self.k

    s0 = Stage.options(execution="inproc").remote(1)
    s1 = Stage.options(
        execution="inproc", num_cpus=0, resources={"stage": 1}, max_restarts=1
    ).remote(10)
    with InputNode() as inp:
        d = s0.step.bind(s1.step.bind(inp))
    plan = d.compile_plan(name="repairable")
    try:
        assert plan.execute(5) == 16

        rt.kill(s1, no_restart=False)  # restartable: the FSM revives it
        deadline = time.monotonic() + 30
        while plan.state != "BROKEN" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert plan.state == "BROKEN"
        with pytest.raises((ActorDiedError, RayActorError)):
            plan.execute(5)

        plan.repair(timeout=30)
        assert plan.state == "READY"
        for i in range(5):
            assert plan.execute(i) == i + 11
        assert plan.state_history == ["READY", "BROKEN", "READY"]
        # the cluster-level transition log feeds the chaos invariant sweep
        ours = [t for t in cluster.plan_transitions if t[0] == plan.plan_id]
        assert ours == [
            (plan.plan_id, "READY", "BROKEN"),
            (plan.plan_id, "BROKEN", "READY"),
        ]
    finally:
        plan.teardown()


def test_plan_repair_fails_for_dead_stage(ray_start_regular):
    from ray_tpu.dag import InputNode
    from ray_tpu.exceptions import ActorDiedError, RayActorError

    @rt.remote
    class Stage:
        def step(self, x):
            return x * 2

    s0 = Stage.options(execution="inproc").remote()  # max_restarts=0
    with InputNode() as inp:
        d = s0.step.bind(inp)
    plan = d.compile_plan(name="unrepairable")
    try:
        assert plan.execute(4) == 8
        rt.kill(s0)
        deadline = time.monotonic() + 30
        while plan.state != "BROKEN" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert plan.state == "BROKEN"
        with pytest.raises((ActorDiedError, RayActorError, TimeoutError)):
            plan.repair(timeout=3)
        assert plan.state == "BROKEN"
    finally:
        plan.teardown()


# --------------------------------------------------------------------------
# observability surfaces: /api/autoscaler + `rt nodes`
# --------------------------------------------------------------------------
def test_api_autoscaler_and_rt_nodes_surface_drains(capsys):
    import json
    import urllib.request

    rt.init(num_cpus=2, include_dashboard=True)
    try:
        cluster = rt.get_cluster()
        # head restart FIRST: liveness is rebuilt from the living, so a
        # node drained before the restart would (correctly) drop out of
        # the fresh node table entirely
        cluster.kill_head()
        cluster.restart_head()
        node_b = cluster.add_node({"CPU": 1})
        cluster.drain_node(node_b.node_id)

        url = cluster.dashboard.url
        with urllib.request.urlopen(url + "/api/autoscaler", timeout=30) as resp:
            data = json.loads(resp.read())
        states = {n["node_id"]: n["state"] for n in data["nodes"]}
        assert states[node_b.node_id.hex()] == "DEAD"
        assert any(n["is_head"] and n["state"] == "ALIVE" for n in data["nodes"])
        assert data["head_restarts"] == 1
        assert data["drains"] and data["drains"][0]["node"] == node_b.node_id.hex()[:8]

        from ray_tpu.scripts.cli import main

        assert main(["nodes", "--address", url]) == 0
        out = capsys.readouterr().out
        assert "DEAD" in out and "head restarts: 1" in out and "drains: 1" in out
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# schedule validation (`rt chaos validate`)
# --------------------------------------------------------------------------
def test_validate_schedule_catches_schema_errors():
    errors = validate_schedule(
        {
            "seed": "not-an-int",
            "events": [
                {"t": -1.0, "kind": "kill_node", "index": 0},
                {"t": 0.5, "kind": "explode"},
                {"t": 1.0, "kind": "arm"},                      # missing spec
                {"t": 1.5, "kind": "arm", "spec": "x=frobnicate"},
                {"t": 2.0, "kind": "lose_objects", "fraction": 1.5},
                {"t": 2.5, "kind": "kill_node", "index": -2},
                {"t": 3.0, "kind": "partition", "fp": "rpc.call", "duration": 0},
                {"t": 3.5, "kind": "restart_head"},             # no kill_head
                {"t": 4.0, "kind": "kill_node", "whom": 1},     # unknown param
            ],
        }
    )
    text = "\n".join(errors)
    assert "'seed' must be an integer" in text
    assert "'t' must be >= 0" in text
    assert "unknown kind 'explode'" in text
    assert "missing required parameter 'spec'" in text
    assert "bad failpoint spec" in text
    assert "'fraction' must be in [0, 1]" in text
    assert "'index' must be >= 0" in text
    assert "'duration' must be > 0" in text
    assert "restart_head without a preceding kill_head" in text
    assert "unknown parameter 'whom'" in text


def test_validate_schedule_bounds_node_indices():
    events = [
        {"t": 0.0, "kind": "kill_node", "index": 1},
        {"t": 1.0, "kind": "add_node", "resources": {"CPU": 1}},
        {"t": 2.0, "kind": "drain_node", "index": 1},
        {"t": 3.0, "kind": "kill_node", "index": 1},  # only 1 node left
    ]
    errors = validate_schedule({"seed": 0, "events": events}, num_nodes=2)
    assert len(errors) == 1 and "index 1 out of range" in errors[0]
    assert not validate_schedule({"seed": 0, "events": events[:3]}, num_nodes=2)


def test_validate_schedule_accepts_elasticity_schedule():
    sched = {
        "seed": 7,
        "events": [
            {"t": 0.0, "kind": "arm", "spec": "object_store.put=raise(0.3)"},
            {"t": 0.5, "kind": "add_node", "resources": {"CPU": 2}},
            {"t": 1.0, "kind": "drain_node", "index": 0, "timeout": 10},
            {"t": 1.5, "kind": "kill_head"},
            {"t": 2.5, "kind": "restart_head"},
            {"t": 3.0, "kind": "disarm"},
        ],
    }
    assert validate_schedule(sched, num_nodes=1) == []


def test_chaos_validate_cli_smoke(tmp_path, capsys):
    import json

    from ray_tpu.scripts.cli import main

    good = tmp_path / "good.json"
    good.write_text(json.dumps({
        "seed": 3,
        "events": [{"t": 0.0, "kind": "arm", "spec": "rpc.call=delay(0.1,0.5)"}],
    }))
    assert main(["chaos", "validate", str(good)]) == 0
    assert "ok (1 events" in capsys.readouterr().out

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"seed": 3, "events": [{"t": 0, "kind": "nope"}]}))
    assert main(["chaos", "validate", str(bad)]) == 1
    assert "unknown kind" in capsys.readouterr().err

    notjson = tmp_path / "notjson.json"
    notjson.write_text("{")
    assert main(["chaos", "validate", str(notjson)]) == 1
    assert "not valid JSON" in capsys.readouterr().err
