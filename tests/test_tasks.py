"""End-to-end task API tests (parity: python/ray/tests/test_basic*.py)."""

import os
import time

import numpy as np
import pytest


def test_basic_task(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def f(x):
        return x + 1

    assert rt.get(f.remote(1)) == 2


def test_chained_dependencies(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(10):
        ref = inc.remote(ref)
    assert rt.get(ref) == 11


def test_multiple_returns(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert rt.get([a, b, c]) == [1, 2, 3]


def test_kwargs_and_ref_kwargs(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def f(a, b=0):
        return a + b

    ref = rt.put(5)
    assert rt.get(f.remote(1, b=ref)) == 6


def test_error_propagation_with_traceback(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def boom():
        raise ZeroDivisionError("oops")

    with pytest.raises(rt.RayTaskError) as info:
        rt.get(boom.remote())
    assert "ZeroDivisionError" in info.value.traceback_str


def test_error_through_dependency(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def boom():
        raise ValueError("first")

    @rt.remote
    def consume(x):
        return x

    # the consumer's arg resolution surfaces the upstream error
    with pytest.raises(Exception):
        rt.get(consume.remote(boom.remote()), timeout=15)


def test_large_array_through_process_worker(ray_start_regular):
    rt = ray_start_regular
    data = np.random.rand(512, 512)

    @rt.remote
    def stats(x):
        return float(x.sum()), x.shape

    total, shape = rt.get(stats.remote(data))
    assert shape == (512, 512)
    assert abs(total - data.sum()) < 1e-6


def test_large_return_from_process_worker(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def make():
        return np.ones((1024, 1024), dtype=np.float32)

    out = rt.get(make.remote())
    assert out.nbytes == 4 * 1024 * 1024
    assert float(out.sum()) == 1024 * 1024


def test_process_isolation(ray_start_regular):
    """execution="process" guarantees worker-process isolation."""
    rt = ray_start_regular

    @rt.remote(execution="process")
    def worker_pid():
        return os.getpid()

    pids = rt.get([worker_pid.remote() for _ in range(4)])
    assert os.getpid() not in pids


def test_adaptive_tiering_fast_tasks_run_inproc(ray_start_regular):
    """Auto-mode tasks with sub-threshold runtimes stay on the zero-IPC
    in-process executor after the trial phase."""
    rt = ray_start_regular

    @rt.remote
    def fast_pid():
        return os.getpid()

    for _ in range(3):
        rt.get(fast_pid.remote())
    assert rt.get(fast_pid.remote()) == os.getpid()


def test_adaptive_tiering_heavy_tasks_migrate_to_process(ray_start_regular):
    """Auto-mode tasks whose observed runtime exceeds the threshold move
    to process workers (GIL-free parallelism)."""
    rt = ray_start_regular

    @rt.remote
    def heavy_pid():
        t0 = time.monotonic()
        while time.monotonic() - t0 < 0.02:
            pass
        return os.getpid()

    for _ in range(3):
        rt.get(heavy_pid.remote())
    assert rt.get(heavy_pid.remote()) != os.getpid()


def test_thread_execution_in_process(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(execution="thread")
    def here():
        return os.getpid()

    assert rt.get(here.remote()) == os.getpid()


def test_nested_tasks(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(execution="thread")
    def inner(x):
        return x * 2

    @rt.remote(execution="thread")
    def outer(x):
        return rt.get(inner.remote(x)) + 1

    assert rt.get(outer.remote(10)) == 21


def test_wait_semantics(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(execution="thread")
    def fast():
        return "fast"

    @rt.remote
    def slow():
        time.sleep(3)
        return "slow"

    refs = [slow.remote(), fast.remote()]
    ready, not_ready = rt.wait(refs, num_returns=1, timeout=10)
    assert len(ready) == 1 and len(not_ready) == 1
    assert rt.get(ready[0]) == "fast"


def test_retries_on_worker_crash(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(max_retries=2)
    def flaky(path):
        # crash the worker process on first attempt
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)
        return "recovered"

    marker = f"/tmp/rt_flaky_{os.getpid()}_{time.time_ns()}"
    try:
        assert rt.get(flaky.remote(marker), timeout=60) == "recovered"
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


def test_no_retry_on_app_error_by_default(ray_start_regular):
    rt = ray_start_regular
    calls = {"n": 0}

    @rt.remote(execution="thread")
    def boom():
        calls["n"] += 1
        raise RuntimeError("app error")

    with pytest.raises(rt.RayTaskError):
        rt.get(boom.remote())
    assert calls["n"] == 1


def test_retry_exceptions_opt_in(ray_start_regular):
    rt = ray_start_regular
    state = {"n": 0}

    @rt.remote(execution="thread", max_retries=3, retry_exceptions=True)
    def eventually():
        state["n"] += 1
        if state["n"] < 3:
            raise RuntimeError("not yet")
        return state["n"]

    assert rt.get(eventually.remote(), timeout=30) == 3


def test_options_override(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def f():
        return 1

    g = f.options(name="renamed", num_returns=1)
    assert rt.get(g.remote()) == 1
    with pytest.raises(ValueError):
        f.options(bogus_option=1)


def test_direct_call_raises(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        f()


def test_jax_array_task_runs_inprocess(ray_start_regular):
    rt = ray_start_regular
    import jax
    import jax.numpy as jnp

    @rt.remote
    def matmul(a, b):
        return a @ b

    a = jnp.ones((64, 64))
    ref = matmul.remote(a, a)
    out = rt.get(ref)
    assert isinstance(out, jax.Array)
    assert out.shape == (64, 64)
    assert float(out[0, 0]) == 64.0


def test_get_timeout(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def slow():
        time.sleep(10)

    with pytest.raises(rt.GetTimeoutError):
        rt.get(slow.remote(), timeout=0.2)


def test_cluster_and_available_resources(ray_start_regular):
    rt = ray_start_regular
    total = rt.cluster_resources()
    assert total["CPU"] == 4
    avail = rt.available_resources()
    assert avail["CPU"] <= total["CPU"]


def test_runtime_context(ray_start_regular):
    rt = ray_start_regular
    ctx = rt.get_runtime_context()
    assert ctx.get_job_id()
    assert ctx.get_node_id()

    @rt.remote(execution="thread")
    def my_task_id():
        return rt.get_runtime_context().get_task_id()

    tid = rt.get(my_task_id.remote())
    assert tid is not None
