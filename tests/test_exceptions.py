"""Pickle round-trips for every exception class in ray_tpu.exceptions.

Errors cross process boundaries constantly (task results, error
tombstones, actor death notices), so every class must survive
pickle/unpickle with its type, message, and typed fields intact.  The
default ``Exception`` pickling replays ``__init__`` with
``args=(message,)`` — for any class whose first parameter is not the
message that corrupts state (the message lands in ``actor_id``),
double-formats, or raises ``TypeError`` outright; such classes need a
``__reduce__``.

Parametrized over the module by introspection: a newly added exception
class is tested automatically, and breaks here if it pickles lossily.
"""

import inspect
import pickle

import pytest

import ray_tpu.exceptions as exc_mod
from ray_tpu.runtime.failpoints import FailpointInjected
from ray_tpu.runtime.rpc import ControlPlaneTimeout

# one representative instance per constructor shape; classes absent here
# are constructed with a plain message (or no args)
_SAMPLES = {
    "RayTaskError": lambda c: c("f", "Traceback: boom\n", ValueError("boom")),
    "RayActorError": lambda c: c("actor-1f2e", "actor actor-1f2e crashed"),
    "ActorDiedError": lambda c: c("actor-1f2e", "actor actor-1f2e died"),
    "ActorUnavailableError": lambda c: c("actor-1f2e", "actor restarting"),
    "ObjectLostError": lambda c: c("obj-77aa"),
    "ObjectReconstructionFailedError": lambda c: c("obj-77aa", "3 retries failed"),
    "OwnerDiedError": lambda c: c("obj-77aa"),
    "TaskCancelledError": lambda c: c("task-0042"),
    "DeadlineExceededError": lambda c: c("train_step", "pulling", 1.5),
    "FencedError": lambda c: c("node-9c", 7),
    "OverloadedError": lambda c: c("router", "queue_full", 2.5),
    "StoreFullError": lambda c: c(4.25, 1 << 20),
    "CollectiveGroupDeadError": lambda c: c("allreduce-g0", "rank 3 died"),
}


def _exception_classes():
    for name, obj in sorted(vars(exc_mod).items()):
        if (
            inspect.isclass(obj)
            and issubclass(obj, BaseException)
            and obj.__module__ == exc_mod.__name__
        ):
            yield name, obj


def _state(e):
    """Picklable typed state: everything __init__ stored on the instance
    (the `cause` of RayTaskError compares by repr — exceptions don't
    define __eq__)."""
    return {
        k: repr(v) if isinstance(v, BaseException) else v
        for k, v in vars(e).items()
    }


@pytest.mark.parametrize(
    "name,cls", list(_exception_classes()), ids=[n for n, _ in _exception_classes()]
)
def test_exception_pickle_round_trip(name, cls):
    build = _SAMPLES.get(name, lambda c: c(f"{c.__name__}: synthetic message"))
    original = build(cls)
    clone = pickle.loads(pickle.dumps(original))
    assert type(clone) is cls
    assert str(clone) == str(original)
    assert _state(clone) == _state(original)


@pytest.mark.parametrize(
    "original",
    [FailpointInjected("data_plane.send_frame", 3), ControlPlaneTimeout("submit_task", 2.0)],
    ids=["FailpointInjected", "ControlPlaneTimeout"],
)
def test_runtime_exception_pickle_round_trip(original):
    # two-required-arg classes outside exceptions.py that ride the same
    # wire paths (chaos faults and rpc timeouts propagate to callers)
    clone = pickle.loads(pickle.dumps(original))
    assert type(clone) is type(original)
    assert str(clone) == str(original)
    assert vars(clone) == vars(original)


def test_every_sampled_class_exists():
    # _SAMPLES rot guard: renaming an exception must fail loudly here,
    # not silently fall back to the generic message constructor
    names = {n for n, _ in _exception_classes()}
    missing = set(_SAMPLES) - names
    assert not missing, f"_SAMPLES references unknown classes: {missing}"
