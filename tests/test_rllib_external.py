"""External (gym-API) and multi-agent env runners (round-1 VERDICT: RLlib
was JAX-native-envs only — no gym/multi-agent support).

Reference anchors: rllib/evaluation/rollout_worker.py (host-loop sampling),
rllib/env/multi_agent_env.py.
"""

import numpy as np
import pytest

from ray_tpu.rllib import GymEnvRunner, MultiAgentEnv, MultiAgentEnvRunner, SampleBatch
import jax

from ray_tpu.rllib.rl_module import ActorCriticModule


class TinyGymEnv:
    """Gymnasium-API env without the gymnasium dependency: 1D position
    walk; +1 reward for action 1, episode ends after 10 steps."""

    def __init__(self):
        self.t = 0

    def reset(self, *, seed=None, options=None):
        self.t = 0
        return np.zeros(4, np.float32), {}

    def step(self, action):
        self.t += 1
        obs = np.full(4, self.t / 10.0, np.float32)
        reward = float(action == 1)
        terminated = self.t >= 10
        return obs, reward, terminated, False, {}


def _module():
    return ActorCriticModule(obs_size=4, num_actions=2, hidden=(16,))


def test_gym_runner_samples_batches():
    module = _module()
    params = module.init(jax.random.key(0))
    runner = GymEnvRunner(
        [TinyGymEnv for _ in range(3)], module,
        rollout_length=25, num_actions=2,
    )
    batch, final_obs, returns = runner.sample(params)
    assert batch[SampleBatch.OBS].shape == (25, 3, 4)
    assert batch[SampleBatch.ACTIONS].shape == (25, 3)
    assert batch[SampleBatch.LOGP].shape == (25, 3)
    assert final_obs.shape == (3, 4)
    # 25 steps x 3 envs with 10-step episodes -> at least 6 completions
    assert len(returns) >= 6
    # terminals recorded at episode boundaries
    assert batch[SampleBatch.DONES].sum() >= 6
    runner.stop()


def test_gym_runner_classic_4tuple_api():
    class OldGym(TinyGymEnv):
        def step(self, action):  # classic gym: no truncated field
            obs, r, term, trunc, info = super().step(action)
            return obs, r, term, info

    module = _module()
    runner = GymEnvRunner([OldGym], module, rollout_length=12, num_actions=2)
    batch, _obs, returns = runner.sample(module.init(jax.random.key(0)))
    assert batch[SampleBatch.REWARDS].shape == (12, 1)
    assert len(returns) >= 1


class TwoAgentTag(MultiAgentEnv):
    """Two agents on a line; each gets its own reward; episode ends for
    all after 8 steps."""

    agents = ["a0", "a1"]

    def __init__(self):
        self.t = 0

    def reset(self):
        self.t = 0
        return {a: np.zeros(4, np.float32) for a in self.agents}, {}

    def step(self, action_dict):
        self.t += 1
        obs = {a: np.full(4, self.t / 8.0, np.float32) for a in self.agents}
        rewards = {a: float(act) for a, act in action_dict.items()}
        done = self.t >= 8
        terms = {a: done for a in self.agents}
        terms["__all__"] = done
        truncs = {"__all__": False}
        return obs, rewards, terms, truncs, {}


def test_multi_agent_runner_shared_policy():
    module = _module()
    params = module.init(jax.random.key(0))
    runner = MultiAgentEnvRunner(TwoAgentTag(), module, rollout_length=20)
    batch, final, returns = runner.sample(params)
    # [T, n_agents, obs]: both agents batched through one policy forward
    assert batch[SampleBatch.OBS].shape == (20, 2, 4)
    assert batch[SampleBatch.ACTIONS].shape == (20, 2)
    assert len(returns) >= 2  # 20 steps / 8-step episodes
    assert final.shape == (2, 4)
