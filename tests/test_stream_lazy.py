"""Bulk streaming-generator items over the data plane + wire-protocol
version handshake + batched head->agent actor dispatch.

Round-3 follow-through: stream items above the inline threshold stay in
the producing agent's store (metadata-only commit; consumers pull
peer-to-peer) — the control connection never carries bulk stream frames.
"""

import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.runtime import rpc

from test_multihost import _spawn_agent, _wait_for_nodes, two_process_cluster  # noqa: F401


def test_remote_stream_bulk_items_are_lazy(two_process_cluster):
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1}, num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield np.full(500_000, i, np.int64)  # 4MB per item: lazy path

    before = cluster.head_service.data_client.stats.snapshot()["pulls_issued"]
    items = []
    for ref in gen.remote(4):
        items.append(rt.get(ref, timeout=120))
    assert [int(x[0]) for x in items] == [0, 1, 2, 3]
    assert all(x.shape == (500_000,) for x in items)
    # the driver pulled the item bytes over the data plane, not control
    after = cluster.head_service.data_client.stats.snapshot()["pulls_issued"]
    assert after >= before + 4


def test_remote_stream_small_items_inline(two_process_cluster):
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1}, num_returns="streaming")
    def gen():
        for i in range(5):
            yield i * 11

    assert [rt.get(r, timeout=60) for r in gen.remote()] == [0, 11, 22, 33, 44]


def test_batched_actor_dispatch_preserves_order(two_process_cluster):
    """A burst of queued calls drains as batch frames head->agent->worker;
    per-actor execution order must hold exactly."""
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1}, execution="process")
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return i

        def get_log(self):
            return list(self.log)

    s = Seq.remote()
    refs = [s.add.remote(i) for i in range(60)]
    assert rt.get(refs, timeout=120) == list(range(60))
    assert rt.get(s.get_log.remote(), timeout=60) == list(range(60))


def test_nested_get_served_from_agent_store(two_process_cluster):
    """A worker's nested rt.get of a SAME-NODE bulk result is answered from
    the agent's local store — the value never round-trips the head."""
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1}, execution="process")
    def produce():
        return np.arange(1_000_000, dtype=np.float32)  # 4MB, lazy commit

    @rt.remote(resources={"remote": 1}, execution="process")
    def consume_nested(refs):
        x = rt.get(refs[0])  # nested get inside the agent's worker
        return float(x[10])

    # the counter the old slow path MOVES: the head fetched agent-held
    # values via its data client before relaying them back on control
    pulls_before = cluster.head_service.data_client.stats.snapshot()["pulls_issued"]
    ref = produce.remote()
    # nested-in-list refs are NOT auto-resolved (reference semantics) — the
    # worker receives the ObjectRef and gets it itself
    assert rt.get(consume_nested.remote([ref]), timeout=120) == 10.0
    # served agent-locally: the head never pulled the bulk value
    pulls_after = cluster.head_service.data_client.stats.snapshot()["pulls_issued"]
    assert pulls_after == pulls_before


def test_nested_put_keeps_bytes_on_agent(two_process_cluster):
    """A worker's nested rt.put stores the bytes in its own node's store
    (head mints the id + metadata only); the driver can still get it."""
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1}, execution="process")
    def put_and_return_ref():
        data = np.full(1_000_000, 9, np.int32)  # 4MB
        return [rt.put(data)]  # nested-in-list: survives as a ref

    [ref] = rt.get(put_and_return_ref.remote(), timeout=120)
    # the value is directory-located on the AGENT node, not the head
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and not cluster.directory.locations(ref.id()):
        time.sleep(0.1)
    locs = cluster.directory.locations(ref.id())
    assert locs and cluster.head_node.node_id not in locs, locs
    out = rt.get(ref, timeout=60)
    assert int(out[5]) == 9 and out.shape == (1_000_000,)


def test_compiled_dag_with_remote_actor(two_process_cluster):
    """Compiled DAGs span OS processes: a stage actor living in the agent
    executes through the compiled schedule (bulk intermediates ride the
    data plane via the normal call path)."""
    from ray_tpu.dag import InputNode

    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1}, execution="thread")
    class Scale:
        def apply(self, x):
            return x * 2.0

    @rt.remote
    class Bias:
        def apply(self, x):
            return x + 1.0

    remote_actor = Scale.remote()
    local_actor = Bias.remote()
    rt.get([remote_actor.apply.remote(np.float64(0)), local_actor.apply.remote(np.float64(0))], timeout=60)

    with InputNode() as inp:
        mid = remote_actor.apply.bind(inp)     # executes in the agent process
        out = local_actor.apply.bind(mid)      # executes in the driver
    dag = out.experimental_compile()
    try:
        for i in range(3):
            x = np.full(400_000, float(i))     # 3.2MB: crosses via data plane
            result = dag.execute(x)
            assert float(result[0]) == i * 2.0 + 1.0
    finally:
        dag.teardown()


def test_protocol_version_mismatch_rejected():
    from ray_tpu.runtime.agent import NodeAgent

    agent = NodeAgent("127.0.0.1:1", {"CPU": 1})
    with pytest.raises(rpc.RpcError, match="protocol version mismatch"):
        agent._check_protocol({"protocol_version": rpc.PROTOCOL_VERSION + 1})
    # matching and legacy (absent) versions pass
    agent._check_protocol({"protocol_version": rpc.PROTOCOL_VERSION})
    agent._check_protocol({})
