"""Cluster lifecycle provisioning: `rt up`-style launch with real agent
processes (round-1 VERDICT missing item 6 — ray up / providers / command
runner).

Reference anchors: python/ray/scripts/scripts.py:1279 (ray up),
python/ray/autoscaler/_private/command_runner.py,
_private/local/node_provider.py.
"""

import time

import pytest
import yaml

import ray_tpu as rt
from ray_tpu.autoscaler.launcher import ClusterLauncher, load_cluster_config, up
from ray_tpu.autoscaler.node_provider import SSHNodeProvider, SubprocessNodeProvider
from ray_tpu.autoscaler.demand import NodeTypeConfig


@pytest.fixture
def cluster_yaml(tmp_path):
    cfg = {
        "cluster_name": "test",
        "provider": {"type": "local"},
        "head": {"num_cpus": 2},
        "available_node_types": {
            "cpu_worker": {
                "resources": {"CPU": 1, "pool": 1},
                "min_workers": 2,
                "max_workers": 4,
            }
        },
        "max_workers": 4,
        "idle_timeout_s": 300,
    }
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(cfg))
    return str(path)


@pytest.mark.full
def test_up_provisions_min_workers_and_down_terminates(cluster_yaml):
    launcher = up(cluster_yaml, timeout_s=120)
    try:
        cluster = rt.get_cluster()
        live = [n for n in cluster.nodes.values() if not n.dead]
        assert len(live) == 3  # head + 2 provisioned agent processes

        # work actually lands on provisioned workers (their exclusive
        # 'pool' resource)
        @rt.remote(resources={"pool": 1})
        def where():
            import os

            return os.getpid()

        import os

        pids = set(rt.get([where.remote() for _ in range(2)], timeout=60))
        assert os.getpid() not in pids

        # provisioned agents carry the provider-id label so the autoscaler
        # can track their busy/idle state
        labeled = [
            n for n in live
            if (getattr(n, "labels", None) or {}).get("rt_provider_id")
        ]
        assert len(labeled) == 2
    finally:
        launcher.down()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sum(1 for n in rt.get_cluster().nodes.values() if not n.dead) == 1:
                break
            time.sleep(0.2)
        assert sum(1 for n in rt.get_cluster().nodes.values() if not n.dead) == 1
        rt.shutdown()


def test_config_validation(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("cluster_name: x\n")
    with pytest.raises(ValueError, match="available_node_types"):
        load_cluster_config(str(bad))


def test_ssh_provider_command_shape():
    """The SSH command runner builds the right remote invocation (no real
    SSH here; command construction is the testable contract)."""
    p = SSHNodeProvider(
        "10.0.0.1:6380", ["worker1"], ssh_user="ubuntu", ssh_key="/k",
        remote_python="python3.11", remote_dir="/opt/app",
    )
    base = p._ssh_base("worker1")
    assert base[0] == "ssh" and "-i" in base and "ubuntu@worker1" == base[-1]
    assert p.non_terminated_nodes() == {}
