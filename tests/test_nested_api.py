"""Nested runtime API from inside worker processes.

The reference embeds a CoreWorker in every worker, so ray.get/.remote/ray.put
work anywhere (SURVEY §1 layer 4). Here workers route API calls back to the
owning driver over the pool socket (runtime/worker_api.py); blocked parents
release their CPU so children can run (raylet NotifyUnblocked parity).
"""

import time

import pytest

import ray_tpu as rt


@pytest.fixture
def runtime():
    rt.init(num_cpus=2)
    try:
        yield rt
    finally:
        rt.shutdown()


def test_nested_task_from_worker(runtime):
    @rt.remote(execution="process")
    def child(x):
        return x * 2

    @rt.remote(execution="process")
    def parent(x):
        # full submit + get round trip from inside a worker process
        return rt.get(child.remote(x)) + 1

    assert rt.get(parent.remote(10), timeout=120) == 21


def test_nested_put_get_from_worker(runtime):
    import numpy as np

    @rt.remote(execution="process")
    def roundtrip():
        arr = np.arange(1000, dtype=np.float64)
        ref = rt.put(arr)
        back = rt.get(ref)
        return float(back.sum())

    assert rt.get(roundtrip.remote(), timeout=120) == pytest.approx(999 * 1000 / 2)


def test_nested_fanout_does_not_deadlock(runtime):
    """Two blocked parents on a 2-CPU node: children can only run because
    blocked workers release their resources."""

    @rt.remote(execution="process")
    def leaf(x):
        return x + 1

    @rt.remote(execution="process")
    def parent(x):
        return sum(rt.get([leaf.remote(x), leaf.remote(x + 10)]))

    refs = [parent.remote(0), parent.remote(100)]
    assert rt.get(refs, timeout=180) == [12, 212]


def test_nested_actor_from_worker(runtime):
    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    @rt.remote(execution="process")
    def drive():
        c = Counter.remote()
        rt.get(c.add.remote(5))
        return rt.get(c.add.remote(7))

    assert rt.get(drive.remote(), timeout=120) == 12


def test_nested_wait_from_worker(runtime):
    @rt.remote(execution="process")
    def slow(x):
        time.sleep(0.2)
        return x

    @rt.remote(execution="process")
    def parent():
        refs = [slow.remote(i) for i in range(4)]
        ready, not_ready = rt.wait(refs, num_returns=2, timeout=60)
        return len(ready), len(not_ready)

    r, nr = rt.get(parent.remote(), timeout=120)
    assert r == 2 and nr == 2


def test_nested_error_propagates(runtime):
    from ray_tpu.exceptions import RayTaskError

    @rt.remote(execution="process")
    def boom():
        raise ValueError("inner")

    @rt.remote(execution="process")
    def parent():
        try:
            rt.get(boom.remote())
        except RayTaskError:
            return "caught"
        return "missed"

    assert rt.get(parent.remote(), timeout=120) == "caught"


def test_streaming_from_worker_rejected(runtime):
    @rt.remote(execution="process")
    def parent():
        @rt.remote(num_returns="streaming")
        def gen():
            yield 1

        try:
            gen.remote()
        except NotImplementedError as exc:
            return str(exc)
        return "no error"

    msg = rt.get(parent.remote(), timeout=120)
    assert "streaming" in msg
