"""Prefix-aware KV reuse tests.

Four contracts:
- PrefixCache unit: hash-chain keys are process-stable and unambiguous,
  match/insert/evict round-trip pages, eviction is LRU over unreferenced
  leaves with a deterministic (last_used, seq) order, max_blocks is honored
  without ever evicting the chain being inserted
- allocator refcounts: share() pins pages, free() releases one reference,
  pages return to the pool only at zero, and misuse (double free, sharing a
  free page or the garbage page) raises instead of corrupting the pool
- engine identity: warm runs (full hit + COW, partial hit, chunked prefill
  resuming mid-prompt, divergent suffixes off a shared prefix) are
  token-identical to the dense reference under greedy decoding
- leak + determinism: every release path under ACTIVE sharing returns the
  request's references (pool == cache after quiesce, flush drains both),
  loop crash invalidates the whole cache, and the same workload on a
  bounded cache evicts the same pages in the same order
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import TransformerConfig, init_params
from ray_tpu.serve.kv_blocks import BlockAllocator
from ray_tpu.serve.llm import LLMEngine
from ray_tpu.serve.prefix_cache import PrefixCache, chain_key, _ROOT

CFG = TransformerConfig(
    vocab_size=89, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
    attention="dense", dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(11))


def _paged(params, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(CFG, params, cache_kind="paged", **kw)


def _dense(params, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(CFG, params, cache_kind="dense", **kw)


def _wait(pred, timeout=60):
    deadline = time.time() + timeout
    while not pred() and time.time() < deadline:
        time.sleep(0.005)
    assert pred()


def _assert_no_leak(eng):
    st = eng.stats()
    assert st["kv_blocks_in_use"] == st["prefix_cache_blocks"]
    eng.flush_prefix_cache()
    st = eng.stats()
    assert st["kv_blocks_in_use"] == 0 and st["prefix_cache_blocks"] == 0


# --------------------------------------------------------------------------
# chain keys
# --------------------------------------------------------------------------
def test_chain_key_stable_and_unambiguous():
    # fixed-width encoding: [1, 23] and [12, 3] must not collide
    assert chain_key(_ROOT, [1, 23]) != chain_key(_ROOT, [12, 3])
    # same inputs, same digest (no per-process salt)
    assert chain_key(_ROOT, [7, 8, 9]) == chain_key(_ROOT, [7, 8, 9])
    # chained: depends on the parent
    k1 = chain_key(_ROOT, [1, 2])
    assert chain_key(k1, [3, 4]) != chain_key(_ROOT, [3, 4])
    # negative token ids encode without error
    assert chain_key(_ROOT, [-1]) != chain_key(_ROOT, [1])


# --------------------------------------------------------------------------
# PrefixCache unit
# --------------------------------------------------------------------------
def test_match_insert_roundtrip():
    pc = PrefixCache(block_size=4)
    toks = [1, 2, 3, 4, 5, 6, 7, 8, 9]  # 2 full blocks + 1 partial token
    adopted, evicted = pc.insert(toks, [10, 11], lambda p: True)
    assert adopted == {10, 11} and evicted == []
    assert len(pc) == 2
    pages, n = pc.match(toks)
    assert pages == [10, 11] and n == 8
    # longer prompt with the same prefix matches the shared chain
    pages, n = pc.match(toks[:8] + [40, 41, 42, 43])
    assert pages == [10, 11] and n == 8
    # diverging second block matches only the first
    pages, n = pc.match([1, 2, 3, 4, 9, 9, 9, 9])
    assert pages == [10] and n == 4
    # no full block -> no match
    assert pc.match([1, 2, 3]) == ([], 0)


def test_insert_adopts_only_new_blocks():
    pc = PrefixCache(block_size=2)
    a1, _ = pc.insert([1, 2, 3, 4], [5, 6], lambda p: True)
    assert a1 == {5, 6}
    # re-inserting the same chain with different pages adopts nothing:
    # the caller keeps (and frees) its duplicates
    a2, _ = pc.insert([1, 2, 3, 4, 9, 9], [7, 8, 9], lambda p: True)
    assert a2 == {9}
    pages, n = pc.match([1, 2, 3, 4])
    assert pages == [5, 6] and n == 4


def test_evict_is_lru_over_unreferenced_leaves():
    pc = PrefixCache(block_size=1)
    pc.insert([1], [11], lambda p: True)
    pc.insert([2], [12], lambda p: True)
    pc.insert([3], [13], lambda p: True)
    pc.match([1])  # chain [1] is now the most recently used
    # LRU order: [2] then [3] then [1]
    assert pc.evict(2, lambda p: True) == [12, 13]
    assert pc.evict(5, lambda p: True) == [11]
    assert len(pc) == 0 and pc.evictions == 3


def test_evict_skips_shared_pages_and_interior_nodes():
    pc = PrefixCache(block_size=1)
    pc.insert([1, 2], [11, 12], lambda p: True)  # chain: 11 -> 12
    # interior node 11 is not a leaf; leaf 12 is "shared" (not evictable)
    assert pc.evict(2, lambda p: p != 12) == []
    assert len(pc) == 2
    # once the leaf is droppable, the sweep cascades up the cold chain
    assert pc.evict(2, lambda p: True) == [12, 11]


def test_insert_at_bound_never_evicts_own_chain():
    pc = PrefixCache(block_size=1, max_blocks=2)
    pc.insert([1], [11], lambda p: True)
    # a 3-deep chain at bound 2: the chain being built is protected, so the
    # sweep takes the cold [1] entry, then stops adopting when nothing else
    # is evictable — never stranding a mid-chain node
    adopted, evicted = pc.insert([5, 6, 7], [21, 22, 23], lambda p: True)
    assert evicted == [11]
    assert adopted == {21, 22}  # third block did not fit; chain intact
    pages, n = pc.match([5, 6, 7])
    assert pages == [21, 22] and n == 2


def test_drain_returns_every_page_regardless_of_sharing():
    pc = PrefixCache(block_size=1)
    pc.insert([1, 2, 3], [11, 12, 13], lambda p: True)
    assert sorted(pc.drain()) == [11, 12, 13]
    assert len(pc) == 0
    assert pc.match([1]) == ([], 0)


def test_cache_eviction_deterministic_across_instances():
    """Same workload, two fresh caches: identical surviving keys and
    identical eviction order (acceptance: same workload -> same evicted
    pages)."""
    def run():
        pc = PrefixCache(block_size=2, max_blocks=3)
        order = []
        for toks in ([1, 2, 3, 4], [5, 6], [7, 8, 9, 10], [1, 2, 11, 12]):
            _, ev = pc.insert(toks, list(range(20, 20 + len(toks) // 2)),
                              lambda p: True)
            order += ev
        pc.match([5, 6])
        order += pc.evict(2, lambda p: True)
        return order, sorted(pc.keys())

    assert run() == run()


# --------------------------------------------------------------------------
# allocator refcounts
# --------------------------------------------------------------------------
def test_allocator_share_and_refcounts():
    a = BlockAllocator(6)
    got = a.alloc(2)
    assert all(a.refcount(b) == 1 for b in got) and a.shared_blocks == 0
    a.share(got)
    assert all(a.refcount(b) == 2 for b in got) and a.shared_blocks == 2
    a.free(got)  # one reference down: pages still held
    assert a.used_blocks == 2 and all(a.refcount(b) == 1 for b in got)
    assert a.shared_blocks == 0
    a.free(got)  # last reference: pages return to the pool
    assert a.used_blocks == 0 and a.free_blocks == 5
    assert a.refcount(got[0]) == 0


def test_allocator_share_misuse_raises_and_is_atomic():
    a = BlockAllocator(6)
    got = a.alloc(2)
    with pytest.raises(ValueError):
        a.share([0])  # the garbage page is never shared
    with pytest.raises(ValueError):
        a.share([got[0], 99])  # 99 is not held
    # atomic: the failed share must not have bumped got[0]
    assert a.refcount(got[0]) == 1
    a.free(got)
    with pytest.raises(ValueError):
        a.share(got)  # sharing a freed page
    with pytest.raises(ValueError):
        a.free(got)  # double free
    assert a.free_blocks == 5


# --------------------------------------------------------------------------
# engine: warm-path token identity
# --------------------------------------------------------------------------
def test_full_hit_cow_token_identical_to_dense(params):
    eng = _paged(params, kv_block_size=8)
    ref = _dense(params)
    try:
        p = list(range(1, 25))  # 24 tokens = 3 full blocks
        want6 = ref.generate(p, max_tokens=6)
        want10 = ref.generate(p, max_tokens=10)
        assert eng.generate(p, max_tokens=6) == want6  # cold
        # warm, different generation length: full hit + COW on the tail block
        assert eng.generate(p, max_tokens=10) == want10
        st = eng.stats()
        assert st["prefix_cache_hits"] >= 1 and st["cow_copies"] >= 1
        assert st["prefix_tokens_reused"] >= 23
        _assert_no_leak(eng)
    finally:
        eng.shutdown()
        ref.shutdown()


def test_divergent_suffixes_share_prefix_blocks(params):
    eng = _paged(params, kv_block_size=8)
    ref = _dense(params)
    try:
        base = list(range(30, 46))  # 16 tokens = 2 full blocks
        p1, p2 = base + [5, 6, 7], base + [8, 9]
        assert eng.generate(p1, max_tokens=5) == ref.generate(p1, max_tokens=5)
        assert eng.generate(p2, max_tokens=5) == ref.generate(p2, max_tokens=5)
        st = eng.stats()
        # p2 reused base's two blocks without COW (its suffix diverges)
        assert st["prefix_cache_hits"] + st["prefix_cache_partial"] >= 1
        assert st["prefix_tokens_reused"] >= 16
        _assert_no_leak(eng)
    finally:
        eng.shutdown()
        ref.shutdown()


@pytest.mark.parametrize("chunk", [7, 8, 16])
def test_chunked_prefill_resumes_at_first_uncached_token(params, chunk):
    """Chunked prefill x cache hit: the warm run starts prefill mid-prompt
    (at the first uncached token) and still produces the dense tokens."""
    eng = _paged(params, kv_block_size=8, prefill_chunk_tokens=chunk)
    ref = _dense(params)
    try:
        p = list(range(1, 31))  # 30 tokens
        want = ref.generate(p, max_tokens=5)
        assert eng.generate(p, max_tokens=5) == want
        chunks_cold = eng.stats()["prefill_chunks"]
        assert eng.generate(p, max_tokens=5) == want
        st = eng.stats()
        # warm prefill only covered the uncached tail: fewer chunks than cold
        assert st["prefill_chunks"] - chunks_cold < chunks_cold
        assert st["prefix_cache_hits"] >= 1
        # an EXTENDED prompt diverges inside the cached completion's block:
        # a PARTIAL hit that resumes after the shared full blocks
        p2 = p + [60, 61, 62]
        assert eng.generate(p2, max_tokens=5) == ref.generate(p2, max_tokens=5)
        assert eng.stats()["prefix_cache_partial"] >= 1
        _assert_no_leak(eng)
    finally:
        eng.shutdown()
        ref.shutdown()


def test_shared_pages_visible_while_request_live(params):
    """While a warm request decodes, the matched pages carry two references
    (cache + block table) and show up in kv_blocks_shared; disconnect-evict
    mid-decode drops only the request's reference."""
    eng = _paged(params, kv_block_size=8)
    try:
        p = list(range(1, 18))  # 2 full blocks
        eng.generate(p, max_tokens=3)  # populate the cache
        cached = eng.stats()["prefix_cache_blocks"]
        assert cached >= 2
        stream = eng.submit_stream(p, max_tokens=40)
        next(stream)
        assert eng.stats()["kv_blocks_shared"] >= 2
        stream.close()  # evict mid-decode while sharing is active
        _wait(lambda: eng.stats()["active_slots"] == 0)
        _wait(lambda: eng.stats()["kv_blocks_in_use"]
              == eng.stats()["prefix_cache_blocks"])
        assert eng.stats()["kv_blocks_shared"] == 0
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


# --------------------------------------------------------------------------
# engine: release paths under active sharing
# --------------------------------------------------------------------------
def test_blocks_released_on_deadline_shed_with_warm_cache(params):
    eng = _paged(params, max_batch_size=1, kv_block_size=8)
    try:
        p = list(range(1, 18))
        eng.generate(p, max_tokens=3)  # warm
        blocker = eng.submit(p, max_tokens=40)  # warm admit, shares pages
        doomed = eng.submit(p, max_tokens=2, deadline_ts=time.time() + 0.05)
        from ray_tpu.exceptions import DeadlineExceededError

        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=120)
        blocker.result(timeout=120)
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_blocks_released_on_disconnect_mid_prefill_under_sharing(params):
    eng = _paged(params, kv_block_size=8, prefill_chunk_tokens=8)
    try:
        p = list(range(1, 25))
        eng.generate(p, max_tokens=3)  # warm: 3+ blocks cached
        entered = threading.Event()
        real = eng._prefill_chunk

        def slow(*a, **k):
            entered.set()
            time.sleep(0.1)
            return real(*a, **k)

        eng._prefill_chunk = slow
        # partial hit + a 12-token uncached suffix -> at least 2 chunks
        stream = eng.submit_stream(p + list(range(50, 62)), max_tokens=20)
        assert entered.wait(timeout=60)
        stream.close()  # abandon while its prefill is still running
        _wait(lambda: eng.stats()["active_slots"] == 0
              and eng.stats()["prefilling"] == 0
              and eng.stats()["queued"] == 0)
        _wait(lambda: eng.stats()["kv_blocks_in_use"]
              == eng.stats()["prefix_cache_blocks"])
        eng._prefill_chunk = real
        # the pool still serves warm traffic afterwards
        assert len(eng.generate(p, max_tokens=3)) == 3
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_loop_crash_invalidates_whole_cache(params):
    """After _fail_inflight resets the device pool, every cached page's
    contents are gone — the index must drain with them, and the next warm
    prompt is a MISS that still decodes correctly."""
    eng = _paged(params, kv_block_size=8)
    try:
        p = list(range(1, 18))
        want = eng.generate(p, max_tokens=4)
        assert eng.stats()["prefix_cache_blocks"] > 0
        real = eng._decode_k_paged
        eng._decode_k_paged = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("injected decode fault")
        )
        with pytest.raises(RuntimeError):
            eng.submit(p, max_tokens=8).result(timeout=120)
        _wait(lambda: eng.stats()["kv_blocks_in_use"] == 0)
        assert eng.stats()["prefix_cache_blocks"] == 0  # drained, not leaked
        eng._decode_k_paged = real
        misses = eng.stats()["prefix_cache_misses"]
        assert eng.generate(p, max_tokens=4) == want  # recomputed, identical
        assert eng.stats()["prefix_cache_misses"] == misses + 1
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_shutdown_with_populated_cache(params):
    eng = _paged(params, kv_block_size=8)
    eng.generate(list(range(1, 18)), max_tokens=3)
    assert eng.stats()["prefix_cache_blocks"] > 0
    eng.shutdown()  # must not raise; gauges zeroed with pages still cached


# --------------------------------------------------------------------------
# engine: pool pressure + determinism
# --------------------------------------------------------------------------
def test_pool_short_admission_evicts_cache_before_holding(params):
    eng = _paged(params, max_batch_size=1, kv_num_blocks=5)  # 4 usable
    try:
        assert len(eng.generate([1] * 40, max_tokens=20)) == 20
        assert eng.stats()["prefix_cache_blocks"] == 3  # 59 tokens, bs=16
        # a different prompt needs all 4 pages: admission LRU-sweeps the
        # cache instead of holding (no other request will ever free pages)
        assert len(eng.generate([2] * 40, max_tokens=20)) == 20
        assert eng.stats()["prefix_evictions"] >= 3
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_never_fitting_prompt_rejected_with_cache_populated(params):
    eng = _paged(params, kv_num_blocks=4)  # 3 usable blocks
    try:
        eng.generate(list(range(1, 18)), max_tokens=2)  # caches 1 block
        with pytest.raises(ValueError, match="never be admitted"):
            eng.submit([1] * 40, max_tokens=20)  # needs 4 > 3 total
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_pool_exhaustion_shed_is_typed_with_retry_hint():
    from ray_tpu.exceptions import OverloadedError

    a = BlockAllocator(4)
    held = a.alloc(2)
    a.share(held)  # sharing must not change the exhaustion contract
    with pytest.raises(OverloadedError) as exc:
        a.alloc(2)
    assert exc.value.layer == "engine" and exc.value.reason == "kv_blocks"
    assert exc.value.retry_after_s > 0
    a.free(held)
    a.free(held)
    assert a.free_blocks == 3


def test_engine_eviction_deterministic_across_runs(params):
    """Same workload on a bounded cache, twice: identical surviving chain
    keys, identical eviction and hit counters."""
    prompts = [list(range(1, 18)), list(range(40, 57)),
               list(range(1, 22)), list(range(60, 77))]

    def run():
        eng = _paged(params, kv_block_size=8, prefix_cache_max_blocks=4)
        try:
            for p in prompts:
                eng.generate(p, max_tokens=3)
            st = eng.stats()
            return (sorted(eng._prefix.keys()), st["prefix_evictions"],
                    st["prefix_cache_hits"], st["prefix_cache_partial"],
                    st["prefix_cache_misses"])
        finally:
            eng.shutdown()

    assert run() == run()


def test_prefix_metric_families_registered(params):
    from ray_tpu.observability import metric_defs
    from ray_tpu.runtime import admission

    names = {m.name for m in metric_defs.ALL_METRICS}
    for family in (
        "llm_prefix_cache_hits_total",
        "llm_prefix_cache_blocks",
        "llm_kv_blocks_shared",
        "llm_prefix_evictions_total",
    ):
        assert family in names
    eng = _paged(params, kv_block_size=8)
    try:
        p = list(range(1, 18))
        eng.generate(p, max_tokens=3)
        eng.generate(p, max_tokens=3)
        snap = [s for s in admission.sources_snapshot()
                if s.get("layer") == "engine"][-1]
        assert snap["prefix_cache_enabled"] is True
        assert snap["prefix_cache_blocks"] >= 2
        assert 0.0 < snap["prefix_hit_rate"] <= 1.0
        assert snap["prefix_tokens_reused"] >= 16
    finally:
        eng.shutdown()
