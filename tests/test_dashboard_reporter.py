"""Dashboard per-node reporter: utilization time series + remote log viewer
(round-3 VERDICT item 7).

Each agent piggybacks CPU/mem/TPU samples on its resource reports; the head
ring-buffers per-node series and per-node worker-log tails and serves both
over REST (and graphs them in the UI).  Reference parity:
``dashboard/agent.py:28`` + ``dashboard/modules/reporter/`` + the log
module.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu.dashboard.reporter import MetricsHistory, NodeLogStore, SystemSampler

from test_multihost import _spawn_agent, _wait_for_nodes


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


# ---------------------------------------------------------------- unit
def test_system_sampler_reports_cpu_and_memory():
    s = SystemSampler()
    s.sample()          # first call primes the /proc/stat delta
    time.sleep(0.15)
    out = s.sample()
    assert 0.0 <= out["cpu_percent"] <= 100.0
    assert out["mem_total"] > 0 and 0 < out["mem_used"] <= out["mem_total"]
    assert "ts" in out


def test_metrics_history_ring_and_throttle():
    h = MetricsHistory(maxlen=5, min_interval_s=0.0)
    for i in range(9):
        h.add("node1", {"ts": time.time(), "cpu_percent": float(i)})
    series = h.series("node1", minutes=5)
    assert len(series) == 5 and series[-1]["cpu_percent"] == 8.0

    throttled = MetricsHistory(min_interval_s=60.0)
    throttled.add("n", {"ts": time.time(), "cpu_percent": 1.0})
    throttled.add("n", {"ts": time.time(), "cpu_percent": 2.0})  # inside window
    assert len(throttled.series("n", minutes=5)) == 1


def test_node_log_store_tail():
    s = NodeLogStore(maxlen=10)
    s.append("n", [f"line{i}" for i in range(25)])
    assert s.tail("n", 3) == ["line22", "line23", "line24"]
    assert s.tail("unknown") == []


# ------------------------------------------------------- integration
@pytest.fixture
def dash_multihost():
    rt.init(num_cpus=2, include_dashboard=True)
    cluster = rt.get_cluster()
    address = cluster.start_head_service()
    proc = _spawn_agent(address)
    try:
        _wait_for_nodes(cluster, 2)
        yield cluster, proc
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        rt.shutdown()


def test_both_nodes_report_series_and_remote_logs_visible(dash_multihost):
    """The acceptance bar: a two-process cluster surfaces BOTH nodes' live
    utilization series and the remote node's worker logs through the
    dashboard REST API the UI graphs."""
    cluster, proc = dash_multihost
    url = cluster.dashboard.url

    # generate remote worker logs
    @rt.remote(resources={"remote": 1}, execution="process")
    def chatty(i):
        print(f"reporter-test-line-{i}")
        return i

    assert rt.get([chatty.remote(i) for i in range(3)], timeout=60) == [0, 1, 2]

    # both nodes produce utilization samples (head sampler ~2s period;
    # agent piggybacks on resource reports)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        hist = _get(url + "/api/metrics_history?minutes=5")["nodes"]
        live = [
            n for n, pts in hist.items()
            if pts and pts[-1].get("cpu_percent") is not None
        ]
        if len(live) >= 2:
            break
        time.sleep(0.5)
    assert len(live) >= 2, f"expected 2 nodes with samples, got {hist.keys()}"

    # per-node series route (prefix form)
    some_node = live[0]
    series = _get(url + f"/api/nodes/{some_node[:12]}/metrics?minutes=5")["series"]
    assert series and series[-1]["mem_total"] > 0

    # the remote node's worker logs are viewable per node
    remote_hex = next(
        nid.hex() for nid, n in cluster.nodes.items() if nid != cluster.head_node.node_id
    )
    deadline = time.monotonic() + 30
    lines = []
    while time.monotonic() < deadline:
        lines = _get(url + f"/api/nodes/{remote_hex}/logs?lines=50")["lines"]
        if any("reporter-test-line-" in ln for ln in lines):
            break
        time.sleep(0.5)
    assert any("reporter-test-line-" in ln for ln in lines), lines

    # the UI page embeds the utilization + log panels
    with urllib.request.urlopen(url + "/", timeout=10) as r:
        html = r.read().decode()
    assert "Node utilization" in html and "Node logs" in html


def test_drilldowns_and_transfer_counters(dash_multihost):
    """Round-4 VERDICT item 6 acceptance: a two-process cluster surfaces
    per-task timing, per-actor state + its call history, and LIVE data-plane
    byte counters through the dashboard REST API."""
    import numpy as np

    cluster, proc = dash_multihost
    url = cluster.dashboard.url

    @rt.remote(resources={"remote": 1}, execution="thread")
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self, arr):
            self.n += 1
            return self.n

    c = Counter.remote()
    # 1 MB by-REFERENCE arg: the agent resolves the dependency with a real
    # data-plane pull (inline args ride the control spec and wouldn't count)
    big_ref = rt.put(np.zeros(1 << 20, dtype=np.uint8))
    for _ in range(3):
        rt.get(c.bump.remote(big_ref), timeout=60)

    # per-actor drill-down: state + its method-call task events
    actors = _get(url + "/api/actors")["actors"]
    aid = next(a["actor_id"] for a in actors if a["class_name"] == "Counter")
    detail = _get(url + f"/api/actors/{aid[:16]}")
    assert detail["state"] == "ALIVE", detail
    assert detail["class_name"] == "Counter"
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        detail = _get(url + f"/api/actors/{aid[:16]}")
        if len(detail.get("events", [])) >= 3:
            break
        time.sleep(0.5)
    assert len(detail["events"]) >= 3, detail.get("events")
    assert all(e["state"] == "FINISHED" for e in detail["events"][-3:])

    # per-task drill-down: duration + event history for one of those calls
    tid = detail["events"][-1]["task_id"]
    task = _get(url + f"/api/tasks/{tid[:16]}")
    assert task["task_id"] == tid and task["state"] == "FINISHED"
    assert task.get("duration_s") is not None or task.get("total_s") is not None
    assert task["events"], task

    # live transfer counters: the agent moved >=3 MB of args; its piggyback
    # snapshot must show nonzero data-plane bytes within a report cycle
    deadline = time.monotonic() + 30
    seen = {}
    while time.monotonic() < deadline:
        seen = _get(url + "/api/transfers")["nodes"]
        moved = sum(
            s.get(side, {}).get(counter, 0)
            for s in seen.values()
            for side in ("data_server", "data_client")
            for counter in ("bytes_received", "bytes_sent")
        )
        if len(seen) >= 1 and moved > 0:
            break
        time.sleep(0.5)
    assert seen and moved > 0, seen

    # the UI page embeds the transfers panel + drill-down plumbing
    with urllib.request.urlopen(url + "/", timeout=10) as r:
        html = r.read().decode()
    assert "Data-plane transfers" in html and "showDetail" in html


def test_data_panel_lists_recent_executions(dash_multihost):
    """Dataset executions show up in the dashboard's Data panel with
    per-op rows/bytes/timings (reference: the Data dashboard module)."""
    from ray_tpu import data

    cluster, proc = dash_multihost
    url = cluster.dashboard.url

    ds = data.range(100, parallelism=2).map_batches(lambda b: {"x": b["id"] + 1})
    ds.materialize()

    execs = _get(url + "/api/data/datasets")["executions"]
    assert execs, "no executions recorded"
    last = execs[-1]
    assert last["wall_s"] >= 0 and last["ops"], last
    total_rows = max(op["rows_out"] for op in last["ops"])
    assert total_rows == 100, last
    with urllib.request.urlopen(url + "/", timeout=10) as r:
        assert "Dataset executions" in r.read().decode()


def test_memory_and_placement_group_panels(dash_multihost):
    """`ray memory` role in the browser: /api/memory aggregates per-node
    object totals by tier, names the largest objects, and reports shm-arena
    occupancy for nodes that have one; placement groups list alongside."""
    import numpy as np

    cluster, _proc = dash_multihost
    base = cluster.dashboard.url

    big = rt.put(np.arange(1 << 17, dtype=np.float64))  # 1 MiB, driver store

    @rt.remote(resources={"remote": 1})
    def produce():
        return np.ones(1 << 16, np.float64)  # agent-side object

    remote_ref = produce.remote()
    rt.get(remote_ref)
    pg = rt.util.placement_group([{"remote": 1}], strategy="PACK")
    rt.get(pg.ready(), timeout=30)

    mem = _get(base + "/api/memory")
    total_objects = sum(n["count"] for n in mem["nodes"].values())
    assert total_objects >= 2
    assert any(
        o["size_bytes"] >= (1 << 17) * 8 for o in mem["top_objects"]
    ), mem["top_objects"][:3]
    for n in mem["nodes"].values():
        assert n["bytes"] == sum(t["bytes"] for t in n["tiers"].values())
    # the agent node runs a native shm arena in ITS process; the occupancy
    # piggybacks on resource reports, so give one report cycle to land
    agent_hex = next(
        nid.hex() for nid, n in cluster.nodes.items() if n is not cluster.head_node
    )
    deadline = time.monotonic() + 15
    arena = None
    while time.monotonic() < deadline:
        arena = _get(base + "/api/memory")["arenas"].get(agent_hex)
        if arena is not None:
            break
        time.sleep(0.3)
    assert arena is not None, "agent arena occupancy never reached the head"
    assert arena["capacity"] > 0 and arena["used"] >= 0

    pgs = _get(base + "/api/placement_groups")["placement_groups"]
    assert any(p["strategy"] == "PACK" for p in pgs)

    del big, remote_ref
    rt.util.remove_placement_group(pg)


def test_cluster_rate_panels_and_log_search(dash_multihost):
    """VERDICT r4 #7: cluster-level rate time series (tasks/s, transfer
    B/s) render from the REST API, and cross-node log grep finds worker
    prints on a remote node."""
    cluster, proc = dash_multihost
    url = cluster.dashboard.url

    @rt.remote(resources={"remote": 1}, execution="process")
    def chatty(i):
        print(f"needle-{i}-haystack")
        return i

    assert rt.get([chatty.remote(i) for i in range(8)], timeout=120) == list(range(8))

    # rate series: at least one sampled point with a task rate after work ran
    deadline = time.monotonic() + 30
    pts = []
    while time.monotonic() < deadline:
        pts = _get(url + "/api/metrics/cluster_history?minutes=5")["points"]
        if any(p.get("tasks_per_s", 0) > 0 for p in pts):
            break
        time.sleep(0.5)
    assert any(p.get("tasks_per_s", 0) > 0 for p in pts), pts[-3:]

    # cross-node grep: worker prints from the REMOTE node match a regex
    deadline = time.monotonic() + 30
    matches = []
    while time.monotonic() < deadline:
        matches = _get(url + "/api/logs/search?q=needle-%5Cd%2B-hay")["matches"]
        if len(matches) >= 8:
            break
        time.sleep(0.5)
    assert len(matches) >= 8, matches
    assert all("needle-" in m["line"] for m in matches)
    # node filter narrows to that node only
    node = matches[0]["node"]
    only = _get(url + f"/api/logs/search?q=needle&node={node}")["matches"]
    assert only and all(m["node"] == node for m in only)


def test_timeline_window_and_inline_gantt_source(dash_multihost, tmp_path):
    """The inline Gantt polls /api/timeline?since_s=&limit=: spans carry
    chrome-trace fields, the trailing window drops stale spans, and limit
    caps the event count."""
    cluster, proc = dash_multihost
    url = cluster.dashboard.url

    @rt.remote
    def quick(i):
        return i

    assert rt.get([quick.remote(i) for i in range(6)], timeout=60) == list(range(6))
    # a synthetic span that ended hours ago must fall outside the window
    cluster.control.task_events.add(
        {"task_id": "stale", "name": "stale_task", "ts": time.time() - 7200,
         "start_ts": time.time() - 7201, "state": "FINISHED", "node": "n", "worker": "w"}
    )
    deadline = time.monotonic() + 30
    windowed = []
    while time.monotonic() < deadline:
        windowed = _get(url + "/api/timeline?since_s=120&limit=400")
        if len(windowed) >= 6:
            break
        time.sleep(0.5)
    assert len(windowed) >= 6, windowed
    span = windowed[0]
    assert span["ph"] == "X" and span["dur"] >= 0 and span["pid"].startswith("node:")
    names = {e["name"] for e in windowed}
    assert "stale_task" not in names
    # no window: the stale span IS served (download path unchanged)
    full = _get(url + "/api/timeline")
    assert any(e["name"] == "stale_task" for e in full)
    # limit caps (applied AFTER the window filter: newest-N of the window)
    assert len(_get(url + "/api/timeline?since_s=120&limit=2")) <= 2
    # rt.timeline(file) writes chrome-trace JSON (ray.timeline parity)
    out = tmp_path / "trace.json"
    trace = rt.timeline(str(out))
    assert out.exists() and json.loads(out.read_text()) == trace
    assert any(e.get("ph") == "X" for e in trace)
