"""Head fault tolerance (round-3 VERDICT item 4).

The head is no longer a hard SPOF: agents survive a head outage, reconnect
with backoff, and re-register; a restarted head restores durable control
state (KV, jobs, actor records) from the snapshot and reconciles the
rejoining agents' live actor instances.

Reference parity anchors: GCS restart against Redis
(src/ray/gcs/store_client/redis_store_client.h) and raylet reconnection
(core_worker.proto:443 RayletNotifyGCSRestart).
"""

import pytest
import os
import signal
import socket
import subprocess
import sys
import time

import ray_tpu as rt

from test_multihost import REPO_ROOT, _spawn_agent, _wait_for_nodes

HEAD_RUNNER = """
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import ray_tpu as rt
rt.init(num_cpus=1, _system_config={{"control_snapshot_path": {snap!r}}}, head_port={port})
cluster = rt.get_cluster()
deadline = time.time() + 90
while sum(1 for n in cluster.nodes.values() if not n.dead) < 2:
    if time.time() > deadline:
        raise SystemExit("agent never joined")
    time.sleep(0.1)

@rt.remote(resources={{"remote": 1}}, execution="thread")
class Keeper:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n

k = Keeper.options(name="keeper").remote()
assert rt.get(k.bump.remote(), timeout=60) == 1
cluster.control.kv.put(b"restart_marker", b"written-by-head-a")
cluster.control.save_snapshot({snap!r})
print("READY", flush=True)
time.sleep(600)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_head_restart_from_snapshot_agents_rejoin(tmp_path):
    """Kill -9 the head; a new head on the same address restores the
    snapshot; the agent rejoins (instead of exiting); a resubmitted task
    completes; a named actor's IN-PROCESS state survives the outage."""
    port = _free_port()
    snap = str(tmp_path / "control.snap")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")

    head_a = subprocess.Popen(
        [sys.executable, "-c", HEAD_RUNNER.format(repo=REPO_ROOT, snap=snap, port=port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    agent = None
    try:
        # the agent's INITIAL join has no retry (by design — rejoin backoff
        # only covers established sessions): wait for the head to listen
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), timeout=1).close()
                break
            except OSError:
                assert head_a.poll() is None, "head A died before listening"
                time.sleep(0.2)
        agent = _spawn_agent(f"127.0.0.1:{port}")
        line = ""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = head_a.stdout.readline()
            if "READY" in line or head_a.poll() is not None:
                break
        assert "READY" in line, f"head A never became ready (exit={head_a.poll()})"

        # ---- the outage: kill -9 the whole head process ----
        head_a.send_signal(signal.SIGKILL)
        head_a.wait(timeout=10)

        # ---- head B: same address, restored from the snapshot ----
        rt.init(
            num_cpus=1,
            _system_config={"control_snapshot_path": snap},
            head_port=port,
        )
        cluster = rt.get_cluster()
        # durable KV survived the restart
        assert cluster.control.kv.get(b"restart_marker") == b"written-by-head-a"

        # the agent reconnects (with backoff) instead of exiting
        _wait_for_nodes(cluster, 2, timeout=90)
        assert agent.poll() is None, "agent process exited instead of rejoining"

        # a driver-resubmitted task completes on the rejoined agent
        @rt.remote(resources={"remote": 1})
        def f(x):
            return os.getpid(), x * 2

        pid, val = rt.get(f.remote(21), timeout=60)
        assert val == 42 and pid != os.getpid()

        # the named actor's record was restored AND its live instance was
        # reconciled at rejoin: in-process state (n == 1) survived the
        # head's death
        k = rt.get_actor("keeper")
        deadline = time.monotonic() + 60
        while True:
            try:
                assert rt.get(k.bump.remote(), timeout=30) == 2
                break
            except AssertionError:
                raise
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
    finally:
        if head_a.poll() is None:
            head_a.kill()
            head_a.wait(timeout=10)
        if agent is not None and agent.poll() is None:
            agent.kill()
            agent.wait(timeout=10)
        if rt.is_initialized():
            rt.shutdown()


def test_agent_rejoins_same_head_after_transient_disconnect():
    """A dropped control connection (not a dead head) heals: the agent
    reconnects to the SAME head and re-registers; tasks flow again."""
    rt.init(num_cpus=2)
    cluster = rt.get_cluster()
    address = cluster.start_head_service()
    proc = _spawn_agent(address)
    try:
        _wait_for_nodes(cluster, 2)

        @rt.remote(resources={"remote": 1})
        def f():
            return "on-agent"

        assert rt.get(f.remote(), timeout=60) == "on-agent"

        # sever the control connection from the head side
        for conn in cluster.head_service.server.connections():
            conn.close()

        # the agent must rejoin as a live node (same process, same node id)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            live = [n for n in cluster.nodes.values() if not n.dead]
            if len(live) >= 2:
                break
            time.sleep(0.1)
        live = [n for n in cluster.nodes.values() if not n.dead]
        assert len(live) >= 2, "agent never rejoined after the disconnect"
        assert proc.poll() is None, "agent process exited on transient disconnect"

        assert rt.get(f.remote(), timeout=60) == "on-agent"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        rt.shutdown()


HEAD_RUNNER_LOAD = """
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import ray_tpu as rt
from ray_tpu.util import collective
rt.init(num_cpus=1, _system_config={{"control_snapshot_path": {snap!r}}}, head_port={port})
cluster = rt.get_cluster()
deadline = time.time() + 90
while sum(1 for n in cluster.nodes.values() if not n.dead) < 3:
    if time.time() > deadline:
        raise SystemExit("agents never joined")
    time.sleep(0.1)

@rt.remote(execution="thread")
class Member:
    def __init__(self):
        self.rounds = 0

    def reduce(self, x, rank):
        out = collective.allreduce(np.array([x], np.float32), group_name="restartg", rank=rank)
        self.rounds += 1
        return float(np.asarray(out)[0])

m0 = Member.options(name="m0", resources={{"a": 1}}).remote()
m1 = Member.options(name="m1", resources={{"b": 1}}).remote()
collective.create_collective_group([m0, m1], 2, [0, 1], group_name="restartg")
a = m0.reduce.remote(1.0, 0)
b = m1.reduce.remote(2.0, 1)
assert rt.get(a, timeout=60) == 3.0 and rt.get(b, timeout=60) == 3.0

@rt.remote
def slow(i):
    time.sleep(0.5)
    return i

# the 50-task stream, half per agent, all in flight when the head dies
refs = [slow.options(resources={{"a" if i % 2 else "b": 0.01}}).remote(i) for i in range(50)]
cluster.control.save_snapshot({snap!r})
print("READY", flush=True)
time.sleep(600)
"""


@pytest.mark.full
def test_head_restart_under_load_5x():
    """Round-4 VERDICT item 7: kill -9 the head while 2 agents run a
    50-task in-flight stream and hold an open collective group; the
    restarted head must (a) get both agents back, (b) run a fresh 50-task
    stream to completion (no wedged state from the orphaned in-flight
    work — their owner died with head A, so the agents must DRAIN them,
    not resubmit work nobody owns), (c) re-rendezvous the surviving named
    actors' collective group under a bumped epoch.  Looped 5x: a restart
    path that works 4 times out of 5 is a restart path that doesn't work."""
    for attempt in range(5):
        _run_restart_under_load(attempt)


def _run_restart_under_load(attempt):
    import numpy as np

    from ray_tpu.util import collective

    port = _free_port()
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        snap = os.path.join(tmp, "control.snap")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")

        head_a = subprocess.Popen(
            [sys.executable, "-c", HEAD_RUNNER_LOAD.format(repo=REPO_ROOT, snap=snap, port=port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        agents = []
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    socket.create_connection(("127.0.0.1", port), timeout=1).close()
                    break
                except OSError:
                    assert head_a.poll() is None, "head A died before listening"
                    time.sleep(0.2)
            agents.append(_spawn_agent(f"127.0.0.1:{port}", extra_resources='{"a": 4}'))
            agents.append(_spawn_agent(f"127.0.0.1:{port}", extra_resources='{"b": 4}'))
            line = ""
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                line = head_a.stdout.readline()
                if "READY" in line or head_a.poll() is not None:
                    break
            assert "READY" in line, f"head A never ready (attempt {attempt}, exit={head_a.poll()})"

            # the stream is in flight NOW (50 x 0.5s over 2 agents): kill
            time.sleep(1.0)
            head_a.send_signal(signal.SIGKILL)
            head_a.wait(timeout=10)

            rt.init(
                num_cpus=1,
                _system_config={"control_snapshot_path": snap},
                head_port=port,
            )
            cluster = rt.get_cluster()
            _wait_for_nodes(cluster, 3, timeout=90)
            for agent in agents:
                assert agent.poll() is None, "an agent exited instead of rejoining"

            # (b) a fresh 50-task stream completes on the rejoined agents
            @rt.remote
            def quick(i):
                return i * 2

            refs = [
                quick.options(resources={"a" if i % 2 else "b": 0.01}).remote(i)
                for i in range(50)
            ]
            assert rt.get(refs, timeout=120) == [i * 2 for i in range(50)]

            # (c) the named actors survived (live instances reconciled) and
            # the group re-rendezvouses under a NEW epoch
            m0, m1 = rt.get_actor("m0"), rt.get_actor("m1")
            collective.create_collective_group([m0, m1], 2, [0, 1], group_name="restartg")
            a = m0.reduce.remote(10.0, 0)
            b = m1.reduce.remote(20.0, 1)
            assert rt.get(a, timeout=90) == 30.0, f"attempt {attempt}"
            assert rt.get(b, timeout=90) == 30.0

            # orphaned in-flight tasks drained: agent resources free again
            # (each named Member actor permanently holds 1 of its resource,
            # so fully-drained means 3 of 4 available per agent)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                avail = rt.available_resources()
                if avail.get("a", 0) >= 2.9 and avail.get("b", 0) >= 2.9:
                    break
                time.sleep(0.5)
            avail = rt.available_resources()
            assert avail.get("a", 0) >= 2.9 and avail.get("b", 0) >= 2.9, avail
        finally:
            if head_a.poll() is None:
                head_a.kill()
                head_a.wait(timeout=10)
            for agent in agents:
                if agent.poll() is None:
                    agent.kill()
                    agent.wait(timeout=10)
            if rt.is_initialized():
                rt.shutdown()
