"""Spanning-tree object broadcast (ISSUE 4).

Three coupled layers:

  * ``data_plane.py`` grows a ``relay`` op — a chunk-pipelined tree edge
    (recv chunk -> local write + forward) so one object reaches N
    destinations with the SOURCE sending only ``fanout`` copies
    (Cornet/Orchestra-style cooperative broadcast),
  * the head-side ``PullManager`` coalesces concurrent pulls of one object
    to different destinations into a bounded-fanout **broadcast plan** —
    parked children hold no budget and are promoted when their tree
    parent's copy commits; a dead relay re-parents its subtree onto
    surviving replicas via the purge-then-retry path,
  * the ``ObjectDirectory`` grows replica-aware ``pick_location`` so new
    and late-joining pulls spread across copies instead of hammering the
    first location.

Root-egress bounds are asserted with BYTE accounting (socket bytes for the
relay op, per-store read counts for the in-process plan), never timing.
"""

import threading
import time

import numpy as np
import pytest

from ray_tpu.core.ids import NodeID, ObjectID
from ray_tpu.core.object_store import ObjectStore
from ray_tpu.observability import metric_defs
from ray_tpu.runtime import data_plane
from ray_tpu.runtime.cluster import ObjectDirectory
from ray_tpu.runtime.pull_manager import PullManager


def _wait_for(predicate, timeout=20.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ==========================================================================
# unit: relay tree construction
# ==========================================================================
def test_build_relay_tree_fanout_bounded():
    def check(tree, fanout):
        seen = []
        def walk(node):
            seen.append(node["addr"])
            assert len(node["children"]) <= fanout
            for child in node["children"]:
                walk(child)
        for sub in tree:
            walk(sub)
        return seen

    for n in range(1, 10):
        for fanout in (1, 2, 3):
            addrs = [f"a{i}" for i in range(n)]
            tree = data_plane.build_relay_tree(addrs, fanout)
            assert len(tree) <= fanout                   # source egress bound
            seen = check(tree, fanout)
            assert sorted(seen) == sorted(addrs)         # every dest exactly once

    # fanout 1 is a chain: depth == N
    tree = data_plane.build_relay_tree(["a", "b", "c"], 1)
    assert tree[0]["addr"] == "a"
    assert tree[0]["children"][0]["addr"] == "b"
    assert tree[0]["children"][0]["children"][0]["addr"] == "c"


# ==========================================================================
# unit: the data-plane relay op over real sockets
# ==========================================================================
@pytest.fixture
def dest_farm():
    """N (store, server) pairs + a client; closed afterwards."""
    created = []

    def make(n, chunk_bytes=1 << 20):
        stores = [ObjectStore(shm_store=None) for _ in range(n)]
        servers = [data_plane.store_server(s, chunk_bytes=chunk_bytes) for s in stores]
        client = data_plane.DataClient(chunk_bytes=chunk_bytes)
        created.append((servers, client))
        return stores, servers, client

    yield make
    for servers, client in created:
        client.close()
        for server in servers:
            server.close()


def test_relay_root_socket_egress_bounded_64mb(dest_farm):
    """THE acceptance bar: one 64 MiB object to N >= 4 destinations moves
    <= fanout x object bytes out of the root — socket-byte accounting on
    the root's DataClient, not timing.  (Repeated unicast would be N x.)"""
    n_dest, fanout = 4, 2
    stores, servers, client = dest_farm(n_dest, chunk_bytes=8 << 20)
    size = 64 << 20
    value = np.full(size, 7, np.uint8)
    oid = ObjectID.from_random()
    tree = data_plane.build_relay_tree([s.address for s in servers], fanout)
    failed = client.relay(oid.binary(), value, tree)
    assert failed == []
    for store in stores:
        assert store.contains(oid)
    got = stores[-1].get(oid, timeout=5)
    assert got.nbytes == size and got[0] == 7 and got[-1] == 7
    # root egress: fanout copies plus per-frame header slack — NOT n_dest
    assert client.stats.bytes_sent <= fanout * size + 64 * 1024
    assert client.stats.bytes_sent >= fanout * size  # both subtrees streamed


def test_relay_chain_pipelines_through_interior_nodes(dest_farm):
    """fanout=1 chain of 4: the root sends ONE copy; every interior server
    forwards what it receives (server-side socket-byte stats), and the
    broadcast_relay_bytes_total metric records the forwarded bytes."""
    stores, servers, client = dest_farm(4)
    size = 4 << 20
    value = np.arange(size, dtype=np.uint8)
    oid = ObjectID.from_random()
    relayed_before = metric_defs.BROADCAST_RELAY_BYTES.get()
    failed = client.relay(oid.binary(), value, data_plane.build_relay_tree(
        [s.address for s in servers], 1))
    assert failed == []
    for store in stores:
        np.testing.assert_array_equal(store.get(oid, timeout=5), value)
    assert client.stats.bytes_sent <= size + 64 * 1024  # ONE copy out of the root
    for server in servers[:3]:  # interior hops forwarded the whole object
        assert server.stats.bytes_sent >= size
    assert servers[3].stats.bytes_sent == 0  # the leaf forwards nothing
    assert metric_defs.BROADCAST_RELAY_BYTES.get() - relayed_before >= 3 * size


def test_relay_reports_failed_subtree_and_serves_the_rest(dest_farm):
    """A dead child mid-tree: its whole subtree is reported failed (the
    planner re-pulls exactly those); live destinations still commit."""
    stores, servers, client = dest_farm(2)
    # a listener that is closed before the relay: connection refused
    import socket as _socket

    dead = _socket.socket()
    dead.bind(("127.0.0.1", 0))
    dead_addr = f"127.0.0.1:{dead.getsockname()[1]}"
    dead.close()
    size = 1 << 20
    value = np.ones(size, np.uint8)
    oid = ObjectID.from_random()
    tree = [
        {"addr": servers[0].address, "children": [
            {"addr": dead_addr, "children": [
                {"addr": servers[1].address, "children": []},
            ]},
        ]},
    ]
    failed = client.relay(oid.binary(), value, tree)
    # the dead hop AND its descendant are reported; the live parent served
    assert dead_addr in failed
    assert servers[1].address in failed
    assert stores[0].contains(oid)
    assert not stores[1].contains(oid)


# ==========================================================================
# unit: broadcast plans in the PullManager (in-process fabric)
# ==========================================================================
class _CountingStore(ObjectStore):
    """Counts get() calls; optional gate (block-until-open) or tripwire
    (block, then raise once released) to control transfer order."""

    def __init__(self, gate=None, raise_on_release=False):
        super().__init__(shm_store=None)
        self.get_calls = 0
        self.gate = gate
        self.raise_on_release = raise_on_release

    def get(self, object_id, timeout=None):
        self.get_calls += 1
        if self.gate is not None:
            assert self.gate.wait(30)
            if self.raise_on_release:
                raise RuntimeError("relay source died")
        return super().get(object_id, timeout=timeout)


class _FakeNode:
    def __init__(self, store=None):
        self.node_id = NodeID.from_random()
        self.store = store if store is not None else ObjectStore(shm_store=None)
        self.dead = False


class _FakeCluster:
    def __init__(self):
        self.directory = ObjectDirectory()
        self.nodes = {}
        self.transfer_bytes = 0
        self.transfer_count = 0

    def add(self, node):
        self.nodes[node.node_id] = node
        return node

    def _is_pending(self, oid):
        return False

    def _try_recover(self, oid):
        return False


def _make_pm(fake, fanout=None):
    pm = PullManager(fake)
    if fanout is not None:
        pm._fanout = fanout
    fake.directory.location_observer = pm.on_location_committed
    return pm


def test_plan_bounds_root_reads_and_drains_budget():
    """5 concurrent pulls of one object, fanout 2: the source store is read
    exactly TWICE (the root's direct children); the other 3 copies are
    relayed by destinations.  Budget returns to zero after the plan
    drains and the plan itself is torn down."""
    fake = _FakeCluster()
    gate = threading.Event()
    root = fake.add(_FakeNode(store=_CountingStore(gate=gate)))
    dests = [fake.add(_FakeNode(store=_CountingStore())) for _ in range(5)]
    pm = _make_pm(fake, fanout=2)
    try:
        oid = ObjectID.from_random()
        value = np.ones(1 << 20, np.uint8)
        root.store.put(oid, value)
        root.store.get_calls = 0  # the put-side bookkeeping doesn't count
        fake.directory.add_location(oid, root.node_id, size=value.nbytes, tier="host")
        plans_before = pm.plans_created
        events = [threading.Event() for _ in dests]
        for dest, event in zip(dests, events):
            pm.pull(oid, dest, event.set)
        assert pm.plans_created - plans_before == 1
        snap = pm.broadcast_snapshot()
        assert snap["active"] and snap["active"][0]["dests"] == 5
        gate.set()
        for event in events:
            assert event.wait(20)
        for dest in dests:
            assert dest.store.contains(oid)
        # root egress bound: fanout reads, was N reads before the planner
        assert root.store.get_calls == 2
        # the other three edges were relayed by destinations
        assert sum(d.store.get_calls for d in dests) == 3
        assert pm.relay_bytes == 3 * value.nbytes
        snap = pm.snapshot()
        assert snap["inflight"] == 0 and snap["inflight_bytes"] == 0
        assert _wait_for(lambda: not pm.broadcast_snapshot()["active"])
    finally:
        pm.shutdown()


def test_parked_children_hold_no_budget():
    """Children waiting on a pending tree parent charge nothing against
    the in-flight-byte budget — only active edges are budgeted."""
    fake = _FakeCluster()
    gate = threading.Event()
    root = fake.add(_FakeNode(store=_CountingStore(gate=gate)))
    dests = [fake.add(_FakeNode()) for _ in range(5)]
    pm = _make_pm(fake, fanout=2)
    try:
        oid = ObjectID.from_random()
        root.store.put(oid, np.ones(1 << 20, np.uint8))
        fake.directory.add_location(oid, root.node_id, size=1 << 20, tier="host")
        events = [threading.Event() for _ in dests]
        for dest, event in zip(dests, events):
            pm.pull(oid, dest, event.set)
        snap = pm.snapshot()
        # two root edges admitted (blocked on the gate); three children parked
        assert snap["inflight"] == 2
        assert snap["inflight_bytes"] == 2 << 20
        assert pm.broadcast_snapshot()["active"][0]["parked"] == 3
        gate.set()
        for event in events:
            assert event.wait(20)
        assert pm.snapshot()["inflight_bytes"] == 0
    finally:
        pm.shutdown()


def test_late_joiner_pulls_from_replica_not_root():
    """fanout=1 chain root->A1->A2: a pull that joins while the plan is
    active attaches under a destination, and after the plan drains the
    round-robin directory pick keeps spreading load off the root."""
    fake = _FakeCluster()
    root_gate, a1_gate = threading.Event(), threading.Event()
    root = fake.add(_FakeNode(store=_CountingStore(gate=root_gate)))
    a1 = fake.add(_FakeNode(store=_CountingStore(gate=a1_gate)))
    a2 = fake.add(_FakeNode(store=_CountingStore()))
    late = fake.add(_FakeNode(store=_CountingStore()))
    pm = _make_pm(fake, fanout=1)
    try:
        oid = ObjectID.from_random()
        root.store.put(oid, np.ones(1 << 18, np.uint8))
        root.store.get_calls = 0
        fake.directory.add_location(oid, root.node_id, size=1 << 18, tier="host")
        ev1, ev2, ev_late = threading.Event(), threading.Event(), threading.Event()
        pm.pull(oid, a1, ev1.set)   # root child, blocked on root_gate
        pm.pull(oid, a2, ev2.set)   # child of a1: parks
        root_gate.set()
        assert ev1.wait(20)         # a1 is now a replica; a2 promoted,
        #                             blocked on a1_gate mid-edge
        pm.pull(oid, late, ev_late.set)  # late joiner: root (fanout 1) and
        #                                  a1 are full -> attaches under a2
        a1_gate.set()
        assert ev2.wait(20) and ev_late.wait(20)
        assert late.store.contains(oid)
        assert root.store.get_calls == 1      # the root served exactly ONE edge
        assert a1.store.get_calls == 1        # a1 relayed to a2
        assert a2.store.get_calls == 1        # the late joiner read from a2
        # post-drain pulls keep spreading: round-robin over all four replicas
        more = [fake.add(_FakeNode()) for _ in range(4)]
        for node in more:
            done = threading.Event()
            pm.pull(oid, node, done.set)
            assert done.wait(20)
        assert root.store.get_calls < 1 + 4   # not every new pull hit the root
    finally:
        pm.shutdown()


def test_dead_relay_reparents_subtree_onto_survivors():
    """fanout=1 chain root->d1->d2->d3.  d1 dies after completing, while
    serving d2: d2's failed edge purges + retries onto the root (surviving
    replica), and d3 — parked under d2 — still completes through the
    repaired chain.  The purge-then-retry path, end to end."""
    fake = _FakeCluster()
    root_gate = threading.Event()
    trip = threading.Event()
    root = fake.add(_FakeNode(store=_CountingStore(gate=root_gate)))
    d1 = fake.add(_FakeNode(store=_CountingStore(gate=trip, raise_on_release=True)))
    d2 = fake.add(_FakeNode(store=_CountingStore()))
    d3 = fake.add(_FakeNode(store=_CountingStore()))
    pm = _make_pm(fake, fanout=1)
    try:
        oid = ObjectID.from_random()
        root.store.put(oid, np.ones(1 << 18, np.uint8))
        root.store.get_calls = 0
        fake.directory.add_location(oid, root.node_id, size=1 << 18, tier="host")
        events = {n.node_id: threading.Event() for n in (d1, d2, d3)}
        for node in (d1, d2, d3):
            pm.pull(oid, node, events[node.node_id].set)
        root_gate.set()
        assert events[d1.node_id].wait(20)   # d1 committed its copy
        # d2's edge is now blocked inside d1's store; kill d1 mid-broadcast
        d1.dead = True
        fake.directory.drop_node(d1.node_id)
        pm.on_node_dead(d1.node_id)
        trip.set()                            # d1's serve raises -> purge+retry
        assert events[d2.node_id].wait(20)
        assert events[d3.node_id].wait(20)
        assert d2.store.contains(oid) and d3.store.contains(oid)
        assert pm.snapshot()["retries"] >= 1
        # d2 re-parented onto the root (the only surviving replica then)
        assert root.store.get_calls == 2
        snap = pm.snapshot()
        assert snap["inflight"] == 0 and snap["inflight_bytes"] == 0
    finally:
        pm.shutdown()


# ==========================================================================
# unit: wire relay — the PullManager drives ONE data-plane relay for a
# group of remote destinations (socket-byte root egress bound)
# ==========================================================================
class _HeadCacheStore(ObjectStore):
    """Head-side cache surface of a RemoteNodeHandle's store."""

    def __init__(self):
        super().__init__(shm_store=None)

    def skip_push_once(self, oid):
        pass


class _FakeRemoteDest:
    """RemoteNodeHandle shape: a head-side cache store + the agent's real
    store served by a DataServer at data_address."""

    def __init__(self, server_address):
        self.node_id = NodeID.from_random()
        self.store = _HeadCacheStore()
        self.data_address = server_address
        self.dead = False


def test_wire_relay_serves_remote_group_with_bounded_root_egress(dest_farm):
    """4 remote destinations pull one 8 MiB object BEFORE it is produced
    (the checkpoint-broadcast pattern).  When the location commits, the
    planner runs ONE chunk-pipelined relay: every agent store receives the
    bytes, head caches fill without echo pushes, and the head's socket
    egress stays <= fanout x size (was N x)."""
    from types import SimpleNamespace

    agent_stores, servers, client = dest_farm(4, chunk_bytes=1 << 20)
    fake = _FakeCluster()
    fake.head_service = SimpleNamespace(data_client=client)
    src = fake.add(_FakeNode())
    dests = [fake.add(_FakeRemoteDest(server.address)) for server in servers]
    pm = _make_pm(fake, fanout=2)
    try:
        oid = ObjectID.from_random()
        size = 8 << 20
        value = np.full(size, 3, np.uint8)
        events = [threading.Event() for _ in dests]
        for dest, event in zip(dests, events):
            pm.pull(oid, dest, event.set)   # object not produced yet: all wait
        assert pm.snapshot()["inflight"] == 0   # unlocated pulls hold no budget
        # the producer commits: one wire relay covers the whole group
        src.store.put(oid, value)
        fake.directory.add_location(oid, src.node_id, size=size, tier="host")
        for event in events:
            assert event.wait(30)
        for store in agent_stores:               # the AGENT stores got the bytes
            assert store.contains(oid)
        for dest in dests:                       # and the head caches filled
            assert dest.store.contains(oid)
            assert dest.node_id in fake.directory.locations(oid)
        # socket-byte accounting: the head streamed only fanout copies
        assert client.stats.bytes_sent <= 2 * size + 64 * 1024
        assert servers[0].stats.bytes_sent + servers[1].stats.bytes_sent >= 2 * size
        snap = pm.snapshot()
        assert snap["inflight"] == 0 and snap["inflight_bytes"] == 0
        assert _wait_for(lambda: not pm.broadcast_snapshot()["active"])
    finally:
        pm.shutdown()


# ==========================================================================
# unit: replica-aware directory selection (satellite)
# ==========================================================================
def test_pick_location_spreads_across_replicas():
    directory = ObjectDirectory()
    oid = ObjectID.from_random()
    nodes = [NodeID.from_random() for _ in range(3)]
    for nid in nodes:
        directory.add_location(oid, nid, size=1024, tier="host")
    picks = [directory.pick_location(oid) for _ in range(9)]
    counts = {nid: picks.count(nid) for nid in nodes}
    assert all(count == 3 for count in counts.values()), counts  # round-robin
    # exclude filters; a sole replica is always returned
    only = directory.pick_location(oid, exclude=set(nodes[1:]))
    assert only == nodes[0]
    sole = ObjectID.from_random()
    directory.add_location(sole, nodes[0])
    assert all(directory.pick_location(sole) == nodes[0] for _ in range(3))
    assert directory.pick_location(ObjectID.from_random()) is None


def test_pick_location_feeds_source_metric():
    directory = ObjectDirectory()
    oid = ObjectID.from_random()
    for _ in range(2):
        directory.add_location(oid, NodeID.from_random(), size=64, tier="host")
    balanced_before = metric_defs.PULL_SOURCE_SELECTED.get({"kind": "balanced"})
    directory.pick_location(oid)
    assert metric_defs.PULL_SOURCE_SELECTED.get({"kind": "balanced"}) == balanced_before + 1


def test_assign_remote_source_chains_behind_inflight_requesters():
    """locate_object-side broadcasting: with the sole replica saturated at
    ``fanout`` children, the next requesters are chained behind IN-FLIGHT
    requesters — forming a tree instead of N streams out of the producer.
    Completed requesters (location committed) become balanced sources."""
    fake = _FakeCluster()
    producer = fake.add(_FakeNode())
    requesters = [fake.add(_FakeNode()) for _ in range(5)]
    pm = _make_pm(fake, fanout=1)
    try:
        oid = ObjectID.from_random()
        producer.store.put(oid, b"x" * 64)
        fake.directory.add_location(oid, producer.node_id, size=64, tier="host")
        relay_before = metric_defs.PULL_SOURCE_SELECTED.get({"kind": "relay"})
        first = pm.assign_remote_source(oid, requesters[0].node_id)
        assert first == producer.node_id            # replica has capacity
        second = pm.assign_remote_source(oid, requesters[1].node_id)
        assert second == requesters[0].node_id      # producer saturated: chain
        third = pm.assign_remote_source(oid, requesters[2].node_id)
        assert third == requesters[1].node_id       # chain extends, fanout 1
        assert metric_defs.PULL_SOURCE_SELECTED.get({"kind": "relay"}) >= relay_before + 2
        # requester 0 commits its copy: it now serves as a REPLICA and its
        # parent's (the producer's) assignment slot is RELEASED; a failed
        # peer is dropped from assignment entirely, freeing its slot too
        fake.directory.add_location(oid, requesters[0].node_id, size=64, tier="host")
        pm.note_source_failed(oid, requesters[1].node_id)
        fake.directory.remove_location(oid, requesters[1].node_id)
        fourth = pm.assign_remote_source(oid, requesters[3].node_id)
        # freed committed replicas win over chaining behind in-flight pulls
        assert fourth in (producer.node_id, requesters[0].node_id)
    finally:
        pm.shutdown()


def test_assign_remote_source_never_closes_a_cycle():
    """Both chained requesters lose their source: re-assignment must not
    chain A behind B while B (transitively) pulls from A — that would
    deadlock both until the pull timeout."""
    fake = _FakeCluster()
    producer = fake.add(_FakeNode())
    req_a = fake.add(_FakeNode())
    req_b = fake.add(_FakeNode())
    req_c = fake.add(_FakeNode())
    pm = _make_pm(fake, fanout=1)
    try:
        oid = ObjectID.from_random()
        producer.store.put(oid, b"x" * 64)
        fake.directory.add_location(oid, producer.node_id, size=64, tier="host")
        assert pm.assign_remote_source(oid, req_a.node_id) == producer.node_id
        assert pm.assign_remote_source(oid, req_b.node_id) == req_a.node_id
        # the producer dies before either copy lands
        producer.dead = True
        fake.directory.remove_location(oid, producer.node_id)
        pm.note_source_failed(oid, producer.node_id)
        # A re-locates: B is the only other entry, but B pulls FROM A —
        # assignment must refuse the loop (fall back to the directory pick)
        assert pm.assign_remote_source(oid, req_a.node_id) is None
        # an unrelated requester may still chain behind B
        assert pm.assign_remote_source(oid, req_c.node_id) in (
            req_a.node_id, req_b.node_id
        )
    finally:
        pm.shutdown()


def test_broadcast_metric_families_in_catalog():
    """The new families ride the default catalog, so the tier-1
    exposition-validity test (test_tracing) covers them automatically."""
    names = {m.name for m in metric_defs.ALL_METRICS}
    assert {
        "broadcast_plans_total",
        "broadcast_relay_bytes_total",
        "pull_source_selected_total",
    } <= names


# ==========================================================================
# satellite: data-server frame cache knob + hit/miss counters
# ==========================================================================
def test_frame_cache_knob_and_counters(monkeypatch):
    from ray_tpu.core.config import get_config

    monkeypatch.setattr(get_config(), "data_server_frame_cache_entries", 2)
    store = ObjectStore(shm_store=None)
    server = data_plane.store_server(store, chunk_bytes=1 << 20)
    client = data_plane.DataClient(chunk_bytes=1 << 20)
    try:
        oids = [ObjectID.from_random() for _ in range(3)]
        for oid in oids:
            store.put(oid, np.ones(2048, np.uint8))
        client.pull(server.address, oids[0].binary())
        client.pull(server.address, oids[0].binary())
        assert server.stats.frame_cache_hits == 1
        assert server.stats.frame_cache_misses == 1
        # capacity 2: pulling two more objects evicts the first (LRU)
        client.pull(server.address, oids[1].binary())
        client.pull(server.address, oids[2].binary())
        client.pull(server.address, oids[0].binary())
        assert server.stats.frame_cache_misses == 4
        snap = server.stats.snapshot()
        assert snap["frame_cache_hits"] == 1 and snap["frame_cache_misses"] == 4
    finally:
        client.close()
        server.close()


# ==========================================================================
# integration: real cluster — plans form for real concurrent consumers
# ==========================================================================
def test_broadcast_plan_forms_for_concurrent_consumers(ray_start_cluster):
    """N consumers of one 8 MiB object pinned to DIFFERENT nodes: the
    fabric builds one broadcast plan and the object lands everywhere with
    the producing store read at most fanout times."""
    rt, cluster = ray_start_cluster
    producer_node = cluster.add_node({"CPU": 1, "prod": 1})
    consumer_nodes = [cluster.add_node({"CPU": 1}) for _ in range(3)]
    nbytes = 8 * 1024 * 1024

    @rt.remote(execution="thread", resources={"prod": 1}, num_cpus=0)
    def produce():
        return np.ones(nbytes, np.uint8)

    ref = produce.remote()
    assert _wait_for(lambda: cluster.directory.locations(ref.id()))
    # gate the producing store so all three pulls register while the first
    # edges are in flight (the broadcast window is microseconds otherwise)
    gate = threading.Event()
    orig_get = producer_node.store.get

    def gated_get(oid, timeout=None):
        assert gate.wait(30)
        return orig_get(oid, timeout=timeout)

    producer_node.store.get = gated_get
    try:
        plans_before = cluster.pull_manager.plans_created
        relay_before = cluster.pull_manager.relay_bytes
        events = [threading.Event() for _ in consumer_nodes]
        for node, event in zip(consumer_nodes, events):
            cluster.pull_object(ref.id(), node, event.set)
        gate.set()
        for event in events:
            assert event.wait(30)
    finally:
        producer_node.store.get = orig_get
    assert cluster.pull_manager.plans_created - plans_before == 1
    for node in consumer_nodes:
        assert node.store.contains(ref.id())
        assert node.node_id in cluster.directory.locations(ref.id())
    # tree accounting: with fanout 2 and 3 dests, the third edge relayed
    assert cluster.pull_manager.relay_bytes - relay_before >= nbytes
    snap = cluster.pull_manager.snapshot()
    assert snap["inflight"] == 0 and snap["inflight_bytes"] == 0
