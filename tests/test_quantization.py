"""Quantization op tests: absmax round-trip accuracy, the Pallas int8
matmul vs f32 reference (interpret mode), ragged shapes, pytree helpers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.quantization import (
    NO_SCALE,
    dequantize_int8,
    dequantize_tree,
    int8_matmul,
    quantize_int8,
    quantize_tree,
)


def test_quantize_roundtrip_accuracy():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((128, 64)), jnp.float32)
    w_q, scales = quantize_int8(w, axis=0)
    assert w_q.dtype == jnp.int8
    assert scales.shape == (1, 64)
    w_back = dequantize_int8(w_q, scales)
    # absmax int8: error bounded by scale/2 per element
    err = np.abs(np.asarray(w - w_back))
    bound = np.asarray(scales)[0] / 2 + 1e-7
    assert (err <= bound[None, :]).all()


def test_zero_column_safe():
    w = jnp.zeros((16, 4), jnp.float32)
    w_q, scales = quantize_int8(w, axis=0)
    assert np.isfinite(np.asarray(scales)).all()
    assert (np.asarray(dequantize_int8(w_q, scales)) == 0).all()


@pytest.mark.parametrize("shape", [(64, 128, 96), (100, 300, 50)])  # ragged too
def test_int8_matmul_matches_reference(shape):
    M, K, N = shape
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((M, K)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((K, N)), jnp.float32)
    w_q, scales = quantize_int8(w, axis=0)
    out = int8_matmul(x, w_q, scales, block_m=32, block_n=64, block_k=32)
    ref = x @ dequantize_int8(w_q, scales)  # same quantized weights
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-3)
    # and close to the UNQUANTIZED result within quantization error
    full = np.asarray(x @ w)
    rel = np.abs(np.asarray(out) - full) / (np.abs(full) + 1.0)
    assert np.median(rel) < 0.02


def test_bf16_activations():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((32, 64)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    w_q, scales = quantize_int8(w, axis=0)
    out = int8_matmul(x, w_q, scales)
    assert out.dtype == jnp.bfloat16
    ref = x.astype(jnp.float32) @ dequantize_int8(w_q, scales)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=2e-2, atol=2e-1
    )


def test_quantize_tree_roundtrip():
    rng = np.random.default_rng(3)
    params = {
        "big": jnp.asarray(rng.standard_normal((128, 64)), jnp.float32),
        "small": jnp.asarray(rng.standard_normal((4,)), jnp.float32),  # kept
        "nested": {"w": jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)},
    }
    wq, sc = quantize_tree(params, min_size=1024)
    assert wq["big"].dtype == jnp.int8
    assert wq["nested"]["w"].dtype == jnp.int8
    assert wq["small"].dtype == jnp.float32  # too small: untouched
    assert sc["small"] is NO_SCALE
    back = dequantize_tree(wq, sc)
    assert (np.asarray(back["small"]) == np.asarray(params["small"])).all()
    err = np.abs(np.asarray(back["big"] - params["big"]))
    assert err.max() < np.abs(np.asarray(params["big"])).max() / 100
