"""Locality-aware scheduling + PullManager (ISSUE 3).

Two coupled subsystems:

  * the ObjectDirectory records per-object size/tier at commit time and
    ``ClusterScheduler.pick_node`` grows a locality stage — big-arg tasks
    run where their bytes already live (reference: locality_with_output,
    ``lease_policy.cc``),
  * all inbound object traffic funnels through an admission-controlled
    ``PullManager`` (``pull_manager.h:52`` parity): dedup of concurrent
    pulls, in-flight-byte cap, transfers on pull workers, retry-with-purge
    on failed sources.
"""

import threading
import time

import numpy as np
import pytest

from ray_tpu.core.ids import NodeID, ObjectID, TaskID
from ray_tpu.core.object_store import ObjectStore
from ray_tpu.core.resources import ResourcePool, ResourceSet
from ray_tpu.runtime.cluster import ObjectDirectory
from ray_tpu.runtime.scheduler import (
    ClusterScheduler,
    NodeAffinitySchedulingStrategy,
    TaskSpec,
)


def _wait_for(predicate, timeout=20.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ==========================================================================
# unit: PullManager over fake nodes (full control of sources/failures)
# ==========================================================================
class _FakeNode:
    def __init__(self, store=None):
        self.node_id = NodeID.from_random()
        self.store = store if store is not None else ObjectStore(shm_store=None)
        self.dead = False


class _FakeCluster:
    """The slice of the Cluster surface PullManager touches."""

    def __init__(self):
        self.directory = ObjectDirectory()
        self.nodes = {}
        self.transfer_bytes = 0
        self.transfer_count = 0

    def add(self, node):
        self.nodes[node.node_id] = node
        return node

    def _is_pending(self, oid):
        return False

    def _try_recover(self, oid):
        return False


class _GatedStore(ObjectStore):
    """get() blocks until the gate opens — makes admission observable."""

    def __init__(self):
        super().__init__(shm_store=None)
        self.gate = threading.Event()

    def get(self, object_id, timeout=None):
        assert self.gate.wait(30)
        return super().get(object_id, timeout=timeout)


class _FailingStore(ObjectStore):
    """get() raises — a wedged-but-alive source."""

    def __init__(self):
        super().__init__(shm_store=None)
        self.get_calls = 0

    def get(self, object_id, timeout=None):
        self.get_calls += 1
        raise RuntimeError("wedged source")


def _make_pm(cluster):
    from ray_tpu.runtime.pull_manager import PullManager

    return PullManager(cluster)


def test_concurrent_pulls_dedup_into_one_transfer():
    fake = _FakeCluster()
    gated = _GatedStore()  # hold the transfer in flight while pulls pile on
    src = fake.add(_FakeNode(store=gated))
    dest = fake.add(_FakeNode())
    pm = _make_pm(fake)
    try:
        oid = ObjectID.from_random()
        value = np.ones(1 << 20, np.uint8)
        gated.put(oid, value)
        fake.directory.add_location(oid, src.node_id, size=value.nbytes, tier="host")

        events = [threading.Event() for _ in range(6)]
        for e in events:
            pm.pull(oid, dest, e.set)
        gated.gate.set()
        for e in events:
            assert e.wait(20)
        assert fake.transfer_count == 1  # ONE transfer, six waiters
        assert pm.snapshot()["dedup_hits"] >= 5
        assert dest.store.contains(oid)
        # the new copy is a recorded location with its size
        assert dest.node_id in fake.directory.locations(oid)
        assert fake.directory.object_size(oid) == value.nbytes
    finally:
        pm.shutdown()


def test_admission_caps_inflight_bytes():
    fake = _FakeCluster()
    gated = _GatedStore()
    src = fake.add(_FakeNode(store=gated))
    dest = fake.add(_FakeNode())
    pm = _make_pm(fake)
    pm._max_inflight = 100  # tiny budget: two 80-byte pulls cannot coexist
    try:
        oids = [ObjectID.from_random() for _ in range(2)]
        for oid in oids:
            gated.put(oid, np.ones(80, np.uint8))
            fake.directory.add_location(oid, src.node_id, size=80, tier="host")
        done = [threading.Event() for _ in oids]
        pm.pull(oids[0], dest, done[0].set)
        pm.pull(oids[1], dest, done[1].set)
        snap = pm.snapshot()
        assert snap["inflight"] == 1 and snap["queued"] == 1
        assert snap["inflight_bytes"] == 80
        gated.gate.set()  # release the transfer workers
        for e in done:
            assert e.wait(20)
        snap = pm.snapshot()
        assert snap["queued"] == 0 and snap["inflight_bytes"] == 0
        assert fake.transfer_count == 2
    finally:
        pm.shutdown()


def test_admission_is_fifo_small_pulls_queue_behind_large():
    """A stream of small pulls must not jump a queued large pull — later
    arrivals line up behind the queue head, or the large pull (and the task
    blocked on it) starves while the budget churns under it."""
    fake = _FakeCluster()
    gated = _GatedStore()
    src = fake.add(_FakeNode(store=gated))
    dest = fake.add(_FakeNode())
    pm = _make_pm(fake)
    pm._max_inflight = 100
    try:
        sizes = [80, 80, 10]  # in-flight, queued-large, late-small
        oids = [ObjectID.from_random() for _ in sizes]
        for oid, size in zip(oids, sizes):
            gated.put(oid, np.ones(size, np.uint8))
            fake.directory.add_location(oid, src.node_id, size=size, tier="host")
        done = [threading.Event() for _ in oids]
        for oid, e in zip(oids, done):
            pm.pull(oid, dest, e.set)
        snap = pm.snapshot()
        # the 10-byte pull FITS the remaining budget but must queue behind
        # the 80-byte pull that was already waiting
        assert snap["inflight"] == 1 and snap["queued"] == 2
        gated.gate.set()
        for e in done:
            assert e.wait(20)
        assert fake.transfer_count == 3
    finally:
        pm.shutdown()


def test_unlocated_pull_holds_no_budget():
    """A pull waiting for an object that doesn't exist yet (or is being
    reconstructed) must NOT hold admission budget — otherwise recovery's
    own dependency pulls can deadlock behind the pull that triggered the
    recovery.  Budget is charged only while a located transfer runs."""
    fake = _FakeCluster()
    src = fake.add(_FakeNode())
    dest = fake.add(_FakeNode())
    pm = _make_pm(fake)
    pm._max_inflight = 100
    try:
        ghost = ObjectID.from_random()   # never produced (yet)
        fake.directory.record_meta(ghost, 90, "host")  # size known, no copy
        waiting = threading.Event()
        pm.pull(ghost, dest, waiting.set)
        snap = pm.snapshot()
        assert snap["inflight"] == 0 and snap["inflight_bytes"] == 0
        # another large pull admits freely — the ghost charges nothing
        oid = ObjectID.from_random()
        src.store.put(oid, np.ones(80, np.uint8))
        fake.directory.add_location(oid, src.node_id, size=80, tier="host")
        done = threading.Event()
        pm.pull(oid, dest, done.set)
        assert done.wait(20)
        # the ghost materializes: its pull proceeds and completes
        src.store.put(ghost, np.ones(90, np.uint8))
        fake.directory.add_location(ghost, src.node_id, size=90, tier="host")
        assert waiting.wait(20)
        assert dest.store.contains(ghost)
    finally:
        pm.shutdown()


def test_prefetch_joins_without_waiter_growth():
    """Repeat prefetches of an in-flight transfer are no-ops: no waiter
    accumulation, no dedup-hit inflation."""
    fake = _FakeCluster()
    gated = _GatedStore()
    src = fake.add(_FakeNode(store=gated))
    dest = fake.add(_FakeNode())
    pm = _make_pm(fake)
    try:
        oid = ObjectID.from_random()
        gated.put(oid, np.ones(64, np.uint8))
        fake.directory.add_location(oid, src.node_id, size=64, tier="host")
        for _ in range(10):
            pm.prefetch([oid], dest)
        key = (oid, dest.node_id)
        with pm._lock:
            assert len(pm._pulls[key].waiters) == 1  # the first prefetch only
        assert pm.snapshot()["dedup_hits"] == 0
        done = threading.Event()
        pm.pull(oid, dest, done.set)  # a REAL consumer still joins
        gated.gate.set()
        assert done.wait(20)
        assert fake.transfer_count == 1
    finally:
        pm.shutdown()


def test_failed_source_is_purged_then_retried():
    """The pre-PullManager bug: a failing source was re-waited WITHOUT
    remove_location, so the same wedged node was retried in a hot loop.
    Now the stale location is purged first and the pull completes from a
    fresh source once one appears."""
    fake = _FakeCluster()
    wedged = fake.add(_FakeNode(store=_FailingStore()))
    healthy = fake.add(_FakeNode())
    dest = fake.add(_FakeNode())
    pm = _make_pm(fake)
    try:
        oid = ObjectID.from_random()
        fake.directory.add_location(oid, wedged.node_id, size=64, tier="host")
        done = threading.Event()
        pm.pull(oid, dest, done.set)
        # the failing get purges the wedged location
        assert _wait_for(lambda: wedged.node_id not in fake.directory.locations(oid))
        assert pm.snapshot()["retries"] >= 1
        assert wedged.store.get_calls == 1  # purged, NOT hot-looped
        # a healthy copy appears: the parked pull completes from it
        healthy.store.put(oid, np.ones(64, np.uint8))
        fake.directory.add_location(oid, healthy.node_id, size=64, tier="host")
        assert done.wait(20)
        assert dest.store.contains(oid)
    finally:
        pm.shutdown()


def test_dest_put_failure_returns_budget_and_retries(capsys):
    """An unexpected failure AFTER the source get (e.g. the destination
    store's put raising MemoryError) must not leak admitted budget or
    strand waiters — the pull uncharges, logs, and retries."""

    class _FlakyPutStore(ObjectStore):
        def __init__(self):
            super().__init__(shm_store=None)
            self.fail_remaining = 2

        def put(self, object_id, value, is_error=False):
            if self.fail_remaining:
                self.fail_remaining -= 1
                raise MemoryError("arena full")
            super().put(object_id, value, is_error=is_error)

    fake = _FakeCluster()
    src = fake.add(_FakeNode())
    dest = fake.add(_FakeNode(store=_FlakyPutStore()))
    pm = _make_pm(fake)
    try:
        oid = ObjectID.from_random()
        src.store.put(oid, np.ones(64, np.uint8))
        fake.directory.add_location(oid, src.node_id, size=64, tier="host")
        done = threading.Event()
        pm.pull(oid, dest, done.set)
        assert done.wait(20)  # retried past the failures, waiter fired
        assert dest.store.contains(oid)
        snap = pm.snapshot()
        assert snap["inflight"] == 0 and snap["inflight_bytes"] == 0
        assert snap["retries"] >= 2
        assert "failed unexpectedly" in capsys.readouterr().err
    finally:
        pm.shutdown()


def test_dead_source_location_purged():
    fake = _FakeCluster()
    src = fake.add(_FakeNode())
    dest = fake.add(_FakeNode())
    pm = _make_pm(fake)
    try:
        oid = ObjectID.from_random()
        src.store.put(oid, b"x" * 64)
        fake.directory.add_location(oid, src.node_id, size=64, tier="host")
        src.dead = True
        done = threading.Event()
        pm.pull(oid, dest, done.set)
        assert _wait_for(lambda: src.node_id not in fake.directory.locations(oid))
        assert not done.is_set()  # parked for a fresh copy, not failed
    finally:
        pm.shutdown()


# ==========================================================================
# unit: the scheduler's locality stage
# ==========================================================================
def _spec(deps, resources=None, strategy=None):
    return TaskSpec(
        task_id=TaskID.from_random(),
        name="t",
        func=None,
        args=(),
        kwargs={},
        dependencies=deps,
        num_returns=1,
        return_ids=[],
        resources=ResourceSet(resources or {"CPU": 1}),
        scheduling_strategy=strategy,
    )


def _two_node_sched():
    sched = ClusterScheduler()
    directory = ObjectDirectory()
    sched.bind_directory(directory)
    pool_a, pool_b = ResourcePool({"CPU": 4}), ResourcePool({"CPU": 4})
    nid_a, nid_b = NodeID.from_random(), NodeID.from_random()
    sched.register_node(nid_a, pool_a)
    sched.register_node(nid_b, pool_b)
    return sched, directory, (nid_a, pool_a), (nid_b, pool_b)


def test_locality_overrides_utilization_for_big_args():
    sched, directory, (nid_a, pool_a), (nid_b, _pool_b) = _two_node_sched()
    # A busy, B idle: the hybrid policy would pick B
    assert pool_a.acquire(ResourceSet({"CPU": 3}))
    dep = ObjectID.from_random()
    directory.add_location(dep, nid_a, size=8 << 20, tier="host")
    for _ in range(5):
        assert sched.pick_node(_spec([dep])) == nid_a


def test_small_args_fall_back_to_hybrid():
    sched, directory, (nid_a, pool_a), (nid_b, _pool_b) = _two_node_sched()
    assert pool_a.acquire(ResourceSet({"CPU": 3}))
    dep = ObjectID.from_random()
    directory.add_location(dep, nid_a, size=1000, tier="host")  # << 1 MiB
    # below the threshold the cheap-to-move arg must not pin placement
    for _ in range(5):
        assert sched.pick_node(_spec([dep])) == nid_b


def test_locality_tie_falls_back():
    sched, directory, (nid_a, pool_a), (nid_b, _pool_b) = _two_node_sched()
    assert pool_a.acquire(ResourceSet({"CPU": 3}))
    dep = ObjectID.from_random()
    # both nodes hold the bytes: no lead over the runner-up -> hybrid
    directory.add_location(dep, nid_a, size=8 << 20, tier="host")
    directory.add_location(dep, nid_b, size=8 << 20, tier="host")
    assert sched.pick_node(_spec([dep])) == nid_b


def test_locality_applies_to_spread_strategy():
    sched, directory, (nid_a, _pa), (nid_b, _pb) = _two_node_sched()
    dep = ObjectID.from_random()
    directory.add_location(dep, nid_b, size=16 << 20, tier="host")
    for _ in range(5):
        assert sched.pick_node(_spec([dep], strategy="SPREAD")) == nid_b


def test_directory_drops_meta_with_last_location():
    directory = ObjectDirectory()
    nid = NodeID.from_random()
    oid = ObjectID.from_random()
    directory.add_location(oid, nid, size=4096, tier="host")
    assert directory.object_size(oid) == 4096
    assert directory.local_bytes([oid]) == {nid: 4096}
    directory.forget(oid)
    assert directory.object_size(oid) == 0
    assert directory.local_bytes([oid]) == {}


# ==========================================================================
# integration: real cluster — the acceptance bars
# ==========================================================================
def test_big_arg_task_lands_on_producer_zero_transfer(ray_start_cluster):
    """2+ nodes: a task whose arg exceeds the locality threshold schedules
    onto the node holding the bytes (directory-verified) and the fabric
    moves ZERO argument bytes; a no-arg workload still spreads."""
    rt, cluster = ray_start_cluster
    producer = cluster.add_node({"CPU": 2, "prod": 1})
    cluster.add_node({"CPU": 2})

    @rt.remote(execution="thread", resources={"prod": 1}, num_cpus=0)
    def produce():
        return np.ones(8 * 1024 * 1024, np.uint8)

    @rt.remote(execution="thread")
    def where(x):
        return rt.get_runtime_context().get_node_id()

    ref = produce.remote()
    assert _wait_for(lambda: cluster.directory.locations(ref.id()))
    assert cluster.directory.object_size(ref.id()) == 8 * 1024 * 1024
    bytes_before = cluster.transfer_bytes
    for _ in range(3):
        assert rt.get(where.remote(ref), timeout=30) == producer.node_id.hex()
    # the 8 MiB argument never moved (result pulls are byte-free ints)
    assert cluster.transfer_bytes == bytes_before

    @rt.remote(execution="thread")
    def where_no_arg():
        time.sleep(0.2)
        return rt.get_runtime_context().get_node_id()

    nodes_seen = set(rt.get([where_no_arg.remote() for _ in range(12)], timeout=60))
    assert len(nodes_seen) >= 2  # locality stage leaves no-arg spread intact


def test_n_consumers_one_remote_arg_single_copy(ray_start_cluster):
    """N concurrent consumers of one remote 8 MiB object, pinned AWAY from
    the bytes: the PullManager coalesces their dependency pulls into ONE
    data transfer (transfer bytes grow by exactly one copy)."""
    rt, cluster = ray_start_cluster
    cluster.add_node({"CPU": 2, "pa": 4})
    node_b = cluster.add_node({"CPU": 4})
    nbytes = 8 * 1024 * 1024

    @rt.remote(execution="thread", resources={"pa": 1}, num_cpus=0)
    def produce():
        return np.ones(nbytes, np.uint8)

    @rt.remote(execution="thread", num_cpus=0)
    def consume(x):
        return int(x[0])

    ref = produce.remote()
    assert _wait_for(lambda: cluster.directory.locations(ref.id()))
    bytes_before = cluster.transfer_bytes
    pin_b = NodeAffinitySchedulingStrategy(node_b.node_id)
    out = rt.get(
        [consume.options(scheduling_strategy=pin_b).remote(ref) for _ in range(4)],
        timeout=60,
    )
    assert out == [1, 1, 1, 1]
    # exactly ONE copy of the argument crossed the fabric
    assert cluster.transfer_bytes - bytes_before == nbytes
    assert node_b.node_id in cluster.directory.locations(ref.id())


def test_explicit_concurrent_pull_object_dedups(ray_start_cluster):
    rt, cluster = ray_start_cluster
    src_node = cluster.add_node({"CPU": 1, "src": 1})
    dest = cluster.add_node({"CPU": 1})

    @rt.remote(execution="thread", resources={"src": 1}, num_cpus=0)
    def produce():
        return np.full(2 << 20, 7, np.uint8)

    ref = produce.remote()
    assert _wait_for(lambda: cluster.directory.locations(ref.id()))
    # slow the source read so all five pulls arrive while one is in flight
    orig_get = src_node.store.get
    gate = threading.Event()

    def gated_get(oid, timeout=None):
        assert gate.wait(30)
        return orig_get(oid, timeout=timeout)

    src_node.store.get = gated_get
    try:
        count_before = cluster.transfer_count
        dedup_before = cluster.pull_manager.dedup_hits
        events = [threading.Event() for _ in range(5)]
        for e in events:
            cluster.pull_object(ref.id(), dest, e.set)
        gate.set()
        for e in events:
            assert e.wait(30)
        assert cluster.transfer_count - count_before == 1
        assert cluster.pull_manager.dedup_hits - dedup_before >= 4
        assert dest.store.contains(ref.id())
    finally:
        src_node.store.get = orig_get


def test_pull_manager_snapshot_shape(ray_start_regular):
    rt = ray_start_regular
    snap = rt.get_cluster().pull_manager.snapshot()
    for key in (
        "inflight", "queued", "inflight_bytes", "max_inflight_bytes",
        "dedup_hits", "retries", "completed", "bytes_pulled",
    ):
        assert key in snap
