"""LLM serving engine tests: greedy engine output == one-shot generate(),
continuous admission (mid-flight joins), slot reuse, eos/max_tokens stops,
and the Serve deployment wrapper."""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import TransformerConfig, generate, init_params
from ray_tpu.serve.llm import LLMEngine, LLMServer, _bucket

CFG = TransformerConfig(
    vocab_size=89, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
    attention="dense", dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(11))


@pytest.fixture()
def engine(params):
    eng = LLMEngine(CFG, params, max_batch_size=4, max_seq_len=64)
    yield eng
    eng.shutdown()


def _reference(params, prompt, n):
    """Greedy reference continuation via the one-shot generate()."""
    p = jnp.asarray([prompt], jnp.int32)
    out, lens = generate(CFG, params, p, max_new_tokens=n, temperature=0)
    return np.asarray(out[0, len(prompt): int(lens[0])]).tolist()


def test_single_request_matches_generate(engine, params):
    prompt = [3, 14, 15, 9, 2]
    got = engine.generate(prompt, max_tokens=6)
    assert got == _reference(params, prompt, 6)


@pytest.mark.full
def test_concurrent_ragged_requests_match(engine, params):
    prompts = [[5, 6], [7, 8, 9, 10, 11], [1] * 17, [42]]
    futs = [engine.submit(p, max_tokens=5) for p in prompts]
    outs = [f.result(timeout=120) for f in futs]
    for p, o in zip(prompts, outs):
        assert o == _reference(params, p, 5)


@pytest.mark.full
def test_continuous_admission_mid_flight(engine, params):
    """A request submitted while another decodes must join its batch and
    still produce exactly the solo-run tokens."""
    first = engine.submit([2, 3, 4], max_tokens=24)
    time.sleep(0.2)  # let decoding start
    second = engine.submit([9, 8, 7, 6], max_tokens=4)
    assert second.result(timeout=120) == _reference(params, [9, 8, 7, 6], 4)
    assert first.result(timeout=120) == _reference(params, [2, 3, 4], 24)


@pytest.mark.full
def test_slot_reuse_more_requests_than_slots(engine, params):
    prompts = [[i + 1, i + 2] for i in range(9)]  # 9 requests, 4 slots
    futs = [engine.submit(p, max_tokens=3) for p in prompts]
    for p, f in zip(prompts, futs):
        assert f.result(timeout=120) == _reference(params, p, 3)


def test_eos_stops_generation(engine, params):
    prompt = [4, 5, 6]
    ref = _reference(params, prompt, 8)
    eos = ref[2]
    got = engine.generate(prompt, max_tokens=8, eos_id=eos)
    # stops at (and includes) the FIRST occurrence of the eos token
    assert got == ref[: ref.index(eos) + 1]


def test_prompt_too_long_rejected(engine):
    with pytest.raises(ValueError):
        engine.submit(list(range(60)), max_tokens=10)


def test_sampled_temperature_valid_tokens(engine):
    out = engine.generate([1, 2, 3], max_tokens=12, temperature=1.3)
    assert len(out) == 12
    assert all(0 <= t < CFG.vocab_size for t in out)


def test_bucket():
    assert _bucket(1) == 16
    assert _bucket(16) == 16
    assert _bucket(17) == 32
    assert _bucket(100) == 128
    # capped: the bucket clamps to the cache capacity instead of growing
    # past it, and a length that cannot fit raises (never-fits contract)
    assert _bucket(100, cap=128) == 128
    assert _bucket(100, cap=100) == 100
    assert _bucket(64, cap=64) == 64
    with pytest.raises(ValueError):
        _bucket(65, cap=64)


def test_llm_server_deployment(params):
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    serve.start(http_port=0)
    try:
        app = serve.deployment(LLMServer).bind(
            lambda: (CFG, params), max_batch_size=4, max_seq_len=64
        )
        handle = serve.run(app, route_prefix=None)
        reqs = [{"prompt": [3, 1, 4], "max_tokens": 5}, {"prompt": [2, 7], "max_tokens": 3}]
        resps = [handle.remote(r) for r in reqs]
        r0, r1 = (r.result() for r in resps)
        assert r0["tokens"] == _reference(params, [3, 1, 4], 5)
        assert r1["tokens"] == _reference(params, [2, 7], 3)
        assert r0["num_generated"] == 5
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_submit_after_shutdown_raises(params):
    eng = LLMEngine(CFG, params, max_batch_size=2, max_seq_len=32)
    eng.shutdown()
    with pytest.raises(RuntimeError):
        eng.submit([1, 2], max_tokens=2)


def test_zero_max_tokens_rejected(engine):
    with pytest.raises(ValueError):
        engine.submit([1, 2], max_tokens=0)


@pytest.mark.full
def test_quantized_engine_generates(params):
    """Weight-only int8 engine: layer linears stored int8 (norm gains stay
    fp), greedy output EXACTLY matches generate() on the dequantized
    weights (in-scan dequant is numerically the same computation)."""
    import jax.numpy as jnp2

    from ray_tpu.ops.quantization import dequantize_int8

    eng_q = LLMEngine(
        CFG, params, max_batch_size=2, max_seq_len=64, quantize=True, quantize_min_size=256
    )
    try:
        q_layers = eng_q.params["layers"]
        assert q_layers["wq"].dtype == jnp2.int8
        assert q_layers["attn_norm"].dtype == CFG.param_dtype  # norms untouched
        prompt = [3, 14, 15]
        q_out = eng_q.generate(prompt, max_tokens=8)

        deq_layers = {
            k: (
                dequantize_int8(w, eng_q._layer_scales[k], CFG.param_dtype)
                if w.dtype == jnp2.int8
                else w
            )
            for k, w in q_layers.items()
        }
        ref_params = {**eng_q.params, "layers": deq_layers}
        assert q_out == _reference(ref_params, prompt, 8)
    finally:
        eng_q.shutdown()


def test_train_then_serve_e2e():
    """The round-trip story: train a tiny LM with the sharded train step,
    then serve the trained weights through the continuous-batching engine."""
    import jax
    import jax.numpy as jnp2

    from ray_tpu.models import make_train_step

    cfg = TransformerConfig(
        vocab_size=32, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        attention="dense", dtype=jnp2.float32,
    )
    init_state, step = make_train_step(cfg, learning_rate=5e-2)
    state = init_state(jax.random.key(0))
    # the "dataset": sequences counting upward — learnable in a few steps
    base = np.arange(18) % 32
    batch = jnp2.asarray(np.stack([np.roll(base, -i) for i in range(8)]), jnp2.int32)
    first = None
    for _ in range(30):
        state, loss = step(state, batch)
        first = first if first is not None else float(loss)
    assert float(loss) < first  # it learned something

    eng = LLMEngine(cfg, state["params"], max_batch_size=2, max_seq_len=32)
    try:
        out = eng.generate([0, 1, 2, 3], max_tokens=4)
        assert out == [4, 5, 6, 7], out  # continues the learned sequence
    finally:
        eng.shutdown()


@pytest.mark.full
def test_submit_stream_tokens_arrive_incrementally(engine, params):
    """Streaming yields the same tokens as the blocking API, and the first
    token arrives before the request completes."""
    prompt = [5, 6, 7]
    ref = _reference(params, prompt, 6)
    got = list(engine.submit_stream(prompt, max_tokens=6))
    assert got == ref


def test_stream_interleaves_with_blocking(engine, params):
    it = engine.submit_stream([2, 3], max_tokens=10)
    blocking = engine.submit([4, 5], max_tokens=4)
    streamed = list(it)
    assert streamed == _reference(params, [2, 3], 10)
    assert blocking.result(timeout=120) == _reference(params, [4, 5], 4)


@pytest.mark.full
def test_http_sse_streaming(params):
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    serve.start(http_port=0)
    try:
        app = serve.deployment(LLMServer).bind(
            lambda: (CFG, params), max_batch_size=2, max_seq_len=64
        )
        serve.run(app, route_prefix="/llm")
        body = json.dumps({"prompt": [3, 1, 4], "max_tokens": 5, "stream": True}).encode()
        req = urllib.request.Request(
            serve.proxy_url() + "/llm", data=body,
            headers={"Content-Type": "application/json"},
        )
        resp = urllib.request.urlopen(req, timeout=120)
        assert resp.headers["Content-Type"] == "text/event-stream"
        events = []
        for line in resp:
            line = line.decode().strip()
            if line.startswith("data: "):
                events.append(json.loads(line[6:]))
        toks = [e["token"] for e in events if "token" in e]
        assert toks == _reference(params, [3, 1, 4], 5)
        assert events[-1] == {"done": True, "num_generated": 5}
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_stream_validation_error_raises_eagerly(engine):
    """submit_stream validates BEFORE returning the iterator."""
    with pytest.raises(ValueError):
        engine.submit_stream(list(range(60)), max_tokens=20)


def test_http_sse_invalid_request_gets_error_response(params):
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    serve.start(http_port=0)
    try:
        app = serve.deployment(LLMServer).bind(
            lambda: (CFG, params), max_batch_size=2, max_seq_len=32
        )
        serve.run(app, route_prefix="/llm2")
        body = json.dumps(
            {"prompt": [1, 2, 3], "max_tokens": 500, "stream": True}  # > max_seq_len
        ).encode()
        req = urllib.request.Request(
            serve.proxy_url() + "/llm2", data=body,
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=60)
        assert exc.value.code == 500  # clean error status, not a broken 200 stream
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_mesh_sharded_engine(params):
    """Tensor-parallel engine over the virtual device mesh: params shard
    per the Megatron layout, cache heads over tp, outputs match the
    single-device engine."""
    if len(jax.devices()) < 2:
        pytest.skip("needs virtual devices")
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))  # kv_heads=2 -> tp=2 shards kv
    eng_m = LLMEngine(CFG, params, max_batch_size=2, max_seq_len=64, mesh=mesh)
    eng_s = LLMEngine(CFG, params, max_batch_size=2, max_seq_len=64)
    try:
        # params really sharded over tp
        wq_sh = eng_m.params["layers"]["wq"].sharding
        assert wq_sh.spec[2] == "tp"
        prompts = [[3, 14, 15], [7, 8]]
        m_out = [eng_m.generate(p, max_tokens=6) for p in prompts]
        s_out = [eng_s.generate(p, max_tokens=6) for p in prompts]
        assert m_out == s_out
    finally:
        eng_m.shutdown()
        eng_s.shutdown()


def test_mesh_engine_kv_replicated_when_indivisible(params):
    if len(jax.devices()) < 4:
        pytest.skip("needs virtual devices")
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))  # kv_heads=2, tp=4 -> replicate kv
    eng = LLMEngine(CFG, params, max_batch_size=2, max_seq_len=48, mesh=mesh)
    try:
        out = eng.generate([5, 6, 7], max_tokens=4)
        assert len(out) == 4
    finally:
        eng.shutdown()


def test_mesh_quantize_rejected(params):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    with pytest.raises(ValueError):
        LLMEngine(CFG, params, mesh=mesh, quantize=True)


def test_mesh_moe_engine(params):
    """MoE + mesh: expert specs fold ep into tp without duplicate-axis
    crashes (fit_spec keeps the first occurrence)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs virtual devices")
    from jax.sharding import Mesh

    from ray_tpu.models import init_params as ip

    moe_cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        num_experts=2, expert_top_k=1, attention="dense", dtype=jnp.float32,
    )
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    eng = LLMEngine(moe_cfg, ip(moe_cfg, jax.random.key(5)), max_batch_size=2, max_seq_len=32, mesh=mesh)
    try:
        out = eng.generate([1, 2, 3], max_tokens=3)
        assert len(out) == 3
    finally:
        eng.shutdown()


@pytest.mark.full
def test_text_requests_with_tokenizer(params):
    """model_factory may return (cfg, params, tokenizer): requests send
    'text', responses carry decoded text."""

    class ByteTok:
        def encode(self, s):
            return [b % CFG.vocab_size for b in s.encode()]

        def decode(self, ids):
            return "".join(chr(97 + (i % 26)) for i in ids)

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    serve.start(http_port=0)
    try:
        app = serve.deployment(LLMServer, name="txt").bind(
            lambda: (CFG, params, ByteTok()), max_batch_size=2, max_seq_len=64
        )
        handle = serve.run(app, route_prefix=None)
        r = handle.remote({"text": "hi", "max_tokens": 4}).result()
        assert r["tokens"] == _reference(params, ByteTok().encode("hi"), 4)
        assert r["text"] == ByteTok().decode(r["tokens"])
        # prompt ids still work on the same deployment
        r2 = handle.remote({"prompt": [1, 2], "max_tokens": 3}).result()
        assert len(r2["tokens"]) == 3
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_text_without_tokenizer_rejected(params):
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    serve.start(http_port=0)
    try:
        app = serve.deployment(LLMServer, name="notok").bind(
            lambda: (CFG, params), max_batch_size=2, max_seq_len=32
        )
        handle = serve.run(app, route_prefix=None)
        with pytest.raises(Exception, match="tokenizer"):
            handle.remote({"text": "hi", "max_tokens": 2}).result()
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_llm_server_mesh_passthrough(params):
    """serve deployments reach the tensor-parallel engine path."""
    if len(jax.devices()) < 2:
        pytest.skip("needs virtual devices")
    from jax.sharding import Mesh

    import ray_tpu
    from ray_tpu import serve

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    ray_tpu.init(num_cpus=4)
    serve.start(http_port=0)
    try:
        app = serve.deployment(LLMServer, name="tp_llm").bind(
            lambda: (CFG, params), max_batch_size=2, max_seq_len=48, mesh=mesh
        )
        handle = serve.run(app, route_prefix=None)
        r = handle.remote({"prompt": [3, 14, 15], "max_tokens": 4}).result()
        assert r["tokens"] == _reference(params, [3, 14, 15], 4)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


@pytest.mark.full
def test_data_batch_inference(params):
    """Dataset map_batches with LLMPredictor: offline batch generation
    rides the continuous-batching engine; outputs match solo runs."""
    import ray_tpu
    import ray_tpu.data as rd
    from ray_tpu.data import LLMPredictor

    prompts = [[3, 1], [4, 1, 5], [9, 2], [6, 5, 3, 5]]
    ray_tpu.init(num_cpus=4)
    try:
        ds = rd.from_items([{"prompt": p} for p in prompts])
        factory = lambda: (CFG, params)  # noqa: E731
        out = ds.map_batches(
            LLMPredictor,
            fn_constructor_args=(factory,),
            fn_constructor_kwargs={
                "max_tokens": 4, "max_batch_size": 4, "max_seq_len": 32,
            },
            batch_size=4,
        ).take_all()
        by_prompt = {tuple(r["prompt"]): list(r["generated"]) for r in out}
        for p in prompts:
            assert by_prompt[tuple(p)] == _reference(params, p, 4)
    finally:
        ray_tpu.shutdown()


def test_llm_predictor_cache_respects_kwargs(params):
    """Different engine kwargs must not share a cached engine; same
    factory+kwargs must reuse one."""
    from ray_tpu.data.llm_inference import LLMPredictor, clear_engine_cache

    factory = lambda: (CFG, params)  # noqa: E731
    try:
        a = LLMPredictor(factory, max_batch_size=2, max_seq_len=32)
        b = LLMPredictor(factory, max_batch_size=2, max_seq_len=32)
        c = LLMPredictor(factory, max_batch_size=2, max_seq_len=48)
        assert a.engine is b.engine
        assert a.engine is not c.engine
        assert c.engine.S == 48
    finally:
        clear_engine_cache()  # the supported release API


@pytest.mark.full
def test_llm_bench_script_tiny(monkeypatch, tmp_path):
    """The decode-throughput bench script measures real waves end-to-end
    (tiny config; same warmup/accounting paths as the serving-scale run)."""
    monkeypatch.setenv("RAY_TPU_LLM_BENCH_TINY", "1")
    from ray_tpu.scripts.llm_bench import main

    out = main(str(tmp_path / "llm.json"))
    assert out["metric"] == "llm_decode_throughput"
    assert out["value"] > 0
    assert out["extra"]["total_tokens"] == 2 * 4 * 3  # slots x tokens x waves
    assert (tmp_path / "llm.json").exists()


# ---------------------------------------------------------------------------
# chunked decode (decode_chunk > 1): K tokens per host round trip
# ---------------------------------------------------------------------------
def test_chunked_engine_matches_generate(params):
    eng = LLMEngine(CFG, params, max_batch_size=4, max_seq_len=64, decode_chunk=3)
    try:
        prompt = [3, 14, 15, 9, 2]
        # 8 tokens with K=3: 1 at admission + 3 + 3 + 1-of-3 — the request
        # finishes mid-chunk and the 2 tail tokens are discarded
        assert eng.generate(prompt, max_tokens=8) == _reference(params, prompt, 8)
    finally:
        eng.shutdown()


def test_chunked_engine_concurrent_ragged(params):
    eng = LLMEngine(CFG, params, max_batch_size=4, max_seq_len=64, decode_chunk=4)
    try:
        prompts = [[5, 6], [7, 8, 9, 10, 11], [1] * 17, [42], [13, 12, 11]]
        ns = [9, 5, 7, 11, 6]  # ragged lengths, several mid-chunk finishes
        futs = [eng.submit(p, max_tokens=n) for p, n in zip(prompts, ns)]
        got = [f.result(timeout=120) for f in futs]
        for p, n, g in zip(prompts, ns, got):
            assert g == _reference(params, p, n)
    finally:
        eng.shutdown()


def test_chunked_engine_eos_mid_chunk(params):
    # eos = the SECOND greedy token: the first comes from prefill at
    # admission, so this eos fires at k=0 INSIDE a 4-token decode chunk —
    # the request must stop there and the chunk's 3 tail tokens discard
    # find a prompt whose first two greedy tokens differ, so eos=t2 cannot
    # fire at admission (t1 from prefill) and must fire INSIDE the chunk
    for seed in range(1, 40):
        prompt = [seed, (seed * 7) % 88 + 1, (seed * 3) % 88 + 1]
        t1, t2 = _reference(params, prompt, 2)
        if t1 != t2:
            break
    assert t1 != t2
    eng = LLMEngine(CFG, params, max_batch_size=2, max_seq_len=64, decode_chunk=4)
    try:
        got = eng.generate(prompt, max_tokens=10, eos_id=t2)
        assert got == [t1, t2]
        # the slot is reusable afterwards: a second request still works
        assert eng.generate(prompt, max_tokens=3) == _reference(params, prompt, 3)
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# OpenAI-compatible adapter (body-shape dispatch; beyond reference parity)
# ---------------------------------------------------------------------------
class _Tok:
    """Toy tokenizer: 1 char = 1 id (offset so ids stay in-vocab)."""

    def encode(self, s):
        return [ord(c) % 80 + 1 for c in s]

    def decode(self, ids):
        return "".join(chr((i - 1) % 80 + 97) for i in ids)


@pytest.fixture()
def oai(params):
    from ray_tpu.serve.llm import OpenAICompatLLMServer

    srv = OpenAICompatLLMServer(
        lambda: (CFG, params, _Tok()), max_batch_size=4, max_seq_len=64
    )
    yield srv
    srv.engine.shutdown()


def test_openai_completions_envelope(oai, params):
    body = {"model": "m", "prompt": "hi", "max_tokens": 5, "temperature": 0}
    resp = oai(body)
    assert resp["object"] == "text_completion" and resp["id"].startswith("cmpl-")
    ch = resp["choices"][0]
    want = _reference(params, _Tok().encode("hi"), 5)
    assert ch["token_ids"] == want and ch["finish_reason"] == "length"
    assert resp["usage"] == {"prompt_tokens": 2, "completion_tokens": 5,
                             "total_tokens": 7}
    # token-id prompts skip the tokenizer entirely
    resp2 = oai({"model": "m", "prompt": [3, 1, 4], "max_tokens": 3, "temperature": 0})
    assert resp2["choices"][0]["token_ids"] == _reference(params, [3, 1, 4], 3)


def test_openai_chat_and_streaming(oai, params):
    body = {"model": "m", "messages": [{"role": "user", "content": "yo"}],
            "max_tokens": 4}
    resp = oai(body)
    assert resp["object"] == "chat.completion"
    msg = resp["choices"][0]["message"]
    assert msg["role"] == "assistant" and isinstance(msg["content"], str)
    # streaming chunks end with a finish_reason frame
    chunks = list(oai({**body, "stream": True}))
    assert chunks[-1]["choices"][0]["finish_reason"] == "length"
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    deltas = [c["choices"][0]["delta"].get("content") for c in chunks[:-1]]
    assert all(isinstance(d, str) for d in deltas)
    assert len(deltas) == 4


def test_openai_stop_token_and_legacy_dispatch(oai, params):
    prompt = [3, 14, 15, 9, 2]
    t1, t2 = _reference(params, prompt, 2)
    resp = oai({"model": "m", "prompt": prompt, "max_tokens": 8, "stop": int(t2),
                "temperature": 0})
    ch = resp["choices"][0]
    if t1 != t2:
        # OpenAI semantics: the stop token is EXCLUDED from the output
        assert ch["token_ids"] == [t1] and ch["finish_reason"] == "stop"
    # streaming also excludes the stop token and reports finish "stop"
    chunks = list(oai({"model": "m", "prompt": prompt, "max_tokens": 8,
                       "stop": int(t2), "stream": True, "temperature": 0}))
    if t1 != t2:
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
        toks = [c["choices"][0]["token_ids"][0] for c in chunks[:-1]]
        assert toks == [t1]
    # multi-token stop strings can't stream: clear error, not silent drop
    with pytest.raises(ValueError, match="stop"):
        oai({"model": "m", "prompt": "ab", "max_tokens": 4,
             "stop": "xyz", "stream": True})
    # a body without model/messages takes the native protocol path
    native = oai({"prompt": prompt, "max_tokens": 3})
    assert native["tokens"] == _reference(params, prompt, 3)


def test_openai_over_http(params):
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import OpenAICompatLLMServer

    ray_tpu.init(num_cpus=4)
    serve.start(http_port=0)
    try:
        app = serve.deployment(OpenAICompatLLMServer).bind(
            lambda: (CFG, params, _Tok()), max_batch_size=2, max_seq_len=64
        )
        serve.run(app, route_prefix="/v1")
        body = json.dumps({"model": "m", "prompt": "ab", "max_tokens": 4,
                           "temperature": 0}).encode()
        req = urllib.request.Request(
            serve.proxy_url() + "/v1/completions", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            resp = json.loads(r.read())
        assert resp["object"] == "text_completion"
        assert resp["choices"][0]["token_ids"] == _reference(
            params, _Tok().encode("ab"), 4)
        assert resp["usage"]["completion_tokens"] == 4
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_openai_multi_token_stop_trims_token_ids_too(oai, params):
    """token_ids/usage must describe the trimmed text when a multi-token
    stop string fires, not the raw generation."""
    # this prompt's greedy continuation changes token mid-way, giving a
    # 2-char window usable as a mid-text stop (probed: [5,6] -> ffff}}}})
    prompt = [5, 6]
    raw = _reference(params, prompt, 8)
    text = _Tok().decode(raw)
    # any 2-char window whose FIRST occurrence is mid-text works as a stop
    stop = None
    for i in range(1, len(text) - 1):
        if text.find(text[i : i + 2]) == i:
            stop = text[i : i + 2]
            break
    if stop is None:
        pytest.skip("greedy continuation has no mid-text 2-char stop here")
    resp = oai({"model": "m", "prompt": prompt, "max_tokens": 8, "stop": stop,
                "temperature": 0})
    ch = resp["choices"][0]
    assert ch["finish_reason"] == "stop"
    # token_ids are a faithful prefix of the actual generation, and the
    # text is their decode — envelope self-consistent
    assert ch["token_ids"] == raw[: len(ch["token_ids"])]
    assert ch["text"] == _Tok().decode(ch["token_ids"])
    assert stop not in ch["text"]
    assert resp["usage"]["completion_tokens"] == len(ch["token_ids"])


# ---------------------------------------------------------------------------
# prefix-aware KV reuse (serve/prefix_cache.py; beyond reference parity)
# ---------------------------------------------------------------------------
def test_prefix_cache_reuses_repeat_prompt_blocks(params):
    """A repeated prompt's full blocks come out of the prefix cache: the
    warm run reuses KV (prefix_tokens_reused grows) and is token-identical
    to the cold run under greedy decoding."""
    eng = LLMEngine(CFG, params, max_batch_size=2, max_seq_len=64,
                    kv_block_size=8)
    try:
        prompt = list(range(1, 18))  # 17 tokens -> 2 full blocks of 8
        want = _reference(params, prompt, 5)
        assert eng.generate(prompt, max_tokens=5) == want
        st = eng.stats()
        assert st["prefix_cache_misses"] == 1 and st["prefix_cache_blocks"] > 0
        assert eng.generate(prompt, max_tokens=5) == want
        st = eng.stats()
        assert st["prefix_cache_hits"] == 1
        assert st["prefix_tokens_reused"] >= 16  # both full blocks skipped
        # a different prompt is a miss and still decodes correctly
        other = [7, 8, 9]
        assert eng.generate(other, max_tokens=4) == _reference(params, other, 4)
        assert eng.stats()["prefix_cache_misses"] == 2
    finally:
        eng.shutdown()


def test_prefix_cache_on_by_default_and_disable_knob(params):
    eng = LLMEngine(CFG, params, max_batch_size=2, max_seq_len=64)
    off = LLMEngine(CFG, params, max_batch_size=2, max_seq_len=64,
                    prefix_cache=False)
    try:
        assert eng.stats()["prefix_cache_enabled"] is True
        assert off.stats()["prefix_cache_enabled"] is False
        p = list(range(1, 20))
        want = _reference(params, p, 3)
        for e in (eng, off):
            assert e.generate(p, max_tokens=3) == want
            assert e.generate(p, max_tokens=3) == want
        # disabled: nothing retained, every page back in the pool
        st = off.stats()
        assert st["prefix_cache_blocks"] == 0 and st["kv_blocks_in_use"] == 0
        assert st["prefix_cache_hits"] == 0
    finally:
        eng.shutdown()
        off.shutdown()


def test_tp_engine_with_chunked_decode(params):
    """decode_chunk composes with tensor-parallel serving: the sharded scan
    program produces the single-device engine's tokens (mesh engines run
    the dense cache, so prefix reuse does not apply there)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs virtual devices")
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    eng = LLMEngine(CFG, params, max_batch_size=2, max_seq_len=64,
                    mesh=mesh, decode_chunk=3)
    try:
        assert eng.stats()["prefix_cache_enabled"] is False  # dense fallback
        prompt = [3, 14, 15, 9, 2]
        want = _reference(params, prompt, 7)
        assert eng.generate(prompt, max_tokens=7) == want
        assert eng.generate(prompt, max_tokens=7) == want
    finally:
        eng.shutdown()


def test_openai_absent_temperature_defaults_to_sampling(oai):
    """OpenAI semantics: a body without temperature means 1.0 (sampling),
    NOT greedy — the engine must receive 1.0, and an explicit 0 must still
    reach it untouched."""
    captured = {}
    orig = oai.engine.generate

    def spy(prompt, **kw):
        captured["temperature"] = kw.get("temperature")
        return orig(prompt, **kw)

    oai.engine.generate = spy
    try:
        oai({"model": "m", "prompt": [1, 2], "max_tokens": 2})
        assert captured["temperature"] == 1.0
        oai({"model": "m", "prompt": [1, 2], "max_tokens": 2, "temperature": 0})
        assert captured["temperature"] == 0.0
    finally:
        oai.engine.generate = orig


def test_openai_rejects_unsupported_sampling_params(oai, params):
    base = {"model": "m", "prompt": [1, 2], "max_tokens": 2}
    # OpenAI-SDK defaults sail through
    ok = oai({**base, "top_p": 1.0, "n": 1, "presence_penalty": 0,
              "frequency_penalty": 0.0})
    assert ok["object"] == "text_completion"
    for extra, match in [({"top_p": 0.5}, "top_p"), ({"n": 3}, "n > 1"),
                         ({"logprobs": 5}, "logprobs"),
                         ({"logprobs": 0}, "logprobs"),  # 0 == False trap
                         ({"presence_penalty": 0.7}, "presence_penalty"),
                         ({"echo": True}, "echo")]:
        with pytest.raises(ValueError, match=match.split()[0]):
            oai({**base, **extra})


def test_openai_top_p_allowed_when_engine_configured(params):
    from ray_tpu.serve.llm import OpenAICompatLLMServer

    srv = OpenAICompatLLMServer(
        lambda: (CFG, params, _Tok()), max_batch_size=2, max_seq_len=64,
        top_p=0.9,
    )
    try:
        resp = srv({"model": "m", "prompt": [1, 2], "max_tokens": 2, "top_p": 0.9})
        assert resp["object"] == "text_completion"
        # the SDK default passes, but a DIFFERENT distribution is refused
        srv({"model": "m", "prompt": [1, 2], "max_tokens": 2, "top_p": 1.0})
        with pytest.raises(ValueError, match="top_p=0.2"):
            srv({"model": "m", "prompt": [1, 2], "max_tokens": 2, "top_p": 0.2})
    finally:
        srv.engine.shutdown()
