"""Autoscaler: demand bin-packing, scale-up on infeasible load, idle
scale-down, TPU slice atomicity.

Parity: reference resource_demand_scheduler tests + autoscaler fake-multinode
e2e (python/ray/tests/test_autoscaler_fake_multinode.py) — the monitor loop
runs for real against in-process nodes.
"""

import time

import pytest

from ray_tpu.autoscaler import (
    AutoscalerConfig,
    InProcessNodeProvider,
    Monitor,
    NodeTypeConfig,
    TPUSliceProvider,
    get_nodes_to_launch,
)


# ---------------------------------------------------------------------------
# demand scheduler (pure unit)
# ---------------------------------------------------------------------------
def test_demand_packs_into_existing_capacity():
    types = {"worker": NodeTypeConfig("worker", {"CPU": 4})}
    out = get_nodes_to_launch(types, {"worker": 1}, [{"CPU": 4.0}], [{"CPU": 2.0}, {"CPU": 2.0}])
    assert out == {}


def test_demand_launches_for_residual():
    types = {"worker": NodeTypeConfig("worker", {"CPU": 4})}
    out = get_nodes_to_launch(types, {}, [], [{"CPU": 2.0}] * 6)
    assert out == {"worker": 3}


def test_demand_respects_max_workers():
    types = {"worker": NodeTypeConfig("worker", {"CPU": 4}, max_workers=2)}
    out = get_nodes_to_launch(types, {}, [], [{"CPU": 4.0}] * 5)
    assert out == {"worker": 2}


def test_demand_min_workers_enforced():
    types = {"worker": NodeTypeConfig("worker", {"CPU": 4}, min_workers=2)}
    out = get_nodes_to_launch(types, {}, [], [])
    assert out == {"worker": 2}


def test_demand_picks_best_fitting_type():
    types = {
        "cpu": NodeTypeConfig("cpu", {"CPU": 16}),
        "tpu": NodeTypeConfig("tpu", {"CPU": 8, "TPU": 8}),
    }
    out = get_nodes_to_launch(types, {}, [], [{"TPU": 8.0}])
    assert out == {"tpu": 1}
    out = get_nodes_to_launch(types, {}, [], [{"CPU": 16.0}])
    assert out == {"cpu": 1}


def test_demand_infeasible_launches_nothing():
    types = {"worker": NodeTypeConfig("worker", {"CPU": 4})}
    out = get_nodes_to_launch(types, {}, [], [{"GPU": 1.0}])
    assert out == {}


def test_global_max_workers_cap():
    types = {"worker": NodeTypeConfig("worker", {"CPU": 1})}
    out = get_nodes_to_launch(types, {}, [], [{"CPU": 1.0}] * 10, max_total_workers=3)
    assert out == {"worker": 3}


# ---------------------------------------------------------------------------
# e2e against the live fabric
# ---------------------------------------------------------------------------
def test_scale_up_makes_infeasible_task_runnable(ray_start_cluster):
    rt, cluster = ray_start_cluster  # head has 2 CPU
    config = AutoscalerConfig(
        node_types={"big": NodeTypeConfig("big", {"CPU": 8})},
        idle_timeout_s=3600,
        update_interval_s=0.1,
    )
    monitor = Monitor(cluster, config).start()
    try:

        @rt.remote(num_cpus=8)
        def needs_big():
            return "ran"

        assert rt.get(needs_big.remote(), timeout=20) == "ran"
        assert monitor.autoscaler.num_launches >= 1
    finally:
        monitor.stop()


def test_idle_nodes_terminate(ray_start_cluster):
    rt, cluster = ray_start_cluster
    provider = InProcessNodeProvider(cluster)
    config = AutoscalerConfig(
        node_types={"w": NodeTypeConfig("w", {"CPU": 4})},
        idle_timeout_s=0.3,
        update_interval_s=0.05,
    )
    monitor = Monitor(cluster, config, provider=provider).start()
    try:

        @rt.remote(num_cpus=4)
        def f():
            return 1

        assert rt.get(f.remote(), timeout=20) == 1
        # generous deadline: on a contended box the monitor thread can
        # starve for tens of seconds before its idle sweep runs (observed
        # as a full-suite-only flake at 10s)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if not provider.non_terminated_nodes() and monitor.autoscaler.num_terminations >= 1:
                break
            time.sleep(0.1)
        assert not provider.non_terminated_nodes()
        assert monitor.autoscaler.num_terminations >= 1
    finally:
        monitor.stop()


def test_min_workers_never_terminated(ray_start_cluster):
    rt, cluster = ray_start_cluster
    provider = InProcessNodeProvider(cluster)
    config = AutoscalerConfig(
        node_types={"w": NodeTypeConfig("w", {"CPU": 4}, min_workers=1)},
        idle_timeout_s=0.1,
        update_interval_s=0.05,
    )
    monitor = Monitor(cluster, config, provider=provider).start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not provider.non_terminated_nodes():
            time.sleep(0.05)
        assert len(provider.non_terminated_nodes()) == 1
        time.sleep(0.5)  # well past idle_timeout
        assert len(provider.non_terminated_nodes()) == 1
    finally:
        monitor.stop()


def test_pending_placement_group_triggers_scale_up(ray_start_cluster):
    rt, cluster = ray_start_cluster
    from ray_tpu.core.ids import PlacementGroupID
    from ray_tpu.core.resources import ResourceSet
    from ray_tpu.runtime.placement import (
        PlacementGroupInfo,
        PlacementGroupState,
        PlacementStrategy,
    )

    info = PlacementGroupInfo(
        PlacementGroupID.from_random(),
        [ResourceSet({"CPU": 8.0})],
        PlacementStrategy.PACK,
    )
    cluster.control.placement_groups.create(info)
    assert info.state is PlacementGroupState.PENDING
    assert {"CPU": 8.0} in cluster.pending_resource_demands()

    config = AutoscalerConfig(
        node_types={"big": NodeTypeConfig("big", {"CPU": 8})},
        idle_timeout_s=3600,
        update_interval_s=0.1,
    )
    monitor = Monitor(cluster, config).start()
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and info.state is PlacementGroupState.PENDING:
            time.sleep(0.1)
        assert info.state is PlacementGroupState.CREATED
    finally:
        monitor.stop()


# ---------------------------------------------------------------------------
# TPU slices
# ---------------------------------------------------------------------------
def test_tpu_slice_created_atomically(ray_start_cluster):
    rt, cluster = ray_start_cluster
    provider = TPUSliceProvider(cluster)
    ntype = TPUSliceProvider.node_type_for("v5e-16")
    [slice_id] = provider.create_nodes(ntype, 1)
    members = provider.slice_members(slice_id)
    assert len(members) == 2  # v5e-16 = 2 hosts x 8 chips
    per_host = [
        n.pool.total.to_dict()
        for n in cluster.nodes.values()
        if n.node_id.hex() in members
    ]
    assert all(r.get("TPU") == 8.0 for r in per_host)
    assert sum(1 for r in per_host if "TPU-v5e-16-head" in r) == 1

    provider.terminate_node(slice_id)
    alive = {nid.hex() for nid, n in cluster.nodes.items() if not n.dead}
    assert not (alive & set(members))  # no partial slice survives


def test_multihost_slice_gang_demand_scales_one_slice(ray_start_cluster):
    """Gang demand targets the slice head token; the autoscaler must launch
    exactly one slice, not loop on an unsatisfiable aggregate chip count."""
    rt, cluster = ray_start_cluster
    provider = TPUSliceProvider(cluster)
    config = AutoscalerConfig(
        node_types={"v5e-16": TPUSliceProvider.node_type_for("v5e-16", max_workers=4)},
        idle_timeout_s=3600,
        update_interval_s=0.1,
    )
    monitor = Monitor(cluster, config, provider=provider).start()
    try:

        @rt.remote(resources={"TPU-v5e-16-head": 1})
        def gang_leader():
            return "leader"

        assert rt.get(gang_leader.remote(), timeout=20) == "leader"
        time.sleep(0.5)  # give the loop a chance to over-launch (it must not)
        slices = [t for t in provider.non_terminated_nodes().values() if t == "v5e-16"]
        assert len(slices) == 1
    finally:
        monitor.stop()


def test_tpu_autoscaler_scales_slice_for_tpu_demand(ray_start_cluster):
    rt, cluster = ray_start_cluster
    provider = TPUSliceProvider(cluster)
    config = AutoscalerConfig(
        node_types={"v5e-8": TPUSliceProvider.node_type_for("v5e-8")},
        idle_timeout_s=3600,
        update_interval_s=0.1,
    )
    monitor = Monitor(cluster, config, provider=provider).start()
    try:

        @rt.remote(resources={"TPU": 8})
        def on_tpu():
            return "tpu"

        assert rt.get(on_tpu.remote(), timeout=20) == "tpu"
        assert any(t == "v5e-8" for t in provider.non_terminated_nodes().values())
    finally:
        monitor.stop()


# ---------------------------------------------------------------------------
# Autoscaler v2: instance FSM + declarative reconciliation
# ---------------------------------------------------------------------------
def test_v2_instance_fsm_validates_transitions():
    from ray_tpu.autoscaler.v2 import (
        ALLOCATED,
        QUEUED,
        REQUESTED,
        RUNNING,
        TERMINATED,
        InstanceManager,
        InvalidTransitionError,
    )

    im = InstanceManager()
    inst = im.create_instance("worker")
    assert inst.state == QUEUED
    im.transition(inst.instance_id, REQUESTED)
    im.transition(inst.instance_id, ALLOCATED, provider_node_id="n1")
    im.transition(inst.instance_id, RUNNING)
    with pytest.raises(InvalidTransitionError):
        im.transition(inst.instance_id, QUEUED)
    im.transition(inst.instance_id, TERMINATED)
    got = im.get(inst.instance_id)
    assert [h[2] for h in got.history] == [REQUESTED, ALLOCATED, RUNNING, TERMINATED]


def test_v2_reconciler_scales_up_and_marks_running(ray_start_cluster):
    from ray_tpu.autoscaler.v2 import RUNNING, AutoscalerV2, AutoscalerV2Config

    rt, cluster = ray_start_cluster
    provider = InProcessNodeProvider(cluster)
    asv2 = AutoscalerV2(
        cluster,
        provider,
        AutoscalerV2Config(
            node_types={"big": NodeTypeConfig("big", {"CPU": 8})}, idle_timeout_s=3600
        ),
    )

    @rt.remote(num_cpus=8)
    def needs_big():
        return "ran"

    ref = needs_big.remote()
    # tick until the demand is served by a launched + running instance
    deadline = time.time() + 20
    while time.time() < deadline:
        asv2.reconcile()
        running = asv2.im.instances({RUNNING})
        if running:
            break
        time.sleep(0.05)
    assert rt.get(ref, timeout=20) == "ran"
    status = asv2.cluster_status()
    assert status["instances_by_state"].get(RUNNING, 0) >= 1


def test_v2_launch_failure_requeues_then_terminates(ray_start_cluster):
    from ray_tpu.autoscaler.v2 import (
        QUEUED,
        TERMINATED,
        AutoscalerV2,
        AutoscalerV2Config,
    )

    rt, cluster = ray_start_cluster

    class FailingProvider(InProcessNodeProvider):
        def create_nodes(self, node_type, count):
            raise RuntimeError("cloud quota exceeded")

    provider = FailingProvider(cluster)
    asv2 = AutoscalerV2(
        cluster,
        provider,
        AutoscalerV2Config(
            node_types={"w": NodeTypeConfig("w", {"CPU": 8})},
            max_launch_retries=2,
        ),
    )

    @rt.remote(num_cpus=8)
    def infeasible():
        return 1

    ref = infeasible.remote()
    for _ in range(10):
        asv2.reconcile()
    insts = asv2.im.instances()
    assert insts, "reconciler should have queued instances for the demand"
    # every attempt failed; after max retries instances must terminate,
    # and the FSM history must show the QUEUED->...->FAILED cycles
    assert any(i.state == TERMINATED for i in insts) or any(
        i.launch_attempt >= 2 for i in insts
    )
    del ref


def test_v2_idle_scale_down(ray_start_cluster):
    from ray_tpu.autoscaler.v2 import RUNNING, TERMINATED, AutoscalerV2, AutoscalerV2Config

    rt, cluster = ray_start_cluster
    provider = InProcessNodeProvider(cluster)
    asv2 = AutoscalerV2(
        cluster,
        provider,
        AutoscalerV2Config(
            node_types={"w": NodeTypeConfig("w", {"CPU": 4})}, idle_timeout_s=0.2
        ),
    )

    @rt.remote(num_cpus=4)
    def f():
        return 1

    ref = f.remote()
    deadline = time.time() + 20
    while time.time() < deadline:
        asv2.reconcile()
        if asv2.im.instances({RUNNING}):
            break
        time.sleep(0.05)
    assert rt.get(ref, timeout=20) == 1
    # node idles; keep reconciling past the timeout
    deadline = time.time() + 10
    while time.time() < deadline:
        asv2.reconcile()
        if asv2.im.instances({TERMINATED}):
            break
        time.sleep(0.05)
    assert asv2.im.instances({TERMINATED})


def test_request_resources_floor_and_clear(ray_start_cluster):
    """sdk.request_resources scales the cluster up with NO pending tasks,
    holds idle nodes at the floor, and releases them when cleared (parity:
    ray.autoscaler.sdk.request_resources replace semantics)."""
    from ray_tpu.autoscaler import sdk

    rt, cluster = ray_start_cluster  # head has 2 CPU
    provider = InProcessNodeProvider(cluster)
    config = AutoscalerConfig(
        node_types={"w": NodeTypeConfig("w", {"CPU": 4})},
        idle_timeout_s=0.3,
        update_interval_s=0.05,
    )
    monitor = Monitor(cluster, config, provider=provider).start()
    try:
        # floor: 6 one-CPU bundles; head covers 2, so >=1 worker must launch
        sdk.request_resources(num_cpus=6)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if len(provider.non_terminated_nodes()) >= 1:
                break
            time.sleep(0.05)
        assert len(provider.non_terminated_nodes()) >= 1
        # the floor pins the idle worker well past idle_timeout_s
        time.sleep(1.0)
        assert len(provider.non_terminated_nodes()) >= 1
        # exact-shape bundles work too and REPLACE the old request
        sdk.request_resources(bundles=[{"CPU": 2.0}])
        assert cluster.resource_requests() == [{"CPU": 2.0}]
        # the floor compares against TOTAL capacity, so an already-large
        # cluster has no unmet residual (no over-provisioning)
        assert cluster.unmet_resource_requests() == []
        # clearing the floor lets idle scale-down reclaim the node
        sdk.request_resources()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if not provider.non_terminated_nodes():
                break
            time.sleep(0.1)
        assert not provider.non_terminated_nodes()
    finally:
        monitor.stop()


def test_request_resources_satisfied_by_busy_capacity(ray_start_cluster):
    """A floor the cluster's TOTAL capacity already holds launches nothing,
    even when that capacity is fully occupied by running tasks (reference
    semantics: request_resources is a floor, not extra demand)."""
    from ray_tpu.autoscaler import sdk

    rt, cluster = ray_start_cluster  # head has 2 CPU

    @rt.remote(num_cpus=1)
    def hog(sec):
        time.sleep(sec)
        return 1

    refs = [hog.remote(2.0) for _ in range(2)]  # occupy both CPUs
    sdk.request_resources(num_cpus=2)
    try:
        assert cluster.unmet_resource_requests() == []
        provider = InProcessNodeProvider(cluster)
        config = AutoscalerConfig(
            node_types={"w": NodeTypeConfig("w", {"CPU": 4})},
            idle_timeout_s=3600,
            update_interval_s=3600,  # drive updates by hand
        )
        from ray_tpu.autoscaler import StandardAutoscaler

        scaler = StandardAutoscaler(cluster, provider, config)
        scaler.update()
        assert scaler.num_launches == 0 and not provider.non_terminated_nodes()
        assert rt.get(refs, timeout=30) == [1, 1]
    finally:
        sdk.request_resources()


def test_request_resources_floor_releases_extra_idle_nodes(ray_start_cluster):
    """A small floor pins only the capacity it needs: extra idle workers
    still scale down (the floor is bin-packed, not every-node-retained)."""
    from ray_tpu.autoscaler import sdk

    rt, cluster = ray_start_cluster  # head: 2 CPU
    provider = InProcessNodeProvider(cluster)
    config = AutoscalerConfig(
        node_types={"w": NodeTypeConfig("w", {"CPU": 4})},
        idle_timeout_s=0.2,
        update_interval_s=3600,
    )
    from ray_tpu.autoscaler import StandardAutoscaler

    scaler = StandardAutoscaler(cluster, provider, config)
    # hand-provision two idle workers
    provider.create_nodes(config.node_types["w"], 2)
    # floor: one 4-CPU bundle -> exactly one worker must survive
    sdk.request_resources(bundles=[{"CPU": 4.0}])
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            scaler.update()
            if len(provider.non_terminated_nodes()) == 1:
                break
            time.sleep(0.1)
        assert len(provider.non_terminated_nodes()) == 1
        # ... and it stays: the floor blocks the last one
        time.sleep(0.5)
        scaler.update()
        assert len(provider.non_terminated_nodes()) == 1
    finally:
        sdk.request_resources()
