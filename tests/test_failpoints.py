"""Failpoint fabric unit + runtime-integration tests.

Covers the determinism contract (decisions are a pure function of
``(seed, name, hit index)``), spec parsing, the disarmed fast path, the
observability wiring (``chaos_faults_injected_total`` metric + ``fault::``
trace events in the timeline), and each instrumented site's recovery path.
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu.runtime import failpoints


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


# --------------------------------------------------------------------------
# spec parsing
# --------------------------------------------------------------------------
def test_parse_spec_grammar():
    spec = failpoints.parse_spec(
        "data_plane.send_frame=drop(0.05); rpc.call=delay(0.2, 0.5),"
        "worker_pool.spawn=kill;scheduler.dispatch=raise"
    )
    assert spec["data_plane.send_frame"] == {"action": "drop", "prob": 0.05, "delay_s": 0.0}
    assert spec["rpc.call"] == {"action": "delay", "prob": 0.5, "delay_s": 0.2}
    assert spec["worker_pool.spawn"] == {"action": "kill", "prob": 1.0, "delay_s": 0.0}
    assert spec["scheduler.dispatch"] == {"action": "raise", "prob": 1.0, "delay_s": 0.0}


@pytest.mark.parametrize(
    "bad",
    [
        "no_equals_sign",
        "a=explode",              # unknown action
        "a=raise(2.0)",           # p out of range
        "a=delay",                # delay needs seconds
        "a=drop(0.5",             # unclosed paren
        "a=raise(nan_is_not_p_)", # unparsable arg
    ],
)
def test_parse_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        failpoints.parse_spec(bad)


# --------------------------------------------------------------------------
# determinism
# --------------------------------------------------------------------------
def test_decision_is_pure_and_seed_sensitive():
    a = [failpoints._decision(42, "fp.x", i) for i in range(200)]
    b = [failpoints._decision(42, "fp.x", i) for i in range(200)]
    assert a == b
    assert a != [failpoints._decision(43, "fp.x", i) for i in range(200)]
    assert a != [failpoints._decision(42, "fp.y", i) for i in range(200)]
    assert all(0.0 <= v < 1.0 for v in a)


def test_fault_log_reproducible_across_rearm():
    def one_run():
        failpoints.reset()
        failpoints.arm("t.fp=drop(0.3)", seed=9)
        hits = []
        for _ in range(100):
            hits.append(failpoints.fp("t.fp"))
        log = failpoints.fault_log()
        return hits, log

    hits1, log1 = one_run()
    hits2, log2 = one_run()
    assert hits1 == hits2
    assert log1 == log2
    assert 0 < len(log1) < 100  # p=0.3 injected some, not all
    # the log is sorted by (fp, hit) and entries carry the action
    assert log1 == sorted(log1, key=lambda e: (e["fp"], e["hit"]))
    assert all(e["action"] == "drop" for e in log1)


def test_disarmed_is_noop_and_cheap():
    assert failpoints.ARMED is False
    assert failpoints.fp("anything.at.all") is None
    # an armed registry only fires for registered names
    failpoints.arm("some.fp=raise", seed=0)
    assert failpoints.fp("other.fp") is None
    failpoints.disarm()
    assert failpoints.ARMED is False
    assert failpoints.fault_log() == []  # full disarm clears the log


def test_actions_raise_delay_and_passthrough():
    failpoints.arm("r=raise;d=delay(0.05);x=drop;k=kill;p=partition", seed=1)
    with pytest.raises(failpoints.FailpointInjected):
        failpoints.fp("r")
    t0 = time.perf_counter()
    assert failpoints.fp("d") is None  # delay handled internally
    assert time.perf_counter() - t0 >= 0.045
    assert failpoints.fp("x") == "drop"
    assert failpoints.fp("k") == "kill"
    assert failpoints.fp("p") == "partition"


def test_single_name_disarm_preserves_log_and_counters():
    """Closing a partition window disarms ONE name — the run's fault log
    must survive, and a re-arm of the same name must resume its hit index
    stream (indices never restart mid-run)."""
    failpoints.arm("a=drop;b=drop", seed=0)
    failpoints.fp("a")
    failpoints.fp("a")
    failpoints.disarm("a")
    assert failpoints.fault_log(), "single-name disarm must not clear the log"
    failpoints.disarm("b")  # registry now empty — log still survives
    assert failpoints.fault_log()
    failpoints.arm("a=drop")
    failpoints.fp("a")
    hits = [e["hit"] for e in failpoints.fault_log() if e["fp"] == "a"]
    assert hits == [0, 1, 2], hits  # resumed, not restarted
    failpoints.disarm()  # full disarm resets everything
    assert failpoints.fault_log() == []


def test_metric_family_counts_injections():
    from ray_tpu.observability import metrics

    failpoints.arm("m.fp=drop", seed=0)
    for _ in range(3):
        failpoints.fp("m.fp")
    text = metrics.global_registry().render_prometheus()
    line = [
        ln for ln in text.splitlines()
        if ln.startswith("ray_tpu_chaos_faults_injected_total") and 'failpoint="m.fp"' in ln
    ]
    assert line and float(line[0].rsplit(" ", 1)[1]) >= 3


# --------------------------------------------------------------------------
# runtime integration per instrumented site
# --------------------------------------------------------------------------
def test_dispatch_fault_retries_to_success(ray_start_regular):
    @rt.remote(max_retries=10)
    def bump(x):
        return x + 1

    failpoints.arm("scheduler.dispatch=raise(0.5)", seed=3)
    assert rt.get([bump.remote(i) for i in range(20)], timeout=60) == [
        i + 1 for i in range(20)
    ]
    assert len(failpoints.fault_log()) > 0


def test_dispatch_fault_exhausts_retries_loudly(ray_start_regular):
    from ray_tpu.exceptions import WorkerCrashedError

    @rt.remote(max_retries=1)
    def bump(x):
        return x + 1

    failpoints.arm("scheduler.dispatch=raise(1.0)", seed=3)
    with pytest.raises(WorkerCrashedError, match="scheduler.dispatch"):
        rt.get(bump.remote(1), timeout=30)


def test_put_fault_raises_loudly(ray_start_regular):
    ok_ref = rt.put("before")
    failpoints.arm("object_store.put=raise", seed=0)
    with pytest.raises(failpoints.FailpointInjected):
        rt.put("during")
    failpoints.disarm()
    assert rt.get(ok_ref) == "before"
    assert rt.get(rt.put("after")) == "after"


def test_worker_spawn_fault_fanout_still_completes(ray_start_regular):
    @rt.remote(execution="process")
    def pid_task(x):
        import os

        return (os.getpid(), x)

    # warm one worker so recovery always has a drain path, then fault spawns
    rt.get(pid_task.remote(-1))
    failpoints.arm("worker_pool.spawn=raise(0.6)", seed=5)
    out = rt.get([pid_task.remote(i) for i in range(12)], timeout=120)
    assert [x for _pid, x in out] == list(range(12))


def test_fault_events_visible_in_timeline(ray_start_regular):
    @rt.remote(max_retries=8)
    def bump(x):
        return x + 1

    failpoints.arm("scheduler.dispatch=raise(0.5)", seed=11)
    rt.get([bump.remote(i) for i in range(10)], timeout=60)
    failpoints.disarm()
    events = rt.timeline()
    fault_events = [e for e in events if str(e.get("name", "")).startswith("fault::")]
    assert fault_events, "injected faults must surface as fault:: trace events"
    ev = fault_events[0]
    assert ev["attrs"]["failpoint"] == "scheduler.dispatch"
    assert ev["attrs"]["action"] == "raise"
    # and the chrome-trace rendering keeps them (rt timeline --tracing path)
    from ray_tpu.observability.timeline import chrome_trace

    slices = [s for s in chrome_trace(events) if s["name"].startswith("fault::")]
    assert slices


def test_shutdown_disarms_session_failpoints():
    rt.init(num_cpus=2, _system_config={"failpoints": "t.cfg=drop", "failpoint_seed": 4})
    try:
        assert failpoints.configured("t.cfg") == {
            "action": "drop", "prob": 1.0, "delay_s": 0.0,
        }
        assert failpoints.fp("t.cfg") == "drop"
    finally:
        rt.shutdown()
    assert failpoints.ARMED is False
