"""Model-layer tests: forward shapes, training convergence, sharded train
step over the virtual 8-device mesh (dp/sp/tp + ep), pipeline dryrun."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import (
    MLPConfig,
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    mlp_apply,
    mlp_init,
)

TINY = TransformerConfig(vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64, attention="dense")


def test_forward_shape():
    params = init_params(TINY, jax.random.key(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = forward(TINY, params, tokens)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_init_is_not_a_confident_token_copier():
    """Tied-embedding init regression (a std-1 embedding made diag logits
    ~|E_t|^2 ~ d): init logits must be O(1), random-token loss must sit
    near the uniform baseline ln(V) (the bug measured ~26), and
    repeated-token loss must not be ~zero (the bug measured 8e-6 — a
    CONFIDENT copier).  A mild copy preference in the argmax is inherent
    to tied embeddings + residual streams and is fine."""
    from ray_tpu.models.transformer import loss_fn

    params = init_params(TINY, jax.random.key(0))
    tokens = jnp.asarray(np.random.default_rng(1).integers(0, 128, (2, 32)), jnp.int32)
    logits = np.asarray(forward(TINY, params, tokens))
    # O(1) logits at init (the copier produced ~d-scale diagonals)
    assert np.abs(logits).max() < 25.0, np.abs(logits).max()
    loss = float(loss_fn(TINY, params, tokens))
    assert 0.5 * np.log(128) < loss < 2.5 * np.log(128), loss
    # repeated tokens are predictable-but-not-free: a confident copier
    # scores ~0 here
    ones_loss = float(loss_fn(TINY, params, jnp.ones((2, 32), jnp.int32)))
    assert ones_loss > 0.05, f"near-zero repeated-token loss {ones_loss} (copier init)"


def test_loss_decreases():
    init_state, step = make_train_step(TINY, learning_rate=1e-2)
    state = init_state(jax.random.key(0))
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 128, (4, 17)), jnp.int32)
    first = None
    for _ in range(10):
        state, loss = step(state, tokens)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_moe_forward():
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        num_experts=4, expert_top_k=2, attention="dense",
    )
    params = init_params(cfg, jax.random.key(1))
    logits = forward(cfg, params, jnp.zeros((2, 8), jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_train_step():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        num_experts=4, attention="dense",
    )
    with mesh:
        init_state, step = make_train_step(cfg, mesh=mesh, ep="dp")
        state = init_state(jax.random.key(0))
        tokens = step.shard_batch(
            jnp.asarray(np.random.default_rng(0).integers(0, 128, (4, 16)), jnp.int32)
        )
        state, loss = step(state, tokens)
        assert np.isfinite(float(loss))
        # param shardings actually landed on the tp axis
        wq = state["params"]["layers"]["wq"]
        assert "tp" in str(wq.sharding.spec)


def test_sharded_matches_single_device():
    """Same seed/batch: the sharded loss must equal the unsharded loss."""
    from jax.sharding import Mesh

    cfg = TINY
    tokens = jnp.asarray(np.random.default_rng(2).integers(0, 128, (4, 16)), jnp.int32)
    params = init_params(cfg, jax.random.key(3))
    ref = float(loss_fn(cfg, params, tokens))
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
    with mesh:
        from ray_tpu.models.transformer import shard_params
        from jax.sharding import NamedSharding, PartitionSpec as P

        sp = shard_params(params, mesh, cfg)
        toks = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
        got = float(jax.jit(lambda p, t: loss_fn(cfg, p, t))(sp, toks))
    # bf16 matmuls: collective reduction order differs across shardings
    assert abs(got - ref) / abs(ref) < 1e-3


def test_mlp():
    cfg = MLPConfig(in_dim=8, hidden=16, depth=2, out_dim=4)
    params = mlp_init(cfg, jax.random.key(0))
    out = mlp_apply(params, jnp.ones((3, 8)))
    assert out.shape == (3, 4)


@pytest.mark.full
def test_graft_entry_hooks():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[-1] == 2048
    g.dryrun_multichip(8)


@pytest.mark.full
def test_ring_attention_mode_matches_dense():
    """attention="ring" (sp-sharded ring attention in the model) must agree
    with the dense einsum path on loss and gradients."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from ray_tpu.models.transformer import TransformerConfig, make_train_step

    devices = np.array(jax.devices()[:4]).reshape(2, 2, 1)
    mesh = Mesh(devices, ("dp", "sp", "tp"))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (4, 32)), jnp.int32
    )

    losses = {}
    params_after = {}
    for mode in ("dense", "ring"):
        cfg = TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
            max_seq_len=64, attention=mode, remat=False,
        )
        with mesh:
            init_state, step = make_train_step(cfg, mesh=mesh)
            state = init_state(jax.random.key(0))
            state, loss = step(state, step.shard_batch(tokens))
            losses[mode] = float(loss)
            params_after[mode] = jax.tree.map(np.asarray, state["params"])
    assert losses["ring"] == pytest.approx(losses["dense"], rel=1e-3)
    # the backward pass must agree too, not just the forward loss
    flat_d = jax.tree.leaves(params_after["dense"])
    flat_r = jax.tree.leaves(params_after["ring"])
    for a, b in zip(flat_d, flat_r):
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-3)


# --------------------------------------------------------------------------
# ViT (image model family)
# --------------------------------------------------------------------------
def _vit_tiny():
    from ray_tpu.models import ViTConfig

    return ViTConfig(
        image_size=16, patch_size=4, channels=3, num_classes=10,
        d_model=32, n_layers=2, n_heads=2, d_ff=64, attention="dense", remat=False,
    )


def test_vit_forward_shape_and_patchify():
    from ray_tpu.models import init_vit_params, patchify, vit_forward

    cfg = _vit_tiny()
    params = init_vit_params(cfg, jax.random.key(0))
    images = jnp.asarray(np.random.default_rng(0).standard_normal((2, 16, 16, 3)), jnp.float32)
    patches = patchify(cfg, images)
    assert patches.shape == (2, 16, 48)
    # patchify must preserve pixel content (first patch == top-left block)
    first = np.asarray(images[0, :4, :4, :]).reshape(-1)
    np.testing.assert_allclose(np.asarray(patches[0, 0]), first, rtol=1e-6)
    logits = vit_forward(cfg, params, images)
    assert logits.shape == (2, 10) and logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_vit_trains():
    from ray_tpu.models import make_vit_train_step

    cfg = _vit_tiny()
    init_state, step = make_vit_train_step(cfg, learning_rate=1e-2)
    state = init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.standard_normal((8, 16, 16, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
    first = None
    for _ in range(30):
        state, loss = step(state, images, labels)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.5  # memorizes the tiny batch


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 virtual devices")
def test_vit_sharded_train_step():
    from jax.sharding import Mesh

    from ray_tpu.models import make_vit_train_step

    cfg = _vit_tiny()
    devices = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devices, ("dp", "tp"))
    with mesh:
        init_state, step = make_vit_train_step(cfg, mesh=mesh)
        state = init_state(jax.random.key(0))
        rng = np.random.default_rng(0)
        images, labels = step.shard_batch(
            jnp.asarray(rng.standard_normal((4, 16, 16, 3)), jnp.float32),
            jnp.asarray(rng.integers(0, 10, (4,)), jnp.int32),
        )
        state, loss = step(state, images, labels)
        assert np.isfinite(float(loss))


def test_shard_params_typo_axis_raises():
    from jax.sharding import Mesh

    from ray_tpu.models.transformer import shard_params

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64)
    params = init_params(cfg, jax.random.key(0))
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    with pytest.raises(ValueError, match="not a mesh axis"):
        shard_params(params, mesh, cfg, tp="model")  # typo'd axis name


def test_shard_params_moe_on_dp_less_mesh():
    """Implicit ep->dp default must not raise on a tp-only mesh."""
    from jax.sharding import Mesh

    from ray_tpu.models.transformer import shard_params

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        num_experts=2, expert_top_k=1,
    )
    params = init_params(cfg, jax.random.key(0))
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    sharded = shard_params(params, mesh, cfg, tp="tp")
    assert sharded["layers"]["we1"].shape == params["layers"]["we1"].shape


def test_moe_capacity_matches_dense_when_ample():
    """With capacity >= every assignment, the GShard dispatch equals the
    dense-dispatch formulation bit-for-bit-ish."""
    base = dict(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        num_experts=4, expert_top_k=2, attention="dense", dtype=jnp.float32,
    )
    dense_cfg = TransformerConfig(**base)
    cap_cfg = TransformerConfig(**base, moe_capacity_factor=8.0)  # no drops
    params = init_params(dense_cfg, jax.random.key(0))
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 64, (2, 12)), jnp.int32)
    a = forward(dense_cfg, params, toks)
    b = forward(cap_cfg, params, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_moe_capacity_tight_still_finite_and_trains():
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        num_experts=4, expert_top_k=2, attention="dense",
        moe_capacity_factor=1.0,  # tight: some tokens drop
    )
    init_state, step = make_train_step(cfg, learning_rate=1e-2)
    state = init_state(jax.random.key(2))
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 64, (4, 13)), jnp.int32)
    first = None
    for _ in range(8):
        state, loss = step(state, toks)
        first = first if first is not None else float(loss)
    assert np.isfinite(float(loss)) and float(loss) < first


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_moe_capacity_sharded_train_step():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        num_experts=4, expert_top_k=2, attention="dense",
        moe_capacity_factor=2.0,
    )
    with mesh:
        init_state, step = make_train_step(cfg, mesh=mesh, ep="dp")
        state = init_state(jax.random.key(0))
        toks = step.shard_batch(
            jnp.asarray(np.random.default_rng(0).integers(0, 128, (4, 16)), jnp.int32)
        )
        state, loss = step(state, toks)
        assert np.isfinite(float(loss))


@pytest.mark.full
def test_unrolled_and_dots_remat_match_scan():
    """The headline TPU bench runs remat="dots" + scan_layers=False; this
    CPU parity check pins that exact configuration to the default scan
    path: identical logits and loss gradients."""
    import dataclasses

    tokens = jnp.asarray(np.random.default_rng(3).integers(0, 128, (2, 16)), jnp.int32)
    params = init_params(TINY, jax.random.key(1))
    ref_logits = np.asarray(forward(TINY, params, tokens))
    ref_grad = jax.grad(lambda p: loss_fn(TINY, p, tokens))(params)

    # bf16 activations: scan vs unrolled reassociates fusions, so agreement
    # is bounded by bf16 rounding (~4e-3 relative), not float32 epsilon
    for remat, scan in ((False, False), ("dots", False), ("dots", True), (True, False)):
        cfg = dataclasses.replace(TINY, remat=remat, scan_layers=scan)
        np.testing.assert_allclose(
            np.asarray(forward(cfg, params, tokens)), ref_logits, rtol=0.05, atol=0.02
        )
        g = jax.grad(lambda p: loss_fn(cfg, p, tokens))(params)
        for a, b in zip(jax.tree_util.tree_leaves(ref_grad), jax.tree_util.tree_leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0.05, atol=0.02)

    with pytest.raises(ValueError):
        dataclasses.replace(TINY, remat="Dots")  # typo must not silently full-remat
