"""Thin-client mode: remote tasks/actors/objects over a real socket.

Parity: python/ray/util/client tests — a client session drives a server-side
driver; refs are session-scoped; errors propagate across the wire.
"""

import threading

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.util.client import ClientServer, connect


@pytest.fixture
def client_server(ray_start_regular):
    server = ClientServer(port=0).start()
    yield server
    server.stop()


def test_task_roundtrip(client_server):
    with connect(client_server.address) as ctx:

        def add(a, b):
            return a + b

        ref = ctx.remote(add).remote(2, 3)
        assert ctx.get(ref) == 5


def test_ray_scheme_address(client_server):
    with connect(f"ray://{client_server.address}") as ctx:
        assert ctx.get(ctx.put("hello")) == "hello"


def test_put_get_ndarray(client_server):
    with connect(client_server.address) as ctx:
        arr = np.arange(100_000, dtype=np.float32)
        ref = ctx.put(arr)
        np.testing.assert_array_equal(ctx.get(ref), arr)


def test_ref_passing_between_tasks(client_server):
    with connect(client_server.address) as ctx:

        def double(x):
            return x * 2

        d = ctx.remote(double)
        ref = d.remote(d.remote(10))  # ClientObjectRef as an arg
        assert ctx.get(ref) == 40


def test_error_propagates(client_server):
    with connect(client_server.address) as ctx:

        def boom():
            raise ValueError("kaboom")

        with pytest.raises(Exception, match="kaboom"):
            ctx.get(ctx.remote(boom).remote())


def test_actor_lifecycle(client_server):
    with connect(client_server.address) as ctx:

        class Counter:
            def __init__(self, start=0):
                self.n = start

            def incr(self, by=1):
                self.n += by
                return self.n

        counter = ctx.remote(Counter).remote(10)
        assert ctx.get(counter.incr.remote()) == 11
        assert ctx.get(counter.incr.remote(5)) == 16
        ctx.kill(counter)


def test_wait(client_server):
    with connect(client_server.address) as ctx:
        import time as _t

        def slow():
            _t.sleep(5)
            return "slow"

        def fast():
            return "fast"

        refs = [ctx.remote(slow).remote(), ctx.remote(fast).remote()]
        ready, not_ready = ctx.wait(refs, num_returns=1, timeout=10)
        assert len(ready) == 1 and len(not_ready) == 1
        assert ctx.get(ready[0]) == "fast"


def test_concurrent_gets_multiplexed(client_server):
    """Two threads block in get concurrently on one connection."""
    with connect(client_server.address) as ctx:
        import time as _t

        def delayed(x):
            _t.sleep(0.3)
            return x

        refs = [ctx.remote(delayed).remote(i) for i in range(4)]
        out = {}

        def getter(i, r):
            out[i] = ctx.get(r)

        threads = [threading.Thread(target=getter, args=(i, r)) for i, r in enumerate(refs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert out == {0: 0, 1: 1, 2: 2, 3: 3}


def test_cluster_info(client_server):
    with connect(client_server.address) as ctx:
        assert ctx.cluster_resources().get("CPU", 0) > 0
        assert len(ctx.nodes()) >= 1


def test_options_resources(client_server):
    with connect(client_server.address) as ctx:

        def rsrc():
            return "ran"

        f = ctx.remote(rsrc).options(num_cpus=2)
        assert ctx.get(f.remote()) == "ran"


def test_two_sessions_isolated(client_server):
    with connect(client_server.address) as a, connect(client_server.address) as b:
        ra = a.put("A")
        rb = b.put("B")
        assert a.get(ra) == "A"
        assert b.get(rb) == "B"
        # a ref id from session a is unknown to session b
        from ray_tpu.util.client.worker import ClientObjectRef

        alien = ClientObjectRef(ra._id, b)
        with pytest.raises(Exception):
            b.get(alien)
        alien._ctx = None  # don't send a bogus release on GC
