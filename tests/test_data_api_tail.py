"""Dataset public-API tail (parity: python/ray/data/dataset.py surface —
the methods beyond the core transform/consume set: sampling, indexed
splits, refs-based consumption, lineage serialization, random-access
serving, image/webdataset writes, and the gated external-frame interop).
"""

import os

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data as rd


@pytest.fixture(scope="module", autouse=True)
def runtime():
    rt.init(num_cpus=4, ignore_reinit_error=True)
    yield
    rt.shutdown()


def test_metadata_surface():
    ds = rd.range(10)
    assert ds.names() == ds.columns()
    assert ds.types() is not None
    assert isinstance(ds.context(), rd.DataContext)
    copy = ds.copy()
    assert copy is not ds and copy.count() == 10


def test_input_files(tmp_path):
    p = tmp_path / "files"
    rd.range(10).write_csv(str(p))
    ds = rd.read_csv(str(p))
    files = ds.input_files()
    assert files and all(f.endswith(".csv") for f in files)
    # a plan over in-memory items has no input files
    assert rd.from_items([1, 2]).input_files() == []


def test_random_sample():
    ds = rd.range(2000)
    n = rd.Dataset.count(ds.random_sample(0.5, seed=7))
    assert 700 < n < 1300
    # deterministic with a seed: same plan, same sample
    n2 = ds.random_sample(0.5, seed=7).count()
    assert n == n2
    with pytest.raises(ValueError):
        ds.random_sample(1.5)


def test_randomize_block_order():
    ds = rd.range(100, parallelism=10)
    shuffled = ds.randomize_block_order(seed=3)
    # same rows, plausibly different order
    assert sorted(r["id"] for r in shuffled.take_all()) == list(range(100))


def test_split_at_indices():
    ds = rd.range(100, parallelism=7)
    a, b, c = ds.split_at_indices([30, 65])
    assert [s.count() for s in (a, b, c)] == [30, 35, 35]
    rows = [r["id"] for r in a.take_all()] + [r["id"] for r in b.take_all()] + [
        r["id"] for r in c.take_all()
    ]
    assert rows == list(range(100))
    with pytest.raises(ValueError):
        ds.split_at_indices([50, 20])


def test_split_proportionately():
    ds = rd.range(100, parallelism=4)
    train, val, rest = ds.split_proportionately([0.7, 0.2])
    assert train.count() == 70 and val.count() == 20 and rest.count() == 10


def test_refs_consumption():
    ds = rd.range(40, parallelism=4)
    refs = ds.get_internal_block_refs()
    assert len(refs) >= 1
    np_refs = ds.to_numpy_refs(column="id")
    arrays = rt.get(np_refs)
    assert int(np.concatenate(arrays).sum()) == sum(range(40))
    pd_refs = ds.to_pandas_refs()
    dfs = rt.get(pd_refs)
    assert sum(len(d) for d in dfs) == 40


def test_from_refs_roundtrip():
    arrs = [np.arange(5), np.arange(5, 10)]
    refs = [rt.put(a) for a in arrs]
    ds = rd.from_numpy_refs(refs, column="v")
    assert int(ds.sum("v")) == sum(range(10))

    import pandas as pd

    df_refs = [rt.put(pd.DataFrame({"x": [1, 2]})), rt.put(pd.DataFrame({"x": [3]}))]
    ds2 = rd.from_pandas_refs(df_refs)
    assert ds2.count() == 3 and int(ds2.sum("x")) == 6


def test_lineage_serialization():
    ds = rd.range(25).map_batches(lambda b: {"id": b["id"] * 2})
    assert ds.has_serializable_lineage()
    blob = ds.serialize_lineage()
    revived = rd.Dataset.deserialize_lineage(blob)
    assert revived.count() == 25
    assert int(revived.sum("id")) == 2 * sum(range(25))
    # materialized lineage is process-local and must refuse
    mat = ds.materialize()
    assert not mat.has_serializable_lineage()
    with pytest.raises(ValueError):
        mat.serialize_lineage()


def test_to_torch():
    import torch

    ds = rd.from_items([{"x": float(i), "y": float(i % 2)} for i in range(16)])
    it = ds.to_torch(label_column="y", feature_columns=["x"], batch_size=4)
    batches = list(it)
    assert len(batches) == 4
    feats, label = batches[0]
    assert isinstance(feats, torch.Tensor) and isinstance(label, torch.Tensor)
    assert feats.shape[0] == 4


def test_random_access_dataset():
    ds = rd.from_items([{"key": i, "val": i * 10} for i in range(200)])
    # as many workers as the runtime has CPUs: the serving actors are
    # num_cpus=0 (reference parity), so they must NOT starve later work
    rad = ds.to_random_access_dataset("key", num_workers=4)
    assert rt.get(rad.get_async(17))["val"] == 170
    assert rt.get(rad.get_async(199))["val"] == 1990
    assert rt.get(rad.get_async(-5)) is None
    got = rad.multiget([3, 150, 9999, 42])
    assert [g["val"] if g else None for g in got] == [30, 1500, None, 420]
    assert "workers=4" in rad.stats()
    # a pipeline still executes while the serving pool is alive
    assert rd.range(50, parallelism=4).count() == 50


def test_write_images_roundtrip(tmp_path):
    from PIL import Image

    imgs = [np.full((8, 8, 3), i * 20, np.uint8) for i in range(4)]
    ds = rd.from_items([{"image": im} for im in imgs])
    out = str(tmp_path / "imgs")
    ds.write_images(out, column="image")
    files = sorted(os.listdir(out))
    assert len(files) == 4 and all(f.endswith(".png") for f in files)
    back = np.asarray(Image.open(os.path.join(out, files[1])))
    assert back.shape == (8, 8, 3)


def test_write_webdataset_roundtrip(tmp_path):
    rows = [
        {"__key__": f"sample{i:03d}", "txt": f"hello {i}", "cls": i, "npy": np.arange(3) + i}
        for i in range(6)
    ]
    out = str(tmp_path / "wds")
    rd.from_items(rows).write_webdataset(out)
    shards = [os.path.join(out, f) for f in sorted(os.listdir(out))]
    assert shards and all(s.endswith(".tar") for s in shards)
    back = rd.read_webdataset(shards).take_all()
    back.sort(key=lambda r: r["__key__"])
    assert back[2]["txt"] == "hello 2"
    assert back[3]["cls"] == 3
    np.testing.assert_array_equal(back[1]["npy"], np.arange(3) + 1)


def test_read_parquet_bulk(tmp_path):
    p = str(tmp_path / "pq")
    rd.range(30, parallelism=3).write_parquet(p)
    files = [os.path.join(p, f) for f in os.listdir(p) if f.endswith(".parquet")]
    ds = rd.read_parquet_bulk(files)
    assert ds.count() == 30


def test_gated_interop_raises_actionably():
    ds = rd.range(4)
    for fn in (ds.to_dask, ds.to_mars, ds.to_modin, ds.to_spark):
        with pytest.raises(ImportError):
            fn()
    with pytest.raises(ImportError):
        ds.write_mongo("mongodb://x", "db", "coll")
    with pytest.raises(ImportError):
        ds.write_bigquery("proj", "ds")
    with pytest.raises(ImportError):
        rd.from_dask(None)
    with pytest.raises(ImportError):
        rd.read_avro(["f.avro"])


def test_compat_surface_actor_pool_sinks_schema(tmp_path):
    """2.9-era surface: ActorPoolStrategy on function UDFs, Datasink file
    bases, Schema accessors, ExecutionOptions view, DatasetContext alias."""
    import numpy as np

    from ray_tpu import data as rd

    # ActorPoolStrategy routes a FUNCTION udf through the actor pool
    ds = rd.range(8, parallelism=4).map_batches(
        lambda b: {"id": np.asarray(b["id"]) * 2},
        batch_format="numpy",
        compute=rd.ActorPoolStrategy(size=2),
    )
    assert sorted(r["id"] for r in ds.take_all()) == [0, 2, 4, 6, 8, 10, 12, 14]

    # row-based sink writes one part per block through the user hook
    class JsonlSink(rd.RowBasedFileDatasink):
        def __init__(self):
            super().__init__(file_extension="jsonl")

        def write_row_to_file(self, row, file):
            import json

            file.write((json.dumps({k: int(v) for k, v in row.items()}) + "\n").encode())

    out = tmp_path / "sink"
    rd.range(4, parallelism=2).write_datasink(JsonlSink(), path=str(out))
    import json

    rows = []
    for p in sorted(out.glob("part-*.jsonl")):
        rows += [json.loads(line) for line in p.read_text().splitlines()]
    assert sorted(r["id"] for r in rows) == [0, 1, 2, 3]

    # Schema: dict-compatible with names/types accessors
    schema = rd.range(3).schema()
    assert isinstance(schema, rd.Schema)
    assert schema.names == ["id"] and list(schema) == ["id"]

    # ExecutionOptions is a live view over DataContext.preserve_order
    ctx = rd.DataContext.get_current()
    assert rd.DatasetContext is rd.DataContext
    ctx.execution_options.preserve_order = True
    try:
        assert ctx.preserve_order is True
    finally:
        ctx.preserve_order = False
    assert ctx.execution_options.preserve_order is False

    # resource limits throttle dispatch but execution still completes
    ctx.execution_options.resource_limits = rd.ExecutionResources(
        cpu=1, object_store_memory=64 * 1024 * 1024
    )
    try:
        rows = rd.range(8, parallelism=4).map_batches(
            lambda b: b, batch_format="numpy"
        ).take_all()
        assert sorted(r["id"] for r in rows) == list(range(8))
    finally:
        ctx.execution_options.resource_limits = rd.ExecutionResources()


# --------------------------------------------------------------------------
# DataIterator tail: schema/stats/to_torch (parity: iterator.py:253/258/485)
# --------------------------------------------------------------------------
def test_iterator_schema_and_stats():
    ds = rd.from_items([{"a": float(i), "label": i % 2} for i in range(8)])
    it = ds.iterator()
    sch = it.schema()
    assert sch is not None and "a" in sch.names
    assert isinstance(it.stats(), str)


def test_iterator_to_torch_packs_features_and_label():
    import torch

    ds = rd.from_items(
        [{"a": float(i), "b": float(2 * i), "label": float(i % 2)} for i in range(8)]
    )
    tds = ds.iterator().to_torch(label_column="label", batch_size=4)
    batches = list(tds)
    assert len(batches) == 2
    feats, label = batches[0]
    assert feats.shape == (4, 2) and label.shape == (4, 1)
    # dict-of-column-lists -> dict of tensors; no label -> None
    tds2 = ds.iterator().to_torch(
        feature_columns={"x": ["a"], "y": ["b", "a"]}, batch_size=8
    )
    feats2, label2 = next(iter(tds2))
    assert label2 is None
    assert feats2["x"].shape == (8, 1) and feats2["y"].shape == (8, 2)
    assert torch.equal(feats2["y"][:, 1:2], feats2["x"])


def test_to_torch_dtype_list_prefetch_and_dataset_delegation():
    import torch

    ds = rd.from_items(
        [{"a": float(i), "b": float(3 * i), "label": float(i)} for i in range(8)]
    )
    # positional dtype list + background prefetch
    tds = ds.iterator().to_torch(
        label_column="label", feature_columns=["a", "b"],
        feature_column_dtypes=[torch.float64, torch.float32],
        batch_size=4, prefetch_batches=2,
    )
    feats, label = next(iter(tds))
    assert feats.shape == (4, 2) and feats.dtype == torch.float64  # cat upcasts
    # Dataset.to_torch is the same implementation
    feats2, label2 = next(iter(ds.to_torch(label_column="label", batch_size=4)))
    assert feats2.shape == (4, 2) and label2.shape == (4, 1)
    # multiple 1-D columns with unsqueeze off is a clear error, not a crash
    with pytest.raises(ValueError, match="unsqueeze_feature_tensors"):
        next(iter(ds.iterator().to_torch(
            label_column="label", feature_columns=["a", "b"],
            unsqueeze_feature_tensors=False, batch_size=4,
        )))
    # owner-less (streaming_split) schema is None, and no rows are lost
    left, right = ds.streaming_split(2)
    assert left.schema() is None
    n = sum(len(b["a"]) for b in left.iter_batches(batch_size=4)) + sum(
        len(b["a"]) for b in right.iter_batches(batch_size=4))
    assert n == 8


def test_to_torch_prefetch_shuts_down_on_early_stop():
    import gc
    import threading
    import time

    def pumps():
        return sum(1 for t in threading.enumerate() if t.name == "to-torch-prefetch")

    ds = rd.from_items([{"a": float(i), "label": 0.0} for i in range(64)])
    for _ in range(5):
        it = iter(ds.to_torch(label_column="label", batch_size=4, prefetch_batches=1))
        next(it)   # consume one batch, then abandon the iterator
        del it
    gc.collect()
    deadline = 50
    while pumps() and deadline:
        time.sleep(0.1)
        deadline -= 1
    assert pumps() == 0  # every abandoned pump exited; no leak


def test_to_torch_grouped_feature_columns():
    """List[List[str]] feature_columns -> a list of per-group tensors, with
    feature_column_dtypes as one dtype per group (ADVICE low)."""
    import torch

    ds = rd.from_items(
        [{"a": float(i), "b": float(2 * i), "c": float(3 * i), "label": 1.0} for i in range(4)]
    )
    feats, label = next(iter(ds.to_torch(
        label_column="label", feature_columns=[["a", "b"], ["c"]], batch_size=4,
    )))
    assert isinstance(feats, list) and len(feats) == 2
    assert feats[0].shape == (4, 2) and feats[1].shape == (4, 1)
    assert label.shape == (4, 1)
    f2, _ = next(iter(ds.to_torch(
        label_column="label", feature_columns=[["a", "b"], ["c"]],
        feature_column_dtypes=[torch.float64, torch.float32], batch_size=4,
    )))
    assert f2[0].dtype == torch.float64 and f2[1].dtype == torch.float32
    with pytest.raises(ValueError, match="one dtype per group"):
        ds.to_torch(
            feature_columns=[["a"], ["b"]],
            feature_column_dtypes=[torch.float32], batch_size=4,
        )
    with pytest.raises(ValueError, match="mixes"):
        ds.to_torch(feature_columns=["a", ["b"]], batch_size=4)


def test_to_torch_warns_on_dropped_non_numeric_columns():
    """Default feature selection must NAME the non-numeric columns it drops
    (ADVICE low: silent drops make thinner feature tensors undiagnosable)."""
    ds = rd.from_items(
        [{"name": f"r{i}", "a": float(i), "label": 0.0} for i in range(4)]
    )
    with pytest.warns(UserWarning, match="name"):
        feats, _ = next(iter(ds.to_torch(label_column="label", batch_size=4)))
    assert feats.shape == (4, 1)


def test_to_torch_skips_object_columns_and_rejects_bad_dtype_spec():
    import torch

    ds = rd.from_items(
        [{"name": f"row{i}", "a": float(i), "label": 0.0} for i in range(4)]
    )
    feats, _ = next(iter(ds.to_torch(label_column="label", batch_size=4)))
    assert feats.shape == (4, 1)  # 'name' (object dtype) skipped
    with pytest.raises(ValueError, match="dict feature_columns"):
        next(iter(ds.to_torch(
            feature_columns={"x": ["a"]},
            feature_column_dtypes=[torch.float32], batch_size=4,
        )))
    with pytest.raises(ValueError, match="entries for"):
        next(iter(ds.to_torch(
            feature_columns=["a"],
            feature_column_dtypes=[torch.float32, torch.float64], batch_size=4,
        )))
