"""ID scheme tests (reference parity: src/ray/common/id.h semantics)."""

import pickle

from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID


def test_sizes():
    assert len(JobID.next().binary()) == 4
    job = JobID.from_int(7)
    actor = ActorID.of(job)
    assert len(actor.binary()) == 12
    task = TaskID.for_actor_task(actor)
    assert len(task.binary()) == 20
    oid = ObjectID.for_task_return(task, 1)
    assert len(oid.binary()) == 24


def test_lineage_embedding():
    job = JobID.from_int(3)
    task = TaskID.for_normal_task(job)
    oid = ObjectID.for_task_return(task, 2)
    assert oid.task_id() == task
    assert oid.job_id() == job
    assert oid.index() == 2
    assert oid.is_return() and not oid.is_put()


def test_put_vs_return_ids():
    job = JobID.from_int(1)
    task = TaskID.for_normal_task(job)
    put_id = ObjectID.for_put(task, 1)
    ret_id = ObjectID.for_task_return(task, 1)
    assert put_id != ret_id
    assert put_id.is_put()


def test_actor_id_embeds_job():
    job = JobID.from_int(9)
    actor = ActorID.of(job)
    assert actor.job_id() == job
    creation = TaskID.for_actor_creation(actor)
    assert creation.actor_id() == actor


def test_nil_and_equality():
    nil = ActorID.nil()
    assert nil.is_nil()
    job = JobID.from_int(1)
    t = TaskID.for_normal_task(job)
    assert t.actor_id().is_nil()
    assert t == TaskID(t.binary())
    assert hash(t) == hash(TaskID(t.binary()))


def test_pickle_roundtrip():
    job = JobID.from_int(5)
    for id_obj in [job, NodeID.from_random(), ActorID.of(job), PlacementGroupID.of(job)]:
        assert pickle.loads(pickle.dumps(id_obj)) == id_obj


def test_hex_roundtrip():
    n = NodeID.from_random()
    assert NodeID.from_hex(n.hex()) == n
