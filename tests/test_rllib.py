"""RL library tests: envs, rollouts, replay, and each algorithm learning
(parity model: rllib's per-algorithm smoke + learning tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.rllib import (
    BCConfig,
    CartPole,
    DQNConfig,
    EnvRunner,
    Pendulum,
    PPOConfig,
    ReplayBuffer,
    SACConfig,
    SampleBatch,
)
from ray_tpu.rllib.rl_module import ActorCriticModule


def test_cartpole_dynamics():
    env = CartPole()
    state, obs = env.reset(jax.random.key(0))
    assert obs.shape == (4,)
    for _ in range(5):
        state, obs, reward, terminated, truncated = env.step(state, jnp.asarray(1))
    assert float(reward) == 1.0
    assert not bool(terminated)
    # pushing one way forever tips the pole over — a true terminal
    for _ in range(200):
        state, obs, reward, terminated, truncated = env.step(state, jnp.asarray(1))
    assert bool(terminated)
    assert not bool(truncated)


def test_pendulum_truncates_not_terminates():
    env = Pendulum(max_episode_steps=10)
    state, obs = env.reset(jax.random.key(1))
    assert obs.shape == (3,)
    for _ in range(10):
        state, obs, reward, terminated, truncated = env.step(state, jnp.asarray([0.5]))
    assert float(reward) <= 0.0
    # time-limit cut is reported as truncation, never termination
    assert not bool(terminated)
    assert bool(truncated)


def test_env_runner_rollout_shapes_and_autoreset():
    env = CartPole(max_episode_steps=20)
    module = ActorCriticModule(env.observation_size, env.num_actions, (16,))
    runner = EnvRunner(env, module, num_envs=4, rollout_length=64, seed=0)
    params = module.init(jax.random.key(0))
    batch, final_obs, ep_returns = runner.sample(params)
    assert batch[SampleBatch.OBS].shape == (64, 4, 4)
    assert batch[SampleBatch.ACTIONS].shape == (64, 4)
    assert batch[SampleBatch.LOGP].shape == (64, 4)
    assert final_obs.shape == (4, 4)
    # 64 steps x 4 envs with <=20-step episodes must finish many episodes
    assert len(ep_returns) >= 8
    assert all(r <= 20 for r in ep_returns)


def test_replay_buffer_wraps():
    buf = ReplayBuffer(capacity=100)
    batch = SampleBatch(
        {"obs": np.arange(250, dtype=np.float32).reshape(250, 1), "r": np.ones(250)}
    )
    buf.add(batch)
    assert len(buf) == 100
    s = buf.sample(32)
    assert s["obs"].shape == (32, 1)
    # only the newest 100 rows remain
    assert s["obs"].min() >= 150


def test_ppo_learns_cartpole():
    config = (
        PPOConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=16, rollout_length=128)
        .training(lr=3e-4, num_epochs=4, minibatch_size=512)
        .debugging(seed=0)
    )
    algo = config.build()
    first = None
    result = None
    for _ in range(15):
        result = algo.train()
        if first is None and not np.isnan(result["episode_return_mean"]):
            first = result["episode_return_mean"]
    assert result["episode_return_mean"] > max(60.0, first * 1.5)
    assert result["num_env_steps_sampled_lifetime"] == 15 * 16 * 128
    algo.stop()


def test_dqn_runs_and_improves():
    config = (
        DQNConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=8, rollout_length=64)
        .training(
            learning_starts=500,
            num_updates_per_iter=32,
            epsilon_decay_steps=2500,
        )
        .debugging(seed=0)
    )
    algo = config.build()
    result = None
    for _ in range(25):
        result = algo.train()
    assert "td_error_mean" in result["learners"]
    assert result["episode_return_mean"] > 15.0
    algo.stop()


@pytest.mark.full
def test_sac_runs_on_pendulum():
    config = (
        SACConfig()
        .environment(Pendulum())
        .env_runners(num_envs_per_runner=4, rollout_length=64)
        .training(learning_starts=200, num_updates_per_iter=4)
        .debugging(seed=0)
    )
    algo = config.build()
    result = None
    for _ in range(4):
        result = algo.train()
    assert "critic_loss" in result["learners"]
    assert np.isfinite(result["learners"]["critic_loss"])
    algo.stop()


def test_bc_fits_expert_actions():
    # expert: push toward upright (action = theta > 0)
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(2000, 4)).astype(np.float32)
    actions = (obs[:, 2] > 0).astype(np.int32)
    data = SampleBatch({SampleBatch.OBS: obs, SampleBatch.ACTIONS: actions})
    config = BCConfig().environment(CartPole()).offline(data).training(lr=1e-2)
    algo = config.build()
    first = algo.train()["learners"]["neg_logp"]
    last = None
    for _ in range(5):
        last = algo.train()["learners"]["neg_logp"]
    assert last < first * 0.5


def test_checkpoint_roundtrip(tmp_path):
    config = PPOConfig().environment(CartPole()).env_runners(
        num_envs_per_runner=4, rollout_length=32
    )
    algo = config.build()
    algo.train()
    path = algo.save(str(tmp_path / "ckpt.pkl"))
    algo2 = config.copy().build()
    algo2.restore(path)
    assert algo2.iteration == 1
    p1 = jax.tree.leaves(algo.learners.params)
    p2 = jax.tree.leaves(algo2.learners.params)
    for a, b in zip(p1, p2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    algo.stop()


def test_algorithm_as_tune_trainable(ray_start_regular):
    from ray_tpu import tune

    config = PPOConfig().environment(CartPole()).env_runners(
        num_envs_per_runner=4, rollout_length=32
    )
    trainable = PPOConfig.algo_class.as_trainable(config, stop_iters=2)
    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([1e-3, 3e-4])},
        tune_config=tune.TuneConfig(metric="episode_return_mean", mode="max"),
    )
    results = tuner.fit()
    assert len(results) == 2


@pytest.mark.full
def test_remote_env_runners(ray_start_regular):
    config = (
        PPOConfig()
        .environment(CartPole())
        .env_runners(
            num_env_runners=2, num_envs_per_runner=4, rollout_length=32, remote=True
        )
    )
    algo = config.build()
    result = algo.train()
    assert result["num_env_steps_sampled_lifetime"] == 2 * 4 * 32
    algo.stop()


# ---------------------------------------------------------------------------
# IMPALA / APPO (V-trace)
# ---------------------------------------------------------------------------
def test_vtrace_matches_gae_when_on_policy():
    """With behavior == target policy (rho == 1) and c/rho clips >= 1,
    V-trace with lambda-free recursion equals the TD(lambda=1)-style
    corrected returns; sanity: targets are finite and shaped [T, B]."""
    import jax
    from ray_tpu.rllib.algorithms.impala import vtrace

    T, B = 16, 4
    rng = np.random.default_rng(0)
    logp = jnp.asarray(rng.normal(size=(T, B)).astype(np.float32))
    rewards = jnp.asarray(rng.normal(size=(T, B)).astype(np.float32))
    values = jnp.asarray(rng.normal(size=(T, B)).astype(np.float32))
    dones = jnp.zeros((T, B), bool)
    final_v = jnp.zeros((B,), jnp.float32)
    vs, pg = vtrace(logp, logp, rewards, values, dones, final_v, 0.99, 1.0, 1.0)
    assert vs.shape == (T, B) and pg.shape == (T, B)
    assert bool(jnp.all(jnp.isfinite(vs))) and bool(jnp.all(jnp.isfinite(pg)))
    # rho==1: vs should equal discounted lambda=1 corrected values
    np.testing.assert_allclose(
        np.asarray(vs[-1]), np.asarray(rewards[-1]), rtol=1e-5, atol=1e-5
    )


def test_impala_learns_cartpole():
    from ray_tpu.rllib import IMPALAConfig

    config = (
        IMPALAConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=16, rollout_length=128)
        .training(lr=2e-3, entropy_coeff=0.005, broadcast_interval=2)
        .debugging(seed=0)
    )
    algo = config.build()
    first = None
    result = None
    for _ in range(25):
        result = algo.train()
        if first is None and not np.isnan(result["episode_return_mean"]):
            first = result["episode_return_mean"]
    assert result["episode_return_mean"] > max(60.0, first * 1.5)
    algo.stop()


def test_appo_runs_and_improves():
    from ray_tpu.rllib import APPOConfig

    config = (
        APPOConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=16, rollout_length=64)
        .training(lr=5e-4, clip_param=0.3)
        .debugging(seed=1)
    )
    algo = config.build()
    first = None
    result = None
    for _ in range(15):
        result = algo.train()
        if first is None and not np.isnan(result["episode_return_mean"]):
            first = result["episode_return_mean"]
    assert np.isfinite(result["learners"]["policy_loss"])
    assert result["episode_return_mean"] > first
    algo.stop()


# ---------------------------------------------------------------------------
# offline: MARWIL / CQL / offline module
# ---------------------------------------------------------------------------
def _expert_cartpole_data(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    obs = rng.normal(size=(n, 4)).astype(np.float32)
    actions = (obs[:, 2] > 0).astype(np.int32)  # push toward the lean
    rewards = np.ones(n, np.float32)
    returns = rng.uniform(5, 20, size=n).astype(np.float32)
    return SampleBatch(
        {
            SampleBatch.OBS: obs,
            SampleBatch.ACTIONS: actions,
            SampleBatch.REWARDS: rewards,
            SampleBatch.RETURNS: returns,
        }
    )


def test_marwil_fits_expert():
    from ray_tpu.rllib import MARWILConfig

    data = _expert_cartpole_data()
    config = MARWILConfig().environment(CartPole()).offline(data).training(lr=1e-2, beta=1.0)
    algo = config.build()
    first = algo.train()["learners"]["policy_loss"]
    last = None
    for _ in range(5):
        last = algo.train()["learners"]["policy_loss"]
    assert last < first


def test_marwil_beta_zero_is_bc():
    from ray_tpu.rllib import MARWILConfig

    data = _expert_cartpole_data()
    config = MARWILConfig().environment(CartPole()).offline(data).training(lr=1e-2, beta=0.0)
    algo = config.build()
    first = algo.train()["learners"]["policy_loss"]
    for _ in range(5):
        last = algo.train()["learners"]["policy_loss"]
    assert last < first * 0.7


def test_cql_offline_pendulum():
    from ray_tpu.rllib import CQLConfig

    rng = np.random.default_rng(0)
    n = 2000
    data = SampleBatch(
        {
            SampleBatch.OBS: rng.normal(size=(n, 3)).astype(np.float32),
            SampleBatch.NEXT_OBS: rng.normal(size=(n, 3)).astype(np.float32),
            SampleBatch.ACTIONS: rng.uniform(-2, 2, size=(n, 1)).astype(np.float32),
            SampleBatch.REWARDS: rng.normal(size=n).astype(np.float32),
            SampleBatch.DONES: np.zeros(n, bool),
        }
    )
    config = (
        CQLConfig()
        .environment(Pendulum())
        .offline(data)
        .training(num_updates_per_iter=4, cql_alpha=1.0)
    )
    algo = config.build()
    result = None
    for _ in range(3):
        result = algo.train()
    stats = result["learners"]
    assert np.isfinite(stats["bellman"]) and np.isfinite(stats["cql_penalty"])
    # conservative penalty must be active (logsumexp > dataset Q on average)
    assert stats["cql_penalty"] != 0.0
    # checkpoint roundtrip through the custom learner state
    import tempfile, os as _os

    with tempfile.TemporaryDirectory() as d:
        p = algo.save(_os.path.join(d, "ckpt.pkl"))
        algo2 = config.build()
        algo2.restore(p)
        l1 = jax.tree.leaves(algo.learner.params)
        l2 = jax.tree.leaves(algo2.learner.params)
        for a, b in zip(l1, l2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_offline_record_save_load_roundtrip(tmp_path):
    from ray_tpu.rllib import offline
    from ray_tpu.rllib.rl_module import ActorCriticModule

    env = CartPole()
    module = ActorCriticModule(env.observation_size, env.num_actions, (32,))
    params = module.init(jax.random.key(0))
    data = offline.record_rollouts(
        env, module, params, num_iterations=2, num_envs=4, rollout_length=32
    )
    assert len(data) == 2 * 4 * 32
    assert SampleBatch.RETURNS in data
    path = offline.save_batch(data, str(tmp_path / "data.npz"))
    loaded = offline.load_batch(path)
    np.testing.assert_array_equal(
        np.asarray(data[SampleBatch.OBS]), loaded[SampleBatch.OBS]
    )


# --------------------------------------------------------------------------
# Connectors (parity: rllib/connectors env-to-module / module-to-env)
# --------------------------------------------------------------------------
def test_connector_pipeline_composition():
    import jax.numpy as jnp
    from ray_tpu.rllib.connectors import (
        CastObs,
        ClipActions,
        ClipObs,
        ConnectorPipeline,
        FlattenObs,
        NormalizeObs,
        UnsquashActions,
        env_to_module,
    )

    pipe = env_to_module(NormalizeObs(mean=1.0, std=2.0), ClipObs(-1.0, 1.0))
    out = pipe(jnp.asarray([1.0, 5.0, -9.0]))
    assert out.tolist() == [0.0, 1.0, -1.0]

    flat = FlattenObs(batch_dims=1)(jnp.ones((4, 2, 3)))
    assert flat.shape == (4, 6)

    clip = ClipActions(-0.5, 0.5)(jnp.asarray([-2.0, 0.1, 3.0]))
    assert clip.tolist() == pytest.approx([-0.5, 0.1, 0.5])

    unsq = UnsquashActions(0.0, 10.0)(jnp.asarray([-100.0, 0.0, 100.0]))
    assert float(unsq[0]) == pytest.approx(0.0, abs=1e-3)
    assert float(unsq[1]) == pytest.approx(5.0)
    assert float(unsq[2]) == pytest.approx(10.0, abs=1e-3)

    # pipelines compose and extend
    p2 = ConnectorPipeline([CastObs(jnp.float32)]).append(NormalizeObs(0.0, 1.0))
    assert p2(jnp.asarray([1, 2], jnp.int32)).dtype == jnp.float32


def test_env_runner_with_connectors():
    """An observation-normalizing connector inside the jitted rollout must
    still produce learnable PPO batches."""
    import jax.numpy as jnp
    from ray_tpu.rllib.connectors import NormalizeObs, env_to_module
    from ray_tpu.rllib.env_runner import EnvRunner
    from ray_tpu.rllib.envs import CartPole
    from ray_tpu.rllib.rl_module import ActorCriticModule

    env = CartPole()
    module = ActorCriticModule(env.observation_size, env.num_actions, hidden=(32,))
    runner = EnvRunner(
        env,
        module,
        num_envs=4,
        rollout_length=16,
        env_to_module=env_to_module(NormalizeObs(mean=0.0, std=1.0)),
    )
    params = module.init(jax.random.key(0))
    batch, final_obs, returns = runner.sample(params)
    assert batch["obs"].shape[:2] == (16, 4)


# --------------------------------------------------------------------------
# DreamerV3 (parity: rllib/algorithms/dreamerv3 — model-based RL)
# --------------------------------------------------------------------------
def _tiny_dreamer():
    from ray_tpu.rllib.algorithms import DreamerV3Config
    from ray_tpu.rllib.envs import CartPole

    cfg = DreamerV3Config().environment(CartPole()).debugging(seed=0)
    cfg.num_envs = 4
    cfg.seq_len = 8
    cfg.batch_size_seqs = 4
    cfg.deter_size = 64
    cfg.units = 64
    cfg.latent_cats = 8
    cfg.latent_classes = 8
    cfg.horizon = 8
    cfg.updates_per_iter = 1
    return cfg


@pytest.mark.full
def test_dreamerv3_world_model_learns():
    """The world-model loss on a FIXED probe batch must drop with training
    (same data before and after isolates learning from replay drift)."""
    algo = _tiny_dreamer().build()
    algo.train()  # fill replay; compile
    probe = {k: jnp.asarray(v) for k, v in algo._replay[0].items()}
    key = jax.random.key(123)
    before = float(algo._observe_loss(algo.state["wm"], key, probe))
    last = {}
    for _ in range(8):
        last = algo.train()["learners"]
    after = float(algo._observe_loss(algo.state["wm"], key, probe))
    assert np.isfinite(list(last.values())).all()
    assert after < before
    algo.stop()


@pytest.mark.full
def test_dreamerv3_checkpoint_roundtrip(tmp_path):
    algo = _tiny_dreamer().build()
    algo.train()
    state = algo.get_state()
    algo2 = _tiny_dreamer().build()
    algo2.set_state(state)
    a = jax.tree.leaves(algo.state["wm"])[0]
    b = jax.tree.leaves(algo2.state["wm"])[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    algo2.train()  # resumed instance keeps training
    algo.stop()
    algo2.stop()


def test_dreamerv3_symlog_twohot_roundtrip():
    from ray_tpu.rllib.algorithms.dreamerv3 import _BINS, symexp, symlog, twohot, twohot_mean

    x = jnp.asarray([-50.0, -1.0, 0.0, 0.5, 7.0, 300.0])
    np.testing.assert_allclose(np.asarray(symexp(symlog(x))), np.asarray(x), rtol=1e-5)
    # twohot encoding is exact for in-range scalars: decode via bin atoms
    enc = twohot(symlog(x))
    dec = symexp(jnp.sum(enc * _BINS, -1))
    np.testing.assert_allclose(np.asarray(dec), np.asarray(x), rtol=1e-4, atol=1e-4)


# --------------------------------------------------------------------------
# Algorithm API tail (parity: compute_single_action / weights / checkpoint)
# --------------------------------------------------------------------------
def test_algorithm_inference_and_weights_api():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=4, rollout_length=32)
        .build()
    )
    try:
        algo.train()
        obs = np.zeros(4, np.float32)
        a = algo.compute_single_action(obs)
        assert a in (0, 1)
        acts = algo.compute_actions(np.zeros((5, 4), np.float32))
        assert acts.shape == (5,)
        # module/policy accessors and the weights roundtrip
        assert algo.get_policy() is algo.get_module()
        w = algo.get_weights()
        algo.set_weights(w)
        assert algo.compute_single_action(obs) == a  # same weights, same action
        # step() is the Trainable alias for train()
        r = algo.step()
        assert r["training_iteration"] == 2
    finally:
        algo.stop()


def test_algorithm_from_checkpoint_roundtrip(tmp_path):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=4, rollout_length=32)
        .build()
    )
    try:
        algo.train()
        path = algo.save(str(tmp_path / "ckpt.pkl"))
        obs = np.linspace(-0.1, 0.1, 4).astype(np.float32)
        want = algo.compute_single_action(obs)
    finally:
        algo.stop()
    from ray_tpu.rllib.algorithm import Algorithm

    revived = Algorithm.from_checkpoint(path)
    try:
        assert revived.iteration == 1
        assert revived.compute_single_action(obs) == want
    finally:
        revived.stop()


def test_offline_checkpoint_strips_dataset(tmp_path):
    from ray_tpu.rllib.algorithms.bc import BCConfig
    from ray_tpu.rllib.sample_batch import SampleBatch

    rng = np.random.default_rng(0)
    big = SampleBatch({
        "obs": rng.normal(size=(4096, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=4096).astype(np.int32),
    })
    config = BCConfig().environment(CartPole()).offline(big).training(lr=1e-2)
    algo = config.build()
    try:
        algo.train()
        path = algo.save(str(tmp_path / "bc.pkl"))
    finally:
        algo.stop()
    import os

    # the 4096x4 float32 dataset (~64KB+) is NOT in the checkpoint
    blob_size = os.path.getsize(path)
    import pickle

    with open(path, "rb") as f:
        blob = pickle.load(f)
    assert blob["stripped_config_attrs"] == ["offline_data"]
    assert blob["config"].offline_data is None
    from ray_tpu.rllib.algorithm import Algorithm

    with pytest.raises(ValueError, match="offline datasets are not serialized"):
        Algorithm.from_checkpoint(path)
    # passing a config with data attached revives it
    revived = Algorithm.from_checkpoint(path, config=config)
    try:
        assert revived.iteration == 1
    finally:
        revived.stop()


def test_periodic_evaluation_in_train():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=4, rollout_length=32)
        .evaluation(evaluation_interval=2, evaluation_duration=3)
        .build()
    )
    try:
        r1 = algo.train()
        assert "evaluation" not in r1          # iteration 1: off-interval
        r2 = algo.train()
        ev = r2["evaluation"]                   # iteration 2: evaluated
        assert ev["num_episodes"] == 3
        assert "episode_return_mean" in ev
    finally:
        algo.stop()
    from ray_tpu.rllib.algorithm import AlgorithmConfig

    with pytest.raises(ValueError, match="positive"):
        AlgorithmConfig().evaluation(evaluation_interval=0)
    with pytest.raises(ValueError, match="positive"):
        AlgorithmConfig().evaluation(evaluation_duration=-1)
