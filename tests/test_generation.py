"""KV-cache decoding tests: cached forward == full forward, ragged batches,
GQA, sampling knobs, eos early-stop."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import (
    TransformerConfig,
    decode_step,
    forward,
    generate,
    init_cache,
    init_params,
    prefill,
    sample_logits,
)

CFG = TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
    attention="dense", dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


def test_prefill_matches_forward(params):
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 97, (3, 9)), jnp.int32)
    full = forward(CFG, params, tokens)  # [B, T, V]
    cache = init_cache(CFG, 3, 16)
    last, _ = prefill(CFG, params, cache, tokens, jnp.full((3,), 9, jnp.int32))
    np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, -1]), rtol=2e-4, atol=2e-4)


def test_decode_matches_forward_stepwise(params):
    """Greedy decode via the cache equals rerunning the full forward."""
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, 97, (2, 5)), jnp.int32)
    n_new = 4
    cache = init_cache(CFG, 2, 5 + n_new)
    logits, cache = prefill(CFG, params, cache, prompt, jnp.full((2,), 5, jnp.int32))
    seq = prompt
    pos = jnp.full((2,), 5, jnp.int32)
    for _ in range(n_new):
        ref_logits = forward(CFG, params, seq)[:, -1]
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)
        logits, cache = decode_step(CFG, params, cache, tok, pos)
        pos = pos + 1


def test_generate_greedy_ragged(params):
    """Each ragged row generates exactly what its solo run generates."""
    rng = np.random.default_rng(2)
    r0 = jnp.asarray(rng.integers(0, 97, (1, 3)), jnp.int32)
    r1 = jnp.asarray(rng.integers(0, 97, (1, 7)), jnp.int32)
    batch = jnp.zeros((2, 7), jnp.int32)
    batch = batch.at[0, :3].set(r0[0]).at[1].set(r1[0])
    lengths = jnp.asarray([3, 7], jnp.int32)

    out, out_len = generate(
        CFG, params, batch, lengths, max_new_tokens=5, temperature=0
    )
    solo0, _ = generate(CFG, params, r0, max_new_tokens=5, temperature=0)
    solo1, _ = generate(CFG, params, r1, max_new_tokens=5, temperature=0)
    assert np.array_equal(np.asarray(out[0, :8]), np.asarray(solo0[0]))
    assert np.array_equal(np.asarray(out[1, :12]), np.asarray(solo1[0]))
    assert np.asarray(out_len).tolist() == [8, 12]


def test_generate_jits(params):
    import functools

    gen = jax.jit(
        functools.partial(generate, CFG, max_new_tokens=3, temperature=0)
    )
    prompt = jnp.ones((2, 4), jnp.int32)
    out, lens = gen(params, prompt)
    assert out.shape == (2, 7)
    assert np.asarray(lens).tolist() == [7, 7]


def test_eos_early_stop(params):
    prompt = jnp.ones((1, 4), jnp.int32)
    first, _ = generate(CFG, params, prompt, max_new_tokens=1, temperature=0)
    eos = int(first[0, 4])
    out, lens = generate(CFG, params, prompt, max_new_tokens=6, temperature=0, eos_id=eos)
    assert int(lens[0]) == 5  # prompt 4 + the eos token itself
    assert np.asarray(out[0, 5:]).tolist() == [eos] * 5  # padded with eos


def test_gqa_matches_mha_shapes():
    cfg = TransformerConfig(
        vocab_size=31, d_model=16, n_layers=1, n_heads=4, n_kv_heads=1, d_ff=32,
        attention="dense", dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.key(3))
    assert params["layers"]["wk"].shape == (1, 16, 1, 4)
    logits = forward(cfg, params, jnp.zeros((2, 6), jnp.int32))
    assert logits.shape == (2, 6, 31)
    assert np.isfinite(np.asarray(logits)).all()
    # cached path agrees with the uncached one under GQA too
    cache = init_cache(cfg, 2, 6)
    last, _ = prefill(cfg, params, cache, jnp.zeros((2, 6), jnp.int32), jnp.full((2,), 6, jnp.int32))
    np.testing.assert_allclose(np.asarray(last), np.asarray(logits[:, -1]), rtol=2e-4, atol=2e-4)


def test_sampling_knobs():
    logits = jnp.asarray([[0.0, 10.0, 0.0, 0.0]])
    key = jax.random.key(0)
    assert int(sample_logits(logits, key, temperature=0)[0]) == 1
    assert int(sample_logits(logits, key, temperature=1.0, top_k=1)[0]) == 1
    assert int(sample_logits(logits, key, temperature=1.0, top_p=0.5)[0]) == 1
    # high temperature + full support still returns a valid token id
    tok = sample_logits(jnp.zeros((3, 8)), key, temperature=5.0, top_k=4, top_p=0.9)
    assert tok.shape == (3,)
    assert ((np.asarray(tok) >= 0) & (np.asarray(tok) < 8)).all()


def test_gqa_kv_replicated_under_tp():
    """kv_heads smaller than the tp axis: wk/wv fall back to replicated."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "tp"))
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=8, n_kv_heads=2, d_ff=64,
        attention="dense",
    )
    from ray_tpu.models import make_train_step

    with mesh:
        init_state, step = make_train_step(cfg, mesh=mesh, sp=None)
        state = init_state(jax.random.key(0))
        tokens = step.shard_batch(
            jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 12)), jnp.int32)
        )
        state, loss = step(state, tokens)
        assert np.isfinite(float(loss))


def test_invalid_gqa_config_raises():
    with pytest.raises(ValueError):
        TransformerConfig(n_heads=8, n_kv_heads=3)
    with pytest.raises(ValueError):
        TransformerConfig(n_heads=8, n_kv_heads=16)


def test_prefill_flash_kernel_matches_einsum(params):
    """The flash-prefill path (interpret mode here) equals the masked
    cache-wide einsum path."""
    from ray_tpu.models.generation import init_cache, prefill

    toks = jnp.asarray(np.random.default_rng(20).integers(0, 97, (2, 9)), jnp.int32)
    lens = jnp.asarray([9, 5], jnp.int32)
    c1 = init_cache(CFG, 2, 16)
    c2 = init_cache(CFG, 2, 16)
    l_flash, c1 = prefill(CFG, params, c1, toks, lens, use_prefill_kernel=True)
    l_einsum, c2 = prefill(CFG, params, c2, toks, lens, use_prefill_kernel=False)
    np.testing.assert_allclose(np.asarray(l_flash), np.asarray(l_einsum), rtol=2e-4, atol=2e-4)
    # caches identical too (writes don't depend on the attention path)
    np.testing.assert_allclose(np.asarray(c1["k"]), np.asarray(c2["k"]), rtol=1e-6, atol=1e-6)
