"""ray_tpu.util.dask — the dask-graph scheduler over the task fabric
(parity: python/ray/util/dask/scheduler.py).  Graphs are plain dicts, so
everything except the dask.config hook is tested without dask installed."""

import operator

import pytest

import ray_tpu
from ray_tpu.util.dask import ray_dask_get, ray_dask_get_sync


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _graph():
    return {
        "a": 1,
        "b": (operator.add, "a", 2),          # 3
        ("c", 0): (sum, ["a", "b"]),      # list-of-keys arg; tuple key: 4
        "d": (operator.mul, ("c", 0), 2),      # 8
    }


def test_scheduler_executes_graph_on_the_fabric():
    assert ray_dask_get(_graph(), "d") == 8
    assert ray_dask_get(_graph(), ["d", "b"]) == [8, 3]


def test_nested_key_lists_match_dask_get_contract():
    out = ray_dask_get(_graph(), [["d"], ["b", "a"]])
    assert out == [[8], [3, 1]]


def test_sync_scheduler_matches():
    assert ray_dask_get_sync(_graph(), ["d", ("c", 0)]) == [8, 4]


def test_persist_returns_refs():
    refs = ray_dask_get(_graph(), ["d", "b"], ray_persist=True)
    assert ray_tpu.get(refs) == [8, 3]


def test_nested_task_args_and_dict_literals():
    dsk = {
        "x": 10,
        "y": (dict, [["k", "x"]]),        # dict built from nested list w/ key ref
        "z": (operator.getitem, "y", "k"),
    }
    assert ray_dask_get(dsk, "z") == 10


def test_wide_graph_fans_out():
    dsk = {"src": 2}
    for i in range(20):
        dsk[("leaf", i)] = (operator.mul, "src", i)
    dsk["total"] = (sum, [("leaf", i) for i in range(20)])
    assert ray_dask_get(dsk, "total") == 2 * sum(range(20))


def test_cycle_detection():
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get_sync({"a": (operator.add, "b", 1), "b": (operator.add, "a", 1)}, "a")


def test_deep_chain_no_recursion_blowup():
    dsk = {"k0": 0}
    n = 3000
    for i in range(1, n):
        dsk[f"k{i}"] = (operator.add, f"k{i-1}", 1)
    assert ray_dask_get_sync(dsk, f"k{n-1}") == n - 1


def test_enable_hook_is_gated():
    from ray_tpu.util.dask import enable_dask_on_ray

    try:
        import dask  # noqa: F401
    except ImportError:
        with pytest.raises(ImportError, match="pip install dask"):
            enable_dask_on_ray()
