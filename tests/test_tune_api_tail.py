"""Tune public-surface tail (parity: python/ray/tune/__init__.py __all__):
the Trainable class API, Experiment/run_experiments/ExperimentAnalysis,
Stopper-driven termination, registries, with_parameters/with_resources,
sampling distributions, and the string factories.
"""

import random

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import tune


@pytest.fixture(scope="module", autouse=True)
def runtime():
    rt.init(num_cpus=4, ignore_reinit_error=True)
    yield
    rt.shutdown()


def test_sampling_distributions():
    rng = random.Random(0)
    assert -10 < tune.randn(0, 2).sample(rng) < 10
    q = tune.qrandn(5, 1, 0.5).sample(rng)
    assert abs(q / 0.5 - round(q / 0.5)) < 1e-9
    for _ in range(50):
        v = tune.lograndint(1, 1000).sample(rng)
        assert 1 <= v < 1000 and isinstance(v, int)
        v = tune.qrandint(10, 100, 10).sample(rng)
        assert v % 10 == 0 and v >= 10
        v = tune.qloguniform(0.001, 1.0, 0.001).sample(rng)
        assert v >= 0.001
        v = tune.qlograndint(1, 100, 5)._q_check() if False else tune.qlograndint(1, 100, 5).sample(rng)
        assert isinstance(v, int) and v >= 1


def test_class_trainable_with_stop_criteria():
    class MyTrainable(tune.Trainable):
        def setup(self, config):
            self.base = config.get("base", 0.0)

        def step(self):
            return {"score": self.base + self.iteration}

    grid = tune.run(
        MyTrainable,
        config={"base": tune.grid_search([0.0, 10.0])},
        metric="score",
        mode="max",
        stop={"training_iteration": 3},
    )
    best = grid.get_best_result()
    assert best.metrics["score"] == 13.0  # base 10 + final iteration 3
    assert all(r.metrics["training_iteration"] <= 3 for r in [grid[i] for i in range(len(grid))])


def test_stopper_object():
    calls = []

    class ScoreStopper(tune.Stopper):
        def __call__(self, trial_id, result):
            calls.append(trial_id)
            return result.get("score", 0) >= 2

    def trainable(config):
        import time

        for i in range(100):
            # reports buffer in the trial actor and the controller drains
            # periodically — a sleep gives the stop decision a window to
            # land (an instant 100-report burst outruns any async stopper)
            tune.report({"score": i})
            time.sleep(0.05)

    grid = tune.run(trainable, config={}, metric="score", mode="max", stop=ScoreStopper())
    assert calls
    assert grid[0].metrics["score"] < 99  # interrupted well before the end


def test_experiment_and_run_experiments():
    def trainable(config):
        tune.report({"val": config["x"] * 2})

    exps = [
        tune.Experiment(name="exp_a", run=trainable, config={"x": tune.grid_search([1, 2])},
                        metric="val", mode="max"),
        tune.Experiment(name="exp_b", run=trainable, config={"x": 5}, metric="val", mode="max"),
    ]
    out = tune.run_experiments(exps)
    assert set(out) == {"exp_a", "exp_b"}
    assert out["exp_a"].get_best_result().metrics["val"] == 4
    analysis = tune.ExperimentAnalysis(out["exp_b"], metric="val", mode="max")
    assert analysis.best_result.metrics["val"] == 10
    assert len(analysis.dataframe()) == 1


def test_register_trainable_by_name():
    def trainable(config):
        tune.report({"out": config["k"] + 1})

    tune.register_trainable("my_trainable", trainable)
    grid = tune.run("my_trainable", config={"k": 41}, metric="out", mode="max")
    assert grid.get_best_result().metrics["out"] == 42
    with pytest.raises(tune.TuneError):
        tune.run("never_registered", config={})


def test_with_parameters_injects_large_objects():
    big = np.arange(100_000)

    def trainable(config, data=None):
        tune.report({"total": float(data.sum()) + config["off"]})

    wrapped = tune.with_parameters(trainable, data=big)
    grid = tune.run(wrapped, config={"off": 1.0}, metric="total", mode="max")
    assert grid.get_best_result().metrics["total"] == float(big.sum()) + 1.0


def test_with_resources_and_pgf():
    pgf = tune.PlacementGroupFactory([{"CPU": 1}, {"CPU": 1}])
    assert pgf.required_resources() == {"CPU": 2}

    def trainable(config):
        tune.report({"ok": 1})

    wrapped = tune.with_resources(trainable, pgf)
    assert wrapped._tune_resources == {"CPU": 1}
    grid = tune.run(wrapped, config={}, metric="ok", mode="max")
    assert grid.get_best_result().metrics["ok"] == 1


def test_with_resources_class_trainable_still_trains():
    # Regression: wrapping a class Trainable in a plain function hid it from
    # Tuner.fit's issubclass adapter, so the trial ran setup() once and
    # reported nothing.
    class Step(tune.Trainable):
        def setup(self, config):
            self.k = config["k"]
            self.i = 0

        def train(self):
            self.i += 1
            return {"score": self.k + self.i, "training_iteration": self.i}

    wrapped = tune.with_resources(Step, {"CPU": 1})
    assert isinstance(wrapped, type) and issubclass(wrapped, tune.Trainable)
    assert wrapped._tune_resources == {"CPU": 1}
    grid = tune.run(
        wrapped, config={"k": 10}, metric="score", mode="max",
        stop={"training_iteration": 3},
    )
    best = grid.get_best_result()
    assert best.metrics["score"] == 13  # trained 3 steps, not zero


def test_factories_and_misc():
    s = tune.create_scheduler("asha")
    from ray_tpu.tune.schedulers import AsyncHyperBandScheduler

    assert isinstance(s, AsyncHyperBandScheduler)
    g = tune.create_searcher("random", param_space={"x": tune.uniform(0, 1)})
    from ray_tpu.tune.search import BasicVariantGenerator

    assert isinstance(g, BasicVariantGenerator)
    with pytest.raises(tune.TuneError):
        tune.create_scheduler("nope")
    assert isinstance(tune.ResumeConfig(), tune.ResumeConfig)
    stopper = tune.MaximumIterationStopper(2)
    assert stopper("t", {"training_iteration": 2})
    assert not stopper("t", {"training_iteration": 1})


def test_cli_reporter_throttles(capsys):
    rep = tune.CLIReporter(max_report_frequency=0.0)

    class FakeTrial:
        trial_id = "t1"
        status = "RUNNING"

    rep.on_trial_result(FakeTrial(), {"loss": 0.5})
    out = capsys.readouterr().out
    assert "Tune progress" in out and "t1" in out


def test_register_env_reaches_rllib():
    from ray_tpu.rllib.algorithm import AlgorithmConfig

    class DummyEnv:
        pass

    tune.register_env("my_env", lambda cfg: DummyEnv())
    config = AlgorithmConfig().environment("my_env")
    assert isinstance(config.env, DummyEnv)
    with pytest.raises(ValueError):
        AlgorithmConfig().environment("unregistered_env")


def test_q_samplers_clip_to_bounds():
    # review regression: rounding must never exceed the declared upper bound
    rng = random.Random(1)
    for _ in range(300):
        assert 1 <= tune.qloguniform(1, 130, 50).sample(rng) <= 130
        assert 1 <= tune.qlograndint(1, 130, 50).sample(rng) <= 130
        assert 10 <= tune.qrandint(10, 95, 10).sample(rng) <= 95


def test_stop_all_halts_whole_experiment():
    import time as _time

    class AfterFirstResult(tune.Stopper):
        fired = False

        def stop_all(self):
            return AfterFirstResult.fired

        def __call__(self, trial_id, result):
            AfterFirstResult.fired = True
            return False

    def trainable(config):
        for i in range(200):
            tune.report({"i": i})
            _time.sleep(0.03)

    grid = tune.run(
        trainable, config={"x": tune.grid_search([1, 2, 3, 4, 5, 6])},
        metric="i", mode="max", max_concurrent_trials=2, stop=AfterFirstResult(),
    )
    # experiment-wide stop: pending trials never launched, nothing ran long
    started = [grid[i] for i in range(len(grid)) if grid[i].metrics]
    assert len(started) <= 3, [r.metrics for r in started]
    assert all(r.metrics.get("i", 0) < 199 for r in started)


# --------------------------------------------------------------------------
# OptunaSearch (real ask/tell wrapper; gated on the optuna import)
# --------------------------------------------------------------------------
def _fake_optuna(monkeypatch):
    """Minimal optuna lookalike exercising the exact surface _OptunaSearch
    drives (ask/tell, suggest_float/int/categorical, TrialState)."""
    import sys
    import types
    import random as _random

    mod = types.ModuleType("optuna")

    class _Trial:
        def __init__(self, rng):
            self.params = {}
            self._rng = rng

        def suggest_float(self, name, low, high, log=False, step=None):
            import math
            if log:
                v = math.exp(self._rng.uniform(math.log(low), math.log(high)))
            elif step:
                v = round(self._rng.uniform(low, high) / step) * step
            else:
                v = self._rng.uniform(low, high)
            self.params[name] = v
            return v

        def suggest_int(self, name, low, high, log=False, step=1):
            v = self._rng.randrange(low, high + 1, step if step else 1)
            self.params[name] = v
            return v

        def suggest_categorical(self, name, choices):
            v = self._rng.choice(list(choices))
            self.params[name] = v
            return v

    class _Study:
        def __init__(self, direction, sampler):
            self.direction = direction
            self.tells = []
            self._rng = _random.Random(0)

        def ask(self):
            return _Trial(self._rng)

        def tell(self, trial, value, state=None):
            self.tells.append((trial, value, state))

    mod.create_study = lambda direction, sampler=None: _Study(direction, sampler)
    mod.samplers = types.SimpleNamespace(TPESampler=lambda seed=None: ("tpe", seed))
    mod.trial = types.SimpleNamespace(
        TrialState=types.SimpleNamespace(COMPLETE="COMPLETE", FAIL="FAIL")
    )
    mod.logging = types.SimpleNamespace(
        set_verbosity=lambda *_: None, WARNING=30
    )
    monkeypatch.setitem(sys.modules, "optuna", mod)
    return mod


def test_optuna_search_translation_and_telling(monkeypatch):
    _fake_optuna(monkeypatch)
    from ray_tpu.tune.search import _OptunaSearch

    space = {
        "lr": tune.loguniform(1e-5, 1e-1),
        "bs": tune.choice([16, 32, 64]),
        "n": tune.randint(1, 10),
        "d": tune.uniform(0.0, 1.0),
        "fixed": 7,
    }
    s = _OptunaSearch(space, metric="score", mode="max")
    cfg = s.suggest("t1")
    assert 1e-5 <= cfg["lr"] <= 1e-1
    assert cfg["bs"] in (16, 32, 64)
    assert 1 <= cfg["n"] <= 9  # our randint upper bound is exclusive
    assert 0.0 <= cfg["d"] <= 1.0
    assert cfg["fixed"] == 7
    s.on_trial_complete("t1", {"score": 0.5})
    assert s._study.tells[-1][1] == 0.5 and s._study.tells[-1][2] == "COMPLETE"
    cfg2 = s.suggest("t2")
    assert cfg2 is not None
    s.on_trial_complete("t2", None, error=True)
    assert s._study.tells[-1][1] is None and s._study.tells[-1][2] == "FAIL"


def test_optuna_search_drives_tune_run(monkeypatch):
    _fake_optuna(monkeypatch)
    from ray_tpu.tune.search import _OptunaSearch

    def trainable(config):
        tune.report({"score": -(config["x"] - 0.7) ** 2})

    searcher = _OptunaSearch({"x": tune.uniform(0.0, 1.0)}, metric="score", mode="max")
    grid = tune.run(trainable, search_alg=searcher, num_samples=6,
                    metric="score", mode="max")
    best = grid.get_best_result()
    assert "score" in best.metrics
    assert len(searcher._study.tells) == 6  # every trial reported back


def test_optuna_stub_raises_actionably_when_missing():
    import importlib

    try:
        import optuna  # noqa: F401
        pytest.skip("optuna installed in this env")
    except ImportError:
        pass
    from ray_tpu.tune import search as search_mod

    importlib.reload(search_mod)
    try:
        with pytest.raises(ImportError, match="optuna"):
            search_mod.OptunaSearch()
    finally:
        importlib.reload(search_mod)
