"""Distributed tracing + default-metric catalog.

Covers the observability tentpole: span context propagation across the
process boundary (driver → pool worker → driver), chrome-trace nesting,
the predefined metric families of ``observability/metric_defs.py`` firing
from the instrumented hot paths, exposition-format validity for every
defined family, and the CLI surfaces (``ray_tpu metrics``, ``ray_tpu
timeline --tracing``).
"""

import json
import os
import re
import time

import pytest

import ray_tpu as rt
from ray_tpu.observability import metric_defs, tracing
from ray_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    global_registry,
)
from ray_tpu.observability.timeline import chrome_trace


# ----------------------------------------------------------------------
# span primitives (no runtime needed)
# ----------------------------------------------------------------------
def test_nested_spans_share_trace_and_chain_parents():
    drained = tracing.drain_span_events()  # isolate from other tests
    with tracing.span("outer") as outer:
        assert tracing.current_context().span_id == outer.span_id
        with tracing.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    assert tracing.current_context() is None
    events = tracing.drain_span_events()
    assert [e["name"] for e in events] == ["inner", "outer"]
    assert all(e["type"] == "span" and e["ts"] >= e["start_ts"] for e in events)
    del drained


def test_task_trace_context_inherits_enclosing_span():
    with tracing.span("root") as root:
        ctx = tracing.task_trace_context()
        assert ctx[0] == root.trace_id
        assert ctx[2] == root.span_id
    tracing.drain_span_events()
    # no enclosing span: a fresh trace is minted
    ctx = tracing.task_trace_context()
    assert ctx[0] and ctx[1] and ctx[2] is None


def test_histogram_overflow_bucket_regression():
    """Values above the largest boundary must be counted (previously only
    ``+Inf`` via the total), so bucket counts always sum to the total."""
    h = Histogram("overflow", boundaries=[1.0, 2.0])
    for v in (0.5, 1.5, 3.0, 1000.0):
        h.observe(v)
    counts, total_sum, total = h.snapshot()
    assert counts == [1, 1, 2]
    assert sum(counts) == total == 4
    assert total_sum == pytest.approx(1005.0)


def test_prometheus_escape_roundtrip():
    """Label values containing quotes and newlines must survive rendering
    (exercises ``_escape``) and be recoverable by unescaping."""
    reg = MetricsRegistry()
    raw = 'he said "hi"\nback\\slash'
    reg.counter("esc").inc(1, tags={"msg": raw})
    text = reg.render_prometheus()
    m = re.search(r'ray_tpu_esc\{msg="((?:[^"\\]|\\.)*)"\} 1', text)
    assert m, text
    unescaped = m.group(1).replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    assert unescaped == raw
    # the sample must stay on one physical line
    assert all(line.count('"') % 2 == 0 for line in text.splitlines() if "esc" in line)


# ----------------------------------------------------------------------
# metric_defs catalog: every family renders spec-valid exposition text
# ----------------------------------------------------------------------
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                      # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\""  # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\")*\})?"  # more labels
    r" -?[0-9.eE+\-]+(\s[0-9]+)?$"                    # value [timestamp]
)


def test_every_metric_def_renders_valid_exposition():
    """Tier-1 catalog guard: a new metric added to metric_defs.py cannot
    silently break the /metrics scrape endpoint."""
    names = [m.name for m in metric_defs.ALL_METRICS]
    assert len(names) == len(set(names)), "duplicate metric names"
    reg = MetricsRegistry()
    for m in metric_defs.ALL_METRICS:
        assert _NAME_RE.match(m.name), m.name
        assert m.description, f"metric {m.name} has no HELP text"
        # clone into a scratch registry (global state untouched) and drive
        # one sample with a representative tag set
        if isinstance(m, Histogram):
            reg.histogram(m.name, m.description, m.unit, m.boundaries).observe(
                0.123, tags={"node": "abc"}
            )
        elif isinstance(m, Counter):
            reg.counter(m.name, m.description, m.unit).inc(2, tags={"state": "x"})
        else:
            assert isinstance(m, Gauge), type(m)
            reg.gauge(m.name, m.description, m.unit).set(7, tags={"state": "x"})
    text = reg.render_prometheus()
    seen_types = {}
    samples = 0
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            assert re.match(r"^# HELP ray_tpu_[a-zA-Z0-9_:]+ \S", line), line
        elif line.startswith("# TYPE "):
            m2 = re.match(r"^# TYPE (ray_tpu_[a-zA-Z0-9_:]+) (counter|gauge|histogram)$", line)
            assert m2, line
            seen_types[m2.group(1)] = m2.group(2)
        else:
            assert _SAMPLE_RE.match(line), f"invalid sample line: {line!r}"
            samples += 1
    for m in metric_defs.ALL_METRICS:
        full = f"ray_tpu_{m.name}"
        assert seen_types.get(full) == m.kind, f"{full} missing or wrong # TYPE"
    assert samples >= len(metric_defs.ALL_METRICS)
    # histogram buckets are cumulative and consistent with _count
    for m in metric_defs.ALL_METRICS:
        if isinstance(m, Histogram):
            full = f"ray_tpu_{m.name}"
            bucket_lines = [l for l in text.splitlines() if l.startswith(full + "_bucket")]
            assert any('le="+Inf"' in l for l in bucket_lines), full


# ----------------------------------------------------------------------
# against a live runtime
# ----------------------------------------------------------------------
@pytest.fixture
def rt_cluster():
    rt.init(num_cpus=2)
    yield
    rt.shutdown()


def _span_events():
    return [e for e in rt.timeline() if e.get("type") == "span"]


def test_cross_process_trace_propagation(rt_cluster):
    """A task submitted from the driver produces driver-side (task root,
    schedule, put) and worker-side (execute) spans sharing one trace_id,
    and the chrome trace nests them."""

    @rt.remote(execution="process")
    def traced(x):
        return x + 1

    assert rt.get(traced.remote(41)) == 42
    deadline = time.monotonic() + 10
    trace = None
    while time.monotonic() < deadline and trace is None:
        by_trace = {}
        for s in _span_events():
            if s["name"].endswith("::traced"):
                by_trace.setdefault(s["trace_id"], []).append(s)
        for tid, spans in by_trace.items():
            if len({s["pid"] for s in spans}) >= 2:
                trace = spans
                break
        if trace is None:
            time.sleep(0.1)
    assert trace is not None, "no multi-process trace appeared"

    names = {s["name"].split("::")[0]: s for s in trace}
    root = names["task"]
    execute = names["execute"]
    assert root["pid"] == os.getpid()
    assert execute["pid"] != os.getpid(), "execute span must come from the worker"
    assert execute["parent_id"] == root["span_id"]
    assert "schedule" in names and names["schedule"]["parent_id"] == root["span_id"]
    # nesting: the root covers the worker-side execution
    assert root["start_ts"] <= execute["start_ts"] + 1e-6
    assert root["ts"] >= execute["ts"] - 1e-6

    slices = chrome_trace(trace)
    group = {s["pid"] for s in slices}
    assert group == {f"trace:{root['trace_id'][:8]}"}
    root_slice = next(s for s in slices if s["name"].startswith("task::"))
    exec_slice = next(s for s in slices if s["name"].startswith("execute::"))
    assert root_slice["ts"] <= exec_slice["ts"] + 1
    assert root_slice["ts"] + root_slice["dur"] >= exec_slice["ts"] + exec_slice["dur"] - 1


def test_inproc_and_actor_spans_share_trace(rt_cluster):
    @rt.remote
    class Tracer:
        def poke(self):
            return 1

    t = Tracer.options(execution="inproc").remote()
    assert rt.get(t.poke.remote()) == 1
    # the task root span is emitted just AFTER the value commits (so its
    # interval covers the put phase) — poll briefly for it
    deadline = time.monotonic() + 10
    kinds = set()
    while time.monotonic() < deadline and not {"task", "execute"} <= kinds:
        # actor-call specs are named Class.method
        spans = [s for s in _span_events() if s["name"].endswith("Tracer.poke")]
        by_trace = {}
        for s in spans:
            by_trace.setdefault(s["trace_id"], []).append(s)
        if by_trace:
            _tid, group = max(by_trace.items(), key=lambda kv: len(kv[1]))
            kinds = {s["name"].split("::")[0] for s in group}
        if not {"task", "execute"} <= kinds:
            time.sleep(0.05)
    assert {"task", "execute"} <= kinds, kinds


def test_workload_acceptance_metrics_and_spans(rt_cluster):
    """ISSUE acceptance: tasks + actor calls + puts drive ≥ 10 distinct
    non-zero ray_tpu_* families, and the timeline carries spans from ≥ 2
    processes sharing one trace_id with correct parent/child nesting."""
    import numpy as np

    @rt.remote(execution="process")
    def proc_task(x):
        return x * 2

    @rt.remote
    def quick(x):
        return x + 1

    @rt.remote
    class Acc:
        def __init__(self):
            self.n = 0

        def add(self, x):
            self.n += x
            return self.n

    a = Acc.remote()
    rt.get([proc_task.remote(i) for i in range(4)])
    rt.get([quick.remote(i) for i in range(4)])
    rt.get([a.add.remote(1) for _ in range(4)])
    rt.get(rt.put(np.arange(1024, dtype=np.float32)))
    time.sleep(0.3)

    text = global_registry().render_prometheus()
    nonzero = set()
    for line in text.splitlines():
        if line.startswith("#") or not line.startswith("ray_tpu_"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            v = float(value)
        except ValueError:
            continue
        if v:
            base = name.split("{")[0]
            base = re.sub(r"_(bucket|sum|count)$", "", base)
            nonzero.add(base)
    defined = {f"ray_tpu_{m.name}" for m in metric_defs.ALL_METRICS}
    hot = nonzero & defined
    assert len(hot) >= 10, f"only {sorted(hot)}"

    spans = _span_events()
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    multi = [ss for ss in by_trace.values() if len({s["pid"] for s in ss}) >= 2]
    assert multi, "no trace crossed a process boundary"
    ss = multi[0]
    root = next(s for s in ss if s["name"].startswith("task::"))
    children = [s for s in ss if s["parent_id"] == root["span_id"]]
    assert children, "root span has no children"


def test_cross_host_trace_propagation():
    """trace_ctx rides encode_spec to a node agent and the agent's execute
    spans ride task_finished back: a task executed on a remote agent still
    lands a worker-side execute span under the head-side task root."""
    from test_multihost import _spawn_agent, _wait_for_nodes

    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        address = cluster.start_head_service()
        proc = _spawn_agent(address)
        try:
            _wait_for_nodes(cluster, 2)

            @rt.remote(resources={"remote": 1}, execution="process")
            def afar(i):
                return i * 3

            assert rt.get([afar.remote(i) for i in range(3)], timeout=60) == [0, 3, 6]
            deadline = time.monotonic() + 15
            ok = False
            while time.monotonic() < deadline and not ok:
                spans = [s for s in _span_events() if "afar" in s["name"]]
                roots = {s["span_id"]: s for s in spans if s["name"].startswith("task::")}
                ok = any(
                    s["name"].startswith("execute::")
                    and s["pid"] != os.getpid()
                    and roots.get(s["parent_id"], {}).get("pid") == os.getpid()
                    for s in spans
                )
                if not ok:
                    time.sleep(0.2)
            assert ok, "no agent-side execute span reached the head's span store"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
    finally:
        rt.shutdown()


def test_tracing_disabled_stamps_nothing():
    rt.init(num_cpus=1, _system_config={"tracing_enabled": False})
    try:
        @rt.remote
        def f():
            return 1

        assert rt.get(f.remote()) == 1
        assert _span_events() == []
    finally:
        rt.shutdown()


# ----------------------------------------------------------------------
# CLI smoke: metrics + timeline --tracing against a live dashboard
# ----------------------------------------------------------------------
def test_cli_metrics_and_tracing_timeline(tmp_path, capsys):
    from ray_tpu.scripts.cli import main

    rt.init(num_cpus=2, include_dashboard=True)
    try:
        url = rt.get_cluster().dashboard.url

        @rt.remote(execution="process")
        def job(x):
            return x

        rt.get([job.remote(i) for i in range(3)])
        time.sleep(0.3)

        assert main(["metrics", "--address", url]) == 0
        out = capsys.readouterr().out
        assert "# TYPE ray_tpu_tasks_terminal_total counter" in out
        assert "ray_tpu_scheduler_tasks_dispatched_total" in out

        out_file = tmp_path / "trace.json"
        assert main(["timeline", "--tracing", "--address", url, "-o", str(out_file)]) == 0
        trace = json.loads(out_file.read_text())
        span_slices = [e for e in trace if e.get("cat") == "span"]
        assert span_slices, "timeline --tracing carried no spans"
        assert any(e["pid"].startswith("trace:") for e in span_slices)

        # without the flag, spans stay out of the dump
        plain_file = tmp_path / "plain.json"
        assert main(["timeline", "--address", url, "-o", str(plain_file)]) == 0
        plain = json.loads(plain_file.read_text())
        assert not [e for e in plain if e.get("cat") == "span"]
    finally:
        rt.shutdown()
