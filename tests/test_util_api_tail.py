"""ray.util surface tail (reference util/__init__ __all__ parity):
ParallelIterator, named-actor listing, custom serializers, placement-group
lookups, node IP, log-once switches, and the pdb shim.
"""

import pytest

import ray_tpu as rt
from ray_tpu import util
from ray_tpu.util import iter as rt_iter


@pytest.fixture(scope="module", autouse=True)
def runtime():
    rt.init(num_cpus=4, ignore_reinit_error=True)
    yield
    rt.shutdown()


def test_parallel_iterator_sync_and_async():
    it = rt_iter.from_range(20, num_shards=3)
    assert it.num_shards() == 3
    doubled = it.for_each(lambda x: x * 2).filter(lambda x: x % 4 == 0)
    got = sorted(doubled.gather_sync())
    assert got == sorted(x * 2 for x in range(20) if (x * 2) % 4 == 0)
    # async gather yields the same multiset
    got_async = sorted(doubled.gather_async())
    assert got_async == got


def test_parallel_iterator_batch_flatmap_union_take():
    a = rt_iter.from_items([1, 2, 3, 4], num_shards=2)
    b = rt_iter.from_items([10, 20], num_shards=1)
    u = a.union(b)
    assert u.num_shards() == 3
    assert sorted(u.gather_sync()) == [1, 2, 3, 4, 10, 20]
    tripled = rt_iter.from_range(6, num_shards=2).flat_map(lambda x: [x, x])
    assert sorted(tripled.gather_sync()) == sorted([x for x in range(6) for _ in range(2)])
    batches = list(rt_iter.from_range(10, num_shards=2).batch(3).gather_sync())
    assert all(isinstance(b, list) and len(b) <= 3 for b in batches)
    assert sorted(x for b in batches for x in b) == list(range(10))
    assert len(rt_iter.from_range(100, num_shards=4).take(7)) == 7


def test_list_named_actors():
    @rt.remote
    class Named:
        def ping(self):
            return "ok"

    a = Named.options(name="util_named_actor").remote()
    rt.get(a.ping.remote())
    names = util.list_named_actors()
    assert "util_named_actor" in names
    detailed = util.list_named_actors(all_namespaces=True)
    assert any(d["name"] == "util_named_actor" for d in detailed)
    rt.kill(a)


def test_register_serializer_roundtrip():
    import pickle

    from tests_util_helpers import Opaque  # noqa: F401 — see helper module

    util.register_serializer(
        Opaque, serializer=lambda o: o.v, deserializer=lambda v: Opaque(v)
    )
    try:
        # the copyreg hook applies to every pickle path (control plane,
        # worker IPC, data plane all pickle through the same machinery)
        back = pickle.loads(pickle.dumps(Opaque(42), protocol=5))
        assert isinstance(back, Opaque) and back.v == 42
    finally:
        util.deregister_serializer(Opaque)
    with pytest.raises(TypeError):
        pickle.dumps(Opaque(1))  # poisoned __reduce__ is back in charge


def test_placement_group_lookup():
    pg = util.placement_group([{"CPU": 1}], strategy="PACK", name="util_pg")
    assert pg.wait(timeout_seconds=30)
    found = util.get_placement_group("util_pg")
    assert found.id == pg.id
    with pytest.raises(ValueError):
        util.get_placement_group("missing_pg")
    # outside any actor: no current placement group
    assert util.get_current_placement_group() is None
    util.remove_placement_group(pg)


def test_node_ip_and_log_once():
    ip = util.get_node_ip_address()
    assert ip.count(".") == 3
    assert util.log_once("tail_key")
    assert not util.log_once("tail_key")


def test_pdb_shim_noop_without_tty(capsys):
    # under pytest stdin is not a tty: the shim must skip, not hang
    util.pdb.set_trace()
    assert "skipped" in capsys.readouterr().err
