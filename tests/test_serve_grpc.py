"""gRPC ingress tests: Predict routing by application metadata, JSON and
pickle codecs, Healthz/ListApplications, and error statuses (mirrors the
reference's serve gRPC proxy tests, which drive a real channel)."""

import json
import pickle

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve

grpc = pytest.importorskip("grpc")


@pytest.fixture(scope="module", autouse=True)
def _serve():
    ray_tpu.init(num_cpus=8)
    serve.start(http_port=0, grpc_port=0, grpc_allow_pickle=True)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _clean_apps():
    yield
    for name in serve.status()["deployments"]:
        serve.delete(name)


@pytest.fixture(scope="module")
def channel():
    ch = grpc.insecure_channel(serve.grpc_address())
    yield ch
    ch.close()


def _method(channel, name):
    return channel.unary_unary(f"/ray_tpu.serve.Serve/{name}")


def test_healthz_and_list_apps(channel):
    assert _method(channel, "Healthz")(b"") == b"success"

    @serve.deployment
    def echo(x):
        return {"echo": x}

    serve.run(echo.bind(), name="echo_app", route_prefix=None)
    apps = json.loads(_method(channel, "ListApplications")(b""))
    assert "echo_app" in apps


def test_predict_json(channel):
    @serve.deployment
    def double(x):
        return {"doubled": [v * 2 for v in x["values"]]}

    serve.run(double.bind(), name="double", route_prefix=None)
    resp = _method(channel, "Predict")(
        json.dumps({"values": [1, 2, 3]}).encode(),
        metadata=(("application", "double"),),
    )
    assert json.loads(resp) == {"doubled": [2, 4, 6]}


def test_predict_pickle_numpy(channel):
    @serve.deployment
    def matsum(arr):
        return np.asarray(arr).sum(axis=0)

    serve.run(matsum.bind(), name="matsum", route_prefix=None)
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    resp = _method(channel, "Predict")(
        pickle.dumps(arr),
        metadata=(("application", "matsum"), ("payload-codec", "pickle")),
    )
    np.testing.assert_allclose(pickle.loads(resp), arr.sum(axis=0))


def test_unknown_application_not_found(channel):
    with pytest.raises(grpc.RpcError) as exc:
        _method(channel, "Predict")(b"{}", metadata=(("application", "nope"),))
    assert exc.value.code() == grpc.StatusCode.NOT_FOUND


def test_replica_error_propagates_as_internal(channel):
    @serve.deployment
    def boom(x):
        raise RuntimeError("kaboom")

    serve.run(boom.bind(), name="boom", route_prefix=None)
    with pytest.raises(grpc.RpcError) as exc:
        _method(channel, "Predict")(b"{}", metadata=(("application", "boom"),))
    assert exc.value.code() == grpc.StatusCode.INTERNAL
    assert "kaboom" in exc.value.details()


def test_pickle_codec_requires_opt_in():
    """A proxy started WITHOUT allow_pickle rejects pickle payloads."""
    from ray_tpu.serve.grpc_proxy import GRPCProxy
    from ray_tpu.serve.router import DeploymentHandle

    proxy = GRPCProxy(port=0)  # default: pickle off
    try:
        ch = grpc.insecure_channel(proxy.address)
        with pytest.raises(grpc.RpcError) as exc:
            ch.unary_unary("/ray_tpu.serve.Serve/Predict")(
                pickle.dumps({"x": 1}),
                metadata=(("application", "a"), ("payload-codec", "pickle")),
            )
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        ch.close()
    finally:
        proxy.shutdown()


def test_apps_deployed_before_grpc_start_are_served():
    """run() before the gRPC proxy exists, then a late start(grpc_port=...)
    must backfill the app registry."""
    import ray_tpu as rt2

    serve.shutdown()
    serve.start(http_port=0)  # no gRPC yet

    @serve.deployment
    def early(x):
        return {"ok": x}

    serve.run(early.bind(), name="early_app", route_prefix=None)
    serve.start(http_port=0, grpc_port=0)  # late gRPC start
    ch = grpc.insecure_channel(serve.grpc_address())
    resp = ch.unary_unary("/ray_tpu.serve.Serve/Predict")(
        json.dumps(5).encode(), metadata=(("application", "early_app"),)
    )
    assert json.loads(resp) == {"ok": 5}
    ch.close()
