"""Demand queue: infeasible work parks without spawning threads.

Round-2 VERDICT item 7: a many_tasks-style burst (BASELINE.md many_tasks,
10k queued tasks) must keep the thread count flat — the reference keeps
infeasible work in scheduler queues drained on resource events
(src/ray/raylet/scheduling/cluster_task_manager.h:42), not in per-task
waiters.
"""

import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu.exceptions import OverloadedError, RayTaskError


@pytest.fixture
def runtime():
    # 10s deadline: long enough that a contended box finishes submitting the
    # 10k burst before entries start expiring (2s flaked under load), short
    # enough that the expiry assertion stays inside its rt.get timeout
    rt.init(num_cpus=2, _system_config={"infeasible_task_timeout_s": 10.0})
    try:
        yield rt
    finally:
        rt.shutdown()


def test_infeasible_burst_flat_thread_count(runtime):
    @rt.remote(resources={"GPU_THAT_DOES_NOT_EXIST": 1})
    def f():
        return 1

    before = threading.active_count()
    refs = [f.remote() for _ in range(10_000)]
    after = threading.active_count()
    # the shared drainer (plus at most a lazily-started runtime thread) —
    # growth must be O(1), never O(tasks)
    assert after - before <= 3, f"thread count grew {before} -> {after}"
    # the queue is BOUNDED (ISSUE 9): exactly demand_queue_max_entries park
    # (visible to the autoscaler as demand), the overflow sheds typed —
    # offered load can never grow the parked set without limit
    from ray_tpu.core.config import get_config

    bound = get_config().demand_queue_max_entries
    cluster = rt.get_cluster()
    parked = len(cluster.pending_resource_demands())
    assert parked == bound, f"{parked} parked demands vs bound {bound}"
    with pytest.raises(OverloadedError):
        rt.get(refs[-1], timeout=60)  # past the bound: shed on arrival
    # parked entries fail with the infeasibility error after the deadline
    with pytest.raises(RayTaskError):
        rt.get(refs[0], timeout=60)


def test_parked_task_runs_when_node_joins(runtime):
    @rt.remote(resources={"LATE": 1})
    def f():
        return "ran"

    ref = f.remote()
    time.sleep(0.2)
    cluster = rt.get_cluster()
    cluster.add_node({"CPU": 1, "LATE": 1})
    assert rt.get(ref, timeout=10) == "ran"


def test_parked_actor_creation_drains(runtime):
    @rt.remote(resources={"SLOT": 1})
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    time.sleep(0.2)
    rt.get_cluster().add_node({"CPU": 1, "SLOT": 1})
    assert rt.get(a.ping.remote(), timeout=10) == "pong"
