"""Top-level public-API surface parity (reference python/ray/__init__.py
__all__): id types, connection-mode constants, Language markers, the
ClientBuilder entry, accelerator accessors, and the cross-language stubs
whose absence is a declared decision, not an accident.
"""

import pytest

import ray_tpu as rt


def test_id_types_exported():
    assert rt.TaskID is not None and rt.ObjectID is not None
    assert rt.UniqueID.SIZE == 28
    assert issubclass(rt.FunctionID, rt.UniqueID)
    assert issubclass(rt.ActorClassID, rt.UniqueID)
    u = rt.UniqueID.from_random()
    assert len(u.binary()) == 28 and not u.is_nil()
    # the hot ids are the native tier; these cold ones are pure-Python —
    # both live under the same import path
    from ray_tpu.core import ids

    assert rt.TaskID is ids.TaskID


def test_modes_language_and_generator_alias():
    assert (rt.SCRIPT_MODE, rt.WORKER_MODE, rt.LOCAL_MODE) == (0, 1, 2)
    assert rt.Language.PYTHON == "PYTHON" and rt.Language.CPP == "CPP"
    assert rt.DynamicObjectRefGenerator is rt.ObjectRefGenerator


def test_client_builder():
    b = rt.client("ray://127.0.0.1:1")
    assert isinstance(b, rt.ClientBuilder)
    # no server there: connect must fail cleanly, not hang
    with pytest.raises(OSError):
        b.connect()


def test_get_gpu_ids_returns_list():
    ids = rt.get_gpu_ids()
    assert isinstance(ids, list)


def test_cross_language_stubs_refuse():
    with pytest.raises(NotImplementedError):
        rt.java_function("com.example.C", "f")
    with pytest.raises(NotImplementedError):
        rt.java_actor_class("com.example.C")
    with pytest.raises(NotImplementedError):
        rt.cpp_function("f")


def test_lazy_submodules_resolve():
    import importlib

    for name in ("data", "serve", "train", "tune", "workflow", "util", "state"):
        assert getattr(rt, name) is importlib.import_module(f"ray_tpu.{name}")
    with pytest.raises(AttributeError):
        rt.not_a_module  # noqa: B018


def test_show_in_dashboard_lands_in_events():
    rt.show_in_dashboard("hello from the driver", key="greeting")
    from ray_tpu.observability.events import global_event_manager

    evs = global_event_manager().list_events(limit=50)
    assert any(e.label == "greeting" for e in evs)
