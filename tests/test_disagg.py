"""Disaggregated prefill/decode serving tests (serve/disagg.py).

Contracts:
- identity: tokens decoded from a migrated block set are bit-for-bit the
  one-shot reference continuation (the migration moves state, never math)
- control stream is header-only: the ticket carries block-table metadata,
  zero KV payload bytes
- prefix-cache interaction: migrated blocks insert into the decode
  replica's prefix cache on finish, a warm decode prefix short-circuits
  re-migration (only the uncached suffix is pulled), identity holds
- fallback ladder: a released staging surfaces as the typed
  KVMigrationError, never a hang or a silent wrong answer
- deploy-time role validation fails fast with a typed ValueError
- serve stack end-to-end: a ``roles=`` deployment routes prefill by queue
  depth and decode by free KV pages, sync + streaming both work, and every
  staged migration is audited to exactly one terminal
- chaos: a scheduled decode-replica kill walks the re-prefill ladder;
  same-seed runs replay byte-identical fault logs and invariant 13 sweeps
  (every staged block set freed exactly once)
- observability: the ``kv_migrate`` waterfall segment exists and phase
  durations still sum exactly to end-to-end
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import TransformerConfig, generate, init_params
from ray_tpu.observability import metric_defs
from ray_tpu.observability.reqtrace import RequestTrace
from ray_tpu.runtime import failpoints
from ray_tpu.serve import disagg
from ray_tpu.serve.disagg import (
    KVMigrationError,
    migration_uuid,
    validate_roles,
)
from ray_tpu.serve.llm import LLMEngine, LLMServer

CFG = TransformerConfig(
    vocab_size=89, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
    attention="dense", dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(11))


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def _reference(params, prompt, n):
    """Greedy reference continuation via the one-shot generate()."""
    p = jnp.asarray([prompt], jnp.int32)
    out, lens = generate(CFG, params, p, max_new_tokens=n, temperature=0)
    return np.asarray(out[0, len(prompt): int(lens[0])]).tolist()


def _paged(params, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(CFG, params, cache_kind="paged", **kw)


def _wait(pred, timeout=60):
    deadline = time.time() + timeout
    while not pred() and time.time() < deadline:
        time.sleep(0.005)
    assert pred()


def _assert_no_leak(eng):
    """Quiesced-engine leak check under prefix caching: every held page is
    accounted for by the prefix cache, and flushing it empties the pool."""
    st = eng.stats()
    assert st["kv_blocks_in_use"] == st["prefix_cache_blocks"]
    eng.flush_prefix_cache()
    st = eng.stats()
    assert st["kv_blocks_in_use"] == 0 and st["prefix_cache_blocks"] == 0


def _migrate(p_eng, d_eng, prompt, mig_id, max_tokens=6):
    """Manual dispatcher: export on the prefill engine, pull only the
    uncached-suffix blocks, adopt on the decode engine.  Returns
    ``(ticket, tokens, rungs)`` where ``rungs`` has one entry per block
    actually pulled (empty on a full decode-side prefix hit)."""
    ticket = p_eng.prefill_export(prompt, mig_id=mig_id).result(timeout=120)
    bs = d_eng.kv_block_size
    matched = d_eng.peek_prefix_match(prompt)
    arrays, rungs = {}, []
    for bidx in range(matched // bs, int(ticket["n_blocks"])):
        arr, rung = disagg.pull_block(ticket, bidx)
        arrays[bidx] = arr
        rungs.append(rung)
    req = d_eng.adopt_migration(ticket, arrays, max_tokens=max_tokens)
    out = req.future.result(timeout=120)
    return ticket, out, rungs


# --------------------------------------------------------------------------
# identity + wire format
# --------------------------------------------------------------------------
def test_migration_uuid_derived_never_random():
    a = migration_uuid("LLMServer/m1", 0)
    assert a == migration_uuid("LLMServer/m1", 0)
    assert a != migration_uuid("LLMServer/m1", 1)
    assert a != migration_uuid("LLMServer/m2", 0)
    # low 32 bits carry the block index; never zero (transfer-server uuids)
    assert migration_uuid("LLMServer/m1", 5) & 0xFFFFFFFF == 5
    assert migration_uuid("LLMServer/m1", 0) != 0


def test_ticket_is_header_only(params):
    """Satellite guard: zero KV payload bytes on the control stream — the
    ticket is plain block-table metadata, small and array-free."""
    import json

    p_eng = _paged(params)
    try:
        prompt = list(range(1, 20))  # 19 tokens -> 2 blocks @ block_size=16
        ticket = p_eng.prefill_export(prompt, mig_id="t/hdr").result(timeout=120)
        assert set(ticket) == {
            "mig_id", "prompt", "tok0", "n_blocks", "block_size",
            "block_shape", "block_dtype", "transfer_addr", "data_addr",
            "source",
        }
        for v in ticket.values():
            assert not hasattr(v, "shape") or isinstance(v, tuple)
            assert isinstance(v, (str, int, float, list, tuple, type(None)))
        assert ticket["n_blocks"] == 2 and ticket["block_size"] == 16
        # [2(k,v), L, block_size, Hkv, Dh]
        assert tuple(ticket["block_shape"]) == (2, CFG.n_layers, 16,
                                                CFG.n_kv_heads, 8)
        assert ticket["tok0"] == _reference(params, prompt, 1)[0]
        # header-only really means header-only: a few hundred bytes
        assert len(json.dumps(ticket)) < 2048
        assert p_eng.release_migration("t/hdr")
    finally:
        p_eng.shutdown()


def test_migration_bit_identical(params):
    p_eng, d_eng = _paged(params), _paged(params)
    try:
        prompt = [3, 14, 15, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2, 3, 8, 4, 6]
        ref = _reference(params, prompt, 8)
        _, out, rungs = _migrate(p_eng, d_eng, prompt, "t/ident", max_tokens=8)
        assert out == ref
        # no runtime in this test: every pull resolves via the in-process
        # registry rung (reported as the host fallback)
        assert rungs and all(r == "host" for r in rungs)
        st = p_eng.stats()
        assert st["migrations_out"] == 1 and st["staged_migrations"] == 1
        assert d_eng.stats()["migrations_in"] == 1
        # exactly-once: release drops the staging, the second is a no-op
        assert p_eng.release_migration("t/ident") is True
        assert p_eng.release_migration("t/ident") is False
        _wait(lambda: d_eng.stats()["active_slots"] == 0)
        _assert_no_leak(p_eng)
        _assert_no_leak(d_eng)
    finally:
        p_eng.shutdown()
        d_eng.shutdown()


def test_warm_decode_prefix_short_circuits_re_migration(params):
    p_eng, d_eng = _paged(params), _paged(params)
    try:
        prompt = [(7 * i + 3) % CFG.vocab_size for i in range(32)]  # 2 full blocks
        ref = _reference(params, prompt, 6)

        _, out1, rungs1 = _migrate(p_eng, d_eng, prompt, "t/warm1")
        assert out1 == ref
        assert len(rungs1) == 2  # cold decode side: every block pulled
        assert p_eng.release_migration("t/warm1")
        _wait(lambda: d_eng.stats()["active_slots"] == 0)

        # migrated blocks landed in the DECODE replica's prefix cache
        assert d_eng.peek_prefix_match(prompt) == 32

        # same prompt again: full prefix hit, ZERO blocks re-migrated,
        # tokens still bit-for-bit
        _, out2, rungs2 = _migrate(p_eng, d_eng, prompt, "t/warm2")
        assert out2 == ref
        assert rungs2 == []
        assert p_eng.release_migration("t/warm2")

        # extended prompt: only the uncached suffix block crosses the wire
        prompt3 = prompt + [11, 12, 13, 14, 15, 16, 17, 18]  # 40 -> 3 blocks
        ref3 = _reference(params, prompt3, 5)
        _, out3, rungs3 = _migrate(p_eng, d_eng, prompt3, "t/warm3",
                                   max_tokens=5)
        assert out3 == ref3
        assert len(rungs3) == 1
        assert p_eng.release_migration("t/warm3")

        _wait(lambda: d_eng.stats()["active_slots"] == 0)
        _assert_no_leak(p_eng)
        _assert_no_leak(d_eng)
    finally:
        p_eng.shutdown()
        d_eng.shutdown()


def test_released_staging_raises_typed_error(params):
    """Fallback-ladder floor: once the staging is gone and no rung can
    reach it, pull_block raises the typed KVMigrationError (the dispatcher
    turns this into a re-prefill, callers only see it ladder-exhausted)."""
    p_eng = _paged(params)
    try:
        prompt = list(range(2, 21))
        ticket = p_eng.prefill_export(prompt, mig_id="t/gone").result(timeout=120)
        assert p_eng.release_migration("t/gone")
        with pytest.raises(KVMigrationError) as exc:
            disagg.pull_block(ticket, 0)
        assert exc.value.mig_id == "t/gone"
        assert exc.value.stage == "staging"
        _assert_no_leak(p_eng)
    finally:
        p_eng.shutdown()


# --------------------------------------------------------------------------
# role validation
# --------------------------------------------------------------------------
def test_validate_roles_typed_errors():
    validate_roles(None)  # homogeneous deployments validate vacuously
    validate_roles({"prefill": 2, "decode": 3})
    with pytest.raises(ValueError, match="unknown deployment role"):
        validate_roles({"prefill": 1, "decode": 1, "draft": 1})
    with pytest.raises(ValueError, match="at least one 'decode'"):
        validate_roles({"prefill": 2})
    with pytest.raises(ValueError, match="at least one 'prefill'"):
        validate_roles({"prefill": 0, "decode": 2})
    with pytest.raises(ValueError, match="paged"):
        validate_roles({"prefill": 1, "decode": 1}, {"cache_kind": "dense"})


# --------------------------------------------------------------------------
# observability
# --------------------------------------------------------------------------
def test_disagg_metrics_registered():
    assert metric_defs.LLM_KV_MIGRATIONS in metric_defs.ALL_METRICS
    assert metric_defs.LLM_KV_MIGRATION_SECONDS in metric_defs.ALL_METRICS
    assert metric_defs.SERVE_POOL_REPLICAS in metric_defs.ALL_METRICS
    assert metric_defs.SERVE_POOL_ONGOING in metric_defs.ALL_METRICS


def test_kv_migrate_waterfall_sums_to_e2e():
    """Satellite 1: a disaggregated request's waterfall carries the
    kv_migrate segment, the trailing segment is decode, and phase durations
    still sum exactly to the last mark's offset."""
    tr = RequestTrace(route="/llm", deployment="LLMServer")
    for m in ("router_in", "router_dequeue", "replica_in", "engine_submit",
              "wfq_pop", "admitted", "first_token", "kv_migrate", "finished"):
        tr.mark(m)
    phases = tr.phases()
    names = [p[0] for p in phases]
    assert names == ["proxy", "router_queue", "dispatch", "replica",
                     "engine_queue", "kv_block_wait", "prefill",
                     "kv_migrate", "decode"]
    # contiguous: each segment starts where the previous ended
    for (_, _, end), (_, start, _) in zip(phases, phases[1:]):
        assert end == start
    total = sum(end - start for _, start, end in phases)
    assert total == pytest.approx(tr.mark_offset("finished"))
    # co-located requests (no kv_migrate mark) still sum to e2e and end in
    # decode, so the disagg segment is additive, not a schema fork
    tr2 = RequestTrace(route="/llm", deployment="LLMServer")
    for m in ("router_in", "replica_in", "first_token", "finished"):
        tr2.mark(m)
    p2 = tr2.phases()
    assert p2[-1][0] == "decode"
    assert sum(e - s for _, s, e in p2) == pytest.approx(
        tr2.mark_offset("finished"))


# --------------------------------------------------------------------------
# serve stack end-to-end
# --------------------------------------------------------------------------
def test_serve_disagg_roles_end_to_end(params):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.runtime.worker import global_worker

    ray_tpu.init(num_cpus=4)
    serve.start(http_port=0)
    try:
        # deploy-time validation: typed ValueError, fail fast — never a
        # deployment that wedges at its first migration (the controller
        # raise arrives wrapped in RayTaskError with the original cause)
        from ray_tpu.exceptions import RayTaskError

        def _deploy_must_fail(dep, bind_kwargs, needle):
            with pytest.raises((ValueError, RayTaskError)) as exc:
                serve.run(dep.bind(lambda: (CFG, params), **bind_kwargs),
                          route_prefix=None)
            cause = getattr(exc.value, "cause", exc.value)
            assert isinstance(cause, ValueError), exc.value
            assert needle in str(cause)

        _deploy_must_fail(
            serve.deployment(LLMServer, name="BadRoles",
                             roles={"prefill": 1}),
            {}, "at least one 'decode'")
        _deploy_must_fail(
            serve.deployment(LLMServer, name="BadKind",
                             roles={"prefill": 1, "decode": 1}),
            {"cache_kind": "dense"}, "paged")

        app = serve.deployment(
            LLMServer, roles={"prefill": 1, "decode": 1}
        ).bind(lambda: (CFG, params), max_batch_size=4, max_seq_len=64)
        handle = serve.run(app, route_prefix=None)

        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2, 3]
        ref = _reference(params, prompt, 6)

        # sync: prefill-pool chunked prefill -> device-plane migration ->
        # decode-pool continuous batching, tokens bit-for-bit
        r = handle.remote({"prompt": prompt, "max_tokens": 6}).result(
            timeout=120)
        assert r["tokens"] == ref and r["num_generated"] == 6

        # streaming rides the same migration path
        events = list(
            handle.remote(
                {"prompt": prompt, "max_tokens": 6, "stream": True}
            ).result(timeout=120)
        )
        toks = [e["token"] for e in events if "token" in e]
        assert toks == ref
        assert events[-1] == {"done": True, "num_generated": 6}

        # warm decode prefix (populated by the requests above) still
        # produces identical output through the serve stack
        r2 = handle.remote({"prompt": prompt, "max_tokens": 6}).result(
            timeout=120)
        assert r2["tokens"] == ref

        # every staged migration reached exactly one terminal, all adopted
        cluster = global_worker().cluster
        audits = list(cluster.kv_migration_audits)
        staged = [a for a in audits if a["event"] == "staged"]
        released = [a for a in audits if a["event"] == "released"]
        assert len(staged) >= 3
        assert sorted(a["mig_id"] for a in staged) == sorted(
            a["mig_id"] for a in released)
        assert all(a["outcome"] == "adopted" for a in released)

        # per-role pools surface in the overload snapshot (rt overload)
        pools = cluster.overload_snapshot()["serve_pools"]["LLMServer"]
        assert set(pools) == {"prefill", "decode"}
        assert pools["prefill"]["replicas"] == 1
        assert pools["decode"]["replicas"] == 1
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


# --------------------------------------------------------------------------
# chaos: decode-replica kill -> re-prefill ladder, byte-identical replays
# --------------------------------------------------------------------------
_CHAOS_PROMPT = [5, 3, 7, 1, 9, 2, 8, 4, 6, 1, 2, 3, 4, 5, 6, 7, 8, 9]


def _disagg_chaos_run(seed, params, refs):
    """One seeded chaos run: roles={prefill:1, decode:2}; the schedule
    hard-kills decode replica 0 with traffic in flight (NO failpoint
    decisions consumed — membership perturbation only), then the workload
    arms ``disagg.decode_call=raise(0.4)`` and drives strictly sequential
    requests so every decision-stream index is workload-ordered."""
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.chaos import ChaosEvent, ChaosRunner, ChaosSchedule
    from ray_tpu.runtime.worker import global_worker

    rt.init(num_cpus=4)
    try:
        schedule = ChaosSchedule(
            [
                ChaosEvent(0.8, "kill_decode_replica", deployment="LLMServer",
                           role="decode", index=0),
            ],
            seed=seed, name="disagg-decode-kill",
        )

        def workload():
            t_start = time.monotonic()
            serve.start(http_port=0)
            app = serve.deployment(
                LLMServer, roles={"prefill": 1, "decode": 2}
            ).bind(lambda: (CFG, params), max_batch_size=4, max_seq_len=64)
            handle = serve.run(app, route_prefix=None)
            # the kill at t=0.8 needs a decode pool to aim at
            ctls = list(global_worker().cluster.serve_controllers.values())
            assert ctls, "controller never registered its chaos hook"
            _wait(lambda: ctls[0].pool_status().get("LLMServer", {})
                  .get("decode", {}).get("replicas", 0) >= 2, timeout=30)

            prompt = _CHAOS_PROMPT
            ref = refs[tuple(prompt)]
            # phase 1 — races the scheduled kill: a decode death
            # mid-migration may exhaust the ladder (typed error), anything
            # else must still be the exact reference tokens
            try:
                r = handle.remote({"prompt": prompt, "max_tokens": 4}).result(
                    timeout=60)
                assert r["tokens"] == ref
            except KVMigrationError:
                pass
            # wait out the kill window: the armed phase must see a stable
            # membership (a dead-replica retry would consume an extra
            # failpoint decision and break byte-identity)
            time.sleep(max(0.0, 2.0 - (time.monotonic() - t_start)))

            # phase 2 — deterministic failpoint hits: sequential requests,
            # each route attempt consumes exactly one decision index
            failpoints.arm("disagg.decode_call=raise(0.4)")
            ladder_exhausted = 0
            for i in range(5):
                p = prompt + [i + 1]
                try:
                    r = handle.remote({"prompt": p, "max_tokens": 3}).result(
                        timeout=60)
                    assert r["tokens"] == refs[tuple(p)]
                except KVMigrationError:
                    ladder_exhausted += 1
            # NO disarm here: failpoints.disarm() clears the fault log, and
            # the runner captures it (then disarms) after quiescence
            serve.shutdown()
            return ladder_exhausted

        result = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert result.ok, (result.workload_error,
                           result.invariants.violations)
        kills = [e for e in result.events_applied
                 if e["kind"] == "kill_decode_replica"]
        assert len(kills) == 1 and "skipped" not in kills[0], kills
        # invariant 13 had migrations to sweep: phase 1 + 5 armed requests,
        # each staging at least one block set
        assert result.invariants.checked.get("kv_migrations", 0) >= 5
        return result
    finally:
        rt.shutdown()


@pytest.mark.parametrize("seed", [41])
def test_chaos_decode_replica_kill_byte_identical(seed, params):
    # references precomputed OUTSIDE the runs: the workload's wall-clock
    # shape stays identical across both replays (and the one-shot
    # generate() compiles don't run twice)
    refs = {tuple(_CHAOS_PROMPT): _reference(params, _CHAOS_PROMPT, 4)}
    for i in range(5):
        p = _CHAOS_PROMPT + [i + 1]
        refs[tuple(p)] = _reference(params, p, 3)
    r1 = _disagg_chaos_run(seed, params, refs)
    r2 = _disagg_chaos_run(seed, params, refs)
    assert r1.faults, "the disagg.decode_call failpoint must actually fire"
    assert all(f["fp"] == "disagg.decode_call" for f in r1.faults)
    assert r1.same_faults(r2), (r1.faults, r2.faults)
