"""Memory monitor + OOM killing policies.

Parity: reference memory_monitor tests + worker_killing_policy tests
(src/ray/raylet/worker_killing_policy_test.cc): policy selection order,
threshold behavior, and the e2e kill path where a process task dies with
OutOfMemoryError and retriable tasks come back.
"""

import time

import pytest

from ray_tpu.runtime.memory_monitor import (
    GroupByOwnerPolicy,
    KillCandidate,
    MemoryMonitor,
    RetriableFIFOPolicy,
    system_memory,
)


def _cand(task_id, owner, start, retriable):
    return KillCandidate(task_id, owner, start, retriable, kill_fn=lambda: None)


def test_system_memory_reads():
    used, total = system_memory()
    assert total > 0 and 0 < used < total


def test_retriable_fifo_prefers_retriable_newest():
    policy = RetriableFIFOPolicy()
    picked = policy.select(
        [
            _cand("old-retriable", "a", 1.0, True),
            _cand("new-retriable", "a", 5.0, True),
            _cand("new-nonretriable", "a", 9.0, False),
        ]
    )
    assert picked.task_id == "new-retriable"
    assert policy.select([]) is None


def test_group_by_owner_picks_biggest_group():
    policy = GroupByOwnerPolicy()
    picked = policy.select(
        [
            _cand("a1", "A", 1.0, True),
            _cand("b1", "B", 2.0, True),
            _cand("b2", "B", 3.0, True),
        ]
    )
    assert picked.task_id == "b2"  # biggest owner group, newest within it


def test_monitor_kills_only_above_threshold():
    kills = []
    cands = [
        KillCandidate("t1", "a", 1.0, True, kill_fn=lambda: kills.append("t1"))
    ]
    fake_mem = {"used": 50, "total": 100}
    mon = MemoryMonitor(
        lambda: cands,
        usage_threshold=0.9,
        memory_fn=lambda: (fake_mem["used"], fake_mem["total"]),
        min_kill_interval_s=0.0,
    )
    assert mon.check_once() is False
    fake_mem["used"] = 95
    assert mon.check_once() is True
    assert kills == ["t1"]
    assert mon.num_kills == 1


def test_monitor_respects_min_kill_interval():
    kills = []
    cands = [KillCandidate("t", "a", 1.0, True, kill_fn=lambda: kills.append(1))]
    mon = MemoryMonitor(
        lambda: cands,
        usage_threshold=0.5,
        memory_fn=lambda: (99, 100),
        min_kill_interval_s=60.0,
    )
    assert mon.check_once() is True
    assert mon.check_once() is False  # within the kill cooldown
    assert len(kills) == 1


def test_oom_kill_fails_nonretriable_task(ray_start_regular):
    rt = ray_start_regular
    cluster = rt.get_cluster()
    from ray_tpu.exceptions import OutOfMemoryError, RayTaskError

    @rt.remote(execution="process", max_retries=0)
    def hog():
        time.sleep(30)
        return "survived"

    ref = hog.remote()
    # wait until the task is running in a worker process
    node = cluster.head_node
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not node.kill_candidates():
        time.sleep(0.05)
    cands = node.kill_candidates()
    assert cands, "task never reached a process worker"
    assert cands[0].retriable is False
    cands[0].kill_fn()
    with pytest.raises((OutOfMemoryError, RayTaskError)):
        rt.get(ref, timeout=30)


def test_oom_killed_retriable_task_retries(ray_start_regular):
    rt = ray_start_regular
    cluster = rt.get_cluster()

    @rt.remote(execution="process", max_retries=2)
    def flaky(x):
        return x * 2

    # burn-in so the fn is known; then kill mid-flight
    assert rt.get(flaky.remote(1)) == 2

    @rt.remote(execution="process", max_retries=2)
    def slowish(x):
        import time as _t

        _t.sleep(1.0)
        return x + 100

    ref = slowish.remote(1)
    node = cluster.head_node
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not node.kill_candidates():
        time.sleep(0.02)
    cands = node.kill_candidates()
    assert cands and cands[0].retriable is True
    cands[0].kill_fn()
    # the retry must produce the result anyway
    assert rt.get(ref, timeout=60) == 101


def test_cluster_has_monitor_running(ray_start_regular):
    rt = ray_start_regular
    cluster = rt.get_cluster()
    assert cluster.memory_monitor is not None
    # live poll must not kill anything on a healthy host
    assert cluster.memory_monitor.num_kills == 0
