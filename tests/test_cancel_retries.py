"""Cancellation + max_task_retries (round-2 VERDICT item 6).

Reference semantics anchors: CancelTask force_kill
(src/ray/protobuf/core_worker.proto:441-502) and in-flight actor-method
resubmission under max_task_retries (src/ray/core_worker/task_manager.h:208).
"""

import os
import time

import pytest

import ray_tpu as rt
from ray_tpu.exceptions import ActorDiedError, RayActorError, TaskCancelledError


@pytest.fixture
def runtime():
    rt.init(num_cpus=2)
    try:
        yield rt
    finally:
        rt.shutdown()


def test_cancel_queued_task(runtime):
    @rt.remote(execution="process")
    def blocker():
        time.sleep(5)
        return "blocked"

    @rt.remote(execution="process")
    def victim():
        return "ran"

    # fill both CPUs so the victim stays queued
    blockers = [blocker.remote() for _ in range(2)]
    ref = victim.remote()
    rt.cancel(ref)
    with pytest.raises(TaskCancelledError):
        rt.get(ref, timeout=30)
    del blockers


def test_force_cancel_interrupts_running_task(runtime):
    @rt.remote(execution="process", max_retries=3)
    def spin():
        while True:
            time.sleep(0.1)

    ref = spin.remote()
    # wait until the task is actually running in a worker process (slow
    # shared CI boxes can take seconds to spawn one)
    pool = rt.get_cluster().head_node.worker_pool
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not pool.inflight_tasks():
        time.sleep(0.05)
    assert pool.inflight_tasks(), "spin task never started"
    t0 = time.monotonic()
    rt.cancel(ref, force=True)
    with pytest.raises(TaskCancelledError):
        rt.get(ref, timeout=30)
    # force-cancel must interrupt promptly (not wait out the task) and must
    # not burn retries on the killed worker
    assert time.monotonic() - t0 < 10


def test_cancel_is_index_lookup_not_scan(runtime):
    """Queued-cancel goes through the task-id index (no pending scan)."""

    @rt.remote(execution="process")
    def slow():
        time.sleep(3)

    refs = [slow.remote() for _ in range(200)]
    spec = rt.get_cluster().task_manager.get_pending(refs[50].id().task_id())
    assert spec is not None
    rt.cancel(refs[50])
    assert spec._cancelled


def test_actor_max_task_retries_transparent_result(runtime):
    """Actor dies mid-call; with max_task_retries the caller sees the
    retried result, not ActorDiedError."""

    import tempfile

    marker = tempfile.mktemp(prefix="rt_flaky_")

    @rt.remote(max_restarts=2, max_task_retries=2)
    class Flaky:
        def __init__(self, marker):
            self.marker = marker

        def maybe_die(self):
            # first incarnation dies mid-call; the restart serves the retry
            if not os.path.exists(self.marker):
                open(self.marker, "w").close()
                os._exit(1)
            return "survived"

        def ping(self):
            return "pong"

    a = Flaky.remote(marker)
    assert rt.get(a.ping.remote()) == "pong"
    try:
        assert rt.get(a.maybe_die.remote(), timeout=60) == "survived"
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


def test_actor_without_task_retries_still_errors(runtime):
    @rt.remote(max_restarts=1)
    class Fragile:
        def die(self):
            os._exit(1)

    a = Fragile.remote()
    with pytest.raises((ActorDiedError, RayActorError)):
        rt.get(a.die.remote(), timeout=30)
