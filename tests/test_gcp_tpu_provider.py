"""GCP TPU-VM provider: slice-gang provisioning (round-3 VERDICT item 8).

Unit tier drives :class:`GcpTpuNodeProvider` against the fake gcloud API
(calls recorded, no processes); the integration tier runs ``rt up`` with a
``provider: gcp-tpu`` YAML where the fake's slice hosts are REAL local
agent processes — create→join→drain→delete end to end, plus STRICT gang
placement onto one slice via labels.

Reference anchors: ``python/ray/autoscaler/_private/gcp/node_provider.py``,
``python/ray/_private/accelerators/tpu.py:13-33``.
"""

import os
import time

import pytest

import ray_tpu as rt
from ray_tpu.autoscaler.demand import NodeTypeConfig
from ray_tpu.autoscaler.gcp import (
    FakeGcloudTpuAPI,
    GcpTpuNodeProvider,
    live_slice_hosts_fn,
)


def _ntype(pod):
    return NodeTypeConfig(name=pod, resources={"TPU": 8.0}, min_workers=0, max_workers=4)


# ---------------------------------------------------------------- unit
def test_create_records_gcloud_calls_and_labels():
    api = FakeGcloudTpuAPI(spawn=False)
    p = GcpTpuNodeProvider("head:1", zone="us-z", api=api, name_prefix="t")
    created = p.create_nodes(_ntype("v5e-16"), 1)
    assert created == ["t-v5e-16-1"]
    kinds = [c[0] for c in api.calls]
    # first use reconciles against the cloud listing, then creates
    assert kinds == ["list", "create", "ssh_all"]
    _, name, zone, accel, version = api.calls[1]
    assert (name, zone, accel) == ("t-v5e-16-1", "us-z", "v5e-16")
    # the shipped agent command carries slice-topology labels + resources
    cmd = api.calls[2][3]
    assert "ray_tpu.runtime.agent" in cmd
    assert "slice-id" in cmd and "t-v5e-16-1" in cmd
    assert "TPU-v5e-16-host" in cmd
    assert p.non_terminated_nodes() == {"t-v5e-16-1": "v5e-16"}


def test_terminate_deletes_tpu_vm():
    api = FakeGcloudTpuAPI(spawn=False)
    p = GcpTpuNodeProvider("head:1", zone="us-z", api=api)
    (name,) = p.create_nodes(_ntype("v5e-8"), 1)
    p.terminate_node(name)
    assert ("delete", name, "us-z") in api.calls
    assert p.non_terminated_nodes() == {}


def test_unknown_pod_type_rejected():
    p = GcpTpuNodeProvider("head:1", zone="z", api=FakeGcloudTpuAPI(spawn=False))
    with pytest.raises(ValueError):
        p.create_nodes(_ntype("v99-backwards"), 1)


def test_gang_join_timeout_is_all_or_nothing():
    """A slice whose hosts never join is DELETED (by the async gang
    watcher — create must not stall the autoscaler loop), never left
    half-registered."""
    api = FakeGcloudTpuAPI(spawn=False)
    p = GcpTpuNodeProvider(
        "head:1", zone="us-z", api=api,
        gang_join_timeout_s=0.5,
        live_slice_hosts=lambda slice_id: 0,  # nobody ever joins
    )
    (name,) = p.create_nodes(_ntype("v5e-16"), 1)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if ("delete", name, "us-z") in api.calls:
            break
        time.sleep(0.1)
    assert ("delete", name, "us-z") in api.calls
    assert p.non_terminated_nodes() == {}


def test_restart_reconciliation_adopts_and_advances_seq():
    """A fresh provider (head restart) adopts surviving slices from the
    cloud listing and never reuses their names."""
    api = FakeGcloudTpuAPI(spawn=False)
    p1 = GcpTpuNodeProvider("head:1", zone="us-z", api=api, name_prefix="t")
    p1.create_nodes(_ntype("v5e-8"), 2)  # t-v5e-8-1, t-v5e-8-2
    # new incarnation over the same cloud state
    p2 = GcpTpuNodeProvider("head:1", zone="us-z", api=api, name_prefix="t")
    adopted = p2.non_terminated_nodes()
    assert set(adopted) == {"t-v5e-8-1", "t-v5e-8-2"}
    assert adopted["t-v5e-8-1"] == "v5e-8"
    (new,) = p2.create_nodes(_ntype("v5e-8"), 1)
    assert new == "t-v5e-8-3"  # no collision with survivors


def test_external_deletion_reflected_in_non_terminated():
    api = FakeGcloudTpuAPI(spawn=False)
    p = GcpTpuNodeProvider("head:1", zone="us-z", api=api)
    (name,) = p.create_nodes(_ntype("v5e-8"), 1)
    # someone deletes the TPU out-of-band (quota reaper, console)
    api.vms.pop(name)
    assert p.non_terminated_nodes() == {}


# ------------------------------------------------------- integration
@pytest.mark.full
def test_rt_up_gcp_tpu_fake_full_lifecycle(tmp_path):
    """`rt up` with provider: gcp-tpu drives the fake through
    create→join→drain→delete; slice hosts are real agent processes carrying
    slice-topology labels; a STRICT gang PG lands on ONE slice."""
    import yaml

    from ray_tpu.autoscaler.launcher import ClusterLauncher, load_cluster_config

    config = {
        "cluster_name": "tputest",
        "provider": {"type": "gcp-tpu", "zone": "us-test2-b", "fake": True,
                     "gang_join_timeout_s": 90},
        "head": {"num_cpus": 2},
        "available_node_types": {
            "v5e-16": {"resources": {"TPU": 8}, "min_workers": 1, "max_workers": 2},
        },
        "max_workers": 4,
    }
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(config))

    launcher = ClusterLauncher(load_cluster_config(str(path)))
    try:
        launcher.up(wait_for_min_workers=False)
        cluster = rt.get_cluster()
        api = launcher.provider.api
        assert any(c[0] == "create" for c in api.calls)

        # gang join: BOTH hosts of the v5e-16 slice appear with labels
        count = live_slice_hosts_fn(cluster)
        slice_id = next(iter(launcher.provider.non_terminated_nodes()))
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline and count(slice_id) < 2:
            time.sleep(0.25)
        assert count(slice_id) == 2, f"only {count(slice_id)} slice hosts joined"
        members = [
            n for n in cluster.nodes.values()
            if not n.dead and (n.labels or {}).get("ray_tpu.io/slice-id") == slice_id
        ]
        indices = sorted(n.labels.get("ray_tpu.io/worker-index") for n in members)
        assert indices == ["0", "1"]
        assert all(n.labels.get("ray_tpu.io/pod-type") == "v5e-16" for n in members)

        # STRICT gang placement onto one slice via labels: one 8-chip
        # bundle per host of the SAME slice
        from ray_tpu.util.placement import placement_group, remove_placement_group

        pg = placement_group(
            [{"TPU": 8.0}, {"TPU": 8.0}],
            strategy="STRICT_SPREAD",
            labels={"ray_tpu.io/pod-type": "v5e-16"},
            pack_by_label="ray_tpu.io/slice-id",
        )
        assert pg.wait(timeout_seconds=30)
        info = cluster.control.placement_groups.get(pg.id)
        placed_nodes = set(info.bundle_placements.values())
        assert len(placed_nodes) == 2
        placed_slices = {
            cluster.nodes[nid].labels.get("ray_tpu.io/slice-id") for nid in placed_nodes
        }
        assert placed_slices == {slice_id}
        remove_placement_group(pg)

        # drain + delete: down() terminates the slice (fake records delete,
        # host agents exit)
        launcher.down()
        assert any(c[0] == "delete" for c in api.calls)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(
                n.dead or (n.labels or {}).get("ray_tpu.io/slice-id") != slice_id
                for n in cluster.nodes.values()
            ):
                break
            time.sleep(0.25)
        live = [
            n for n in cluster.nodes.values()
            if not n.dead and (n.labels or {}).get("ray_tpu.io/slice-id") == slice_id
        ]
        assert live == [], "slice hosts survived deletion"
    finally:
        launcher.down()
        if rt.is_initialized():
            rt.shutdown()
