"""Decode-attention kernel tests (interpret mode on CPU): vs reference
einsum over ragged lengths, GQA groups, multi-block streaming, and the
forward_with_cache integration."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.decode_attention import decode_attention


def _reference(q, kc, vc, lengths):
    B, H, D = q.shape
    Hkv, S = kc.shape[1], kc.shape[2]
    n_rep = H // Hkv
    keys = jnp.repeat(kc, n_rep, axis=1).astype(jnp.float32)   # [B, H, S, D]
    vals = jnp.repeat(vc, n_rep, axis=1).astype(jnp.float32)
    s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32), keys) / math.sqrt(D)
    mask = jnp.arange(S)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bhsd->bhd", p, vals)


@pytest.mark.parametrize("n_rep", [1, 4])
@pytest.mark.parametrize("block_s", [64, 128])
def test_matches_reference(n_rep, block_s):
    rng = np.random.default_rng(0)
    B, Hkv, S, D = 3, 2, 200, 32  # S not a block multiple: exercises padding
    H = Hkv * n_rep
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    lengths = jnp.asarray([1, 77, 200], jnp.int32)
    out = decode_attention(q, kc, vc, lengths, block_s=block_s)
    ref = _reference(q, kc, vc, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_bf16_cache():
    rng = np.random.default_rng(1)
    B, Hkv, S, D = 2, 2, 64, 16
    q = jnp.asarray(rng.standard_normal((B, 4, D)), jnp.bfloat16)
    kc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.bfloat16)
    vc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.bfloat16)
    lengths = jnp.asarray([5, 64], jnp.int32)
    out = decode_attention(q, kc, vc, lengths)
    ref = _reference(q, kc, vc, lengths)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=3e-2, atol=3e-2
    )


def test_forward_with_cache_kernel_path_matches_einsum():
    from ray_tpu.models import TransformerConfig, init_params
    from ray_tpu.models.generation import forward_with_cache, init_cache

    cfg = TransformerConfig(
        vocab_size=53, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
        attention="dense", dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.key(2))
    cache = init_cache(cfg, 2, 24)
    # prefill via the einsum path
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 53, (2, 6)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(6)[None, :], (2, 6))
    _, cache = forward_with_cache(cfg, params, cache, toks, pos)
    # one decode step, both paths, same cache
    tok = jnp.asarray([[7], [9]], jnp.int32)
    dpos = jnp.asarray([[6], [6]], jnp.int32)
    l_kernel, _ = forward_with_cache(cfg, params, cache, tok, dpos, use_decode_kernel=True)
    l_einsum, _ = forward_with_cache(cfg, params, cache, tok, dpos, use_decode_kernel=False)
    np.testing.assert_allclose(
        np.asarray(l_kernel), np.asarray(l_einsum), rtol=2e-4, atol=2e-4
    )


def test_generate_with_kernel_matches():
    """Full generate loop with the kernel forced on equals the einsum loop."""
    import functools

    from ray_tpu.models import TransformerConfig, init_params
    from ray_tpu.models.generation import decode_step, init_cache, prefill

    cfg = TransformerConfig(
        vocab_size=41, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        attention="dense", dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.key(4))
    prompt = jnp.asarray([[3, 5, 8]], jnp.int32)
    outs = {}
    for use in (True, False):
        cache = init_cache(cfg, 1, 8)
        logits, cache = prefill(cfg, params, cache, prompt, jnp.asarray([3], jnp.int32))
        toks = []
        pos = jnp.asarray([3], jnp.int32)
        for _ in range(4):
            t = jnp.argmax(logits, -1).astype(jnp.int32)
            toks.append(int(t[0]))
            logits, cache = __import__("ray_tpu.models.generation", fromlist=["forward_with_cache"]).forward_with_cache(
                cfg, params, cache, t[:, None], pos[:, None], use_decode_kernel=use
            )
            logits = logits[:, 0]
            pos = pos + 1
        outs[use] = toks
    assert outs[True] == outs[False]


def test_large_n_rep_sublane_rounding():
    """n_rep > 8 and not a multiple of 8 (rounds up to 16 sublanes)."""
    rng = np.random.default_rng(5)
    B, Hkv, n_rep, S, D = 2, 2, 12, 64, 16
    q = jnp.asarray(rng.standard_normal((B, Hkv * n_rep, D)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    lengths = jnp.asarray([10, 64], jnp.int32)
    out = decode_attention(q, kc, vc, lengths)
    ref = _reference(q, kc, vc, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_block_shrinks_to_divisor_instead_of_padding():
    """S=600 with block_s=512 -> block shrinks to 300 (divisor), no pad."""
    rng = np.random.default_rng(6)
    B, Hkv, S, D = 2, 1, 600, 32
    q = jnp.asarray(rng.standard_normal((B, 2, D)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    lengths = jnp.asarray([600, 123], jnp.int32)
    out = decode_attention(q, kc, vc, lengths, block_s=512)
    ref = _reference(q, kc, vc, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_zero_length_rows_yield_zeros():
    """An empty slot (lengths == 0) must emit zeros, not garbage-V means."""
    rng = np.random.default_rng(7)
    B, Hkv, S, D = 3, 2, 64, 16
    q = jnp.asarray(rng.standard_normal((B, 4, D)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    lengths = jnp.asarray([0, 5, 0], jnp.int32)
    out = np.asarray(decode_attention(q, kc, vc, lengths))
    assert (out[0] == 0).all() and (out[2] == 0).all()
    ref = _reference(q, kc, vc, lengths)
    np.testing.assert_allclose(out[1], np.asarray(ref[1]), rtol=2e-5, atol=2e-5)
