"""Seeded chaos regression suite (ISSUE 2 satellite).

Each entry is a ``(seed, ChaosSchedule, workload)`` triple run through
``ChaosRunner`` TWICE, asserting:

  * the deterministic fault log is identical across the two runs (for the
    workload-driven schedules, where every failpoint hit is caused by the
    workload — frame drops, put faults, spawn faults), and
  * the invariant sweep passes every time: tasks terminal exactly once per
    attempt, no silent object loss, refcounts back at baseline, retries
    visible as spans.

Time-driven entries (heartbeat partition — hits happen per report tick, so
run LENGTH varies with wall clock) assert positional decision consistency
on the common prefix instead of full equality, plus full recovery.

The node-kill entry drives the existing ``cluster.kill_node`` chaos hook
through the new schedule runner.
"""

import os
import time

import pytest

import ray_tpu as rt
from ray_tpu.chaos import ChaosEvent, ChaosRunner, ChaosSchedule
from ray_tpu.runtime import failpoints
from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def _assert_prefix_consistent(log_a, log_b):
    """Per failpoint, the injected-fault sequences must agree on the hit
    range both runs reached — the positional determinism contract for
    time-driven failpoints whose total hit counts differ run to run."""
    def by_fp(log):
        out = {}
        for e in log:
            out.setdefault(e["fp"], []).append(e)
        return out

    a_by, b_by = by_fp(log_a), by_fp(log_b)
    for fp_name in set(a_by) | set(b_by):
        a, b = a_by.get(fp_name, []), b_by.get(fp_name, [])
        if not a or not b:
            continue
        horizon = min(a[-1]["hit"], b[-1]["hit"])
        assert [e for e in a if e["hit"] <= horizon] == [
            e for e in b if e["hit"] <= horizon
        ], f"decision streams diverged for {fp_name}"


# --------------------------------------------------------------------------
# 1. frame-drop during push-shuffle (map on node B, reduce on head: every
#    reduce dependency crosses nodes through the in-process data plane)
# --------------------------------------------------------------------------
def test_schedule_frame_drop_during_push_shuffle(ray_start_cluster):
    rt_mod, cluster = ray_start_cluster
    node_b = cluster.add_node({"CPU": 2})
    head_id = cluster.head_node.node_id

    schedule = ChaosSchedule(
        [ChaosEvent(0.0, "arm", spec="data_plane.send_frame=drop(0.3)")],
        seed=21, name="frame-drop-shuffle",
    )

    def workload():
        @rt.remote(execution="thread")
        def map_block(i):
            return [i * 10 + j for j in range(5)]

        @rt.remote(execution="thread")
        def reduce_blocks(*blocks):
            return sorted(x for b in blocks for x in b)

        maps = [
            map_block.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(node_b.node_id)
            ).remote(i)
            for i in range(8)
        ]
        rt.wait(maps, num_returns=len(maps), timeout=30)
        out = reduce_blocks.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
        ).remote(*maps)
        expected = sorted(i * 10 + j for i in range(8) for j in range(5))
        assert rt.get(out, timeout=60) == expected
        return [out]

    r1 = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
    r2 = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
    assert r1.ok, (r1.workload_error, r1.invariants.violations)
    assert r2.ok, (r2.workload_error, r2.invariants.violations)
    assert r1.faults, "the drop failpoint must actually fire"
    assert r1.same_faults(r2), (r1.faults, r2.faults)
    assert all(f["fp"] == "data_plane.send_frame" for f in r1.faults)


# --------------------------------------------------------------------------
# 2. put-fault + object loss during lineage reconstruction
# --------------------------------------------------------------------------
def test_schedule_put_fault_during_lineage_reconstruction(ray_start_regular):
    schedule = ChaosSchedule(
        [
            ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)"),
            ChaosEvent(1.0, "lose_objects", fraction=0.6),
        ],
        seed=33, name="put-fault-lineage",
    )

    def workload():
        from ray_tpu.exceptions import ObjectLostError

        @rt.remote(max_retries=5, execution="thread")
        def produce(i):
            return i * 2

        task_refs = [produce.remote(i) for i in range(12)]
        rt.wait(task_refs, num_returns=len(task_refs), timeout=30)
        put_refs = []
        for i in range(8):
            while True:  # application-level retry: each miss consumes a hit
                try:
                    put_refs.append(rt.put(("blob", i)))
                    break
                except failpoints.FailpointInjected:
                    continue
        # sleep past the lose_objects event, then verify recovery:
        # task-produced objects REBUILD via lineage; put objects have no
        # lineage, so a lost one must RAISE ObjectLostError (loudly)
        time.sleep(1.3)
        assert rt.get(task_refs, timeout=60) == [i * 2 for i in range(12)]
        for i, ref in enumerate(put_refs):
            try:
                assert rt.get(ref, timeout=30) == ("blob", i)
            except ObjectLostError:
                pass
        return task_refs + put_refs

    r1 = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
    r2 = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
    assert r1.ok, (r1.workload_error, r1.invariants.violations)
    assert r2.ok, (r2.workload_error, r2.invariants.violations)
    assert any(f["fp"] == "object_store.put" for f in r1.faults)
    lose = [e for e in r1.events_applied if e["kind"] == "lose_objects"]
    assert lose and lose[0]["lost"] > 0
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 3. worker-spawn failure during (actor-creation) fan-out — sequential
#    creations make every spawn attempt workload-driven, so the fault log
#    is strictly reproducible
# --------------------------------------------------------------------------
def test_schedule_worker_spawn_failure_during_fanout():
    # 8 CPUs: five 1-CPU actors coexist with headroom — this test is about
    # spawn faults, not resource exhaustion
    rt.init(num_cpus=8, _system_config={"num_prestart_workers": 0})
    try:
        schedule = ChaosSchedule(
            [ChaosEvent(0.0, "arm", spec="worker_pool.spawn=raise(0.35)")],
            seed=47, name="spawn-failure-fanout",
        )

        def workload():
            @rt.remote(max_restarts=25)
            class Echo:
                def __init__(self, tag):
                    self.tag = tag

                def ping(self):
                    return self.tag

            refs, actors = [], []
            for i in range(5):
                a = Echo.remote(i)
                ref = a.ping.remote()
                assert rt.get(ref, timeout=60) == i
                refs.append(ref)
                actors.append(a)
            for a in actors:
                # release the dedicated workers + CPUs: the second run of
                # this workload must not inherit a crowded node
                rt.kill(a)
            return refs

        r1 = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        r2 = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert r1.ok, (r1.workload_error, r1.invariants.violations)
        assert r2.ok, (r2.workload_error, r2.invariants.violations)
        assert any(f["fp"] == "worker_pool.spawn" for f in r1.faults)
        assert r1.same_faults(r2), (r1.faults, r2.faults)
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# 4. heartbeat partition during actor calls (multihost: real agent process;
#    the head's ping rescue must keep the flapping node ALIVE and every
#    call must complete). Hits are per report tick (time-driven), so the
#    determinism assertion is positional consistency on the common prefix
#    of the two runs' agent-side fault logs.
# --------------------------------------------------------------------------
def _spawn_chaos_agent(address, fp_spec, seed):
    import subprocess
    import sys

    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["RAY_TPU_FAILPOINTS"] = fp_spec
    env["RAY_TPU_FAILPOINT_SEED"] = str(seed)
    log_dir = "/tmp/rt_agent_logs"
    os.makedirs(log_dir, exist_ok=True)
    log = open(os.path.join(log_dir, f"chaos_agent_{os.getpid()}_{time.monotonic_ns()}.log"), "w")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.runtime.agent", "--address", address,
             "--num-cpus", "2", "--resources", '{"remote": 4}'],
            env=env, stdout=subprocess.DEVNULL, stderr=log,
        )
    finally:
        log.close()


def _heartbeat_partition_run(seed):
    from ray_tpu.chaos import check_invariants, snapshot_baseline

    rt.init(num_cpus=2)
    proc = None
    try:
        cluster = rt.get_cluster()
        address = cluster.start_head_service()
        proc = _spawn_chaos_agent(
            address, "agent.heartbeat=drop(0.7)", seed
        )
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if sum(1 for n in cluster.nodes.values() if not n.dead) >= 2:
                break
            time.sleep(0.05)
        else:
            raise TimeoutError("agent never joined")

        baseline = snapshot_baseline()

        @rt.remote(resources={"remote": 1})
        class Counter:
            def __init__(self):
                self.n = 0

            def add(self, k):
                self.n += k
                return self.n

        c = Counter.remote()
        refs = [c.add.remote(1) for _ in range(15)]
        assert rt.get(refs, timeout=90) == list(range(1, 16))

        # evidence the partition is real: with reports every ~0.1s, a
        # >0.5s report gap only happens when heartbeats are being dropped
        handle = next(
            n for n in cluster.nodes.values()
            if not n.dead and n is not cluster.head_node
        )
        max_gap = 0.0
        for _ in range(30):
            max_gap = max(max_gap, time.monotonic() - handle.last_report)
            time.sleep(0.1)
        assert max_gap > 0.4, f"no heartbeat gap observed (max {max_gap:.2f}s)"
        # the ping rescue must have kept the flapping node alive
        assert not handle.dead

        report = check_invariants(refs=refs, baseline=baseline, timeout=60)
        assert report.ok, report.violations

        # the agent piggybacks its fault log on (surviving) reports
        agent_log = []
        settle = time.monotonic() + 10
        while time.monotonic() < settle:
            agent_log = list(getattr(handle, "chaos_faults", []) or [])
            if agent_log:
                break
            time.sleep(0.2)
        assert agent_log, "agent-side fault log never reached the head"
        assert all(f["fp"] == "agent.heartbeat" for f in agent_log)
        # the piggyback accumulates in append order; canonical order is
        # (fp, hit) — sort before cross-run comparison
        return sorted(agent_log, key=lambda e: (e["fp"], e["hit"]))
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        rt.shutdown()


def test_schedule_heartbeat_partition_during_actor_calls():
    log1 = _heartbeat_partition_run(seed=77)
    log2 = _heartbeat_partition_run(seed=77)
    _assert_prefix_consistent(log1, log2)


# --------------------------------------------------------------------------
# 5. node-kill schedule: the existing kill_node chaos hook driven through
#    the new runner, with the invariant sweep proving recovery
# --------------------------------------------------------------------------
def test_schedule_node_kill_through_runner(ray_start_cluster):
    rt_mod, cluster = ray_start_cluster
    cluster.add_node({"CPU": 2})

    schedule = ChaosSchedule(
        [ChaosEvent(0.4, "kill_node", index=0)],
        seed=5, name="node-kill",
    )

    def workload():
        @rt.remote(max_retries=4, execution="thread")
        def slow_double(i):
            time.sleep(0.8)
            return i * 2

        refs = [
            slow_double.options(scheduling_strategy="SPREAD").remote(i)
            for i in range(8)
        ]
        assert rt.get(refs, timeout=60) == [i * 2 for i in range(8)]
        return refs

    result = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
    assert result.ok, (result.workload_error, result.invariants.violations)
    killed = [e for e in result.events_applied if e["kind"] == "kill_node"]
    assert killed and "node" in killed[0]
    assert sum(1 for n in cluster.nodes.values() if n.dead) == 1


# --------------------------------------------------------------------------
# 6. relay-node kill mid-broadcast (ISSUE 4): a fanout-1 broadcast chain
#    head -> B -> C -> D with B killed by the schedule while it is serving
#    C.  C's failed edge takes the purge-then-retry path and re-parents
#    onto the surviving replica (the head); D — parked under C — completes
#    through the repaired chain.  The armed put failpoint makes every
#    commit attempt a workload-driven decision-stream hit: the broadcast
#    is fully sequential (gated), so same-seed runs produce byte-identical
#    fault logs even THROUGH the kill.
# --------------------------------------------------------------------------
def _relay_kill_run(seed):
    import threading

    import numpy as np

    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        node_b = cluster.add_node({"CPU": 1})  # schedule victim (index 0)
        node_c = cluster.add_node({"CPU": 1})
        node_d = cluster.add_node({"CPU": 1})

        schedule = ChaosSchedule(
            [
                ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)"),
                ChaosEvent(0.8, "kill_node", index=0),
            ],
            seed=seed, name="relay-kill-broadcast",
        )

        def workload():
            pm = cluster.pull_manager
            old_fanout = pm._fanout
            pm._fanout = 1  # chain topology: B is everyone's relay
            # the broadcast payload; the armed put failpoint may fire —
            # application-level retry consumes hits deterministically
            while True:
                try:
                    ref = rt.put(np.ones(4 << 20, np.uint8))
                    break
                except failpoints.FailpointInjected:
                    continue
            oid = ref.id()
            # hold B's outbound serve: C stays blocked mid-edge until the
            # schedule's kill lands, then the edge fails loudly
            trip = threading.Event()
            orig_get = node_b.store.get

            def tripping_get(o, timeout=None):
                assert trip.wait(60)
                raise RuntimeError("relay node died mid-serve")

            node_b.store.get = tripping_get
            try:
                done = {
                    n.node_id: threading.Event() for n in (node_b, node_c, node_d)
                }
                for n in (node_b, node_c, node_d):
                    cluster.pull_object(oid, n, done[n.node_id].set)
                assert done[node_b.node_id].wait(30)  # B holds a copy; C is
                #                                       blocked inside B's store
                deadline = time.monotonic() + 30
                while not node_b.dead and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert node_b.dead, "schedule kill never landed"
                trip.set()  # C's edge fails -> purge-then-retry -> the head
                assert done[node_c.node_id].wait(60)
                assert done[node_d.node_id].wait(60)
                assert node_c.store.contains(oid)
                assert node_d.store.contains(oid)
            finally:
                node_b.store.get = orig_get
                pm._fanout = old_fanout
            return [ref]

        result = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
        assert result.ok, (result.workload_error, result.invariants.violations)
        killed = [e for e in result.events_applied if e["kind"] == "kill_node"]
        assert killed and killed[0]["node"] == node_b.node_id.hex()[:8]
        assert cluster.pull_manager.retries >= 1  # the re-parenting retry
        return result
    finally:
        rt.shutdown()


def test_schedule_relay_node_kill_mid_broadcast():
    r1 = _relay_kill_run(seed=11)
    r2 = _relay_kill_run(seed=11)
    assert r1.faults, "the put failpoint must actually fire"
    assert all(f["fp"] == "object_store.put" for f in r1.faults)
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 7. stage-actor kill mid-compiled-plan (ISSUE 5): a 3-stage execution plan
#    spanning two nodes runs iterations through its installed channels while
#    an armed put failpoint generates a workload-driven decision stream;
#    killing the middle stage actor mid-plan must surface a TYPED error
#    (ActorDiedError) and flip the plan to BROKEN — and the same-seed runs'
#    fault logs stay byte-identical THROUGH the kill, because plan traffic
#    rides channels (zero store puts) and never perturbs the hit stream.
# --------------------------------------------------------------------------
def _plan_actor_kill_run(seed):
    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        cluster.add_node({"CPU": 2, "stage": 4})

        schedule = ChaosSchedule(
            [ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)")],
            seed=seed, name="plan-stage-kill",
        )

        def workload():
            from ray_tpu.dag import InputNode
            from ray_tpu.exceptions import ActorDiedError, RayActorError

            @rt.remote
            class Stage:
                def __init__(self, k):
                    self.k = k

                def step(self, x):
                    return x + self.k

            head = dict(execution="inproc")
            other = dict(execution="inproc", resources={"stage": 1}, num_cpus=0)
            s0 = Stage.options(**head).remote(1)
            s1 = Stage.options(**other).remote(10)
            s2 = Stage.options(**head).remote(100)
            with InputNode() as inp:
                d = s2.step.bind(s1.step.bind(s0.step.bind(inp)))
            plan = d.compile_plan(name="chaos")
            # deterministic failpoint hits: app-retried puts — each attempt
            # consumes exactly one decision-stream index
            refs = []
            for i in range(6):
                while True:
                    try:
                        refs.append(rt.put(("blob", i)))
                        break
                    except failpoints.FailpointInjected:
                        continue
            for i in range(10):
                assert plan.execute(i) == i + 111
            rt.kill(s1)  # mid-plan: installed, channels live, between iters
            deadline = time.monotonic() + 30
            raised = None
            while time.monotonic() < deadline:
                try:
                    plan.execute(0)
                except (ActorDiedError, RayActorError) as exc:
                    raised = exc
                    break
            assert isinstance(raised, (ActorDiedError, RayActorError)), raised
            assert plan.state == "BROKEN"
            plan.teardown()
            return refs

        r1 = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert r1.ok, (r1.workload_error, r1.invariants.violations)
        return r1
    finally:
        rt.shutdown()


def test_schedule_stage_actor_kill_mid_plan():
    r1 = _plan_actor_kill_run(seed=29)
    r2 = _plan_actor_kill_run(seed=29)
    assert r1.faults, "the put failpoint must actually fire"
    assert all(f["fp"] == "object_store.put" for f in r1.faults)
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# schedule JSON round trip + CLI-facing loader
# --------------------------------------------------------------------------
def test_schedule_json_round_trip(tmp_path):
    sched = ChaosSchedule(
        [
            ChaosEvent(0.0, "arm", spec="rpc.call=delay(0.1,0.2)"),
            ChaosEvent(1.0, "partition", fp="agent.heartbeat", duration=2.0),
            ChaosEvent(2.0, "kill_node", index=1),
        ],
        seed=9, name="round-trip",
    )
    path = str(tmp_path / "sched.json")
    sched.save(path)
    loaded = ChaosSchedule.load(path, seed=123)
    assert loaded.seed == 123  # explicit seed override
    assert loaded.name == "round-trip"
    assert [e.to_dict() for e in loaded.events] == [e.to_dict() for e in sched.events]
    assert loaded.duration() == 3.0
    with pytest.raises(ValueError, match="unknown chaos event kind"):
        ChaosEvent(0.0, "explode")
