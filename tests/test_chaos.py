"""Seeded chaos regression suite (ISSUE 2 satellite).

Each entry is a ``(seed, ChaosSchedule, workload)`` triple run through
``ChaosRunner`` TWICE, asserting:

  * the deterministic fault log is identical across the two runs (for the
    workload-driven schedules, where every failpoint hit is caused by the
    workload — frame drops, put faults, spawn faults), and
  * the invariant sweep passes every time: tasks terminal exactly once per
    attempt, no silent object loss, refcounts back at baseline, retries
    visible as spans.

Time-driven entries (heartbeat partition — hits happen per report tick, so
run LENGTH varies with wall clock) assert positional decision consistency
on the common prefix instead of full equality, plus full recovery.

The node-kill entry drives the existing ``cluster.kill_node`` chaos hook
through the new schedule runner.
"""

import os
import time

import pytest

import ray_tpu as rt
from ray_tpu.chaos import ChaosEvent, ChaosRunner, ChaosSchedule
from ray_tpu.runtime import failpoints
from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def _assert_prefix_consistent(log_a, log_b):
    """Per failpoint, the injected-fault sequences must agree on the hit
    range both runs reached — the positional determinism contract for
    time-driven failpoints whose total hit counts differ run to run."""
    def by_fp(log):
        out = {}
        for e in log:
            out.setdefault(e["fp"], []).append(e)
        return out

    a_by, b_by = by_fp(log_a), by_fp(log_b)
    for fp_name in set(a_by) | set(b_by):
        a, b = a_by.get(fp_name, []), b_by.get(fp_name, [])
        if not a or not b:
            continue
        horizon = min(a[-1]["hit"], b[-1]["hit"])
        assert [e for e in a if e["hit"] <= horizon] == [
            e for e in b if e["hit"] <= horizon
        ], f"decision streams diverged for {fp_name}"


# --------------------------------------------------------------------------
# 1. frame-drop during push-shuffle (map on node B, reduce on head: every
#    reduce dependency crosses nodes through the in-process data plane)
# --------------------------------------------------------------------------
def test_schedule_frame_drop_during_push_shuffle(ray_start_cluster):
    rt_mod, cluster = ray_start_cluster
    node_b = cluster.add_node({"CPU": 2})
    head_id = cluster.head_node.node_id

    schedule = ChaosSchedule(
        [ChaosEvent(0.0, "arm", spec="data_plane.send_frame=drop(0.3)")],
        seed=21, name="frame-drop-shuffle",
    )

    def workload():
        @rt.remote(execution="thread")
        def map_block(i):
            return [i * 10 + j for j in range(5)]

        @rt.remote(execution="thread")
        def reduce_blocks(*blocks):
            return sorted(x for b in blocks for x in b)

        maps = [
            map_block.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(node_b.node_id)
            ).remote(i)
            for i in range(8)
        ]
        rt.wait(maps, num_returns=len(maps), timeout=30)
        out = reduce_blocks.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
        ).remote(*maps)
        expected = sorted(i * 10 + j for i in range(8) for j in range(5))
        assert rt.get(out, timeout=60) == expected
        return [out]

    r1 = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
    r2 = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
    assert r1.ok, (r1.workload_error, r1.invariants.violations)
    assert r2.ok, (r2.workload_error, r2.invariants.violations)
    assert r1.faults, "the drop failpoint must actually fire"
    assert r1.same_faults(r2), (r1.faults, r2.faults)
    assert all(f["fp"] == "data_plane.send_frame" for f in r1.faults)


# --------------------------------------------------------------------------
# 2. put-fault + object loss during lineage reconstruction
# --------------------------------------------------------------------------
def test_schedule_put_fault_during_lineage_reconstruction(ray_start_regular):
    schedule = ChaosSchedule(
        [
            ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)"),
            ChaosEvent(1.0, "lose_objects", fraction=0.6),
        ],
        seed=33, name="put-fault-lineage",
    )

    def workload():
        from ray_tpu.exceptions import ObjectLostError

        @rt.remote(max_retries=5, execution="thread")
        def produce(i):
            return i * 2

        task_refs = [produce.remote(i) for i in range(12)]
        rt.wait(task_refs, num_returns=len(task_refs), timeout=30)
        put_refs = []
        for i in range(8):
            while True:  # application-level retry: each miss consumes a hit
                try:
                    put_refs.append(rt.put(("blob", i)))
                    break
                except failpoints.FailpointInjected:
                    continue
        # sleep past the lose_objects event, then verify recovery:
        # task-produced objects REBUILD via lineage; put objects have no
        # lineage, so a lost one must RAISE ObjectLostError (loudly)
        time.sleep(1.3)
        assert rt.get(task_refs, timeout=60) == [i * 2 for i in range(12)]
        for i, ref in enumerate(put_refs):
            try:
                assert rt.get(ref, timeout=30) == ("blob", i)
            except ObjectLostError:
                pass
        return task_refs + put_refs

    r1 = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
    r2 = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
    assert r1.ok, (r1.workload_error, r1.invariants.violations)
    assert r2.ok, (r2.workload_error, r2.invariants.violations)
    assert any(f["fp"] == "object_store.put" for f in r1.faults)
    lose = [e for e in r1.events_applied if e["kind"] == "lose_objects"]
    assert lose and lose[0]["lost"] > 0
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 3. worker-spawn failure during (actor-creation) fan-out — sequential
#    creations make every spawn attempt workload-driven, so the fault log
#    is strictly reproducible
# --------------------------------------------------------------------------
def test_schedule_worker_spawn_failure_during_fanout():
    # 8 CPUs: five 1-CPU actors coexist with headroom — this test is about
    # spawn faults, not resource exhaustion
    rt.init(num_cpus=8, _system_config={"num_prestart_workers": 0})
    try:
        schedule = ChaosSchedule(
            [ChaosEvent(0.0, "arm", spec="worker_pool.spawn=raise(0.35)")],
            seed=47, name="spawn-failure-fanout",
        )

        def workload():
            @rt.remote(max_restarts=25)
            class Echo:
                def __init__(self, tag):
                    self.tag = tag

                def ping(self):
                    return self.tag

            refs, actors = [], []
            for i in range(5):
                a = Echo.remote(i)
                ref = a.ping.remote()
                assert rt.get(ref, timeout=60) == i
                refs.append(ref)
                actors.append(a)
            for a in actors:
                # release the dedicated workers + CPUs: the second run of
                # this workload must not inherit a crowded node
                rt.kill(a)
            return refs

        r1 = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        r2 = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert r1.ok, (r1.workload_error, r1.invariants.violations)
        assert r2.ok, (r2.workload_error, r2.invariants.violations)
        assert any(f["fp"] == "worker_pool.spawn" for f in r1.faults)
        assert r1.same_faults(r2), (r1.faults, r2.faults)
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# 4. heartbeat partition during actor calls (multihost: real agent process;
#    the head's ping rescue must keep the flapping node ALIVE and every
#    call must complete). Hits are per report tick (time-driven), so the
#    determinism assertion is positional consistency on the common prefix
#    of the two runs' agent-side fault logs.
# --------------------------------------------------------------------------
def _spawn_chaos_agent(address, fp_spec, seed):
    import subprocess
    import sys

    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["RAY_TPU_FAILPOINTS"] = fp_spec
    env["RAY_TPU_FAILPOINT_SEED"] = str(seed)
    log_dir = "/tmp/rt_agent_logs"
    os.makedirs(log_dir, exist_ok=True)
    log = open(os.path.join(log_dir, f"chaos_agent_{os.getpid()}_{time.monotonic_ns()}.log"), "w")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.runtime.agent", "--address", address,
             "--num-cpus", "2", "--resources", '{"remote": 4}'],
            env=env, stdout=subprocess.DEVNULL, stderr=log,
        )
    finally:
        log.close()


def _heartbeat_partition_run(seed):
    from ray_tpu.chaos import check_invariants, snapshot_baseline

    rt.init(num_cpus=2)
    proc = None
    try:
        cluster = rt.get_cluster()
        address = cluster.start_head_service()
        proc = _spawn_chaos_agent(
            address, "agent.heartbeat=drop(0.7)", seed
        )
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if sum(1 for n in cluster.nodes.values() if not n.dead) >= 2:
                break
            time.sleep(0.05)
        else:
            raise TimeoutError("agent never joined")

        baseline = snapshot_baseline()

        @rt.remote(resources={"remote": 1})
        class Counter:
            def __init__(self):
                self.n = 0

            def add(self, k):
                self.n += k
                return self.n

        c = Counter.remote()
        refs = [c.add.remote(1) for _ in range(15)]
        assert rt.get(refs, timeout=90) == list(range(1, 16))

        # evidence the partition is real: with reports every ~0.1s, a
        # >0.5s report gap only happens when heartbeats are being dropped
        handle = next(
            n for n in cluster.nodes.values()
            if not n.dead and n is not cluster.head_node
        )
        max_gap = 0.0
        for _ in range(30):
            max_gap = max(max_gap, time.monotonic() - handle.last_report)
            time.sleep(0.1)
        assert max_gap > 0.4, f"no heartbeat gap observed (max {max_gap:.2f}s)"
        # the ping rescue must have kept the flapping node alive
        assert not handle.dead

        report = check_invariants(refs=refs, baseline=baseline, timeout=60)
        assert report.ok, report.violations

        # the agent piggybacks its fault log on (surviving) reports
        agent_log = []
        settle = time.monotonic() + 10
        while time.monotonic() < settle:
            agent_log = list(getattr(handle, "chaos_faults", []) or [])
            if agent_log:
                break
            time.sleep(0.2)
        assert agent_log, "agent-side fault log never reached the head"
        assert all(f["fp"] == "agent.heartbeat" for f in agent_log)
        # the piggyback accumulates in append order; canonical order is
        # (fp, hit) — sort before cross-run comparison
        return sorted(agent_log, key=lambda e: (e["fp"], e["hit"]))
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        rt.shutdown()


def test_schedule_heartbeat_partition_during_actor_calls():
    log1 = _heartbeat_partition_run(seed=77)
    log2 = _heartbeat_partition_run(seed=77)
    _assert_prefix_consistent(log1, log2)


# --------------------------------------------------------------------------
# 5. node-kill schedule: the existing kill_node chaos hook driven through
#    the new runner, with the invariant sweep proving recovery
# --------------------------------------------------------------------------
def test_schedule_node_kill_through_runner(ray_start_cluster):
    rt_mod, cluster = ray_start_cluster
    cluster.add_node({"CPU": 2})

    schedule = ChaosSchedule(
        [ChaosEvent(0.4, "kill_node", index=0)],
        seed=5, name="node-kill",
    )

    def workload():
        @rt.remote(max_retries=4, execution="thread")
        def slow_double(i):
            time.sleep(0.8)
            return i * 2

        refs = [
            slow_double.options(scheduling_strategy="SPREAD").remote(i)
            for i in range(8)
        ]
        assert rt.get(refs, timeout=60) == [i * 2 for i in range(8)]
        return refs

    result = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
    assert result.ok, (result.workload_error, result.invariants.violations)
    killed = [e for e in result.events_applied if e["kind"] == "kill_node"]
    assert killed and "node" in killed[0]
    assert sum(1 for n in cluster.nodes.values() if n.dead) == 1


# --------------------------------------------------------------------------
# 6. relay-node kill mid-broadcast (ISSUE 4): a fanout-1 broadcast chain
#    head -> B -> C -> D with B killed by the schedule while it is serving
#    C.  C's failed edge takes the purge-then-retry path and re-parents
#    onto the surviving replica (the head); D — parked under C — completes
#    through the repaired chain.  The armed put failpoint makes every
#    commit attempt a workload-driven decision-stream hit: the broadcast
#    is fully sequential (gated), so same-seed runs produce byte-identical
#    fault logs even THROUGH the kill.
# --------------------------------------------------------------------------
def _relay_kill_run(seed):
    import threading

    import numpy as np

    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        node_b = cluster.add_node({"CPU": 1})  # schedule victim (index 0)
        node_c = cluster.add_node({"CPU": 1})
        node_d = cluster.add_node({"CPU": 1})

        schedule = ChaosSchedule(
            [
                ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)"),
                ChaosEvent(0.8, "kill_node", index=0),
            ],
            seed=seed, name="relay-kill-broadcast",
        )

        def workload():
            pm = cluster.pull_manager
            old_fanout = pm._fanout
            pm._fanout = 1  # chain topology: B is everyone's relay
            # the broadcast payload; the armed put failpoint may fire —
            # application-level retry consumes hits deterministically
            while True:
                try:
                    ref = rt.put(np.ones(4 << 20, np.uint8))
                    break
                except failpoints.FailpointInjected:
                    continue
            oid = ref.id()
            # hold B's outbound serve: C stays blocked mid-edge until the
            # schedule's kill lands, then the edge fails loudly
            trip = threading.Event()
            orig_get = node_b.store.get

            def tripping_get(o, timeout=None):
                assert trip.wait(60)
                raise RuntimeError("relay node died mid-serve")

            node_b.store.get = tripping_get
            try:
                done = {
                    n.node_id: threading.Event() for n in (node_b, node_c, node_d)
                }
                for n in (node_b, node_c, node_d):
                    cluster.pull_object(oid, n, done[n.node_id].set)
                assert done[node_b.node_id].wait(30)  # B holds a copy; C is
                #                                       blocked inside B's store
                deadline = time.monotonic() + 30
                while not node_b.dead and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert node_b.dead, "schedule kill never landed"
                trip.set()  # C's edge fails -> purge-then-retry -> the head
                assert done[node_c.node_id].wait(60)
                assert done[node_d.node_id].wait(60)
                assert node_c.store.contains(oid)
                assert node_d.store.contains(oid)
            finally:
                node_b.store.get = orig_get
                pm._fanout = old_fanout
            return [ref]

        result = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
        assert result.ok, (result.workload_error, result.invariants.violations)
        killed = [e for e in result.events_applied if e["kind"] == "kill_node"]
        assert killed and killed[0]["node"] == node_b.node_id.hex()[:8]
        assert cluster.pull_manager.retries >= 1  # the re-parenting retry
        return result
    finally:
        rt.shutdown()


def test_schedule_relay_node_kill_mid_broadcast():
    r1 = _relay_kill_run(seed=11)
    r2 = _relay_kill_run(seed=11)
    assert r1.faults, "the put failpoint must actually fire"
    assert all(f["fp"] == "object_store.put" for f in r1.faults)
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 7. stage-actor kill mid-compiled-plan (ISSUE 5): a 3-stage execution plan
#    spanning two nodes runs iterations through its installed channels while
#    an armed put failpoint generates a workload-driven decision stream;
#    killing the middle stage actor mid-plan must surface a TYPED error
#    (ActorDiedError) and flip the plan to BROKEN — and the same-seed runs'
#    fault logs stay byte-identical THROUGH the kill, because plan traffic
#    rides channels (zero store puts) and never perturbs the hit stream.
# --------------------------------------------------------------------------
def _plan_actor_kill_run(seed):
    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        cluster.add_node({"CPU": 2, "stage": 4})

        schedule = ChaosSchedule(
            [ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)")],
            seed=seed, name="plan-stage-kill",
        )

        def workload():
            from ray_tpu.dag import InputNode
            from ray_tpu.exceptions import ActorDiedError, RayActorError

            @rt.remote
            class Stage:
                def __init__(self, k):
                    self.k = k

                def step(self, x):
                    return x + self.k

            head = dict(execution="inproc")
            other = dict(execution="inproc", resources={"stage": 1}, num_cpus=0)
            s0 = Stage.options(**head).remote(1)
            s1 = Stage.options(**other).remote(10)
            s2 = Stage.options(**head).remote(100)
            with InputNode() as inp:
                d = s2.step.bind(s1.step.bind(s0.step.bind(inp)))
            plan = d.compile_plan(name="chaos")
            # deterministic failpoint hits: app-retried puts — each attempt
            # consumes exactly one decision-stream index
            refs = []
            for i in range(6):
                while True:
                    try:
                        refs.append(rt.put(("blob", i)))
                        break
                    except failpoints.FailpointInjected:
                        continue
            for i in range(10):
                assert plan.execute(i) == i + 111
            rt.kill(s1)  # mid-plan: installed, channels live, between iters
            deadline = time.monotonic() + 30
            raised = None
            while time.monotonic() < deadline:
                try:
                    plan.execute(0)
                except (ActorDiedError, RayActorError) as exc:
                    raised = exc
                    break
            assert isinstance(raised, (ActorDiedError, RayActorError)), raised
            assert plan.state == "BROKEN"
            plan.teardown()
            return refs

        r1 = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert r1.ok, (r1.workload_error, r1.invariants.violations)
        return r1
    finally:
        rt.shutdown()


def test_schedule_stage_actor_kill_mid_plan():
    r1 = _plan_actor_kill_run(seed=29)
    r2 = _plan_actor_kill_run(seed=29)
    assert r1.faults, "the put failpoint must actually fire"
    assert all(f["fp"] == "object_store.put" for f in r1.faults)
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 8. drain a relay node mid-broadcast (ISSUE 6): the fanout-1 chain of
#    test 6 (head -> B -> C -> D) with the relay GRACEFULLY drained instead
#    of killed.  B holds no sole-replica objects (its broadcast copy also
#    lives at the head), so the drain's evacuation is a no-op and its
#    terminate lands while C is still blocked mid-edge — C re-parents onto
#    the surviving replica through purge-then-retry, parked D completes
#    through the repaired chain, and the elasticity invariants (drain lost
#    nothing, every ref resolves) hold.  The armed put failpoint makes the
#    decision stream workload-driven: same-seed fault logs are
#    byte-identical THROUGH the drain.
# --------------------------------------------------------------------------
def _relay_drain_run(seed):
    import threading

    import numpy as np

    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        node_b = cluster.add_node({"CPU": 1})  # schedule victim (index 0)
        node_c = cluster.add_node({"CPU": 1})
        node_d = cluster.add_node({"CPU": 1})

        schedule = ChaosSchedule(
            [
                ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)"),
                ChaosEvent(0.8, "drain_node", index=0, timeout=5.0),
            ],
            seed=seed, name="relay-drain-broadcast",
        )

        def workload():
            pm = cluster.pull_manager
            old_fanout = pm._fanout
            pm._fanout = 1  # chain topology: B is everyone's relay
            while True:
                try:
                    ref = rt.put(np.ones(4 << 20, np.uint8))
                    break
                except failpoints.FailpointInjected:
                    continue
            oid = ref.id()
            # hold B's outbound serve: C stays blocked mid-edge until the
            # schedule's drain terminates B, then the edge fails loudly
            trip = threading.Event()
            orig_get = node_b.store.get

            def tripping_get(o, timeout=None):
                assert trip.wait(60)
                raise RuntimeError("relay node drained mid-serve")

            node_b.store.get = tripping_get
            try:
                done = {
                    n.node_id: threading.Event() for n in (node_b, node_c, node_d)
                }
                for n in (node_b, node_c, node_d):
                    cluster.pull_object(oid, n, done[n.node_id].set)
                assert done[node_b.node_id].wait(30)  # B holds a copy; C is
                #                                       blocked inside B's store
                deadline = time.monotonic() + 30
                while not node_b.dead and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert node_b.dead, "schedule drain never landed"
                trip.set()  # C's edge fails -> purge-then-retry -> the head
                assert done[node_c.node_id].wait(60)
                assert done[node_d.node_id].wait(60)
                assert node_c.store.contains(oid)
                assert node_d.store.contains(oid)
            finally:
                node_b.store.get = orig_get
                pm._fanout = old_fanout
            return [ref]

        result = ChaosRunner(schedule, quiesce_timeout=60).run(workload)
        assert result.ok, (result.workload_error, result.invariants.violations)
        drained = [e for e in result.events_applied if e["kind"] == "drain_node"]
        assert drained and drained[0]["node"] == node_b.node_id.hex()[:8]
        # nothing was sole-replica on B: the drain had nothing to evacuate
        # and nothing to lose (elasticity invariant 6 audited this)
        assert drained[0]["evacuated"] == 0
        assert cluster.drain_reports[-1]["failed_evacuations"] == 0
        assert cluster.pull_manager.retries >= 1  # the re-parenting retry
        return result
    finally:
        rt.shutdown()


def test_schedule_relay_node_drain_mid_broadcast():
    r1 = _relay_drain_run(seed=13)
    r2 = _relay_drain_run(seed=13)
    assert r1.faults, "the put failpoint must actually fire"
    assert all(f["fp"] == "object_store.put" for f in r1.faults)
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 9. kill_head + restart_head mid-workload (ISSUE 6): a live workload (an
#    actor with in-process state, app-retried puts driving the decision
#    stream) runs across a full head outage.  The kill-time snapshot carries
#    the failpoint hit counters, the restart re-adopts the live node and
#    reconciles the actor instance back to ALIVE, work resumes — and the
#    same-seed fault logs are byte-identical ACROSS the restart boundary.
#    A doomed-incarnation KV write between kill and restart is discarded,
#    exactly what a write to a dying GCS loses.
# --------------------------------------------------------------------------
def _head_outage_run(seed):
    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        cluster.add_node({"CPU": 2})

        schedule = ChaosSchedule(
            [
                ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)"),
                ChaosEvent(2.0, "kill_head"),
                ChaosEvent(3.5, "restart_head"),
            ],
            seed=seed, name="head-outage",
        )

        def retried_puts(tag, n):
            out = []
            for i in range(n):
                while True:
                    try:
                        out.append(rt.put((tag, i)))
                        break
                    except failpoints.FailpointInjected:
                        continue
            return out

        def workload():
            t0 = time.monotonic()

            @rt.remote
            class Counter:
                def __init__(self):
                    self.n = 0

                def add(self, k):
                    self.n += k
                    return self.n

            c = Counter.options(name="outage-counter", max_restarts=1).remote()
            # ---- phase 1: everything resolves BEFORE the kill lands ----
            refs = retried_puts("pre", 6)
            assert rt.get([c.add.remote(1) for _ in range(5)], timeout=30) == [
                1, 2, 3, 4, 5
            ]
            cluster.control.kv.put(b"outage_marker", b"pre-kill")
            # ---- the outage window: quiesce through kill + restart ----
            while time.monotonic() - t0 < 4.2:
                time.sleep(0.05)
                if cluster._head_down:
                    # doomed-incarnation write: must vanish at restart
                    cluster.control.kv.put(b"doomed_marker", b"lost")
            assert cluster.head_restarts >= 1, "restart_head never landed"
            # ---- phase 2: the fabric works after the restart ----
            assert cluster.control.kv.get(b"outage_marker") == b"pre-kill"
            assert cluster.control.kv.get(b"doomed_marker") is None
            refs += retried_puts("post", 6)
            # the named record survived the outage AND the live instance
            # reconciled: in-process state (n == 5) carried through
            c2 = rt.get_actor("outage-counter")
            assert rt.get(c2.add.remote(1), timeout=30) == 6
            return refs

        result = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert result.ok, (result.workload_error, result.invariants.violations)
        kinds = [e["kind"] for e in result.events_applied]
        assert kinds.count("kill_head") == 1 and kinds.count("restart_head") == 1
        restart = next(e for e in result.events_applied if e["kind"] == "restart_head")
        assert restart["reconciled"] >= 1
        return result
    finally:
        rt.shutdown()


def test_schedule_head_outage_mid_workload():
    r1 = _head_outage_run(seed=61)
    r2 = _head_outage_run(seed=61)
    assert r1.faults, "the put failpoint must actually fire"
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 10. kill a plan stage node, then auto-repair (ISSUE 6): a compiled plan
#     with a restartable stage actor on a doomed node keeps executing while
#     the schedule kills that node.  The plan flips BROKEN (typed error),
#     the restart FSM revives the actor on the surviving "stage" node, the
#     auto-repair thread reinstalls onto it, and subsequent iterations
#     produce correct outputs — READY -> BROKEN -> READY, audited by the
#     invariant sweep from the cluster's transition log.
# --------------------------------------------------------------------------
def _plan_auto_repair_run(seed):
    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        node_b = cluster.add_node({"CPU": 1, "stage": 1})  # victim (index 0)
        cluster.add_node({"CPU": 1, "stage": 1})           # restart target

        schedule = ChaosSchedule(
            [
                ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)"),
                ChaosEvent(1.0, "kill_node", index=0),
            ],
            seed=seed, name="plan-node-kill-auto-repair",
        )

        def workload():
            from ray_tpu.dag import InputNode
            from ray_tpu.exceptions import (
                ActorDiedError,
                RayActorError,
                WorkerCrashedError,
            )

            @rt.remote
            class Stage:
                def __init__(self, k):
                    self.k = k

                def step(self, x):
                    return x + self.k

            # s0/s2 pinned to the head: default placement could land them
            # on the doomed node, where max_restarts=0 would (correctly)
            # make the plan unrepairable — not what this test is about
            head = dict(
                execution="inproc",
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    cluster.head_node.node_id
                ),
            )
            s0 = Stage.options(**head).remote(1)
            s1 = Stage.options(
                execution="inproc", num_cpus=0, resources={"stage": 1},
                max_restarts=1,
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_b.node_id, soft=True
                ),
            ).remote(10)
            s2 = Stage.options(**head).remote(100)
            with InputNode() as inp:
                d = s2.step.bind(s1.step.bind(s0.step.bind(inp)))
            plan = d.compile_plan(name="self-healing", auto_repair=True)
            refs = []
            for i in range(4):
                while True:
                    try:
                        refs.append(rt.put(("blob", i)))
                        break
                    except failpoints.FailpointInjected:
                        continue
            # iterate THROUGH the node kill: broken iterations surface
            # typed errors, auto-repair reinstalls, iterations resume
            saw_break = False
            deadline = time.monotonic() + 45
            completed_after_break = 0
            while time.monotonic() < deadline and completed_after_break < 5:
                try:
                    assert plan.execute(7) == 118
                    if saw_break:
                        completed_after_break += 1
                    elif node_b.dead:
                        # raced: repair finished before an execute failed
                        saw_break = True
                except (ActorDiedError, RayActorError, WorkerCrashedError):
                    saw_break = True
                    time.sleep(0.05)
            assert saw_break, "the stage-node kill never surfaced"
            assert completed_after_break >= 5, "plan never healed"
            assert plan.state == "READY"
            assert "BROKEN" in plan.state_history
            assert plan.state_history[-1] == "READY"
            plan.teardown()
            return refs

        result = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert result.ok, (result.workload_error, result.invariants.violations)
        killed = [e for e in result.events_applied if e["kind"] == "kill_node"]
        assert killed and killed[0]["node"] == node_b.node_id.hex()[:8]
        return result
    finally:
        rt.shutdown()


def test_schedule_plan_stage_node_kill_auto_repair():
    r1 = _plan_auto_repair_run(seed=37)
    r2 = _plan_auto_repair_run(seed=37)
    assert r1.faults, "the put failpoint must actually fire"
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 11. the full elasticity schedule (ISSUE 6 acceptance): ONE seeded timeline
#     containing add_node, drain_node, kill_head, AND restart_head runs a
#     live workload to completion — the drained node's sole-replica objects
#     evacuate (zero loss with survivors present), the head outage discards
#     doomed writes and reconciles on restart, and the fault log is
#     byte-identical across two same-seed runs INCLUDING across the head
#     restart boundary (every put/transfer hit is workload-driven).
# --------------------------------------------------------------------------
def _elasticity_run(seed):
    import numpy as np

    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        node_b = cluster.add_node({"CPU": 1})  # drain victim (index 0)

        schedule = ChaosSchedule(
            [
                ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)"),
                ChaosEvent(0.6, "add_node", resources={"CPU": 1}),
                ChaosEvent(1.2, "drain_node", index=0, timeout=10.0),
                ChaosEvent(2.4, "kill_head"),
                ChaosEvent(3.9, "restart_head"),
            ],
            seed=seed, name="full-elasticity",
        )

        def retried_puts(tag, n):
            out = []
            for i in range(n):
                while True:
                    try:
                        out.append(rt.put((tag, i)))
                        break
                    except failpoints.FailpointInjected:
                        continue
            return out

        def workload():
            t0 = time.monotonic()

            @rt.remote(execution="thread", max_retries=4)
            def produce(i):
                return np.full(150_000, i, np.uint8)

            # sole replicas on the doomed node: the drain MUST evacuate them
            refs = [
                produce.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(node_b.node_id)
                ).remote(i)
                for i in range(4)
            ]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and any(
                not cluster.directory.locations(r.id()) for r in refs
            ):
                time.sleep(0.02)
            put_refs = retried_puts("pre", 4)
            # ---- wait out the drain (t=1.2), then prove zero loss ----
            while time.monotonic() - t0 < 2.2:
                time.sleep(0.05)
            assert node_b.dead, "schedule drain never landed"
            values = rt.get(refs, timeout=30)
            assert all(
                v[0] == i and v.nbytes == 150_000 for i, v in enumerate(values)
            ), "evacuated objects must survive the drain byte-for-byte"
            # ---- wait out the head outage (kill 2.4 -> restart 3.9) ----
            while time.monotonic() - t0 < 4.6:
                time.sleep(0.05)
            assert cluster.head_restarts >= 1, "restart_head never landed"
            # ---- the elastic fabric still works end to end ----
            put_refs += retried_puts("post", 4)
            added = [
                n for n in cluster.nodes.values()
                if not n.dead and n is not cluster.head_node
            ]
            assert added, "the add_node event's node must be live"
            out = produce.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    added[0].node_id
                )
            ).remote(9)
            assert rt.get(out, timeout=30)[0] == 9
            return refs + put_refs + [out]

        result = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert result.ok, (result.workload_error, result.invariants.violations)
        kinds = [e["kind"] for e in result.events_applied]
        for kind in ("add_node", "drain_node", "kill_head", "restart_head"):
            assert kind in kinds, f"{kind} never applied: {result.events_applied}"
        drained = next(e for e in result.events_applied if e["kind"] == "drain_node")
        assert drained["evacuated"] == 4 and drained["outcome"] == "ok"
        assert cluster.drain_reports[-1]["failed_evacuations"] == 0
        return result
    finally:
        rt.shutdown()


def test_schedule_full_elasticity_byte_identical_through_restart():
    r1 = _elasticity_run(seed=101)
    r2 = _elasticity_run(seed=101)
    assert r1.faults, "the put failpoint must actually fire"
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 11. leased-worker kill mid-workload (ISSUE 7): dispatch faults hit the
#     LEASED direct-dispatch path (repeat-shape tasks riding one cached
#     lease) and the lease-pinned process worker is SIGKILLed between
#     bursts — retries flow through the normal FSM, the lease machinery
#     re-pins, and the same-seed fault logs stay byte-identical.
# --------------------------------------------------------------------------
def test_schedule_leased_worker_kill_mid_push():
    rt.init(
        num_cpus=2,
        _system_config={
            "num_prestart_workers": 0,
            # keep every "auto" task in process workers so the leased path
            # exercises worker pinning (the kill target)
            "inproc_task_threshold_s": 0.0,
        },
    )
    try:
        schedule = ChaosSchedule(
            [ChaosEvent(0.0, "arm", spec="scheduler.dispatch=raise(0.12)")],
            seed=61, name="leased-worker-kill",
        )

        def workload():
            @rt.remote(max_retries=25)
            def bump():
                return 1

            cluster = rt.get_cluster()
            pool = cluster.head_node.worker_pool
            # burst 1 rides the freshly-granted lease (dispatch faults
            # land on leased submissions; the FSM retries them)
            assert rt.get([bump.remote() for _ in range(15)], timeout=90) == [1] * 15
            assert cluster.lease_manager.reuse_hits >= 10
            # sequential calls land on an IDLE worker, forming the pin
            # (the async burst above arrived before any worker existed)
            for _ in range(3):
                assert rt.get(bump.remote(), timeout=90) == 1
            # kill the lease-pinned worker at a QUIESCENT point (nothing
            # in flight -> the kill adds no nondeterministic retries, so
            # both runs see the identical dispatch-hit sequence)
            with pool._lock:
                pinned = list(pool._lease_pins.values())
            assert pinned, "leased shape never pinned a process worker"
            for w in pinned:
                try:
                    w.proc.kill()
                except OSError:
                    pass
            for w in pinned:
                try:
                    w.proc.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    pass
            # burst 2: the dead pin is detected, the pool re-pins/regrows,
            # every task still completes through the lease path
            refs = [bump.remote() for _ in range(15)]
            assert rt.get(refs, timeout=90) == [1] * 15
            return refs

        r1 = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        r2 = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert r1.ok, (r1.workload_error, r1.invariants.violations)
        assert r2.ok, (r2.workload_error, r2.invariants.violations)
        assert any(f["fp"] == "scheduler.dispatch" for f in r1.faults)
        assert r1.same_faults(r2), (r1.faults, r2.faults)
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# schedule JSON round trip + CLI-facing loader
# --------------------------------------------------------------------------
def test_schedule_json_round_trip(tmp_path):
    sched = ChaosSchedule(
        [
            ChaosEvent(0.0, "arm", spec="rpc.call=delay(0.1,0.2)"),
            ChaosEvent(1.0, "partition", fp="agent.heartbeat", duration=2.0),
            ChaosEvent(2.0, "kill_node", index=1),
        ],
        seed=9, name="round-trip",
    )
    path = str(tmp_path / "sched.json")
    sched.save(path)
    loaded = ChaosSchedule.load(path, seed=123)
    assert loaded.seed == 123  # explicit seed override
    assert loaded.name == "round-trip"
    assert [e.to_dict() for e in loaded.events] == [e.to_dict() for e in sched.events]
    assert loaded.duration() == 3.0
    with pytest.raises(ValueError, match="unknown chaos event kind"):
        ChaosEvent(0.0, "explode")


# --------------------------------------------------------------------------
# 12. partition-heal smoke matrix (ISSUE 8): a gray-partitioned node keeps
#     executing after its death declaration — every commit from the fenced
#     incarnation is rejected, the resubmitted attempts own the results,
#     the healed (fresh) node serves new work, and the same-seed fault logs
#     are byte-identical run to run, at THREE seeds.
# --------------------------------------------------------------------------
_PARTITION_HEAL_SCHEDULE = {
    "name": "partition-heal",
    "events": [
        {"t": 0.0, "kind": "arm", "spec": "scheduler.dispatch=raise(0.08)"},
        {"t": 0.2, "kind": "slow_node", "index": 0, "delay": 0.05},
        {"t": 0.45, "kind": "partition_node", "index": 0},
        {"t": 0.9, "kind": "heal_partition"},
        {"t": 1.1, "kind": "disarm"},
    ],
}


def _partition_heal_run(seed):
    from ray_tpu import api
    from ray_tpu.chaos.schedule import validate_schedule
    from ray_tpu.observability import metric_defs
    from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy

    sched_dict = dict(_PARTITION_HEAL_SCHEDULE, seed=seed)
    assert validate_schedule(sched_dict, num_nodes=1) == []
    rt.init(num_cpus=1)
    try:
        cluster = api.get_cluster()
        victim = cluster.add_node({"CPU": 2})
        fences0 = len(cluster.fence_events)
        schedule = ChaosSchedule.from_dict(sched_dict)

        def workload():
            @rt.remote(max_retries=6)
            def bump(i):
                time.sleep(0.12)
                return i + 1

            # soft affinity onto the victim: tasks are IN FLIGHT there when
            # the partition lands, so the stale incarnation tries to commit
            strat = NodeAffinitySchedulingStrategy(victim.node_id, soft=True)
            return [bump.options(scheduling_strategy=strat).remote(i) for i in range(20)]

        result = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert result.ok, (result.workload_error, result.invariants.violations)
        # the split-brain regression: the fenced incarnation DID try to
        # commit, and every attempt was rejected (invariants 9/10 audited
        # the directory + terminal records inside result.invariants)
        assert len(cluster.fence_events) > fences0, "no fenced commit observed"
        assert metric_defs.FENCED_FRAMES.get(tags={"kind": "task_finished"}) > 0
        # the healed (fresh) node serves new work
        fresh = [
            n for n in cluster.nodes.values()
            if not n.dead and n is not cluster.head_node
        ]
        assert fresh, "heal_partition never produced a fresh node"

        @rt.remote
        def after_heal(x):
            return x * 10

        strat = NodeAffinitySchedulingStrategy(fresh[0].node_id)
        assert rt.get(after_heal.options(scheduling_strategy=strat).remote(4), timeout=30) == 40
        return result
    finally:
        rt.shutdown()


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_schedule_partition_heal_matrix(seed):
    """Seeded smoke matrix: each seed runs TWICE and must produce
    byte-identical fault logs through the partition, the fencing, and the
    heal (the chaos determinism contract survives gray failures)."""
    r1 = _partition_heal_run(seed)
    r2 = _partition_heal_run(seed)
    assert r1.same_faults(r2), (r1.faults, r2.faults)


def test_chaos_validate_cli_partition_heal(tmp_path, capsys):
    """`rt chaos validate` schema-checks the new kinds end to end."""
    import json as _json

    from ray_tpu.chaos.schedule import validate_cli

    path = str(tmp_path / "partition_heal.json")
    with open(path, "w") as f:
        _json.dump(dict(_PARTITION_HEAL_SCHEDULE, seed=1), f)

    class Args:
        schedule = path
        nodes = 1

    assert validate_cli(Args()) == 0
    # a heal without a partition fails validation loudly
    bad = {"seed": 1, "events": [{"t": 0.0, "kind": "heal_partition"}]}
    with open(path, "w") as f:
        _json.dump(bad, f)
    assert validate_cli(Args()) == 1


# --------------------------------------------------------------------------
# 14. kill one SPMD gang member mid-execute_async (ISSUE 11): the plan
#     flips BROKEN with a typed ActorDiedError, repair() waits for the
#     restart FSM and reinstalls the whole group (warmup re-primed), and
#     iterations resume — with same-seed fault logs byte-identical (every
#     failpoint hit is a workload-driven retried put; gang traffic rides
#     the same send_frame failpoint as every other frame, unarmed here).
# --------------------------------------------------------------------------
def _gang_member_kill_run(seed):
    rt.init(num_cpus=2)
    try:
        schedule = ChaosSchedule(
            [ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)")],
            seed=seed, name="gang-member-kill",
        )

        def workload():
            import jax
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.dag import InputNode, StageGroup
            from ray_tpu.exceptions import ActorDiedError, RayActorError

            step_fn = jax.jit(lambda x: x + 1.0)

            @rt.remote
            class Member:
                def step(self, x):
                    return step_fn(x)

            members = [
                Member.options(execution="inproc", max_restarts=1).remote()
                for _ in range(2)
            ]
            gang = StageGroup(members, "step", split_axis=0, warmup=((4, 8), "float32"))
            with InputNode() as inp:
                d = gang.bind(inp)
            plan = d.compile_plan(name="gang-chaos")
            # deterministic failpoint hits: app-retried puts — each attempt
            # consumes exactly one decision-stream index
            refs = []
            for i in range(10):
                while True:
                    try:
                        refs.append(rt.put(("blob", i)))
                        break
                    except failpoints.FailpointInjected:
                        continue
            x = jnp.ones((4, 8), jnp.float32)
            for _ in range(10):
                out = plan.execute(x)
                assert float(np.asarray(out).sum()) == 4 * 8 * 2
            # kill one member with an iteration in flight
            fut = plan.execute_async(x)
            rt.kill(members[1], no_restart=False)
            raised = None
            try:
                fut.result(timeout=30)
            except (ActorDiedError, RayActorError) as exc:
                raised = exc
            deadline = time.monotonic() + 30
            while raised is None and time.monotonic() < deadline:
                try:
                    plan.execute(x)
                except (ActorDiedError, RayActorError) as exc:
                    raised = exc
                    break
            assert isinstance(raised, (ActorDiedError, RayActorError)), raised
            assert plan.state == "BROKEN"
            # the restart FSM revives the member; repair reinstalls the gang
            plan.repair(timeout=30)
            assert plan.state == "READY"
            for _ in range(5):
                out = plan.execute(x)
                assert float(np.asarray(out).sum()) == 4 * 8 * 2
            plan.teardown()
            return refs

        result = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert result.ok, (result.workload_error, result.invariants.violations)
        return result
    finally:
        rt.shutdown()


def test_schedule_gang_member_kill_repair_byte_identical():
    r1 = _gang_member_kill_run(seed=53)
    r2 = _gang_member_kill_run(seed=53)
    assert r1.faults, "the put failpoint must actually fire"
    assert all(f["fp"] == "object_store.put" for f in r1.faults)
    assert r1.same_faults(r2), (r1.faults, r2.faults)


# --------------------------------------------------------------------------
# 15. elastic gang training under chaos (ISSUE 17): the schedule hard-KILLS
#     one gang member mid-step (typed death -> BROKEN -> recover: restore
#     the latest step checkpoint, shrink-rebuild, resume) and then PREEMPTS
#     another gracefully (checkpoint -> shrink -> continue — the serving-
#     burst ladder).  Invariant 12 replays every repair audit against an
#     uninterrupted single-process run from the same checkpoint state and
#     byte-compares the loss trajectories.  Neither injector consumes
#     failpoint decisions, so same-seed fault logs stay byte-identical
#     (every logged fault is a workload-driven retried put).
# --------------------------------------------------------------------------
def _train_gang_chaos_run(seed):
    rt.init(num_cpus=4)
    try:
        schedule = ChaosSchedule(
            [
                ChaosEvent(0.0, "arm", spec="object_store.put=raise(0.4)"),
                ChaosEvent(1.0, "preempt_gang_member", job="chaos_gang",
                           graceful=False),
                ChaosEvent(2.0, "preempt_gang_member", job="chaos_gang",
                           graceful=True),
            ],
            seed=seed, name="train-gang-kill-preempt",
        )

        def workload():
            from ray_tpu.train.controller import TrainController

            ctl = TrainController(
                "chaos_gang", world_size=4, batch_size=8, feature_dim=4,
                seed=29, checkpoint_period=2, preemptible=True,
            )
            # deterministic failpoint hits: app-retried puts — each attempt
            # consumes exactly one decision-stream index
            refs = []
            for i in range(10):
                while True:
                    try:
                        refs.append(rt.put(("train", i)))
                        break
                    except failpoints.FailpointInjected:
                        continue
            # train through both scheduled disruptions; the recovery
            # ladder (checkpoint restore -> repair/shrink) is armed
            deadline = time.monotonic() + 2.6
            while time.monotonic() < deadline:
                ctl.run(1, auto_repair=True)
            # a few post-disruption steps so invariant 12 has a resumed
            # trajectory to replay
            ctl.run(3, auto_repair=True)
            assert ctl.repair_history, "the chaos kill never triggered a repair"
            outcomes = {r["outcome"] for r in ctl.repair_history}
            assert outcomes <= {"repaired", "shrunk"}, outcomes
            assert any(
                r["reason"] == "preempt" for r in ctl.resize_history
            ), "the graceful preempt never resized the gang"
            assert ctl.world_size < 4
            ctl.shutdown()
            return refs

        result = ChaosRunner(schedule, quiesce_timeout=90).run(workload)
        assert result.ok, (result.workload_error, result.invariants.violations)
        preempts = [
            e for e in result.events_applied
            if e["kind"] == "preempt_gang_member"
        ]
        assert len(preempts) == 2 and all(
            e.get("job") == "chaos_gang" for e in preempts
        ), preempts
        assert result.invariants.checked.get("train_repairs", 0) >= 1
        assert result.invariants.checked.get("train_replayed_steps", 0) >= 1
        return result
    finally:
        rt.shutdown()


@pytest.mark.parametrize("seed", [37, 59])
def test_schedule_train_gang_kill_preempt_byte_identical(seed):
    r1 = _train_gang_chaos_run(seed)
    r2 = _train_gang_chaos_run(seed)
    assert r1.faults, "the put failpoint must actually fire"
    assert all(f["fp"] == "object_store.put" for f in r1.faults)
    assert r1.same_faults(r2), (r1.faults, r2.faults)
