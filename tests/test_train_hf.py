"""HuggingFace integration tests: Accelerate gangs and a real
transformers.Trainer over the worker-group fabric (reference parity:
train/tests/test_torch_accelerate.py + transformers integration tests —
models built from config, no hub access)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import ScalingConfig


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_accelerate_trainer_gang():
    from ray_tpu.train.huggingface import AccelerateTrainer

    def loop(config):
        import torch
        from accelerate import Accelerator

        acc = Accelerator(cpu=True)
        model = torch.nn.Linear(4, 1)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        model, opt = acc.prepare(model, opt)
        x = torch.randn(16, 4)
        y = x.sum(dim=1, keepdim=True)
        for _ in range(3):
            loss = ((model(x) - y) ** 2).mean()
            acc.backward(loss)
            opt.step()
            opt.zero_grad()
        train.report(
            {
                "loss": float(loss.detach()),
                "world": acc.num_processes,
                "rank": acc.process_index,
            }
        )

    trainer = AccelerateTrainer(loop, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.metrics["world"] == 2


def test_transformers_trainer_tiny_model(tmp_path):
    from ray_tpu.train.huggingface import TransformersTrainer

    def trainer_init(config):
        import torch
        from transformers import GPT2Config, GPT2LMHeadModel, Trainer, TrainingArguments

        model = GPT2LMHeadModel(
            GPT2Config(vocab_size=64, n_positions=16, n_embd=16, n_layer=1, n_head=2)
        )

        class Toks(torch.utils.data.Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                ids = torch.randint(0, 64, (8,))
                return {"input_ids": ids, "labels": ids.clone()}

        args = TrainingArguments(
            output_dir=str(tmp_path / "hf_out"),
            per_device_train_batch_size=4,
            max_steps=2,
            logging_steps=1,
            report_to=[],
            use_cpu=True,
            save_strategy="no",
        )
        return Trainer(model=model, args=args, train_dataset=Toks())

    trainer = TransformersTrainer(
        trainer_init, scaling_config=ScalingConfig(num_workers=1)
    )
    result = trainer.fit()
    assert "loss" in result.metrics or "train_loss" in result.metrics
