"""Test fixtures.

Parity with the reference's ``python/ray/tests/conftest.py``: a
``ray_start_regular``-style fixture for a fresh single-node runtime, and a
``ray_start_cluster`` fixture that builds multi-node clusters in one process
(reference: ``python/ray/cluster_utils.py:135`` spawns extra raylets; here
extra Node objects share one control service).

JAX runs on a virtual 8-device CPU mesh so sharding/collective tests work
without TPU hardware.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# No arena pre-fault in tests: populating a 2 GB segment per rt.init steals
# ~0.5 s of the single core per test for bandwidth no test needs (the bench
# keeps it — that is where cold-page memcpy rates matter).
os.environ.setdefault("RAY_TPU_SHM_PREFAULT", "0")

# The image's sitecustomize registers the axon TPU backend and pins
# JAX_PLATFORMS; config.update is the override that sticks.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the suite compiles hundreds of tiny jitted
# programs (8-device mesh shardings, pallas kernels, train steps) — on a
# 1-core box recompiling them every run is a large share of suite wall
# time. Cache survives across runs in the repo's .jax_cache.
jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(__file__), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--full", action="store_true", default=False,
        help="run the full tier (slow/soak tests) in addition to the smoke tier",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "full: slow/soak tests excluded from the default smoke tier "
        "(run with --full; always run before capturing BENCH/MULTICHIP artifacts)",
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--full"):
        return
    skip_full = pytest.mark.skip(reason="full tier: run with --full")
    for item in items:
        if "full" in item.keywords:
            item.add_marker(skip_full)


@pytest.fixture
def ray_start_regular():
    import ray_tpu as rt

    rt.init(num_cpus=4)
    try:
        yield rt
    finally:
        rt.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Multi-node in-process cluster; yields (rt, cluster)."""
    import ray_tpu as rt

    cluster = rt.init(num_cpus=2)
    try:
        yield rt, cluster
    finally:
        rt.shutdown()
