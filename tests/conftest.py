"""Test fixtures.

Parity with the reference's ``python/ray/tests/conftest.py``: a
``ray_start_regular``-style fixture for a fresh single-node runtime, and a
``ray_start_cluster`` fixture that builds multi-node clusters in one process
(reference: ``python/ray/cluster_utils.py:135`` spawns extra raylets; here
extra Node objects share one control service).

JAX runs on a virtual 8-device CPU mesh so sharding/collective tests work
without TPU hardware.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The image's sitecustomize registers the axon TPU backend and pins
# JAX_PLATFORMS; config.update is the override that sticks.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    import ray_tpu as rt

    rt.init(num_cpus=4)
    try:
        yield rt
    finally:
        rt.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Multi-node in-process cluster; yields (rt, cluster)."""
    import ray_tpu as rt

    cluster = rt.init(num_cpus=2)
    try:
        yield rt, cluster
    finally:
        rt.shutdown()
