"""Stress/sanitizer tier (round-2 VERDICT item 10).

* ``test_asan_native_stress`` builds the C++ stress harness under
  AddressSanitizer and runs it (reference role: ``.bazelrc:104-127``
  ``--config=asan``) — allocator/refcount/eviction races in the shm store
  and IO pool are exercised from 10 threads.
* ``test_fabric_stress`` hammers the Python fabric: concurrent put/get,
  task submission and object transfer racing ``add_node``/``kill_node``
  chaos, asserting the runtime neither deadlocks nor corrupts results.
"""

import os
import shutil
import subprocess
import threading
import time

import numpy as np
import pytest

import ray_tpu as rt

pytestmark = pytest.mark.full  # stress + sanitizer legs; always run before capturing artifacts

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "ray_tpu", "native"
)


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++ toolchain")
def test_asan_native_stress():
    res = subprocess.run(
        ["make", "asan"], cwd=NATIVE_DIR, capture_output=True, text=True, timeout=600
    )
    assert res.returncode == 0, f"ASAN stress failed:\n{res.stdout}\n{res.stderr}"
    assert "stress: OK" in res.stdout


def test_fabric_stress():
    rt.init(num_cpus=4)
    try:
        cluster = rt.get_cluster()
        stop = threading.Event()
        errors = []

        @rt.remote
        def double(a):
            return a * 2

        def put_get_loop(seed):
            rng = np.random.default_rng(seed)
            try:
                while not stop.is_set():
                    arr = rng.random(int(rng.integers(100, 20_000)))
                    ref = rt.put(arr)
                    out = rt.get(ref)
                    if out.shape != arr.shape:
                        errors.append("shape mismatch")
            except Exception as exc:  # noqa: BLE001
                errors.append(f"put_get: {exc!r}")

        def task_loop(seed):
            rng = np.random.default_rng(seed)
            try:
                while not stop.is_set():
                    x = float(rng.random())
                    if abs(rt.get(double.remote(x), timeout=60) - 2 * x) > 1e-9:
                        errors.append("bad task result")
            except Exception as exc:  # noqa: BLE001
                errors.append(f"task: {exc!r}")

        def chaos_loop():
            try:
                while not stop.is_set():
                    node = cluster.add_node({"CPU": 1, "chaos": 1})
                    time.sleep(0.3)
                    cluster.kill_node(node.node_id)
                    time.sleep(0.1)
            except Exception as exc:  # noqa: BLE001
                errors.append(f"chaos: {exc!r}")

        threads = (
            [threading.Thread(target=put_get_loop, args=(i,)) for i in range(3)]
            + [threading.Thread(target=task_loop, args=(10 + i,)) for i in range(3)]
            + [threading.Thread(target=chaos_loop)]
        )
        for t in threads:
            t.start()
        time.sleep(6.0)
        stop.set()
        for t in threads:
            t.join(timeout=90)
            assert not t.is_alive(), "stress thread hung (deadlock)"
        assert not errors, errors[:5]
    finally:
        rt.shutdown()


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++ toolchain")
def test_tsan_native_stress():
    """ThreadSanitizer leg over the shm store + io pool stress harness
    (reference role: .bazelrc:104-127 --config=tsan) — the r04 shm
    open-race (robust-mutex trample under concurrency) is exactly the bug
    class this catches."""
    res = subprocess.run(
        ["make", "tsan"], cwd=NATIVE_DIR, capture_output=True, text=True, timeout=600
    )
    assert res.returncode == 0, f"TSAN stress failed:\n{res.stdout}\n{res.stderr}"
    assert "stress: OK" in res.stdout


@pytest.mark.skipif(shutil.which("gcc") is None, reason="no toolchain")
def test_asan_hotpath_extension():
    """The C id types + FrameDecoder run their FULL parity suites under
    AddressSanitizer: an ASAN-instrumented build of the extension is
    selected via RAY_TPU_HOTPATH_LIB and loaded into a pytest subprocess
    with the asan runtime LD_PRELOADed."""
    import glob
    import sys

    build = subprocess.run(
        ["make", "-s", f"PYTHON={sys.executable}", "_hotpath_asan.so"],
        cwd=NATIVE_DIR, capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, build.stderr
    libasan = sorted(glob.glob("/usr/lib/gcc/*/*/libasan.so")) or sorted(
        glob.glob("/usr/lib/*/libasan.so*")
    )
    if not libasan:
        pytest.skip("no libasan runtime found")
    env = dict(os.environ)
    env.update(
        LD_PRELOAD=libasan[-1],
        RAY_TPU_HOTPATH_LIB="_hotpath_asan.so",
        # CPython leaks by design at interpreter exit; we want memory
        # ERRORS (overflow/UAF in the extension), not leak reports
        ASAN_OPTIONS="detect_leaks=0,abort_on_error=1",
        JAX_PLATFORMS="cpu",
    )
    res = subprocess.run(
        [sys.executable, "-m", "pytest", "-x", "-q",
         "tests/test_native_ids.py", "tests/test_native_frames.py"],
        cwd=os.path.dirname(os.path.dirname(NATIVE_DIR)),
        capture_output=True, text=True, timeout=900, env=env,
    )
    assert res.returncode == 0, f"ASAN hotpath run failed:\n{res.stdout[-3000:]}\n{res.stderr[-3000:]}"
    assert "passed" in res.stdout
