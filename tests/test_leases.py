"""Worker leases + peer-to-peer direct dispatch (ISSUE 7).

The lifecycle under test: the FIRST task of a scheduling key pays one head
scheduling decision (grant); every repeat-shape task reuses the cached
lease with ZERO head-side work (the O(tasks) -> O(lease churn) acceptance
bar, asserted via ``ClusterScheduler.num_picks``); leases return on idle
expiry, revoke on node death/DRAINING, spill back to a fresh grant when
the leased node saturates while an alternative exists, and pin a warm
process worker that rejoins the pool when the lease goes away.  Actor
calls get the same treatment through cached direct routes.  Cross-process
leases push tasks peer-to-peer on the data plane with owner-routed result
frames (no per-task head control RPCs).
"""

import os
import subprocess
import sys
import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu.observability import metric_defs


def _wait_for(predicate, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# --------------------------------------------------------------------------
# grant-once / reuse-many: the O(K) -> O(1) head-RPC collapse
# --------------------------------------------------------------------------
def test_repeat_shape_tasks_one_grant_zero_picks():
    rt.init(num_cpus=2)
    try:

        @rt.remote
        def noop():
            return 1

        # warm: the first submission grants the lease (and trials the fn
        # in a process worker for the adaptive tier)
        assert rt.get([noop.remote() for _ in range(20)], timeout=60) == [1] * 20
        cluster = rt.get_cluster()
        picks0 = cluster.cluster_scheduler.num_picks
        grants0 = cluster.lease_manager.grants
        hits0 = cluster.lease_manager.reuse_hits
        assert rt.get([noop.remote() for _ in range(300)], timeout=120) == [1] * 300
        # steady state: ZERO head scheduling decisions for 300 repeat tasks
        assert cluster.cluster_scheduler.num_picks - picks0 == 0
        assert cluster.lease_manager.grants == grants0
        assert cluster.lease_manager.reuse_hits - hits0 >= 300
        snap = cluster.lease_manager.snapshot()
        assert snap["active"], snap
        assert snap["active"][0]["function"] == "noop"
    finally:
        rt.shutdown()


def test_multi_client_workload_o_n_head_rpcs():
    """K repeat-shape tasks from N concurrent clients: the head's
    scheduling work is bounded by lease churn (~O(N) at worst), never
    O(K) — the ISSUE 7 acceptance assertion."""
    rt.init(num_cpus=4)
    try:

        @rt.remote
        def noop():
            return None

        rt.get([noop.remote() for _ in range(20)], timeout=60)  # grant + warm
        cluster = rt.get_cluster()
        picks0 = cluster.cluster_scheduler.num_picks
        n_clients, per_client = 4, 250
        errors = []

        def client():
            try:
                rt.get([noop.remote() for _ in range(per_client)], timeout=120)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=client) for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        picks = cluster.cluster_scheduler.num_picks - picks0
        # single node: reuse hits cover everything — no spillback possible,
        # so the bound is lease churn, not K=1000.  Allow slack for an idle
        # expiry racing the run.
        assert picks <= n_clients, f"{picks} head picks for {n_clients * per_client} tasks"
        assert metric_defs.HEAD_RPCS_AVOIDED.get() > 0
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# idle expiry -> return -> re-grant
# --------------------------------------------------------------------------
def test_lease_idle_expiry_returns_and_regrants():
    rt.init(num_cpus=2, _system_config={"lease_idle_timeout_s": 0.3})
    try:

        @rt.remote
        def noop():
            return None

        rt.get([noop.remote() for _ in range(5)], timeout=60)
        cluster = rt.get_cluster()
        lm = cluster.lease_manager
        assert lm.grants == 1
        time.sleep(0.8)  # past lease_idle_timeout_s
        rt.get(noop.remote(), timeout=60)
        assert lm.expired >= 1
        assert lm.grants == 2  # the post-expiry task re-granted
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# revocation on node death
# --------------------------------------------------------------------------
def test_lease_revoked_on_node_kill():
    cluster = rt.init(num_cpus=2)
    try:
        aux = cluster.add_node({"CPU": 1, "aux": 1})

        @rt.remote(resources={"aux": 1}, num_cpus=0, execution="thread")
        def on_aux():
            return 1

        assert rt.get([on_aux.remote() for _ in range(5)], timeout=60) == [1] * 5
        lm = cluster.lease_manager
        assert lm.leases_on(aux.node_id) == 1
        cluster.kill_node(aux.node_id)
        assert lm.leases_on(aux.node_id) == 0
        assert lm.snapshot()["revoked"] >= 1
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# spillback when the leased node saturates
# --------------------------------------------------------------------------
def test_lease_spillback_spreads_under_saturation():
    cluster = rt.init(num_cpus=1)
    try:
        cluster.add_node({"CPU": 1})

        @rt.remote(execution="thread")
        def where():
            time.sleep(0.15)  # hold the CPU so the local queue builds
            return rt.get_runtime_context().get_node_id()

        nodes_seen = set(rt.get([where.remote() for _ in range(10)], timeout=60))
        assert len(nodes_seen) >= 2, nodes_seen  # spillback found the second node
        assert cluster.lease_manager.spillbacks >= 1
        assert metric_defs.LEASE_GRANTS.get(tags={"reason": "spillback"}) >= 1
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# worker pinning: a leased shape holds a warm process worker; revocation
# returns it to the pool
# --------------------------------------------------------------------------
def test_leased_process_worker_pinned_then_returned():
    # inproc_task_threshold_s=0 keeps every "auto" task in process workers,
    # so the leased dispatches exercise the pin path
    cluster = rt.init(num_cpus=2, _system_config={"inproc_task_threshold_s": 0.0})
    try:

        @rt.remote
        def proc_task():
            return os.getpid()

        pids = rt.get([proc_task.remote() for _ in range(10)], timeout=60)
        assert all(p != os.getpid() for p in pids)  # really ran out of process
        pool = cluster.head_node.worker_pool
        assert _wait_for(lambda: bool(pool._lease_pins), timeout=10)
        cluster.lease_manager.revoke_node(cluster.head_node.node_id)
        assert _wait_for(lambda: not pool._lease_pins, timeout=10)
        # the returned worker is reusable — next submit re-grants and runs
        assert rt.get(proc_task.remote(), timeout=60)
    finally:
        rt.shutdown()


def test_many_shapes_never_deadlock_on_pinned_workers():
    """Regression: with more leased shapes than pool workers, every worker
    ends up pinned to SOME shape — a fresh shape's task must steal a free
    pin instead of backlogging behind idle-but-pinned processes forever
    (a pin reserves warmth, never capacity)."""
    rt.init(num_cpus=2, _system_config={"inproc_task_threshold_s": 0.0})
    try:
        # 6 distinct shapes sequentially on a 2-worker pool: each grant
        # pins, later shapes must still run
        for i in range(6):

            @rt.remote
            def shape(i=i):
                return i

            shape._rt_name = f"shape_{i}"
            assert rt.get([shape.remote() for _ in range(3)], timeout=60) == [i] * 3
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# lease-ineligible shapes keep their policies
# --------------------------------------------------------------------------
def test_strategy_and_dep_tasks_bypass_leases():
    cluster = rt.init(num_cpus=2)
    try:
        n2 = cluster.add_node({"CPU": 2})
        from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy

        @rt.remote(execution="thread")
        def where():
            return rt.get_runtime_context().get_node_id()

        strategy = NodeAffinitySchedulingStrategy(n2.node_id)
        for _ in range(5):
            assert (
                rt.get(where.options(scheduling_strategy=strategy).remote(), timeout=60)
                == n2.node_id.hex()
            )
        # dep-bearing tasks take the scheduled path (locality stage intact)
        picks0 = cluster.cluster_scheduler.num_picks

        @rt.remote(execution="thread")
        def consume(x):
            return x

        ref = rt.put(7)
        assert rt.get([consume.remote(ref) for _ in range(5)], timeout=60) == [7] * 5
        assert cluster.cluster_scheduler.num_picks - picks0 >= 5
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# actor direct routes (the actor-shaped lease)
# --------------------------------------------------------------------------
def test_actor_direct_route_ordering_and_counts():
    rt.init(num_cpus=2)
    try:

        @rt.remote
        class Counter:
            def __init__(self):
                self.x = 0

            def inc(self):
                self.x += 1
                return self.x

        a = Counter.remote()
        assert rt.get(a.inc.remote(), timeout=60) == 1
        cluster = rt.get_cluster()
        before = cluster.actor_route_stats()["direct_submits"]
        vals = rt.get([a.inc.remote() for _ in range(50)], timeout=60)
        assert vals == list(range(2, 52))  # per-actor order preserved
        stats = cluster.actor_route_stats()
        assert stats["active_routes"] >= 1
        assert stats["direct_submits"] - before >= 45
        rt.kill(a)
        assert _wait_for(
            lambda: cluster.actor_route_stats()["active_routes"] == 0, timeout=10
        )
    finally:
        rt.shutdown()


def test_actor_direct_route_survives_restart():
    rt.init(num_cpus=2)
    try:

        @rt.remote(max_restarts=2)
        class Echo:
            def ping(self):
                return "pong"

        a = Echo.remote()
        assert rt.get(a.ping.remote(), timeout=60) == "pong"
        cluster = rt.get_cluster()
        assert cluster.actor_route_stats()["active_routes"] == 1
        rt.kill(a, no_restart=False)  # restart FSM brings it back
        # the route revokes with the death and re-grants on the restart
        assert rt.get(a.ping.remote(), timeout=60) == "pong"
        assert _wait_for(
            lambda: cluster.actor_route_stats()["active_routes"] == 1, timeout=10
        )
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# cross-process: leased tasks push peer-to-peer, results owner-routed
# --------------------------------------------------------------------------
def _spawn_agent(address, resources='{"remote": 4}'):
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.runtime.agent", "--address", address,
         "--num-cpus", "2", "--resources", resources],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def test_remote_lease_pushes_tasks_on_data_plane():
    import numpy as np

    cluster = rt.init(num_cpus=1)
    proc = None
    try:
        address = cluster.start_head_service()
        proc = _spawn_agent(address)
        assert _wait_for(
            lambda: sum(1 for n in cluster.nodes.values() if not n.dead) >= 2,
            timeout=60,
        )

        @rt.remote(resources={"remote": 1}, num_cpus=0)
        def remote_nine():
            return 9

        assert rt.get(remote_nine.remote(), timeout=120) == 9  # grant + warm
        pushes0 = metric_defs.DIRECT_PUSHES.get(tags={"transport": "data_plane"})
        picks0 = cluster.cluster_scheduler.num_picks
        assert rt.get([remote_nine.remote() for _ in range(40)], timeout=120) == [9] * 40
        # O(lease churn), not O(tasks): ~zero head decisions for 40 repeat
        # tasks.  A saturated leased queue may legitimately trigger ONE
        # spillback re-grant (a designed pick, rate-limited to 50ms/lease)
        # on a loaded box — tolerate that, not per-task scheduling.
        assert cluster.cluster_scheduler.num_picks - picks0 <= 2
        # a meaningful share of the burst rode push_task frames (the
        # 16-in-flight cap bounds how many can be outstanding at once —
        # on a slow box the whole burst lands before any push completes,
        # so the floor is below the cap; overflow legitimately takes the
        # control path)
        assert (
            metric_defs.DIRECT_PUSHES.get(tags={"transport": "data_plane"}) - pushes0
            >= 10
        )

        # bulk results commit lazily: bytes stay on the agent, the owner
        # records the location, the consumer pulls peer-to-peer
        @rt.remote(resources={"remote": 1}, num_cpus=0)
        def remote_big():
            return np.ones(1 << 20, np.uint8)

        rt.get(remote_big.remote(), timeout=120)  # grant
        out = rt.get(remote_big.remote(), timeout=120)  # leased push, lazy reply
        assert out.nbytes == 1 << 20 and int(out[0]) == 1

        # a worker-minted put whose ref rides the owner-routed push reply
        # races its own control-channel registration (nothing orders the
        # two channels): the metadata grace window in _try_recover must let
        # the notice land instead of tombstoning the object as lost
        @rt.remote(resources={"remote": 1}, num_cpus=0)
        def remote_putter():
            return rt.put(np.full(50_000, 3, np.int64))

        rt.get(remote_putter.remote(), timeout=120)  # grant
        for _ in range(5):  # leased pushes: get the inner ref immediately
            inner = rt.get(rt.get(remote_putter.remote(), timeout=120), timeout=120)
            assert int(inner[0]) == 3 and inner.shape == (50_000,)
    finally:
        if proc is not None:
            proc.kill()
            proc.wait(timeout=10)
        rt.shutdown()


# --------------------------------------------------------------------------
# observability: /api/leases + `rt leases` CLI smoke
# --------------------------------------------------------------------------
def test_api_leases_and_cli_smoke(capsys):
    from ray_tpu.scripts.cli import main

    rt.init(num_cpus=2, include_dashboard=True)
    try:
        url = rt.get_cluster().dashboard.url

        @rt.remote
        def leased_fn():
            return None

        rt.get([leased_fn.remote() for _ in range(20)], timeout=60)
        assert main(["leases", "--address", url]) == 0
        out = capsys.readouterr().out
        assert "leased_fn" in out and "reuse hits" in out
        assert main(["leases", "--address", url, "--format", "json"]) == 0
        import json as _json

        data = _json.loads(capsys.readouterr().out)
        assert data["leases"]["grants"] >= 1
        assert data["leases"]["reuse_hits"] >= 10
        assert data["head"]["scheduling_decisions"] >= 1
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# put placement rides inside the ownership notice (no trailing-commit window)
# --------------------------------------------------------------------------
def test_register_put_async_commits_location_inline():
    """A relayed worker put's placement is part of the register notice
    itself: the directory must know the location the instant ownership is
    recorded — a separate (batched) location frame could trail it, and a
    node dying in that window left an owned object the death/drain sweeps
    couldn't see (rt.get would hang instead of raising lost-object)."""
    rt.init(num_cpus=1)
    try:
        from ray_tpu import api
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.runtime import worker_api

        cluster = api.get_cluster()
        oid = ObjectID.from_random()
        blob = worker_api._dumps(
            ("register_put_async",
             {"oid": oid.binary(), "size": 123, "device": False})
        )
        worker_api.execute(
            cluster.core_worker, blob,
            worker_key=(cluster.head_node.node_id, 4242),
        )
        # ownership AND placement landed from the one frame
        assert oid in cluster.core_worker.ref_counter._refs
        assert cluster.head_node.node_id in cluster.directory.locations(oid)
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# push_task exactly-once protocol: delivery ack, receipt ack, control re-route
# --------------------------------------------------------------------------
def test_push_task_ack_protocol_and_control_reroute():
    """The push_task exchange brackets execution with two acks: the agent
    acks DELIVERY before dispatch (so the owner never control-resubmits a
    task that may be running), and the owner acks RECEIPT of the result (so
    a reply sent into a silently dead socket re-routes over the control
    channel instead of stranding the owner's get forever).  Decode/dispatch
    failures come back as a typed ``task_error`` — a task outcome, not a
    transport error to fall back from."""
    import pickle
    import socket as socklib

    from ray_tpu.runtime import data_plane

    rerouted = []

    def handler(spec_blob, accept):
        mode = pickle.loads(spec_blob)
        if mode == "boom":
            raise ValueError("undecodable spec")
        if mode == "need_fn":
            return {"ok": False, "need_fn": True}, None, None, None
        accept()
        meta, buffers = data_plane.to_frames({"v": 7})
        return {"ok": True}, meta, buffers, lambda: rerouted.append(mode)

    server = data_plane.DataServer(
        get_frames=lambda oid, timeout: (_ for _ in ()).throw(KeyError(oid)),
        put_frames=lambda *a: None,
    )
    server.task_handler = handler
    client = data_plane.DataClient()
    try:
        # happy path: accept -> result -> receipt ack; no re-route
        header, value = client.push_task(server.address, pickle.dumps("ok"))
        assert header["ok"] and value == {"v": 7} and not rerouted

        # cold fn cache: need_fn rides back without a delivery ack
        header, value = client.push_task(server.address, pickle.dumps("need_fn"))
        assert header.get("need_fn") and not header.get("ok")

        # handler failure surfaces as a TASK outcome, not a transport error
        header, value = client.push_task(server.address, pickle.dumps("boom"))
        assert header.get("task_error") and not header.get("ok")
        assert not rerouted

        # owner vanishes after reading the delivery ack: the reply goes
        # unconfirmed and the completion must re-route (control channel)
        host, _, port = server.address.rpartition(":")
        sock = socklib.create_connection((host or "127.0.0.1", int(port)))
        blob = pickle.dumps("ok")
        data_plane._send_header(sock, {"op": "push_task", "spec_size": len(blob)})
        data_plane._send_frame_raw(sock, blob)
        assert data_plane._recv_header(sock).get("accepted")
        sock.close()  # owner gone before the result / receipt ack
        assert _wait_for(lambda: rerouted == ["ok"], timeout=15), rerouted
    finally:
        server.close()


def test_pushed_duplicate_guard():
    """A control-plane submit that duplicates a pushed task — in flight OR
    recently completed at this agent — must be dropped: the pushed copy's
    completion is guaranteed to reach the owner, and running the duplicate
    would break exactly-once side effects.  A genuine retry (bumped
    attempt) must pass."""
    import threading as _threading

    from ray_tpu.core.ids import ObjectID, TaskID
    from ray_tpu.core.resources import ResourceSet
    from ray_tpu.runtime.agent import AgentFabric
    from ray_tpu.runtime.scheduler import TaskSpec

    fabric = AgentFabric("/tmp/rt_test_session")
    tid = TaskID.from_random()
    spec = TaskSpec(
        task_id=tid, name="t", func=None, args=(), kwargs={},
        dependencies=[], num_returns=1, return_ids=[ObjectID.from_random()],
        resources=ResourceSet.from_fixed_dict({}),
    )
    spec._push_reply = ({}, _threading.Event())
    fabric._remember(spec)
    assert fabric.pushed_duplicate(tid.binary(), spec.attempt)
    # a retry carries a bumped attempt: never deduped
    assert not fabric.pushed_duplicate(tid.binary(), spec.attempt + 1)
    # unknown tasks pass through
    assert not fabric.pushed_duplicate(TaskID.from_random().binary(), 0)
    # completion moves the guard to the recent-done window
    with fabric._specs_lock:
        fabric._pushed_done[(tid.binary(), spec.attempt)] = None
    fabric._forget(spec)
    assert fabric.pushed_duplicate(tid.binary(), spec.attempt)
