"""Dataset preprocessors (parity: python/ray/data/preprocessors/ — fit on a
Dataset, transform Datasets AND in-memory batches identically)."""

import numpy as np
import pytest

import ray_tpu as rt
import ray_tpu.data as data
from ray_tpu.data.preprocessors import (
    BatchMapper,
    Chain,
    Concatenator,
    CountVectorizer,
    FeatureHasher,
    KBinsDiscretizer,
    LabelEncoder,
    MinMaxScaler,
    Normalizer,
    OneHotEncoder,
    OrdinalEncoder,
    Preprocessor,
    PreprocessorNotFittedError,
    SimpleImputer,
    StandardScaler,
)


@pytest.fixture
def runtime():
    rt.init(num_cpus=2)
    # row-order assertions below need deterministic block order
    ctx = data.DataContext.get_current()
    ctx.preserve_order = True
    try:
        yield rt
    finally:
        ctx.preserve_order = False
        rt.shutdown()


def _rows(ds):
    return ds.take_all()


def test_standard_scaler_fit_and_batch_parity(runtime):
    ds = data.from_items([{"x": float(i), "y": float(2 * i)} for i in range(10)])
    scaler = StandardScaler(["x"])
    out = _rows(scaler.fit_transform(ds))
    xs = np.array([r["x"] for r in out])
    assert abs(xs.mean()) < 1e-9 and abs(xs.std() - 1.0) < 1e-9
    # y untouched
    assert [r["y"] for r in out] == [float(2 * i) for i in range(10)]
    # the SAME fitted object transforms serving-time batches identically
    b = scaler.transform_batch({"x": np.array([4.5]), "y": np.array([0.0])})
    assert abs(b["x"][0]) < 1e-9  # 4.5 is the fitted mean
    assert scaler.stats_["mean(x)"] == 4.5


def test_unfitted_raises(runtime):
    with pytest.raises(PreprocessorNotFittedError):
        StandardScaler(["x"]).transform_batch({"x": np.array([1.0])})


def test_minmax_and_discretizer(runtime):
    ds = data.from_items([{"x": float(i)} for i in range(11)])
    mm = MinMaxScaler(["x"]).fit(ds)
    out = _rows(mm.transform(ds))
    assert out[0]["x"] == 0.0 and out[-1]["x"] == 1.0

    kb = KBinsDiscretizer(["x"], bins=5, strategy="uniform").fit(ds)
    bins = [r["x"] for r in _rows(kb.transform(ds))]
    assert min(bins) == 0 and max(bins) == 4 and bins == sorted(bins)

    kq = KBinsDiscretizer(["x"], bins=2, strategy="quantile").fit(ds)
    bins = [r["x"] for r in _rows(kq.transform(ds))]
    assert bins.count(0) in (5, 6) and bins.count(1) in (5, 6)


def test_encoders(runtime):
    ds = data.from_items([{"c": v} for v in ["b", "a", "c", "a"]])
    enc = OrdinalEncoder(["c"]).fit(ds)
    assert [r["c"] for r in _rows(enc.transform(ds))] == [1, 0, 2, 0]
    # unseen at serving time -> -1
    assert enc.transform_batch({"c": np.array(["zz"])})["c"][0] == -1

    oh = OneHotEncoder(["c"]).fit(ds)
    mats = np.stack([r["c"] for r in _rows(oh.transform(ds))])
    assert mats.shape == (4, 3)
    assert mats.sum() == 4 and (mats[1] == [1, 0, 0]).all()
    assert oh.transform_batch({"c": np.array(["zz"])})["c"].sum() == 0

    le = LabelEncoder("c")
    assert [r["c"] for r in _rows(le.fit_transform(ds))] == [1, 0, 2, 0]


def test_imputer_strategies(runtime):
    ds = data.from_items([{"x": 1.0}, {"x": float("nan")}, {"x": 3.0}])
    mean_imp = SimpleImputer(["x"], strategy="mean").fit(ds)
    assert [r["x"] for r in _rows(mean_imp.transform(ds))] == [1.0, 2.0, 3.0]

    const = SimpleImputer(["x"], strategy="constant", fill_value=9.0)
    # constant needs no fit
    assert const.transform_batch({"x": np.array([np.nan])})["x"][0] == 9.0

    dsm = data.from_items([{"c": "a"}, {"c": "b"}, {"c": "a"}, {"c": None}])
    mf = SimpleImputer(["c"], strategy="most_frequent").fit(dsm)
    assert [r["c"] for r in _rows(mf.transform(dsm))] == ["a", "b", "a", "a"]

    # an all-missing column fails with a clear error, not an IndexError
    ds_empty = data.from_items([{"c": None}, {"c": None}])
    with pytest.raises(ValueError, match="no non-missing values"):
        SimpleImputer(["c"], strategy="most_frequent").fit(ds_empty)


def test_normalizer_concatenator_chain(runtime):
    ds = data.from_items([{"a": 3.0, "b": 4.0, "keep": 7}])
    norm = Normalizer(["a", "b"], norm="l2")
    row = _rows(norm.transform(ds))[0]
    assert abs(row["a"] - 0.6) < 1e-9 and abs(row["b"] - 0.8) < 1e-9

    cat = Concatenator(["a", "b"], output_column_name="vec")
    row = _rows(cat.transform(ds))[0]
    assert list(row["vec"]) == [3.0, 4.0] and row["keep"] == 7 and "a" not in row

    # chain: scale then concatenate; fit flows through stage outputs
    ds2 = data.from_items([{"a": float(i), "b": float(i)} for i in range(4)])
    chain = Chain(MinMaxScaler(["a", "b"]), Concatenator(["a", "b"], "vec"))
    rows = _rows(chain.fit_transform(ds2))
    assert list(rows[-1]["vec"]) == [1.0, 1.0]
    b = chain.transform_batch({"a": np.array([0.0]), "b": np.array([3.0])})
    assert list(b["vec"][0]) == [0.0, 1.0]


def test_batch_mapper(runtime):
    ds = data.from_items([{"x": 2}])
    bm = BatchMapper(lambda b: {"x": np.asarray(b["x"]) * 10})
    assert _rows(bm.transform(ds))[0]["x"] == 20


def test_text_pipeline(runtime):
    ds = data.from_items(
        [{"t": "the cat sat"}, {"t": "the dog sat down"}]
    )
    cv = CountVectorizer(["t"]).fit(ds)
    rows = _rows(cv.transform(ds))
    vocab = cv.stats_["token_counts(t)"]
    assert set(vocab) == {"the", "cat", "sat", "dog", "down"}
    assert rows[0]["t"][vocab["cat"]] == 1.0 and rows[0]["t"][vocab["dog"]] == 0.0

    # max_features keeps the most frequent tokens only
    cv2 = CountVectorizer(["t"], max_features=2).fit(ds)
    assert set(cv2.stats_["token_counts(t)"]) == {"the", "sat"}

    fh = FeatureHasher(["t"], num_features=32)
    vec = fh.transform_batch({"t": np.array(["cat cat dog"])})["t"]
    assert vec.shape == (1, 32) and vec.sum() == 3.0
    # deterministic across calls (md5, not PYTHONHASHSEED)
    assert (vec == fh.transform_batch({"t": np.array(["cat cat dog"])})["t"]).all()


def test_tokenizer_cells_stay_lists_even_when_uniform(runtime):
    # all rows tokenize to the same length: the column must remain a 1-D
    # object array of LISTS, not silently become a 2-D token matrix
    ds = data.from_items([{"t": "a b"}, {"t": "c d"}])
    from ray_tpu.data.preprocessors import Tokenizer

    tk = Tokenizer(["t"])
    b = tk.transform_batch({"t": np.array(["a b", "c d"])})
    assert b["t"].ndim == 1 and b["t"].dtype == object
    assert b["t"][0] == ["a", "b"] and b["t"][1] == ["c", "d"]
    rows = tk.transform(ds).take_all()
    assert rows[0]["t"] == ["a", "b"]
