"""Loop-proof for the driver's multi-chip gate (round-3 VERDICT next #1).

The round-3 gate flipped red under load because the cross-process leg's
agent subprocess inherited ``JAX_PLATFORMS=axon`` and initialized the real
tunneled TPU inside the dryrun.  The harness now pins the child to CPU and
budgets the outer ``rt.get`` (180 s) above the collective round (60 s).
This test runs the FULL dryrun — dp/sp/tp/ep train steps, ring attention,
GPipe, tp serving, and the cross-process collective + device-envelope leg —
five times back to back: the flake rate the gate can tolerate is zero.
"""

import pytest

pytestmark = pytest.mark.full  # soak: the full dryrun 5x back-to-back
def test_dryrun_multichip_5x_loop():
    import __graft_entry__ as graft

    for i in range(5):
        graft.dryrun_multichip(8)
