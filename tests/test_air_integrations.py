"""AIR: tune callbacks, wandb/mlflow logger fallbacks, usage stats.

Parity: python/ray/air/integrations tests + tune callback tests.
"""

import json
import os

import pytest

import ray_tpu as rt


def _run_small_experiment(tmp_path, callbacks):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig
    from ray_tpu.tune import Tuner
    from ray_tpu.tune.tuner import TuneConfig

    def trainable(config):
        from ray_tpu.tune import session

        for i in range(3):
            session.report({"score": config["x"] * (i + 1)})

    tuner = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="score", mode="max", num_samples=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="exp", callbacks=callbacks),
    )
    return tuner.fit()


def test_callback_lifecycle(ray_start_regular, tmp_path):
    from ray_tpu.tune.callback import Callback

    events = []

    class Recorder(Callback):
        def on_trial_start(self, trial):
            events.append(("start", trial.trial_id))

        def on_trial_result(self, trial, result):
            events.append(("result", trial.trial_id, result["score"]))

        def on_trial_complete(self, trial):
            events.append(("complete", trial.trial_id))

        def on_experiment_end(self, trials):
            events.append(("end", len(trials)))

    results = _run_small_experiment(tmp_path, [Recorder()])
    assert len(results) == 2
    starts = [e for e in events if e[0] == "start"]
    completes = [e for e in events if e[0] == "complete"]
    assert len(starts) == 2 and len(completes) == 2
    assert events[-1] == ("end", 2)
    assert any(e[0] == "result" for e in events)


def test_broken_callback_does_not_kill_experiment(ray_start_regular, tmp_path):
    from ray_tpu.tune.callback import Callback

    class Broken(Callback):
        def on_trial_result(self, trial, result):
            raise RuntimeError("boom")

    results = _run_small_experiment(tmp_path, [Broken()])
    assert len(results) == 2
    assert all(r.metrics.get("score") is not None for r in results)


def test_wandb_offline_fallback(ray_start_regular, tmp_path):
    from ray_tpu.air.integrations.wandb import WandbLoggerCallback

    cb = WandbLoggerCallback(project="proj", dir=str(tmp_path / "wb"))
    cb._wandb = None  # force the no-package path even if wandb is installed
    _run_small_experiment(tmp_path, [cb])
    wb_dir = tmp_path / "wb" / "wandb"
    assert (wb_dir / "config.json").exists()
    lines = (wb_dir / "history.jsonl").read_text().strip().splitlines()
    assert len(lines) >= 3
    assert "score" in json.loads(lines[0])


def test_mlflow_filestore_fallback(ray_start_regular, tmp_path):
    from ray_tpu.air.integrations.mlflow import MLflowLoggerCallback

    cb = MLflowLoggerCallback(tracking_uri=f"file:{tmp_path}/ml", experiment_name="e1")
    cb._mlflow = None
    _run_small_experiment(tmp_path, [cb])
    runs = list((tmp_path / "ml" / "mlruns" / "e1").iterdir())
    assert len(runs) == 2
    for run in runs:
        assert (run / "params.json").exists()
        assert (run / "status").read_text() == "FINISHED"
        metrics = [json.loads(l) for l in (run / "metrics.jsonl").read_text().splitlines()]
        assert any("score" in m for m in metrics)


def test_usage_stats_report_written(tmp_path):
    from ray_tpu.usage import record_extra_usage_tag, usage_report

    record_extra_usage_tag("test_feature", "1")
    report = usage_report()
    assert report["tags"]["test_feature"] == "1"
    assert report["source"] == "ray_tpu"

    rt.init(num_cpus=2)
    cluster = rt.get_cluster()
    session_dir = cluster.session_dir
    rt.shutdown()
    assert os.path.exists(os.path.join(session_dir, "usage_stats.json"))


def test_usage_stats_opt_out(monkeypatch):
    from ray_tpu.usage import usage_lib

    monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "0")
    before = dict(usage_lib._tags)
    usage_lib.record_extra_usage_tag("should_not_appear", "1")
    assert "should_not_appear" not in usage_lib._tags
    assert usage_lib.usage_stats_enabled() is False
