"""Push-based (Exoshuffle) shuffle: round/merge structure + correctness.

Round-2 VERDICT item 5. Reference:
python/ray/data/_internal/planner/exchange/push_based_shuffle_task_scheduler.py:400.
"""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.data import shuffle as shuffle_mod
from ray_tpu.data.context import DataContext


@pytest.fixture
def runtime():
    rt.init(num_cpus=4)
    try:
        yield rt
    finally:
        rt.shutdown()


def _blocks(n_blocks, rows_per_block, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {"k": rng.integers(0, 1000, rows_per_block), "v": rng.random(rows_per_block)}
        for _ in range(n_blocks)
    ]


def test_push_shuffle_schedule_structure(runtime):
    blocks = _blocks(12, 100)
    refs = [rt.put(b) for b in blocks]
    DataContext.get_current().use_push_based_shuffle = True
    out_refs, metas = shuffle_mod.run_exchange(refs, kind="sort", n_parts=6, key="k")
    sched = shuffle_mod.last_push_schedule
    assert sched is not None
    # bounded mergers: never more than one per partition nor per CPU
    assert 1 <= sched.num_mergers <= min(sched.n_parts, 4)
    # rounds cover all inputs with the configured round width
    assert sched.num_rounds * sched.maps_per_round >= sched.num_inputs
    assert sched.num_rounds == -(-12 // sched.maps_per_round)
    # merger ranges tile [0, n_parts)
    covered = [p for lo, hi in sched.merger_ranges for p in range(lo, hi)]
    assert covered == list(range(sched.n_parts))
    # sorted output equals the dense sort of all input rows
    out = rt.get(out_refs)
    got = np.concatenate([b["k"] for b in out if len(b.get("k", ()))])
    want = np.sort(np.concatenate([b["k"] for b in blocks]))
    np.testing.assert_array_equal(got, want)


def test_push_vs_pull_same_result(runtime):
    blocks = _blocks(8, 64, seed=3)
    ctx = DataContext.get_current()

    results = {}
    for mode in (True, False):
        ctx.use_push_based_shuffle = mode
        refs = [rt.put(b) for b in blocks]
        out_refs, _ = shuffle_mod.run_exchange(refs, kind="sort", n_parts=4, key="k")
        out = rt.get(out_refs)
        results[mode] = np.concatenate([b["k"] for b in out if len(b.get("k", ()))])
    np.testing.assert_array_equal(results[True], results[False])
    ctx.use_push_based_shuffle = True


def test_dataset_sort_and_groupby_ride_push_shuffle(runtime):
    import ray_tpu.data as data

    DataContext.get_current().use_push_based_shuffle = True
    shuffle_mod.last_push_schedule = None
    ds = data.from_items([{"k": int(i % 5), "v": float(i)} for i in range(1000)]).repartition(8)
    sorted_rows = ds.sort("k").take_all()
    assert [r["k"] for r in sorted_rows] == sorted(int(i % 5) for i in range(1000))
    assert shuffle_mod.last_push_schedule is not None  # went through push path

    agg = ds.groupby("k").sum("v").take_all()
    want = {}
    for i in range(1000):
        want[int(i % 5)] = want.get(int(i % 5), 0.0) + float(i)
    got = {int(r["k"]): r["sum(v)"] for r in agg}
    assert got == pytest.approx(want)


def test_push_shuffle_bench_smoke(runtime):
    """Push >= functional on ~64 MiB of blocks; perf table lives in PERF.md
    (the GB-scale bench runs via `rt microbenchmark`/bench.py on real HW)."""
    import time

    rng = np.random.default_rng(0)
    blocks = [
        {"k": rng.integers(0, 1 << 30, 1 << 17), "v": rng.random(1 << 17)}  # ~1.5MiB
        for _ in range(16)
    ]
    ctx = DataContext.get_current()
    timings = {}
    for mode in (True, False):
        ctx.use_push_based_shuffle = mode
        refs = [rt.put(b) for b in blocks]
        t0 = time.perf_counter()
        out_refs, _ = shuffle_mod.run_exchange(refs, kind="sort", n_parts=8, key="k")
        rt.get(out_refs)
        timings[mode] = time.perf_counter() - t0
    ctx.use_push_based_shuffle = True
    # both complete; no perf assertion (1-core CI box)
    assert timings[True] > 0 and timings[False] > 0
