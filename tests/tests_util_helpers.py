"""Module-level helper types for util tests (custom-serializer targets
must be importable by module+qualname for the deserializer lookup)."""


class Opaque:
    """Unpicklable by default — only a registered custom serializer can
    move it."""

    def __init__(self, v):
        self.v = v

    def __reduce__(self):
        raise TypeError("Opaque is not directly picklable")
