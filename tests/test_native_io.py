"""Native parallel IO pool tests: reads/writes/ranges, error paths,
concurrency, and the Data datasource fast path riding it."""

import os
import threading

import numpy as np
import pytest

from ray_tpu.native.io_pool import IOPool, default_pool, file_size


@pytest.fixture(scope="module")
def pool():
    p = IOPool(num_threads=4)
    yield p
    p.close()


def test_read_files_in_order(tmp_path, pool):
    paths = []
    for i in range(10):
        p = tmp_path / f"f{i}.bin"
        p.write_bytes(bytes([i]) * (1000 + i))
        paths.append(str(p))
    out = pool.read_files(paths)
    for i, data in enumerate(out):
        assert data == bytes([i]) * (1000 + i)


def test_read_ranges(tmp_path, pool):
    p = tmp_path / "r.bin"
    p.write_bytes(bytes(range(256)))
    out = pool.read_ranges([(str(p), 0, 16), (str(p), 100, 28), (str(p), 250, 20)])
    assert out[0] == bytes(range(16))
    assert out[1] == bytes(range(100, 128))
    assert out[2] == bytes(range(250, 256))  # short read at EOF truncates


def test_write_then_read(tmp_path, pool):
    p = str(tmp_path / "w.bin")
    payload = os.urandom(65536)
    assert pool.write_file(p, payload) == len(payload)
    assert pool.read_files([p])[0] == payload


def test_write_files_concurrent(tmp_path, pool):
    items = [(str(tmp_path / f"w{i}.bin"), os.urandom(1024)) for i in range(8)]
    ns = pool.write_files(items)
    assert ns == [1024] * 8
    for path, data in items:
        with open(path, "rb") as f:
            assert f.read() == data


def test_missing_file_raises(pool, tmp_path):
    jid = pool.submit_read(str(tmp_path / "nope.bin"), bytearray(10))
    with pytest.raises(OSError):
        pool.wait(jid)
    with pytest.raises(OSError):
        file_size(str(tmp_path / "nope.bin"))


def test_file_size(tmp_path, pool):
    p = tmp_path / "s.bin"
    p.write_bytes(b"x" * 12345)
    assert file_size(str(p)) == 12345


def test_many_concurrent_jobs(tmp_path, pool):
    """More in-flight jobs than threads: queue drains correctly."""
    p = tmp_path / "big.bin"
    blob = os.urandom(1 << 20)
    p.write_bytes(blob)
    ranges = [(str(p), i * 4096, 4096) for i in range(256)]
    out = pool.read_ranges(ranges)
    for i, chunk in enumerate(out):
        assert chunk == blob[i * 4096 : (i + 1) * 4096]


def test_default_pool_singleton():
    a, b = default_pool(), default_pool()
    assert a is b and a is not None


def test_datasource_rides_native_pool(tmp_path, monkeypatch):
    """A grouped read task goes through IOPool.read_files (checked in-process
    — the cluster path runs tasks in worker processes where a monkeypatch
    can't observe them)."""
    from ray_tpu.data.datasource import BinaryDatasource
    from ray_tpu.native import io_pool

    for i in range(6):
        (tmp_path / f"b{i}.bin").write_bytes(bytes([i]) * 64)

    calls = []
    orig = io_pool.IOPool.iter_reads

    def spy(self, ranges):
        calls.append(list(ranges))
        return orig(self, ranges)

    monkeypatch.setattr(io_pool.IOPool, "iter_reads", spy)
    tasks = BinaryDatasource(str(tmp_path)).get_read_tasks(2)
    blocks = [b for t in tasks for b in t.fn()]
    assert sorted(bytes(b["bytes"][0])[:1] for b in blocks) == [bytes([i]) for i in range(6)]
    assert calls and all(len(c) == 3 for c in calls), calls


def test_datasource_end_to_end_through_cluster(tmp_path):
    """Full cluster path still returns correct rows (workers use the pool
    or the fallback, whichever their process supports)."""
    import ray_tpu
    import ray_tpu.data as data

    for i in range(6):
        (tmp_path / f"b{i}.bin").write_bytes(bytes([i]) * 64)
    ray_tpu.init(num_cpus=2)
    try:
        ds = data.read_binary_files(str(tmp_path), parallelism=2)
        rows = sorted(bytes(r["bytes"])[:1] for r in ds.take_all())
        assert rows == [bytes([i]) for i in range(6)]
    finally:
        ray_tpu.shutdown()


def test_read_files_missing_second_file_safe(tmp_path, pool):
    """A stat failure mid-batch must not leave native threads writing into
    freed buffers (submit happens only after all sizes are known)."""
    ok = tmp_path / "ok.bin"
    ok.write_bytes(b"y" * 4096)
    with pytest.raises(OSError):
        pool.read_files([str(ok), str(tmp_path / "gone.bin")])
    # pool still healthy afterwards
    assert bytes(pool.read_files([str(ok)])[0]) == b"y" * 4096


def test_iter_reads_early_close_drains(tmp_path, pool):
    paths = []
    for i in range(6):
        p = tmp_path / f"it{i}.bin"
        p.write_bytes(bytes([i]) * 8192)
        paths.append(str(p))
    it = pool.iter_reads([(p, 0, 8192) for p in paths])
    first = next(it)
    assert bytes(first) == bytes([0]) * 8192
    it.close()  # outstanding jobs must be drained, not abandoned
    out = pool.read_files(paths)  # pool still consistent
    assert all(bytes(b) == bytes([i]) * 8192 for i, b in enumerate(out))


def test_default_pool_failure_cached(monkeypatch):
    from ray_tpu.native import io_pool as mod

    monkeypatch.setattr(mod, "_default_pool", None)
    attempts = []

    class Boom:
        def __init__(self, *a, **k):
            attempts.append(1)
            raise OSError("no toolchain")

    monkeypatch.setattr(mod, "IOPool", Boom)
    assert mod.default_pool() is None
    assert mod.default_pool() is None
    assert len(attempts) == 1  # second call hits the cached failure


def test_zero_byte_files(tmp_path, pool):
    empty = tmp_path / "empty.bin"
    empty.write_bytes(b"")
    full = tmp_path / "full.bin"
    full.write_bytes(b"z" * 128)
    out = pool.read_files([str(empty), str(full), str(empty)])
    assert [bytes(b) for b in out] == [b"", b"z" * 128, b""]


def test_iter_reads_window_bounds_inflight(tmp_path, pool):
    paths = []
    for i in range(12):
        p = tmp_path / f"w{i}.bin"
        p.write_bytes(bytes([i]) * 256)
        paths.append((str(p), 0, 256))
    out = list(pool.iter_reads(paths, window=2))
    assert [bytes(b)[:1] for b in out] == [bytes([i]) for i in range(12)]


def test_write_files_partial_failure_drains(tmp_path, pool):
    ok = str(tmp_path / "ok.bin")
    bad = str(tmp_path / "nodir" / "x.bin")  # parent missing -> ENOENT
    with pytest.raises(OSError):
        pool.write_files([(bad, b"a" * 64), (ok, b"b" * 64)])
    # pool healthy and no leaked pending buffers
    assert pool.write_file(ok, b"c" * 64) == 64
    assert not pool._pending_bufs
