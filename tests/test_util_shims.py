"""util shims: multiprocessing.Pool, joblib backend, internal_kv, tqdm.

Parity: python/ray/util/multiprocessing + util/joblib tests.
"""

import numpy as np
import pytest

import ray_tpu as rt


def test_pool_map(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=4) as pool:
        out = pool.map(lambda x: x * x, range(20))
    assert out == [x * x for x in range(20)]


def test_pool_starmap_and_apply(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    pool = Pool()
    assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
    assert pool.apply(lambda a, b: a * b, (3, 4)) == 12
    res = pool.apply_async(lambda: "async")
    assert res.get(timeout=30) == "async"
    assert res.successful()
    pool.close()
    with pytest.raises(ValueError):
        pool.map(lambda x: x, [1])


def test_pool_imap_ordered_and_unordered(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    pool = Pool()
    assert list(pool.imap(lambda x: x + 1, range(10), chunksize=3)) == list(range(1, 11))
    assert sorted(pool.imap_unordered(lambda x: x * 2, range(10), chunksize=2)) == [
        x * 2 for x in range(10)
    ]


def test_pool_initializer(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    def init(v):
        import builtins

        builtins._pool_test_v = v

    def use(x):
        import builtins

        return x + builtins._pool_test_v

    with Pool(initializer=init, initargs=(100,)) as pool:
        assert pool.map(use, [1, 2]) == [101, 102]


def test_joblib_backend(ray_start_regular):
    import joblib

    from ray_tpu.util.joblib import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        out = joblib.Parallel(n_jobs=4)(joblib.delayed(np.sqrt)(i**2) for i in range(10))
    assert out == [float(i) for i in range(10)]


def test_internal_kv(ray_start_regular):
    from ray_tpu.experimental import internal_kv as kv

    assert kv._internal_kv_initialized()
    assert kv._internal_kv_put(b"k1", b"v1") is False  # didn't exist
    assert kv._internal_kv_put(b"k1", b"v2") is True
    assert kv._internal_kv_get(b"k1") == b"v2"
    assert kv._internal_kv_exists(b"k1")
    assert b"k1" in kv._internal_kv_list(b"k")
    assert kv._internal_kv_del(b"k1") == 1
    assert kv._internal_kv_get(b"k1") is None


def test_tqdm_renders(capsys, monkeypatch):
    from ray_tpu.experimental import tqdm_ray

    bar = tqdm_ray.tqdm(desc="test", total=10)
    for _ in range(10):
        bar.update(1)
    bar.close()
    err = capsys.readouterr().err
    assert "10/10" in err
    # iterable wrapping
    assert list(tqdm_ray.tqdm(range(3), desc="it")) == [0, 1, 2]
    tqdm_ray.safe_print("hello")


def test_scheduling_strategies_module():
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        NodeLabelSchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    assert NodeAffinitySchedulingStrategy is not None
    assert NodeLabelSchedulingStrategy is not None
    assert PlacementGroupSchedulingStrategy is not None
