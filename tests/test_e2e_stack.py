"""Full-stack integration: Data pipeline -> Train worker gang -> Tune search
over the flagship transformer — the reference's flagship composition
(Train-on-Tune with attached Datasets, SURVEY §3.5) exercised end to end
on the real model code."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train, tune
from ray_tpu.train import JaxTrainer, ScalingConfig


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_transformer_tune_train_data_stack():
    import ray_tpu.data as data

    # Data: a streaming pipeline of token blocks with a map transform
    vocab, seq = 64, 16
    rows = [
        {"tokens": np.random.default_rng(i).integers(0, vocab, (seq,)).astype(np.int32)}
        for i in range(64)
    ]
    ds = data.from_items(rows).map_batches(lambda b: b)  # exercise the plan

    def loop(config):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import TransformerConfig, make_train_step

        cfg = TransformerConfig(
            vocab_size=vocab, d_model=16, n_layers=1, n_heads=2, d_ff=32,
            max_seq_len=seq, attention="dense", remat=False,
        )
        init_state, step = make_train_step(cfg, learning_rate=config["lr"])
        state = init_state(jax.random.key(0))
        shard = train.get_dataset_shard("train")
        losses = []
        for batch in shard.iter_batches(batch_size=8):
            tokens = jnp.asarray(np.stack(batch["tokens"]))
            state, loss = step(state, tokens)
            losses.append(float(loss))
        train.report({"loss": losses[-1], "num_batches": len(losses)})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": ds},
        train_loop_config={"lr": 1e-2},
    )

    tuner = tune.Tuner(
        trainer,
        param_space={"train_loop_config": {"lr": tune.grid_search([1e-2, 3e-3])}},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    best = grid.get_best_result()
    assert np.isfinite(best.metrics["loss"])
    assert best.metrics["num_batches"] >= 1
