// End-to-end C++ frontend test driver (compiled and run by
// tests/test_cpp_client.py against a live thin-client server).
// Exercises Put/Get round-trip, cross-language Call by importable name,
// Ref args (object passed by reference into a task), and Release.

#include <cstdio>
#include <cstring>
#include <string>

#include "../ray_tpu/native/include/ray_tpu_client.h"

#define CHECK(cond, msg)                      \
  do {                                        \
    if (!(cond)) {                            \
      std::fprintf(stderr, "FAIL: %s (%s)\n", \
                   msg, c.last_error().c_str()); \
      return 1;                               \
    }                                         \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s host port\n", argv[0]);
    return 2;
  }
  ray_tpu::Client c;
  CHECK(c.Connect(argv[1], std::atoi(argv[2])), "connect");
  CHECK(c.Ping(), "ping");

  // put/get round trip
  ray_tpu::ObjectID id = c.Put("hello from c++");
  CHECK(id.valid, "put");
  CHECK(c.Get(id) == "hello from c++", "get round-trip");

  // cross-language call: python function by import path, i64 + str args
  ray_tpu::ObjectID r = c.Call(
      "tests.cpp_client_funcs:format_sum",
      {ray_tpu::Arg::I64(40), ray_tpu::Arg::I64(2), ray_tpu::Arg::Str("answer")});
  CHECK(r.valid, "call");
  CHECK(c.Get(r) == "answer=42", "call result");

  // ref arg: pass a stored object into a task by reference
  ray_tpu::ObjectID payload = c.Put("abcdef");
  ray_tpu::ObjectID rev = c.Call("tests.cpp_client_funcs:reverse_bytes",
                                 {ray_tpu::Arg::Ref(payload)});
  CHECK(c.Get(rev) == "fedcba", "ref arg");

  // release then get must fail
  CHECK(c.Release(payload), "release");
  std::string gone = c.Get(payload);
  CHECK(gone.empty() && !c.last_error().empty(), "get released ref errors");

  std::printf("CPP CLIENT OK\n");
  return 0;
}
