"""Reduced-size control-plane scale guard (reference: release/benchmarks/
many_actors / many_tasks / many_pgs release tests).

The full-size artifact (10k actors, 50k tasks, 1k PGs) is captured by
``python -m ray_tpu.scripts.scale_bench SCALE_r05.json``; this in-suite run
shrinks the sizes ~20x and asserts throughput floors WELL below the
measured rates (r05: 1190 actors/s, 10.5k tasks/s, 2.2k pgs/s) so a
control-plane regression trips it without making the suite flaky on a
loaded box.
"""

import ray_tpu as rt
from ray_tpu.scripts import scale_bench


def test_scale_suite_reduced():
    rt.init(num_cpus=4)
    try:
        actors = scale_bench.many_actors(rt, 500)
        tasks = scale_bench.many_tasks(rt, 2500)
        pgs = scale_bench.many_pgs(rt, 50)
    finally:
        rt.shutdown()

    # floors ~5-10x under the measured full-size rates
    assert actors["actors_per_s"] > 150, actors
    assert tasks["tasks_per_s"] > 1500, tasks
    assert pgs["pgs_per_s"] > 100, pgs
    # NO RSS assertion here: ru_maxrss is process-wide and a full pytest
    # run legitimately peaks >>8 GB before this test runs; the dedicated
    # scale_bench process captures the honest head-RSS number
