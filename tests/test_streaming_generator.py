"""Streaming-generator task tests (reference: ObjectRefGenerator /
TryReadObjectRefStream semantics): items arrive before the task finishes,
mid-stream errors surface as errored refs, cancellation closes streams."""

import time

import pytest

import ray_tpu as rt


@pytest.fixture(autouse=True)
def _ray():
    rt.init(num_cpus=4)
    yield
    rt.shutdown()


def test_basic_streaming():
    @rt.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(5)
    assert isinstance(g, rt.ObjectRefGenerator)
    values = [rt.get(ref) for ref in g]
    assert values == [0, 10, 20, 30, 40]
    assert g.is_finished()


def test_items_arrive_before_task_finishes():
    @rt.remote(num_returns="streaming")
    def slow_gen():
        yield "first"
        time.sleep(1.5)
        yield "second"

    g = slow_gen.remote()
    t0 = time.monotonic()
    first_ref = next(g)
    first_latency = time.monotonic() - t0
    assert rt.get(first_ref) == "first"
    # the first item must land well before the 1.5s sleep completes
    assert first_latency < 1.0, f"first item took {first_latency:.2f}s"
    assert rt.get(next(g)) == "second"
    with pytest.raises(StopIteration):
        next(g)


def test_midstream_error_is_next_item():
    @rt.remote(num_returns="streaming")
    def bad_gen():
        yield 1
        yield 2
        raise ValueError("stream broke")

    g = bad_gen.remote()
    assert rt.get(next(g)) == 1
    assert rt.get(next(g)) == 2
    err_ref = next(g)
    with pytest.raises(rt.RayTaskError) as exc:
        rt.get(err_ref)
    assert "stream broke" in str(exc.value)
    with pytest.raises(StopIteration):
        next(g)


def test_empty_generator():
    @rt.remote(num_returns="streaming")
    def empty():
        if False:
            yield

    g = empty.remote()
    assert list(g) == []


def test_next_ready_timeout():
    @rt.remote(num_returns="streaming")
    def slow():
        time.sleep(0.8)
        yield 42

    g = slow.remote()
    assert g.next_ready(timeout=0.05) is None  # not yet
    ref = None
    deadline = time.monotonic() + 30
    while ref is None and time.monotonic() < deadline:
        ref = g.next_ready(timeout=0.5)
    assert rt.get(ref) == 42


def test_streaming_args_are_resolved():
    @rt.remote
    def make_base():
        return 100

    @rt.remote(num_returns="streaming")
    def gen(base, n):
        for i in range(n):
            yield base + i

    g = gen.remote(make_base.remote(), 3)
    assert [rt.get(r) for r in g] == [100, 101, 102]


def test_many_items():
    @rt.remote(num_returns="streaming")
    def lots():
        for i in range(500):
            yield i

    g = lots.remote()
    assert [rt.get(r) for r in g] == list(range(500))


def test_consumer_can_lag():
    """Producer finishes long before the consumer reads: items buffer."""

    @rt.remote(num_returns="streaming")
    def quick():
        for i in range(10):
            yield i

    g = quick.remote()
    time.sleep(0.5)  # let the producer finish entirely
    assert g.num_ready() == 10
    assert [rt.get(r) for r in g] == list(range(10))


def test_infeasible_streaming_task_fails_stream():
    """An unschedulable streaming task must close its stream with an error
    (not hang the consumer forever)."""
    from ray_tpu.core.config import get_config

    cfg = get_config()
    old = cfg.infeasible_task_timeout_s
    cfg.infeasible_task_timeout_s = 0.3
    try:

        @rt.remote(num_returns="streaming", resources={"GPU": 99})
        def g():
            yield 1

        gen = g.remote()
        ref = next(gen)  # the error item
        with pytest.raises(Exception):
            rt.get(ref)
        with pytest.raises(StopIteration):
            next(gen)
    finally:
        cfg.infeasible_task_timeout_s = old


def test_actor_streaming_rejected():
    @rt.remote
    class A:
        def gen(self):
            yield 1

    a = A.remote()
    with pytest.raises(ValueError):
        a.gen.options(num_returns="streaming").remote()
