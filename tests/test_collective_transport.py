"""Transport-native collectives (round-3 VERDICT item 5).

Cross-process ``send``/``recv`` and group rendezvous move store-to-store on
the chunked data plane; the head KV carries only tiny rank→address
registrations — never message payloads (the round-2 path polled pickled
values through ``rt_p2p/`` KV keys at 2 ms).  Declarative
``create_collective_group`` binds actors to ranks so collective ops need no
manual ``set_rank``.

Reference parity anchors: ``python/ray/util/collective/collective.py``
:151 (create), :531/:594 (send/recv);
``collective_group/nccl_collective_group.py`` (the transport-bound backend
role NCCL plays for GPUs).
"""

import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy

from test_multihost import _spawn_agent, _wait_for_nodes, two_process_cluster  # noqa: F401


class _KVRecorder:
    """Wraps the head's InternalKV put to record every key written."""

    def __init__(self, kv):
        self._kv = kv
        self._orig_put = kv.put
        self.keys = []

    def __enter__(self):
        def recording_put(key, value, *a, **kw):
            self.keys.append(bytes(key))
            return self._orig_put(key, value, *a, **kw)

        self._kv.put = recording_put
        return self

    def __exit__(self, *exc):
        self._kv.put = self._orig_put
        return False


def test_no_payload_keys_hit_head_kv(two_process_cluster):
    """THE acceptance assertion: collective payloads never ride the head KV —
    no rt_p2p/ (old payload prefix) and no rt_coll/ (old rendezvous payload
    prefix) keys are ever written during cross-process collectives."""
    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    @rt.remote(execution="thread")
    class Rank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="nokv")
            self.rank = rank

        def roundtrip(self, x):
            from ray_tpu.util import collective

            out = collective.allreduce(
                np.array([x], np.float32), group_name="nokv", rank=self.rank
            )
            return np.asarray(out).tolist()

        def send_to(self, value, dst):
            from ray_tpu.util import collective

            collective.send(value, dst, group_name="nokv", rank=self.rank)
            return True

        def recv_from(self, src):
            from ray_tpu.util import collective

            return collective.recv(src, group_name="nokv", rank=self.rank, timeout=60)

    with _KVRecorder(cluster.control.kv) as rec:
        r0 = Rank.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
        ).remote(0, 2)
        r1 = Rank.options(resources={"remote": 1}).remote(1, 2)
        a = r0.roundtrip.remote(1.0)
        b = r1.roundtrip.remote(2.0)
        assert rt.get(a, timeout=90) == [3.0]
        assert rt.get(b, timeout=90) == [3.0]
        sent = r0.send_to.remote(np.arange(10), 1)
        got = r1.recv_from.remote(0)
        assert rt.get(sent, timeout=90) is True
        np.testing.assert_array_equal(rt.get(got, timeout=90), np.arange(10))

    payload_keys = [
        k for k in rec.keys if k.startswith(b"rt_p2p/") or k.startswith(b"rt_coll/")
    ]
    assert payload_keys == [], payload_keys
    # only tiny metadata (rank->address) may appear
    for k in rec.keys:
        if k.startswith(b"rt_coll_addr/"):
            break
    else:
        pytest.fail("expected rank-address registrations in the KV")


def test_process_worker_group_rides_transport(two_process_cluster):
    """Round-3 VERDICT missing #2: default-execution actors land in spawned
    WORKER PROCESSES, which had no data-plane endpoint and silently fell
    back to KV polling.  Workers now build their own endpoint lazily
    (p2p.ensure_endpoint), so a group of two process-execution actors —
    one per node, each in a grandchild process — rendezvouses store-to-store
    with zero payload keys through the head KV."""
    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    @rt.remote(execution="process")
    class Rank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="procgrp")
            self.rank = rank

        def roundtrip(self, x):
            from ray_tpu.util import collective

            out = collective.allreduce(
                np.array([x], np.float32), group_name="procgrp", rank=self.rank
            )
            return np.asarray(out).tolist()

        def send_to(self, value, dst):
            from ray_tpu.util import collective

            collective.send(value, dst, group_name="procgrp", rank=self.rank)
            return True

        def recv_from(self, src):
            from ray_tpu.util import collective

            return collective.recv(src, group_name="procgrp", rank=self.rank, timeout=60)

    with _KVRecorder(cluster.control.kv) as rec:
        r0 = Rank.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
        ).remote(0, 2)
        r1 = Rank.options(resources={"remote": 1}).remote(1, 2)
        a = r0.roundtrip.remote(1.0)
        b = r1.roundtrip.remote(2.0)
        assert rt.get(a, timeout=90) == [3.0]
        assert rt.get(b, timeout=90) == [3.0]
        sent = r0.send_to.remote(np.arange(7), 1)
        got = r1.recv_from.remote(0)
        assert rt.get(sent, timeout=90) is True
        np.testing.assert_array_equal(rt.get(got, timeout=90), np.arange(7))

    payload_keys = [
        k for k in rec.keys if k.startswith(b"rt_p2p/") or k.startswith(b"rt_coll/")
    ]
    assert payload_keys == [], payload_keys


def test_mixed_thread_process_group(two_process_cluster):
    """A group mixing a thread-execution actor (node-process endpoint) and a
    process-execution actor (worker-process endpoint) must route uniformly:
    round 3's latch split such groups between transport and KV polling and
    deadlocked to the timeout."""
    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    def _body(rank, world, x):
        from ray_tpu.util import collective

        out = collective.allreduce(
            np.array([x], np.float32), group_name="mixed", rank=rank
        )
        return np.asarray(out).tolist()

    @rt.remote(execution="thread")
    class ThreadRank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="mixed")
            self.rank = rank

        def roundtrip(self, x):
            return _body(self.rank, 2, x)

    @rt.remote(execution="process")
    class ProcRank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="mixed")
            self.rank = rank

        def roundtrip(self, x):
            from ray_tpu.util import collective

            out = collective.allreduce(
                np.array([x], np.float32), group_name="mixed", rank=self.rank
            )
            return np.asarray(out).tolist()

    with _KVRecorder(cluster.control.kv) as rec:
        r0 = ThreadRank.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
        ).remote(0, 2)
        r1 = ProcRank.options(resources={"remote": 1}).remote(1, 2)
        a = r0.roundtrip.remote(5.0)
        b = r1.roundtrip.remote(6.0)
        assert rt.get(a, timeout=90) == [11.0]
        assert rt.get(b, timeout=90) == [11.0]

    payload_keys = [
        k for k in rec.keys if k.startswith(b"rt_p2p/") or k.startswith(b"rt_coll/")
    ]
    assert payload_keys == [], payload_keys


def test_local_mixed_group_no_agent(ray_start_regular):
    """Single-host, NO remote agent: a thread actor (driver process) and a
    process actor (spawned worker) share a group.  The thread rank's first
    collective can run before the worker even spawns — the unproven inproc
    wait must detect the process participant and re-route mid-round instead
    of dying at the full timeout (parallel/collective._ReRoute)."""
    rt = ray_start_regular
    from ray_tpu.util import collective

    @rt.remote(execution="thread")
    class T:
        def __init__(self, rank):
            collective.init_collective_group(2, rank, group_name="lmix")
            self.rank = rank

        def step(self, x):
            out = collective.allreduce(
                np.array([x], np.float32), group_name="lmix", rank=self.rank
            )
            return np.asarray(out).tolist()

    @rt.remote(execution="process")
    class P:
        def __init__(self, rank):
            from ray_tpu.util import collective

            collective.init_collective_group(2, rank, group_name="lmix")
            self.rank = rank

        def step(self, x):
            from ray_tpu.util import collective

            out = collective.allreduce(
                np.array([x], np.float32), group_name="lmix", rank=self.rank
            )
            return np.asarray(out).tolist()

    t, p = T.remote(0), P.remote(1)
    a, b = t.step.remote(1.0), p.step.remote(2.0)
    assert rt.get(a, timeout=90) == [3.0]
    assert rt.get(b, timeout=90) == [3.0]
    # second round rides the latched transport route
    a2, b2 = t.step.remote(10.0), p.step.remote(20.0)
    assert rt.get(a2, timeout=90) == [30.0]
    assert rt.get(b2, timeout=90) == [30.0]


def test_declarative_group_process_actors(two_process_cluster):
    """Declarative binding works for process-execution actors too: rank is
    inferred from the worker's task context (TaskIDs embed the ActorID) and
    the group record is fetched through the worker's KV channel."""
    from ray_tpu.util import collective

    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    @rt.remote(execution="process")
    class Worker:
        def contribute(self, x):
            from ray_tpu.util import collective

            out = collective.allreduce(np.array([x], np.float32), group_name="pdecl")
            return np.asarray(out).tolist()

        def whoami(self):
            return "alive"

    w0 = Worker.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
    ).remote()
    w1 = Worker.options(resources={"remote": 1}).remote()
    assert rt.get([w0.whoami.remote(), w1.whoami.remote()], timeout=60) == ["alive", "alive"]

    collective.create_collective_group([w0, w1], 2, [0, 1], group_name="pdecl")
    a = w0.contribute.remote(40.0)
    b = w1.contribute.remote(2.0)
    assert rt.get(a, timeout=90) == [42.0]
    assert rt.get(b, timeout=90) == [42.0]
    collective.destroy_collective_group("pdecl")


def test_collective_fails_fast_on_node_death(two_process_cluster):
    """VERDICT r4 item 5: a death notice fails open collective waits NOW.
    Rank 0 (driver thread actor) blocks mid-allreduce waiting on rank 1
    (agent); killing the agent's node must raise CollectiveGroupDeadError
    in rank 0 within 2 s — not at the 120 s rendezvous timeout.  Anchor:
    the reference fails pending actor calls atomically with the death
    notice (direct_actor_task_submitter.h:120)."""
    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    @rt.remote(execution="thread")
    class Rank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="doomed")
            self.rank = rank

        def step(self, x):
            from ray_tpu.util import collective

            t0 = time.monotonic()
            try:
                out = collective.allreduce(
                    np.array([x], np.float32), group_name="doomed", rank=self.rank
                )
                return ("ok", float(np.asarray(out)[0]), time.monotonic() - t0)
            except Exception as exc:  # noqa: BLE001 — name travels back
                return (type(exc).__name__, str(exc), time.monotonic() - t0)

    r0 = Rank.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
    ).remote(0, 2)
    r1 = Rank.options(resources={"remote": 1}).remote(1, 2)
    # warm round: transport latched, every rank's address + node registered
    a, b = r0.step.remote(1.0), r1.step.remote(2.0)
    assert rt.get(a, timeout=90)[0] == "ok"
    assert rt.get(b, timeout=90)[0] == "ok"

    # rank 0 enters a round alone and blocks on rank 1's contribution
    fut = r0.step.remote(5.0)
    time.sleep(1.0)
    import ray_tpu.runtime.p2p  # noqa: F401 — imported for clarity below

    from test_multihost import _remote_node_id

    t_kill = time.monotonic()
    cluster.kill_node(_remote_node_id(cluster))
    name, detail, _waited = rt.get(fut, timeout=60)
    notice_to_raise = time.monotonic() - t_kill
    assert name == "CollectiveGroupDeadError", (name, detail)
    assert notice_to_raise < 2.0, f"took {notice_to_raise:.1f}s after the death notice"


def test_collective_fails_fast_worker_rank_kill9(two_process_cluster):
    """Same bar end to end with kill -9 and a PROCESS-worker survivor: the
    notice must relay head -> pool worker (reader thread) and wake the
    worker's blocked wait.  Budget covers death DETECTION (disconnect +
    health checks) plus the notice — far below the 120 s rendezvous
    timeout."""
    import signal

    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    @rt.remote(execution="process")
    class ProcRank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="doomed9")
            self.rank = rank

        def step(self, x):
            from ray_tpu.util import collective

            try:
                out = collective.allreduce(
                    np.array([x], np.float32), group_name="doomed9", rank=self.rank
                )
                return ("ok", float(np.asarray(out)[0]))
            except Exception as exc:  # noqa: BLE001
                return (type(exc).__name__, str(exc))

    @rt.remote(execution="thread")
    class AgentRank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="doomed9")
            self.rank = rank

        def step(self, x):
            from ray_tpu.util import collective

            out = collective.allreduce(
                np.array([x], np.float32), group_name="doomed9", rank=self.rank
            )
            return ("ok", float(np.asarray(out)[0]))

    r0 = ProcRank.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
    ).remote(0, 2)
    r1 = AgentRank.options(resources={"remote": 1}).remote(1, 2)
    a, b = r0.step.remote(1.0), r1.step.remote(2.0)
    assert rt.get(a, timeout=90)[0] == "ok"
    assert rt.get(b, timeout=90)[0] == "ok"

    fut = r0.step.remote(5.0)
    time.sleep(1.0)
    t_kill = time.monotonic()
    import os as _os

    _os.kill(proc.pid, signal.SIGKILL)
    name, detail = rt.get(fut, timeout=90)
    total = time.monotonic() - t_kill
    assert name == "CollectiveGroupDeadError", (name, detail)
    assert total < 30.0, f"kill -9 to raise took {total:.1f}s (budget 30s incl. detection)"


def test_send_recv_throughput_above_100mbps(two_process_cluster):
    """Loopback cross-process send/recv sustains >100 MB/s (acceptance bar;
    the 2ms-KV-polling path measured far below it)."""
    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id
    nbytes = 100 * 1024 * 1024

    @rt.remote(execution="thread")
    class Rank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="tput")
            self.rank = rank

        def send_big(self, dst):
            from ray_tpu.util import collective

            collective.send(
                np.ones(nbytes, np.uint8), dst, group_name="tput", rank=self.rank
            )
            return True

        def recv_big(self, src):
            from ray_tpu.util import collective

            t0 = time.monotonic()
            out = collective.recv(src, group_name="tput", rank=self.rank, timeout=120)
            return out.nbytes, time.monotonic() - t0

    r0 = Rank.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
    ).remote(0, 2)
    r1 = Rank.options(resources={"remote": 1}).remote(1, 2)

    # warm the path (address resolution, connection setup)
    assert rt.get(r0.send_big.remote(1), timeout=120) is True
    got, _ = rt.get(r1.recv_big.remote(0), timeout=120)
    assert got == nbytes

    t0 = time.monotonic()
    sent = r0.send_big.remote(1)
    got, _recv_wait = rt.get(r1.recv_big.remote(0), timeout=120)
    assert rt.get(sent, timeout=120) is True
    elapsed = time.monotonic() - t0
    assert got == nbytes
    mbps = nbytes / (1024 * 1024) / elapsed
    assert mbps > 100, f"send/recv sustained only {mbps:.1f} MB/s"


def test_declarative_group_binds_ranks(two_process_cluster):
    """create_collective_group(actors, world, ranks) alone suffices: actors
    call collective ops with NO rank argument and NO set_rank."""
    from ray_tpu.util import collective

    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    @rt.remote(execution="thread")
    class Worker:
        def contribute(self, x):
            out = collective.allreduce(np.array([x], np.float32), group_name="decl")
            return np.asarray(out).tolist()

        def whoami(self):
            return "alive"

    w0 = Worker.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
    ).remote()
    w1 = Worker.options(resources={"remote": 1}).remote()
    # make sure both actors exist before binding ranks to their nodes
    assert rt.get([w0.whoami.remote(), w1.whoami.remote()], timeout=60) == ["alive", "alive"]

    collective.create_collective_group([w0, w1], 2, [0, 1], group_name="decl")
    a = w0.contribute.remote(10.0)
    b = w1.contribute.remote(32.0)
    assert rt.get(a, timeout=90) == [42.0]
    assert rt.get(b, timeout=90) == [42.0]
    collective.destroy_collective_group("decl")
