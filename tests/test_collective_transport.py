"""Transport-native collectives (round-3 VERDICT item 5).

Cross-process ``send``/``recv`` and group rendezvous move store-to-store on
the chunked data plane; the head KV carries only tiny rank→address
registrations — never message payloads (the round-2 path polled pickled
values through ``rt_p2p/`` KV keys at 2 ms).  Declarative
``create_collective_group`` binds actors to ranks so collective ops need no
manual ``set_rank``.

Reference parity anchors: ``python/ray/util/collective/collective.py``
:151 (create), :531/:594 (send/recv);
``collective_group/nccl_collective_group.py`` (the transport-bound backend
role NCCL plays for GPUs).
"""

import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy

from test_multihost import _spawn_agent, _wait_for_nodes, two_process_cluster  # noqa: F401


class _KVRecorder:
    """Wraps the head's InternalKV put to record every key written."""

    def __init__(self, kv):
        self._kv = kv
        self._orig_put = kv.put
        self.keys = []

    def __enter__(self):
        def recording_put(key, value, *a, **kw):
            self.keys.append(bytes(key))
            return self._orig_put(key, value, *a, **kw)

        self._kv.put = recording_put
        return self

    def __exit__(self, *exc):
        self._kv.put = self._orig_put
        return False


def test_no_payload_keys_hit_head_kv(two_process_cluster):
    """THE acceptance assertion: collective payloads never ride the head KV —
    no rt_p2p/ (old payload prefix) and no rt_coll/ (old rendezvous payload
    prefix) keys are ever written during cross-process collectives."""
    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    @rt.remote(execution="thread")
    class Rank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="nokv")
            self.rank = rank

        def roundtrip(self, x):
            from ray_tpu.util import collective

            out = collective.allreduce(
                np.array([x], np.float32), group_name="nokv", rank=self.rank
            )
            return np.asarray(out).tolist()

        def send_to(self, value, dst):
            from ray_tpu.util import collective

            collective.send(value, dst, group_name="nokv", rank=self.rank)
            return True

        def recv_from(self, src):
            from ray_tpu.util import collective

            return collective.recv(src, group_name="nokv", rank=self.rank, timeout=60)

    with _KVRecorder(cluster.control.kv) as rec:
        r0 = Rank.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
        ).remote(0, 2)
        r1 = Rank.options(resources={"remote": 1}).remote(1, 2)
        a = r0.roundtrip.remote(1.0)
        b = r1.roundtrip.remote(2.0)
        assert rt.get(a, timeout=90) == [3.0]
        assert rt.get(b, timeout=90) == [3.0]
        sent = r0.send_to.remote(np.arange(10), 1)
        got = r1.recv_from.remote(0)
        assert rt.get(sent, timeout=90) is True
        np.testing.assert_array_equal(rt.get(got, timeout=90), np.arange(10))

    payload_keys = [
        k for k in rec.keys if k.startswith(b"rt_p2p/") or k.startswith(b"rt_coll/")
    ]
    assert payload_keys == [], payload_keys
    # only tiny metadata (rank->address) may appear
    for k in rec.keys:
        if k.startswith(b"rt_coll_addr/"):
            break
    else:
        pytest.fail("expected rank-address registrations in the KV")


def test_send_recv_throughput_above_100mbps(two_process_cluster):
    """Loopback cross-process send/recv sustains >100 MB/s (acceptance bar;
    the 2ms-KV-polling path measured far below it)."""
    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id
    nbytes = 100 * 1024 * 1024

    @rt.remote(execution="thread")
    class Rank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="tput")
            self.rank = rank

        def send_big(self, dst):
            from ray_tpu.util import collective

            collective.send(
                np.ones(nbytes, np.uint8), dst, group_name="tput", rank=self.rank
            )
            return True

        def recv_big(self, src):
            from ray_tpu.util import collective

            t0 = time.monotonic()
            out = collective.recv(src, group_name="tput", rank=self.rank, timeout=120)
            return out.nbytes, time.monotonic() - t0

    r0 = Rank.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
    ).remote(0, 2)
    r1 = Rank.options(resources={"remote": 1}).remote(1, 2)

    # warm the path (address resolution, connection setup)
    assert rt.get(r0.send_big.remote(1), timeout=120) is True
    got, _ = rt.get(r1.recv_big.remote(0), timeout=120)
    assert got == nbytes

    t0 = time.monotonic()
    sent = r0.send_big.remote(1)
    got, _recv_wait = rt.get(r1.recv_big.remote(0), timeout=120)
    assert rt.get(sent, timeout=120) is True
    elapsed = time.monotonic() - t0
    assert got == nbytes
    mbps = nbytes / (1024 * 1024) / elapsed
    assert mbps > 100, f"send/recv sustained only {mbps:.1f} MB/s"


def test_declarative_group_binds_ranks(two_process_cluster):
    """create_collective_group(actors, world, ranks) alone suffices: actors
    call collective ops with NO rank argument and NO set_rank."""
    from ray_tpu.util import collective

    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    @rt.remote(execution="thread")
    class Worker:
        def contribute(self, x):
            out = collective.allreduce(np.array([x], np.float32), group_name="decl")
            return np.asarray(out).tolist()

        def whoami(self):
            return "alive"

    w0 = Worker.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
    ).remote()
    w1 = Worker.options(resources={"remote": 1}).remote()
    # make sure both actors exist before binding ranks to their nodes
    assert rt.get([w0.whoami.remote(), w1.whoami.remote()], timeout=60) == ["alive", "alive"]

    collective.create_collective_group([w0, w1], 2, [0, 1], group_name="decl")
    a = w0.contribute.remote(10.0)
    b = w1.contribute.remote(32.0)
    assert rt.get(a, timeout=90) == [42.0]
    assert rt.get(b, timeout=90) == [42.0]
    collective.destroy_collective_group("decl")
