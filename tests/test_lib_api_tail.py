"""Serve/Train/Workflow public-surface tail (reference __init__ __all__
parity): replica context, app handles, HTTPOptions; TrainingIterator,
SyncConfig/BackendConfig/TRAIN_DATASET_KEY; workflow continuations, typed
errors, durable sleep, options, resume_all/get_output_async/get_metadata.
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu import serve, train, workflow


@pytest.fixture(scope="module", autouse=True)
def runtime():
    rt.init(num_cpus=4, ignore_reinit_error=True)
    yield
    serve.shutdown()
    rt.shutdown()


# ------------------------------------------------------------------ serve
def test_replica_context_and_app_handle():
    seen = {}

    @serve.deployment
    class Echo:
        def __init__(self):
            ctx = serve.get_replica_context()
            seen["init"] = (ctx.deployment, ctx.replica_tag)

        def __call__(self, x):
            return serve.get_replica_context().deployment, x

    handle = serve.run(Echo.bind(), name="ctx_app")
    dep, x = handle.remote("v").result(timeout_s=30)
    assert dep == "Echo" and x == "v"
    assert seen["init"][0] == "Echo" and seen["init"][1].startswith("Echo#")

    same = serve.get_app_handle("ctx_app")
    assert same.remote("w").result(timeout_s=30)[1] == "w"
    with pytest.raises(KeyError):
        serve.get_app_handle("nope")
    # outside a replica: clean error
    with pytest.raises(RuntimeError):
        serve.get_replica_context()


def test_http_options_and_ingress_gate():
    assert serve.HTTPOptions().host == "127.0.0.1"
    with pytest.raises(ImportError):
        serve.ingress(object())


# ------------------------------------------------------------------ train
def test_training_iterator_streams_reports():
    def loop(config):
        for i in range(3):
            train.report({"step": i, "loss": 1.0 / (i + 1)})

    trainer = train.DataParallelTrainer(
        loop, scaling_config=train.ScalingConfig(num_workers=2)
    )
    it = trainer.training_iterator()
    rows = list(it)
    assert [r["step"] for r in rows] == [0, 1, 2]  # rank-0 stream, in order
    result = it.result()
    assert result.metrics["step"] == 2 and result.error is None
    with pytest.raises(RuntimeError):
        train.DataParallelTrainer(loop).training_iterator().result()


def test_train_config_surface():
    assert train.TRAIN_DATASET_KEY == "train"
    assert train.SyncConfig().sync_period == 300.0
    assert train.BackendConfig().backend_name == "backend"

    class JaxBackendConfig(train.BackendConfig):
        pass

    assert JaxBackendConfig().backend_name == "jaxbackend"


# --------------------------------------------------------------- workflow
def test_workflow_continuation_and_sleep(tmp_path):
    workflow.init(str(tmp_path))

    @rt.remote
    def tail(x):
        return x * 10

    @rt.remote
    def head(x):
        # a returned DAG is the workflow's continuation (tail call)
        return workflow.continuation(tail.bind(x + 1))

    out = workflow.run(head.bind(4), workflow_id="wf_cont")
    assert out == 50
    # sub-steps checkpoint under the parent step's key
    meta = workflow.get_metadata("wf_cont")
    assert meta["status"] == "SUCCESSFUL"
    assert any("/" in k for k in meta["step_names"]), meta["step_names"]

    t0 = time.monotonic()
    assert workflow.run(workflow.sleep(0.3), workflow_id="wf_sleep") == 0.3
    assert time.monotonic() - t0 >= 0.25
    # replay: the sleep is durable, so resume returns instantly
    t0 = time.monotonic()
    workflow.resume("wf_sleep")
    assert time.monotonic() - t0 < 0.25


def test_workflow_options_and_errors(tmp_path):
    workflow.init(str(tmp_path))

    @workflow.options(catch_exceptions=True)
    @rt.remote
    def flaky():
        raise ValueError("expected")

    result, err = workflow.run(flaky.bind(), workflow_id="wf_catch")
    assert result is None and "expected" in str(err)

    with pytest.raises(ValueError):
        workflow.options(bogus_key=1)
    assert issubclass(workflow.WorkflowCancellationError, RuntimeError)
    assert issubclass(workflow.WorkflowExecutionError, workflow.WorkflowError)


def test_workflow_async_and_resume_all(tmp_path):
    workflow.init(str(tmp_path))

    @rt.remote
    def add(a, b):
        return a + b

    fut = workflow.run_async(add.bind(1, 2), workflow_id="wf_async")
    assert fut.result(timeout=60) == 3
    out = workflow.get_output_async("wf_async")
    assert out.result(timeout=60) == 3

    resumed = workflow.resume_all()
    assert isinstance(resumed, list)


def test_workflow_catch_exceptions_with_continuation(tmp_path):
    # review regression: a continuation under catch_exceptions must
    # tail-call (and absorb the sub-plan's failure as data)
    workflow.init(str(tmp_path))

    @rt.remote
    def ok_tail(x):
        return x + 100

    @workflow.options(catch_exceptions=True)
    @rt.remote
    def outer(x):
        return workflow.continuation(ok_tail.bind(x))

    result, err = workflow.run(outer.bind(1), workflow_id="wf_cc")
    assert result == 101 and err is None

    @rt.remote
    def boom_tail(x):
        raise RuntimeError("sub-plan boom")

    @workflow.options(catch_exceptions=True)
    @rt.remote
    def outer2(x):
        return workflow.continuation(boom_tail.bind(x))

    result, err = workflow.run(outer2.bind(1), workflow_id="wf_cc2")
    assert result is None and "boom" in str(err)


def test_get_output_async_unknown_id_fails_fast(tmp_path):
    workflow.init(str(tmp_path))
    fut = workflow.get_output_async("never_existed")
    with pytest.raises(KeyError):
        fut.result(timeout=5)
