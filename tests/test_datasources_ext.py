"""Extended datasource breadth: TFRecords, framework ingestion, gated
connectors (round-1 VERDICT missing item 7 — datasource breadth).

Reference anchors: python/ray/data/datasource/tfrecords_datasource.py,
read_api.from_torch/from_tf/from_huggingface, mongo/bigquery datasources.
"""

import numpy as np
import pytest

import ray_tpu as rt
import ray_tpu.data as data


@pytest.fixture
def runtime():
    rt.init(num_cpus=2)
    try:
        yield rt
    finally:
        rt.shutdown()


@pytest.mark.full
def test_tfrecords_roundtrip(runtime, tmp_path):
    tf = pytest.importorskip("tensorflow")  # noqa: F841
    out = str(tmp_path / "tfr")
    ds = data.from_items(
        [{"x": int(i), "y": float(i) / 2, "name": f"row{i}"} for i in range(50)]
    )
    ds.write_tfrecords(out)
    back = data.read_tfrecords(out).take_all()
    assert len(back) == 50
    got = sorted(back, key=lambda r: r["x"])
    assert got[10]["x"] == 10
    assert got[10]["y"] == pytest.approx(5.0)
    assert got[10]["name"] == b"row10"  # bytes_list roundtrip


def test_tfrecords_raw_bytes(runtime, tmp_path):
    pytest.importorskip("tensorflow")
    out = str(tmp_path / "tfr")
    data.from_items([{"x": i} for i in range(5)]).write_tfrecords(out)
    raw = data.read_tfrecords(out, decode_examples=False).take_all()
    assert len(raw) == 5
    assert all(isinstance(r["bytes"], bytes) for r in raw)


def test_from_torch(runtime):
    torch = pytest.importorskip("torch")

    class DS(torch.utils.data.Dataset):
        def __len__(self):
            return 10

        def __getitem__(self, i):
            return torch.tensor([i, i + 1]), i % 2

    ds = data.from_torch(DS())
    rows = ds.take_all()
    assert len(rows) == 10
    # parallel read tasks may complete out of order: index by content
    got = sorted((list(r["item"]), int(r["label"])) for r in rows)
    assert got[3] == ([3, 4], 1)


def test_from_tf(runtime):
    tf = pytest.importorskip("tensorflow")
    tfds = tf.data.Dataset.from_tensor_slices({"a": np.arange(6), "b": np.arange(6) * 2.0})
    rows = data.from_tf(tfds).take_all()
    assert len(rows) == 6
    # parallel read tasks may complete out of order: compare as a set
    got = sorted((int(r["a"]), float(r["b"])) for r in rows)
    assert got == [(i, 2.0 * i) for i in range(6)]


def test_from_huggingface(runtime):
    hf = pytest.importorskip("datasets")
    hf_ds = hf.Dataset.from_dict({"text": ["a", "b", "c"], "n": [1, 2, 3]})
    rows = data.from_huggingface(hf_ds).take_all()
    assert [r["text"] for r in rows] == ["a", "b", "c"]
    assert [int(r["n"]) for r in rows] == [1, 2, 3]


def test_mongo_bigquery_gated(runtime):
    """Absent optional deps produce a clear install hint, not a crash;
    present deps construct the datasource without connecting."""
    try:
        import pymongo  # noqa: F401

        data.read_mongo("mongodb://localhost:1/x", "db", "coll")  # lazy: no IO yet
    except ImportError as exc:
        assert "pymongo" in str(exc)
    try:
        from google.cloud import bigquery  # noqa: F401

        data.read_bigquery("proj", dataset="d.t")  # lazy: no IO yet
    except ImportError as exc:
        assert "bigquery" in str(exc)


def test_iter_tf_batches_and_to_tf(runtime):
    tf = pytest.importorskip("tensorflow")
    ds = data.from_items([{"x": float(i), "y": i % 2} for i in range(64)])
    batches = list(ds.iter_tf_batches(batch_size=16))
    assert len(batches) == 4
    assert batches[0]["x"].shape == (16,)
    tfds = ds.to_tf("x", "y", batch_size=32)
    feats, labels = next(iter(tfds))
    assert int(feats.shape[0]) == 32
    assert labels.dtype in (tf.int64, tf.int32)


def test_datasource_tail_gated(runtime):
    """Round-4 VERDICT item 8: hudi / delta-sharing / clickhouse /
    databricks readers exist and fail ACTIONABLY when their optional dep
    (or credentials) is absent — construction is lazy, errors name the
    missing piece."""
    # hudi: lazy construction; materialization needs the hudi package
    ds = data.read_hudi("/tmp/nonexistent_hudi")
    with pytest.raises(Exception) as exc_info:
        ds.take_all()
    assert "hudi" in str(exc_info.value).lower()

    # delta-sharing: same gating through the profile-parsing path
    ds = data.read_delta_sharing_tables("/tmp/profile.json#share.schema.table")
    with pytest.raises(Exception) as exc_info:
        ds.take_all()
    assert "delta" in str(exc_info.value).lower() or "sharing" in str(exc_info.value).lower()

    # clickhouse
    ds = data.read_clickhouse("t", "clickhouse://localhost:1/db")
    with pytest.raises(Exception) as exc_info:
        ds.take_all()
    assert "clickhouse" in str(exc_info.value).lower()

    # databricks: fails fast at CONSTRUCTION on missing credentials
    import os

    assert not os.environ.get("DATABRICKS_HOST")
    with pytest.raises(ValueError, match="DATABRICKS_HOST"):
        data.read_databricks_tables(warehouse_id="w", table="t")
    with pytest.raises(ValueError, match="exactly one"):
        data.read_databricks_tables(warehouse_id="w")


def test_dataset_stats_per_op_format(runtime):
    """Round-4 VERDICT item 8: ds.stats() prints the reference's per-op
    report — operator lines with task/block counts and wall/cpu/rows/bytes
    min-max-mean-total breakdowns (stats.py to_summary format)."""
    ds = data.range(200, parallelism=4).map_batches(lambda b: {"x": b["id"] * 2})
    ds.materialize()
    report = ds.stats()
    assert "Operator" in report and "tasks executed" in report, report
    assert "Remote wall time" in report and "min," in report and "total" in report
    assert "Output num rows per block" in report, report
