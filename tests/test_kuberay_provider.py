"""Kubernetes node provider (KubeRay role) against a fake pod API
(reference: python/ray/autoscaler/_private/kuberay/node_provider.py).
Exercises the full create -> list -> head-restart adoption -> terminate
lifecycle, plus manifest shape for CPU and GKE TPU node types.
"""

import json

from ray_tpu.autoscaler import KubernetesNodeProvider, NodeTypeConfig
from ray_tpu.autoscaler.kuberay import CLUSTER_LABEL, TYPE_LABEL, KubernetesAPI


class FakePodAPI(KubernetesAPI):
    def __init__(self):
        self.pods = {}
        self.deleted = []

    def create_pod(self, manifest):
        name = manifest["metadata"]["name"]
        assert name not in self.pods, "name collision"
        self.pods[name] = {
            "name": name,
            "phase": "Running",
            "labels": manifest["metadata"]["labels"],
            "manifest": manifest,
        }

    def delete_pod(self, name):
        self.deleted.append(name)
        self.pods.pop(name, None)

    def list_pods(self, label_selector):
        key, _, val = label_selector.partition("=")
        return [
            {"name": p["name"], "phase": p["phase"], "labels": p["labels"]}
            for p in self.pods.values()
            if p["labels"].get(key) == val
        ]


CPU_TYPE = NodeTypeConfig(name="cpu-worker", resources={"CPU": 4})
TPU_TYPE = NodeTypeConfig(name="v5e-8", resources={"CPU": 8, "TPU": 8})


def test_create_list_terminate_lifecycle():
    api = FakePodAPI()
    p = KubernetesNodeProvider("head:6380", api=api, cluster_name="rtk")
    created = p.create_nodes(CPU_TYPE, 2)
    assert len(created) == 2 and all(n.startswith("rtk-cpu-worker-") for n in created)
    assert p.non_terminated_nodes() == {created[0]: "cpu-worker", created[1]: "cpu-worker"}

    p.terminate_node(created[0])
    assert api.deleted == [created[0]]
    assert p.non_terminated_nodes() == {created[1]: "cpu-worker"}


def test_manifest_runs_agent_with_resources_and_labels():
    api = FakePodAPI()
    p = KubernetesNodeProvider("10.0.0.1:6380", api=api, cluster_name="rtk",
                               image="my/img:1", service_account="rt-sa")
    (name,) = p.create_nodes(CPU_TYPE, 1)
    m = api.pods[name]["manifest"]
    assert m["metadata"]["labels"][CLUSTER_LABEL] == "rtk"
    assert m["metadata"]["labels"][TYPE_LABEL] == "cpu-worker"
    spec = m["spec"]
    assert spec["serviceAccountName"] == "rt-sa"
    assert spec["restartPolicy"] == "Never"
    (ctr,) = spec["containers"]
    assert ctr["image"] == "my/img:1"
    cmd = ctr["command"][-1]
    assert "ray_tpu.runtime.agent" in cmd and "10.0.0.1:6380" in cmd
    assert json.loads(cmd.split("--resources ")[1].split(" --labels")[0].strip("'")) == {"CPU": 4}
    assert ctr["resources"]["limits"]["cpu"] == "4"


def test_gke_tpu_node_type_requests_tpu_resource():
    api = FakePodAPI()
    p = KubernetesNodeProvider("h:1", api=api, cluster_name="rtk")
    (name,) = p.create_nodes(TPU_TYPE, 1)
    m = api.pods[name]["manifest"]
    limits = m["spec"]["containers"][0]["resources"]["limits"]
    assert limits["google.com/tpu"] == "8"
    cmd = m["spec"]["containers"][0]["command"][-1]
    labels = json.loads(cmd.split("--labels ")[1].strip("'"))
    assert labels["ray_tpu.io/pod-type"] == "v5e-8"


def test_head_restart_adopts_pods_and_advances_sequence():
    api = FakePodAPI()
    p1 = KubernetesNodeProvider("h:1", api=api, cluster_name="rtk")
    created = p1.create_nodes(CPU_TYPE, 3)

    # fresh provider (restarted head) sees the fleet and never collides
    p2 = KubernetesNodeProvider("h:1", api=api, cluster_name="rtk")
    assert set(p2.non_terminated_nodes()) == set(created)
    more = p2.create_nodes(CPU_TYPE, 1)
    assert more[0] not in created
    assert int(more[0].rsplit("-", 1)[1]) > max(int(c.rsplit("-", 1)[1]) for c in created)


def test_finished_pods_drop_out():
    api = FakePodAPI()
    p = KubernetesNodeProvider("h:1", api=api, cluster_name="rtk")
    created = p.create_nodes(CPU_TYPE, 2)
    api.pods[created[0]]["phase"] = "Failed"
    live = p.non_terminated_nodes()
    assert created[0] not in live and created[1] in live


def test_other_clusters_pods_invisible():
    api = FakePodAPI()
    a = KubernetesNodeProvider("h:1", api=api, cluster_name="aaa")
    b = KubernetesNodeProvider("h:1", api=api, cluster_name="bbb")
    a.create_nodes(CPU_TYPE, 1)
    assert b.non_terminated_nodes() == {}


def test_provider_id_label_and_fractional_cpu():
    api = FakePodAPI()
    p = KubernetesNodeProvider("h:1", api=api, cluster_name="rtk")
    (name,) = p.create_nodes(NodeTypeConfig(name="frac", resources={"CPU": 0.5}), 1)
    m = api.pods[name]["manifest"]
    # busy/idle mapping key reaches the agent labels
    cmd = m["spec"]["containers"][0]["command"][-1]
    labels = json.loads(cmd.split("--labels ")[1].strip("'"))
    assert labels["rt_provider_id"] == name
    # fractional CPUs become millicores, never a zero quota
    assert m["spec"]["containers"][0]["resources"]["limits"]["cpu"] == "500m"


def test_reconcile_retries_after_api_outage():
    class FlakyAPI(FakePodAPI):
        def __init__(self):
            super().__init__()
            self.fail_next = True

        def list_pods(self, sel):
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("API down")
            return super().list_pods(sel)

    api = FlakyAPI()
    seed = KubernetesNodeProvider("h:1", api=api, cluster_name="rtk")
    api.fail_next = False
    existing = seed.create_nodes(CPU_TYPE, 2)

    api.fail_next = True
    p = KubernetesNodeProvider("h:1", api=api, cluster_name="rtk")
    assert p.non_terminated_nodes()  # first call failed reconcile, retried
    created = p.create_nodes(CPU_TYPE, 1)
    assert created[0] not in existing  # sequence advanced past survivors
