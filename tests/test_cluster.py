"""Multi-node scheduling, object transfer, placement groups, chaos.

Parity: python/ray/tests with ray_start_cluster (cluster_utils.Cluster
spawning extra raylets against one GCS — here extra Node objects against one
control service), plus test_chaos.py-style kill-and-recover assertions.
"""

import time

import numpy as np
import pytest

from ray_tpu.core.resources import ResourceSet
from ray_tpu.runtime.placement import PlacementGroupInfo, PlacementStrategy
from ray_tpu.core.ids import PlacementGroupID, JobID
from ray_tpu.runtime.scheduler import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


def test_tasks_spread_across_nodes(ray_start_cluster):
    rt, cluster = ray_start_cluster
    n2 = cluster.add_node({"CPU": 2})
    n3 = cluster.add_node({"CPU": 2})

    @rt.remote(execution="thread")
    def where():
        time.sleep(0.3)  # hold the CPU so utilization pressure builds
        return rt.get_runtime_context().get_node_id()

    nodes_seen = set(rt.get([where.remote() for _ in range(12)], timeout=60))
    assert len(nodes_seen) >= 2  # hybrid policy spills over


def test_node_affinity(ray_start_cluster):
    rt, cluster = ray_start_cluster
    n2 = cluster.add_node({"CPU": 2})

    @rt.remote(execution="thread")
    def where():
        return rt.get_runtime_context().get_node_id()

    strategy = NodeAffinitySchedulingStrategy(n2.node_id)
    for _ in range(5):
        assert rt.get(where.options(scheduling_strategy=strategy).remote()) == n2.node_id.hex()


def test_custom_resource_routing(ray_start_cluster):
    rt, cluster = ray_start_cluster
    special = cluster.add_node({"CPU": 1, "special": 1})

    @rt.remote(execution="thread", resources={"special": 1}, num_cpus=0)
    def where():
        return rt.get_runtime_context().get_node_id()

    assert rt.get(where.remote()) == special.node_id.hex()


def test_object_transfer_between_nodes(ray_start_cluster):
    rt, cluster = ray_start_cluster
    n2 = cluster.add_node({"CPU": 2, "n2": 1})

    @rt.remote(execution="thread", resources={"n2": 1}, num_cpus=0)
    def produce():
        return np.ones((256, 256))

    @rt.remote(execution="thread")
    def consume(x):
        return float(x.sum())

    ref = produce.remote()
    # consumer may land on head; transfer must occur
    strategy = NodeAffinitySchedulingStrategy(cluster.head_node.node_id)
    out = rt.get(consume.options(scheduling_strategy=strategy).remote(ref), timeout=30)
    assert out == 256 * 256
    assert cluster.transfer_count >= 1


def test_infeasible_then_feasible(ray_start_cluster):
    rt, cluster = ray_start_cluster

    @rt.remote(execution="thread", resources={"late": 1}, num_cpus=0)
    def needs_late():
        return "ran"

    ref = needs_late.remote()
    time.sleep(0.3)
    cluster.add_node({"CPU": 1, "late": 1})
    assert rt.get(ref, timeout=30) == "ran"


# ---------------------------------------------------------------- chaos
def test_node_death_task_retry(ray_start_cluster):
    rt, cluster = ray_start_cluster
    doomed = cluster.add_node({"CPU": 2, "doomed": 1})

    @rt.remote(execution="thread", resources={"doomed": 1}, num_cpus=0, max_retries=2)
    def trapped():
        time.sleep(2)
        return "done"

    ref = trapped.remote()
    time.sleep(0.3)
    # free the resource constraint then kill the node: retry must land on a
    # new node offering the resource
    replacement = cluster.add_node({"CPU": 2, "doomed": 1})
    cluster.kill_node(doomed.node_id)
    assert rt.get(ref, timeout=60) == "done"


def test_lost_object_reconstruction(ray_start_cluster):
    rt, cluster = ray_start_cluster
    volatile = cluster.add_node({"CPU": 2, "volatile": 1})

    @rt.remote(execution="thread", resources={"volatile": 1}, num_cpus=0, max_retries=2)
    def produce():
        return np.full((64,), 7.0)

    ref = produce.remote()
    rt.wait([ref], num_returns=1, timeout=30)
    # replacement node able to re-run the producer
    cluster.add_node({"CPU": 2, "volatile": 1})
    cluster.kill_node(volatile.node_id)
    # the only copy died with the node; lineage reconstruction must re-run
    out = rt.get(ref, timeout=60)
    assert float(out.sum()) == 64 * 7.0


def test_actor_restart_on_node_death(ray_start_cluster):
    rt, cluster = ray_start_cluster
    doomed = cluster.add_node({"CPU": 2, "spot": 1})

    @rt.remote(max_restarts=3, resources={"spot": 1}, num_cpus=0)
    class Survivor:
        def ping(self):
            return "alive"

    s = Survivor.remote()
    assert rt.get(s.ping.remote(), timeout=30) == "alive"
    cluster.add_node({"CPU": 2, "spot": 1})
    cluster.kill_node(doomed.node_id)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            assert rt.get(s.ping.remote(), timeout=10) == "alive"
            break
        except Exception:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not restart on a new node")


# ------------------------------------------------------- placement groups
def _make_pg(cluster, bundles, strategy):
    info = PlacementGroupInfo(
        PlacementGroupID.of(JobID.from_int(1)),
        [ResourceSet(b) for b in bundles],
        strategy,
    )
    ok = cluster.control.placement_groups.create(info)
    return info, ok


def test_pg_strict_pack(ray_start_cluster):
    rt, cluster = ray_start_cluster
    cluster.add_node({"CPU": 4})
    info, ok = _make_pg(cluster, [{"CPU": 1}, {"CPU": 1}], PlacementStrategy.STRICT_PACK)
    assert ok
    nodes = set(info.bundle_placements.values())
    assert len(nodes) == 1


def test_pg_strict_spread(ray_start_cluster):
    rt, cluster = ray_start_cluster
    cluster.add_node({"CPU": 2})
    cluster.add_node({"CPU": 2})
    info, ok = _make_pg(cluster, [{"CPU": 1}] * 3, PlacementStrategy.STRICT_SPREAD)
    assert ok
    assert len(set(info.bundle_placements.values())) == 3


def test_pg_strict_spread_infeasible(ray_start_cluster):
    rt, cluster = ray_start_cluster
    # only head node exists: 4 strict-spread bundles cannot fit
    info, ok = _make_pg(cluster, [{"CPU": 1}] * 4, PlacementStrategy.STRICT_SPREAD)
    assert not ok


def test_pg_reserves_resources(ray_start_cluster):
    rt, cluster = ray_start_cluster
    head = cluster.head_node
    before = head.pool.available.get("CPU")
    info, ok = _make_pg(cluster, [{"CPU": 1}], PlacementStrategy.PACK)
    assert ok
    after = head.pool.available.get("CPU")
    assert after == before - 1
    cluster.control.placement_groups.remove(info.pg_id)
    assert head.pool.available.get("CPU") == before


def test_pg_scheduling_strategy_targets_bundle_node(ray_start_cluster):
    rt, cluster = ray_start_cluster
    n2 = cluster.add_node({"CPU": 4})
    info, ok = _make_pg(cluster, [{"CPU": 2}], PlacementStrategy.PACK)
    assert ok
    target = info.bundle_placements[0]

    @rt.remote(execution="thread", num_cpus=0)
    def where():
        return rt.get_runtime_context().get_node_id()

    strategy = PlacementGroupSchedulingStrategy(info, placement_group_bundle_index=0)
    assert rt.get(where.options(scheduling_strategy=strategy).remote()) == target.hex()


def test_runtime_env_conda_gates_and_pip_passthrough():
    from ray_tpu.runtime_env.plugin import apply_to_process_env

    # pip deps already installed pass through conda's pip section
    env, cwd = apply_to_process_env(
        {"conda": {"dependencies": ["python", {"pip": ["numpy"]}]}}, {}, None
    )
    # a named conda env cannot exist here
    with pytest.raises(RuntimeError, match="conda"):
        apply_to_process_env({"conda": "my-env"}, {}, None)
    with pytest.raises(RuntimeError, match="not pre-installed"):
        apply_to_process_env(
            {"conda": {"dependencies": [{"pip": ["definitely-not-a-real-pkg-xyz"]}]}},
            {},
            None,
        )


def test_control_state_snapshot_restore(tmp_path):
    """GCS-with-Redis parity: durable control state (KV, jobs, task events)
    survives a full runtime restart via the snapshot file."""
    import ray_tpu

    snap = str(tmp_path / "control.snap")
    ray_tpu.init(num_cpus=2, _system_config={"control_snapshot_path": snap})
    try:
        cluster = ray_tpu.get_cluster()
        cluster.control.kv.put(b"cfg/key", b"value-1")
        cluster.control.kv.put(b"other", b"v2", namespace="ns2")

        @ray_tpu.remote
        def f():
            return 1

        ray_tpu.get(f.remote())
    finally:
        ray_tpu.shutdown()

    ray_tpu.init(num_cpus=2, _system_config={"control_snapshot_path": snap})
    try:
        cluster = ray_tpu.get_cluster()
        assert cluster.control.kv.get(b"cfg/key") == b"value-1"
        assert cluster.control.kv.get(b"other", namespace="ns2") == b"v2"
        jobs = cluster.control.jobs.list_jobs()
        # the cleanly-shut-down driver job restored as SUCCEEDED
        assert any(j.status == "SUCCEEDED" for j in jobs)
        # new driver's job id must not collide with restored history
        assert len({j.job_id for j in jobs}) == len(jobs) >= 2
        assert len(cluster.control.task_events) > 0
    finally:
        ray_tpu.shutdown()


def test_util_placement_group_api(ray_start_cluster):
    """ray.util.placement_group parity: create, table, strategy use, remove."""
    rt, cluster = ray_start_cluster
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        placement_group_table,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}], strategy="PACK", name="mygang")
    assert pg.wait(5)
    assert rt.get(pg.ready()) is True
    table = placement_group_table(pg)
    assert table["state"] == "CREATED" and table["name"] == "mygang"

    @rt.remote(num_cpus=1)
    def inside():
        return "in-pg"

    strat = PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    assert rt.get(inside.options(scheduling_strategy=strat).remote(), timeout=30) == "in-pg"
    remove_placement_group(pg)
    assert placement_group_table(pg)["state"] == "REMOVED"

    # validation errors
    import pytest as _p
    with _p.raises(ValueError, match="empty"):
        placement_group([])
    with _p.raises(ValueError, match="strategy"):
        placement_group([{"CPU": 1}], strategy="NOT_A_STRATEGY")
    with _p.raises(ValueError, match="lifetime"):
        placement_group([{"CPU": 1}], lifetime="bogus")
