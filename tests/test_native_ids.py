"""Parity tests for the native id tier (`ray_tpu/native/src/hotpath.c`).

The C types must be drop-in equivalents of the pure-Python classes in
`ray_tpu/core/ids.py` (which aliases them on import): same layouts, same
nil/mint conventions, same pickling identity.  The pure-Python classes are
reached here via a subprocess with RAY_TPU_PURE_PY_IDS=1 — in-process both
tiers can't be active at once (mixed instances would break dict equality).
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys

import pytest

from ray_tpu.native import hotpath as hp


def test_native_tier_is_active():
    # The suite must exercise the C tier — if the build broke, this fails
    # loudly instead of silently testing the fallback.
    from ray_tpu.core import ids

    assert ids.TaskID is hp.TaskID
    assert ids.ObjectID is hp.ObjectID


def test_layouts_and_lineage():
    job = hp.JobID.from_int(9)
    assert job.binary() == (9).to_bytes(4, "little")
    assert job.int_value() == 9

    actor = hp.ActorID.of(job)
    assert len(actor.binary()) == 12
    assert actor.job_id() == job

    t = hp.TaskID.for_actor_task(actor)
    assert len(t.binary()) == 20
    assert t.actor_id() == actor
    assert t.job_id() == job

    tn = hp.TaskID.for_normal_task(job)
    assert tn.actor_id().is_nil()
    assert tn.job_id() == job

    tc = hp.TaskID.for_actor_creation(actor)
    assert tc.binary()[:8] == b"\x00" * 8
    assert tc.actor_id() == actor

    td = hp.TaskID.for_driver(job)
    assert td.binary()[:8] == b"\xfe" * 8

    o = hp.ObjectID.for_task_return(t, 3)
    assert o.task_id() == t
    assert o.job_id() == job
    assert o.index() == 3
    assert o.is_return() and not o.is_put()

    p = hp.ObjectID.for_put(t, 3)
    assert p.is_put() and not p.is_return()
    assert p.index() & 0x7FFFFFFF == 3
    assert p != o

    pg = hp.PlacementGroupID.of(job)
    assert pg.job_id() == job


def test_task_mint_monotonic_and_unique():
    job = hp.JobID.from_int(1)
    a = hp.TaskID.for_normal_task(job)
    b = hp.TaskID.for_normal_task(job)
    assert a != b
    assert int.from_bytes(a.binary()[:8], "little") < int.from_bytes(b.binary()[:8], "little")


def test_equality_hash_dict_semantics():
    t = hp.TaskID.for_normal_task(hp.JobID.from_int(2))
    same = hp.TaskID(t.binary())
    assert t == same and hash(t) == hash(same)
    assert {t: "x"}[same] == "x"
    # same bytes, different 16-byte kinds: never equal
    n = hp.NodeID.from_random()
    w = hp.WorkerID(n.binary())
    assert n != w
    assert t != t.binary()
    assert t != "not an id"
    # ordering is raw-bytes, mirroring the Python classes' __lt__
    lo, hi = hp.NodeID(b"\x00" * 16), hp.NodeID(b"\x01" + b"\x00" * 15)
    assert lo < hi


def test_nil_and_validation():
    assert hp.ActorID.nil().is_nil()
    assert hp.ActorID.nil() == hp.ActorID.nil()
    with pytest.raises(ValueError):
        hp.TaskID(b"short")
    rt = hp.NodeID.from_hex(hp.NodeID.from_random().hex())
    assert isinstance(rt, hp.NodeID)


def test_pickle_resolves_through_ids_module():
    t = hp.TaskID.for_normal_task(hp.JobID.from_int(5))
    blob = pickle.dumps(t, protocol=5)
    # the pickle references ray_tpu.core.ids.TaskID — the aliasing module
    assert b"ray_tpu.core.ids" in blob
    t2 = pickle.loads(blob)
    assert t2 == t and type(t2) is type(t)


def test_job_counter_ensure_above():
    hp.JobID.ensure_above(10_000)
    assert hp.JobID.next().int_value() > 10_000


def test_pure_python_fallback_parity():
    """A RAY_TPU_PURE_PY_IDS=1 subprocess must produce byte-identical ids
    from the same recipe, unpickle ids minted by the C tier, and round-trip
    its own back to us."""
    t = hp.TaskID.for_actor_task(hp.ActorID.of(hp.JobID.from_int(3)))
    o = hp.ObjectID.for_put(t, 7)
    script = r"""
import os, pickle, sys
assert os.environ["RAY_TPU_PURE_PY_IDS"] == "1"
from ray_tpu.core import ids
# must actually be the Python tier: pure-Python classes are heap types
# (Py_TPFLAGS_HEAPTYPE, bit 9); the C extension's are static types
assert ids.TaskID.__flags__ & (1 << 9), "expected the pure-Python id tier"
import ray_tpu.native
o = pickle.loads(sys.stdin.buffer.read())
assert type(o) is ids.ObjectID
t = o.task_id()
assert o.is_put() and o.index() & 0x7FFFFFFF == 7
job = ids.JobID.from_int(3)
assert t.job_id() == job
# same recipes, same layouts
td = ids.TaskID.for_driver(job)
assert td.binary()[:8] == b"\xfe" * 8
sys.stdout.buffer.write(pickle.dumps(o))
"""
    env = dict(os.environ, RAY_TPU_PURE_PY_IDS="1")
    proc = subprocess.run(
        [sys.executable, "-c", script],
        input=pickle.dumps(o),
        capture_output=True,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr.decode()
    back = pickle.loads(proc.stdout)
    assert back == o and type(back) is hp.ObjectID


def test_abstract_base_rejects_classmethods():
    # BaseID is abstract: the inherited classmethods must error cleanly,
    # never read a size off the bare base type (review finding: the cast
    # previously walked past the PyTypeObject)
    for m in ("nil", "from_random"):
        with pytest.raises(TypeError):
            getattr(hp.BaseID, m)()
    with pytest.raises(TypeError):
        hp.BaseID.from_hex("00")

    # a Python heap subclass is not an IDType either — the classmethods
    # must refuse it instead of downcasting past PyTypeObject
    class MyID(hp.BaseID):
        pass

    for m in ("nil", "from_random"):
        with pytest.raises(TypeError):
            getattr(MyID, m)()
