"""Lakehouse datasources + partitioned parquet writes (round-3 VERDICT 9).

Delta round-trips natively (log replay, no deltalake dependency); Lance and
Iceberg gate on their libraries (skipped when absent, with the ImportError
message asserted).  Partitioned parquet writes cover hive / hash / range.

Parity anchors: python/ray/data/datasource/{delta_sharing,lance,iceberg}
_datasource.py and parquet partitioning.
"""

import json
import os

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.data import read_api


@pytest.fixture(scope="module", autouse=True)
def _rt():
    rt.init(num_cpus=2, ignore_reinit_error=True)
    yield
    rt.shutdown()


def _make_ds(n=100):
    from ray_tpu.data import read_api as ra

    return ra.range(n).map(lambda row: {"id": row["id"], "bucket": int(row["id"] % 4)})


# ---------------------------------------------------------------- delta
def test_delta_write_read_roundtrip(tmp_path):
    path = str(tmp_path / "delta_table")
    ds = _make_ds(50)
    ds.write_delta(path)
    # the on-disk table is a real Delta layout
    assert os.path.isdir(os.path.join(path, "_delta_log"))
    commits = os.listdir(os.path.join(path, "_delta_log"))
    assert any(c.endswith(".json") for c in commits)

    back = read_api.read_delta(path)
    rows = sorted(r["id"] for r in back.take_all())
    assert rows == list(range(50))


def test_delta_append_and_overwrite(tmp_path):
    path = str(tmp_path / "delta_table")
    _make_ds(10).write_delta(path)
    _make_ds(10).write_delta(path, mode="append")
    assert len(read_api.read_delta(path).take_all()) == 20

    _make_ds(5).write_delta(path, mode="overwrite")
    rows = read_api.read_delta(path).take_all()
    assert sorted(r["id"] for r in rows) == list(range(5))
    # overwritten files are tombstoned in the log, not deleted from disk
    log = os.path.join(path, "_delta_log")
    removes = []
    for commit in sorted(os.listdir(log)):
        if commit.endswith(".json"):
            with open(os.path.join(log, commit)) as f:
                removes += [json.loads(l) for l in f if '"remove"' in l]
    assert removes, "overwrite must emit remove actions"


def test_delta_column_projection(tmp_path):
    path = str(tmp_path / "delta_table")
    _make_ds(20).write_delta(path)
    rows = read_api.read_delta(path, columns=["bucket"]).take_all()
    assert set(rows[0].keys()) == {"bucket"}


def test_delta_rejects_non_table(tmp_path):
    with pytest.raises(Exception):
        read_api.read_delta(str(tmp_path / "nope")).take_all()


# ---------------------------------------------------------------- lance / iceberg gating
def test_lance_gated_or_roundtrip(tmp_path):
    try:
        import lance  # noqa: F401

        have = True
    except ImportError:
        have = False
    if not have:
        with pytest.raises(ImportError, match="lance"):
            read_api.read_lance(str(tmp_path / "t.lance")).take_all()
        with pytest.raises(ImportError, match="lance"):
            _make_ds(5).write_lance(str(tmp_path / "t.lance"))
        return
    path = str(tmp_path / "t.lance")
    _make_ds(30).write_lance(path)
    rows = sorted(r["id"] for r in read_api.read_lance(path).take_all())
    assert rows == list(range(30))


def test_iceberg_gated():
    try:
        import pyiceberg  # noqa: F401

        pytest.skip("pyiceberg installed; gating path not applicable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="pyiceberg"):
        read_api.read_iceberg("db.table").take_all()


# ------------------------------------------------- partitioned parquet
def test_hive_partitioned_parquet_roundtrip(tmp_path):
    path = str(tmp_path / "hive")
    _make_ds(40).write_parquet(path, partition_cols=["bucket"])
    # hive layout on disk
    subdirs = sorted(d for d in os.listdir(path) if d.startswith("bucket="))
    assert subdirs == ["bucket=0", "bucket=1", "bucket=2", "bucket=3"]
    # partition values come back as columns
    rows = read_api.read_parquet(path).take_all()
    assert len(rows) == 40
    assert all(r["bucket"] == r["id"] % 4 for r in rows)


def test_hash_partitioned_parquet_write(tmp_path):
    path = str(tmp_path / "hashed")
    _make_ds(64).write_parquet(
        path, partition_by={"column": "id", "mode": "hash", "num_partitions": 4}
    )
    spec = json.load(open(os.path.join(path, "_partition_spec.json")))
    assert spec["mode"] == "hash" and spec["num_partitions"] == 4
    parts = sorted(d for d in os.listdir(path) if d.startswith("hash="))
    assert 1 < len(parts) <= 4
    rows = read_api.read_parquet(path).take_all()
    assert sorted(r["id"] for r in rows) == list(range(64))


def test_range_partitioned_parquet_write_is_ordered(tmp_path):
    path = str(tmp_path / "ranged")
    _make_ds(100).write_parquet(
        path, partition_by={"column": "id", "mode": "range", "num_partitions": 4}
    )
    spec = json.load(open(os.path.join(path, "_partition_spec.json")))
    assert spec["mode"] == "range" and len(spec["bounds"]) == 3
    import pyarrow.parquet as pq

    parts = sorted(d for d in os.listdir(path) if d.startswith("range="))
    assert len(parts) == 4
    maxes = []
    for part in parts:
        vals = []
        for f in os.listdir(os.path.join(path, part)):
            vals += pq.read_table(os.path.join(path, part, f))["id"].to_pylist()
        assert vals, part
        maxes.append((min(vals), max(vals)))
    # ranges are disjoint and ordered
    for (lo1, hi1), (lo2, hi2) in zip(maxes, maxes[1:]):
        assert hi1 <= lo2
    rows = read_api.read_parquet(path).take_all()
    assert sorted(r["id"] for r in rows) == list(range(100))
