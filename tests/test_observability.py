"""Observability: metrics registry, structured events, timeline, state API.

Mirrors the reference's coverage of its stats/event/state surfaces
(``src/ray/stats/``, ``src/ray/util/event.h``, ``python/ray/util/state``).
"""

import json
import time

import pytest

import ray_tpu as rt
from ray_tpu.observability.events import EventManager, EventSeverity
from ray_tpu.observability.metrics import Counter, Gauge, Histogram, MetricsRegistry
from ray_tpu.observability.timeline import chrome_trace, dump_timeline


# ----------------------------------------------------------------------
# metrics
# ----------------------------------------------------------------------
def test_counter_gauge_histogram():
    reg = MetricsRegistry()
    c = reg.counter("tasks", "task count")
    c.inc()
    c.inc(2, tags={"state": "FINISHED"})
    assert c.get() == 1
    assert c.get({"state": "FINISHED"}) == 2

    g = reg.gauge("mem", "bytes", "By")
    g.set(123.5)
    assert g.get() == 123.5

    h = reg.histogram("lat", "latency", "s", boundaries=[0.1, 1, 10])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(100)
    counts, total_sum, total = h.snapshot()
    assert counts == [1, 1, 0, 1]    # 100 lands in the explicit overflow bucket
    assert sum(counts) == total == 3
    assert total_sum == pytest.approx(100.55)


def test_registry_same_name_returns_same_metric_and_type_conflict_raises():
    reg = MetricsRegistry()
    a = reg.counter("x")
    b = reg.counter("x")
    assert a is b
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_prometheus_rendering():
    reg = MetricsRegistry()
    reg.counter("tasks", "help text").inc(3, tags={"state": "FINISHED"})
    reg.gauge("mem").set(7)
    reg.histogram("lat", boundaries=[1, 5]).observe(0.5)
    text = reg.render_prometheus()
    assert "# TYPE ray_tpu_tasks counter" in text
    assert 'ray_tpu_tasks{state="FINISHED"} 3' in text
    assert "ray_tpu_mem 7" in text
    assert 'ray_tpu_lat_bucket{le="1"} 1' in text
    assert 'ray_tpu_lat_bucket{le="+Inf"} 1' in text
    assert "ray_tpu_lat_count 1" in text


# ----------------------------------------------------------------------
# events
# ----------------------------------------------------------------------
def test_event_manager_filters_and_file_sink(tmp_path):
    em = EventManager(log_dir=str(tmp_path))
    em.info("raylet", "NODE_ADDED", "node up", node_id="abc")
    em.error("gcs", "NODE_DEAD", "node down")
    assert len(em.list_events()) == 2
    assert len(em.list_events(severity=EventSeverity.ERROR)) == 1
    assert em.list_events(source_type="raylet")[0].custom_fields["node_id"] == "abc"
    lines = (tmp_path / "events.jsonl").read_text().strip().splitlines()
    assert len(lines) == 2
    assert json.loads(lines[1])["label"] == "NODE_DEAD"


# ----------------------------------------------------------------------
# timeline + state API against a live runtime
# ----------------------------------------------------------------------
@pytest.fixture
def rt_cluster():
    rt.init(num_cpus=2)
    yield
    rt.shutdown()


def test_task_events_and_chrome_timeline(rt_cluster, tmp_path):
    @rt.remote
    def work(x):
        return x * 2

    assert rt.get([work.remote(i) for i in range(5)]) == [0, 2, 4, 6, 8]
    events = rt.timeline()
    finished = [e for e in events if e["state"] == "FINISHED" and e["name"] == "work"]
    assert len(finished) == 5
    ev = finished[0]
    assert ev["submit_ts"] and ev["start_ts"] and ev["ts"] >= ev["start_ts"] >= ev["submit_ts"]

    trace = chrome_trace(events)
    assert all(t["ph"] == "X" for t in trace)
    path = dump_timeline(str(tmp_path / "timeline.json"))
    data = json.loads(open(path).read())
    assert len(data) >= 5


def test_failed_task_event(rt_cluster):
    @rt.remote
    def boom():
        raise ValueError("x")

    with pytest.raises(rt.RayTaskError):
        rt.get(boom.remote())
    states = {e["state"] for e in rt.timeline() if e["name"] == "boom"}
    assert "FAILED" in states


def test_state_api_lists(rt_cluster):
    from ray_tpu import state

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.options(name="state-test").remote()
    assert rt.get(c.incr.remote()) == 1

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["is_head"]
    assert nodes[0]["resources_total"]["CPU"] == 2

    actors = state.list_actors()
    assert any(a["name"] == "state-test" and a["state"] == "ALIVE" for a in actors)

    # filters
    assert state.list_actors(filters=[("state", "=", "DEAD")]) == []

    ref = rt.put(list(range(100)))
    objs = state.list_objects()
    assert any(o["object_id"] == ref.id().hex() for o in objs)

    jobs = state.list_jobs()
    assert len(jobs) == 1 and jobs[0]["status"] == "RUNNING"
    _ = ref


def test_state_api_summaries(rt_cluster):
    from ray_tpu import state

    @rt.remote
    def stepper():
        return 1

    rt.get([stepper.remote() for _ in range(4)])
    summary = state.summarize_tasks()
    assert summary["summary"]["stepper"]["state_counts"]["FINISHED"] == 4

    actors = state.summarize_actors()
    assert isinstance(actors["total_actors"], int)

    objs = state.summarize_objects()
    assert objs["total_objects"] >= 0


def test_task_metrics_incremented(rt_cluster):
    from ray_tpu.observability.metrics import global_registry

    before = global_registry().counter("tasks_terminal_total").get({"state": "FINISHED"})

    @rt.remote
    def t():
        return 1

    rt.get([t.remote() for _ in range(3)])
    after = global_registry().counter("tasks_terminal_total").get({"state": "FINISHED"})
    assert after - before >= 3


def test_util_metrics_user_api():
    """Parity: ray.util.metrics — user-defined metrics export through the
    same Prometheus endpoint as system metrics."""
    from ray_tpu.observability.metrics import global_registry
    from ray_tpu.util.metrics import Counter, Gauge, Histogram

    c = Counter("app_requests_total", "requests")
    c.inc()
    c.inc(2)
    g = Gauge("app_queue_depth")
    g.set(7)
    h = Histogram("app_latency_s", boundaries=[0.01, 0.1, 1.0])
    h.observe(0.05)
    out = global_registry().render_prometheus()
    assert "app_requests_total" in out
    assert "app_queue_depth 7" in out
    assert "app_latency_s" in out


# --------------------------------------------------------------------------
# rt stack: cluster-wide live stack dump (reference: `ray stack`,
# scripts.py:1830)
# --------------------------------------------------------------------------
def test_cluster_stack_dump_two_process():
    import time as _time

    import ray_tpu as rt
    from test_multihost import _spawn_agent, _wait_for_nodes

    rt.init(num_cpus=2)
    try:
        cluster = rt.get_cluster()
        address = cluster.start_head_service()
        proc = _spawn_agent(address)
        try:
            _wait_for_nodes(cluster, 2)

            # something long-running in a remote pool worker so its stack
            # shows a real user frame
            @rt.remote(resources={"remote": 1}, execution="process")
            def parked():
                _time.sleep(8)
                return 1

            ref = parked.remote()
            deadline = _time.monotonic() + 30
            # wait until the worker is actually executing
            while _time.monotonic() < deadline:
                dump = cluster.dump_cluster_stacks(timeout=5.0)
                agents = [e for e in dump["nodes"].values() if "process" in e]
                if agents and any("parked" in s for e in agents for s in e.get("workers", {}).values()):
                    break
                _time.sleep(0.5)
            else:
                raise AssertionError(f"worker stack never showed the parked task: {dump}")

            # driver stacks present and name this very test
            assert "test_cluster_stack_dump_two_process" in dump["driver"]
            # the agent's own process stacks came across the wire
            assert any("Thread" in e.get("process", "") for e in agents)
            assert rt.get(ref, timeout=60) == 1
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
    finally:
        rt.shutdown()
