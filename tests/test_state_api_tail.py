"""State/job/runtime_env/air surface tail (parity: ray.util.state get_*/
list_workers/list_cluster_events/StateApiClient, ray.job_submission models,
ray.runtime_env.RuntimeEnv, ray.air type shims)."""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import state


@pytest.fixture
def runtime():
    rt.init(num_cpus=2)
    try:
        yield rt
    finally:
        rt.shutdown()


def test_get_accessors_and_client(runtime):
    @rt.remote
    def f(x):
        return x + 1

    @rt.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert rt.get(a.ping.remote()) == "pong"
    assert rt.get(f.remote(1)) == 2
    ref = rt.put(np.zeros(1000))

    nodes = state.list_nodes()
    assert state.get_node(nodes[0]["node_id"])["node_id"] == nodes[0]["node_id"]
    actor_row = state.list_actors()[0]
    assert state.get_actor(actor_row["actor_id"])["class_name"] == "A"
    # prefix lookup works like the reference CLI
    assert state.get_actor(actor_row["actor_id"][:8]) is not None
    task_row = next(t for t in state.list_tasks() if t.get("name", "").startswith("f"))
    assert state.get_task(task_row["task_id"]) is not None
    objs = state.get_objects(ref.hex())
    assert objs and objs[0]["size_bytes"] > 0

    client = state.StateApiClient()
    assert len(client.list("nodes")) == len(nodes)
    assert client.get("actors", actor_row["actor_id"])["class_name"] == "A"
    with pytest.raises(ValueError, match="unknown resource"):
        client.list("gremlins")


def test_list_workers_and_events(runtime):
    @rt.remote(execution="process")
    def heavy():
        return 42

    assert rt.get(heavy.remote()) == 42
    workers = state.list_workers()
    assert workers and all(w["node_id"] for w in workers)
    assert state.get_worker(workers[0]["worker_id"])["pid"] == workers[0]["pid"]

    events = state.list_cluster_events()
    assert isinstance(events, list)
    for e in events[:3]:
        assert "severity" in e and "message" in e

    # log surface exists even with no remote nodes, and an unknown node id
    # yields no fabricated sources
    assert state.list_logs() == {}
    assert state.list_logs("nope") == {}
    assert state.get_log("nope") == []

    # workers filter actually applies
    alive = state.list_workers(filters=[("is_alive", "=", "True")])
    assert all(w["is_alive"] for w in alive)

    # jobs resolve by job_id (the key list_jobs actually emits)
    jobs = state.list_jobs()
    if jobs:
        assert state.get_job(jobs[0]["job_id"]) is not None


def test_runtime_env_class_validates(runtime):
    from ray_tpu.runtime_env import RuntimeEnv, RuntimeEnvConfig

    env = RuntimeEnv(env_vars={"A": "hello"}, config={"setup_timeout_seconds": 5})
    assert env.to_dict()["env_vars"] == {"A": "hello"}
    assert env["config"].setup_timeout_seconds == 5
    with pytest.raises(ValueError, match="unknown runtime_env field"):
        RuntimeEnv(not_a_field=1)

    # a RuntimeEnv with a config ACTUALLY RUNS on a process worker (the
    # config meta key must not be rejected as an unknown plugin)
    @rt.remote(execution="process", runtime_env=env)
    def read_env():
        import os

        return os.environ.get("A")

    assert rt.get(read_env.remote(), timeout=60) == "hello"


def test_job_models_roundtrip():
    from ray_tpu.job_submission import JobDetails, JobInfo, JobStatus, JobType

    d = {
        "submission_id": "raysubmit_abc",
        "entrypoint": "python x.py",
        "status": "SUCCEEDED",
        "message": "ok",
        "metadata": {"k": "v"},
        "start_time": 1.0,
        "end_time": 2.0,
    }
    info = JobInfo.from_dict(d)
    assert info.status is JobStatus.SUCCEEDED and info.metadata == {"k": "v"}
    details = JobDetails.from_dict(dict(d, driver_info={"id": "d1", "pid": 7}))
    assert details.type is JobType.SUBMISSION and details.job_id == "raysubmit_abc"
    assert details.driver_info.pid == 7


def test_air_type_shims():
    from ray_tpu.air import AcquiredResources, DatasetConfig, ResourceRequest

    req = ResourceRequest([{"CPU": 2.0}, {"CPU": 1.0}])
    assert req.head_bundle == {"CPU": 2.0}
    got = AcquiredResources(request=req)
    assert got.request.strategy == "PACK"
    assert DatasetConfig().split is True


def test_util_metrics_default_tags(runtime):
    from ray_tpu.observability.metrics import global_registry
    from ray_tpu.util import metrics

    c = metrics.Counter("app_requests_total", "requests").set_default_tags(
        {"deployment": "d1"}
    )
    c.inc()
    c.inc(2.0, tags={"deployment": "d2"})  # per-call override wins
    series = dict(global_registry().counter("app_requests_total").series())
    by_tag = {frozenset(k): v for k, v in series.items()}
    assert by_tag[frozenset({("deployment", "d1")})] == 1.0
    assert by_tag[frozenset({("deployment", "d2")})] == 2.0

    # gauge default-tag merge verified on the recorded series
    g = metrics.Gauge("app_inflight").set_default_tags({"app": "x"})
    g.set(3.0)
    gseries = {frozenset(k): v for k, v in global_registry().gauge("app_inflight").series()}
    assert gseries[frozenset({("app", "x")})] == 3.0

    h = metrics.Histogram(
        "app_latency_s", boundaries=[0.1, 1.0], tag_keys=("route",)
    ).set_default_tags({"route": "/a"})
    h.observe(0.05)
    # declared tag_keys reject typo'd tags instead of exporting stray series
    with pytest.raises(ValueError, match="unknown tag"):
        h.observe(0.05, tags={"rouet": "/a"})
    # reference parity: counters refuse non-positive increments
    with pytest.raises(ValueError, match="value > 0"):
        c.inc(0)
    assert c.info["default_tags"] == {"deployment": "d1"}
    assert h.info["tag_keys"] == ("route",)
