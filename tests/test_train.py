"""Tests for ray_tpu.train — mirrors the reference's train/tests strategy:
worker-group orchestration, report/checkpoint streaming, data sharding,
fault tolerance."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_single_worker_report():
    def loop(config):
        for i in range(3):
            train.report({"step": i, "loss": 1.0 / (i + 1)})

    result = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.metrics["step"] == 2
    assert len(result.metrics_dataframe) == 3
    assert result.error is None


def test_multi_worker_context():
    def loop(config):
        ctx = train.get_context()
        train.report({"rank": ctx.get_world_rank(), "world": ctx.get_world_size()})

    result = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=4)).fit()
    assert result.metrics["world"] == 4
    assert result.metrics["rank"] == 0


def test_train_loop_config_passed():
    def loop(config):
        train.report({"lr": config["lr"]})

    result = JaxTrainer(loop, train_loop_config={"lr": 0.1}).fit()
    assert result.metrics["lr"] == 0.1


def test_checkpoint_roundtrip(tmp_path):
    def loop(config):
        ckpt = Checkpoint.from_dict({"weights": [1, 2, 3]}, base_dir=str(tmp_path))
        train.report({"done": 1}, checkpoint=ckpt)

    result = JaxTrainer(loop).fit()
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["weights"] == [1, 2, 3]


def test_pytree_checkpoint(tmp_path):
    import jax.numpy as jnp

    def loop(config):
        params = {"w": jnp.ones((2, 2)), "b": jnp.zeros(2)}
        train.report({"ok": 1}, checkpoint=Checkpoint.from_pytree(params, base_dir=str(tmp_path)))

    result = JaxTrainer(loop).fit()
    tree = result.checkpoint.to_pytree()
    assert np.allclose(np.asarray(tree["w"]), 1.0)


def test_dataset_shards():
    ds = rd.range(80, parallelism=4)

    def loop(config):
        shard = train.get_dataset_shard("train")
        total = sum(int(b["id"].sum()) for b in shard.iter_batches(batch_size=16))
        n = shard.count()
        train.report({"n": n, "total": total})

    trainer = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=4), datasets={"train": ds})
    result = trainer.fit()
    assert result.metrics["n"] == 20  # 80 rows / 4 workers


def test_mesh_available_in_worker():
    def loop(config):
        ctx = train.get_context()
        mesh = ctx.get_mesh()
        train.report({"n_devices": len(ctx.get_devices()), "has_mesh": mesh is not None})

    result = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.metrics["has_mesh"] is True
    assert result.metrics["n_devices"] >= 1


def test_failure_restart_resumes_from_checkpoint(tmp_path):
    marker = tmp_path / "attempt"

    def loop(config):
        ckpt = train.get_checkpoint()
        start = ckpt.to_dict()["step"] + 1 if ckpt else 0
        for step in range(start, 4):
            if step == 2 and not marker.exists():
                marker.write_text("crashed")
                raise RuntimeError("injected failure")
            train.report(
                {"step": step},
                checkpoint=Checkpoint.from_dict({"step": step}, base_dir=str(tmp_path)),
            )

    result = JaxTrainer(
        loop,
        run_config=RunConfig(failure_config=FailureConfig(max_failures=2)),
    ).fit()
    assert result.error is None
    assert result.metrics["step"] == 3


def test_failure_exhausts_budget():
    def loop(config):
        raise ValueError("always fails")

    result = JaxTrainer(loop, run_config=RunConfig(failure_config=FailureConfig(max_failures=1))).fit()
    assert result.error is not None


def test_jax_training_end_to_end():
    """An actual jax model trained through the trainer (MLP on synthetic data)."""
    import jax
    import jax.numpy as jnp

    def loop(config):
        key = jax.random.PRNGKey(0)
        w = jnp.zeros((4, 1))
        x = jax.random.normal(key, (64, 4))
        true_w = jnp.array([[1.0], [-2.0], [0.5], [3.0]])
        y = x @ true_w

        @jax.jit
        def step(w, x, y):
            def loss_fn(w):
                return jnp.mean((x @ w - y) ** 2)

            loss, g = jax.value_and_grad(loss_fn)(w)
            return w - 0.1 * g, loss

        for i in range(100):
            w, loss = step(w, x, y)
        train.report({"loss": float(loss)})

    result = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.metrics["loss"] < 0.05


# ---------------------------------------------------------------------------
# TorchTrainer: real gloo process groups across process-actor ranks
# ---------------------------------------------------------------------------
def test_torch_trainer_allreduce():
    """Two process ranks join one gloo world and all-reduce a tensor."""
    from ray_tpu.train import ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu import train

        ctx = train.get_context()
        t = torch.tensor([float(ctx.get_world_rank() + 1)])
        dist.all_reduce(t)  # 1 + 2 = 3 across both ranks
        train.report({"reduced": float(t.item()), "world": dist.get_world_size()})

    trainer = TorchTrainer(loop, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.metrics["reduced"] == 3.0
    assert result.metrics["world"] == 2


def test_torch_trainer_ddp_training():
    """prepare_model wraps DDP; both ranks converge to identical weights."""
    from ray_tpu.train import ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def loop(config):
        import numpy as np
        import torch

        from ray_tpu import train
        from ray_tpu.train.torch import prepare_model

        torch.manual_seed(42)  # same init on every rank
        model = torch.nn.Linear(4, 1)
        model = prepare_model(model)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        rank = train.get_context().get_world_rank()
        rng = np.random.default_rng(rank)  # different data per rank
        for _ in range(5):
            x = torch.tensor(rng.normal(size=(8, 4)), dtype=torch.float32)
            y = x.sum(dim=1, keepdim=True)
            loss = ((model(x) - y) ** 2).mean()
            opt.zero_grad()
            loss.backward()  # DDP all-reduces grads here
            opt.step()
        w = model.module.weight if hasattr(model, "module") else model.weight
        # verify sync ACROSS ranks inside the gang: gather every rank's w0
        import torch.distributed as dist

        w0 = torch.tensor([w[0, 0].item()])
        gathered = [torch.zeros(1) for _ in range(dist.get_world_size())]
        dist.all_gather(gathered, w0)
        spread = float(max(g.item() for g in gathered) - min(g.item() for g in gathered))
        train.report(
            {"w0": float(w[0, 0].item()), "loss": float(loss.item()), "w0_spread": spread}
        )

    trainer = TorchTrainer(loop, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert np.isfinite(result.metrics["loss"])
    # DDP kept weights identical on every rank (spread gathered in-gang)
    assert result.metrics["w0_spread"] == 0.0
