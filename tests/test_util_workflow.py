"""Tests for ray_tpu.util (ActorPool, Queue, collective, check_serialize)
and ray_tpu.workflow (durable DAG execution, resume, replay-skipping)."""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.util import ActorPool, Queue, inspect_serializability
from ray_tpu.util import collective as col


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


# ------------------------------------------------------------- ActorPool
def test_actor_pool_map():
    @ray_tpu.remote
    class Doubler:
        def double(self, x):
            return x * 2

    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    assert out == [i * 2 for i in range(8)]


def test_actor_pool_unordered():
    @ray_tpu.remote
    class Sleeper:
        def go(self, t):
            time.sleep(t)
            return t

    pool = ActorPool([Sleeper.remote() for _ in range(2)])
    out = list(pool.map_unordered(lambda a, v: a.go.remote(v), [0.2, 0.01]))
    assert sorted(out) == [0.01, 0.2]
    assert out[0] == 0.01  # faster task finished first


# ----------------------------------------------------------------- Queue
def test_queue_basic():
    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    assert q.qsize() == 2
    with pytest.raises(Exception):
        q.put(3, block=False)
    assert q.get() == 1
    assert q.get() == 2
    assert q.empty()
    q.shutdown()


def test_queue_cross_task():
    q = Queue()

    def producer(qq):
        for i in range(5):
            qq.put(i)
        return True

    # Nested API calls require in-process execution (process workers have no
    # fabric connection back to the driver — thread tasks do).
    ray_tpu.get(ray_tpu.remote(producer).options(execution="thread").remote(q))
    got = [q.get(timeout=5) for _ in range(5)]
    assert got == [0, 1, 2, 3, 4]
    q.shutdown()


# ------------------------------------------------------------ collective
def test_collective_allreduce_threads():
    col.init_collective_group(world_size=4, rank=0, group_name="g1")
    results = {}

    def worker(rank):
        results[rank] = col.allreduce(np.full(4, rank + 1.0), group_name="g1", rank=rank)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for r in range(4):
        assert np.allclose(results[r], 10.0)  # 1+2+3+4
    col.destroy_collective_group("g1")


def test_collective_send_recv():
    out = {}

    def sender():
        col.send(np.arange(3), dst_rank=1, group_name="p2p", rank=0)

    def receiver():
        out["v"] = col.recv(src_rank=0, group_name="p2p", rank=1, timeout=10)

    t1, t2 = threading.Thread(target=sender), threading.Thread(target=receiver)
    t2.start(); t1.start(); t1.join(); t2.join()
    assert np.array_equal(out["v"], np.arange(3))


def test_collective_in_actors():
    col.init_collective_group(world_size=3, rank=0, group_name="ag")

    @ray_tpu.remote
    class Member:
        def __init__(self, rank):
            self.rank = rank

        def gather(self, value):
            return col.allgather(value, group_name="ag", rank=self.rank)

    members = [Member.options(execution="inproc").remote(r) for r in range(3)]
    refs = [m.gather.remote(i * 10) for i, m in enumerate(members)]
    outs = ray_tpu.get(refs)
    assert all(o == [0, 10, 20] for o in outs)
    col.destroy_collective_group("ag")


# ------------------------------------------------------- check_serialize
def test_inspect_serializability():
    ok, problems = inspect_serializability(lambda x: x + 1)
    assert ok
    lock = threading.Lock()
    ok, problems = inspect_serializability(lock)
    assert not ok
    assert problems


# -------------------------------------------------------------- workflow
def test_workflow_run_and_output(tmp_path):
    workflow.init(str(tmp_path))

    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def double(x):
        return x * 2

    dag = double.bind(add.bind(1, 2))
    result = workflow.run(dag, workflow_id="wf1")
    assert result == 6
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    assert workflow.get_output("wf1") == 6
    assert {"workflow_id": "wf1", "status": "SUCCESSFUL"} in workflow.list_all()


def test_workflow_resume_skips_completed(tmp_path):
    workflow.init(str(tmp_path))
    calls = {"n": 0}
    marker = tmp_path / "fail_once"
    marker.write_text("x")

    @ray_tpu.remote
    def step_a():
        return 10

    @ray_tpu.remote
    def step_b(x):
        import os

        if os.path.exists(str(marker)):
            os.unlink(str(marker))
            raise RuntimeError("transient failure")
        return x + 5

    dag = step_b.bind(step_a.bind())
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf2")
    assert workflow.get_status("wf2") == "FAILED"
    # resume: step_a's durable result is reused, step_b reruns and succeeds
    assert workflow.resume("wf2") == 15
    assert workflow.get_status("wf2") == "SUCCESSFUL"


def test_workflow_run_async(tmp_path):
    workflow.init(str(tmp_path))

    @ray_tpu.remote
    def slow():
        time.sleep(0.2)
        return "done"

    fut = workflow.run_async(slow.bind(), workflow_id="wf3")
    assert fut.result(timeout=30) == "done"


def test_workflow_delete(tmp_path):
    workflow.init(str(tmp_path))

    @ray_tpu.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="wf4")
    workflow.delete("wf4")
    assert workflow.get_status("wf4") is None


# --------------------------------------------------------------------------
# Workflow events (parity: event_listener.py / wait_for_event)
# --------------------------------------------------------------------------
def test_workflow_wait_for_event_and_replay(tmp_path):
    from ray_tpu import workflow
    import ray_tpu

    workflow.init(str(tmp_path / "wf"))

    @ray_tpu.remote
    def combine(evt, base):
        return {"event": evt, "base": base}

    # deliver before waiting so the poll returns immediately
    workflow.deliver_event("approval", {"approved": True})
    evt_node = workflow.wait_for_event(workflow.QueueEventListener, "approval", 10.0)
    dag = combine.bind(evt_node, 7)
    out = workflow.run(dag, workflow_id="wf_events")
    assert out == {"event": {"approved": True}, "base": 7}

    # resume must REPLAY the checkpointed event, not wait again (no second
    # deliver_event happens; a re-poll would block and time out)
    out2 = workflow.resume("wf_events")
    assert out2 == {"event": {"approved": True}, "base": 7}


def test_timer_listener_fires():
    from ray_tpu.workflow.events import TimerListener
    import time as _t

    t0 = _t.monotonic()
    val = TimerListener().poll_for_event(0.05)
    assert _t.monotonic() - t0 >= 0.05
    assert isinstance(val, float)
