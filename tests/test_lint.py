"""rt lint — checker framework tests.

Each checker gets a good/bad fixture twin: the bad fixture violates the
invariant and must produce exactly the expected finding; the good twin is
the minimal fix and must be clean.  Fixtures are injected in-memory via
``run_lint(files=...)`` (``full_tree=True`` arms the whole-tree parity
checks), so the tests never touch the real tree — except the tier-1 gate
at the bottom, which pins the repo itself at ZERO violations and holds
the analyzer to its speed bound.
"""

import time

import pytest

from ray_tpu.analysis import run_lint
from ray_tpu.analysis.protocol_parity import check_manifest, kind_digest

# ----------------------------------------------------------------------
# lock-discipline
# ----------------------------------------------------------------------

_LOCKED_CLASS_BAD = '''
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump(self):
        with self._lock:
            self.value += 1

    def peek(self):
        return self.value
'''

_LOCKED_CLASS_GOOD = _LOCKED_CLASS_BAD.replace(
    "    def peek(self):\n        return self.value",
    "    def peek(self):\n        with self._lock:\n            return self.value",
)


def _lint(src, check, relpath="ray_tpu/mod.py", **kw):
    return run_lint(files=[(relpath, src)], checks={check}, full_tree=True, **kw)


def test_lock_discipline_bad():
    vs = _lint(_LOCKED_CLASS_BAD, "lock-discipline")
    assert len(vs) == 1
    assert "Counter.value" in vs[0].message and "_lock" in vs[0].message
    assert vs[0].check_id == "lock-discipline"


def test_lock_discipline_good():
    assert _lint(_LOCKED_CLASS_GOOD, "lock-discipline") == []


def test_lock_discipline_condition_aliases_lock():
    src = '''
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.items = []

    def put(self, x):
        with self._lock:
            self.items.append(x)

    def pop(self):
        with self._cv:
            return self.items.pop()
'''
    assert _lint(src, "lock-discipline") == []


def test_lock_discipline_locked_suffix_convention():
    # a *_locked helper's caller holds the lock: the suffix IS the contract
    src = _LOCKED_CLASS_BAD.replace("def peek(self):", "def peek_locked(self):")
    assert _lint(src, "lock-discipline") == []


def test_lock_discipline_guarded_by_annotation():
    src = _LOCKED_CLASS_BAD.replace(
        "    def peek(self):",
        "    # rt-lint: guarded-by(_lock)\n    def peek(self):",
    )
    assert _lint(src, "lock-discipline") == []


def test_lock_discipline_disable_annotation():
    src = _LOCKED_CLASS_BAD.replace(
        "        return self.value",
        "        # rt-lint: disable=lock-discipline -- stat snapshot\n"
        "        return self.value",
    )
    assert _lint(src, "lock-discipline") == []


def test_lock_discipline_publication_store():
    # disable on the locked WRITE declares a benign publication: the store
    # makes no guard claim, so the unlocked readers are clean too
    src = '''
import threading

class Svc:
    def __init__(self):
        self._swap_lock = threading.Lock()
        self.backend = object()

    def swap(self, fresh):
        with self._swap_lock:
            # rt-lint: disable=lock-discipline -- atomic rebind
            self.backend = fresh

    def call(self):
        return self.backend
'''
    assert _lint(src, "lock-discipline") == []


# ----------------------------------------------------------------------
# protocol-parity
# ----------------------------------------------------------------------

_RPC_FIXTURE = "PROTOCOL_VERSION = 1\n"


def _proto_files(handler_line):
    sender = (
        "def go(conn):\n"
        "    conn.send(\"ping\", {})\n"
    )
    dispatcher = (
        "class H:\n"
        "    def _h_ping(self, msg):\n"
        "        return {}\n"
        "    def table(self):\n"
        f"        return {handler_line}\n"
    )
    return [
        ("ray_tpu/runtime/rpc.py", _RPC_FIXTURE),
        ("ray_tpu/runtime/sender.py", sender),
        ("ray_tpu/runtime/dispatch.py", dispatcher),
    ]


_MANIFEST_OK = {
    "digest": kind_digest(["ping"]),
    "kinds": ["ping"],
    "protocol_version": 1,
}


def test_protocol_parity_good():
    vs = run_lint(
        files=_proto_files('{"ping": self._h_ping}'),
        checks={"protocol-parity"},
        full_tree=True,
        manifest_override=_MANIFEST_OK,
    )
    assert vs == []


def test_protocol_parity_unhandled_send():
    # registry handles a DIFFERENT kind: "ping" is sent into the void
    vs = run_lint(
        files=_proto_files('{"pong": self._h_ping}'),
        checks={"protocol-parity"},
        full_tree=True,
        manifest_override=_MANIFEST_OK,
    )
    assert len(vs) == 1
    assert "ping" in vs[0].message
    assert vs[0].file == "ray_tpu/runtime/sender.py"


def test_protocol_parity_manifest_detects_new_kind():
    # a new frame kind without a PROTOCOL_VERSION bump fails the manifest
    files = _proto_files('{"ping": self._h_ping, "probe": self._h_ping}')
    files[1] = (
        "ray_tpu/runtime/sender.py",
        "def go(conn):\n"
        "    conn.send(\"ping\", {})\n"
        "    conn.send(\"probe\", {})\n",
    )
    vs = run_lint(
        files=files,
        checks={"protocol-parity"},
        full_tree=True,
        manifest_override=_MANIFEST_OK,
    )
    assert len(vs) == 1
    assert "PROTOCOL_VERSION" in vs[0].message
    assert vs[0].file == "ray_tpu/runtime/rpc.py"  # anchored at the version


def test_check_manifest_pure():
    manifest = {"digest": kind_digest(["a", "b"]), "kinds": ["a", "b"], "protocol_version": 3}
    assert check_manifest(manifest, ["a", "b"], 3) == []
    # kind added, version unchanged -> must fail and name the addition
    errs = check_manifest(manifest, ["a", "b", "c"], 3)
    assert errs and "c" in errs[0] and "PROTOCOL_VERSION" in errs[0]
    # kind added WITH a bump: regenerated manifest is clean
    bumped = {"digest": kind_digest(["a", "b", "c"]), "kinds": ["a", "b", "c"], "protocol_version": 4}
    assert check_manifest(bumped, ["a", "b", "c"], 4) == []
    # version drift without kind change is still an error
    assert check_manifest(manifest, ["a", "b"], 4) != []
    assert check_manifest(None, ["a"], 1) != []


# ----------------------------------------------------------------------
# metric-parity
# ----------------------------------------------------------------------

_METRIC_DEFS_GOOD = '''
REQS = _reg.counter("requests_total")
LAT = _reg.histogram("latency_seconds")
ALL_METRICS = [REQS, LAT]
'''

_METRIC_USER = '''
from ray_tpu.observability.metric_defs import REQS

def handle():
    REQS.inc(tags={"route": "a"})

def handle2():
    REQS.inc(tags={"route": "b"})
'''


def _metric_files(defs=_METRIC_DEFS_GOOD, user=_METRIC_USER):
    return [
        ("ray_tpu/observability/metric_defs.py", defs),
        ("ray_tpu/serve/user.py", user),
    ]


def test_metric_parity_good():
    vs = run_lint(files=_metric_files(), checks={"metric-parity"}, full_tree=True)
    assert vs == []


def test_metric_parity_missing_from_all_metrics():
    defs = _METRIC_DEFS_GOOD.replace("ALL_METRICS = [REQS, LAT]", "ALL_METRICS = [REQS]")
    vs = run_lint(files=_metric_files(defs=defs), checks={"metric-parity"}, full_tree=True)
    assert len(vs) == 1
    assert "LAT" in vs[0].message and "ALL_METRICS" in vs[0].message


def test_metric_parity_unknown_foreign_family():
    user = _METRIC_USER + '\nROGUE = _reg.counter("rogue_total")\n'
    vs = run_lint(files=_metric_files(user=user), checks={"metric-parity"}, full_tree=True)
    assert len(vs) == 1
    assert "rogue_total" in vs[0].message


def test_metric_parity_inconsistent_tags():
    user = _METRIC_USER + '''
def handle3():
    REQS.inc(tags={"rout": "c"})
'''
    vs = run_lint(files=_metric_files(user=user), checks={"metric-parity"}, full_tree=True)
    assert len(vs) == 1
    assert "rout" in vs[0].message


# ----------------------------------------------------------------------
# chaos-determinism
# ----------------------------------------------------------------------

def test_chaos_determinism_bad():
    src = '''
import random

def decide(spec):
    return random.random() < spec.prob
'''
    vs = _lint(src, "chaos-determinism", relpath="ray_tpu/chaos/decider.py")
    assert len(vs) == 1
    assert "random.random" in vs[0].message


def test_chaos_determinism_good():
    src = '''
def decide(spec, stream):
    return stream.next_float() < spec.prob
'''
    assert _lint(src, "chaos-determinism", relpath="ray_tpu/chaos/decider.py") == []


def test_chaos_determinism_unsorted_set_iteration():
    src = '''
def emit(nodes):
    return [n for n in set(nodes)]
'''
    vs = _lint(src, "chaos-determinism", relpath="ray_tpu/chaos/emit.py")
    assert len(vs) == 1
    # sorted() fixes it
    good = src.replace("set(nodes)", "sorted(set(nodes))")
    assert _lint(good, "chaos-determinism", relpath="ray_tpu/chaos/emit.py") == []


def test_chaos_determinism_frame_path_allows_time():
    # frame modules (data_plane) ban randomness but allow wall-clock
    src = '''
import time
import random

def stamp():
    return time.time(), random.random()
'''
    vs = _lint(src, "chaos-determinism", relpath="ray_tpu/runtime/data_plane.py")
    assert len(vs) == 1
    assert "random" in vs[0].message and "time.time" not in vs[0].message


def test_chaos_determinism_disable_annotation():
    src = '''
import os

def token():
    # rt-lint: disable=chaos-determinism -- identity token, not a decision
    return os.urandom(4).hex()
'''
    assert _lint(src, "chaos-determinism", relpath="ray_tpu/chaos/ident.py") == []


# ----------------------------------------------------------------------
# knob-hygiene
# ----------------------------------------------------------------------

_CONFIG_SRC = '''
class Config:
    pull_retries: int = 3
'''

_READER_SRC = '''
def f(cfg):
    return cfg.pull_retries
'''


def test_knob_hygiene_good():
    vs = run_lint(
        files=[("ray_tpu/core/config.py", _CONFIG_SRC), ("ray_tpu/runtime/r.py", _READER_SRC)],
        checks={"knob-hygiene"},
        full_tree=True,
        docs_override={"config.md": "| `pull_retries` | `3` | retry count |"},
    )
    assert vs == []


def test_knob_hygiene_dead_knob():
    vs = run_lint(
        files=[("ray_tpu/core/config.py", _CONFIG_SRC), ("ray_tpu/runtime/r.py", "def f():\n    pass\n")],
        checks={"knob-hygiene"},
        full_tree=True,
        docs_override={"config.md": "| `pull_retries` | `3` | retry count |"},
    )
    assert len(vs) == 1
    assert "pull_retries" in vs[0].message
    assert vs[0].file == "ray_tpu/core/config.py"


def test_knob_hygiene_undocumented_knob():
    vs = run_lint(
        files=[("ray_tpu/core/config.py", _CONFIG_SRC), ("ray_tpu/runtime/r.py", _READER_SRC)],
        checks={"knob-hygiene"},
        full_tree=True,
        docs_override={"config.md": "nothing here"},
    )
    assert len(vs) == 1
    assert "pull_retries" in vs[0].message and "doc" in vs[0].message.lower()


# ----------------------------------------------------------------------
# annotation scoping
# ----------------------------------------------------------------------

def test_standalone_annotation_binds_next_statement_only():
    # the comment covers the first statement after it, not the whole file
    src = '''
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.a = 0
        self.b = 0

    def w(self):
        with self._lock:
            self.a = 1
            self.b = 1

    def r(self):
        # rt-lint: disable=lock-discipline -- covered
        x = self.a
        y = self.b
        return x + y
'''
    vs = _lint(src, "lock-discipline")
    assert len(vs) == 1
    assert "C.b" in vs[0].message


def test_def_line_annotation_covers_whole_block():
    src = _LOCKED_CLASS_BAD.replace(
        "    def peek(self):",
        "    def peek(self):  # rt-lint: disable=lock-discipline -- snapshot",
    )
    assert _lint(src, "lock-discipline") == []


def test_disable_all():
    src = _LOCKED_CLASS_BAD.replace(
        "        return self.value",
        "        return self.value  # rt-lint: disable=all -- fixture",
    )
    assert _lint(src, "lock-discipline") == []


# ----------------------------------------------------------------------
# tier-1 gate: the repo itself lints clean, fast
# ----------------------------------------------------------------------

def test_repo_tree_is_clean_and_fast():
    t0 = time.perf_counter()
    violations = run_lint()
    elapsed = time.perf_counter() - t0
    rendered = "\n".join(v.render() for v in violations)
    assert violations == [], f"rt lint must stay at zero violations:\n{rendered}"
    assert elapsed < 5.0, f"full-tree lint took {elapsed:.2f}s (budget 5s)"


def test_unknown_check_id_raises():
    with pytest.raises(ValueError):
        run_lint(checks={"no-such-check"})
