"""DiT (diffusion transformer) tests: forward shapes, adaLN-Zero identity
init, training convergence, jitted DDIM sampling, sharded dp train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import (
    DiTConfig,
    ddim_sample,
    dit_forward,
    init_dit_params,
    make_dit_train_step,
)

TINY = DiTConfig(
    image_size=8, patch_size=4, channels=1, num_classes=3,
    d_model=32, n_layers=2, n_heads=2, timesteps=50, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_dit_params(TINY, jax.random.key(0))


def test_forward_shape_and_finite(params):
    imgs = jnp.zeros((2, 8, 8, 1), jnp.float32)
    t = jnp.asarray([0, 49], jnp.int32)
    labels = jnp.asarray([0, 2], jnp.int32)
    eps = dit_forward(TINY, params, imgs, t, labels)
    assert eps.shape == (2, 8, 8, 1)
    assert np.isfinite(np.asarray(eps)).all()


def test_zero_init_means_zero_output(params):
    """adaLN-Zero + zero head: a fresh model predicts exactly zero noise."""
    imgs = jnp.asarray(np.random.default_rng(0).standard_normal((2, 8, 8, 1)), jnp.float32)
    eps = dit_forward(TINY, params, imgs, jnp.zeros(2, jnp.int32), jnp.zeros(2, jnp.int32))
    assert float(jnp.abs(eps).max()) == 0.0


def test_training_loss_decreases():
    init_state, step = make_dit_train_step(TINY, learning_rate=2e-3)
    state = init_state(jax.random.key(1))
    rng = np.random.default_rng(2)
    imgs = jnp.asarray(rng.standard_normal((8, 8, 8, 1)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 3, 8), jnp.int32)
    losses = []
    key = jax.random.key(3)
    for i in range(30):
        key, sub = jax.random.split(key)
        state, loss = step(state, imgs, labels, sub)
        losses.append(float(loss))
    # zero-init predicts 0 -> initial loss ~ E[eps^2] ~ 1; training must cut it
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.9


def test_ddim_sampler_jits_and_is_finite(params):
    import functools

    sampler = jax.jit(
        functools.partial(ddim_sample, TINY, num=2, steps=8, guidance_scale=0.0)
    )
    out = sampler(params, jax.random.key(4))
    assert out.shape == (2, 8, 8, 1)
    assert np.isfinite(np.asarray(out)).all()


def test_classifier_free_guidance(params):
    labels = jnp.asarray([1, 2], jnp.int32)
    out = ddim_sample(
        TINY, params, jax.random.key(5), num=2, steps=4,
        labels=labels, guidance_scale=1.5,
    )
    assert out.shape == (2, 8, 8, 1)
    assert np.isfinite(np.asarray(out)).all()


def test_invalid_config_raises():
    with pytest.raises(ValueError):
        DiTConfig(image_size=10, patch_size=4)
    with pytest.raises(ValueError):
        DiTConfig(d_model=30, n_heads=4)


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs virtual devices")
def test_sharded_dp_train_step():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    init_state, step = make_dit_train_step(TINY, mesh=mesh)
    state = init_state(jax.random.key(6))
    rng = np.random.default_rng(7)
    imgs, labels = step.shard_batch(
        jnp.asarray(rng.standard_normal((8, 8, 8, 1)), jnp.float32),
        jnp.asarray(rng.integers(0, 3, 8), jnp.int32),
    )
    state, loss = step(state, imgs, labels, jax.random.key(8))
    assert np.isfinite(float(loss))


def test_null_label_gets_trained():
    """Label dropout routes gradients into the null (CFG) embedding."""
    import optax

    from ray_tpu.models import dit_loss_fn

    params = init_dit_params(TINY, jax.random.key(9))
    # adaLN-Zero + zero head block all conditioning gradients at exact
    # init; perturb them as one training step would
    params["head"] = jnp.ones_like(params["head"]) * 0.01
    params["final_ada"] = jnp.ones_like(params["final_ada"]) * 0.01
    params["layers"]["ada"] = jnp.ones_like(params["layers"]["ada"]) * 0.01
    rng = np.random.default_rng(10)
    imgs = jnp.asarray(rng.standard_normal((16, 8, 8, 1)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 3, 16), jnp.int32)
    grads = jax.grad(
        lambda p: dit_loss_fn(TINY, p, imgs, labels, jax.random.key(11), label_dropout=0.5)
    )(params)
    null_grad = np.abs(np.asarray(grads["label_embed"][TINY.num_classes]))
    assert null_grad.max() > 0, "null label embedding never received a gradient"


def test_model_checkpoint_roundtrip(tmp_path, params):
    from ray_tpu.models.checkpoint import load_model, save_model

    save_model(str(tmp_path / "m"), TINY, params)
    cfg2, params2 = load_model(str(tmp_path / "m"))
    assert cfg2 == TINY
    flat1 = jax.tree.leaves(params)
    flat2 = jax.tree.leaves(params2)
    assert len(flat1) == len(flat2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_into_llm_server(tmp_path):
    """save_model -> load_model as a serving model_factory."""
    from ray_tpu.models import TransformerConfig, init_params
    from ray_tpu.models.checkpoint import load_model, save_model
    from ray_tpu.serve.llm import LLMEngine

    cfg = TransformerConfig(
        vocab_size=41, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        attention="dense", dtype=jnp.float32,
    )
    save_model(str(tmp_path / "lm"), cfg, init_params(cfg, jax.random.key(0)))
    cfg2, params2 = load_model(str(tmp_path / "lm"))
    eng = LLMEngine(cfg2, params2, max_batch_size=1, max_seq_len=16)
    try:
        out = eng.generate([1, 2], max_tokens=3)
        assert len(out) == 3
    finally:
        eng.shutdown()
