"""Tests for ray_tpu.data — mirrors the reference's Data test strategy
(python/ray/data/tests: plan optimization + streaming semantics + transforms)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.aggregate import Count, Max, Mean, Min, Std, Sum, Unique


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_range_count_take():
    ds = rd.range(100)
    assert ds.count() == 100
    rows = ds.take(5)
    assert rows == [{"id": i} for i in range(5)]


def test_range_tensor():
    ds = rd.range_tensor(8, shape=(2, 2))
    rows = ds.take(2)
    assert rows[0]["data"].shape == (2, 2)
    assert (rows[1]["data"] == 1).all()


def test_from_items_map_filter():
    ds = rd.from_items([{"x": i} for i in range(50)])
    out = ds.map(lambda r: {"y": r["x"] * 2}).filter(lambda r: r["y"] % 4 == 0)
    vals = sorted(r["y"] for r in out.take_all())
    assert vals == [i * 2 for i in range(50) if (i * 2) % 4 == 0]


def test_map_batches_numpy():
    ds = rd.range(64)
    out = ds.map_batches(lambda b: {"sq": b["id"] ** 2}, batch_size=16)
    vals = sorted(r["sq"] for r in out.take_all())
    assert vals == sorted(i * i for i in range(64))


def test_flat_map():
    ds = rd.from_items([{"x": 1}, {"x": 2}])
    out = ds.flat_map(lambda r: [{"x": r["x"]}, {"x": -r["x"]}])
    assert sorted(r["x"] for r in out.take_all()) == [-2, -1, 1, 2]


def test_fusion_in_plan():
    from ray_tpu.data import logical as L

    ds = rd.range(10).map(lambda r: r).map(lambda r: r)
    optimized = L.optimize(ds._logical_op)
    assert isinstance(optimized, L.FusedMap)
    assert len(optimized.stages) == 2


def test_limit_streaming():
    ds = rd.range(1000)
    assert len(ds.take(7)) == 7
    assert ds.limit(13).count() == 13


def test_sort():
    rng = np.random.default_rng(0)
    vals = rng.permutation(200)
    ds = rd.from_items([{"v": int(v)} for v in vals])
    out = [r["v"] for r in ds.sort("v").take_all()]
    assert out == sorted(vals.tolist())
    out_desc = [r["v"] for r in ds.sort("v", descending=True).take_all()]
    assert out_desc == sorted(vals.tolist(), reverse=True)


def test_random_shuffle_preserves_multiset():
    ds = rd.range(100)
    out = sorted(r["id"] for r in ds.random_shuffle(seed=42).take_all())
    assert out == list(range(100))


def test_repartition():
    ds = rd.range(100, parallelism=4)
    mat = ds.repartition(10).materialize()
    assert mat.num_blocks() == 10
    assert mat.count() == 100


def test_groupby_aggregate():
    ds = rd.from_items([{"k": i % 3, "v": i} for i in range(30)])
    rows = ds.groupby("k").sum("v").take_all()
    expect = {k: sum(i for i in range(30) if i % 3 == k) for k in range(3)}
    got = {r["k"]: r["sum(v)"] for r in rows}
    assert got == expect


def test_global_aggregates():
    ds = rd.from_items([{"v": float(i)} for i in range(10)])
    assert ds.sum("v") == 45.0
    assert ds.min("v") == 0.0
    assert ds.max("v") == 9.0
    assert ds.mean("v") == pytest.approx(4.5)
    assert ds.std("v") == pytest.approx(np.std(np.arange(10.0), ddof=1))


def test_unique():
    ds = rd.from_items([{"v": i % 4} for i in range(20)])
    assert ds.unique("v") == [0, 1, 2, 3]


def test_union_zip():
    a = rd.from_items([{"x": 1}, {"x": 2}])
    b = rd.from_items([{"x": 3}])
    assert sorted(r["x"] for r in a.union(b).take_all()) == [1, 2, 3]
    c = rd.from_items([{"y": 10}, {"y": 20}])
    z = a.zip(c).take_all()
    assert {(r["x"], r["y"]) for r in z} == {(1, 10), (2, 20)}


def test_add_drop_select_columns():
    ds = rd.range(10).add_column("double", lambda b: b["id"] * 2)
    row = ds.take(1)[0]
    assert row["double"] == 0
    assert ds.select_columns(["double"]).take(1)[0] == {"double": 0}
    assert "id" not in ds.drop_columns(["id"]).take(1)[0]


def test_iter_batches():
    ds = rd.range(100)
    batches = list(ds.iter_batches(batch_size=32))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 100
    assert sizes[:3] == [32, 32, 32]


def test_iter_batches_local_shuffle():
    ds = rd.range(100)
    ids = []
    for b in ds.iter_batches(batch_size=50, local_shuffle_buffer_size=100, local_shuffle_seed=0):
        ids.extend(b["id"].tolist())
    assert sorted(ids) == list(range(100))
    assert ids != list(range(100))


def test_iter_jax_batches():
    import jax

    ds = rd.range(64)
    batch = next(ds.iter_jax_batches(batch_size=32))
    assert isinstance(batch["id"], jax.Array)
    assert batch["id"].shape == (32,)


def test_split_and_streaming_split():
    ds = rd.range(100, parallelism=4)
    shards = ds.split(4)
    assert sum(s.count() for s in shards) == 100
    its = rd.range(100, parallelism=4).streaming_split(2)
    total = 0
    for it in its:
        for b in it.iter_batches(batch_size=None):
            total += len(b["id"])
    assert total == 100


def test_actor_pool_map_batches():
    class AddConst:
        def __init__(self, c=100):
            self.c = c

        def __call__(self, batch):
            return {"v": batch["id"] + self.c}

    ds = rd.range(40).map_batches(AddConst, batch_size=10, concurrency=2, fn_constructor_args=(100,))
    vals = sorted(r["v"] for r in ds.take_all())
    assert vals == [i + 100 for i in range(40)]


def test_csv_json_roundtrip(tmp_path):
    ds = rd.from_items([{"a": i, "b": float(i) / 2} for i in range(20)])
    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    back = rd.read_csv(csv_dir)
    assert back.count() == 20
    assert sorted(r["a"] for r in back.take_all()) == list(range(20))

    json_dir = str(tmp_path / "json")
    ds.write_json(json_dir)
    back = rd.read_json(json_dir)
    assert back.count() == 20


def test_numpy_roundtrip(tmp_path):
    ds = rd.from_numpy(np.arange(12).reshape(12, 1))
    np_dir = str(tmp_path / "np")
    ds.write_numpy(np_dir)
    back = rd.read_numpy(np_dir)
    assert back.count() == 12


def test_map_groups():
    ds = rd.from_items([{"k": i % 2, "v": i} for i in range(10)])

    def top1(group):
        from ray_tpu.data.block import BlockAccessor

        acc = BlockAccessor(group)
        best = max(acc.iter_rows(), key=lambda r: r["v"])
        return [best]

    rows = ds.groupby("k").map_groups(top1).take_all()
    assert {(r["k"], r["v"]) for r in rows} == {(0, 8), (1, 9)}


def test_train_test_split():
    train, test = rd.range(100).train_test_split(0.2)
    assert train.count() == 80
    assert test.count() == 20


def test_stats_after_execution():
    ds = rd.range(50)
    ds.count()
    assert "tasks" in ds.stats()


# ---------------------------------------------------------------------------
# file-format datasources: text, binary, images, webdataset
# ---------------------------------------------------------------------------
def test_read_text(tmp_path):
    from ray_tpu import data as rd

    p = tmp_path / "a.txt"
    p.write_text("alpha\nbeta\n\ngamma\n")
    ds = rd.read_text(str(p))
    assert [r["text"] for r in ds.take_all()] == ["alpha", "beta", "gamma"]


def test_read_binary_files(tmp_path):
    from ray_tpu import data as rd

    (tmp_path / "x.bin").write_bytes(b"\x00\x01\x02")
    (tmp_path / "y.bin").write_bytes(b"abc")
    ds = rd.read_binary_files(str(tmp_path), include_paths=True)
    rows = sorted(ds.take_all(), key=lambda r: r["path"])
    assert rows[0]["bytes"] == b"\x00\x01\x02"
    assert rows[1]["bytes"] == b"abc"


def test_read_images(tmp_path):
    from PIL import Image

    from ray_tpu import data as rd

    for i, color in enumerate([(255, 0, 0), (0, 255, 0)]):
        Image.new("RGB", (8, 6), color).save(tmp_path / f"img{i}.png")
    ds = rd.read_images(str(tmp_path), size=(4, 4), mode="RGB")
    rows = ds.take_all()
    assert len(rows) == 2
    assert all(r["image"].shape == (4, 4, 3) for r in rows)


def test_read_webdataset(tmp_path):
    import io
    import json as _json
    import tarfile

    from ray_tpu import data as rd

    tar_path = tmp_path / "shard-000.tar"
    with tarfile.open(tar_path, "w") as tf:
        for key, label in [("sample_a", 3), ("sample_b", 7)]:
            payloads = {
                f"{key}.txt": f"caption for {key}".encode(),
                f"{key}.cls": str(label).encode(),
                f"{key}.json": _json.dumps({"k": key}).encode(),
            }
            for name, payload in payloads.items():
                info = tarfile.TarInfo(name)
                info.size = len(payload)
                tf.addfile(info, io.BytesIO(payload))
    ds = rd.read_webdataset(str(tar_path))
    rows = sorted(ds.take_all(), key=lambda r: r["__key__"])
    assert rows[0]["__key__"] == "sample_a"
    assert rows[0]["cls"] == 3
    assert rows[1]["txt"] == "caption for sample_b"
    assert rows[1]["json"] == {"k": "sample_b"}


def test_read_webdataset_dotted_dirs_and_multipart_exts(tmp_path):
    import io
    import tarfile

    import numpy as np

    from ray_tpu import data as rd

    tar_path = tmp_path / "shard-dotted.tar"
    arr = np.arange(6, dtype=np.int32)
    buf = io.BytesIO()
    np.save(buf, arr)
    with tarfile.open(tar_path, "w") as tf:
        payloads = {
            "v1.0/a.txt": b"hello",        # dotted directory must not split key
            "v1.0/a.seg.npy": buf.getvalue(),  # multi-part ext decodes by last suffix
            "v1.0/a.cls": b"-1",           # negative labels stay ints
        }
        for name, payload in payloads.items():
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))
    rows = rd.read_webdataset(str(tar_path)).take_all()
    assert len(rows) == 1
    row = rows[0]
    assert row["__key__"] == "v1.0/a"
    assert row["txt"] == "hello"
    assert row["cls"] == -1
    np.testing.assert_array_equal(row["seg.npy"], arr)


def test_write_read_parquet_roundtrip(tmp_path):
    from ray_tpu import data as rd

    ds = rd.from_items([{"a": i, "b": float(i) * 0.5} for i in range(100)])
    out = str(tmp_path / "pq")
    ds.write_parquet(out)
    back = rd.read_parquet(out)
    rows = sorted(back.take_all(), key=lambda r: r["a"])
    assert len(rows) == 100
    assert rows[10]["b"] == 5.0


def test_read_sql_sqlite(tmp_path):
    """DB-API reads (parity: read_api.read_sql over sql_datasource.py)."""
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE users (id INTEGER, name TEXT, score REAL)")
    conn.executemany(
        "INSERT INTO users VALUES (?, ?, ?)",
        [(i, f"u{i}", i * 1.5) for i in range(10)],
    )
    conn.commit()
    conn.close()

    import ray_tpu.data as data

    ds = data.read_sql("SELECT * FROM users ORDER BY id", lambda: sqlite3.connect(db))
    rows = ds.take_all()
    assert len(rows) == 10
    assert rows[3]["name"] == "u3" and rows[3]["score"] == 4.5

    # sharded parallel read
    ds2 = data.read_sql(
        "SELECT * FROM users",
        lambda: sqlite3.connect(db),
        shard_queries=[
            "SELECT * FROM users WHERE id < 5",
            "SELECT * FROM users WHERE id >= 5",
        ],
    )
    assert sorted(r["id"] for r in ds2.take_all()) == list(range(10))


def test_read_text_crlf_newlines(tmp_path):
    p = tmp_path / "crlf.txt"
    p.write_bytes(b"alpha\r\nbeta\rgamma\n")
    rows = [r["text"] for r in rd.read_text(str(p)).take_all()]
    assert rows == ["alpha", "beta", "gamma"]


def test_preserve_order_reorders_skewed_completions():
    """DataContext.preserve_order (parity: ExecutionOptions.preserve_order):
    a slow first block must not be overtaken in the output stream; with the
    flag off, completion order is allowed (and expected here)."""
    import time

    def slow_first(b):
        if int(np.asarray(b["id"])[0]) == 0:
            time.sleep(0.4)
        return b

    ctx = rd.DataContext.get_current()
    ds = rd.range(4, parallelism=4)
    ctx.preserve_order = True
    try:
        rows = [r["id"] for r in ds.map_batches(slow_first, batch_format="numpy").take_all()]
        assert rows == [0, 1, 2, 3]
    finally:
        ctx.preserve_order = False
    # default: completion order is allowed — all rows arrive, any order
    # (asserting the slow block lands last would flake when a contended
    # box serializes the tasks)
    rows = [r["id"] for r in ds.map_batches(slow_first, batch_format="numpy").take_all()]
    assert sorted(rows) == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# hash-partition determinism (shuffle.py used salted hash() before: the SAME
# key could land in DIFFERENT reduce partitions across worker processes)
# ---------------------------------------------------------------------------
def test_partition_by_hash_stable_across_processes():
    """Partition assignment must be identical in processes with different
    hash salts — no PYTHONHASHSEED pinning anywhere."""
    import json
    import subprocess
    import sys

    code = (
        "import json, sys\n"
        "from ray_tpu.data.shuffle import _stable_key_hash\n"
        "keys = ['alpha', 'beta', '\\u03b4elta', b'raw', 2, 2.0, True, 2.5,"
        " -7, None, ('t', 1)]\n"
        "print(json.dumps([_stable_key_hash(k) % 8 for k in keys]))\n"
    )

    def run(seed):
        env = dict(os.environ, PYTHONHASHSEED=str(seed), JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        return json.loads(out.stdout)

    a, b = run(1), run(4242)
    assert a == b
    # numeric keys hash by VALUE, like dict keys: 2 == 2.0 == True
    assert a[4] == a[5] == a[6]
    assert a[7] != a[4] or a[8] != a[4]  # non-integral/other values may differ


def test_groupby_string_keys_one_group_per_key():
    """Multi-process hash groupby over string keys: every key reduces in
    exactly ONE partition (the salted-hash bug split a key's rows across
    partitions, yielding duplicate groups with partial sums)."""
    items = [{"k": f"key-{i % 5}", "v": 1} for i in range(200)]
    ds = rd.from_items(items, parallelism=8)
    rows = ds.groupby("k").sum("v").take_all()
    assert len(rows) == 5, rows  # one group per key, never split
    assert {r["k"]: r["sum(v)"] for r in rows} == {
        f"key-{i}": 40 for i in range(5)
    }
