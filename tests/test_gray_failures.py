"""Gray-failure survival (ISSUE 8): incarnation fencing, end-to-end
deadlines, hedged straggler retries.

Fail-stop faults (PRs 2/6/7) die loudly; gray faults fail SLOW and SPLIT —
a partitioned-but-alive agent outliving its death declaration, a straggler
holding a tail latency hostage, a call with no time bound.  These tests
drive each defense end to end:

* raw-socket stale-incarnation frame injection — location commits, task
  results, heartbeats, push results from a superseded epoch are rejected,
  counted (``fenced_frames_total``), and answered with a typed ``fenced``
  notice,
* a real fenced agent self-fences and rejoins as a FRESH node that serves
  new work,
* ``.options(deadline_s=...)`` fires at all four lifecycle stages (parked /
  queued / pulling / executing) within the grace budget and never retries,
* ``.options(hedge_after_s=...)`` launches the second attempt on a
  different node; first commit wins, the loser is cancelled and its late
  commit discarded by attempt fencing,
* the memory monitor killing a lease-pinned warm worker unpins and
  re-grants (ISSUE 8 satellite),
* ``rpc.request`` timeouts are typed ``ControlPlaneTimeout`` and the shared
  backoff helper retries them deterministically.
"""

import os
import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu import api
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import NodeID, ObjectID, TaskID
from ray_tpu.exceptions import DeadlineExceededError
from ray_tpu.observability import metric_defs
from ray_tpu.runtime import rpc
from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy


# ==========================================================================
# incarnation fencing
# ==========================================================================
def _register_fake_agent(address, node_id_bin, rejoin=False, fenced_box=None):
    """Speak the agent registration protocol over a raw rpc connection."""
    handlers = {}
    if fenced_box is not None:
        handlers["fenced"] = lambda c, p: fenced_box.append(p)
    # unsolicited one-ways the head may send (peer_fenced, shutdown) are
    # dropped by the dispatch loop's no-handler error print; register no-ops
    for msg in ("peer_fenced", "shutdown", "pool_update"):
        handlers.setdefault(msg, lambda c, p, rid=None: None)
    conn = rpc.connect(address, handlers=handlers, name="fake-agent")
    conn.request("register_node_config", {})
    payload = {
        "node_id": node_id_bin,
        "resources": {"CPU": 1},
        "labels": {},
        "address": "fake",
        "data_address": None,
    }
    if rejoin:
        payload["rejoin"] = True
        payload["actors"] = []
    reply = conn.request("register_node", payload)
    return conn, reply


def test_stale_incarnation_frames_fenced(ray_start_regular):
    """Raw-socket frame injection: a superseded incarnation's location
    commits, task results, and heartbeats are all rejected and logged."""
    cluster = api.get_cluster()
    address = cluster.start_head_service()
    node_id = NodeID.from_random()
    fenced_a: list = []

    conn_a, reply_a = _register_fake_agent(
        address, node_id.binary(), fenced_box=fenced_a
    )
    assert reply_a["incarnation"] == 1
    handle_a = cluster.nodes[node_id]

    # the same node id re-registers (partition-heal race: the rejoin beat
    # the death sweep): a NEW incarnation supersedes the old epoch
    conn_b, reply_b = _register_fake_agent(address, node_id.binary(), rejoin=True)
    assert reply_b["incarnation"] == 2
    assert cluster.control.nodes.incarnation_of(node_id) == 2
    assert handle_a.dead, "superseded handle must be fenced"
    assert cluster.nodes[node_id] is not handle_a

    base = {
        kind: metric_defs.FENCED_FRAMES.get(tags={"kind": kind})
        for kind in ("object_location", "task_finished", "resource_report")
    }
    oid = ObjectID.from_random()

    # 1. stale location commit (batched form)
    conn_a.send("object_locations", {"locs": [(oid.binary(), 128, False)], "inc": 1})
    # 2. stale task result
    conn_a.send(
        "task_finished",
        {"task_id": TaskID.from_random().binary(), "value": rpc.encode_value(1),
         "error": None, "inc": 1},
    )
    # 3. stale heartbeat (must not refresh the new epoch's liveness)
    conn_a.send(
        "resource_report",
        {"total": {}, "available": {}, "queue_len": 0, "stats": {}, "inc": 1},
    )

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if (
            metric_defs.FENCED_FRAMES.get(tags={"kind": "object_location"}) > base["object_location"]
            and metric_defs.FENCED_FRAMES.get(tags={"kind": "task_finished"}) > base["task_finished"]
            and metric_defs.FENCED_FRAMES.get(tags={"kind": "resource_report"}) > base["resource_report"]
        ):
            break
        time.sleep(0.02)
    for kind in base:
        assert metric_defs.FENCED_FRAMES.get(tags={"kind": kind}) > base[kind], kind
    # the stale commit never touched the directory
    assert not cluster.directory.locations(oid)
    # the sender was told, with the kind that tripped the fence
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(fenced_a) < 3:
        time.sleep(0.02)
    assert len(fenced_a) >= 3
    assert {p["kind"] for p in fenced_a} >= {
        "object_location", "task_finished", "resource_report"
    }
    # audit log captured every rejection
    kinds = [fe["kind"] for fe in cluster.fence_events]
    assert "object_location" in kinds and "task_finished" in kinds

    conn_a.close()
    conn_b.close()


def test_stale_push_result_fenced(ray_start_regular):
    """A data-plane push_task result stamped with a superseded incarnation
    is discarded by the owner (attempt fencing keeps the resubmitted
    attempt's result the only one visible)."""
    from ray_tpu.runtime.scheduler import TaskSpec
    from ray_tpu.core.resources import ResourceSet

    cluster = api.get_cluster()
    address = cluster.start_head_service()
    node_id = NodeID.from_random()
    conn_a, _ = _register_fake_agent(address, node_id.binary())
    handle = cluster.nodes[node_id]

    task_id = TaskID.from_random()
    spec = TaskSpec(
        task_id=task_id, name="t", func=None, args=(), kwargs={},
        dependencies=[], num_returns=1,
        return_ids=[ObjectID.for_task_return(task_id, 1)],
        resources=ResourceSet({"CPU": 1}),
    )
    handle._track(spec)
    base = metric_defs.FENCED_FRAMES.get(tags={"kind": "push_result"})
    # supersede the incarnation, then deliver a push result from epoch 1
    conn_b, reply_b = _register_fake_agent(address, node_id.binary(), rejoin=True)
    assert reply_b["incarnation"] == 2
    handle._on_push_reply(spec, {"ok": True, "src": (node_id.hex(), 1)}, 42)
    assert metric_defs.FENCED_FRAMES.get(tags={"kind": "push_result"}) == base + 1
    # the stale result did NOT commit: no terminal record, value not stored
    assert not cluster.head_node.store.contains(spec.return_ids[0])
    # the in-flight spec was adopted by the superseding incarnation's
    # handle (rejoin migration): it is NOT resolved by the stale reply
    assert cluster.nodes[node_id]._lookup(task_id.binary()) is spec
    conn_a.close()
    conn_b.close()


def test_fenced_rejoin_refused_after_death_declaration(ray_start_regular):
    """A rejoin attempt for a node id the death sweep already processed is
    answered ``fenced`` — the agent must join as a fresh node instead."""
    cluster = api.get_cluster()
    address = cluster.start_head_service()
    node_id = NodeID.from_random()
    conn_a, _ = _register_fake_agent(address, node_id.binary())
    handle = cluster.nodes[node_id]
    # break the notification channel first: a gray partition's victim never
    # hears its own death declaration
    handle.conn.close()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not handle.dead:
        time.sleep(0.02)
    assert handle.dead

    conn_b, reply = _register_fake_agent(address, node_id.binary(), rejoin=True)
    assert reply.get("fenced") is True
    # a FRESH node id (the self-fence path) is accepted and counted
    rejoins = metric_defs.NODE_REJOINS.get()
    fresh_id = NodeID.from_random()
    conn_c = rpc.connect(address, handlers={}, name="fake-agent")
    conn_c.request("register_node_config", {})
    reply = conn_c.request(
        "register_node",
        {"node_id": fresh_id.binary(), "resources": {"CPU": 1}, "labels": {},
         "address": "fake", "data_address": None, "refenced": True},
    )
    assert reply["incarnation"] == 1
    assert metric_defs.NODE_REJOINS.get() == rejoins + 1
    conn_b.close()
    conn_c.close()


def _spawn_agent(address):
    import subprocess
    import sys

    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    log_dir = "/tmp/rt_agent_logs"
    os.makedirs(log_dir, exist_ok=True)
    log = open(os.path.join(log_dir, f"gray_agent_{os.getpid()}_{time.monotonic_ns()}.log"), "w")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.runtime.agent", "--address", address,
             "--num-cpus", "2", "--resources", '{"remote": 4}'],
            env=env, stdout=subprocess.DEVNULL, stderr=log,
        )
    finally:
        log.close()


def test_fenced_agent_self_fences_and_serves_new_work():
    """End to end with a REAL agent process: partition it past the death
    declaration (the head kills it without the shutdown notice arriving),
    heal — the agent learns it is fenced, self-fences, rejoins as a fresh
    node, and runs new tasks."""
    rt.init(num_cpus=2)
    proc = None
    try:
        cluster = rt.get_cluster()
        address = cluster.start_head_service()
        proc = _spawn_agent(address)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            remote = [
                n for n in cluster.nodes.values()
                if not n.dead and hasattr(n, "conn")
            ]
            if remote:
                break
            time.sleep(0.05)
        else:
            raise TimeoutError("agent never joined")
        handle = remote[0]
        old_id = handle.node_id

        # gray partition: the head declares the node dead, but the shutdown
        # notice cannot reach it (we sever the send side first) — the agent
        # runtime stays alive, exactly like a real partition victim
        def broken_send(*a, **k):
            raise rpc.RpcError("partitioned")

        handle.conn.send = broken_send
        cluster.kill_node(old_id, reason="test gray partition")
        assert handle.dead
        handle.conn.close()  # heal trigger: the agent reconnects...

        # ...is told it is fenced, self-fences, and rejoins as a FRESH node
        deadline = time.monotonic() + 90
        fresh = None
        while time.monotonic() < deadline:
            fresh = next(
                (
                    n for n in cluster.nodes.values()
                    if not n.dead and hasattr(n, "conn") and n.node_id != old_id
                ),
                None,
            )
            if fresh is not None:
                break
            time.sleep(0.05)
        assert fresh is not None, "fenced agent never rejoined as a fresh node"
        assert fresh.incarnation == 1  # fresh node id, first incarnation
        assert cluster.control.nodes.get(old_id).state.value == "DEAD"

        # the rejoined node serves new work
        @rt.remote(resources={"remote": 1})
        def on_remote(x):
            return x * 3

        assert rt.get([on_remote.remote(i) for i in range(6)], timeout=60) == [
            i * 3 for i in range(6)
        ]
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        rt.shutdown()


# ==========================================================================
# end-to-end deadlines
# ==========================================================================
@pytest.fixture
def fast_grace():
    cfg = get_config()
    old = cfg.task_deadline_grace_s
    cfg.task_deadline_grace_s = 0.4
    yield cfg
    cfg.task_deadline_grace_s = old


def test_deadline_parked(ray_start_regular, fast_grace):
    @rt.remote(num_cpus=512)
    def infeasible():
        return 1

    t0 = time.monotonic()
    ref = infeasible.options(deadline_s=0.3).remote()
    with pytest.raises(DeadlineExceededError) as ei:
        rt.get(ref, timeout=10)
    assert ei.value.stage == "parked"
    assert time.monotonic() - t0 < 2.0  # well before infeasible_task_timeout_s
    assert metric_defs.TASK_DEADLINE_EXCEEDED.get(tags={"stage": "parked"}) >= 1


def test_deadline_queued(ray_start_regular, fast_grace):
    sem = threading.Event()

    @rt.remote(num_cpus=4, execution="process")
    def hog():
        time.sleep(3)

    @rt.remote(num_cpus=4)
    def target():
        return 1

    blocker = hog.remote()
    time.sleep(0.15)  # let the hog acquire all CPUs
    t0 = time.monotonic()
    ref = target.options(deadline_s=0.3).remote()
    with pytest.raises(DeadlineExceededError) as ei:
        rt.get(ref, timeout=10)
    elapsed = time.monotonic() - t0
    assert ei.value.stage == "queued"
    # fires at the deadline + at most ~a grace of slack, NOT when the hog
    # finally frees the CPUs at t+3s
    assert elapsed < 0.3 + 2 * get_config().task_deadline_grace_s + 1.0
    assert metric_defs.TASK_DEADLINE_EXCEEDED.get(tags={"stage": "queued"}) >= 1
    sem.set()


def test_deadline_pulling(ray_start_regular, fast_grace):
    @rt.remote(execution="process")
    def producer():
        time.sleep(5)
        return 7

    @rt.remote
    def consumer(x):
        return x

    dep = producer.remote()
    t0 = time.monotonic()
    ref = consumer.options(deadline_s=0.3).remote(dep)
    with pytest.raises(DeadlineExceededError) as ei:
        rt.get(ref, timeout=10)
    elapsed = time.monotonic() - t0
    assert ei.value.stage == "pulling"
    assert elapsed < 2.0  # fired at the deadline, not when the dep landed
    assert metric_defs.TASK_DEADLINE_EXCEEDED.get(tags={"stage": "pulling"}) >= 1


def test_deadline_executing_force_kills_within_grace(ray_start_regular, fast_grace):
    @rt.remote(execution="process", max_retries=5)
    def stuck():
        time.sleep(60)

    t0 = time.monotonic()
    ref = stuck.options(deadline_s=0.3).remote()
    with pytest.raises(DeadlineExceededError) as ei:
        rt.get(ref, timeout=20)
    elapsed = time.monotonic() - t0
    assert ei.value.stage == "executing"
    grace = get_config().task_deadline_grace_s
    # cooperative window + force-kill + commit, with CI slack
    assert elapsed < 0.3 + 2 * grace + 3.0, elapsed
    assert metric_defs.TASK_DEADLINE_EXCEEDED.get(tags={"stage": "executing"}) >= 1


def test_deadline_never_retries(ray_start_regular, fast_grace):
    """max_retries is irrelevant to a deadline failure: one attempt, one
    terminal record, no retry spans burned."""
    cluster = api.get_cluster()
    before = cluster.task_manager.num_retries

    @rt.remote(execution="process", max_retries=5, retry_exceptions=True)
    def stuck():
        time.sleep(60)

    with pytest.raises(DeadlineExceededError):
        rt.get(stuck.options(deadline_s=0.2).remote(), timeout=20)
    time.sleep(0.3)
    assert cluster.task_manager.num_retries == before


def test_deadline_nested_budget_propagates(ray_start_regular, fast_grace):
    """A nested call inherits the parent's REMAINING budget: the deadline
    installed around the parent's execution rides into the child's spec."""

    @rt.remote(execution="process")
    def child():
        from ray_tpu.runtime.context import current_deadline_ts

        # the deadline context worker_main installed for THIS (child) task
        # is the budget inherited from the parent
        return current_deadline_ts()

    @rt.remote(execution="process")
    def parent():
        # no explicit child deadline: inheritance must supply one
        return rt.get(child.remote(), timeout=25)

    t0 = time.time()
    child_deadline = rt.get(parent.options(deadline_s=30.0).remote(), timeout=30)
    assert child_deadline is not None, "child inherited no deadline"
    # the child's installed deadline IS (parent submit + 30s), within slack
    assert abs(child_deadline - (t0 + 30.0)) < 5.0

    # and a short parent budget genuinely bounds a stuck child: the child's
    # inherited deadline fires owner-side even though the child set none
    @rt.remote(execution="process")
    def stuck_child():
        time.sleep(60)

    @rt.remote(execution="process")
    def impatient_parent():
        try:
            rt.get(stuck_child.remote(), timeout=50)
            return "no-deadline"
        except DeadlineExceededError as exc:
            return f"child-deadline:{exc.stage}"

    t0 = time.monotonic()
    try:
        result = rt.get(impatient_parent.options(deadline_s=1.0).remote(), timeout=30)
        assert result.startswith("child-deadline:"), result
    except DeadlineExceededError:
        pass  # the parent's own reap won the race — equally bounded
    assert time.monotonic() - t0 < 10.0


# ==========================================================================
# hedged straggler retries
# ==========================================================================
def _two_node_cluster(cluster):
    node_b = cluster.add_node({"CPU": 1})
    return cluster.head_node, node_b


def test_hedge_beats_slow_node(ray_start_cluster):
    """Primary lands on a delay-armed slow node; the hedge launches on the
    other node, wins, and the loser's late commit is discarded — exactly
    one terminal record per (task_id, attempt)."""
    _rt, _cluster = ray_start_cluster
    cluster = api.get_cluster()
    node_a, node_b = cluster.head_node, cluster.add_node({"CPU": 1})
    node_a._chaos_delay_s = 2.5  # deterministic straggler

    @rt.remote(max_retries=3)
    def quick():
        return 11

    # occupy B so the primary deterministically lands on slow A
    @rt.remote(max_retries=0, scheduling_strategy=NodeAffinitySchedulingStrategy(node_b.node_id))
    def blocker():
        time.sleep(0.4)

    b_ref = blocker.remote()
    time.sleep(0.1)
    wd = cluster.watchdog
    won0, events0 = wd.hedges_won, len(cluster.control.task_events)
    t0 = time.monotonic()
    ref = quick.options(hedge_after_s=0.25).remote()
    assert rt.get(ref, timeout=15) == 11
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, f"hedge never won ({elapsed:.2f}s)"
    assert wd.hedges_won == won0 + 1
    assert metric_defs.TASK_HEDGES.get(tags={"outcome": "won"}) >= 1
    rt.get(b_ref, timeout=10)

    # the loser (still sleeping through the chaos delay) gets cancelled and
    # its commit discarded — zero duplicate terminal records
    deadline = time.monotonic() + 6
    while time.monotonic() < deadline and wd.hedge_discards == 0:
        time.sleep(0.05)
    assert wd.hedge_discards >= 1
    terminal = {}
    for ev in cluster.control.task_events.list_events():
        if ev.get("state") in ("FINISHED", "FAILED"):
            key = (ev["task_id"], ev.get("attempt"))
            terminal[key] = terminal.get(key, 0) + 1
    assert all(n == 1 for n in terminal.values()), terminal
    node_a._chaos_delay_s = 0.0


def test_hedge_lost_when_primary_wins(ray_start_cluster):
    """The hedge lands on a node slower than the primary: the primary
    commits first and the hedge is the (cancelled, discarded) loser."""
    _rt, _cluster = ray_start_cluster
    cluster = api.get_cluster()
    node_b = cluster.add_node({"CPU": 1})
    node_b._chaos_delay_s = 3.0  # the hedge's destination is the straggler
    wd = cluster.watchdog
    lost0 = wd.hedges_lost

    # occupy B briefly so the primary deterministically lands on the head
    @rt.remote(max_retries=0, scheduling_strategy=NodeAffinitySchedulingStrategy(node_b.node_id))
    def blocker():
        time.sleep(0.3)

    b_ref = blocker.remote()
    time.sleep(0.1)

    @rt.remote(execution="process", max_retries=3)
    def modest():
        time.sleep(0.6)
        return 5

    assert rt.get(modest.options(hedge_after_s=0.15).remote(), timeout=15) == 5
    assert wd.hedges_launched >= 1
    deadline = time.monotonic() + 6
    while time.monotonic() < deadline and wd.hedges_lost == lost0:
        time.sleep(0.05)
    assert wd.hedges_lost >= lost0 + 1
    assert metric_defs.TASK_HEDGES.get(tags={"outcome": "lost"}) >= 1
    node_b._chaos_delay_s = 0.0


def test_hedge_requires_alternative_node(ray_start_regular):
    """Single node: the hedge cannot launch (no different node) and the
    primary still completes normally."""
    cluster = api.get_cluster()
    wd = cluster.watchdog
    launched0 = wd.hedges_launched

    @rt.remote(execution="process", max_retries=3)
    def solo():
        time.sleep(0.4)
        return 9

    assert rt.get(solo.options(hedge_after_s=0.1).remote(), timeout=15) == 9
    assert wd.hedges_launched == launched0


def test_hedge_auto_ewma_mode(ray_start_cluster):
    """Opt-in auto mode: once the per-shape latency EWMA settles, a
    straggler past ewma * multiplier hedges without an explicit option."""
    _rt, _cluster = ray_start_cluster
    cluster = api.get_cluster()
    node_b = cluster.add_node({"CPU": 1})
    cfg = get_config()
    old = (cfg.hedge_auto_enabled, cfg.hedge_auto_min_samples, cfg.hedge_auto_min_s)
    cfg.hedge_auto_enabled = True
    cfg.hedge_auto_min_samples = 5
    cfg.hedge_auto_min_s = 0.05
    try:
        cluster.watchdog.auto_on = True

        @rt.remote(max_retries=3)
        def shape():
            return os.getpid()

        # settle the EWMA on the fast shape — SEQUENTIALLY, so queue wait
        # doesn't inflate the observed latency
        for _ in range(8):
            rt.get(shape.remote(), timeout=30)
        wd = cluster.watchdog
        assert wd._ewma, "EWMA never fed"
        launched0 = wd.hedges_launched
        # every node becomes a straggler: wherever the primary lands it
        # outlives ewma * multiplier, so the auto mode MUST hedge it (the
        # hedge is equally slow — this tests the trigger, not the rescue)
        for node in cluster.nodes.values():
            node._chaos_delay_s = 2.0
        t0 = time.monotonic()
        assert isinstance(rt.get(shape.remote(), timeout=20), int)
        elapsed = time.monotonic() - t0
        assert wd.hedges_launched >= launched0 + 1, "auto mode never hedged"
        assert elapsed < 6.0
        # terminal-exactly-once held across the racing attempts
        terminal = {}
        for ev in cluster.control.task_events.list_events():
            if ev.get("state") in ("FINISHED", "FAILED"):
                key = (ev["task_id"], ev.get("attempt"))
                terminal[key] = terminal.get(key, 0) + 1
        assert all(n == 1 for n in terminal.values()), terminal
    finally:
        for node in cluster.nodes.values():
            node._chaos_delay_s = 0.0
        cfg.hedge_auto_enabled, cfg.hedge_auto_min_samples, cfg.hedge_auto_min_s = old
        cluster.watchdog.auto_on = old[0]


# ==========================================================================
# memory-kill / lease interaction (ISSUE 8 satellite)
# ==========================================================================
def test_memory_kill_unpins_leased_worker(ray_start_regular):
    """RetriableFIFOPolicy killing a lease-pinned warm worker must unpin it
    and the retried task must re-grant onto a live worker."""
    from ray_tpu.runtime.memory_monitor import MemoryMonitor

    cluster = api.get_cluster()
    node = cluster.head_node
    release = threading.Event()

    @rt.remote(execution="process", max_retries=2)
    def leased_sleep(marker):
        import time as _t

        _t.sleep(0.8 if marker == 0 else 0.0)
        return os.getpid()

    # prime the lease: repeat fast dispatches until one lands on an idle
    # worker and pins it (the first may race the async prestart)
    pool = node.worker_pool
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not pool._lease_pins:
        rt.get(leased_sleep.remote(1), timeout=30)
        time.sleep(0.05)
    assert pool._lease_pins, "leased dispatch never pinned a warm worker"
    pinned = next(iter(pool._lease_pins.values()))

    # a long leased task occupies the pinned worker; the memory monitor
    # (fed a fake 99% reading) must select and kill it through the normal
    # candidate path — node.kill_candidates -> RetriableFIFOPolicy
    ref = leased_sleep.remote(0)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not node.worker_pool.inflight_tasks():
        time.sleep(0.02)
    monitor = MemoryMonitor(
        node.kill_candidates,
        usage_threshold=0.9,
        memory_fn=lambda: (99, 100),
        min_kill_interval_s=0.0,
    )
    assert monitor.check_once(), "monitor never killed the leased task"
    # the kill unpinned the dead worker
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and pinned in pool._lease_pins.values():
        time.sleep(0.02)
    assert pinned not in pool._lease_pins.values()
    # the OOM-killed task retries and completes on a fresh (re-pinned) worker
    assert isinstance(rt.get(ref, timeout=30), int)
    assert isinstance(rt.get(leased_sleep.remote(2), timeout=30), int)


# ==========================================================================
# typed control-plane timeouts + backoff helper (ISSUE 8 satellite)
# ==========================================================================
def test_rpc_timeout_is_typed():
    server = rpc.RpcServer(
        handler_factory=lambda conn: {"slow": lambda c, p, rid: rpc.DEFER},
        name="slow-server",
    )
    conn = rpc.connect(server.address, handlers={})
    try:
        with pytest.raises(rpc.ControlPlaneTimeout) as ei:
            conn.request("slow", {}, timeout=0.2)
        assert isinstance(ei.value, rpc.RpcError)       # transport family
        assert isinstance(ei.value, TimeoutError)       # and a timeout
        assert ei.value.msg_type == "slow"
    finally:
        conn.close()
        server.close()


def test_retry_with_backoff_retries_timeouts():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise rpc.ControlPlaneTimeout("x", 0.1)
        return "ok"

    assert (
        rpc.retry_with_backoff(flaky, attempts=4, base_backoff_s=0.01)
        == "ok"
    )
    assert len(calls) == 3
    # non-retriable errors pass straight through
    def dead():
        raise rpc.RpcError("connection lost")

    with pytest.raises(rpc.RpcError):
        rpc.retry_with_backoff(dead, attempts=3, base_backoff_s=0.01)

    # an exhausted budget re-raises instead of sleeping past the deadline
    calls.clear()

    def always_slow():
        calls.append(1)
        raise rpc.ControlPlaneTimeout("y", 0.1)

    with pytest.raises(rpc.ControlPlaneTimeout):
        rpc.retry_with_backoff(
            always_slow, attempts=10, base_backoff_s=5.0,
            deadline_ts=time.time() + 0.01,
        )
    assert len(calls) == 1  # no second attempt fits the budget


def test_request_with_budget_uses_remaining_deadline():
    from ray_tpu.runtime.context import pop_deadline, push_deadline

    server = rpc.RpcServer(
        handler_factory=lambda conn: {"slow": lambda c, p, rid: rpc.DEFER},
        name="slow-server",
    )
    conn = rpc.connect(server.address, handlers={})
    token = push_deadline(time.time() + 0.3)
    try:
        t0 = time.monotonic()
        with pytest.raises(rpc.ControlPlaneTimeout):
            rpc.request_with_budget(conn, "slow", {}, default_timeout=30.0)
        assert time.monotonic() - t0 < 5.0  # NOT the 30s flat default
    finally:
        pop_deadline(token)
        conn.close()
        server.close()


# ==========================================================================
# chaos schema: the new kinds validate
# ==========================================================================
def test_chaos_validate_new_kinds():
    from ray_tpu.chaos.schedule import validate_schedule

    good = {
        "seed": 1,
        "events": [
            {"t": 0.0, "kind": "slow_node", "index": 0, "delay": 1.5},
            {"t": 0.5, "kind": "partition_node", "index": 0},
            {"t": 1.0, "kind": "heal_partition"},
        ],
    }
    assert validate_schedule(good, num_nodes=1) == []
    assert validate_schedule(
        {"events": [{"t": 0, "kind": "heal_partition"}]}
    )  # heal without partition
    assert validate_schedule(
        {"events": [{"t": 0, "kind": "slow_node", "delay": -1}]}
    )  # negative delay
    assert validate_schedule(
        {"events": [{"t": 0, "kind": "partition_node", "index": 3}]},
        num_nodes=1,
    )  # index out of range
