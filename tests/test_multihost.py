"""Multi-host fabric: a node agent in a separate OS process joins over TCP.

Validates the round-2 'real multi-host runtime' milestone: two processes form
one cluster — the driver submits, tasks/actors run in the agent process,
results transfer back; kill -9 of the agent exercises the node-failure path
(resubmission, actor death) end to end.

Reference parity anchors: cluster_utils.Cluster.add_node spawning real
raylets (python/ray/cluster_utils.py:135), chaos NodeKillerActor
(python/ray/_private/test_utils.py:1497).
"""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu as rt
from ray_tpu.runtime.scheduler import NodeAffinitySchedulingStrategy

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_agent(address, num_cpus=2, extra_resources='{"remote": 4}'):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    # stderr goes to a per-pid file, not an unread PIPE: when a test fails
    # because an agent silently died, the traceback (or its absence — clean
    # exit vs crash) is the difference between a diagnosis and a shrug
    log_dir = "/tmp/rt_agent_logs"
    os.makedirs(log_dir, exist_ok=True)
    log = open(os.path.join(log_dir, f"agent_{os.getpid()}_{time.monotonic_ns()}.log"), "w")
    try:
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "ray_tpu.runtime.agent",
                "--address", address,
                "--num-cpus", str(num_cpus),
                "--resources", extra_resources,
                "--labels", '{"zone": "agent-zone"}',
            ],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=log,
        )
    finally:
        log.close()  # Popen duped the fd; keeping ours leaks one per agent
    return proc


def _wait_for_nodes(cluster, n, timeout=90.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if sum(1 for node in cluster.nodes.values() if not node.dead) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError(f"cluster never reached {n} live nodes")


@pytest.fixture
def two_process_cluster():
    rt.init(num_cpus=2)
    cluster = rt.get_cluster()
    address = cluster.start_head_service()
    proc = _spawn_agent(address)
    try:
        _wait_for_nodes(cluster, 2)
        yield cluster, proc
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        rt.shutdown()


def _remote_node_id(cluster):
    head_id = cluster.head_node.node_id
    for nid, node in cluster.nodes.items():
        if nid != head_id and not node.dead:
            return nid
    raise AssertionError("no live remote node")


# --------------------------------------------------------------------------
def test_task_runs_in_agent_process(two_process_cluster):
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1})
    def whoami(x):
        return os.getpid(), x * 2

    pid, doubled = rt.get(whoami.remote(21))
    assert doubled == 42
    assert pid != os.getpid()  # ran outside the driver process


def test_dependency_transfer_both_directions(two_process_cluster):
    import numpy as np

    cluster, proc = two_process_cluster
    arr = np.arange(100_000, dtype=np.float32)
    ref = rt.put(arr)  # lives on the head node

    @rt.remote(resources={"remote": 1})
    def remote_sum(a):
        return float(a.sum())

    # head -> agent dependency push
    remote_ref = remote_sum.remote(ref)

    @rt.remote
    def local_add_one(s):
        return s + 1.0

    # agent -> head result transfer feeding a local task
    assert rt.get(local_add_one.remote(remote_ref)) == pytest.approx(float(arr.sum()) + 1.0)


def test_actor_on_remote_node_ordered_calls(two_process_cluster):
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1})
    class Counter:
        def __init__(self):
            self.value = 0
            self.pid = os.getpid()

        def add(self, n):
            self.value += n
            return self.value

        def get_pid(self):
            return self.pid

    c = Counter.remote()
    results = rt.get([c.add.remote(1) for _ in range(20)])
    assert results == list(range(1, 21))  # strict per-actor ordering
    assert rt.get(c.get_pid.remote()) != os.getpid()


def test_streaming_generator_from_agent(two_process_cluster):
    cluster, proc = two_process_cluster

    @rt.remote(num_returns="streaming", resources={"remote": 1}, execution="thread")
    def gen(n):
        for i in range(n):
            yield i * i

    out = [rt.get(ref) for ref in gen.remote(5)]
    assert out == [0, 1, 4, 9, 16]


def test_scheduler_spreads_by_resource(two_process_cluster):
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1})
    def remote_pid():
        return os.getpid()

    @rt.remote
    def local_pid():
        return os.getpid()

    remote_pids = set(rt.get([remote_pid.remote() for _ in range(4)]))
    local_head_pid = os.getpid()
    assert local_head_pid not in remote_pids


def test_node_affinity_targets_agent(two_process_cluster):
    cluster, proc = two_process_cluster
    target = _remote_node_id(cluster)

    @rt.remote
    def where():
        return os.getpid()

    pid = rt.get(
        where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(target)
        ).remote()
    )
    assert pid != os.getpid()


def test_kill9_agent_resubmits_inflight_tasks(two_process_cluster):
    cluster, proc = two_process_cluster
    target = _remote_node_id(cluster)

    @rt.remote(max_retries=2)
    def slow(x):
        time.sleep(1.5)
        return x + 1

    # soft affinity: prefers the agent, survives its death by rescheduling
    refs = [
        slow.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(target, soft=True)
        ).remote(i)
        for i in range(4)
    ]
    time.sleep(0.3)  # let them start on the agent
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)
    assert rt.get(refs, timeout=60) == [1, 2, 3, 4]


def test_kill9_agent_fails_actors_and_recovers_node_table(two_process_cluster):
    from ray_tpu.exceptions import ActorDiedError, RayActorError

    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1})
    class Holder:
        def poke(self):
            return "ok"

    h = Holder.remote()
    assert rt.get(h.poke.remote()) == "ok"
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)
    # Tight timeout on purpose: the death sweep must fail the pending call
    # promptly (the former 90 s value masked a submit/death-sweep TOCTOU
    # race where the call was never failed at all).
    with pytest.raises((ActorDiedError, RayActorError)):
        rt.get(h.poke.remote(), timeout=15)
    # node table marks the agent dead
    _wait_for_nodes(cluster, 1)
    dead = [n for n in cluster.nodes.values() if n.dead]
    assert len(dead) == 1


def test_agent_rejoin_after_restart(two_process_cluster):
    cluster, proc = two_process_cluster
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)
    _wait_for_nodes(cluster, 1)

    proc2 = _spawn_agent(cluster.head_service.address)
    try:
        _wait_for_nodes(cluster, 2)

        @rt.remote(resources={"remote": 1})
        def f():
            return os.getpid()

        assert rt.get(f.remote()) != os.getpid()
    finally:
        proc2.kill()
        proc2.wait(timeout=10)


def test_labels_propagate(two_process_cluster):
    cluster, proc = two_process_cluster
    target = _remote_node_id(cluster)
    assert cluster.nodes[target].labels.get("zone") == "agent-zone"


def test_collective_group_across_processes(two_process_cluster):
    """ray.util.collective parity with ranks in different OS processes
    (round-2 VERDICT item 9): allreduce + send/recv ride the cluster KV
    over the transport."""
    import numpy as np

    cluster, proc = two_process_cluster
    head_id = cluster.head_node.node_id

    @rt.remote(execution="thread")
    class Rank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group_name="xproc")
            self.rank = rank

        def allreduce(self, x):
            from ray_tpu.util import collective

            out = collective.allreduce(
                np.array([x], dtype=np.float32), group_name="xproc", rank=self.rank
            )
            return np.asarray(out).tolist()

        def send_to(self, value, dst):
            from ray_tpu.util import collective

            collective.send(value, dst, group_name="xproc", rank=self.rank)
            return True

        def recv_from(self, src):
            from ray_tpu.util import collective

            return collective.recv(src, group_name="xproc", rank=self.rank, timeout=60)

    r0 = Rank.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(head_id)
    ).remote(0, 2)
    r1 = Rank.options(resources={"remote": 1}).remote(1, 2)

    a = r0.allreduce.remote(1.0)
    b = r1.allreduce.remote(2.0)
    assert rt.get(a, timeout=90) == [3.0]
    assert rt.get(b, timeout=90) == [3.0]

    # point-to-point across the process boundary, both directions
    sent = r0.send_to.remote("ping", 1)
    got = r1.recv_from.remote(0)
    assert rt.get(sent, timeout=90) is True
    assert rt.get(got, timeout=90) == "ping"

    sent = r1.send_to.remote({"x": 42}, 0)
    got = r0.recv_from.remote(1)
    assert rt.get(sent, timeout=90) is True
    assert rt.get(got, timeout=90) == {"x": 42}


def test_worker_prints_forward_to_driver(two_process_cluster, capsys):
    """Task prints on an agent's workers surface on the driver's stderr
    (log_monitor-to-driver parity across hosts)."""
    cluster, proc = two_process_cluster

    @rt.remote(resources={"remote": 1}, execution="process")
    def chatty():
        print("hello-from-agent-worker")
        return 1

    assert rt.get(chatty.remote(), timeout=60) == 1
    deadline = time.monotonic() + 30
    seen = ""
    while time.monotonic() < deadline:
        seen += capsys.readouterr().err
        if "hello-from-agent-worker" in seen:
            break
        time.sleep(0.2)
    assert "hello-from-agent-worker" in seen
    assert "(node=" in seen  # head prefixes the source node


def test_nested_api_from_agent_worker(two_process_cluster):
    """A worker process ON THE AGENT makes nested rt calls; they relay
    agent -> head over the transport to the owning driver."""
    cluster, proc = two_process_cluster

    @rt.remote
    def child(x):
        return x * 3

    @rt.remote(resources={"remote": 1}, execution="process")
    def parent(x):
        import numpy as np

        ref = rt.put(np.arange(10))
        nested = rt.get(child.remote(x))
        return nested + int(rt.get(ref).sum())

    # child may run anywhere; parent runs in an agent worker process
    assert rt.get(parent.remote(2), timeout=120) == 6 + 45
