"""TrainController: gang-compiled training with repair-and-resume.

Covers the ISSUE 17 robustness ladder below the chaos harness:
checkpoint durability (crash-atomic framing, torn-file fallback,
cross-process round trips), bit-exact recovery from member death, claim
after head restart, and the TrainingIterator's typed-error / never-hang
contract when a gang member is killed mid-run.
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.exceptions import RayActorError, WorkerCrashedError
from ray_tpu.runtime.control import ActorState
from ray_tpu.train import (
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    TrainController,
)
from ray_tpu.train.checkpoint import load_framed, save_framed


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# checkpoint durability (satellite: crash-atomic framing)
# ---------------------------------------------------------------------------
def test_framed_roundtrip_cross_process(tmp_path):
    """Save a pytree in a PROCESS worker, restore on the head: every leaf
    — RNG keys included — comes back bit-equal."""

    @ray_tpu.remote(execution="process")
    def save_in_worker(path):
        import jax
        import numpy as _np

        from ray_tpu.train.checkpoint import save_framed as _save

        tree = {
            "params": _np.arange(16, dtype=_np.float32) / 3.0,
            "momentum": _np.full(16, -0.25, dtype=_np.float32),
            "rng_key": _np.asarray(jax.random.PRNGKey(1234)),
            "step": 7,
        }
        _save(path, tree)
        return {
            k: v.tobytes() if hasattr(v, "tobytes") else v
            for k, v in tree.items()
        }

    path = str(tmp_path / "state.ckpt")
    expected = ray_tpu.get(save_in_worker.remote(path), timeout=120)
    restored = load_framed(path)
    assert restored is not None
    assert restored["step"] == expected["step"]
    for key in ("params", "momentum", "rng_key"):
        assert np.asarray(restored[key]).tobytes() == expected[key], key


def test_framed_rejects_torn_file_and_falls_back(tmp_path):
    path = str(tmp_path / "state.ckpt")
    save_framed(path, {"step": 1})
    save_framed(path, {"step": 2})  # rotates step-1 into .prev
    assert load_framed(path)["step"] == 2

    # tear the current file mid-write: digest check must reject it and the
    # loader must fall back to the previous generation
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 3)
    assert load_framed(path)["step"] == 1

    # both generations torn -> None, never a half-parsed object
    with open(path + ".prev", "r+b") as f:
        f.seek(12)
        f.write(b"\xff\xff\xff")
    assert load_framed(path) is None


def test_checkpoint_survives_member_state(tmp_path):
    """Controller save/restore round-trips ALL resume state bit-exact."""
    ctl = TrainController(
        "ckpt_rt", world_size=2, batch_size=8, feature_dim=4, seed=5,
        checkpoint_dir=str(tmp_path), checkpoint_period=10**9,
    )
    try:
        ctl.run(3)
        ctl.save_checkpoint()
        state = load_framed(ctl.checkpoint_path)
        assert state["step"] == 3
        assert state["params"].tobytes() == ctl._state()["params"].tobytes()
        assert state["rng_key"].tobytes() == ctl._state()["rng_key"].tobytes()
    finally:
        ctl.shutdown()


# ---------------------------------------------------------------------------
# repair-and-resume: bit-exact vs an uninterrupted run
# ---------------------------------------------------------------------------
def test_recover_resumes_bit_exact():
    """Kill a gang member mid-run; the repaired gang's full loss history
    must be byte-identical to an uninterrupted same-seed run's."""
    ctl = TrainController(
        "bitexact_a", world_size=2, batch_size=8, feature_dim=4, seed=21,
        checkpoint_period=4,
    )
    ref = TrainController(
        "bitexact_b", world_size=2, batch_size=8, feature_dim=4, seed=21,
        checkpoint_period=10**9,
    )
    try:
        ctl.run(6)  # checkpoint lands at step 4
        ray_tpu.kill(ctl._members[1], no_restart=False)
        ctl.run(4, auto_repair=True)  # death surfaces, recover(), resume
        assert ctl.step_count == 10
        assert ctl.repair_history, "member death never triggered a repair"

        uninterrupted = ref.run(10)
        got = np.asarray(ctl.losses(), np.float32).tobytes()
        want = np.asarray(uninterrupted, np.float32).tobytes()
        assert got == want, "post-repair loss trajectory diverged"
    finally:
        ctl.shutdown()
        ref.shutdown()


def test_recover_without_auto_repair_raises_typed():
    ctl = TrainController(
        "typed_err", world_size=2, batch_size=8, feature_dim=4, seed=3,
    )
    try:
        ctl.run(2)
        ray_tpu.kill(ctl._members[0], no_restart=True)
        with pytest.raises((RayActorError, WorkerCrashedError)):
            ctl.run(3, auto_repair=False)
    finally:
        ctl.shutdown()


def test_claim_after_head_restart():
    """Step state rides head snapshots: save, kill_head/restart_head, then
    claim() rebuilds the controller from the KV summary + checkpoint."""
    cluster = ray_tpu.get_cluster()
    ctl = TrainController(
        "claimed", world_size=2, batch_size=8, feature_dim=4, seed=9,
        checkpoint_period=10**9,
    )
    ckpt_dir = os.path.dirname(ctl.checkpoint_path)
    try:
        ctl.run(5)
        ctl.save_checkpoint()
        saved = ctl._state()
    finally:
        ctl.shutdown()

    cluster.kill_head()
    cluster.restart_head()

    ctl2 = TrainController.claim("claimed")
    try:
        assert os.path.dirname(ctl2.checkpoint_path) == ckpt_dir
        assert ctl2.step_count == 5
        restored = ctl2._state()
        assert restored["params"].tobytes() == saved["params"].tobytes()
        assert restored["rng_key"].tobytes() == saved["rng_key"].tobytes()
        # and it trains on from the claimed state
        ctl2.run(1)
        assert ctl2.step_count == 6
    finally:
        ctl2.shutdown()


# ---------------------------------------------------------------------------
# TrainingIterator: typed errors, never a hang (satellite 2)
# ---------------------------------------------------------------------------
def _kill_one_train_worker(cluster, done: threading.Event, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline and not done.is_set():
        for info in cluster.control.actors.list_actors():
            if (
                info.class_name.endswith("TrainWorkerActor")
                and info.state is ActorState.ALIVE
            ):
                cluster.kill_actor(info.actor_id, no_restart=True)
                done.set()
                return
        time.sleep(0.02)


def test_training_iterator_member_kill_raises_typed_never_hangs():
    def loop(config):
        for i in range(200):  # ~10s — far beyond the kill
            train.report({"i": i})
            time.sleep(0.05)

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=0)),
    )
    it = trainer.training_iterator()
    cluster = ray_tpu.get_cluster()
    killed = threading.Event()
    killer = threading.Thread(
        target=_kill_one_train_worker, args=(cluster, killed), daemon=True
    )
    killer.start()
    t0 = time.monotonic()
    with pytest.raises((RayActorError, WorkerCrashedError)):
        for _ in it:
            pass
    killer.join(timeout=30)
    assert killed.is_set()
    assert time.monotonic() - t0 < 30, "iterator hung instead of raising"
    assert it.result().error is not None


def test_training_iterator_auto_repair_restarts_gang():
    def loop(config):
        for i in range(20):
            train.report({"i": i})
            time.sleep(0.02)

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=2)),
    )
    it = trainer.training_iterator(auto_repair=True)
    cluster = ray_tpu.get_cluster()
    killed = threading.Event()
    killer = threading.Thread(
        target=_kill_one_train_worker, args=(cluster, killed), daemon=True
    )
    killer.start()
    rows = list(it)
    killer.join(timeout=30)
    result = it.result()
    assert result.error is None, f"auto_repair leaked the error: {result.error}"
    assert rows, "repaired run produced no reports"
    # the restarted attempt announces itself through the session context
    assert killed.is_set()


def test_gang_mode_jaxtrainer_fit():
    """JaxTrainer(gang=...) compiles the step into a StageGroup plan and
    returns a Result backed by the controller's checkpoint."""
    trainer = JaxTrainer(
        gang=dict(world_size=2, batch_size=8, feature_dim=4, seed=2),
        num_steps=4,
        run_config=RunConfig(name="gangfit"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 4
    assert result.metrics["world_size"] == 2
    assert len(result.metrics_dataframe) == 4
    assert result.checkpoint is not None
    ctl = trainer.controller
    try:
        assert ctl.last_checkpoint and os.path.exists(ctl.last_checkpoint)
        assert ctl.status()["plan_state"] == "READY"
    finally:
        ctl.shutdown()
