"""Compiled-DAG tests: interpreted execution, XLA fusion, direct schedule
with actors, auto fallback, channels."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.dag import Channel, ChannelClosed, DeviceChannel, InputNode, MultiOutputNode


def test_interpreted_dag(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def plus(x, y):
        return x + y

    @rt.remote
    def times(x, k):
        return x * k

    with InputNode() as inp:
        d = times.bind(plus.bind(inp, 10), 2)
    ref = d.execute(5)
    assert rt.get(ref) == 30


def test_interpreted_multi_output(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def double(x):
        return 2 * x

    @rt.remote
    def square(x):
        return x * x

    with InputNode() as inp:
        d = MultiOutputNode([double.bind(inp), square.bind(inp)])
    refs = d.execute(3)
    assert rt.get(refs) == [6, 9]


def test_compiled_jit_fusion(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def matmul(x, w):
        return x @ w

    @rt.remote
    def act(x):
        return jax.nn.relu(x)

    with InputNode() as inp:
        d = act.bind(matmul.bind(inp.x, inp.w))
    compiled = d.experimental_compile()
    assert compiled.mode == "jit"
    x = jnp.ones((4, 8))
    w = jnp.full((8, 2), -1.0)
    out = compiled.execute(x=x, w=w)
    np.testing.assert_array_equal(np.asarray(out), np.zeros((4, 2)))
    # repeat executions hit the jit cache
    out2 = compiled.execute(x=x, w=w)
    assert out2.shape == (4, 2)


def test_compiled_auto_fallback(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def shout(s):
        return s.upper()  # not jax-traceable

    with InputNode() as inp:
        d = shout.bind(inp)
    compiled = d.experimental_compile()
    assert compiled.execute("hi") == "HI"
    assert compiled.mode == "direct"


def test_compiled_actor_direct(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, x):
            self.n += x
            return self.n

    counter = Counter.options(execution="inproc").remote()
    rt.get(counter.add.remote(0))  # wait alive

    with InputNode() as inp:
        d = counter.add.bind(inp)
    compiled = d.experimental_compile(fuse="none")
    assert compiled.mode == "direct"
    assert compiled.execute(5) == 5
    assert compiled.execute(7) == 12
    # repeated executes are much faster than the task path: just check they run
    t0 = time.perf_counter()
    for _ in range(100):
        compiled.execute(1)
    assert time.perf_counter() - t0 < 1.0
    assert compiled.execute(0) == 112
    compiled.teardown()


def test_compiled_actor_serializes_with_remote_calls(ray_start_regular):
    """Direct DAG calls must not race queued .remote() calls (both ride the
    actor's call queue)."""
    rt = ray_start_regular

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, x):
            n = self.n
            time.sleep(0)  # widen the read-modify-write window
            self.n = n + x
            return self.n

        def total(self):
            return self.n

    counter = Counter.options(execution="inproc").remote()
    rt.get(counter.total.remote())

    with InputNode() as inp:
        d = counter.add.bind(inp)
    compiled = d.experimental_compile(fuse="none")

    import threading

    refs = []

    def via_remote():
        for _ in range(200):
            refs.append(counter.add.remote(1))

    t = threading.Thread(target=via_remote)
    t.start()
    for _ in range(200):
        compiled.execute(1)
    t.join()
    rt.get(refs)
    assert rt.get(counter.total.remote()) == 400
    compiled.teardown()


def test_compiled_fuse_jit_rejects_actors(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class A:
        def f(self, x):
            return x

    a = A.options(execution="inproc").remote()
    rt.get(a.f.remote(0))
    with InputNode() as inp:
        d = a.f.bind(inp)
    with pytest.raises(ValueError, match="jit"):
        d.experimental_compile(fuse="jit")


def test_execute_async(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        d = inc.bind(inp)
    compiled = d.experimental_compile(fuse="none")
    futs = [compiled.execute_async(i) for i in range(10)]
    assert [f.result() for f in futs] == list(range(1, 11))
    compiled.teardown()


def test_channel_roundtrip():
    ch = Channel()
    import threading

    out = []
    t = threading.Thread(target=lambda: out.append(ch.read()))
    t.start()
    ch.write(42)
    t.join(2)
    assert out == [42]
    ch.close()
    with pytest.raises(ChannelClosed):
        ch.read()


def test_device_channel():
    ch = DeviceChannel(jax.devices()[0])
    ch.write(jnp.arange(4))
    got = ch.read()
    assert list(np.asarray(got)) == [0, 1, 2, 3]
