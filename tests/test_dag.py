"""Compiled-DAG tests: interpreted execution, XLA fusion, direct schedule
with actors, auto fallback, channels."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.dag import Channel, ChannelClosed, DeviceChannel, InputNode, MultiOutputNode


def test_interpreted_dag(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def plus(x, y):
        return x + y

    @rt.remote
    def times(x, k):
        return x * k

    with InputNode() as inp:
        d = times.bind(plus.bind(inp, 10), 2)
    ref = d.execute(5)
    assert rt.get(ref) == 30


def test_interpreted_multi_output(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def double(x):
        return 2 * x

    @rt.remote
    def square(x):
        return x * x

    with InputNode() as inp:
        d = MultiOutputNode([double.bind(inp), square.bind(inp)])
    refs = d.execute(3)
    assert rt.get(refs) == [6, 9]


def test_compiled_jit_fusion(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def matmul(x, w):
        return x @ w

    @rt.remote
    def act(x):
        return jax.nn.relu(x)

    with InputNode() as inp:
        d = act.bind(matmul.bind(inp.x, inp.w))
    compiled = d.experimental_compile()
    assert compiled.mode == "jit"
    x = jnp.ones((4, 8))
    w = jnp.full((8, 2), -1.0)
    out = compiled.execute(x=x, w=w)
    np.testing.assert_array_equal(np.asarray(out), np.zeros((4, 2)))
    # repeat executions hit the jit cache
    out2 = compiled.execute(x=x, w=w)
    assert out2.shape == (4, 2)


def test_compiled_auto_fallback(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def shout(s):
        return s.upper()  # not jax-traceable

    with InputNode() as inp:
        d = shout.bind(inp)
    compiled = d.experimental_compile()
    assert compiled.execute("hi") == "HI"
    assert compiled.mode == "direct"


def test_compiled_actor_direct(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, x):
            self.n += x
            return self.n

    counter = Counter.options(execution="inproc").remote()
    rt.get(counter.add.remote(0))  # wait alive

    with InputNode() as inp:
        d = counter.add.bind(inp)
    compiled = d.experimental_compile(fuse="none")
    assert compiled.mode == "direct"
    assert compiled.execute(5) == 5
    assert compiled.execute(7) == 12
    # repeated executes are much faster than the task path: just check they run
    t0 = time.perf_counter()
    for _ in range(100):
        compiled.execute(1)
    assert time.perf_counter() - t0 < 1.0
    assert compiled.execute(0) == 112
    compiled.teardown()


def test_compiled_actor_serializes_with_remote_calls(ray_start_regular):
    """Direct DAG calls must not race queued .remote() calls (both ride the
    actor's call queue)."""
    rt = ray_start_regular

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, x):
            n = self.n
            time.sleep(0)  # widen the read-modify-write window
            self.n = n + x
            return self.n

        def total(self):
            return self.n

    counter = Counter.options(execution="inproc").remote()
    rt.get(counter.total.remote())

    with InputNode() as inp:
        d = counter.add.bind(inp)
    compiled = d.experimental_compile(fuse="none")

    import threading

    refs = []

    def via_remote():
        for _ in range(200):
            refs.append(counter.add.remote(1))

    t = threading.Thread(target=via_remote)
    t.start()
    for _ in range(200):
        compiled.execute(1)
    t.join()
    rt.get(refs)
    assert rt.get(counter.total.remote()) == 400
    compiled.teardown()


def test_compiled_fuse_jit_rejects_actors(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class A:
        def f(self, x):
            return x

    a = A.options(execution="inproc").remote()
    rt.get(a.f.remote(0))
    with InputNode() as inp:
        d = a.f.bind(inp)
    with pytest.raises(ValueError, match="jit"):
        d.experimental_compile(fuse="jit")


def test_execute_async(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        d = inc.bind(inp)
    compiled = d.experimental_compile(fuse="none")
    futs = [compiled.execute_async(i) for i in range(10)]
    assert [f.result() for f in futs] == list(range(1, 11))
    compiled.teardown()


def test_compiled_jit_fallback_only_on_first_trace(ray_start_regular):
    """fuse='auto' may fall back to the direct schedule only on the FIRST
    trace; once a jit trace has succeeded, later errors are user errors and
    re-raise instead of silently degrading the compiled program."""
    rt = ray_start_regular

    @rt.remote
    def double(x):
        return x * 2

    with InputNode() as inp:
        d = double.bind(inp)
    compiled = d.experimental_compile()
    assert compiled.mode == "jit"
    out = compiled.execute(jnp.arange(3))
    assert list(np.asarray(out)) == [0, 2, 4]

    class Poison:
        def __mul__(self, other):
            raise RuntimeError("poisoned operand")

        __rmul__ = __mul__

    with pytest.raises(Exception):
        compiled.execute(Poison())
    # still jit — the error did NOT demote the program to direct mode
    assert compiled.mode == "jit"
    assert list(np.asarray(compiled.execute(jnp.arange(3)))) == [0, 2, 4]


def test_compiled_teardown_idempotent_and_execute_after(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def inc(x):
        return x + 1

    @rt.remote
    class A:
        def m(self, x):
            return x

    # jit mode
    with InputNode() as inp:
        d = inc.bind(inp)
    compiled = d.experimental_compile()
    assert compiled.mode == "jit"
    compiled.teardown()
    compiled.teardown()  # idempotent
    with pytest.raises(RuntimeError, match="torn down"):
        compiled.execute(1)

    # direct mode
    a = A.options(execution="inproc").remote()
    rt.get(a.m.remote(0))
    with InputNode() as inp:
        d = a.m.bind(inp)
    direct = d.experimental_compile(fuse="none")
    assert direct.mode == "direct"
    assert direct.execute(7) == 7
    direct.teardown()
    direct.teardown()
    with pytest.raises(RuntimeError, match="torn down"):
        direct.execute(1)


def test_compiled_actor_kill_surfaces_immediately(ray_start_regular):
    """Satellite fix: a direct DAG call queued on a killed actor raises
    ActorDiedError the instant the death sweep runs — via the actor's death
    notification, not an up-to-1s poll tick."""
    from ray_tpu.exceptions import ActorDiedError

    rt = ray_start_regular

    @rt.remote
    class Slow:
        def snooze(self, s):
            time.sleep(s)
            return s

        def quick(self, x):
            return x

    a = Slow.options(execution="inproc").remote()
    rt.get(a.quick.remote(0))
    with InputNode() as inp:
        d = a.quick.bind(inp)
    compiled = d.experimental_compile(fuse="none")

    # occupy the actor thread so the direct call stays QUEUED
    a.snooze.remote(5.0)
    time.sleep(0.1)
    out = {}

    def run():
        t0 = time.perf_counter()
        try:
            compiled.execute(1)
        except ActorDiedError:
            out["latency"] = time.perf_counter() - t0

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.2)
    t_kill = time.perf_counter()
    rt.kill(a)
    t.join(3)
    assert "latency" in out, "queued direct call never surfaced the death"
    # immediate, not the old poll tick (which fired up to 1s after submit)
    assert time.perf_counter() - t_kill < 0.5
    compiled.teardown()


def test_channel_close_while_blocked_stress():
    """N readers and N writers all blocked on single-slot channels; close()
    must wake every one of them promptly with ChannelClosed."""
    channels = [Channel() for _ in range(8)]
    for ch in channels[4:]:
        ch.write("occupied")  # writers on these will block on the full slot
    results = []
    lock = threading.Lock()

    def blocked_reader(ch):
        try:
            ch.read(timeout=10)
            outcome = "value"
        except ChannelClosed:
            outcome = "closed"
        with lock:
            results.append(outcome)

    def blocked_writer(ch):
        try:
            ch.write("late", timeout=10)
            outcome = "wrote"
        except ChannelClosed:
            outcome = "closed"
        with lock:
            results.append(outcome)

    threads = [threading.Thread(target=blocked_reader, args=(ch,), daemon=True)
               for ch in channels[:4]]
    threads += [threading.Thread(target=blocked_writer, args=(ch,), daemon=True)
                for ch in channels[4:]]
    for t in threads:
        t.start()
    time.sleep(0.1)
    t0 = time.perf_counter()
    for ch in channels:
        ch.close()
    for t in threads:
        t.join(5)
    assert time.perf_counter() - t0 < 2.0
    assert results.count("closed") == 8, results


def test_device_channel_places_after_slot_acquired():
    """Satellite fix: under backpressure the blocked writer must NOT hold a
    device-placed second copy — jax.device_put runs only once the slot is
    free (observable: placement count trails the write call)."""
    placed = []

    class CountingChannel(DeviceChannel):
        def _place(self, value):
            placed.append(True)
            return super()._place(value)

    ch = CountingChannel(jax.devices()[0])
    ch.write(jnp.arange(4))
    assert len(placed) == 1

    done = threading.Event()

    def second_write():
        ch.write(jnp.arange(4))
        done.set()

    t = threading.Thread(target=second_write, daemon=True)
    t.start()
    time.sleep(0.1)
    # writer is blocked on the full slot: placement must NOT have happened
    assert len(placed) == 1 and not done.is_set()
    ch.read()
    t.join(2)
    assert done.is_set() and len(placed) == 2
    assert list(np.asarray(ch.read())) == [0, 1, 2, 3]


def test_channel_roundtrip():
    ch = Channel()
    import threading

    out = []
    t = threading.Thread(target=lambda: out.append(ch.read()))
    t.start()
    ch.write(42)
    t.join(2)
    assert out == [42]
    ch.close()
    with pytest.raises(ChannelClosed):
        ch.read()


def test_device_channel():
    ch = DeviceChannel(jax.devices()[0])
    ch.write(jnp.arange(4))
    got = ch.read()
    assert list(np.asarray(got)) == [0, 1, 2, 3]
