"""Elastic gang resize: re-trace discipline, deterministic batch
re-sharding, drain-backed scale-down, capacity-driven elastic ticks.

ISSUE 17 satellite: scale 2 -> 4 -> 2 must re-trace the jit'd step exactly
once per NEW mesh size (`step_fn._cache_size()` flat otherwise), and the
global batch order must be a pure function of (seed, step) — world size is
deliberately NOT an input, so resharding after a resize is a pure split of
the same rows.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train.controller import TrainController, global_batch


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_resize_retraces_once_per_mesh_size():
    ctl = TrainController(
        "retrace", world_size=2, batch_size=16, feature_dim=4, seed=1,
        checkpoint_period=10**9,
    )
    try:
        ctl.run(2)
        traces_at_2 = ctl.step_fn._cache_size()
        ctl.run(2)
        assert ctl.step_fn._cache_size() == traces_at_2, \
            "steps at a fixed mesh size re-traced"

        ctl.resize(4, reason="scale_up")
        ctl.run(2)
        traces_at_4 = ctl.step_fn._cache_size()
        assert traces_at_4 == traces_at_2 + 1, \
            "scale-up must re-trace exactly once for the new member shape"
        ctl.run(2)
        assert ctl.step_fn._cache_size() == traces_at_4

        # returning to a previously-seen mesh size hits the cached trace
        ctl.resize(2, reason="scale_down")
        ctl.run(2)
        assert ctl.step_fn._cache_size() == traces_at_4, \
            "revisiting a mesh size must not re-trace"
    finally:
        ctl.shutdown()


def test_resize_preserves_step_state_exactly():
    """Scale 2 -> 4 -> 2 loses zero step state: params/rng/step carry
    across each rebuild byte-for-byte."""
    ctl = TrainController(
        "carry", world_size=2, batch_size=16, feature_dim=4, seed=13,
        checkpoint_period=10**9,
    )
    try:
        ctl.run(3)
        before = ctl._state()
        ctl.resize(4, reason="scale_up")
        mid = ctl._state()
        assert mid["params"].tobytes() == before["params"].tobytes()
        assert mid["rng_key"].tobytes() == before["rng_key"].tobytes()
        assert mid["step"] == before["step"]
        ctl.resize(2, reason="scale_down")
        after = ctl._state()
        assert after["params"].tobytes() == before["params"].tobytes()
        assert after["step"] == 3
        ctl.run(1)  # and it still trains
        assert ctl.step_count == 4
        reasons = [r["reason"] for r in ctl.resize_history]
        assert reasons == ["scale_up", "scale_down"]
    finally:
        ctl.shutdown()


def test_global_batch_pure_function_of_seed_and_step():
    a = global_batch(7, 3, batch_size=16, feature_dim=4)
    b = global_batch(7, 3, batch_size=16, feature_dim=4)
    assert a.tobytes() == b.tobytes()
    assert a.tobytes() != global_batch(7, 4, batch_size=16, feature_dim=4).tobytes()
    assert a.tobytes() != global_batch(8, 3, batch_size=16, feature_dim=4).tobytes()

    # re-sharding after a resize is a pure split of the SAME rows: the
    # concatenation of per-member shards reproduces the global batch for
    # every world size
    for world in (1, 2, 4):
        shards = np.split(a, world, axis=0)
        assert np.concatenate(shards, axis=0).tobytes() == a.tobytes()


def test_scale_down_drains_departing_member_node():
    cluster = ray_tpu.get_cluster()
    from ray_tpu.observability.metric_defs import NODE_DRAINS

    n0 = cluster.add_node({"CPU": 1, "gang0": 1})
    n1 = cluster.add_node({"CPU": 1, "gang1": 1})
    drains_before = len(getattr(cluster, "drain_reports", ()))
    ok_before = NODE_DRAINS.get({"outcome": "ok"})
    ctl = TrainController(
        "drainy", world_size=2, batch_size=8, feature_dim=4, seed=4,
        checkpoint_period=10**9,
        member_resources=[{"gang0": 1}, {"gang1": 1}],
    )
    try:
        ctl.run(2)
        state_before = ctl._state()
        ctl.resize(1, reason="scale_down")
        assert ctl.world_size == 1
        # the departing member's dedicated node went through the graceful
        # drain path, not a kill
        reports = list(getattr(cluster, "drain_reports", ()))[drains_before:]
        assert reports, "scale-down bypassed the drain path"
        assert reports[-1]["outcome"] == "ok"
        assert NODE_DRAINS.get({"outcome": "ok"}) == ok_before + 1
        # zero lost step state
        after = ctl._state()
        assert after["params"].tobytes() == state_before["params"].tobytes()
        assert after["step"] == 2
        ctl.run(1)
        assert ctl.step_count == 3
    finally:
        ctl.shutdown()


def test_elastic_tick_grows_into_capacity():
    """elastic_tick reconciles the gang against live CPU capacity — the
    autoscaler calls this after every capacity change."""
    ctl = TrainController(
        "elastic", world_size=2, batch_size=8, feature_dim=4, seed=6,
        checkpoint_period=10**9,
    )
    try:
        size = ctl.elastic_tick()
        assert size >= 2, "elastic tick shrank below the starting size"
        if size > 2:
            assert ctl.resize_history[-1]["reason"] == "scale_up"
            assert ctl.world_size == size
        ctl.run(1)  # gang still steps after the reconcile
    finally:
        ctl.shutdown()
