"""GBDT trainers (XGBoost / LightGBM) — parity with the reference's
``train/xgboost`` + ``train/lightgbm`` packages, driven against minimal
framework lookalikes (same gating style as the fake-optuna Tune tests):
per-round reports, end-of-train checkpoints, resume, the rabit-tracker
rendezvous, and LightGBM's ``machines`` negotiation."""

import sys
import types

import numpy as np
import pandas as pd
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.train import RunConfig, ScalingConfig


@pytest.fixture(scope="module", autouse=True)
def _ray():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def _frame(n=32, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    y = (X @ np.array([1.0, -2.0, 0.5]) > 0).astype(np.float64)
    return pd.DataFrame({"a": X[:, 0], "b": X[:, 1], "c": X[:, 2], "label": y})


# ---------------------------------------------------------------------------
# fake xgboost
# ---------------------------------------------------------------------------
def _fake_xgboost(monkeypatch, calls, with_collective=False):
    mod = types.ModuleType("xgboost")

    class DMatrix:
        def __init__(self, X, label=None, **kw):
            self.X, self.label, self.kw = X, label, kw

    class Booster:
        def __init__(self):
            self.rounds = 0

        def num_boosted_rounds(self):
            return self.rounds

        def save_model(self, path):
            with open(path, "w") as f:
                f.write(str(self.rounds))

        def load_model(self, path):
            with open(path) as f:
                self.rounds = int(f.read())

    class TrainingCallback:
        pass

    def train(params, dtrain, evals=(), evals_result=None, num_boost_round=10,
              xgb_model=None, callbacks=(), **kw):
        model = Booster()
        if xgb_model is not None:
            model.rounds = xgb_model.rounds
        calls.append({
            "params": dict(params),
            "nrows": len(dtrain.X),
            "rounds": num_boost_round,
            "eval_names": [name for _, name in evals],
            "resumed_at": model.rounds,
        })
        evals_log = {name: {"rmse": []} for _, name in evals}
        for epoch in range(num_boost_round):
            model.rounds += 1
            for name in evals_log:
                evals_log[name]["rmse"].append(1.0 / model.rounds)
            for cb in callbacks:
                if hasattr(cb, "after_iteration"):
                    cb.after_iteration(model, epoch, evals_log)
        for cb in callbacks:
            if hasattr(cb, "after_training"):
                cb.after_training(model)
        if evals_result is not None:
            evals_result.update(evals_log)
        return model

    mod.DMatrix = DMatrix
    mod.Booster = Booster
    mod.train = train
    mod.callback = types.SimpleNamespace(TrainingCallback=TrainingCallback)
    if with_collective:
        entered = []

        class CommunicatorContext:
            def __init__(self, **args):
                self.args = args

            def __enter__(self):
                entered.append(dict(self.args))
                return self

            def __exit__(self, *exc):
                return False

        class RabitTracker:
            def __init__(self, host_ip, n_workers):
                self.host_ip, self.n_workers = host_ip, n_workers

            def start(self):
                pass

            def worker_args(self):
                return {"dmlc_tracker_uri": self.host_ip, "dmlc_tracker_port": 9091}

            def free(self):
                pass

        mod.collective = types.SimpleNamespace(CommunicatorContext=CommunicatorContext)
        mod.tracker = types.SimpleNamespace(RabitTracker=RabitTracker)
        mod._entered = entered
    monkeypatch.setitem(sys.modules, "xgboost", mod)
    return mod


def test_xgboost_trainer_reports_and_checkpoints(monkeypatch, tmp_path):
    calls = []
    _fake_xgboost(monkeypatch, calls)
    from ray_tpu.train.xgboost import RayTrainReportCallback, XGBoostTrainer

    df = _frame()
    result = XGBoostTrainer(
        params={"eta": 0.3},
        label_column="label",
        num_boost_round=5,
        datasets={"train": rd.from_pandas(df), "valid": rd.from_pandas(_frame(seed=1))},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="xgb_single", storage_path=str(tmp_path)),
    ).fit()
    assert result.error is None
    assert result.metrics["training_iteration"] == 5
    assert result.metrics["train-rmse"] == pytest.approx(1.0 / 5)
    assert result.metrics["valid-rmse"] == pytest.approx(1.0 / 5)
    assert calls[0]["eval_names"] == ["train", "valid"]
    assert calls[0]["params"]["eta"] == 0.3
    # end-of-train checkpoint holds the 5-round booster
    assert result.checkpoint is not None
    model = RayTrainReportCallback.get_model(result.checkpoint)
    assert model.num_boosted_rounds() == 5


def test_xgboost_resume_trains_remaining_rounds(monkeypatch, tmp_path):
    calls = []
    _fake_xgboost(monkeypatch, calls)
    from ray_tpu.train.xgboost import XGBoostTrainer

    ds = rd.from_pandas(_frame())
    first = XGBoostTrainer(
        label_column="label", num_boost_round=4, datasets={"train": ds},
        run_config=RunConfig(name="xgb_r1", storage_path=str(tmp_path)),
    ).fit()
    assert first.error is None
    second = XGBoostTrainer(
        label_column="label", num_boost_round=10, datasets={"train": ds},
        run_config=RunConfig(name="xgb_r2", storage_path=str(tmp_path)),
        resume_from_checkpoint=first.checkpoint,
    ).fit()
    assert second.error is None
    assert calls[-1]["resumed_at"] == 4 and calls[-1]["rounds"] == 6
    from ray_tpu.train.xgboost import RayTrainReportCallback

    assert RayTrainReportCallback.get_model(second.checkpoint).num_boosted_rounds() == 10


def test_xgboost_two_workers_shard_and_join_collective(monkeypatch, tmp_path):
    calls = []
    mod = _fake_xgboost(monkeypatch, calls, with_collective=True)
    from ray_tpu.train.xgboost import XGBoostTrainer

    result = XGBoostTrainer(
        label_column="label", num_boost_round=3,
        datasets={"train": rd.from_pandas(_frame(n=40))},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="xgb_gang", storage_path=str(tmp_path)),
    ).fit()
    assert result.error is None
    # both ranks trained on disjoint row shards of the 40-row frame
    assert sorted(c["nrows"] for c in calls) == [20, 20]
    # and joined the collective with the tracker args rank 0 published
    assert len(mod._entered) == 2
    assert all(a["dmlc_tracker_port"] == 9091 for a in mod._entered)


def test_xgboost_missing_dependency_is_actionable(tmp_path):
    sys.modules.pop("xgboost", None)
    try:
        import xgboost  # noqa: F401

        pytest.skip("xgboost installed in this env")
    except ImportError:
        pass
    from ray_tpu.train.xgboost import XGBoostTrainer

    result = XGBoostTrainer(
        label_column="label", num_boost_round=2,
        datasets={"train": rd.from_pandas(_frame())},
        run_config=RunConfig(name="xgb_missing", storage_path=str(tmp_path)),
    ).fit()
    assert result.error is not None
    assert "pip install xgboost" in str(result.error)


# ---------------------------------------------------------------------------
# fake lightgbm
# ---------------------------------------------------------------------------
def _fake_lightgbm(monkeypatch, calls):
    import collections

    mod = types.ModuleType("lightgbm")
    CallbackEnv = collections.namedtuple(
        "CallbackEnv",
        ["model", "params", "iteration", "begin_iteration", "end_iteration",
         "evaluation_result_list"],
    )

    class Dataset:
        def __init__(self, X, label=None, reference=None):
            self.X, self.label, self.reference = X, label, reference

    class Booster:
        def __init__(self, model_file=None):
            self.iters = 0
            if model_file is not None:
                with open(model_file) as f:
                    self.iters = int(f.read())

        def current_iteration(self):
            return self.iters

        def save_model(self, path):
            with open(path, "w") as f:
                f.write(str(self.iters))

    def train(params, train_set, num_boost_round=10, valid_sets=(), valid_names=(),
              init_model=None, callbacks=(), **kw):
        model = Booster()
        if init_model is not None:
            model.iters = init_model.iters
        calls.append({
            "params": dict(params),
            "nrows": len(train_set.X),
            "rounds": num_boost_round,
            "valid_names": list(valid_names),
        })
        for it in range(num_boost_round):
            model.iters += 1
            results = [(n, "l2", 1.0 / model.iters, False) for n in valid_names]
            env = CallbackEnv(model, params, it, 0, num_boost_round, results)
            for cb in callbacks:
                cb(env)
        return model

    mod.Dataset = Dataset
    mod.Booster = Booster
    mod.train = train
    monkeypatch.setitem(sys.modules, "lightgbm", mod)
    return mod


def test_lightgbm_trainer_reports_and_checkpoints(monkeypatch, tmp_path):
    calls = []
    _fake_lightgbm(monkeypatch, calls)
    from ray_tpu.train.lightgbm import LightGBMTrainer, RayTrainReportCallback

    result = LightGBMTrainer(
        params={"objective": "regression"},
        label_column="label",
        num_boost_round=4,
        datasets={"train": rd.from_pandas(_frame()), "valid": rd.from_pandas(_frame(seed=2))},
        run_config=RunConfig(name="lgbm_single", storage_path=str(tmp_path)),
    ).fit()
    assert result.error is None
    assert result.metrics["training_iteration"] == 4
    assert result.metrics["train-l2"] == pytest.approx(0.25)
    assert result.metrics["valid-l2"] == pytest.approx(0.25)
    assert calls[0]["valid_names"] == ["train", "valid"]
    assert RayTrainReportCallback.get_model(result.checkpoint).current_iteration() == 4


def test_lightgbm_two_workers_negotiate_machines(monkeypatch, tmp_path):
    calls = []
    _fake_lightgbm(monkeypatch, calls)
    from ray_tpu.train.lightgbm import LightGBMTrainer

    result = LightGBMTrainer(
        label_column="label", num_boost_round=2,
        datasets={"train": rd.from_pandas(_frame(n=40))},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="lgbm_gang", storage_path=str(tmp_path)),
    ).fit()
    assert result.error is None
    assert sorted(c["nrows"] for c in calls) == [20, 20]
    ports = set()
    for c in calls:
        p = c["params"]
        assert p["num_machines"] == 2 and p["tree_learner"] == "data"
        machines = p["machines"].split(",")
        assert len(machines) == 2
        # each rank listens on the port it advertised in the machines list
        assert any(m.endswith(f":{p['local_listen_port']}") for m in machines)
        ports.add(p["local_listen_port"])
    assert len(ports) == 2  # distinct listen ports on the shared host
    # both ranks agreed on the same machines list
    assert calls[0]["params"]["machines"] == calls[1]["params"]["machines"]


def test_lightgbm_resume_trains_remaining_rounds(monkeypatch, tmp_path):
    calls = []
    _fake_lightgbm(monkeypatch, calls)
    from ray_tpu.train.lightgbm import LightGBMTrainer

    ds = rd.from_pandas(_frame())
    first = LightGBMTrainer(
        label_column="label", num_boost_round=3, datasets={"train": ds},
        run_config=RunConfig(name="lgbm_r1", storage_path=str(tmp_path)),
    ).fit()
    second = LightGBMTrainer(
        label_column="label", num_boost_round=8, datasets={"train": ds},
        run_config=RunConfig(name="lgbm_r2", storage_path=str(tmp_path)),
        resume_from_checkpoint=first.checkpoint,
    ).fit()
    assert second.error is None
    assert calls[-1]["rounds"] == 5


def test_group_token_unique_per_gang_attempt():
    from ray_tpu.train.worker_group import WorkerGroup

    g1 = WorkerGroup(ScalingConfig(num_workers=1), "same_name", "/tmp/rt_tok")
    g2 = WorkerGroup(ScalingConfig(num_workers=1), "same_name", "/tmp/rt_tok")
    assert g1.group_token and g1.group_token != g2.group_token


def test_lightgbm_resume_at_target_rounds_still_reports_checkpoint(monkeypatch, tmp_path):
    calls = []
    _fake_lightgbm(monkeypatch, calls)
    from ray_tpu.train.lightgbm import LightGBMTrainer, RayTrainReportCallback

    ds = rd.from_pandas(_frame())
    first = LightGBMTrainer(
        label_column="label", num_boost_round=3, datasets={"train": ds},
        run_config=RunConfig(name="lgbm_done1", storage_path=str(tmp_path)),
    ).fit()
    n_calls = len(calls)
    again = LightGBMTrainer(
        label_column="label", num_boost_round=3, datasets={"train": ds},
        run_config=RunConfig(name="lgbm_done2", storage_path=str(tmp_path)),
        resume_from_checkpoint=first.checkpoint,
    ).fit()
    assert again.error is None
    assert len(calls) == n_calls  # zero boosting rounds -> lightgbm.train never ran
    assert again.metrics["training_iteration"] == 3
    assert RayTrainReportCallback.get_model(again.checkpoint).current_iteration() == 3
