"""Object store + refcount tests (parity: memory_store / reference_count
test matrices, src/ray/core_worker/test/)."""

import threading

import numpy as np
import pytest

from ray_tpu.core.ids import JobID, ObjectID, TaskID
from ray_tpu.core.object_store import ObjectStore, Tier
from ray_tpu.core.refcount import ReferenceCounter
from ray_tpu.exceptions import GetTimeoutError


def _oid(i=None):
    task = TaskID.for_normal_task(JobID.from_int(1))
    return ObjectID.for_task_return(task, i or 1)


def test_put_get():
    store = ObjectStore()
    oid = _oid()
    store.put(oid, {"a": 1})
    assert store.get(oid) == {"a": 1}


def test_blocking_get_wakes_on_put():
    store = ObjectStore()
    oid = _oid()
    result = []

    def getter():
        result.append(store.get(oid, timeout=5))

    t = threading.Thread(target=getter)
    t.start()
    store.put(oid, 42)
    t.join(timeout=5)
    assert result == [42]


def test_get_timeout():
    store = ObjectStore()
    with pytest.raises(GetTimeoutError):
        store.get(_oid(), timeout=0.05)


def test_host_spill_to_disk_and_restore(tmp_path):
    from ray_tpu.core import config

    cfg = config.Config()
    cfg.spill_dir = str(tmp_path)
    config.set_config(cfg)
    try:
        store = ObjectStore(host_budget=1024 * 1024)
        oids = []
        for i in range(1, 6):
            oid = _oid(i)
            store.put(oid, np.ones(200_000, dtype=np.float32))  # 800KB each
            oids.append(oid)
        stats = store.stats()
        assert stats["spills"] > 0
        # restored values are intact
        for oid in oids:
            val = store.get(oid)
            assert val.shape == (200_000,)
            assert float(val[0]) == 1.0
    finally:
        config.reset_config()


def test_delete_accounting():
    store = ObjectStore(host_budget=10**9)
    oid = _oid()
    store.put(oid, np.ones(1000))
    assert store.stats()["host_used"] > 0
    store.delete(oid)
    assert store.stats()["host_used"] == 0
    assert not store.contains(oid)


# -------------------------------------------------------------------------
# reference counting
# -------------------------------------------------------------------------
def test_local_refcount_zero_triggers_delete():
    deleted = []
    rc = ReferenceCounter(on_object_out_of_scope=deleted.append)
    oid = _oid()
    rc.add_owned_object(oid)
    rc.add_local_reference(oid)
    rc.add_local_reference(oid)
    rc.remove_local_reference(oid)
    assert not deleted
    rc.remove_local_reference(oid)
    assert deleted == [oid]


def test_submitted_task_refs_keep_object_alive():
    deleted = []
    rc = ReferenceCounter(on_object_out_of_scope=deleted.append)
    oid = _oid()
    rc.add_local_reference(oid)
    rc.add_submitted_task_references([oid])
    rc.remove_local_reference(oid)
    assert not deleted  # task still holds it
    rc.remove_submitted_task_references([oid])
    assert deleted == [oid]


def test_borrowers_keep_object_alive():
    deleted = []
    rc = ReferenceCounter(on_object_out_of_scope=deleted.append)
    oid = _oid()
    rc.add_local_reference(oid)
    rc.add_borrower(oid, "worker-2")
    rc.remove_local_reference(oid)
    assert not deleted
    rc.remove_borrower(oid, "worker-2")
    assert deleted == [oid]


def test_pinned_objects_survive_zero_refs():
    deleted = []
    rc = ReferenceCounter(on_object_out_of_scope=deleted.append)
    oid = _oid()
    rc.add_local_reference(oid)
    rc.pin(oid)
    rc.remove_local_reference(oid)
    assert not deleted
    rc.unpin(oid)
    assert deleted == [oid]


def test_objectref_lifecycle_integration(ray_start_regular):
    rt = ray_start_regular
    worker = __import__("ray_tpu.runtime.worker", fromlist=["global_worker"]).global_worker()
    ref = rt.put([1, 2, 3])
    oid = ref.id()
    assert worker.ref_counter.has_reference(oid)
    store = rt.get_cluster().head_node.store
    assert store.contains(oid)
    del ref
    import gc
    import time

    gc.collect()
    # deletion is deferred to the refcount drainer thread; the store delete
    # fires after the refcount entry drops, so wait on both
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (worker.ref_counter.has_reference(oid) or store.contains(oid)):
        time.sleep(0.05)
    assert not worker.ref_counter.has_reference(oid)
    assert not store.contains(oid)
