"""TPU accelerator detection (parity: accelerators/tpu.py tests)."""

import pytest

from ray_tpu.accelerators import (
    get_chips_per_host,
    get_current_pod_worker_count,
    get_num_tpu_chips,
    get_tpu_pod_type,
    get_visible_chip_ids,
    tpu_head_resource_name,
    tpu_pod_resources,
)


def test_pod_type_normalization(monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    assert get_tpu_pod_type() == "v5e-16"
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-8")
    assert get_tpu_pod_type() == "v4-8"
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE")
    assert get_tpu_pod_type() is None


def test_worker_count_from_host_bounds(monkeypatch):
    monkeypatch.setenv("TPU_HOST_BOUNDS", "2,2,1")
    assert get_current_pod_worker_count() == 4
    monkeypatch.delenv("TPU_HOST_BOUNDS")
    assert get_current_pod_worker_count() == 1


def test_visible_chips_mask(monkeypatch):
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0,1,2")
    assert get_visible_chip_ids() == [0, 1, 2]
    assert get_num_tpu_chips() == 3
    monkeypatch.delenv("TPU_VISIBLE_CHIPS")
    assert get_visible_chip_ids() is None


def test_chips_per_host():
    assert get_chips_per_host("v5e-16") == 8
    assert get_chips_per_host("v4-8") == 4
    assert get_chips_per_host("v6e-8") == 8


def test_pod_resources_head_token(monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0,1,2,3,4,5,6,7")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    res = tpu_pod_resources()
    assert res["TPU"] == 8.0
    assert res[tpu_head_resource_name("v5e-16")] == 1.0
    # non-head worker carries no token
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    res = tpu_pod_resources()
    assert tpu_head_resource_name("v5e-16") not in res


def test_init_picks_up_pod_resources(monkeypatch):
    import ray_tpu as rt

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-8")
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0,1,2,3,4,5,6,7")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    rt.init(num_cpus=2)
    try:
        res = rt.cluster_resources()
        assert res["TPU"] == 8.0
        assert res["TPU-v5e-8-head"] == 1.0
    finally:
        rt.shutdown()
