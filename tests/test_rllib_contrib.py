"""Contrib-tier algorithm tests (parity model: rllib_contrib's per-algo
smoke/learning CI): PG family, DDPG/TD3, SimpleQ/Ape-X, ES/ARS, bandits,
and the name registry."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.rllib import (
    A2CConfig,
    A3CConfig,
    ApexDQNConfig,
    ARSConfig,
    CartPole,
    DDPGConfig,
    ESConfig,
    LinearBanditEnv,
    LinTSConfig,
    LinUCBConfig,
    Pendulum,
    PGConfig,
    PrioritizedReplayBuffer,
    SampleBatch,
    SimpleQConfig,
    TD3Config,
    get_algorithm_class,
    get_algorithm_config,
    list_algorithms,
)


def test_registry_resolves_every_algorithm():
    names = list_algorithms()
    assert len(names) >= 20
    for name in names:
        cls = get_algorithm_class(name)
        cfg = get_algorithm_config(name)
        # each config builds its registered class
        assert cfg.algo_class is cls
    # case-insensitive + unknown-name error
    assert get_algorithm_class("ppo").__name__ == "PPO"
    with pytest.raises(ValueError, match="unknown algorithm"):
        get_algorithm_class("nope")


@pytest.mark.full
def test_a2c_learns_cartpole():
    config = (
        A2CConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=16, rollout_length=128)
        .training(lr=2e-3, gae_lambda=0.95)
        .debugging(seed=0)
    )
    algo = config.build()
    result = None
    for _ in range(20):
        result = algo.train()
    assert result["episode_return_mean"] > 60.0
    algo.stop()


def test_pg_improves_cartpole():
    config = (
        PGConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=16, rollout_length=128)
        .debugging(seed=0)
    )
    algo = config.build()
    first = None
    result = None
    for _ in range(10):
        result = algo.train()
        if first is None and not np.isnan(result["episode_return_mean"]):
            first = result["episode_return_mean"]
    assert result["episode_return_mean"] > first
    assert "policy_loss" in result["learners"]
    algo.stop()


def test_a3c_interleaves_runner_updates():
    config = (
        A3CConfig()
        .environment(CartPole())
        .env_runners(num_env_runners=2, num_envs_per_runner=4, rollout_length=32)
        .debugging(seed=0)
    )
    algo = config.build()
    before = jax.tree.leaves(algo.learners.params)[0].copy()
    result = algo.train()
    after = jax.tree.leaves(algo.learners.params)[0]
    assert not np.allclose(before, after)
    # both runners' episodes landed in the metrics
    assert result["num_env_steps_sampled_lifetime"] == 2 * 4 * 32
    algo.stop()


def test_ddpg_runs_pendulum_with_bounded_actions():
    config = (
        DDPGConfig()
        .environment(Pendulum())
        .env_runners(num_envs_per_runner=4, rollout_length=64)
        .training(learning_starts=200, num_updates_per_iter=4)
        .debugging(seed=0)
    )
    algo = config.build()
    for _ in range(3):
        result = algo.train()
    stats = result["learners"]
    assert np.isfinite(stats["critic_loss"])
    assert np.isfinite(stats["q_mean"])
    # replayed actions stayed inside the env bounds despite exploration noise
    actions = algo.buffer._store[SampleBatch.ACTIONS][: len(algo.buffer)]
    assert actions.min() >= -2.0 and actions.max() <= 2.0
    algo.stop()


def test_td3_delays_policy_updates():
    from ray_tpu.rllib.algorithms.ddpg import _DDPGLearner
    from ray_tpu.rllib.rl_module import DDPGModule

    cfg = TD3Config().environment(Pendulum())
    assert cfg.twin_q and cfg.policy_delay == 2 and cfg.target_noise > 0
    module = DDPGModule(3, 1, -2.0, 2.0, (16,))
    learner = _DDPGLearner(module, cfg)
    batch = SampleBatch(
        {
            SampleBatch.OBS: np.random.randn(32, 3).astype(np.float32),
            SampleBatch.NEXT_OBS: np.random.randn(32, 3).astype(np.float32),
            SampleBatch.ACTIONS: np.random.uniform(-2, 2, (32, 1)).astype(np.float32),
            SampleBatch.REWARDS: np.random.randn(32).astype(np.float32),
            SampleBatch.DONES: np.zeros(32, bool),
        }
    )
    key = jax.random.key(0)
    s1 = learner.update(batch, key)
    s2 = learner.update(batch, key)
    # step 1 of 2: critic-only (actor loss reported as 0); step 2: both
    assert s1["actor_loss"] == 0.0
    assert s2["actor_loss"] != 0.0


def test_td3_checkpoint_roundtrip():
    config = (
        TD3Config()
        .environment(Pendulum())
        .env_runners(num_envs_per_runner=2, rollout_length=32)
        .training(learning_starts=50, num_updates_per_iter=2)
        .debugging(seed=1)
    )
    algo = config.build()
    algo.train()
    state = algo.get_state()
    algo2 = config.copy().build()
    algo2.set_state(state)
    for a, b in zip(
        jax.tree.leaves(algo.learners.params), jax.tree.leaves(algo2.learners.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert algo2.learners._step == algo.learners._step
    algo.stop()
    algo2.stop()


def test_simple_q_hard_target_sync():
    config = (
        SimpleQConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=8, rollout_length=64)
        .training(learning_starts=100, num_updates_per_iter=8, target_update_freq=8)
        .debugging(seed=0)
    )
    algo = config.build()
    for _ in range(2):
        result = algo.train()
    # 16 updates at freq 8 -> targets were synced; after the last sync +
    # subsequent updates they match the online params only right at sync
    assert np.isfinite(result["learners"]["q_mean"])
    assert algo._updates == 16
    # checkpoint carries the target net + sync counter (not re-derived)
    algo2 = config.copy().build()
    algo2.set_state(algo.get_state())
    assert algo2._updates == 16
    for a, b in zip(
        jax.tree.leaves(algo.target_params), jax.tree.leaves(algo2.target_params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    algo.stop()
    algo2.stop()


def test_prioritized_buffer_biases_and_reweights():
    buf = PrioritizedReplayBuffer(capacity=128, seed=0, alpha=1.0, beta=1.0)
    buf.add(SampleBatch({"x": np.arange(100, dtype=np.float32)}))
    # crank one transition's priority way up
    buf.update_priorities(np.array([7]), np.array([1000.0]))
    s = buf.sample(256)
    frac = float(np.mean(s["x"] == 7.0))
    assert frac > 0.5  # dominates the distribution
    # IS weights: the over-sampled row gets the SMALLEST weight
    assert s["weights"][s["x"] == 7.0].max() <= s["weights"].min() + 1e-6
    assert s.sampled_indices.shape == (256,)


def test_apex_epsilon_ladder_and_priority_writeback():
    config = (
        ApexDQNConfig()
        .environment(CartPole())
        .env_runners(num_env_runners=4, num_envs_per_runner=4, rollout_length=32)
        .training(learning_starts=200, num_updates_per_iter=4)
        .debugging(seed=0)
    )
    algo = config.build()
    # the ladder spans high -> low exploration
    assert algo._epsilons[0] == pytest.approx(0.4)
    assert algo._epsilons[-1] < 0.01
    assert all(a > b for a, b in zip(algo._epsilons, algo._epsilons[1:]))
    for _ in range(2):
        result = algo.train()
    assert np.isfinite(result["learners"]["q_mean"])
    # TD write-back de-uniformized the priorities
    pr = algo.buffer._priorities[: len(algo.buffer)]
    assert pr.std() > 0
    algo.stop()


@pytest.mark.full
def test_es_learns_cartpole():
    config = (
        ESConfig()
        .environment(CartPole())
        .training(population_size=64, noise_std=0.1, lr=0.05, eval_length=200, hidden=(16,))
        .debugging(seed=0)
    )
    algo = config.build()
    first = algo.train()["learners"]["fitness_mean"]
    result = None
    for _ in range(9):
        result = algo.train()
    assert result["learners"]["fitness_mean"] > max(first * 1.5, 40.0)
    # checkpoint roundtrip preserves theta
    algo2 = config.copy().build()
    algo2.set_state(algo.get_state())
    for a, b in zip(jax.tree.leaves(algo.theta), jax.tree.leaves(algo2.theta)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.full
def test_ars_learns_cartpole_with_obs_normalization():
    config = (
        ARSConfig()
        .environment(CartPole())
        .training(population_size=32, noise_std=0.1, lr=0.1, top_directions=8, eval_length=200)
        .debugging(seed=0)
    )
    algo = config.build()
    first = algo.train()["learners"]["fitness_mean"]
    result = None
    for _ in range(9):
        result = algo.train()
    assert result["learners"]["fitness_mean"] > max(first * 1.5, 40.0)
    # the V2 normalizer consumed every sampled step
    assert algo.normalizer.count == pytest.approx(
        result["num_env_steps_sampled_lifetime"], rel=0.01
    )


def test_linucb_regret_shrinks():
    env = LinearBanditEnv(num_arms=4, context_dim=6, noise=0.05, env_seed=3)
    config = LinUCBConfig().environment(env).training(steps_per_iter=64).debugging(seed=0)
    algo = config.build()
    first = algo.train()["learners"]["regret_this_iter"]
    last = None
    for _ in range(4):
        last = algo.train()["learners"]["regret_this_iter"]
    # posterior concentrates: per-iteration regret collapses
    assert last < first * 0.5


def test_lints_runs_and_checkpoints():
    env = LinearBanditEnv(num_arms=3, context_dim=4, env_seed=1)
    config = LinTSConfig().environment(env).training(steps_per_iter=32).debugging(seed=0)
    algo = config.build()
    r1 = algo.train()
    assert np.isfinite(r1["learners"]["reward_mean"])
    algo2 = config.copy().build()
    algo2.set_state(algo.get_state())
    np.testing.assert_array_equal(np.asarray(algo.A), np.asarray(algo2.A))
    np.testing.assert_array_equal(np.asarray(algo.b), np.asarray(algo2.b))


def test_algorithm_evaluate_greedy():
    """Algorithm.evaluate (parity: evaluation with explore=False): greedy
    rollouts on a fresh env set, training state untouched."""
    config = (
        PGConfig()
        .environment(CartPole())
        .env_runners(num_envs_per_runner=8, rollout_length=64)
        .debugging(seed=0)
    )
    algo = config.build()
    algo.train()
    before = jax.tree.leaves(algo.learners.params)[0].copy()
    out = algo.evaluate(num_episodes=5)
    ev = out["evaluation"]
    assert ev["num_episodes"] == 5
    assert ev["episode_return_min"] <= ev["episode_return_mean"] <= ev["episode_return_max"]
    # evaluation must not have trained
    after = jax.tree.leaves(algo.learners.params)[0]
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))
    algo.stop()

    # continuous-control modules evaluate deterministically: same seed,
    # same returns
    cfg2 = (
        DDPGConfig()
        .environment(Pendulum())
        .env_runners(num_envs_per_runner=2, rollout_length=32)
        .debugging(seed=1)
    )
    algo2 = cfg2.build()
    e1 = algo2.evaluate(num_episodes=3)["evaluation"]["episode_return_mean"]
    e2 = algo2.evaluate(num_episodes=3)["evaluation"]["episode_return_mean"]
    assert e1 == e2
    algo2.stop()


def test_es_evaluate_deterministic():
    config = ESConfig().environment(CartPole()).training(
        population_size=8, eval_length=100
    ).debugging(seed=0)
    algo = config.build()
    algo.train()
    ev = algo.evaluate(num_episodes=4)["evaluation"]
    assert ev["num_episodes"] == 4
    assert ev["episode_return_min"] <= ev["episode_return_max"] <= 100
    # evaluation is repeatable AND does not advance the training RNG
    key_before = algo._key
    assert algo.evaluate(num_episodes=4)["evaluation"] == ev
    assert (jax.random.key_data(algo._key) == jax.random.key_data(key_before)).all()


def test_r2d2_recurrent_rollout_and_sequence_replay():
    """R2D2: GRU hidden state rides the rollout scan (reset at episode
    ends), sequences land in replay, burn-in masks the loss prefix."""
    from ray_tpu.rllib import R2D2Config

    config = (
        R2D2Config()
        .environment(CartPole(max_episode_steps=50))
        .env_runners(num_envs_per_runner=4, rollout_length=40)
        .training(
            sequence_length=20,
            burn_in=4,
            learning_starts=8,
            num_updates_per_iter=2,
            train_batch_size=8,
            hidden_size=32,
        )
        .debugging(seed=0)
    )
    algo = config.build()
    result = None
    for _ in range(3):
        result = algo.train()
    # buffer rows are whole sequences
    assert algo.buffer._store[SampleBatch.OBS].shape[1:] == (20, 4)
    assert np.isfinite(result["learners"]["q_mean"])
    assert np.isfinite(result["learners"]["td_abs_mean"])
    # short-episode env: episodes finished inside the recurrent rollout
    assert result["env_runners"]["num_episodes"] > 0
    # checkpoint carries target params
    algo2 = config.copy().build()
    algo2.set_state(algo.get_state())
    for a, b in zip(
        jax.tree.leaves(algo.target_params), jax.tree.leaves(algo2.target_params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # greedy recurrent evaluation works and repeats deterministically
    ev = algo.evaluate(num_episodes=3)["evaluation"]
    assert ev["num_episodes"] == 3
    assert algo.evaluate(num_episodes=3)["evaluation"] == ev
    # the OUT-OF-BOX config builds (sequence_length divides rollout_length)
    from ray_tpu.rllib import get_algorithm_config

    default = get_algorithm_config("R2D2").environment(CartPole()).build()
    default.stop()


def test_gru_unroll_resets_hidden_at_episode_boundaries():
    """The learner's unroll must zero the hidden state where reset_before
    is set — the mirror of the rollout's reset-at-done."""
    from ray_tpu.rllib import GRUQModule

    m = GRUQModule(obs_size=3, num_actions=2, hidden_size=8)
    params = m.init(jax.random.key(0))
    obs = jax.random.normal(jax.random.key(1), (6, 1, 3))
    resets = jnp.zeros((6, 1)).at[3, 0].set(1.0)  # episode ended at t=2
    q = m.unroll(params, m.initial_state((1,)), obs, resets)
    # steps 3..5 must equal a fresh unroll of just obs[3:]
    q_fresh = m.unroll(params, m.initial_state((1,)), obs[3:])
    np.testing.assert_allclose(np.asarray(q[3:]), np.asarray(q_fresh), rtol=1e-6)
    # ...and must differ from the no-reset unroll (history contaminated)
    q_noreset = m.unroll(params, m.initial_state((1,)), obs)
    assert not np.allclose(np.asarray(q[3:]), np.asarray(q_noreset[3:]))


def test_gru_module_unroll_matches_stepwise():
    """The learner's scan unroll must equal stepping the cell manually."""
    from ray_tpu.rllib import GRUQModule

    m = GRUQModule(obs_size=3, num_actions=2, hidden_size=8)
    params = m.init(jax.random.key(0))
    obs_seq = jax.random.normal(jax.random.key(1), (5, 2, 3))  # [T, B, O]
    q_scan = m.unroll(params, m.initial_state((2,)), obs_seq)
    h = m.initial_state((2,))
    for t in range(5):
        h, q = m.step(params, h, obs_seq[t])
        np.testing.assert_allclose(np.asarray(q), np.asarray(q_scan[t]), rtol=1e-5)


@pytest.mark.full
def test_maddpg_learns_simple_spread():
    """MADDPG on the pure-JAX cooperative navigation env: stacked per-agent
    params, centralized critics, shared reward improves."""
    from ray_tpu.rllib import MADDPG, MADDPGConfig, SimpleSpread

    env = SimpleSpread(n_agents=2)
    config = (
        MADDPGConfig()
        .environment(env)
        .training(
            learning_starts=200,
            num_updates_per_iter=8,
            train_batch_size=128,
            exploration_noise=0.3,
            hidden=(64, 64),
        )
        .debugging(seed=0)
    )
    algo = config.build()
    first = None
    result = None
    for _ in range(30):
        result = algo.train()
        if first is None and not np.isnan(result["episode_return_mean"]):
            first = result["episode_return_mean"]
    # cooperative shared return rises (less negative coverage cost)
    assert result["episode_return_mean"] > first
    assert np.isfinite(result["learners"]["critic_loss"])

    # deterministic evaluation, checkpoint roundtrip
    ev = algo.evaluate(num_episodes=4)["evaluation"]
    assert ev["num_episodes"] == 4
    assert algo.evaluate(num_episodes=4)["evaluation"] == ev
    algo2 = config.copy().build()
    algo2.set_state(algo.get_state())
    for a, b in zip(
        jax.tree.leaves(algo.nets.params), jax.tree.leaves(algo2.nets.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_simple_spread_env_shapes_and_reward():
    from ray_tpu.rllib import SimpleSpread

    env = SimpleSpread(n_agents=3)
    state, obs = env.reset(jax.random.key(0))
    assert obs.shape == (3, env.observation_size)
    actions = jnp.zeros((3, 2))
    state, obs2, rewards, term, trunc = env.step(state, actions)
    # cooperative: every agent sees the SAME shared reward, <= 0
    assert rewards.shape == (3,)
    assert float(rewards[0]) == float(rewards[1]) == float(rewards[2])
    assert float(rewards[0]) <= 0.0
    assert not bool(term)
    # truncates at the horizon
    for _ in range(env.max_episode_steps):
        state, obs2, rewards, term, trunc = env.step(state, actions)
    assert bool(trunc)


def test_r2d2_loss_consumes_truncations():
    """Truncations inside a sequence must change the loss (hidden resets +
    bootstrap-from-next_obs correction) — DONES alone is not enough."""
    from ray_tpu.rllib import GRUQModule
    from ray_tpu.rllib.algorithms.r2d2 import _r2d2_loss

    m = GRUQModule(obs_size=4, num_actions=2, hidden_size=8)
    params = m.init(jax.random.key(0))
    target = jax.tree.map(lambda x: x * 0.9, params)
    B, T = 3, 6
    rng = np.random.default_rng(0)
    base = {
        SampleBatch.OBS: rng.normal(size=(B, T, 4)).astype(np.float32),
        SampleBatch.NEXT_OBS: rng.normal(size=(B, T, 4)).astype(np.float32),
        SampleBatch.ACTIONS: rng.integers(0, 2, (B, T)).astype(np.int32),
        SampleBatch.REWARDS: rng.normal(size=(B, T)).astype(np.float32),
        SampleBatch.DONES: np.zeros((B, T), bool),
        SampleBatch.TRUNCATEDS: np.zeros((B, T), bool),
    }
    loss_fn = _r2d2_loss(m, gamma=0.99, burn_in=0)
    l_plain, _ = loss_fn(params, {k: jnp.asarray(v) for k, v in base.items()}, target_params=target)
    trunc = dict(base)
    tr = np.zeros((B, T), bool)
    tr[:, 2] = True  # episode cut mid-sequence
    trunc[SampleBatch.TRUNCATEDS] = tr
    l_trunc, _ = loss_fn(params, {k: jnp.asarray(v) for k, v in trunc.items()}, target_params=target)
    assert float(l_plain) != float(l_trunc)


def _cartpole_offline_data(T=200, n_good=5, n_random=5, seed=0):
    """Time-major [T, B] offline columns from a scripted heuristic policy
    (push toward the pole's lean — solves CartPole) mixed with random."""
    env = CartPole()
    B = n_good + n_random
    key = jax.random.key(seed)
    rng = np.random.default_rng(seed)
    cols = {k: [] for k in ["obs", "actions", "rewards", "dones", "truncateds"]}
    states, obs = [], []
    for b in range(B):
        key, rk = jax.random.split(key)
        s, o = env.reset(rk)
        states.append(s)
        obs.append(np.asarray(o))
    for t in range(T):
        step_obs, step_act, step_rew, step_done, step_trunc = [], [], [], [], []
        for b in range(B):
            o = obs[b]
            if b < n_good:
                a = int(o[2] + 0.5 * o[3] > 0)  # lean-following heuristic
            else:
                a = int(rng.integers(0, 2))
            s2, o2, r, term, trunc = env.step(states[b], jnp.asarray(a))
            step_obs.append(o)
            step_act.append(a)
            step_rew.append(float(r))
            step_done.append(bool(term))
            step_trunc.append(bool(trunc))
            if bool(term) or bool(trunc):
                key, rk = jax.random.split(key)
                s2, o2 = env.reset(rk)
            states[b], obs[b] = s2, np.asarray(o2)
        cols["obs"].append(np.stack(step_obs))
        cols["actions"].append(np.asarray(step_act))
        cols["rewards"].append(np.asarray(step_rew, np.float32))
        cols["dones"].append(np.asarray(step_done))
        cols["truncateds"].append(np.asarray(step_trunc))
    return SampleBatch({k: np.stack(v) for k, v in cols.items()})


@pytest.mark.full
def test_decision_transformer_conditions_on_return():
    """DT trains on mixed-quality offline data and, conditioned on a HIGH
    target return, clearly beats the random half of its training data."""
    from ray_tpu.rllib import DTConfig

    data = _cartpole_offline_data()
    config = (
        DTConfig()
        .environment(CartPole())
        .training(
            context_length=16,
            d_model=64,
            n_layers=2,
            updates_per_iter=60,
            train_batch_size=64,
            target_return=200.0,
        )
        .debugging(seed=0)
        .offline_data(data)
    )
    algo = config.build()
    first = algo.train()["learners"]["bc_loss"]
    last = None
    for _ in range(4):
        last = algo.train()["learners"]["bc_loss"]
    assert last < first  # sequence model fits the data
    ev = algo.evaluate(num_episodes=5)["evaluation"]
    # random CartPole averages ~20; return-conditioned DT must do far better
    assert ev["episode_return_mean"] > 60.0, ev
    # checkpoint roundtrip
    algo2 = config.copy().build()
    algo2.set_state(algo.get_state())
    for a, b in zip(jax.tree.leaves(algo.params), jax.tree.leaves(algo2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.full
def test_qmix_learns_discrete_spread_with_monotone_mixer():
    """QMIX: per-agent argmax policy improves the SHARED return, and the
    mixer is monotone in every agent utility (the QMIX constraint)."""
    from ray_tpu.rllib import DiscreteSpread, QMIXConfig

    env = DiscreteSpread(n_agents=2)
    config = (
        QMIXConfig()
        .environment(env)
        .training(
            learning_starts=200,
            num_updates_per_iter=8,
            train_batch_size=128,
            hidden=(64, 64),
        )
        .debugging(seed=0)
    )
    algo = config.build()
    first = None
    result = None
    for _ in range(30):
        result = algo.train()
        if first is None and not np.isnan(result["episode_return_mean"]):
            first = result["episode_return_mean"]
    assert result["episode_return_mean"] > first
    assert np.isfinite(result["learners"]["loss"])

    # monotonicity: dQ_tot/dQ_i >= 0 for every agent at random inputs
    gs = jax.random.normal(jax.random.key(1), (env.global_state_size,))
    qs = jax.random.normal(jax.random.key(2), (env.n_agents,))
    grad = jax.grad(lambda q: algo.nets.mix(algo.nets.params, q, gs))(qs)
    assert (np.asarray(grad) >= 0).all()

    ev = algo.evaluate(num_episodes=4)["evaluation"]
    assert ev["num_episodes"] == 4
    algo2 = config.copy().build()
    algo2.set_state(algo.get_state())
    for a, b in zip(jax.tree.leaves(algo.nets.params), jax.tree.leaves(algo2.nets.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_crr_weights_good_actions_above_bc_mean():
    """CRR's advantage weighting must recover the GOOD action from a
    dataset whose actions are uniform: one-step episodes on a bandit-like
    continuous env with reward -(a-0.5)^2. Plain BC would regress to the
    data mean (~0); CRR's critic-endorsed imitation lands near +0.5."""
    from ray_tpu.rllib import CRRConfig

    class OneStepEnv:
        """Horizon-1 continuous env: reward peaks at a = +0.5."""

        discrete = False
        observation_size = 2
        action_size = 1
        action_low = -1.0
        action_high = 1.0
        max_episode_steps = 1

        def reset(self, key):
            obs = jax.random.normal(key, (2,)) * 0.1
            return {"o": obs}, obs

        def step(self, state, action):
            a = jnp.reshape(action, ())
            r = -((a - 0.5) ** 2)
            return state, state["o"], r, jnp.ones((), bool), jnp.zeros((), bool)

    rng = np.random.default_rng(0)
    n = 4000
    # behavior actions stay off the exact bounds (a policy at the clip rail
    # would be atanh-degenerate for ANY squashed-gaussian learner)
    acts = rng.uniform(-0.95, 0.95, (n, 1)).astype(np.float32)
    obs = rng.normal(size=(n, 2)).astype(np.float32) * 0.1
    rews = -((acts[:, 0] - 0.5) ** 2).astype(np.float32)
    data = SampleBatch(
        {
            SampleBatch.OBS: obs,
            SampleBatch.ACTIONS: acts,
            SampleBatch.REWARDS: rews,
            SampleBatch.DONES: np.ones(n, bool),
            SampleBatch.NEXT_OBS: obs,
        }
    )
    config = (
        CRRConfig()
        .environment(OneStepEnv())
        .training(
            updates_per_iter=100,
            train_batch_size=256,
            hidden=(64, 64),
            critic_warmup_updates=400,
        )
        .debugging(seed=0)
        .offline_data(data)
    )
    algo = config.build()
    result = None
    for _ in range(8):
        result = algo.train()
    assert np.isfinite(result["learners"]["critic_loss"])
    # the selective weight keeps only profitable actions
    assert 0.0 < result["learners"]["weight_mean"] < 0.9
    # deterministic policy mean sits near the optimum, far from the BC
    # mean (plain behavior cloning on this data would land at ~0)
    a = float(
        jax.jit(algo.module.inference_action)(algo.params, jnp.zeros((2,)))[0]
    )
    assert 0.3 < a < 0.75, a
    ev = algo.evaluate(num_episodes=5)["evaluation"]
    assert ev["episode_return_mean"] > -0.1  # near the 0 optimum
    # checkpoint roundtrip
    algo2 = config.copy().build()
    algo2.set_state(algo.get_state())
    for x, y in zip(jax.tree.leaves(algo.params), jax.tree.leaves(algo2.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_direct_param_algorithms_expose_inference_api():
    """CRR holds params directly (no learner group); the Algorithm-level
    compute_actions/get_weights fall back to self.params."""
    import numpy as np

    from ray_tpu.rllib.algorithms.crr import CRRConfig
    from ray_tpu.rllib.sample_batch import SampleBatch

    class _Env:
        discrete = False
        observation_size = 3
        action_size = 1
        action_low = -1.0
        action_high = 1.0
        max_episode_steps = 1

    rng = np.random.default_rng(0)
    n = 64
    batch = SampleBatch({
        SampleBatch.OBS: rng.normal(size=(n, 3)).astype(np.float32),
        SampleBatch.ACTIONS: rng.uniform(-1, 1, size=(n, 1)).astype(np.float32),
        SampleBatch.REWARDS: rng.normal(size=n).astype(np.float32),
        SampleBatch.NEXT_OBS: rng.normal(size=(n, 3)).astype(np.float32),
        SampleBatch.DONES: np.ones(n, bool),
    })
    algo = (
        CRRConfig()
        .environment(_Env())
        .offline_data(batch)
        .training(critic_warmup_updates=1, updates_per_iter=2)
        .build()
    )
    try:
        algo.train()
        a = algo.compute_single_action(np.zeros(3, np.float32))
        assert np.asarray(a).shape == (1,)
        w = algo.get_weights()
        algo.set_weights(w)
    finally:
        algo.stop()
