"""TensorflowTrainer tests: TF_CONFIG cluster-spec wiring across the
process-worker gang (the rendezvous contract MultiWorkerMirroredStrategy
consumes), and a real single-worker keras fit when TF is importable."""

import json

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import ScalingConfig
from ray_tpu.train.tensorflow import TensorflowTrainer


@pytest.fixture(autouse=True)
def _ray():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_tf_config_cluster_spec_wired(tmp_path):
    out_dir = str(tmp_path)

    def loop(config):
        import os

        spec = json.loads(os.environ["TF_CONFIG"])
        ctx = train.get_context()
        rank = ctx.get_world_rank()
        with open(os.path.join(config["out_dir"], f"rank{rank}.json"), "w") as f:
            json.dump(
                {
                    "rank": rank,
                    "task_index": spec["task"]["index"],
                    "task_type": spec["task"]["type"],
                    "workers": spec["cluster"]["worker"],
                },
                f,
            )
        train.report({"rank": rank})

    trainer = TensorflowTrainer(
        loop,
        train_loop_config={"out_dir": out_dir},
        scaling_config=ScalingConfig(num_workers=2),
    )
    trainer.fit()
    specs = []
    for rank in (0, 1):
        with open(f"{out_dir}/rank{rank}.json") as f:
            specs.append(json.load(f))
    for rank, s in enumerate(specs):
        assert s["task_index"] == rank
        assert s["task_type"] == "worker"
        assert len(s["workers"]) == 2
    # both ranks see the SAME cluster spec, with distinct per-rank addresses
    assert specs[0]["workers"] == specs[1]["workers"]
    assert len(set(specs[0]["workers"])) == 2


def test_single_worker_keras_fit():
    tf = pytest.importorskip("tensorflow")
    del tf

    def loop(config):
        import numpy as np
        import tensorflow as tf

        x = np.random.default_rng(0).standard_normal((64, 4)).astype("float32")
        y = (x.sum(axis=1) > 0).astype("float32")
        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(8, activation="relu"), tf.keras.layers.Dense(1)]
        )
        model.compile(optimizer="adam", loss="mse")
        hist = model.fit(x, y, epochs=2, verbose=0)
        train.report({"loss": float(hist.history["loss"][-1])})

    trainer = TensorflowTrainer(loop, scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.metrics["loss"] >= 0.0
