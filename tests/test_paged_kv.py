"""Paged KV cache + chunked prefill tests.

Four contracts:
- allocator: typed exhaustion shed, no fragmentation across churn, double
  frees raise (leak checks must see corruption, not absorb it)
- ops: paged_decode_attention == dense decode_attention through a shuffled
  block table (XLA fallback and interpret-mode Pallas kernel)
- engine identity: the paged engine is token-identical to the dense engine
  under greedy decoding, and chunked prefill is token-identical to one-shot
  for every chunk width
- leak checks: every release path (finish, eos, deadline shed, disconnect
  evict, prefill crash, loop crash) returns ALL blocks to the pool
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.exceptions import DeadlineExceededError, OverloadedError
from ray_tpu.models import TransformerConfig, init_params
from ray_tpu.serve.kv_blocks import BlockAllocator
from ray_tpu.serve.llm import LLMEngine

CFG = TransformerConfig(
    vocab_size=89, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
    attention="dense", dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(11))


def _paged(params, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(CFG, params, cache_kind="paged", **kw)


def _wait(pred, timeout=60):
    deadline = time.time() + timeout
    while not pred() and time.time() < deadline:
        time.sleep(0.005)
    assert pred()


def _assert_no_leak(eng):
    """Quiesced-engine leak check under prefix caching: every held page is
    accounted for by the prefix cache, and flushing it empties the pool."""
    st = eng.stats()
    assert st["kv_blocks_in_use"] == st["prefix_cache_blocks"]
    eng.flush_prefix_cache()
    st = eng.stats()
    assert st["kv_blocks_in_use"] == 0 and st["prefix_cache_blocks"] == 0


# --------------------------------------------------------------------------
# BlockAllocator
# --------------------------------------------------------------------------
def test_allocator_page_zero_reserved():
    a = BlockAllocator(8)
    assert a.capacity == 7
    got = a.alloc(7)
    assert 0 not in got and sorted(got) == list(range(1, 8))
    assert a.free_blocks == 0 and a.used_blocks == 7
    a.free(got)
    assert a.free_blocks == 7 and a.used_blocks == 0


def test_allocator_too_small_raises():
    with pytest.raises(ValueError):
        BlockAllocator(1)


def test_allocator_exhaustion_is_typed_shed():
    a = BlockAllocator(4)
    held = a.alloc(2)
    with pytest.raises(OverloadedError) as exc:
        a.alloc(2)
    assert exc.value.layer == "engine" and exc.value.reason == "kv_blocks"
    assert exc.value.retry_after_s > 0
    # the failed alloc took nothing
    assert a.free_blocks == 1 and a.used_blocks == 2
    a.free(held)


def test_allocator_double_free_raises():
    a = BlockAllocator(4)
    got = a.alloc(1)
    a.free(got)
    with pytest.raises(ValueError):
        a.free(got)
    with pytest.raises(ValueError):
        a.free([0])  # the garbage page is never held


def test_allocator_no_fragmentation_across_churn():
    """1k admit/release cycles of varying sizes: the pool always refills to
    capacity and a full-capacity alloc still succeeds afterwards (pages are
    interchangeable, so there is nothing to fragment)."""
    a = BlockAllocator(17)
    rng = np.random.default_rng(7)
    for i in range(1000):
        sizes = []
        holds = []
        while a.free_blocks > 0:
            n = min(int(rng.integers(1, 5)), a.free_blocks)
            holds.append(a.alloc(n))
            sizes.append(n)
        for h in rng.permutation(len(holds)):
            a.free(holds[h])
        assert a.free_blocks == a.capacity, f"leak after cycle {i}"
    full = a.alloc(a.capacity)
    assert len(full) == a.capacity
    a.free(full)


# --------------------------------------------------------------------------
# paged_decode_attention op
# --------------------------------------------------------------------------
def _paged_op_case(seed=0, B=3, H=8, Hkv=2, D=16, S=64, bs=16):
    from ray_tpu.ops.decode_attention import decode_attention

    rng = np.random.default_rng(seed)
    M = S // bs
    N = B * M + 1
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    k_pages = jnp.asarray(rng.normal(size=(N, bs, Hkv, D)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(N, bs, Hkv, D)), jnp.float32)
    # shuffled table: physical placement must not matter
    perm = rng.permutation(np.arange(1, N))
    bt = jnp.asarray(perm[: B * M].reshape(B, M).astype(np.int32))
    lengths = jnp.asarray([5, S, 17], jnp.int32)
    kd = jnp.transpose(jnp.take(k_pages, bt, axis=0), (0, 3, 1, 2, 4)).reshape(B, Hkv, S, D)
    vd = jnp.transpose(jnp.take(v_pages, bt, axis=0), (0, 3, 1, 2, 4)).reshape(B, Hkv, S, D)
    ref = decode_attention(q, kd, vd, lengths)
    return q, k_pages, v_pages, bt, lengths, ref


def test_paged_decode_attention_matches_dense_xla():
    from ray_tpu.ops.decode_attention import paged_decode_attention

    q, kp, vp, bt, lengths, ref = _paged_op_case()
    out = paged_decode_attention(q, kp, vp, bt, lengths, use_kernel=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_paged_decode_attention_kernel_interpret():
    from ray_tpu.ops.decode_attention import paged_decode_attention

    q, kp, vp, bt, lengths, ref = _paged_op_case(seed=3)
    out = paged_decode_attention(q, kp, vp, bt, lengths, use_kernel=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# --------------------------------------------------------------------------
# engine identity: paged == dense, chunked == one-shot
# --------------------------------------------------------------------------
PROMPTS = [[3, 5, 7, 11, 13], [2] * 17, list(range(1, 31)), [8, 9]]


def test_paged_engine_token_identical_to_dense(params):
    dense = LLMEngine(CFG, params, max_batch_size=4, max_seq_len=64, cache_kind="dense")
    paged = _paged(params)
    try:
        ref = [f.result(timeout=120) for f in
               [dense.submit(p, max_tokens=8) for p in PROMPTS]]
        got = [f.result(timeout=120) for f in
               [paged.submit(p, max_tokens=8) for p in PROMPTS]]
        assert got == ref
        _assert_no_leak(paged)
    finally:
        dense.shutdown()
        paged.shutdown()


@pytest.mark.parametrize("chunk", [16, 7, 64])  # 1 block, odd, full prompt
def test_chunked_prefill_token_identical_to_one_shot(params, chunk):
    oneshot = _paged(params, prefill_chunk_tokens=0)
    chunked = _paged(params, prefill_chunk_tokens=chunk)
    try:
        ref = [f.result(timeout=120) for f in
               [oneshot.submit(p, max_tokens=8) for p in PROMPTS]]
        got = [f.result(timeout=120) for f in
               [chunked.submit(p, max_tokens=8) for p in PROMPTS]]
        assert got == ref
        assert chunked.stats()["prefill_chunks"] >= len(PROMPTS)
        _assert_no_leak(chunked)
    finally:
        oneshot.shutdown()
        chunked.shutdown()


def test_paged_prefix_reuse_token_identical(params):
    """A repeated prompt admits through the prefix cache: the warm run reuses
    every full prompt block (plus COW on the tail) and the tokens match the
    cold run bit-for-bit."""
    eng = _paged(params, kv_block_size=8)
    try:
        prompt = list(range(40, 7, -1))  # 33 tokens -> 4 full blocks of 8
        a = eng.generate(prompt, max_tokens=6)
        assert eng.stats()["prefix_cache_misses"] == 1
        b = eng.generate(prompt, max_tokens=6)
        assert a == b
        st = eng.stats()
        assert st["prefix_cache_hits"] == 1
        assert st["prefix_tokens_reused"] >= 32
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_paged_never_fitting_prompt_is_value_error(params):
    # pool of 2 usable blocks (32 positions) but max_seq_len still 64: the
    # block check fires where the seq-len check cannot
    eng = _paged(params, kv_num_blocks=3)
    try:
        with pytest.raises(ValueError, match="never be admitted"):
            eng.submit([1] * 30, max_tokens=10)
        assert eng.stats()["kv_blocks_in_use"] == 0
    finally:
        eng.shutdown()


def test_bucket_cap_contract():
    from ray_tpu.serve.llm import _bucket

    assert _bucket(100, cap=128) == 128
    assert _bucket(100, cap=100) == 100  # clamped, not grown past the cache
    assert _bucket(64, cap=64) == 64
    with pytest.raises(ValueError):
        _bucket(65, cap=64)


# --------------------------------------------------------------------------
# leak checks: every release path returns ALL blocks
# --------------------------------------------------------------------------
def test_blocks_released_on_finish_and_eos(params):
    eng = _paged(params)
    try:
        out = eng.generate([4, 5, 6], max_tokens=8)
        eos = out[2]
        eng.generate([4, 5, 6], max_tokens=8, eos_id=eos)  # early eos stop
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_blocks_released_on_disconnect_evict(params):
    eng = _paged(params, max_batch_size=1)
    try:
        stream = eng.submit_stream([4, 2], max_tokens=50)
        next(stream)
        _wait(lambda: eng.stats()["active_slots"] == 1)
        assert eng.stats()["kv_blocks_in_use"] > 0
        stream.close()
        _wait(lambda: eng.stats()["active_slots"] == 0)
        _wait(lambda: eng.stats()["kv_blocks_in_use"] == 0)
        # the freed pages still serve new work
        assert len(eng.generate([4, 2], max_tokens=3)) == 3
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_blocks_released_on_deadline_shed(params):
    eng = _paged(params, max_batch_size=1)
    try:
        blocker = eng.submit([2, 7, 1], max_tokens=40)
        doomed = eng.submit([2, 7, 1], max_tokens=2,
                            deadline_ts=time.time() + 0.05)
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=120)
        blocker.result(timeout=120)
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_blocks_released_on_prefill_crash(params):
    eng = _paged(params, prefill_chunk_tokens=8)
    try:
        real = eng._prefill_chunk
        eng._prefill_chunk = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("injected prefill fault")
        )
        fut = eng.submit([1, 2, 3, 4, 5], max_tokens=4)
        with pytest.raises(RuntimeError, match="prefill failed"):
            fut.result(timeout=120)
        _wait(lambda: eng.stats()["kv_blocks_in_use"] == 0)
        eng._prefill_chunk = real
        # pool intact: the engine keeps serving
        assert len(eng.generate([1, 2, 3], max_tokens=3)) == 3
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_blocks_released_on_loop_crash(params):
    eng = _paged(params)
    try:
        real = eng._decode_k_paged
        eng._decode_k_paged = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("injected decode fault")
        )
        fut = eng.submit([1, 2, 3], max_tokens=8)
        with pytest.raises(RuntimeError):
            fut.result(timeout=120)
        _wait(lambda: eng.stats()["kv_blocks_in_use"] == 0)
        eng._decode_k_paged = real
        # _fail_inflight + _reset_cache recovered the engine
        assert len(eng.generate([1, 2, 3], max_tokens=3)) == 3
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_head_of_line_waits_for_blocks_no_leak(params):
    """Pool fits one max-size request: the second is HELD (not shed, not
    reordered) until the first releases, then admits and completes."""
    eng = _paged(params, max_batch_size=2, kv_num_blocks=5)  # 4 usable blocks
    try:
        a = eng.submit([1] * 40, max_tokens=20)  # needs all 4 blocks
        _wait(lambda: eng.stats()["kv_blocks_in_use"] == 4)
        b = eng.submit([2] * 40, max_tokens=20)  # must wait for the pool
        _wait(lambda: eng.admission_snapshot()["waiting_for_blocks"] == 1)
        assert len(a.result(timeout=120)) == 20
        assert len(b.result(timeout=120)) == 20
        _wait(lambda: eng.stats()["kv_blocks_in_use"]
              == eng.stats()["prefix_cache_blocks"])
        _assert_no_leak(eng)
    finally:
        eng.shutdown()


def test_paged_snapshot_and_metrics_registered(params):
    from ray_tpu.observability import metric_defs
    from ray_tpu.runtime import admission

    names = {m.name for m in metric_defs.ALL_METRICS}
    for family in (
        "llm_kv_block_pool_size",
        "llm_kv_blocks_in_use",
        "llm_prefill_chunks_total",
        "llm_decode_stall_seconds",
    ):
        assert family in names
    eng = _paged(params)
    try:
        snap = [s for s in admission.sources_snapshot()
                if s.get("layer") == "engine"][-1]
        assert snap["cache_kind"] == "paged"
        assert snap["kv_block_pool_size"] == eng._allocator.capacity
        assert snap["kv_blocks_in_use"] == 0
        assert snap["kv_block_occupancy"] == 0.0
    finally:
        eng.shutdown()
