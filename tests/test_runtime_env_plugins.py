"""Container / MPI runtime-env plugins + client proxy mode (missing-list
items 8 from round-1 VERDICT).

Reference anchors: python/ray/_private/runtime_env/container.py,
python/ray/_private/runtime_env/mpi.py:41,
python/ray/util/client/server/proxier.py.
"""

import shutil

import pytest

from ray_tpu.runtime_env.plugin import (
    ContainerPlugin,
    MPIPlugin,
    validate_runtime_env,
    wrap_entrypoint,
)


def test_container_wrap(monkeypatch):
    monkeypatch.setattr(shutil, "which", lambda exe: "/usr/bin/podman" if exe == "podman" else None)
    value = {"image": "python:3.12", "run_options": ["--net=host"]}
    ContainerPlugin().validate(value)
    cmd = wrap_entrypoint(
        {"container": value, "env_vars": {"WANDB_API_KEY": "k"}},
        "python train.py", {"PYTHONPATH": "/repo"}, "/work",
    )
    assert cmd.startswith("podman run --rm --net=host")
    assert "python:3.12" in cmd
    assert "'python train.py'" in cmd
    # user env_vars are forwarded into the container; host paths are not
    assert "-e WANDB_API_KEY=k" in cmd
    assert "PYTHONPATH" not in cmd


def test_container_requires_engine(monkeypatch):
    monkeypatch.setattr(shutil, "which", lambda exe: None)
    with pytest.raises(ValueError, match="podman or docker"):
        validate_runtime_env({"container": {"image": "x"}})


def test_mpi_wrap(monkeypatch):
    monkeypatch.setattr(shutil, "which", lambda exe: "/usr/bin/mpirun" if exe == "mpirun" else None)
    value = {"processes": 4}
    MPIPlugin().validate(value)
    cmd = wrap_entrypoint({"mpi": value}, "python step.py", {}, None)
    assert cmd.startswith("mpirun -n 4")
    assert "'python step.py'" in cmd


def test_mpi_then_container_order(monkeypatch):
    monkeypatch.setattr(
        shutil, "which",
        lambda exe: f"/usr/bin/{exe}" if exe in ("mpirun", "podman") else None,
    )
    cmd = wrap_entrypoint(
        {"mpi": {"processes": 2}, "container": {"image": "img"}},
        "python x.py", {}, "/w",
    )
    # mpi wraps first (priority 80), container wraps the mpirun line (90)
    assert cmd.startswith("podman run")
    assert "mpirun -n 2" in cmd


def test_unknown_runtime_env_key_rejected():
    with pytest.raises(ValueError, match="unknown runtime_env"):
        validate_runtime_env({"not_a_plugin": 1})


# ----------------------------------------------------------- proxy mode
@pytest.mark.full
def test_client_proxy_isolates_tenants():
    """Two clients through one proxy endpoint get separate driver runtimes."""
    from ray_tpu.util.client.proxier import ProxyServer
    from ray_tpu.util.client.worker import connect

    proxy = ProxyServer(port=0, num_cpus_per_backend=1, warm_backends=1).start()
    try:
        ctx1 = connect(proxy.address)
        ctx2 = connect(proxy.address)
        try:
            def whoami():
                import os

                return os.getpid()

            f1 = ctx1.remote(whoami)
            f2 = ctx2.remote(whoami)
            pid1 = ctx1.get(f1.remote(), timeout=120)
            pid2 = ctx2.get(f2.remote(), timeout=120)
            # separate backend driver processes per tenant
            assert pid1 != pid2
        finally:
            ctx1.disconnect()
            ctx2.disconnect()
    finally:
        proxy.stop()
