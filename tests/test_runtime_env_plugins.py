"""Container / MPI runtime-env plugins + client proxy mode (missing-list
items 8 from round-1 VERDICT).

Reference anchors: python/ray/_private/runtime_env/container.py,
python/ray/_private/runtime_env/mpi.py:41,
python/ray/util/client/server/proxier.py.
"""

import shutil

import pytest

from ray_tpu.runtime_env.plugin import (
    ContainerPlugin,
    MPIPlugin,
    validate_runtime_env,
    wrap_entrypoint,
)


def test_container_wrap(monkeypatch):
    monkeypatch.setattr(shutil, "which", lambda exe: "/usr/bin/podman" if exe == "podman" else None)
    value = {"image": "python:3.12", "run_options": ["--net=host"]}
    ContainerPlugin().validate(value)
    cmd = wrap_entrypoint(
        {"container": value, "env_vars": {"WANDB_API_KEY": "k"}},
        "python train.py", {"PYTHONPATH": "/repo"}, "/work",
    )
    assert cmd.startswith("podman run --rm --net=host")
    assert "python:3.12" in cmd
    assert "'python train.py'" in cmd
    # user env_vars are forwarded into the container; host paths are not
    assert "-e WANDB_API_KEY=k" in cmd
    assert "PYTHONPATH" not in cmd


def test_container_requires_engine(monkeypatch):
    monkeypatch.setattr(shutil, "which", lambda exe: None)
    with pytest.raises(ValueError, match="podman or docker"):
        validate_runtime_env({"container": {"image": "x"}})


def test_mpi_wrap(monkeypatch):
    monkeypatch.setattr(shutil, "which", lambda exe: "/usr/bin/mpirun" if exe == "mpirun" else None)
    value = {"processes": 4}
    MPIPlugin().validate(value)
    cmd = wrap_entrypoint({"mpi": value}, "python step.py", {}, None)
    assert cmd.startswith("mpirun -n 4")
    assert "'python step.py'" in cmd


def test_mpi_then_container_order(monkeypatch):
    monkeypatch.setattr(
        shutil, "which",
        lambda exe: f"/usr/bin/{exe}" if exe in ("mpirun", "podman") else None,
    )
    cmd = wrap_entrypoint(
        {"mpi": {"processes": 2}, "container": {"image": "img"}},
        "python x.py", {}, "/w",
    )
    # mpi wraps first (priority 80), container wraps the mpirun line (90)
    assert cmd.startswith("podman run")
    assert "mpirun -n 2" in cmd


def test_unknown_runtime_env_key_rejected():
    with pytest.raises(ValueError, match="unknown runtime_env"):
        validate_runtime_env({"not_a_plugin": 1})


# ----------------------------------------------------------- proxy mode
@pytest.mark.full
def test_client_proxy_isolates_tenants():
    """Two clients through one proxy endpoint get separate driver runtimes."""
    from ray_tpu.util.client.proxier import ProxyServer
    from ray_tpu.util.client.worker import connect

    proxy = ProxyServer(port=0, num_cpus_per_backend=1, warm_backends=1).start()
    try:
        ctx1 = connect(proxy.address)
        ctx2 = connect(proxy.address)
        try:
            def whoami():
                import os

                return os.getpid()

            f1 = ctx1.remote(whoami)
            f2 = ctx2.remote(whoami)
            pid1 = ctx1.get(f1.remote(), timeout=120)
            pid2 = ctx2.get(f2.remote(), timeout=120)
            # separate backend driver processes per tenant
            assert pid1 != pid2
        finally:
            ctx1.disconnect()
            ctx2.disconnect()
    finally:
        proxy.stop()


def test_profiling_plugin_dumps_pstats(tmp_path):
    """runtime_env={'profiling': {'dir': ...}}: every task body runs under
    cProfile and leaves a pstats-loadable dump named after the task."""
    import pstats

    import ray_tpu as rt

    rt.init(num_cpus=2)
    try:
        out = str(tmp_path / "profs")

        @rt.remote(execution="process", runtime_env={"profiling": {"dir": out}})
        def crunch(n):
            total = 0
            for i in range(n):
                total += i * i
            return total

        assert rt.get(crunch.remote(50_000), timeout=120) == sum(i * i for i in range(50_000))
        profs = list((tmp_path / "profs").glob("crunch_*.prof"))
        assert profs, list((tmp_path / "profs").iterdir())
        stats = pstats.Stats(str(profs[0]))
        assert stats.total_calls > 0
    finally:
        rt.shutdown()


def test_profiling_plugin_validation():
    from ray_tpu.runtime_env.plugin import validate_runtime_env

    validate_runtime_env({"profiling": True})
    validate_runtime_env({"profiling": {"dir": "/tmp/x"}})
    import pytest as _pytest

    with _pytest.raises(ValueError):
        validate_runtime_env({"profiling": {"nope": 1}})
    with _pytest.raises(ValueError):
        validate_runtime_env({"profiling": "yes"})


def test_task_level_env_vars_apply_and_restore():
    """Per-task env_vars reach the worker's task body and do not leak into
    the next task on the SAME worker (the restore path, pinned by pid)."""
    import ray_tpu as rt

    rt.init(num_cpus=2)
    try:
        import os as _os
        import time as _time

        @rt.remote(execution="process", runtime_env={"env_vars": {"MY_TASK_FLAG": "on"}})
        def with_env():
            return _os.getpid(), _os.environ.get("MY_TASK_FLAG")

        @rt.remote(execution="process")
        def without_env():
            return _os.getpid(), _os.environ.get("MY_TASK_FLAG")

        pid, flag = rt.get(with_env.remote(), timeout=120)
        assert flag == "on"
        # keep calling until the plain task lands on the SAME worker — only
        # then does "unset" prove the restore, not just a fresh process
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            pid2, flag2 = rt.get(without_env.remote(), timeout=120)
            if pid2 == pid:
                assert flag2 is None, "env var leaked into the next task on the same worker"
                break
        else:
            raise AssertionError("plain task never reused the env task's worker")
        # malformed env fails at the DRIVER with the plugin's error
        import pytest as _pytest

        @rt.remote(execution="process", runtime_env={"env_vars": {"N": 1}})
        def bad():
            return None

        with _pytest.raises((TypeError, ValueError)):
            bad.remote()
    finally:
        rt.shutdown()
