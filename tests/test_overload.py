"""Overload survival (ISSUE 9): end-to-end admission control, load
shedding, and per-tenant fairness.

Covers every bounded layer's shed trigger (router queue, replica backstop,
LLM engine count + prefill-token budget, per-caller submission cap, the
scheduler's parked demand queue, the object store's bounded spill tier),
weighted fairness between competing tenants, expired-deadline
shed-on-arrival, proxy error->status mappings (429/503/504 + Retry-After),
the chaos ``overload`` schedule kind with invariant 11 and byte-identical
same-seed fault logs, and the /api/overload + ``rt overload`` surface.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
import ray_tpu as rt
from ray_tpu.exceptions import (
    DeadlineExceededError,
    OverloadedError,
    RayActorError,
    StoreFullError,
)

CFG_KW = dict(
    vocab_size=89, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
    attention="dense", dtype=jnp.float32,
)


def _engine(**kw):
    from ray_tpu.models import TransformerConfig, init_params
    from ray_tpu.serve.llm import LLMEngine

    cfg = TransformerConfig(**CFG_KW)
    params = init_params(cfg, jax.random.key(11))
    return LLMEngine(cfg, params, max_seq_len=64, **kw)


def _wait_active(eng, n, timeout=60):
    deadline = time.time() + timeout
    while eng.stats()["active_slots"] < n and time.time() < deadline:
        time.sleep(0.005)
    assert eng.stats()["active_slots"] >= n, "request never admitted to a slot"


# --------------------------------------------------------------------------
# typed error shape
# --------------------------------------------------------------------------
def test_overloaded_error_is_typed_and_picklable():
    import pickle

    err = OverloadedError("router", "queue_full", 2.5)
    clone = pickle.loads(pickle.dumps(err))
    assert clone.layer == "router" and clone.reason == "queue_full"
    assert clone.retry_after_s == 2.5
    full = pickle.loads(pickle.dumps(StoreFullError(waited_s=1.5, needed=64)))
    assert full.waited_s == 1.5 and full.needed == 64
    # custom diagnostic detail survives the process/actor boundary
    detailed = OverloadedError("replica", "queue_full", 1.0,
                               "replica 'X#3' at its bound (4)")
    assert str(pickle.loads(pickle.dumps(detailed))) == str(detailed)


# --------------------------------------------------------------------------
# weighted fair queuing (the fairness kernel)
# --------------------------------------------------------------------------
def test_weighted_fair_queue_ratio():
    from ray_tpu.runtime.admission import WeightedFairQueue

    q = WeightedFairQueue({"a": 2.0, "b": 1.0})
    for i in range(30):
        q.push(("a", i), "a")
    for i in range(30):
        q.push(("b", i), "b")
    first = [q.pop()[0] for _ in range(15)]
    # stride scheduling: a gets ~2/3 of the pops while both queues are live
    assert first.count("a") == 10 and first.count("b") == 5
    # FIFO within each tenant
    a_items = [item for item in (q.pop() for _ in range(45)) if item[0] == "a"]
    assert [i for _, i in a_items] == sorted(i for _, i in a_items)


def test_weighted_fair_queue_hot_tenant_cannot_starve():
    from ray_tpu.runtime.admission import WeightedFairQueue

    q = WeightedFairQueue()
    for i in range(100):
        q.push(("hog", i), "hog")
    q.push(("quiet", 0), "quiet")  # late joiner starts at the live floor
    first = [q.pop()[0] for _ in range(3)]
    assert "quiet" in first  # admitted within a couple of pops, not after 100


def test_weighted_fair_queue_idle_tenant_not_starved_on_return():
    """A tenant that was busy, drained, and went idle must NOT be starved
    by its old vtime when it returns against a fresh tenant (the global
    virtual clock floors every empty-queue push)."""
    from ray_tpu.runtime.admission import WeightedFairQueue

    q = WeightedFairQueue({"a": 1.0, "b": 1.0})
    for i in range(100):
        q.push(("a", i), "a")
    while q.pop() is not None:  # a drains completely (vtime_a ~ 100)
        pass
    q.push(("b", 0), "b")  # fresh tenant
    for i in range(10):
        q.push(("a", i), "a")
    first4 = [q.pop()[0] for _ in range(4)]
    # equal weights: near-alternation, never 4 consecutive b-pops
    assert first4.count("a") >= 1, first4


def test_tenant_label_cardinality_bounded():
    from ray_tpu.runtime import admission

    labels = {admission.tenant_label(f"spam-{i}") for i in range(500)}
    assert "other" in labels
    assert len(labels) <= admission.MAX_TENANT_LABELS + 1
    # known ids keep their own series; None/"" collapse to default
    known = admission.tenant_label("spam-0")
    assert known in ("spam-0", "other")
    assert admission.tenant_label(None) == "default"


def test_weighted_fair_queue_prunes_adhoc_tenants():
    """Tenant ids are client-supplied: drained ad-hoc tenants must not
    accumulate in the overload-protection layer itself."""
    from ray_tpu.runtime.admission import WeightedFairQueue

    q = WeightedFairQueue({"configured": 3.0})
    for i in range(200):
        q.push(i, f"drive-by-{i}")
        assert q.pop() == i
    q.push(0, "configured")
    assert q.pop() == 0
    assert len(q._queues) <= 1 and len(q._vtime) <= 1  # only the configured one


# --------------------------------------------------------------------------
# LLM engine: count bound, token budget, deadline shed, fairness, disconnect
# --------------------------------------------------------------------------
def test_engine_queue_count_shed():
    eng = _engine(max_batch_size=1, max_queued_requests=2)
    try:
        # occupy the single slot with a long request, then fill the queue
        futs = [eng.submit([3, 1, 4], max_tokens=40)]
        _wait_active(eng, 1)
        futs += [eng.submit([3, 1, 4], max_tokens=2) for _ in range(2)]
        with pytest.raises(OverloadedError) as exc:
            eng.submit([3, 1, 4], max_tokens=2)
        assert exc.value.layer == "engine" and exc.value.reason == "queue_full"
        assert exc.value.retry_after_s > 0
        for f in futs:
            f.result(timeout=120)
        assert eng.stats()["shed"] >= 1
    finally:
        eng.shutdown()


def test_engine_prefill_token_budget_shed():
    eng = _engine(max_batch_size=1, max_queued_prefill_tokens=10)
    try:
        blocker = eng.submit([5] * 4, max_tokens=40)
        _wait_active(eng, 1)
        ok = eng.submit([5] * 8, max_tokens=2)  # 8 <= 10 queued tokens
        with pytest.raises(OverloadedError) as exc:
            eng.submit([5] * 8, max_tokens=2)  # 8 + 8 > 10
        assert exc.value.reason == "token_budget"
        blocker.result(timeout=120)
        ok.result(timeout=120)
    finally:
        eng.shutdown()


def test_engine_never_fitting_prompt_is_value_error_not_429():
    """A prompt that alone exceeds the prefill-token budget can never be
    admitted — retrying after the hint would loop forever, so it must be a
    ValueError (config/input error), not a retryable OverloadedError."""
    eng = _engine(max_batch_size=1, max_queued_prefill_tokens=10)
    try:
        with pytest.raises(ValueError, match="never be admitted"):
            eng.submit([5] * 11, max_tokens=2)
    finally:
        eng.shutdown()


def test_engine_expired_deadline_sheds_on_arrival():
    eng = _engine(max_batch_size=2)
    try:
        with pytest.raises(DeadlineExceededError):
            eng.submit([1, 2, 3], max_tokens=2, deadline_ts=time.time() - 1.0)
        assert eng.stats()["shed"] == 1
        assert eng.stats()["active_slots"] == 0  # never occupied a slot
    finally:
        eng.shutdown()


def test_engine_deadline_expired_while_queued_never_takes_slot():
    eng = _engine(max_batch_size=1)
    try:
        blocker = eng.submit([2, 7, 1], max_tokens=60)  # holds the only slot
        doomed = eng.submit([2, 7, 1], max_tokens=2,
                            deadline_ts=time.time() + 0.05)
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=120)
        blocker.result(timeout=120)
        assert eng.stats()["shed"] >= 1
        # the shed request never reserved KV pages; the finished blocker's
        # pages are all accounted for by the prefix cache and a flush
        # drains the pool back to empty
        st = eng.stats()
        assert st["kv_blocks_in_use"] == st["prefix_cache_blocks"]
        eng.flush_prefix_cache()
        assert eng.stats()["kv_blocks_in_use"] == 0
    finally:
        eng.shutdown()


def test_engine_tenant_weighted_fairness():
    """Two competing tenants at weights 2:1 admit ~2:1 while both queues
    are backlogged (the admission order IS the completion order with one
    decode slot)."""
    eng = _engine(max_batch_size=1, tenant_weights={"a": 2.0, "b": 1.0})
    try:
        order = []
        blocker = eng.submit([9, 9], max_tokens=30)  # pin the slot first
        futs = []
        for i in range(6):
            fa = eng.submit([3, 1], max_tokens=1, tenant="a")
            fa.add_done_callback(lambda _f: order.append("a"))
            fb = eng.submit([3, 1], max_tokens=1, tenant="b")
            fb.add_done_callback(lambda _f: order.append("b"))
            futs += [fa, fb]
        blocker.result(timeout=120)
        for f in futs:
            f.result(timeout=120)
        first6 = order[:6]
        assert first6.count("a") == 4 and first6.count("b") == 2, order
    finally:
        eng.shutdown()


def test_engine_disconnected_stream_frees_slot():
    from ray_tpu.observability import metric_defs

    eng = _engine(max_batch_size=1)
    try:
        stream = eng.submit_stream([4, 2], max_tokens=50)
        got = [next(stream), next(stream)]
        assert len(got) == 2
        assert eng.stats()["active_slots"] == 1
        stream.close()  # consumer disconnects mid-generation
        deadline = time.time() + 30
        while eng.stats()["active_slots"] and time.time() < deadline:
            time.sleep(0.01)
        assert eng.stats()["active_slots"] == 0, "slot never evicted"
        assert eng.stats()["slots_evicted"] == 1
        assert eng.stats()["kv_blocks_in_use"] == 0  # evict freed its pages
        # the freed slot still serves new work
        assert len(eng.generate([4, 2], max_tokens=3)) == 3
    finally:
        eng.shutdown()


def test_engine_abandoned_queued_stream_never_admits():
    eng = _engine(max_batch_size=1)
    try:
        blocker = eng.submit([8, 8], max_tokens=40)
        _wait_active(eng, 1)
        stream = eng.submit_stream([1, 2], max_tokens=50)
        assert eng.stats()["queued"] == 1
        stream.close()  # gone before a slot ever freed
        # the queued entry's count + prefill tokens release IMMEDIATELY —
        # a burst of connect-then-disconnect clients must not hold the
        # bounded waiting queue against live traffic until slots free
        stats = eng.stats()
        assert stats["queued"] == 0 and stats["queued_prefill_tokens"] == 0
        assert stats["shed"] >= 1
        blocker.result(timeout=120)
        assert eng.stats()["active_slots"] == 0
        st = eng.stats()
        assert st["kv_blocks_in_use"] == st["prefix_cache_blocks"]
        eng.flush_prefix_cache()
        assert eng.stats()["kv_blocks_in_use"] == 0
    finally:
        eng.shutdown()


def test_engine_admission_snapshot_registered():
    from ray_tpu.runtime import admission

    eng = _engine(max_batch_size=2, max_queued_requests=7)
    try:
        snaps = [s for s in admission.sources_snapshot() if s.get("layer") == "engine"]
        assert snaps and snaps[-1]["queue_bound"] == 7
    finally:
        eng.shutdown()
    assert not [
        s for s in admission.sources_snapshot()
        if s.get("layer") == "engine" and s.get("queue_bound") == 7
    ]


# --------------------------------------------------------------------------
# core submission: per-caller in-flight cap (block and shed policies)
# --------------------------------------------------------------------------
def test_submission_cap_shed_policy():
    rt.init(num_cpus=2, _system_config={
        "max_inflight_tasks_per_caller": 3,
        "task_submit_overload_policy": "shed",
    })
    try:
        @rt.remote
        def hold():
            time.sleep(0.4)
            return 1

        refs, sheds = [], 0
        for _ in range(8):
            try:
                refs.append(hold.remote())
            except OverloadedError as exc:
                assert exc.layer == "submission" and exc.reason == "inflight_cap"
                sheds += 1
        assert len(refs) == 3 and sheds == 5
        assert rt.get(refs, timeout=60) == [1, 1, 1]
        # slots released on terminal commit: submission works again
        assert rt.get(hold.remote(), timeout=60) == 1
    finally:
        rt.shutdown()


def test_submission_cap_block_policy_waits_then_succeeds():
    rt.init(num_cpus=4, _system_config={
        "max_inflight_tasks_per_caller": 2,
        "task_submit_overload_policy": "block",
        "task_submit_block_timeout_s": 30.0,
    })
    try:
        @rt.remote
        def quick():
            time.sleep(0.1)
            return 1

        t0 = time.monotonic()
        refs = [quick.remote() for _ in range(6)]  # blocks at the cap
        assert time.monotonic() - t0 > 0.15  # at least two waves waited
        assert rt.get(refs, timeout=60) == [1] * 6
        gate = rt.get_cluster().core_worker.admission_gate.snapshot()
        assert gate["blocks"] >= 1 and gate["sheds"] == 0
    finally:
        rt.shutdown()


def test_submission_cap_block_timeout_sheds():
    rt.init(num_cpus=1, _system_config={
        "max_inflight_tasks_per_caller": 1,
        "task_submit_overload_policy": "block",
        "task_submit_block_timeout_s": 0.2,
    })
    try:
        @rt.remote
        def hold():
            time.sleep(2.0)
            return 1

        ref = hold.remote()
        with pytest.raises(OverloadedError) as exc:
            hold.remote()
        assert exc.value.reason == "block_timeout"
        assert rt.get(ref, timeout=60) == 1
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# scheduler demand queue: bounded parking
# --------------------------------------------------------------------------
def test_demand_queue_bound_sheds_typed():
    rt.init(num_cpus=1, _system_config={
        "demand_queue_max_entries": 3,
        "infeasible_task_timeout_s": 2.0,
    })
    try:
        @rt.remote(num_cpus=8, max_retries=0)  # infeasible on a 1-CPU node
        def big():
            return 1

        refs = [big.remote() for _ in range(8)]
        outcomes = {"overloaded": 0, "infeasible": 0}
        for ref in refs:
            with pytest.raises(Exception) as exc:
                rt.get(ref, timeout=30)
            if isinstance(exc.value, OverloadedError):
                assert exc.value.layer == "demand_queue"
                assert exc.value.retry_after_s > 0
                outcomes["overloaded"] += 1
            else:
                outcomes["infeasible"] += 1
        # 3 parked (fail infeasible at the 2s deadline), 5 shed typed
        assert outcomes["overloaded"] == 5, outcomes
        assert outcomes["infeasible"] == 3, outcomes
        snap = rt.get_cluster().overload_snapshot()
        assert snap["shed_totals"]["demand_queue"]["queue_full"] >= 5
        assert snap["demand_queue"]["bound"] == 3
    finally:
        rt.shutdown()


def test_demand_queue_actor_creation_shed_is_typed():
    """A shed actor creation surfaces the typed OverloadedError (with its
    retry_after_s) to callers — not a generic ActorDiedError."""
    rt.init(num_cpus=1, _system_config={
        "demand_queue_max_entries": 1,
        "infeasible_task_timeout_s": 2.0,
    })
    try:
        @rt.remote(resources={"NO_SUCH_CHIP": 1})
        class Big:
            def ping(self):
                return "pong"

        actors = [Big.remote() for _ in range(3)]  # 1 parks, 2 shed
        errors = []
        for a in actors:
            with pytest.raises(Exception) as exc:
                rt.get(a.ping.remote(), timeout=30)
            errors.append(exc.value)
        overloaded = [e for e in errors if isinstance(e, OverloadedError)]
        assert len(overloaded) == 2, errors
        assert all(e.retry_after_s > 0 for e in overloaded)
    finally:
        rt.shutdown()


# --------------------------------------------------------------------------
# object store: bounded spill tier backpressure
# --------------------------------------------------------------------------
def test_store_full_backpressure_deadline_and_release():
    import hashlib

    import numpy as np

    from ray_tpu.core.config import Config, reset_config, set_config
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_store import ObjectStore

    cfg = Config()
    cfg.object_store_max_disk_bytes = 1 << 20
    cfg.store_put_backpressure_timeout_s = 0.4
    set_config(cfg)
    try:
        store = ObjectStore(shm_store=None, hbm_budget=1 << 30, host_budget=1 << 20)

        def oid(i):
            return ObjectID(hashlib.blake2b(str(i).encode(), digest_size=24).digest())

        chunk = np.zeros(512 * 1024, np.uint8)
        for i in range(4):  # host (1M) + disk (1M) exactly full
            store.put(oid(i), chunk.copy())
        stats = store.stats()
        assert stats["disk_used"] == 1 << 20 and stats["spills"] >= 2

        # full store + nothing freed -> typed StoreFullError at the deadline
        t0 = time.monotonic()
        with pytest.raises(StoreFullError) as exc:
            store.put(oid(99), chunk.copy())
        assert time.monotonic() - t0 >= 0.35
        assert exc.value.waited_s > 0.3 and exc.value.needed == chunk.nbytes

        # a deletion mid-wait releases the backpressured put
        def free():
            time.sleep(0.1)
            store.delete(oid(0))
            store.delete(oid(1))

        threading.Thread(target=free, daemon=True).start()
        store.put(oid(100), chunk.copy())
        stats = store.stats()
        assert stats["puts_shed"] == 1 and stats["put_backpressure_waits"] >= 2

        # error tombstones bypass the gate even when full
        store.put_error(oid(101), RuntimeError("must always commit"))
        assert store.contains(oid(101))

        # overwriting a DISK-spilled entry frees its disk accounting and
        # file — a re-put producer must not inflate disk_used forever.
        # (The overwrite may trigger a fresh spill of another entry, so
        # assert the LEDGER matches the actual disk-tier entries, and the
        # old spill file is gone.)
        import os as _os

        spilled = [
            o for o in (oid(2), oid(3), oid(100))
            if (store.entry_info(o) or {}).get("tier") == "disk"
        ]
        assert spilled, "expected at least one disk-tier entry"
        with store._lock:
            old_path = store._entries[spilled[0]].disk_path
        store.put(spilled[0], np.zeros(16, np.uint8))  # tiny overwrite
        assert not _os.path.exists(old_path), "orphaned spill file"
        actual = sum(
            info["size"] for _o, info in store.list_entries()
            if info["tier"] == "disk"
        )
        assert store.stats()["disk_used"] == actual
    finally:
        reset_config()


def test_store_concurrent_admits_cannot_overshoot_budget():
    """The admission gate RESERVES bytes: two concurrent puts must not both
    claim the same last free room (check-then-commit race)."""
    import hashlib

    import numpy as np

    from ray_tpu.core.config import Config, reset_config, set_config
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_store import ObjectStore

    cfg = Config()
    cfg.object_store_max_disk_bytes = 1 << 19  # host 512K + disk 512K = 1M
    cfg.store_put_backpressure_timeout_s = 0.3
    set_config(cfg)
    try:
        store = ObjectStore(shm_store=None, hbm_budget=1 << 30, host_budget=1 << 19)

        def oid(i):
            return ObjectID(hashlib.blake2b(str(i).encode(), digest_size=24).digest())

        nbytes = 1 << 20  # one admit reserves the WHOLE host+disk budget
        # first admit reserves; a second concurrent admit must
        # backpressure-then-shed even though nothing inserted yet
        assert store._admit_put(oid(0), nbytes) is True
        with pytest.raises(StoreFullError):
            store._admit_put(oid(1), nbytes)
        # releasing the reservation (what put()'s insert does) re-opens the gate
        with store._lock:
            store._pending_put_bytes -= nbytes
            store._space.notify_all()
        assert store._admit_put(oid(1), nbytes) is True
    finally:
        reset_config()


# --------------------------------------------------------------------------
# serve: router queue bound, replica backstop, idempotent replay gate
# --------------------------------------------------------------------------
def _serve_runtime():
    # replicas + the controller each hold a CPU: room for several apps
    rt.init(num_cpus=16)
    from ray_tpu import serve

    serve.start(http_port=0)
    return serve


def test_router_bounded_queue_sheds_and_recovers():
    serve = _serve_runtime()
    try:
        release = threading.Event()

        @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                          max_queued_requests=1)
        class Gate:
            def __call__(self, x):
                release.wait(30)
                return x

        handle = serve.run(Gate.bind(), route_prefix=None)
        results = []
        threads = [
            threading.Thread(
                target=lambda i=i: results.append(handle.remote(i).result(timeout=30)),
                daemon=True,
            )
            for i in range(2)  # 1 ongoing + 1 queued
        ]
        for t in threads:
            t.start()
        deadline = time.time() + 10
        while handle._router._queue_waiters < 1 and time.time() < deadline:
            time.sleep(0.01)
        with pytest.raises(OverloadedError) as exc:  # 3rd: queue full
            handle.remote(99).result(timeout=10)
        assert exc.value.layer == "router" and exc.value.retry_after_s > 0
        release.set()
        for t in threads:
            t.join(timeout=30)
        assert sorted(results) == [0, 1]
        # capacity freed: admission works again
        assert handle.remote(7).result(timeout=30) == 7
    finally:
        serve.shutdown()
        rt.shutdown()


def test_replica_backstop_sheds_typed_through_handle():
    serve = _serve_runtime()
    try:
        from ray_tpu.serve.replica import ReplicaActor

        release = threading.Event()

        def slow(x):
            release.wait(30)
            return x

        replica = ReplicaActor.options(execution="inproc", max_concurrency=4).remote(
            slow, (), {}, None, True, max_ongoing_requests=1,
        )
        first = replica.handle_request.remote("__call__", (1,), {})
        time.sleep(0.2)  # the first call occupies the replica
        with pytest.raises(OverloadedError) as exc:
            # a stale router's direct dispatch past the cap: backstop sheds,
            # and the typed cause surfaces unwrapped at the caller
            try:
                ray_tpu.get(replica.handle_request.remote("__call__", (2,), {}))
            except Exception as raw:
                from ray_tpu.runtime.admission import unwrap

                raise unwrap(raw)
        assert exc.value.layer == "replica"
        release.set()
        assert ray_tpu.get(first, timeout=30) == 1
    finally:
        serve.shutdown()
        rt.shutdown()


def test_non_idempotent_deployment_never_replays():
    """The replica-death replay satellite: without idempotent=True the
    router surfaces the typed actor error instead of re-executing a
    possibly-side-effecting request."""
    serve = _serve_runtime()
    try:
        @serve.deployment(num_replicas=1)
        class Solo:
            def __call__(self, x):
                return x + 100

        handle = serve.run(Solo.bind(), route_prefix=None)
        assert handle.remote(1).result(timeout=30) == 101
        from ray_tpu.serve import api as serve_api

        _v, replicas = ray_tpu.get(serve_api._controller.get_replicas.remote("Solo"))
        ray_tpu.kill(replicas[0])
        with pytest.raises(RayActorError):
            handle.remote(7).result(timeout=30)
    finally:
        serve.shutdown()
        rt.shutdown()


# --------------------------------------------------------------------------
# proxy: error -> HTTP status contract (429 + Retry-After / 503 / 504)
# --------------------------------------------------------------------------
def _http(url, body=None, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode() if body is not None else None,
        headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), err.read()


def test_proxy_status_mappings():
    serve = _serve_runtime()
    try:
        @serve.deployment
        def overloaded(_x):
            raise OverloadedError("engine", "queue_full", 3.0)

        @serve.deployment
        def too_late(_x):
            raise DeadlineExceededError("req", "executing", 1.0)

        @serve.deployment
        def dead_actor(_x):
            from ray_tpu.exceptions import ActorDiedError

            raise ActorDiedError(None, "replica died after retry budget")

        @serve.deployment
        def boom(_x):
            raise ValueError("application bug")

        serve.run(overloaded.bind(), name="overloaded", route_prefix="/overloaded")
        serve.run(too_late.bind(), name="late", route_prefix="/late")
        serve.run(dead_actor.bind(), name="dead", route_prefix="/dead")
        serve.run(boom.bind(), name="boom", route_prefix="/boom")
        base = serve.proxy_url()

        status, headers, body = _http(base + "/overloaded", {"x": 1})
        payload = json.loads(body)
        assert status == 429
        assert headers.get("Retry-After") == "3"
        assert payload["retry_after_s"] == 3.0
        assert payload["type"] == "OverloadedError"

        status, _h, body = _http(base + "/late", {"x": 1})
        assert status == 504
        assert json.loads(body)["type"] == "DeadlineExceededError"

        status, _h, body = _http(base + "/dead", {"x": 1})
        assert status == 503
        assert json.loads(body)["type"] == "ActorDiedError"

        status, _h, _b = _http(base + "/boom", {"x": 1})
        assert status == 500
    finally:
        serve.shutdown()
        rt.shutdown()


def test_proxy_request_timeout_maps_to_504():
    serve = _serve_runtime()
    try:
        from ray_tpu.serve import api as serve_api

        serve_api._proxy.request_timeout_s = 0.3

        @serve.deployment
        def glacial(_x):
            time.sleep(5)
            return "done"

        serve.run(glacial.bind(), route_prefix="/slow")
        status, _h, _b = _http(serve.proxy_url() + "/slow", {"x": 1})
        assert status == 504
    finally:
        serve.shutdown()
        rt.shutdown()


def test_proxy_tenant_header_rides_to_engine_context():
    serve = _serve_runtime()
    try:
        seen = []

        @serve.deployment
        def who(_x):
            from ray_tpu.runtime.context import current_tenant

            seen.append(current_tenant())
            return {"tenant": current_tenant()}

        serve.run(who.bind(), route_prefix="/who")
        status, _h, body = _http(
            serve.proxy_url() + "/who", {"x": 1},
            headers={"X-Tenant-Id": "team-42", "Content-Type": "application/json"},
        )
        assert status == 200
        assert json.loads(body)["tenant"] == "team-42"
        assert seen == ["team-42"]
    finally:
        serve.shutdown()
        rt.shutdown()


def test_grpc_overload_maps_to_resource_exhausted():
    grpc = pytest.importorskip("grpc")
    serve = _serve_runtime()
    try:
        from ray_tpu.serve import api as serve_api

        # open the gRPC ingress alongside the running controller
        serve_api._grpc_proxy = None
        serve.start(grpc_port=0)

        @serve.deployment
        def overloaded(_x):
            raise OverloadedError("engine", "queue_full", 2.0)

        serve.run(overloaded.bind(), name="default", route_prefix=None)
        channel = grpc.insecure_channel(serve.grpc_address())
        predict = channel.unary_unary("/ray_tpu.serve.Serve/Predict")
        with pytest.raises(grpc.RpcError) as exc:
            predict(json.dumps({"x": 1}).encode(), timeout=30)
        assert exc.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert "retry_after_s=2" in exc.value.details()
        channel.close()
    finally:
        serve.shutdown()
        rt.shutdown()


# --------------------------------------------------------------------------
# chaos: the `overload` schedule kind + invariant 11
# --------------------------------------------------------------------------
def _run_overload_schedule():
    from ray_tpu.chaos import ChaosRunner, ChaosSchedule

    rt.init(num_cpus=1, _system_config={
        "demand_queue_max_entries": 8,
        "infeasible_task_timeout_s": 2.0,
    })
    try:
        sched = ChaosSchedule.from_dict({
            "seed": 7,
            "events": [
                {"t": 0.0, "kind": "arm", "spec": "scheduler.dispatch=delay(0.001,0.2)"},
                {"t": 0.05, "kind": "overload", "tasks": 24, "cpus": 4},
                {"t": 0.1, "kind": "overload", "tasks": 16, "cpus": 1, "hold_s": 0.02},
            ],
        })

        def workload():
            @rt.remote(max_retries=2)
            def bump(x):
                return x + 1

            return [bump.remote(i) for i in range(30)]

        result = ChaosRunner(sched, quiesce_timeout=60).run(workload)
        return result
    finally:
        rt.shutdown()


def test_chaos_overload_schedule_invariant_11_and_determinism():
    first = _run_overload_schedule()
    assert first.ok, (first.invariants.violations, first.workload_error)
    # the bounded demand queue shed the infeasible burst's overflow, every
    # shed typed + audited, no shed task executed (invariant 11)
    assert first.invariants.checked["overload_sheds"] >= 8
    injected = [e for e in first.events_applied if e["kind"] == "overload"]
    assert injected and all("submitted" in e for e in injected)

    second = _run_overload_schedule()
    assert second.ok, second.invariants.violations
    assert first.same_faults(second), "same-seed fault logs diverged"
    assert len(first.faults) > 0  # the armed failpoint actually decided


def test_chaos_validate_overload_kind(tmp_path):
    from ray_tpu.chaos.schedule import validate_schedule

    ok = {"seed": 1, "events": [
        {"t": 0.0, "kind": "overload", "tasks": 10, "cpus": 2, "hold_s": 0.1},
    ]}
    assert validate_schedule(ok) == []
    bad = {"seed": 1, "events": [
        {"t": 0.0, "kind": "overload", "tasks": 0, "cpus": -1, "hold_s": -2,
         "bogus": 1},
    ]}
    errors = validate_schedule(bad)
    assert len(errors) == 4, errors

    # CLI round trip
    from ray_tpu.scripts.cli import main

    path = tmp_path / "overload.json"
    path.write_text(json.dumps(ok))
    assert main(["chaos", "validate", str(path)]) == 0


# --------------------------------------------------------------------------
# observability: /api/overload + `rt overload`
# --------------------------------------------------------------------------
def test_api_overload_and_cli_smoke(capsys):
    from ray_tpu.scripts.cli import main

    rt.init(
        num_cpus=1,
        include_dashboard=True,
        _system_config={
            "max_inflight_tasks_per_caller": 2,
            "task_submit_overload_policy": "shed",
        },
    )
    try:
        url = rt.get_cluster().dashboard.url

        @rt.remote
        def hold():
            time.sleep(0.3)
            return 1

        refs, sheds = [], 0
        for _ in range(5):
            try:
                refs.append(hold.remote())
            except OverloadedError:
                sheds += 1
        assert sheds >= 1
        rt.get(refs, timeout=60)

        assert main(["overload", "--address", url, "--format", "json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["shed_totals"]["submission"]["inflight_cap"] >= 1
        assert data["submission"]["cap"] == 2
        assert data["demand_queue"]["bound"] > 0
        assert data["events_total"] >= 1

        assert main(["overload", "--address", url]) == 0
        out = capsys.readouterr().out
        assert "sheds:" in out and "submission gate" in out
    finally:
        rt.shutdown()


def test_new_metric_families_registered():
    from ray_tpu.observability import metric_defs

    names = {m.name for m in metric_defs.ALL_METRICS}
    for family in (
        "requests_shed_total",
        "admission_queue_depth",
        "tenant_admissions_total",
        "store_put_backpressure_seconds",
        "llm_slots_evicted_total",
    ):
        assert family in names
