"""Native shm store tests (plasma lifecycle parity:
src/ray/object_manager/plasma/test/)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from ray_tpu.native.shm_store import ShmObjectStore


@pytest.fixture
def store():
    s = ShmObjectStore(f"/rt_test_{os.getpid()}_{os.urandom(4).hex()}", 4 << 20)
    yield s
    s.close()
    s.unlink()


def _oid(i: int) -> bytes:
    return i.to_bytes(20, "little")


def test_put_get_roundtrip(store):
    store.put(_oid(1), b"hello")
    view, meta = store.get(_oid(1))
    assert bytes(view) == b"hello"
    store.release(_oid(1))


def test_create_seal_lifecycle(store):
    buf = store.create(_oid(2), 4)
    buf[:] = b"abcd"
    # not visible until sealed
    assert store.get(_oid(2)) is None
    assert not store.contains(_oid(2))
    store.seal(_oid(2))
    assert store.contains(_oid(2))


def test_duplicate_create_rejected(store):
    store.put(_oid(3), b"x")
    with pytest.raises(FileExistsError):
        store.create(_oid(3), 1)


def test_delete_and_pinning(store):
    store.put(_oid(4), b"data")
    view, _ = store.get(_oid(4))  # pins
    assert not store.delete(_oid(4))  # refcount > 0
    store.release(_oid(4))
    assert store.delete(_oid(4))
    assert not store.contains(_oid(4))


def test_lru_eviction_under_pressure(store):
    # fill beyond capacity; oldest unreferenced objects evicted
    blob = b"x" * (256 * 1024)
    for i in range(32):
        store.put(_oid(100 + i), blob)
    assert store.num_objects < 32
    # most recent object survives
    assert store.contains(_oid(131))


def test_meta_size_roundtrip(store):
    store.put(_oid(5), b"METAdata", meta_size=4)
    view, meta = store.get(_oid(5))
    assert meta == 4
    assert bytes(view[:meta]) == b"META"
    store.release(_oid(5))


def test_cross_process_read(store):
    arr = np.arange(1000, dtype=np.float64)
    store.put(_oid(6), arr.tobytes())
    code = f"""
import numpy as np
from ray_tpu.native.shm_store import ShmObjectStore
s = ShmObjectStore({store.name!r}, create=False)
view, _ = s.get({_oid(6)!r})
arr = np.frombuffer(view, dtype=np.float64)
assert arr.sum() == {arr.sum()!r}, arr.sum()
s.release({_oid(6)!r})
print("child-ok")
"""
    out = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True, cwd="/root/repo")
    assert "child-ok" in out.stdout, out.stderr
