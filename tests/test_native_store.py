"""Native shm store tests (plasma lifecycle parity:
src/ray/object_manager/plasma/test/)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from ray_tpu.native.shm_store import ShmObjectStore


@pytest.fixture
def store():
    s = ShmObjectStore(f"/rt_test_{os.getpid()}_{os.urandom(4).hex()}", 4 << 20)
    yield s
    s.close()
    s.unlink()


def _oid(i: int) -> bytes:
    return i.to_bytes(20, "little")


def test_put_get_roundtrip(store):
    store.put(_oid(1), b"hello")
    view, meta = store.get(_oid(1))
    assert bytes(view) == b"hello"
    store.release(_oid(1))


def test_create_seal_lifecycle(store):
    buf = store.create(_oid(2), 4)
    buf[:] = b"abcd"
    # not visible until sealed
    assert store.get(_oid(2)) is None
    assert not store.contains(_oid(2))
    store.seal(_oid(2))
    assert store.contains(_oid(2))


def test_duplicate_create_rejected(store):
    store.put(_oid(3), b"x")
    with pytest.raises(FileExistsError):
        store.create(_oid(3), 1)


def test_delete_and_pinning(store):
    store.put(_oid(4), b"data")
    view, _ = store.get(_oid(4))  # pins
    assert not store.delete(_oid(4))  # refcount > 0
    store.release(_oid(4))
    assert store.delete(_oid(4))
    assert not store.contains(_oid(4))


def test_lru_eviction_under_pressure(store):
    # fill beyond capacity; oldest unreferenced objects evicted
    blob = b"x" * (256 * 1024)
    for i in range(32):
        store.put(_oid(100 + i), blob)
    assert store.num_objects < 32
    # most recent object survives
    assert store.contains(_oid(131))


def test_meta_size_roundtrip(store):
    store.put(_oid(5), b"METAdata", meta_size=4)
    view, meta = store.get(_oid(5))
    assert meta == 4
    assert bytes(view[:meta]) == b"META"
    store.release(_oid(5))


def test_cross_process_read(store):
    arr = np.arange(1000, dtype=np.float64)
    store.put(_oid(6), arr.tobytes())
    code = f"""
import numpy as np
from ray_tpu.native.shm_store import ShmObjectStore
s = ShmObjectStore({store.name!r}, create=False)
view, _ = s.get({_oid(6)!r})
arr = np.frombuffer(view, dtype=np.float64)
assert arr.sum() == {arr.sum()!r}, arr.sum()
s.release({_oid(6)!r})
print("child-ok")
"""
    out = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True, cwd="/root/repo")
    assert "child-ok" in out.stdout, out.stderr


def test_concurrent_open_single_initializer():
    """Open-race regression: N processes race shm_open on the SAME name
    (one passing create=True a moment before the rest pile in with
    create=False reads).  Before the O_EXCL + wait-for-magic fix, a late
    opener that saw the segment mid-initialization would re-memset the
    header — including the live process-shared mutex — and the creator
    process later died on the corrupted robust mutex.  Every opener must
    see one consistently-initialized arena and read back the value."""
    name = f"/rt_race_{os.getpid()}_{os.urandom(4).hex()}"
    creator_code = f"""
import sys
from ray_tpu.native.shm_store import ShmObjectStore
s = ShmObjectStore({name!r}, 16 << 20)
s.put(b"k" * 20, b"race-proof")
print("created")
sys.stdout.flush()
import time
time.sleep(3)  # keep the segment alive while readers attach
"""
    reader_code = f"""
from ray_tpu.native.shm_store import ShmObjectStore
import time
s = None
for _ in range(500):  # segment may not exist yet: retry open (test-only —
    try:               # real workers are handed an arena that already exists)
        s = ShmObjectStore({name!r}, create=False)
        break
    except OSError:
        time.sleep(0.01)
assert s is not None, "segment never appeared"
got = None
for _ in range(200):
    got = s.get(b"k" * 20)
    if got is not None:
        break
    time.sleep(0.01)
view, _ = got
assert bytes(view) == b"race-proof", bytes(view)
s.release(b"k" * 20)
print("reader-ok")
"""
    creator = subprocess.Popen(
        [sys.executable, "-c", creator_code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, cwd="/root/repo",
    )
    # readers start IMMEDIATELY — before the creator has finished (or even
    # begun) initializing; with the old magic-check fallback this is the
    # corruption window
    readers = [
        subprocess.Popen(
            [sys.executable, "-c", reader_code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, cwd="/root/repo",
        )
        for _ in range(3)
    ]
    try:
        for r in readers:
            out, err = r.communicate(timeout=60)
            assert "reader-ok" in out, err
        creator.kill()
    finally:
        for p in readers + [creator]:
            if p.poll() is None:
                p.kill()
        ShmObjectStore(name, 1 << 20).unlink()
