"""Actor tests (parity: python/ray/tests/test_actor*.py)."""

import os
import time

import pytest


def test_basic_actor(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert rt.get(c.incr.remote()) == 11
    assert rt.get(c.incr.remote(5)) == 16
    assert rt.get(c.value.remote()) == 16


def test_actor_runs_in_own_process(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class P:
        def pid(self):
            return os.getpid()

    a, b = P.remote(), P.remote()
    pid_a, pid_b = rt.get([a.pid.remote(), b.pid.remote()])
    assert pid_a != pid_b
    assert os.getpid() not in (pid_a, pid_b)


def test_inproc_actor(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(execution="inproc")
    class Here:
        def pid(self):
            return os.getpid()

    h = Here.remote()
    assert rt.get(h.pid.remote()) == os.getpid()


def test_method_ordering(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Log:
        def __init__(self):
            self.items = []

        def append(self, x):
            self.items.append(x)

        def get_items(self):
            return self.items

    log = Log.remote()
    for i in range(20):
        log.append.remote(i)
    assert rt.get(log.get_items.remote()) == list(range(20))


def test_actor_error_propagation(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Fragile:
        def fail(self):
            raise KeyError("missing")

        def ok(self):
            return "fine"

    f = Fragile.remote()
    with pytest.raises(rt.RayTaskError):
        rt.get(f.fail.remote())
    # actor survives application errors
    assert rt.get(f.ok.remote()) == "fine"


def test_creation_failure_surfaces(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("cannot construct")

        def m(self):
            return 1

    b = Broken.remote()
    with pytest.raises((rt.RayTaskError, rt.RayActorError)):
        rt.get(b.m.remote(), timeout=30)


def test_named_actor_and_get_actor(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Service:
        def ping(self):
            return "pong"

    Service.options(name="svc").remote()
    time.sleep(0.1)
    handle = rt.get_actor("svc")
    assert rt.get(handle.ping.remote()) == "pong"
    with pytest.raises(ValueError):
        rt.get_actor("nonexistent")


def test_namespace_isolation(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class S:
        def which(self):
            return "found"

    S.options(name="dup", namespace="ns1").remote()
    S.options(name="dup", namespace="ns2").remote()  # no collision
    time.sleep(0.1)
    assert rt.get(rt.get_actor("dup", namespace="ns1").which.remote()) == "found"
    with pytest.raises(ValueError):
        rt.get_actor("dup", namespace="ns3")


def test_duplicate_name_rejected(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class S:
        def m(self):
            return 1

    S.options(name="unique").remote()
    with pytest.raises(ValueError):
        S.options(name="unique").remote()


def test_kill_actor(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class V:
        def m(self):
            return 1

    v = V.remote()
    assert rt.get(v.m.remote()) == 1
    rt.kill(v)
    with pytest.raises(rt.RayActorError):
        rt.get(v.m.remote(), timeout=30)


def test_actor_restart_on_crash(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(max_restarts=2)
    class Phoenix:
        def __init__(self):
            self.state = "reborn"

        def crash(self):
            os._exit(1)

        def status(self):
            return self.state

    p = Phoenix.remote()
    assert rt.get(p.status.remote()) == "reborn"
    try:
        rt.get(p.crash.remote(), timeout=30)
    except (rt.RayActorError, rt.WorkerCrashedError, rt.RayTaskError):
        pass
    # restarted actor serves again (state reset by re-running __init__)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            assert rt.get(p.status.remote(), timeout=10) == "reborn"
            break
        except (rt.RayActorError, rt.WorkerCrashedError):
            time.sleep(0.2)
    else:
        pytest.fail("actor did not restart")


def test_no_restart_without_max_restarts(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Mortal:
        def crash(self):
            os._exit(1)

        def m(self):
            return 1

    m = Mortal.remote()
    assert rt.get(m.m.remote()) == 1
    try:
        rt.get(m.crash.remote(), timeout=30)
    except Exception:
        pass
    with pytest.raises(rt.RayActorError):
        rt.get(m.m.remote(), timeout=30)


def test_async_actor_concurrency(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class AsyncWorker:
        async def slow_echo(self, x, delay):
            import asyncio

            await asyncio.sleep(delay)
            return x

    w = AsyncWorker.remote()
    t0 = time.perf_counter()
    refs = [w.slow_echo.remote(i, 0.5) for i in range(4)]
    assert rt.get(refs, timeout=30) == [0, 1, 2, 3]
    # concurrent: 4 x 0.5s sleeps overlap
    assert time.perf_counter() - t0 < 1.8


def test_actor_handle_passing(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Store:
        def __init__(self):
            self.v = {}

        def set(self, k, v):
            self.v[k] = v

        def get_value(self, k):
            return self.v.get(k)

    @rt.remote
    def writer(store, k, v):
        rt.get(store.set.remote(k, v))
        return "written"

    s = Store.remote()
    # handle crosses into an in-process task
    assert rt.get(writer.options(execution="thread").remote(s, "x", 42), timeout=30) == "written"
    assert rt.get(s.get_value.remote("x")) == 42


def test_method_num_returns(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Splitter:
        @rt.method(num_returns=2)
        def pair(self):
            return 1, 2

    s = Splitter.remote()
    a, b = s.pair.remote()
    assert rt.get([a, b]) == [1, 2]


def test_actor_with_ref_args(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Adder:
        def add(self, a, b):
            return a + b

    x = rt.put(10)
    a = Adder.remote()
    assert rt.get(a.add.remote(x, 5)) == 15


def test_device_actor_with_jax_state(ray_start_regular):
    rt = ray_start_regular
    import jax.numpy as jnp

    @rt.remote(execution="inproc")
    class Model:
        def __init__(self, dim):
            self.w = jnp.eye(dim)

        def apply(self, x):
            return (self.w @ x).sum()

    m = Model.remote(8)
    out = rt.get(m.apply.remote(jnp.ones(8)))
    assert float(out) == 8.0
